#!/usr/bin/env python
"""Machine-readable index + schema validation over ``results/`` artifacts.

The repo accumulates one round-stamped artifact per measurement PR
(``nscale_r13.json``, ``trace_overhead_r17.json``, ...).  Reviewers and
the regression radar both want to answer "how has metric X moved across
rounds?" without grepping fifteen ad-hoc JSON shapes.  This tool scans
``results/`` recursively, classifies every ``.json`` artifact against
the small set of known schemas, extracts the (metric, round, value,
unit, fingerprint) tuple where one exists, and emits:

- ``results/INDEX.md`` — a human-readable index with per-metric
  trajectories across rounds (newest last), written atomically;
- ``--json`` — the same document as machine-readable JSON on stdout.

Schemas recognised (see _classify):

- ``bench``        dict with ``metric``/``value``/``unit`` — the
                   canonical bench.py payload (validated strictly);
- ``bench-suite``  dict with a ``bench`` name and ``runs`` (serve_r14,
                   serve_fleet_r15);
- ``lifecycle``    dict with a ``bench`` name and a ``lifecycle``
                   section (serve_learn artifacts, lifecycle_r19);
- ``summary``      any other dict (experiment summaries, decisions);
- ``table``        a JSON list (host_seg_bench);
- ``invalid``      unparseable JSON, or a bench payload violating the
                   schema (missing keys, non-numeric value).

Exit status: 0 when every artifact parses and bench payloads validate;
1 under ``--strict`` if any problem was found (always listed either
way).

Usage::

    python tools/results_index.py [--results DIR] [--json] [--strict]
                                  [--no-write]
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from smartcal_tpu.runtime.atomic import atomic_write_text

ROUND_RE = re.compile(r"_r(\d+)(?:\D|$)")

#: bench payload contract (bench.py `_write_results_artifact` and the
#: per-bench extras): these keys must exist and `value` must be numeric.
BENCH_REQUIRED = ("metric", "value", "unit")


def artifact_round(name: str) -> Optional[int]:
    """Round stamp from a ``_rN`` filename suffix (None if unstamped)."""
    m = ROUND_RE.search(os.path.basename(name))
    return int(m.group(1)) if m else None


def fingerprint_kind(doc: Any) -> str:
    """How well the artifact pins its host: ``digest`` (full
    host_fingerprint from obs.baselines), ``legacy`` (ad-hoc
    platform/host_cores tags), or ``none``."""
    if not isinstance(doc, dict):
        return "none"
    if "host_fingerprint_digest" in doc or "host_fingerprint" in doc:
        return "digest"
    if "host_cores" in doc or "platform" in doc:
        return "legacy"
    return "none"


def _classify(doc: Any, problems: List[str], rel: str) -> Dict[str, Any]:
    """Classify one parsed artifact; append schema violations to
    ``problems``.  Returns the per-artifact index row."""
    row: Dict[str, Any] = {"schema": "summary", "metric": None,
                           "value": None, "unit": None}
    if isinstance(doc, list):
        row["schema"] = "table"
        return row
    if not isinstance(doc, dict):
        problems.append(f"{rel}: top-level JSON is {type(doc).__name__}, "
                        "expected object or array")
        row["schema"] = "invalid"
        return row
    if "metric" in doc:
        row["schema"] = "bench"
        missing = [k for k in BENCH_REQUIRED if k not in doc]
        if missing:
            problems.append(f"{rel}: bench payload missing {missing}")
            row["schema"] = "invalid"
        row["metric"] = doc.get("metric")
        row["unit"] = doc.get("unit")
        val = doc.get("value")
        if val is not None and not isinstance(val, (int, float)):
            problems.append(f"{rel}: bench value is "
                            f"{type(val).__name__}, expected number")
            row["schema"] = "invalid"
        else:
            row["value"] = val
        vsb = doc.get("vs_baseline")
        if vsb is not None and not isinstance(vsb, (str, int, float)):
            problems.append(f"{rel}: vs_baseline must be a string or "
                            "number")
    elif "bench" in doc and "runs" in doc:
        row["schema"] = "bench-suite"
        row["metric"] = doc.get("bench")
    elif "bench" in doc and "lifecycle" in doc:
        # tools/serve_learn.py artifact (lifecycle_rN.json): the
        # headline is sigma_res improvement measured on live traffic
        row["schema"] = "lifecycle"
        row["metric"] = f"{doc.get('bench')}_sigma_res_improvement"
        imp = (doc.get("lifecycle") or {}).get("sigma_res_improvement")
        if isinstance(imp, (int, float)):
            row["value"] = imp
            row["unit"] = "fraction"
    elif "stages" in doc and "findings" in doc:
        row["schema"] = "perf-gate"
    elif "schema_version" in doc and "entries" in doc:
        row["schema"] = "baseline-store"
    return row


def scan(results_dir: str) -> Dict[str, Any]:
    """Walk ``results_dir`` and build the full index document."""
    rows: List[Dict[str, Any]] = []
    problems: List[str] = []
    other: List[str] = []
    for dirpath, dirnames, filenames in os.walk(results_dir):
        dirnames.sort()
        for fn in sorted(filenames):
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, results_dir)
            if not fn.endswith(".json"):
                if os.path.dirname(rel) == "":
                    other.append(rel)
                continue
            try:
                with open(path) as fh:
                    doc = json.load(fh)
            except (OSError, ValueError) as exc:
                problems.append(f"{rel}: unreadable JSON ({exc})")
                rows.append({"path": rel, "round": artifact_round(fn),
                             "schema": "invalid", "metric": None,
                             "value": None, "unit": None,
                             "fingerprint": "none"})
                continue
            row = _classify(doc, problems, rel)
            row.update(path=rel, round=artifact_round(fn),
                       fingerprint=fingerprint_kind(doc))
            rows.append(row)
    rows.sort(key=lambda r: r["path"])
    return {"results_dir": results_dir, "artifacts": rows,
            "other_files": other, "problems": problems,
            "trajectories": _trajectories(rows)}


def _trajectories(rows: List[Dict[str, Any]]) -> Dict[str, List[dict]]:
    """Per-metric value trajectory across rounds (bench payloads only,
    top-level artifacts only, ordered by round with unstamped first)."""
    by_metric: Dict[str, List[dict]] = {}
    for r in rows:
        if r["schema"] != "bench" or r["metric"] is None:
            continue
        if os.path.dirname(r["path"]):
            continue  # nested summaries aren't round-over-round series
        by_metric.setdefault(r["metric"], []).append(
            {"round": r["round"], "value": r["value"], "unit": r["unit"],
             "path": r["path"]})
    for pts in by_metric.values():
        pts.sort(key=lambda p: (p["round"] is not None, p["round"] or 0))
    return by_metric


def render_markdown(doc: Dict[str, Any]) -> str:
    """INDEX.md body from a scan document."""
    lines = ["# results/ index", "",
             "Generated by `python tools/results_index.py` — do not edit;",
             "regenerate after adding an artifact.", ""]
    lines += ["## Metric trajectories", ""]
    traj = doc["trajectories"]
    if traj:
        lines += ["| metric | trajectory (by round) | unit |",
                  "|---|---|---|"]
        for metric in sorted(traj):
            pts = traj[metric]
            steps = " → ".join(
                f"r{p['round']}: {p['value']}" if p["round"] is not None
                else f"{p['value']}" for p in pts)
            unit = pts[-1]["unit"] or ""
            lines.append(f"| {metric} | {steps} | {unit} |")
    else:
        lines.append("(no bench-schema artifacts found)")
    lines += ["", "## Artifacts", "",
              "| path | round | schema | metric | fingerprint |",
              "|---|---|---|---|---|"]
    for r in doc["artifacts"]:
        rnd = f"r{r['round']}" if r["round"] is not None else "—"
        lines.append(f"| {r['path']} | {rnd} | {r['schema']} | "
                     f"{r['metric'] or '—'} | {r['fingerprint']} |")
    if doc["other_files"]:
        lines += ["", "## Non-JSON artifacts", ""]
        lines += [f"- {p}" for p in doc["other_files"]]
    if doc["problems"]:
        lines += ["", "## Schema problems", ""]
        lines += [f"- {p}" for p in doc["problems"]]
    lines.append("")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--results", default="results",
                    help="results directory to scan")
    ap.add_argument("--json", action="store_true",
                    help="print the index document as JSON on stdout")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 if any schema problem was found")
    ap.add_argument("--no-write", action="store_true",
                    help="do not write INDEX.md (scan/report only)")
    args = ap.parse_args(argv)

    if not os.path.isdir(args.results):
        print(f"results_index: no such directory: {args.results}",
              file=sys.stderr)
        return 2
    doc = scan(args.results)
    if not args.no_write:
        out = os.path.join(args.results, "INDEX.md")
        atomic_write_text(out, render_markdown(doc))
        doc["index_md"] = out
    if args.json:
        print(json.dumps(doc, indent=1))
    else:
        n_bench = sum(1 for r in doc["artifacts"] if r["schema"] == "bench")
        print(f"results_index: {len(doc['artifacts'])} JSON artifact(s), "
              f"{n_bench} bench payload(s), {len(doc['trajectories'])} "
              f"metric trajectories, {len(doc['problems'])} problem(s)"
              + ("" if args.no_write else f" -> {doc['index_md']}"))
        for p in doc["problems"]:
            print(f"  problem: {p}")
    return 1 if (args.strict and doc["problems"]) else 0


if __name__ == "__main__":
    sys.exit(main())
