#!/usr/bin/env python
"""Serve calibration from a replicated fleet: sweep replica topologies
behind the deadline-aware FleetRouter front door and record the
scaling / kill-and-recover / autoscale artifact.

One invocation runs up to three measurements against ONE shared
on-disk cache (replica 0 of the first topology builds it cold; every
later replica — and every later topology — warm-starts off it):

* ``--replicas 1,2,4``  — the SCALING sweep: per topology, offered
  load of ``--rate-per-replica * n`` for ``--duration`` seconds, with
  per-replica compile-event gauges sampled before and after the load
  so the zero-steady-state-compile claim is asserted FLEET-wide (every
  replica process, not just the parent).  Append ``@2`` to a point
  (e.g. ``4@2``) to spread its replicas over 2 simulated hosts.
* ``--kill``            — 2 replicas under load, one SIGKILLed mid-run:
  the run must complete every admitted job (survivor requeue), shed
  nothing, and respawn the slot; time-to-recover is measured.
* ``--autoscale``       — 1 replica + AutoscalePolicy under a rate
  step: the router must scale up under sustained backlog and reap back
  to the floor when the load drains.
* ``--slowdown``        — the SLO burn-rate demonstration: 2 replicas
  under load, one replica's solve stage stalled mid-run by a
  deterministic fault plan (runtime.faults delay); the fleet's
  SloBurnDetector must FIRE while the stall holds p99 over target,
  LOCALIZE the slow replica (worst per-replica p99), and CLEAR after
  recovery.  The run fails soft (recorded, not raised) so the artifact
  always lands.

``--trace-dir DIR`` gives every measurement its own per-process stream
directory (``DIR/<phase>/``: the router's stream plus one stream per
replica generation, clock-offset handshakes included).  Each phase
record then carries a ``trace`` digest — merged-event counts, per-peer
clock offsets, and the cross-process trace completeness score — and the
directories replay offline through ``tools/obs_report.py`` (critical
path), ``tools/trace_export.py`` (Perfetto) and ``tools/obs_tail.py``
(merged tail).

``--stub`` swaps the CalibServer factory for the stdlib SleepServer
(see :class:`smartcal_tpu.serve.fleet.SleepServer`): sleeps overlap
across processes even on a one-core host, so the stub sweep is the
ROUTER-CAPACITY ceiling the real fleet is compared against — on a
many-core host the real curve approaches it; on a starved one the gap
is the host, not the front door (``host_cores`` is recorded).

    PYTHONPATH=. JAX_PLATFORMS=cpu python tools/serve_fleet.py \
        --tier tiny --M 3 --lanes 3 --replicas 1,2,4 --kill --autoscale \
        --cache-dir /tmp/fleet_cache --metrics /tmp/fleet.jsonl \
        --out results/serve_fleet_r15.json

Fleet telemetry rides the parent RunLog (``--metrics``): fleet_dispatch
/ fleet_result events, fleet-scoped sheds, scale and replica-lifecycle
events, fleet gauges — aggregate with ``tools/obs_report.py`` (the
"fleet SLO" section).
"""

import argparse
import contextlib
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from smartcal_tpu import obs                               # noqa: E402
from smartcal_tpu.runtime.backoff import BackoffPolicy     # noqa: E402
from smartcal_tpu.serve.fleet import (                     # noqa: E402
    AutoscalePolicy, FleetRouter, calib_worker_spec, sleep_worker_spec)
from smartcal_tpu.serve.loadgen import SERVE_TIERS as TIERS  # noqa: E402
from smartcal_tpu.train import blocks                      # noqa: E402


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--tier", choices=sorted(TIERS), default="tiny")
    p.add_argument("--M", type=int, default=3)
    p.add_argument("--lanes", type=int, default=3)
    p.add_argument("--cache-dir", dest="cache_dir", required=True,
                   help="SHARED AOT export + XLA cache root (all "
                        "replicas, all topologies)")
    p.add_argument("--replicas", type=str, default="1,2,4",
                   help="comma list of topology points; 'N@H' spreads "
                        "N replicas over H simulated hosts (e.g. "
                        "1,2,4,4@2); empty string skips the sweep")
    p.add_argument("--rate-per-replica", dest="rate_per_replica",
                   type=float, default=6.0,
                   help="offered jobs/s PER REPLICA at each point")
    p.add_argument("--duration", type=float, default=10.0,
                   help="seconds of offered load per topology point")
    p.add_argument("--pool", type=int, default=8)
    p.add_argument("--pool-mode", dest="pool_mode",
                   choices=("mixed", "uniform"), default="mixed")
    p.add_argument("--deadline-ms", dest="deadline_ms", type=float,
                   default=None)
    p.add_argument("--kill", action="store_true",
                   help="run the kill-and-recover measurement")
    p.add_argument("--autoscale", action="store_true",
                   help="run the rate-step autoscale measurement")
    p.add_argument("--slowdown", action="store_true",
                   help="run the injected-slowdown SLO burn-rate "
                        "demonstration (stub fleets only: the fault "
                        "stalls the stub's solve stage)")
    p.add_argument("--trace-dir", dest="trace_dir", default=None,
                   help="root for per-phase per-process trace streams "
                        "(<dir>/<phase>/{router,replicaN-gK}.jsonl); "
                        "enables the merged-timeline trace digest per "
                        "measurement")
    p.add_argument("--slo-p99-ms", dest="slo_p99_ms", type=float,
                   default=None,
                   help="p99 target for the fleet SLO burn-rate "
                        "detector (default: detector off except in "
                        "--slowdown, which derives one from the stub "
                        "service time)")
    p.add_argument("--stub", action="store_true",
                   help="SleepServer replicas (router-capacity ceiling "
                        "instead of the real CalibServer fleet)")
    p.add_argument("--stub-service-ms", dest="stub_service_ms",
                   type=float, default=50.0)
    p.add_argument("--max-requeues", dest="max_requeues", type=int,
                   default=2)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", type=str, default=None)
    blocks.add_obs_args(p)
    return p.parse_args(argv)


def _spec(args):
    if args.stub:
        return sleep_worker_spec(lanes=args.lanes,
                                 service_s=args.stub_service_ms / 1e3)
    return calib_worker_spec(TIERS[args.tier], M=args.M,
                             lanes=args.lanes, cache_dir=args.cache_dir,
                             max_wait_s=0.02, max_queue=64)


def _pool(args, backend):
    from smartcal_tpu.serve import loadgen

    if args.stub:
        # sleeps don't look at the episode: an empty payload keeps the
        # stub sweep measuring dispatch+IPC, not episode pickling
        return [(1 + i % args.M, None) for i in range(args.pool)]
    return loadgen.build_job_pool(backend, args.M, args.pool,
                                  seed=args.seed + 1,
                                  mixed=(args.pool_mode == "mixed"))


def _router(args, replicas, hosts=1, autoscale=None, metrics_dir=None,
            slo=None, spec=None):
    return FleetRouter(
        spec if spec is not None else _spec(args),
        replicas=replicas, hosts=hosts,
        heartbeat_timeout=30.0, max_restarts=3,
        backoff=BackoffPolicy(base_s=0.1, factor=2.0, max_s=2.0,
                              jitter=0.0),
        seed=args.seed, max_requeues=args.max_requeues,
        autoscale=autoscale, poll_s=0.05, metrics_dir=metrics_dir,
        slo=slo)


def _phase_dir(args, name):
    """Per-measurement stream directory under --trace-dir (or None)."""
    if not args.trace_dir:
        return None
    d = os.path.join(args.trace_dir, name)
    os.makedirs(d, exist_ok=True)
    return d


@contextlib.contextmanager
def _phase_obs(pdir):
    """Route the router-side stream into the phase directory: a fresh
    ``router.jsonl`` RunLog shadows the global one for the phase (stack
    discipline), so dispatch/result/clock_offset events land next to
    the replica streams they merge with."""
    if pdir is None:
        yield
        return
    with obs.recording(os.path.join(pdir, "router.jsonl"),
                       run_id="router"):
        yield


def _slo(args):
    if args.slo_p99_ms is None:
        return None
    return obs.SloBurnDetector(p99_target_s=args.slo_p99_ms / 1e3)


def _trace_digest(pdir):
    """Merge a phase's streams and score its trace reconstruction."""
    if pdir is None:
        return None
    from smartcal_tpu.obs import collect

    merger = collect.TimelineMerger()
    merger.add_directory(pdir)
    events = merger.merge()
    comp = collect.completeness(collect.request_paths(events))
    return {"dir": pdir, **merger.stats(), "completeness": comp}


def _compile_gauges(router):
    """{rid: cumulative compile events in that replica process} from
    the latest beat each replica streamed."""
    per = router.stats()["per_replica"]
    return {rid: float(g.get("compile_events", 0.0))
            for rid, g in per.items()}


def _settle(router, beats=3, beat_s=0.1):
    time.sleep(beats * beat_s)           # let every replica beat again


def _run_load(args, router, pool, rate, duration):
    from smartcal_tpu.serve import loadgen

    gen = loadgen.OpenLoopLoadGen(
        router, pool, rate=rate, duration_s=duration, seed=args.seed,
        deadline_s=(args.deadline_ms / 1e3 if args.deadline_ms
                    else None),
        pick=("cycle" if args.pool_mode == "uniform" else "random"))
    return gen.run()


def sweep_point(args, tobs, pool, replicas, hosts):
    pdir = _phase_dir(args, f"scale{replicas}x{hosts}")
    t0 = time.time()
    with _phase_obs(pdir):
        router = _router(args, replicas, hosts=hosts, metrics_dir=pdir,
                         slo=_slo(args))
        try:
            warm = router.start(warm_timeout_s=900.0)
            boot_s = round(time.time() - t0, 3)
            _settle(router)
            c0 = _compile_gauges(router)
            rate = args.rate_per_replica * replicas
            summary = _run_load(args, router, pool, rate, args.duration)
            _settle(router)
            c1 = _compile_gauges(router)
            steady = sum(c1.get(rid, 0.0) - c0.get(rid, 0.0)
                         for rid in c1)
            point = {
                "replicas": replicas, "hosts": hosts, "boot_s": boot_s,
                "warm_sources": {rid: sorted(set(w["sources"].values()))
                                 for rid, w in warm.items()},
                "offered_rate": rate,
                "summary": summary,
                "steady_compile_events_fleet": steady,
                "router_stats": {k: v for k, v in router.stats().items()
                                 if k != "per_replica"},
            }
        finally:
            router.stop(timeout=20.0)
    point["trace"] = _trace_digest(pdir)
    tobs.echo(f"replicas={replicas}x{hosts}h rate={rate}: "
              f"{summary.get('achieved_jobs_s')} jobs/s, "
              f"p99={summary.get('latency_p99_s')}s, "
              f"fleet steady compiles={steady:.0f}")
    return point


def kill_run(args, tobs, pool):
    pdir = _phase_dir(args, "kill")
    with _phase_obs(pdir):
        router = _router(args, 2, metrics_dir=pdir, slo=_slo(args))
        try:
            router.start(warm_timeout_s=900.0)
            rate = args.rate_per_replica * 2
            duration = max(6.0, args.duration)
            killed = {}

            def _chaos():
                time.sleep(duration / 3)
                t_kill = time.monotonic()
                router.kill_replica(0)
                deadline = t_kill + 60.0
                while (router.replicas_alive() < 2
                       or router.stats()["replica_restarts"] < 1):
                    if time.monotonic() > deadline:
                        return
                    time.sleep(0.02)
                killed["recover_s"] = round(time.monotonic() - t_kill, 3)

            chaos = threading.Thread(target=_chaos, daemon=True)
            chaos.start()
            summary = _run_load(args, router, pool, rate, duration)
            chaos.join(timeout=90.0)
            recover_s = killed.get("recover_s")
            st = router.stats()
        finally:
            router.stop(timeout=20.0)
    rec = {"summary": summary, "recover_s": recover_s,
           "replica_restarts": st["replica_restarts"],
           "requeued": st["requeued"],
           "shed_reasons": st["shed_reasons"],
           "replicas_alive_after": st["replicas_alive"]}
    if pdir is not None:
        # the SIGKILLed replica can't flush its own black box — the
        # router's parent-side frame ring must have dumped one
        try:
            rec["blackbox_files"] = sorted(
                n for n in os.listdir(pdir) if n.startswith("blackbox_"))
        except OSError:
            rec["blackbox_files"] = []
        rec["trace"] = _trace_digest(pdir)
    tobs.echo(f"kill: completed={summary['completed']}/"
              f"{summary['submitted']} shed={summary['shed']} "
              f"requeued={st['requeued']} recover={recover_s}s"
              + (f" blackboxes={len(rec['blackbox_files'])}"
                 if "blackbox_files" in rec else ""))
    return rec


def slowdown_run(args, tobs, pool):
    """Injected-slowdown SLO demonstration: 2 stub replicas, replica
    0's solve stalled for a span of consecutive batches mid-run by a
    deterministic runtime.faults delay plan.  The burn-rate detector
    must fire while the stall holds the fast-window p99 over target,
    name replica 0 as the worst per-replica p99 at fire time, and clear
    once the fleet recovers and the hot window drains."""
    pdir = _phase_dir(args, "slowdown")
    service_s = args.stub_service_ms / 1e3
    delay_s = max(4.0 * service_s, 0.25)
    spec = sleep_worker_spec(lanes=args.lanes, service_s=service_s)
    spec["per_replica"] = {0: {"faults": {
        "delay_stage": "serve_batch", "delay_at": 10,
        "delay_span": 12, "delay_s": delay_s}}}
    target_s = (args.slo_p99_ms / 1e3 if args.slo_p99_ms
                else 2.5 * service_s)
    slo = obs.SloBurnDetector(p99_target_s=target_s, fast_window_s=2.0,
                              slow_window_s=6.0, sustain_s=0.5,
                              clear_sustain_s=2.0, min_samples=5)
    with _phase_obs(pdir):
        router = _router(args, 2, metrics_dir=pdir, slo=slo, spec=spec)
        try:
            router.start(warm_timeout_s=900.0)
            rate = args.rate_per_replica * 2
            summary = _run_load(args, router, pool, rate,
                                max(10.0, args.duration))
            # recovery: the supervise thread keeps evaluating after the
            # load drains — wait for the detector to quiet down
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                snap = slo.snapshot()
                if snap["transitions"] >= 2 and not snap["firing"]:
                    break
                time.sleep(0.1)
            snap = slo.snapshot()
        finally:
            router.stop(timeout=20.0)
    rec = {"summary": summary, "p99_target_s": target_s,
           "delay_s": delay_s, "slow_replica": 0,
           "snapshot": snap}
    if pdir is not None:
        from smartcal_tpu.obs import collect

        burns = [e for e in collect.merge_directory(pdir)
                 if e.get("event") == "slo_burn"]
        rec["transitions"] = [
            {k: e.get(k) for k in ("state", "burn_fast", "p99_fast_s",
                                   "worst_replica", "t_corr")}
            for e in burns]
        fired = [e for e in burns if e.get("state") == "firing"]
        rec["fired"] = bool(fired)
        rec["localized_replica"] = (fired[0].get("worst_replica")
                                    if fired else None)
        rec["cleared"] = any(e.get("state") == "cleared" for e in burns)
        rec["trace"] = _trace_digest(pdir)
    tobs.echo(f"slowdown: fired={rec.get('fired')} "
              f"localized={rec.get('localized_replica')} "
              f"cleared={rec.get('cleared')} "
              f"(target p99={target_s * 1e3:.0f}ms, "
              f"stall={delay_s * 1e3:.0f}ms x12 batches on replica 0)")
    return rec


def autoscale_run(args, tobs, pool):
    pol = AutoscalePolicy(min_replicas=1, max_replicas=4,
                          spawn_depth=1.5, spawn_sustain_s=1.0,
                          reap_idle_s=3.0, cooldown_s=2.0)
    pdir = _phase_dir(args, "autoscale")
    with _phase_obs(pdir):
        router = _router(args, 1, autoscale=pol, metrics_dir=pdir,
                         slo=_slo(args))
        try:
            router.start(warm_timeout_s=900.0)
            low = _run_load(args, router, pool,
                            args.rate_per_replica * 0.5,
                            max(4.0, args.duration / 2))
            # the step must OVERRUN one replica, not merely busy it: 8x
            # the per-replica operating point keeps depth/replica past
            # spawn_depth for the sustain window
            high = _run_load(args, router, pool,
                             args.rate_per_replica * 8,
                             max(6.0, args.duration))
            peak = router.replicas_alive()
            deadline = time.monotonic() + 30.0
            while (router.replicas_alive() > pol.min_replicas
                   and time.monotonic() < deadline):
                time.sleep(0.1)
            st = router.stats()
        finally:
            router.stop(timeout=20.0)
    rec = {"low": low, "high": high, "policy": pol.__dict__,
           "scale_ups": st["scale_ups"], "scale_downs": st["scale_downs"],
           "peak_replicas": peak,
           "replicas_after_drain": st["replicas_alive"]}
    if pdir is not None:
        rec["trace"] = _trace_digest(pdir)
    tobs.echo(f"autoscale: ups={st['scale_ups']} "
              f"downs={st['scale_downs']} peak={peak} "
              f"drained_to={st['replicas_alive']}")
    return rec


def parse_points(s):
    points = []
    for tok in (t for t in s.split(",") if t.strip()):
        n, _, h = tok.partition("@")
        points.append((int(n), int(h or 1)))
    return points


def main(argv=None):
    args = parse_args(argv)
    tobs = blocks.train_obs_from_args(args, "serve_fleet",
                                      tier=args.tier, lanes=args.lanes)
    t_start = time.time()
    backend = None
    if not args.stub:
        from smartcal_tpu.envs import radio

        backend = radio.RadioBackend(**TIERS[args.tier])
    pool = _pool(args, backend)
    record = {
        "bench": "serve_fleet",
        "tier": args.tier, "M": args.M, "lanes": args.lanes,
        "stub": bool(args.stub), "pool_mode": args.pool_mode,
        "rate_per_replica": args.rate_per_replica,
        "duration_s": args.duration,
        "host_cores": len(os.sched_getaffinity(0)),
        "trace_dir": args.trace_dir,
        "scaling": [],
    }
    for n, h in parse_points(args.replicas):
        record["scaling"].append(sweep_point(args, tobs, pool, n, h))
    if args.kill:
        record["kill"] = kill_run(args, tobs, pool)
    if args.autoscale:
        record["autoscale"] = autoscale_run(args, tobs, pool)
    if args.slowdown:
        record["slowdown"] = slowdown_run(args, tobs, pool)
    record["wall_s"] = round(time.time() - t_start, 3)
    obs.flush_counters()
    tobs.close()
    print(json.dumps(record, indent=1))
    if args.out:
        merge_out(args.out, record)
    return record


def merge_out(path, record):
    """Merge-append into ``runs``; derive the scaling digest (jobs/s vs
    replicas, normalized to the 1-replica point of the same run) from
    the latest run that swept more than one topology."""
    doc = {"bench": "serve_fleet", "runs": []}
    if os.path.exists(path):
        with open(path) as fh:
            doc = json.load(fh)
    doc.setdefault("runs", []).append(record)
    digests = []
    for run in doc["runs"]:
        pts = [p for p in run.get("scaling", [])
               if p["summary"].get("achieved_jobs_s")]
        if len(pts) < 2:
            continue
        base = next((p for p in pts if p["replicas"] == 1), pts[0])
        b = base["summary"]["achieved_jobs_s"]
        digests.append({
            "stub": run.get("stub", False),
            "host_cores": run.get("host_cores"),
            "base_jobs_s": b,
            "curve": [{
                "replicas": p["replicas"], "hosts": p["hosts"],
                "jobs_s": p["summary"]["achieved_jobs_s"],
                "speedup": round(p["summary"]["achieved_jobs_s"]
                                 / max(1e-9, b), 2),
                "efficiency": round(p["summary"]["achieved_jobs_s"]
                                    / max(1e-9, b * p["replicas"]), 3),
                "p99_s": p["summary"].get("latency_p99_s"),
                "shed": p["summary"].get("shed"),
                "steady_compiles":
                    p["steady_compile_events_fleet"],
            } for p in pts],
        })
    if digests:
        doc["scaling_digests"] = digests
    tmp = path + ".tmp"
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(tmp, "w") as fh:
        json.dump(doc, fh, indent=1)
    os.replace(tmp, path)


if __name__ == "__main__":
    main()
