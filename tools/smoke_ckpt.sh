#!/bin/bash
# Checkpoint/restore smoke: record an enet_sac run with per-episode
# checkpointing, SIGTERM it mid-run (the preemption case — possibly mid
# checkpoint write), then --resume from the surviving store and assert
#   * the resumed run continues exactly at the checkpointed episode
#     (continuity — no repeated and no skipped episode indices),
#   * the store survived the kill (LATEST + sha-validated payload),
#   * both RunLog streams are free of `recovery`/`watchdog_trip` events
#     (a clean kill-resume must not look like a divergence).
# Companion of tools/smoke_obs.sh; ~1 min on CPU.
#
#   bash tools/smoke_ckpt.sh [workdir]
set -euo pipefail
cd "$(dirname "$0")/.." || exit 1

REPO="$PWD"
WORK="${1:-$(mktemp -d /tmp/smoke_ckpt.XXXXXX)}"
RUN1="$WORK/record.jsonl"
RUN2="$WORK/resume.jsonl"
CK="$WORK/ckpt"
mkdir -p "$WORK"

echo "[smoke_ckpt] recording enet_sac with --ckpt-every 1 -> $CK" >&2
(cd "$WORK" && PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}" \
    exec python -m smartcal_tpu.train.enet_sac \
    --episodes 100000 --steps 4 --seed 3 --quiet \
    --metrics "$RUN1" --ckpt-dir "$CK" --ckpt-every 1) &
PID=$!
# never leak the open-ended recorder, even if this script is killed
trap 'kill -9 "$PID" 2>/dev/null || true' EXIT INT TERM

# wait for a few checkpoints, then SIGTERM mid-run (the count must not
# trip set -e/pipefail while the store is still empty)
for _ in $(seq 1 180); do
  n=$({ ls "$CK" 2>/dev/null || true; } | { grep -c '^ckpt_' || true; })
  if [ "${n:-0}" -ge 3 ]; then break; fi
  sleep 1
done
kill -TERM "$PID" 2>/dev/null || true
wait "$PID" 2>/dev/null || true
[ -f "$CK/LATEST" ] || { echo "[smoke_ckpt] FAIL: no LATEST pointer"; exit 1; }

STEP=$(python - "$CK/LATEST" <<'EOF'
import json, sys
print(json.load(open(sys.argv[1]))["step"])
EOF
)
TARGET=$((STEP + 5))
echo "[smoke_ckpt] killed at >= episode $STEP; resuming to $TARGET" >&2

(cd "$WORK" && PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}" \
    python -m smartcal_tpu.train.enet_sac \
    --episodes "$TARGET" --steps 4 --seed 3 --quiet \
    --metrics "$RUN2" --ckpt-dir "$CK" --resume > "$WORK/resume_out.json")

python - "$RUN1" "$RUN2" "$STEP" "$TARGET" <<'EOF'
import json
import sys

run1, run2, step, target = sys.argv[1], sys.argv[2], int(sys.argv[3]), \
    int(sys.argv[4])


def events(path):
    return [json.loads(ln) for ln in open(path) if ln.strip()]


e1, e2 = events(run1), events(run2)
for name, evs in (("record", e1), ("resume", e2)):
    bad = [e for e in evs if e["event"] in ("recovery", "watchdog_trip")]
    assert not bad, f"{name} stream has recovery/trip events: {bad}"
assert any(e["event"] == "resume" and e["step"] == step for e in e2), \
    f"resume stream missing resume@{step} event"
eps = [e["episode"] for e in e2 if e["event"] == "episode"]
assert eps == list(range(step, target)), \
    f"resumed episode indices not continuous from {step}: {eps}"
end = [e for e in e2 if e["event"] == "run_end"][-1]
assert end["episodes"] == target - step, end
# the record stream may be missing its last <2 s of buffered events (the
# RunLog's bounded-loss flush contract) but must at least have a header
assert e1 and e1[0]["event"] == "run_header", e1[:1]
rec_eps = [e["episode"] for e in e1 if e["event"] == "episode"]
print(f"[smoke_ckpt] OK: killed at >= episode {step} "
      f"({len(rec_eps)} episode events survived the kill), resumed "
      f"{step}..{target - 1} cleanly, no recovery events")
EOF
