"""Tiled Pallas TPU kernel for the blocked residual-Hessian core.

The B ~ N^2 memory tier of the influence engine runs the blocked XLA
Hessian (cal/kernels._hessian_res_core_blocked_sr): a ``lax.scan`` over
baseline blocks whose per-block einsum temporaries — A1/A2/Sp/Sq and
their conjugates, each (K, Td, blk, 2, 2, 2) — still round-trip HBM
between the einsums XLA fuses imperfectly.  This kernel is the Mosaic
twin (ISSUE 17, promoted after the imager family): the baseline axis is
the grid, each step holds ONE (rows, TILE_B) tile of every operand in
VMEM, the split-real 2x2 block algebra is fully unrolled on the VPU,
and the two outputs leave VMEM exactly once per tile —

* ``off``  (K*32, B)  — the off-diagonal block table, written tile by
  tile (the block index map follows the grid);
* ``Dsum`` (N, K*8)   — the station-summed diagonal contributions,
  reduced on the MXU as two one-hot matmuls per tile and ACCUMULATED
  across the grid (init at i == 0 — the standard Pallas pattern, same
  as ops/pallas_imager).

Layout contract: every VMEM tile keeps the BASELINE axis as the minor
(lane) dimension — tiles are ``(rows, TILE_B)`` with TILE_B = 128, so
the only tiled dimension is lane-aligned and every leading-dim reshape
is Mosaic-trivial.  The 2x2 complex algebra is unrolled into python
loops over (u, v, w) at trace time: ~16 fused multiply-add chains over
(K, Td, TILE_B) planes, no gather, no transpose of the minor axis.

The host wrapper zero-pads B to the tile size with SENTINEL station
indices (>= N), which produce all-zero one-hot columns — the same
padding convention as the blocked XLA core, so any phase of a padded
baseline contributes nothing.  The placement tail
(cal/kernels._hessian_assemble) is shared verbatim with the XLA paths:
one copy of the placement math, three front-ends.

Dispatch lives in cal/influence._chunk_influence_opt under the SAME
static threshold as the blocked XLA core (``block_baselines`` > 0),
gated by :func:`ops.pallas_imager.pallas_available`; ``interpret=True``
runs the kernel through the Pallas interpreter on CPU — the tier-1
parity gate against the XLA oracles — and ``interpret=False`` is the
flag-flip on hardware.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from smartcal_tpu.ops.pallas_imager import _VMEM, pallas_available  # noqa: F401

# One baseline tile per grid step: the off-diagonal output block is
# (K*32, 128) — sublane count K*32 is always a multiple of 8, lane count
# 128 — so every tiled block satisfies the Mosaic (8, 128) alignment for
# any direction count K and any Td (input tiles are full in the sublane
# dimension; only the lane/baseline axis is tiled).
TILE_B = 128


def _hessian_kernel(Kn, Td, cre_ref, cim_ref, rre_ref, rim_ref, jpr_ref,
                    jpi_ref, jqr_ref, jqi_ref, ohp_ref, ohq_ref, off_ref,
                    dsum_ref):
    i = pl.program_id(0)
    f32 = jnp.float32  # graftlint: disable=dtype-discipline -- split-real Hessian blocks are pinned f32 by construction (the solve downstream rejects narrowed operands); ops layers below cal so the policy helper can't be imported at kernel scope
    Cr = cre_ref[:].reshape(Kn, Td, 4, TILE_B)
    Ci = cim_ref[:].reshape(Kn, Td, 4, TILE_B)
    Rr = rre_ref[:].reshape(Td, 4, TILE_B)
    Ri = rim_ref[:].reshape(Td, 4, TILE_B)
    Jpr = jpr_ref[:].reshape(Kn, 4, TILE_B)
    Jpi = jpi_ref[:].reshape(Kn, 4, TILE_B)
    Jqr = jqr_ref[:].reshape(Kn, 4, TILE_B)
    Jqi = jqi_ref[:].reshape(Kn, 4, TILE_B)

    # off[k, a=i*2+u, c=j*2+v] = -sum_t conj(C)[k,t,(i,j)] * R[t,(u,v)]
    # (the kernels._hessian_block_sums "kbiujv" row order, flattened)
    rows = []
    for a in range(4):
        ii, u = divmod(a, 2)
        for c in range(4):
            jj, v = divmod(c, 2)
            cr, ci = Cr[:, :, ii * 2 + jj], Ci[:, :, ii * 2 + jj]
            rr, ri = Rr[None, :, u * 2 + v], Ri[None, :, u * 2 + v]
            rows.append(-jnp.sum(cr * rr + ci * ri, axis=1))   # real
            rows.append(-jnp.sum(cr * ri - ci * rr, axis=1))   # imag
    off_ref[:] = jnp.stack(rows, axis=1).reshape(Kn * 32, TILE_B)

    # diag at p: A1[u, w] = sum_v C[u, v] conj(Jq)[w, v]
    a1r, a1i = {}, {}
    for u in range(2):
        for w in range(2):
            ar = ai = 0.0
            for v in range(2):
                cr, ci = Cr[:, :, u * 2 + v], Ci[:, :, u * 2 + v]
                jr = Jqr[:, None, w * 2 + v]
                ji = Jqi[:, None, w * 2 + v]
                ar = ar + cr * jr + ci * ji
                ai = ai + ci * jr - cr * ji
            a1r[u, w], a1i[u, w] = ar, ai           # (K, Td, TILE_B)
    # Sp[u, v] = sum_t,w A1[u, w] conj(A1)[v, w]
    sp = []
    for u in range(2):
        for v in range(2):
            sr = si = 0.0
            for w in range(2):
                sr = sr + a1r[u, w] * a1r[v, w] + a1i[u, w] * a1i[v, w]
                si = si + a1i[u, w] * a1r[v, w] - a1r[u, w] * a1i[v, w]
            sp.append(jnp.sum(sr, axis=1))
            sp.append(jnp.sum(si, axis=1))
    Sp = jnp.stack(sp, axis=1).reshape(Kn * 8, TILE_B)

    # diag at q: A2[u, w] = sum_v Jp[u, v] C[v, w]
    a2r, a2i = {}, {}
    for u in range(2):
        for w in range(2):
            ar = ai = 0.0
            for v in range(2):
                jr = Jpr[:, None, u * 2 + v]
                ji = Jpi[:, None, u * 2 + v]
                cr, ci = Cr[:, :, v * 2 + w], Ci[:, :, v * 2 + w]
                ar = ar + jr * cr - ji * ci
                ai = ai + jr * ci + ji * cr
            a2r[u, w], a2i[u, w] = ar, ai
    # Sq[v, w] = sum_t,u conj(A2)[u, v] A2[u, w]
    sq = []
    for v in range(2):
        for w in range(2):
            sr = si = 0.0
            for u in range(2):
                sr = sr + a2r[u, v] * a2r[u, w] + a2i[u, v] * a2i[u, w]
                si = si + a2r[u, v] * a2i[u, w] - a2i[u, v] * a2r[u, w]
            sq.append(jnp.sum(sr, axis=1))
            sq.append(jnp.sum(si, axis=1))
    Sq = jnp.stack(sq, axis=1).reshape(Kn * 8, TILE_B)

    # station reduction on the MXU: one-hot (N, TILE_B) x (K*8, TILE_B)
    # contracting the lane axis — sentinel columns are all-zero, so
    # padded baselines contribute nothing
    dn = (((1,), (1,)), ((), ()))
    acc = (jax.lax.dot_general(ohp_ref[:], Sp, dn,
                               preferred_element_type=f32)
           + jax.lax.dot_general(ohq_ref[:], Sq, dn,
                                 preferred_element_type=f32))

    @pl.when(i == 0)
    def _init():
        dsum_ref[:] = acc

    @pl.when(i != 0)
    def _accum():
        dsum_ref[:] += acc


def _planes(x, lead):
    """(..., B, 2, 2, 2) split-real block tensor -> two (lead*4, B)
    component planes (re, im) with the baseline axis minor."""
    re = jnp.moveaxis(x[..., 0], -3, -1)        # (..., 2, 2, B)
    im = jnp.moveaxis(x[..., 1], -3, -1)
    return re.reshape(lead * 4, -1), im.reshape(lead * 4, -1)


@functools.partial(jax.jit, static_argnames=("n_stations", "interpret"))
def hessian_block_sums_pallas(R3, C5, Jp, Jq, p_idx, q_idx, n_stations,
                              interpret=False):
    """Tiled Pallas twin of :func:`cal.kernels._hessian_block_sums` over
    the FULL baseline set: R3 (Td, B, 2, 2, 2); C5 (K, Td, B, 2, 2, 2);
    Jp/Jq (K, B, 2, 2, 2); p_idx/q_idx (B,) station indices.  Returns
    (off (K, B, 4, 4, 2), Dsum (K, N, 2, 2, 2)), UNNORMALIZED — the
    shared placement tail (kernels._hessian_assemble) runs in XLA.
    B is zero-padded to TILE_B internally (sentinel station indices on
    the pad -> zero one-hot columns, the blocked-XLA convention)."""
    from smartcal_tpu.cal import kernels as _kernels
    from smartcal_tpu.cal import precision as prec

    K, Td, B = C5.shape[0], C5.shape[1], C5.shape[2]
    N = n_stations
    Bp = pl.cdiv(B, TILE_B) * TILE_B
    padb = Bp - B

    def pad_b(x, axis):
        pw = [(0, 0)] * x.ndim
        pw[axis] = (0, padb)
        return jnp.pad(x, pw)

    pi = jnp.concatenate(
        [jnp.asarray(p_idx), jnp.full((padb,), N, jnp.asarray(p_idx).dtype)])
    qi = jnp.concatenate(
        [jnp.asarray(q_idx), jnp.full((padb,), N, jnp.asarray(q_idx).dtype)])
    ohp = _kernels._block_onehot(pi, N, prec.F32)          # (N, Bp)
    ohq = _kernels._block_onehot(qi, N, prec.F32)

    cre, cim = _planes(pad_b(C5, 2), K * Td)               # (K*Td*4, Bp)
    rre, rim = _planes(pad_b(R3, 1), Td)                   # (Td*4, Bp)
    jpr, jpi = _planes(pad_b(Jp, 1), K)                    # (K*4, Bp)
    jqr, jqi = _planes(pad_b(Jq, 1), K)

    lane = lambda i: (0, i)                                # noqa: E731
    tile = functools.partial(pl.BlockSpec, index_map=lane,
                             memory_space=_VMEM)
    off, dsum = pl.pallas_call(
        functools.partial(_hessian_kernel, K, Td),
        grid=(Bp // TILE_B,),
        in_specs=[
            tile((K * Td * 4, TILE_B)), tile((K * Td * 4, TILE_B)),
            tile((Td * 4, TILE_B)), tile((Td * 4, TILE_B)),
            tile((K * 4, TILE_B)), tile((K * 4, TILE_B)),
            tile((K * 4, TILE_B)), tile((K * 4, TILE_B)),
            tile((N, TILE_B)), tile((N, TILE_B)),
        ],
        out_specs=[
            pl.BlockSpec((K * 32, TILE_B), lambda i: (0, i),
                         memory_space=_VMEM),
            pl.BlockSpec((N, K * 8), lambda i: (0, 0),
                         memory_space=_VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((K * 32, Bp), prec.F32),
            jax.ShapeDtypeStruct((N, K * 8), prec.F32),
        ],
        interpret=interpret,
    )(cre, cim, rre, rim, jpr, jpi, jqr, jqi, ohp, ohq)

    # (K*32, Bp) rows k*32 + (a*4 + c)*2 + z -> (K, B, 4, 4, 2)
    off = jnp.moveaxis(off.reshape(K, 4, 4, 2, Bp)[..., :B], -1, 1)
    # (N, K*8) cols k*8 + (u*2 + v)*2 + z -> (K, N, 2, 2, 2)
    Dsum = jnp.moveaxis(dsum.reshape(N, K, 2, 2, 2), 1, 0)
    return off, Dsum


@functools.partial(jax.jit, static_argnames=("n_stations", "interpret"))
def hessian_res_core_pallas_sr(R3, C5, Jp, Jq, n_stations,
                               interpret=False):
    """Pallas-fronted :func:`cal.kernels._hessian_res_core_sr` /
    ``_hessian_res_core_blocked_sr``: tiled block sums in Mosaic, the
    shared ``_hessian_assemble`` placement tail in XLA.  Same operands
    and output — (K, 4N, 4N, 2) normalized by the global B*Td — so the
    influence engine's dispatch is a one-line swap.  Equal to the XLA
    cores to float round-off (the tile reduction reassociates the
    station sums exactly like the blocked scan; parity tested in
    interpret mode, tests/test_pallas_hessian.py)."""
    from smartcal_tpu.cal import kernels as _kernels

    Td, B = C5.shape[1], C5.shape[2]
    p_idx, q_idx = _kernels.baseline_indices(n_stations)
    off, Dsum = hessian_block_sums_pallas(R3, C5, Jp, Jq, p_idx, q_idx,
                                          n_stations,
                                          interpret=interpret)
    return _kernels._hessian_assemble(off, Dsum, n_stations, B, Td)
