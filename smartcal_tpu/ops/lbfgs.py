"""Pure-functional L-BFGS for TPU.

Re-expresses the capabilities of the reference optimizer
(``elasticnet/lbfgsnew.py:498-759`` in SarodYatawatta/smart-calibration) as
jit-compilable JAX code:

* The reference is an in-place torch ``Optimizer`` whose curvature history
  lives in Python lists and whose ``step(closure)`` runs a data-dependent
  Python ``while`` loop.  Here the whole solve is one ``lax.while_loop`` over a
  fixed-shape carry; the (s, y) curvature pairs live in ``(m, n)`` ring
  buffers; early-exit conditions (``lbfgsnew.py:725-741``) become loop-carry
  flags.
* The reference's strong-Wolfe cubic line search (``lbfgsnew.py:192-316``,
  Fletcher's bracketing + zoom, ``_cubic_interpolate`` at ``:319``) estimates
  directional derivatives with central finite differences (3 closure evals
  each).  Here phi'(alpha) is exact via one ``jax.value_and_grad`` evaluation
  of ``alpha -> f(x + alpha d)`` — fewer evaluations *and* better accuracy.
* The backtracking search with adaptive ``alphabar`` for stochastic (batch)
  mode (``lbfgsnew.py:115-186``) and the online inter-batch gradient
  mean/variance estimate (``lbfgsnew.py:592-607``) are carried in the
  optimizer state as fixed-shape arrays.

Two entry points:

* :func:`lbfgs_solve` — full-batch minimisation of ``fun(x)`` (the hot inner
  solve of the elastic-net / calibration environments).  Fully jittable;
  20 reference "epochs" x ``max_iter=10`` = ``max_iters=200`` here (the
  reference's per-``step()`` re-entry just continues the same iteration with
  per-chunk early exits; a single masked loop has the same fixed point).
* :class:`LBFGS` / :func:`lbfgs_step` — stateful-functional stochastic mode
  matching the reference's per-batch ``step(closure)`` with the trust-region
  ``y + lm0*s`` modification and adaptive ``alphabar`` (``lbfgsnew.py:570-607``).

The returned :class:`LBFGSHistory` is the input to
``smartcal_tpu.ops.autodiff.inv_hessian_mult`` (the BFGS inverse-Hessian
product the influence function needs), mirroring how the reference reuses
``opt.state_dict()['state'][0]['old_dirs'/'old_stps']``
(``autograd_tools.py:35-66``).
"""

from __future__ import annotations

import functools
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax


LBFGS_HISTORY_DEFAULT = 7  # reference default history_size (lbfgsnew.py:62)


class LBFGSHistory(NamedTuple):
    """Ring buffer of curvature pairs, oldest row first.

    ``s[i]`` is a parameter difference (reference ``old_stps``), ``y[i]`` a
    gradient difference (reference ``old_dirs``).  ``count`` rows at the *end*
    of the buffers are valid (rows are shifted up on insert so row ``m-1`` is
    always the newest valid pair).  ``gamma`` is the initial inverse-Hessian
    scale ``y^T s / y^T y`` of the newest pair (reference ``H_diag``).
    """

    s: jnp.ndarray       # (m, n)
    y: jnp.ndarray       # (m, n)
    count: jnp.ndarray   # () int32 — number of valid pairs
    gamma: jnp.ndarray   # () — H_diag

    @property
    def size(self) -> int:
        return self.s.shape[0]


def history_init(n: int, history_size: int = 7, dtype=jnp.float32) -> LBFGSHistory:
    return LBFGSHistory(
        s=jnp.zeros((history_size, n), dtype),
        y=jnp.zeros((history_size, n), dtype),
        count=jnp.asarray(0, jnp.int32),
        gamma=jnp.asarray(1.0, dtype),
    )


def history_push(hist: LBFGSHistory, s: jnp.ndarray, y: jnp.ndarray,
                 accept) -> LBFGSHistory:
    """Append a curvature pair (when ``accept``), evicting the oldest.

    Matches ``lbfgsnew.py:610-622``: on accept, shift history and store
    ``(y, s)``, update ``H_diag = ys/yy``; otherwise leave state untouched.
    """
    def _push(h):
        new_s = jnp.concatenate([h.s[1:], s[None]], axis=0)
        new_y = jnp.concatenate([h.y[1:], y[None]], axis=0)
        ys = jnp.dot(y, s)
        yy = jnp.dot(y, y)
        return LBFGSHistory(
            s=new_s, y=new_y,
            count=jnp.minimum(h.count + 1, h.size).astype(jnp.int32),
            gamma=(ys / yy).astype(h.gamma.dtype),
        )

    return lax.cond(accept, _push, lambda h: h, hist)


def two_loop_direction(hist: LBFGSHistory, grad: jnp.ndarray) -> jnp.ndarray:
    """Descent direction ``-H^{-1} g`` by the two-loop recursion.

    Reference: ``lbfgsnew.py:629-651``.  The Python-list loops become scans
    over the fixed ring buffer with invalid rows masked to no-ops.
    """
    m = hist.size
    valid = jnp.arange(m) >= (m - hist.count)          # row mask, newest at end
    ys = jnp.einsum('in,in->i', hist.y, hist.s)
    rho = jnp.where(valid, 1.0 / jnp.where(valid, ys, 1.0), 0.0)

    q = -grad

    def bwd(q, inp):
        s_i, y_i, rho_i = inp
        al_i = rho_i * jnp.dot(s_i, q)
        return q - al_i * y_i, al_i

    # newest -> oldest
    q, al_rev = lax.scan(bwd, q, (hist.s[::-1], hist.y[::-1], rho[::-1]))
    al = al_rev[::-1]

    r = q * jnp.where(hist.count > 0, hist.gamma, 1.0)

    def fwd(r, inp):
        s_i, y_i, rho_i, al_i = inp
        be_i = rho_i * jnp.dot(y_i, r)
        return r + (al_i - be_i) * s_i, None

    r, _ = lax.scan(fwd, r, (hist.s, hist.y, rho, al))
    return r


def inv_hessian_mult(hist: LBFGSHistory, q: jnp.ndarray) -> jnp.ndarray:
    """``H^{-1} q`` from stored curvature pairs (BFGS approximation).

    Mirrors ``autograd_tools.py:35-66``: identical two-loop recursion but with
    the initial scale taken from the *newest* pair, and ``q`` returned
    unchanged when no pairs are stored.
    """
    r = -two_loop_direction(hist, q)
    return jnp.where(hist.count > 0, r, q)


# ---------------------------------------------------------------------------
# Line searches
# ---------------------------------------------------------------------------

def _phi_maker(fun, x, d):
    """phi(alpha) -> (value, directional derivative) in ONE forward-mode
    pass.  The line search never needs the full gradient, only g.d — jvp
    costs ~2x a forward eval where value_and_grad costs ~3x, and the
    objective here is the calibration chi^2 over all baselines, so every
    avoided eval is real wall-clock (the line search dominates the ADMM
    solver's device time at LOFAR scale)."""
    def phi(alpha):
        alpha = jnp.asarray(alpha)
        return jax.jvp(lambda a: fun(x + a * d), (alpha,),
                       (jnp.ones((), alpha.dtype),))
    return phi


def _cubic_choose(phi, a, fa, fad, b, fb, fbd):
    """Cubic-interpolation trial point in [a, b] from PRECOMPUTED endpoint
    values (reference ``_cubic_interpolate``, lbfgsnew.py:319-409: fit a
    cubic through (f0, f0', f1, f1'), fall back to the better endpoint when
    the discriminant is non-positive or the minimiser leaves the interval).

    Returns ``(point, f(point), f'(point))`` — at most ONE new phi eval
    (the interior minimiser); endpoint evaluations are reused, where the
    round-1 implementation re-evaluated both endpoints on every call.
    """
    denom = jnp.where(b == a, 1.0, b - a)
    aa = 3.0 * (fa - fb) / denom + fbd - fad
    disc = aa * aa - fad * fbd

    def pos(_):
        cc = jnp.sqrt(jnp.maximum(disc, 0.0))
        den2 = fbd - fad + 2.0 * cc
        z0 = jnp.where(den2 == 0.0, 0.5 * (a + b),
                       b - (fbd + cc - aa) * (b - a)
                       / jnp.where(den2 == 0.0, 1.0, den2))
        hi, lo = jnp.maximum(a, b), jnp.minimum(a, b)
        inside = (z0 <= hi) & (z0 >= lo)
        fz0, fz0d = phi(z0)
        # out-of-interval minimiser: force an ENDPOINT choice with +inf
        # (cached true values) — a finite sentinel like fa+fb is not
        # "worse than both" for sign-indefinite objectives, and with the
        # values now carried downstream a fabricated fz0 would leak into
        # later Wolfe tests (the round-1 code re-evaluated phi instead)
        fz0 = jnp.where(inside, fz0, jnp.inf)
        pick_a = (fa < fb) & (fa < fz0)
        pick_b = (~pick_a) & (fb < fz0)
        out = jnp.where(pick_a, a, jnp.where(pick_b, b, z0))
        fout = jnp.where(pick_a, fa, jnp.where(pick_b, fb, fz0))
        fdout = jnp.where(pick_a, fad, jnp.where(pick_b, fbd, fz0d))
        return out, fout, fdout

    def neg(_):
        pa = fa < fb
        return (jnp.where(pa, a, b), jnp.where(pa, fa, fb),
                jnp.where(pa, fad, fbd))

    return lax.cond(disc > 0.0, pos, neg, operand=None)


def strong_wolfe_cubic(fun: Callable, x: jnp.ndarray, d: jnp.ndarray,
                       lr: float = 1.0, phi_maker=None) -> jnp.ndarray:
    """Fletcher strong-Wolfe line search with cubic interpolation.

    Behavioural twin of ``lbfgsnew.py:192-316`` (bracket, ``_linesearch_zoom``
    ``:412-477``, ``_cubic_interpolate`` ``:319-409``) with exact directional
    derivatives replacing the reference's central differences.  Trip counts
    match the reference (bracket: 3, zoom: 4); unlike the reference (and
    this file's round-1 form), every phi value/derivative is computed once
    and carried — the eval count per L-BFGS iteration drops ~2x, which is
    most of the ADMM calibration solver's device time.
    """
    dtype = x.dtype
    sigma, rho_ls = 0.1, 0.01
    t1, t2, t3 = 9.0, 0.1, 0.5
    alpha1 = 10.0 * lr

    # phi_maker lets an objective with structure supply a cheaper
    # phi(alpha) -> (value, directional derivative): the calibration
    # model is bilinear in the parameters, so its chi^2 along d is an
    # EXACT quartic whose five coefficients cost ~3 model evaluations
    # once — after which every probe here is O(1)
    # (cal/solver._quartic_phi_maker).  Contract identical to _phi_maker.
    phi = (phi_maker or _phi_maker)(fun, x, d)

    phi_0, gphi_0 = phi(jnp.asarray(0.0, dtype))
    tol = jnp.minimum(phi_0 * 0.01, 1e-6)
    mu = (tol - phi_0) / (rho_ls * gphi_0)

    def zoom(a, b, fa, fad):
        """Reference ``_linesearch_zoom`` (``lbfgsnew.py:412-477``); carries
        phi(aj) through the interval updates instead of re-evaluating."""
        def body(i, carry):
            aj, bj, faj, fajd, alphak, found = carry
            p01 = aj + t2 * (bj - aj)
            p02 = bj - t3 * (bj - aj)
            f01, f01d = phi(p01)
            f02, f02d = phi(p02)
            alphaj, phi_j, gphi_j = _cubic_choose(
                phi, p01, f01, f01d, p02, f02, f02d)

            cond_shrink = (phi_j > phi_0 + rho_ls * alphaj * gphi_0) \
                | (phi_j >= faj)
            # Fletcher round-off termination and strong-Wolfe curvature exit.
            term1 = (aj - alphaj) * gphi_j <= 1e-6
            term2 = jnp.abs(gphi_j) <= -sigma * gphi_0
            newly_found = (~cond_shrink) & (term1 | term2)

            # interval update when not terminating; aj's phi travels along
            bj_new = jnp.where(cond_shrink, alphaj,
                               jnp.where(gphi_j * (bj - aj) >= 0.0, aj, bj))
            aj_new = jnp.where(cond_shrink, aj, alphaj)
            faj_new = jnp.where(cond_shrink, faj, phi_j)
            fajd_new = jnp.where(cond_shrink, fajd, gphi_j)

            # on termination alphaj is the result; if the loop runs out, the
            # last trial alphaj is the fallback (reference :486-487) — either
            # way the tracked alpha is the latest alphaj unless already found
            alphak_new = jnp.where(found, alphak, alphaj)
            found_new = found | newly_found
            keep = lambda old, new: jnp.where(found, old, new)
            return (keep(aj, aj_new), keep(bj, bj_new), keep(faj, faj_new),
                    keep(fajd, fajd_new), alphak_new, found_new)

        init = (a, b, fa, fad, jnp.asarray(lr, dtype), jnp.asarray(False))
        _, _, _, _, alphak, _ = lax.fori_loop(0, 4, body, init)
        return alphak

    def bracket(_):
        def body(i, carry):
            (alphai, alphai1, fi, fid, fi1, fi1d, phi_prev, alphak,
             done) = carry
            phi_i, gphi_i = fi, fid

            cond0 = phi_i < tol
            cond1 = (phi_i > phi_0 + alphai * gphi_0) \
                | ((i > 0) & (phi_i >= phi_prev))
            cond2 = jnp.abs(gphi_i) <= -sigma * gphi_0
            cond3 = gphi_i >= 0.0

            need_zoom = (~cond0) & (cond1 | ((~cond2) & cond3))
            za = jnp.where(cond1, alphai1, alphai)
            zb = jnp.where(cond1, alphai, alphai1)
            fza = jnp.where(cond1, fi1, fi)
            fzad = jnp.where(cond1, fi1d, fid)
            zoom_val = lax.cond(need_zoom, lambda ab: zoom(*ab),
                                lambda ab: jnp.asarray(lr, dtype),
                                (za, zb, fza, fzad))

            newly_done = cond0 | cond1 | cond2 | cond3
            val = jnp.where(cond0, alphai,
                            jnp.where(cond1, zoom_val,
                                      jnp.where(cond2, alphai, zoom_val)))

            # continuation: extrapolate or interpolate the next trial point
            lo = 2.0 * alphai - alphai1
            hi = jnp.minimum(mu, alphai + t1 * (alphai - alphai1))
            flo, flod = phi(lo)
            fhi, fhid = phi(hi)
            cand, fcand, fcandd = _cubic_choose(
                phi, lo, flo, flod, hi, fhi, fhid)
            use_mu = mu <= lo
            next_ai = jnp.where(use_mu, mu, cand)
            next_ai1 = jnp.where(use_mu, alphai, alphai1)
            # phi at the next iterate: cached from the interpolation, or a
            # fresh eval only in the mu-capped branch
            fnext, fnextd = lax.cond(use_mu, lambda _: phi(mu),
                                     lambda _: (fcand, fcandd), operand=None)
            fnext1 = jnp.where(use_mu, fi, fi1)
            fnext1d = jnp.where(use_mu, fid, fi1d)

            alphak_new = jnp.where(done, alphak,
                                   jnp.where(newly_done, val, alphak))
            done_new = done | newly_done
            keep = lambda old, new: jnp.where(done_new, old, new)
            return (keep(alphai, next_ai), keep(alphai1, next_ai1),
                    keep(fi, fnext), keep(fid, fnextd),
                    keep(fi1, fnext1), keep(fi1d, fnext1d),
                    keep(phi_prev, phi_i), alphak_new, done_new)

        f_a1, f_a1d = phi(jnp.asarray(alpha1, dtype))
        init = (jnp.asarray(alpha1, dtype), jnp.asarray(0.0, dtype),
                f_a1, f_a1d, phi_0, gphi_0, phi_0,
                jnp.asarray(lr, dtype), jnp.asarray(False))
        out = lax.fori_loop(0, 3, body, init)
        return out[7]

    # degenerate-slope guards (reference returns 1.0 on tiny |gphi_0| / nan mu)
    degenerate = (jnp.abs(gphi_0) < 1e-12) | jnp.isnan(mu)
    alphak = lax.cond(degenerate, lambda _: jnp.asarray(1.0, dtype), bracket,
                      operand=None)
    return jnp.where(jnp.isnan(alphak), jnp.asarray(lr, dtype), alphak)


def linesearch_phi_evals(vmapped: bool = True) -> int:
    """Static phi-evaluation count of ONE :func:`strong_wolfe_cubic` call,
    derived from the compiled loop structure (the observability layer's
    line-search cost model; same spirit as ``cal.solver.cost_eval_flops``:
    analytic iteration counts x exact per-unit structure).

    The bracket loop is ``fori_loop(0, 3)`` and zoom is ``fori_loop(0,
    4)`` — fixed trip counts, so phi-eval counts are compile-time
    constants, not data-dependent.  In the PRODUCTION path the search
    runs inside a vmapped solve, where ``lax.cond`` lowers to ``select``
    and BOTH branches execute every trip:

      init: phi(0) + phi(alpha1)                               =  2
      per bracket trip: zoom branch 4 x (p01 + p02 + interior) = 12
                        + continuation (lo + hi + interior + mu) =  4
      total: 2 + 3 x 16                                        = 50

    ``vmapped=False`` returns the un-vmapped lower bound where the zoom
    cond is a real branch (taken at most once per search).
    """
    if vmapped:
        return 2 + 3 * (4 * 3 + 4)
    return 2 + 3 * 4 + 4 * 3


def solve_eval_counts(n_iters: int, use_line_search: bool = True,
                      vmapped: bool = True) -> dict:
    """Evaluation budget of an ``lbfgs_solve`` run that took ``n_iters``
    iterations (``LBFGSResult.n_iters`` — the dynamic factor the solver
    telemetry threads out of the jitted paths): one ``value_and_grad``
    per iteration plus the initial one, and the line-search phi probes."""
    n = int(n_iters)
    return {
        "value_and_grad_evals": n + 1,
        "phi_evals": (n * linesearch_phi_evals(vmapped)
                      if use_line_search else 0),
    }


def backtracking_search(fun: Callable, x: jnp.ndarray, d: jnp.ndarray,
                        grad: jnp.ndarray, alphabar,
                        c1: float = 1e-4, max_halvings: int = 35) -> jnp.ndarray:
    """Armijo backtracking with a negative-step rescue branch.

    Behavioural twin of ``lbfgsnew.py:115-186``: halve from ``alphabar`` until
    the Armijo condition holds; if the decrease is still below
    ``|c1 alpha g.d|``, try the mirrored negative step and keep the better one.
    """
    dtype = x.dtype
    f_old = fun(x)
    prodterm = c1 * jnp.dot(grad, d)

    def halve(alpha0):
        def cond(carry):
            i, alpha, f_new = carry
            bad = jnp.isnan(f_new) | (f_new > f_old + alpha * prodterm)
            return (i < max_halvings) & bad

        def body(carry):
            i, alpha, _ = carry
            alpha = 0.5 * alpha
            return (i + 1, alpha, fun(x + alpha * d))

        a0 = jnp.asarray(alpha0, dtype)
        _, alpha, f_new = lax.while_loop(cond, body, (0, a0, fun(x + a0 * d)))
        return alpha, f_new

    alphak, f_new = halve(alphabar)

    def rescue(_):
        alpha1, f_new1 = halve(-alphabar)
        return jnp.where(f_new1 < f_new, alpha1, alphak)

    return lax.cond(f_old - f_new < jnp.abs(prodterm), rescue,
                    lambda _: alphak, operand=None)


# ---------------------------------------------------------------------------
# Full-batch solver
# ---------------------------------------------------------------------------

class LBFGSResult(NamedTuple):
    x: jnp.ndarray
    loss: jnp.ndarray
    grad: jnp.ndarray
    hist: LBFGSHistory
    n_iters: jnp.ndarray
    converged: jnp.ndarray
    # full stopping state, so a solve can RESUME exactly (lbfgs_resume):
    # converged alone conflates the six early-exit tests with divergence.
    # (plain-bool defaults: a jnp default would initialise a backend at
    # import time, which must never happen — see the one-client TPU rule)
    stop: jnp.ndarray = False
    diverged: jnp.ndarray = False


def _solve_loop(fun: Callable, use_line_search: bool, tolerance_grad: float,
                tolerance_change: float, lr: float, iter_cap,
                phi_maker=None):
    """(cond, body) of the L-BFGS while_loop over the carry
    (x, loss, g, hist, it, stop, diverged) — shared by lbfgs_solve and
    lbfgs_resume so a segmented solve walks the IDENTICAL trajectory."""
    value_and_grad = jax.value_and_grad(fun)

    def cond(carry):
        (x, loss, g, hist, it, stop, diverged) = carry
        return (it < iter_cap) & (~stop)

    def body(carry):
        (x, loss, g, hist, it, stop, diverged) = carry

        d = two_loop_direction(hist, g)

        gtd = jnp.dot(g, d)
        t0 = jnp.where(it == 0,
                       jnp.minimum(1.0, 1.0 / jnp.sum(jnp.abs(g))) * lr,
                       lr)
        if use_line_search:
            t = strong_wolfe_cubic(fun, x, d, lr=lr, phi_maker=phi_maker)
        else:
            t = t0

        s = t * d
        x_new = x + s
        loss_new, g_new = value_and_grad(x_new)

        # curvature acceptance (lbfgsnew.py:610-613): ys > 1e-10 ||s||^2
        y_new = g_new - g
        ys = jnp.dot(y_new, s)
        sn2 = jnp.dot(s, s)
        accept = ys > 1e-10 * sn2
        hist_new = history_push(hist, s, y_new, accept)

        # stopping tests (lbfgsnew.py:725-741); NaN divergence stops the loop
        # but must not report convergence
        abs_gsum = jnp.sum(jnp.abs(g_new))
        diverged_new = diverged | jnp.isnan(abs_gsum) | jnp.isnan(loss_new)
        stop_new = (abs_gsum <= tolerance_grad)
        stop_new |= gtd > -tolerance_change
        stop_new |= jnp.sum(jnp.abs(s)) <= tolerance_change
        stop_new |= jnp.abs(loss_new - loss) < tolerance_change
        stop_new |= diverged_new

        return (x_new, loss_new, g_new, hist_new, it + 1, stop_new,
                diverged_new)

    return cond, body


@functools.partial(jax.jit, static_argnums=(0, 2, 3, 4, 7, 8))
def lbfgs_solve(fun: Callable, x0: jnp.ndarray, max_iters: int = 200,
                history_size: int = 7, use_line_search: bool = True,
                tolerance_grad: float = 1e-5, tolerance_change: float = 1e-9,
                lr: float = 1.0, phi_maker=None) -> LBFGSResult:
    """Minimise ``fun(x)`` by L-BFGS with strong-Wolfe cubic line search.

    One ``lax.while_loop`` replaces the reference's 20x ``step(closure)``
    epochs (``enetenv.py:101-114``); the six early-exit conditions of
    ``lbfgsnew.py:725-741`` end the loop via the carry's ``stop`` flag.
    """
    dtype = x0.dtype
    value_and_grad = jax.value_and_grad(fun)

    loss0, g0 = value_and_grad(x0)
    hist0 = history_init(x0.shape[0], history_size, dtype)

    cond, body = _solve_loop(fun, use_line_search, tolerance_grad,
                             tolerance_change, lr, max_iters,
                             phi_maker=phi_maker)
    init = (x0, loss0, g0, hist0, jnp.asarray(0, jnp.int32),
            jnp.sum(jnp.abs(g0)) <= tolerance_grad,
            jnp.isnan(loss0))
    x, loss, g, hist, it, stop, diverged = lax.while_loop(cond, body, init)
    return LBFGSResult(x=x, loss=loss, grad=g, hist=hist, n_iters=it,
                       converged=stop & ~diverged, stop=stop,
                       diverged=diverged)


@functools.partial(jax.jit, static_argnums=(0, 2, 3, 6, 7))
def lbfgs_resume(fun: Callable, res: LBFGSResult, extra_iters: int,
                 use_line_search: bool = True, tolerance_grad: float = 1e-5,
                 tolerance_change: float = 1e-9,
                 lr: float = 1.0, phi_maker=None) -> LBFGSResult:
    """Continue a (vmappable) ``lbfgs_solve`` for up to ``extra_iters`` more
    iterations — the SAME while_loop body over the carry recovered from the
    result, so ``solve(30)`` and ``solve(10)`` + 2x ``resume(10)`` walk
    identical trajectories.  This is how long solves are split into bounded
    device dispatches (single multi-minute XLA programs can trip device /
    RPC-tunnel watchdogs; see cal/solver.solve_admm_host)."""
    cap = res.n_iters + extra_iters
    cond, body = _solve_loop(fun, use_line_search, tolerance_grad,
                             tolerance_change, lr, cap,
                             phi_maker=phi_maker)
    init = (res.x, res.loss, res.grad, res.hist, res.n_iters, res.stop,
            res.diverged)
    x, loss, g, hist, it, stop, diverged = lax.while_loop(cond, body, init)
    return LBFGSResult(x=x, loss=loss, grad=g, hist=hist, n_iters=it,
                       converged=stop & ~diverged, stop=stop,
                       diverged=diverged)


# ---------------------------------------------------------------------------
# Stochastic (batch-mode) optimizer
# ---------------------------------------------------------------------------

class LBFGSState(NamedTuple):
    """Functional state for stochastic L-BFGS (reference batch mode)."""
    x: jnp.ndarray
    hist: LBFGSHistory
    prev_grad: jnp.ndarray
    prev_d: jnp.ndarray
    prev_t: jnp.ndarray
    running_avg: jnp.ndarray      # online inter-batch gradient mean
    running_avg_sq: jnp.ndarray   # online second moment accumulator
    alphabar: jnp.ndarray
    n_total: jnp.ndarray          # total iterations across step() calls
    initialized: jnp.ndarray      # bool


def lbfgs_init(x0: jnp.ndarray, history_size: int = 7,
               lr: float = 1.0) -> LBFGSState:
    dtype = x0.dtype
    n = x0.shape[0]
    return LBFGSState(
        x=x0,
        hist=history_init(n, history_size, dtype),
        prev_grad=jnp.zeros_like(x0),
        prev_d=jnp.zeros_like(x0),
        prev_t=jnp.asarray(0.0, dtype),
        running_avg=jnp.zeros_like(x0),
        running_avg_sq=jnp.zeros_like(x0),
        alphabar=jnp.asarray(lr, dtype),
        n_total=jnp.asarray(0, jnp.int32),
        initialized=jnp.asarray(False),
    )


@functools.partial(jax.jit, static_argnums=(0, 2))
def lbfgs_step(fun: Callable, state: LBFGSState, max_iter: int = 4,
               lm0: float = 1e-6) -> tuple:
    """One stochastic ``step(closure)`` on a (new) batch.

    ``fun`` closes over the current batch.  Matches the reference batch mode
    (``lbfgsnew.py:554-607``): on batch change the curvature pair is *not*
    stored; instead the online gradient mean/variance updates ``alphabar``
    which caps the backtracking search; within the batch, pairs are stored
    with the trust-region modification ``y <- y + lm0 * s``.

    Returns ``(state, loss)`` where ``loss`` is the PRE-STEP objective at
    the incoming iterate — the first closure evaluation, exactly what the
    reference ``optimizer.step(closure)`` returns (lbfgsnew.py:509-513).
    Callers logging convergence should evaluate ``fun(state.x)`` after the
    step (or log the next call's return) rather than treat this as the
    post-step loss.
    """
    value_and_grad = jax.value_and_grad(fun)

    def inner(i, carry):
        st, loss, g = carry
        is_first_of_batch = (i == 0)
        n_tot = st.n_total + 1

        # --- inter-batch statistics (only on batch change, lbfgsnew.py:592-607)
        grad_nrm = jnp.linalg.norm(g)

        def upd_stats(_):
            g_old = g - st.running_avg
            new_avg = st.running_avg + g_old / n_tot.astype(g.dtype)
            g_new = g - new_avg
            new_sq = st.running_avg_sq + g_new * g_old
            denom = jnp.maximum(n_tot - 1, 1).astype(g.dtype) * grad_nrm
            new_ab = 1.0 / (1.0 + jnp.sum(new_sq) / denom)
            return new_avg, new_sq, new_ab

        batch_changed = is_first_of_batch & st.initialized
        running_avg, running_avg_sq, alphabar = lax.cond(
            batch_changed, upd_stats,
            lambda _: (st.running_avg, st.running_avg_sq, st.alphabar),
            operand=None)

        # --- memory update from previous move
        y = g - st.prev_grad + lm0 * st.prev_d * st.prev_t
        s = st.prev_d * st.prev_t
        ys = jnp.dot(y, s)
        accept = (ys > 1e-10 * jnp.dot(s, s)) & (~batch_changed) & st.initialized
        hist = history_push(st.hist, s, y, accept)

        d = two_loop_direction(hist, g)
        t = backtracking_search(fun, st.x, d, g, alphabar)
        x_new = st.x + t * d
        # skip the post-step re-evaluation on the last inner iteration — in a
        # stochastic setting the next step() entry re-evaluates on the new
        # batch anyway (reference lbfgsnew.py:712-716)
        loss_new, g_new = lax.cond(
            i < max_iter - 1,
            lambda _: value_and_grad(x_new),
            lambda _: (loss, g), operand=None)

        st_new = LBFGSState(
            x=x_new, hist=hist, prev_grad=g, prev_d=d, prev_t=t,
            running_avg=running_avg, running_avg_sq=running_avg_sq,
            alphabar=alphabar, n_total=n_tot,
            initialized=jnp.asarray(True),
        )
        return (st_new, loss_new, g_new)

    loss0, g0 = value_and_grad(state.x)
    st, loss, _ = lax.fori_loop(0, max_iter, inner, (state, loss0, g0))
    return st, loss0
