from .lbfgs import (  # noqa: F401
    LBFGS_HISTORY_DEFAULT,
    LBFGSHistory,
    LBFGSResult,
    LBFGSState,
    backtracking_search,
    history_init,
    history_push,
    inv_hessian_mult,
    lbfgs_init,
    lbfgs_solve,
    lbfgs_step,
    strong_wolfe_cubic,
    two_loop_direction,
)
from .autodiff import (  # noqa: F401
    cross_derivative,
    gradient,
    hessian_vec_prod,
    influence_matrix,
    inverse_hessian_vec_prod,
    jacobian,
    loss_hvp,
)
