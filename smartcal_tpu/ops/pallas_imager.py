"""Fused Pallas TPU kernel for the DFT dirty imager.

The imaging hot op (cal/imager.dirty_image_sr, the in-framework excon/
wsclean role) is

    img[p] = (1/R) * sum_r [cos(phi_pr) v_re[r] + sin(phi_pr) v_im[r]],
    phi_pr = l_p * u_r + m_p * v_r

At reference scale (npix=128 -> P=16384 pixels, N=62 stations ->
R = B*T = 37820 samples) the XLA formulation materializes the (P, R)
phase matrix and its cos/sin — ~2.5 GB of HBM traffic per trig array —
because XLA does not fuse transcendentals into dot-general operands.
This kernel tiles (P, R) over a grid and keeps each (TILE_P, TILE_R)
phase tile in VMEM only: one small matmul builds the tile, the VPU takes
cos/sin in place, and two matvecs on the MXU reduce it into the output
accumulator.  HBM traffic drops from O(P*R) to O(P + R) per tile pass —
the op becomes compute-bound instead of bandwidth-bound.

Grid layout: (P tiles, R tiles); the R axis is the reduction — the
output block index map ignores the R coordinate, so the same VMEM output
tile stays live across the inner R sweep (init at j == 0, accumulate
after; the standard Pallas accumulation pattern).

Dispatch lives in :func:`cal.imager.dirty_image_sr` (Pallas on TPU for
aligned shapes, XLA otherwise), upgrading every single-device caller at
once.  Set ``interpret=True`` to run the kernel through the Pallas
interpreter on CPU (used by the golden test against the XLA oracle).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # the TPU backend module imports on CPU-only installs too
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

# Mosaic requires the last two block dims be (divisible by 8, divisible by
# 128) or equal to the full array dims; the output tile is (TILE_P//128,
# 128), so TILE_P must be a multiple of 8*128 = 1024.  (A 256-pixel tile
# lowered fine in interpreter mode but was REJECTED by the real TPU
# lowering with block shape (2, 128) — caught on hardware.)
TILE_P = 1024    # pixels per tile -> (8, 128) output block
# phase tile + its cos/sin temporaries + double-buffered input blocks must
# fit the 16 MB scoped-vmem budget: 1024x512 tiles OOMed at 19.6 MB on a
# v5e (caught on hardware), 1024x256 leaves headroom
TILE_R = 256     # uv samples per tile; phase tile = 1024x256x4B = 1 MB


def _imager_kernel(lm_ref, uvt_ref, vre_ref, vim_ref, out_ref):
    j = pl.program_id(1)
    # (TILE_P, 2) @ (2, TILE_R) -> phase tile, never leaves VMEM
    phase = jnp.dot(lm_ref[:], uvt_ref[:],
                    preferred_element_type=jnp.float32)
    # explicit range reduction: |phase| reaches ~1e3 rad at LOFAR uv
    # scales, where raw f32 trig approximations diverge visibly between
    # implementations (0.3% pallas-vs-XLA observed on a v5e); one mod-2pi
    # keeps the trig argument small at the cost of two VPU ops
    two_pi = jnp.float32(2.0 * jnp.pi)
    phase = phase - two_pi * jnp.round(phase / two_pi)
    acc = (jnp.dot(jnp.cos(phase), vre_ref[:],
                   preferred_element_type=jnp.float32)
           + jnp.dot(jnp.sin(phase), vim_ref[:],
                     preferred_element_type=jnp.float32))   # (TILE_P, 1)
    acc = acc.reshape(TILE_P // 128, 128)

    @pl.when(j == 0)
    def _init():
        out_ref[:] = acc

    @pl.when(j != 0)
    def _accum():
        out_ref[:] += acc


@functools.partial(jax.jit, static_argnames=("npix", "interpret"))
def dirty_image_pallas(uvw, vis, freq, cell, npix=128, interpret=False):
    """Drop-in Pallas version of :func:`cal.imager.dirty_image_sr`.

    uvw : (R, 3) meters; vis : (R, 2) split-real samples.  Requires
    npix^2 % TILE_P == 0 (npix a multiple of 32); R is
    zero-padded to TILE_R internally (padded vis rows are 0, so any
    phase value contributes nothing).
    """
    from smartcal_tpu.cal.imager import C_LIGHT, pixel_grid

    P = npix * npix
    if P % TILE_P != 0:
        raise ValueError(f"npix={npix}: npix^2 must be a multiple of "
                         f"{TILE_P}; cal.imager.dirty_image_sr falls back "
                         "to the XLA path for unaligned sizes")
    R = uvw.shape[0]
    scale = 2.0 * jnp.pi * freq / C_LIGHT
    uv = (uvw[:, :2] * scale).astype(jnp.float32)
    lm = pixel_grid(npix, cell).astype(jnp.float32)          # (P, 2)

    Rp = pl.cdiv(R, TILE_R) * TILE_R
    uvt = jnp.zeros((2, Rp), jnp.float32).at[:, :R].set(uv.T)
    vre = jnp.zeros((Rp, 1), jnp.float32).at[:R, 0].set(vis[:, 0])
    vim = jnp.zeros((Rp, 1), jnp.float32).at[:R, 0].set(vis[:, 1])

    grid = (P // TILE_P, Rp // TILE_R)
    out = pl.pallas_call(
        _imager_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((TILE_P, 2), lambda i, j: (i, 0),
                         memory_space=_VMEM),
            pl.BlockSpec((2, TILE_R), lambda i, j: (0, j),
                         memory_space=_VMEM),
            pl.BlockSpec((TILE_R, 1), lambda i, j: (j, 0),
                         memory_space=_VMEM),
            pl.BlockSpec((TILE_R, 1), lambda i, j: (j, 0),
                         memory_space=_VMEM),
        ],
        out_specs=pl.BlockSpec((TILE_P // 128, 128),
                               lambda i, j: (i, 0), memory_space=_VMEM),
        out_shape=jax.ShapeDtypeStruct((P // 128, 128), jnp.float32),
        interpret=interpret,
    )(lm, uvt, vre, vim)
    return out.reshape(npix, npix) / R


def pallas_available() -> bool:
    """True when the default backend is a TPU and pallas imported.

    ``SMARTCAL_DISABLE_PALLAS=1`` is the operational escape hatch: it
    forces the XLA path everywhere (e.g. if a new jaxlib's Mosaic
    lowering rejects the kernel) without touching call sites."""
    import os

    flag = os.environ.get("SMARTCAL_DISABLE_PALLAS", "").strip().lower()
    if pltpu is None or flag in ("1", "true", "yes", "on"):
        return False
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # pragma: no cover
        return False

