"""Fused Pallas TPU kernel for the DFT dirty imager.

The imaging hot op (cal/imager.dirty_image_sr, the in-framework excon/
wsclean role) is

    img[p] = (1/R) * sum_r [cos(phi_pr) v_re[r] + sin(phi_pr) v_im[r]],
    phi_pr = l_p * u_r + m_p * v_r

At reference scale (npix=128 -> P=16384 pixels, N=62 stations ->
R = B*T = 37820 samples) the XLA formulation materializes the (P, R)
phase matrix and its cos/sin — ~2.5 GB of HBM traffic per trig array —
because XLA does not fuse transcendentals into dot-general operands.
This kernel tiles (P, R) over a grid and keeps each (TILE_P, TILE_R)
phase tile in VMEM only: one small matmul builds the tile, the VPU takes
cos/sin in place, and two matvecs on the MXU reduce it into the output
accumulator.  HBM traffic drops from O(P*R) to O(P + R) per tile pass —
the op becomes compute-bound instead of bandwidth-bound.

Grid layout: (P tiles, R tiles); the R axis is the reduction — the
output block index map ignores the R coordinate, so the same VMEM output
tile stays live across the inner R sweep (init at j == 0, accumulate
after; the standard Pallas accumulation pattern).

Dispatch lives in :func:`cal.imager.dirty_image_sr` (Pallas on TPU for
aligned shapes, XLA otherwise), upgrading every single-device caller at
once.  Set ``interpret=True`` to run the kernel through the Pallas
interpreter on CPU (used by the golden test against the XLA oracle).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # the TPU backend module imports on CPU-only installs too
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

# Mosaic requires the last two block dims be (divisible by 8, divisible by
# 128) or equal to the full array dims; the output tile is (TILE_P//128,
# 128), so TILE_P must be a multiple of 8*128 = 1024.  (A 256-pixel tile
# lowered fine in interpreter mode but was REJECTED by the real TPU
# lowering with block shape (2, 128) — caught on hardware.)
TILE_P = 1024    # pixels per tile -> (8, 128) output block
# phase tile + its cos/sin temporaries + double-buffered input blocks must
# fit the 16 MB scoped-vmem budget: 1024x512 tiles OOMed at 19.6 MB on a
# v5e (caught on hardware), 1024x256 leaves headroom
TILE_R = 256     # uv samples per tile; phase tile = 1024x256x4B = 1 MB


def _imager_kernel(lm_ref, uvt_ref, vre_ref, vim_ref, out_ref):
    j = pl.program_id(1)
    f32 = jnp.float32  # graftlint: disable=dtype-discipline -- direct-DFT kernel accumulates f32 by construction (pre-policy oracle tier); ops layers below cal so the policy helper can't be imported at kernel scope
    # (TILE_P, 2) @ (2, TILE_R) -> phase tile, never leaves VMEM
    phase = jnp.dot(lm_ref[:], uvt_ref[:], preferred_element_type=f32)
    # explicit range reduction: |phase| reaches ~1e3 rad at LOFAR uv
    # scales, where raw f32 trig approximations diverge visibly between
    # implementations (0.3% pallas-vs-XLA observed on a v5e); one mod-2pi
    # keeps the trig argument small at the cost of two VPU ops
    two_pi = f32(2.0 * jnp.pi)
    phase = phase - two_pi * jnp.round(phase / two_pi)
    acc = (jnp.dot(jnp.cos(phase), vre_ref[:],
                   preferred_element_type=f32)
           + jnp.dot(jnp.sin(phase), vim_ref[:],
                     preferred_element_type=f32))            # (TILE_P, 1)
    acc = acc.reshape(TILE_P // 128, 128)

    @pl.when(j == 0)
    def _init():
        out_ref[:] = acc

    @pl.when(j != 0)
    def _accum():
        out_ref[:] += acc


@functools.partial(jax.jit, static_argnames=("npix", "interpret"))
def dirty_image_pallas(uvw, vis, freq, cell, npix=128, interpret=False):
    """Drop-in Pallas version of :func:`cal.imager.dirty_image_sr`.

    uvw : (R, 3) meters; vis : (R, 2) split-real samples.  Requires
    npix^2 % TILE_P == 0 (npix a multiple of 32); R is
    zero-padded to TILE_R internally (padded vis rows are 0, so any
    phase value contributes nothing).
    """
    from smartcal_tpu.cal.imager import C_LIGHT, pixel_grid
    from smartcal_tpu.cal import precision as prec

    P = npix * npix
    if P % TILE_P != 0:
        raise ValueError(f"npix={npix}: npix^2 must be a multiple of "
                         f"{TILE_P}; cal.imager.dirty_image_sr falls back "
                         "to the XLA path for unaligned sizes")
    R = uvw.shape[0]
    scale = 2.0 * jnp.pi * freq / C_LIGHT
    uv = (uvw[:, :2] * scale).astype(prec.F32)
    lm = pixel_grid(npix, cell).astype(prec.F32)             # (P, 2)

    Rp = pl.cdiv(R, TILE_R) * TILE_R
    uvt = jnp.zeros((2, Rp), prec.F32).at[:, :R].set(uv.T)
    vre = jnp.zeros((Rp, 1), prec.F32).at[:R, 0].set(vis[:, 0])
    vim = jnp.zeros((Rp, 1), prec.F32).at[:R, 0].set(vis[:, 1])

    grid = (P // TILE_P, Rp // TILE_R)
    out = pl.pallas_call(
        _imager_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((TILE_P, 2), lambda i, j: (i, 0),
                         memory_space=_VMEM),
            pl.BlockSpec((2, TILE_R), lambda i, j: (0, j),
                         memory_space=_VMEM),
            pl.BlockSpec((TILE_R, 1), lambda i, j: (j, 0),
                         memory_space=_VMEM),
            pl.BlockSpec((TILE_R, 1), lambda i, j: (j, 0),
                         memory_space=_VMEM),
        ],
        out_specs=pl.BlockSpec((TILE_P // 128, 128),
                               lambda i, j: (i, 0), memory_space=_VMEM),
        out_shape=jax.ShapeDtypeStruct((P // 128, 128), prec.F32),
        interpret=interpret,
    )(lm, uvt, vre, vim)
    return out.reshape(npix, npix) / R


# --------------------------------------------------------------------------
# Tiled FACTORED imager: the npix >= 1024 / B ~ N^2 (SKA-scale) tier
# --------------------------------------------------------------------------
#
# The rank-factored formulation (cal/imager.dirty_image_factored_sr) is
# already transcendental-cheap, but its (npix, R) axis planes grow to
# GB scale at npix=1024 x R~6.5e5 (N=256).  This kernel tiles BOTH the
# pixel axes and the visibility (reduction) axis: each grid step builds
# one (TILE_L, TILE_R) "a" tile and one (TILE_M, TILE_R) "b" tile in
# VMEM, takes cos/sin in place, and reduces into a (TILE_L, TILE_M)
# output tile on the MXU — the largest live buffer is a tile, never a
# plane.  The R axis is the reduction: the output block index map
# ignores the innermost grid coordinate (init at k == 0, accumulate
# after — the same pattern as _imager_kernel above).
#
# The lax fallback with the identical blocking contract is
# cal/imager.dirty_image_factored_blocked_sr (CPU/GPU and inside GSPMD
# programs, where pallas_call has no partitioning rule); interpret=True
# runs this kernel through the Pallas interpreter on CPU for the tier-1
# parity tests.

TILE_L = 128     # output rows per tile  -> (128, 128) output block
TILE_M = 128     # output cols per tile
TILE_FR = 256    # uv samples per tile: a/b tiles are 128x256x4B = 128 kB


def _factored_kernel(dt, li_ref, mi_ref, u_ref, v_ref, vre_ref, vim_ref,
                     out_ref):
    k = pl.program_id(2)
    f32 = jnp.float32  # graftlint: disable=dtype-discipline -- kernel accumulator dtype is pinned f32 by the imager_matmul policy row
    # (TILE_L, 1) @ (1, TILE_R) phase-plane tiles, VMEM-resident
    a = jnp.dot(li_ref[:], u_ref[:], preferred_element_type=f32)
    b = jnp.dot(mi_ref[:], v_ref[:], preferred_element_type=f32)
    # same explicit mod-2pi range reduction as _imager_kernel: |phase|
    # reaches ~1e3 rad at LOFAR uv scales where raw f32 trig diverges
    two_pi = f32(2.0 * jnp.pi)
    a = a - two_pi * jnp.round(a / two_pi)
    b = b - two_pi * jnp.round(b / two_pi)
    ca, sa = jnp.cos(a), jnp.sin(a)
    cb, sb = jnp.cos(b), jnp.sin(b)
    vr, vi = vre_ref[:], vim_ref[:]            # (1, TILE_R)
    p1 = ca * vr + sa * vi                     # (TILE_L, TILE_R)
    p2 = ca * vi - sa * vr
    if dt != f32:                              # mixed-precision operands,
        p1, p2 = p1.astype(dt), p2.astype(dt)  # f32 accumulation (policy
        cb, sb = cb.astype(dt), sb.astype(dt)  # row: imager_matmul)
    # contract the shared TILE_R axis (rhs transposed in the dimension
    # numbers — no explicit VMEM transpose)
    dn = (((1,), (1,)), ((), ()))
    acc = (jax.lax.dot_general(p1, cb, dn, preferred_element_type=f32)
           + jax.lax.dot_general(p2, sb, dn, preferred_element_type=f32))

    @pl.when(k == 0)
    def _init():
        out_ref[:] = acc

    @pl.when(k != 0)
    def _accum():
        out_ref[:] += acc


@functools.partial(jax.jit,
                   static_argnames=("npix", "precision", "interpret"))
def dirty_image_factored_pallas(uvw, vis, freq, cell, npix=1024,
                                precision="f32", interpret=False):
    """Tiled Pallas version of
    :func:`cal.imager.dirty_image_factored_blocked_sr` (same math, same
    blocking contract; parity tested in interpret mode against the XLA
    oracles).  Requires npix a multiple of TILE_L (128); R is zero-padded
    to TILE_FR (padded vis rows are 0, so any phase contributes nothing).

    ``precision`` (static, cal/precision.py ``imager_matmul`` row):
    "bf16" narrows the reduction matmul operands with f32 accumulation.
    """
    from smartcal_tpu.cal import precision as prec
    from smartcal_tpu.cal.imager import C_LIGHT

    if npix % TILE_L != 0:
        raise ValueError(
            f"npix={npix}: must be a multiple of {TILE_L}; "
            "cal.imager.dirty_image_factored_blocked_sr is the unaligned "
            "fallback")
    dt = prec.contraction_dtype("imager_matmul", precision)
    R = uvw.shape[0]
    scale = 2.0 * jnp.pi * freq / C_LIGHT
    half = npix // 2
    idx = ((jnp.arange(npix) - half).astype(prec.F32) * cell)[:, None]
    Rp = pl.cdiv(R, TILE_FR) * TILE_FR
    u = jnp.zeros((1, Rp), prec.F32).at[0, :R].set(uvw[:, 0] * scale)
    v = jnp.zeros((1, Rp), prec.F32).at[0, :R].set(uvw[:, 1] * scale)
    vre = jnp.zeros((1, Rp), prec.F32).at[0, :R].set(vis[:, 0])
    vim = jnp.zeros((1, Rp), prec.F32).at[0, :R].set(vis[:, 1])

    grid = (npix // TILE_L, npix // TILE_M, Rp // TILE_FR)
    out = pl.pallas_call(
        functools.partial(_factored_kernel, dt),
        grid=grid,
        in_specs=[
            pl.BlockSpec((TILE_L, 1), lambda i, j, k: (i, 0),
                         memory_space=_VMEM),
            pl.BlockSpec((TILE_M, 1), lambda i, j, k: (j, 0),
                         memory_space=_VMEM),
            pl.BlockSpec((1, TILE_FR), lambda i, j, k: (0, k),
                         memory_space=_VMEM),
            pl.BlockSpec((1, TILE_FR), lambda i, j, k: (0, k),
                         memory_space=_VMEM),
            pl.BlockSpec((1, TILE_FR), lambda i, j, k: (0, k),
                         memory_space=_VMEM),
            pl.BlockSpec((1, TILE_FR), lambda i, j, k: (0, k),
                         memory_space=_VMEM),
        ],
        out_specs=pl.BlockSpec((TILE_L, TILE_M), lambda i, j, k: (i, j),
                               memory_space=_VMEM),
        out_shape=jax.ShapeDtypeStruct((npix, npix), prec.F32),
        interpret=interpret,
    )(idx, idx, u, v, vre, vim)
    return out / R


def pallas_available() -> bool:
    """True when the default backend is a TPU and pallas imported.

    ``SMARTCAL_DISABLE_PALLAS=1`` is the operational escape hatch: it
    forces the XLA path everywhere (e.g. if a new jaxlib's Mosaic
    lowering rejects the kernel) without touching call sites."""
    import os

    flag = os.environ.get("SMARTCAL_DISABLE_PALLAS", "").strip().lower()
    if pltpu is None or flag in ("1", "true", "yes", "on"):
        return False
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # pragma: no cover
        return False

