"""Jacobian / Hessian / influence-function machinery, JAX-native.

Re-expresses ``elasticnet/autograd_tools.py`` (194 LoC of per-row
``backward()`` loops in the reference) with JAX's functional transforms:

* ``jacobian`` (reference ``:21-29``): the reference builds the Jacobian one
  row at a time with one ``backward()`` per output coordinate; here it is one
  ``jax.jacrev`` (vmapped VJPs — a single batched pass).
* ``inv_hessian_mult`` (reference ``:35-66``): lives with the L-BFGS history
  in :mod:`smartcal_tpu.ops.lbfgs` since it consumes the stored curvature
  pairs; re-exported here for parity.
* ``hessian_vec_prod`` (reference ``:159-176``): the Pearlmutter trick's
  double-``autograd.grad`` R-operator is simply ``jvp(grad(f))`` in JAX.
* ``inverse_hessian_vec_prod`` (reference ``:183-194``): Koh & Liang Taylor
  series with per-step normalisation, as a ``lax.fori_loop``.
* ``influence_matrix`` (reference ``:94-149``): the reference runs an O(M*N)
  Python loop of ``backward()`` calls; here the mixed second derivative
  d(dL/dx)/dtheta is one ``jacrev``-of-``grad``, pushed through the inverse
  Hessian with a ``vmap``, and contracted against the model Jacobian with one
  matmul — no Python loops, fully jittable.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.flatten_util import ravel_pytree

from .lbfgs import LBFGSHistory, inv_hessian_mult  # noqa: F401  (re-export)


def gradient(f: Callable, x: jnp.ndarray, grad_outputs: Optional[jnp.ndarray] = None):
    """VJP ``(dy/dx)^T @ grad_outputs`` (reference ``gradient``, ``:13-18``)."""
    y, vjp = jax.vjp(f, x)
    if grad_outputs is None:
        grad_outputs = jnp.ones_like(y)
    return vjp(grad_outputs)[0]


def jacobian(f: Callable, x: jnp.ndarray) -> jnp.ndarray:
    """Dense Jacobian dy/dx, shape ``(y.size, x.size)``."""
    return jax.jacrev(lambda z: jnp.ravel(f(z)))(x)


def hessian_vec_prod(f: Callable, x, v):
    """Pearlmutter Hessian-vector product ``H(x) v`` for scalar ``f``.

    ``jvp`` of ``grad`` — forward-over-reverse, no Hessian materialised
    (replaces the reference's R-operator ``right_op``, ``:171-176``).
    """
    return jax.jvp(jax.grad(f), (x,), (v,))[1]


def loss_hvp(loss_fn: Callable, params, v):
    """HVP w.r.t. a parameter *pytree*; ``v`` is a flat vector.

    Returns a flat vector.  Mirrors the reference's model/criterion form
    (``hessian_vec_prod(model, criterion, inputs, outputs, v)``, ``:159-169``)
    but for arbitrary pytree parameters.
    """
    flat, unravel = ravel_pytree(params)

    def f(p_flat):
        return loss_fn(unravel(p_flat))

    return hessian_vec_prod(f, flat, v)


def inverse_hessian_vec_prod(f: Callable, x, v, maxiter: int = 10):
    """Taylor-series inverse-HVP (Koh & Liang 2017, sec. 3).

    ``x_{j+1} = v + x_j - H x_j`` with per-iteration normalisation, exactly
    the reference recursion (``autograd_tools.py:183-194``) under a
    ``fori_loop``.
    """
    v0 = v / jnp.linalg.norm(v)

    def body(_, xcur):
        q = hessian_vec_prod(f, x, xcur)
        xnew = v + xcur - q
        return xnew / jnp.linalg.norm(xnew)

    return lax.fori_loop(0, maxiter, body, v0)


def cross_derivative(loss_fn: Callable, params, x) -> jnp.ndarray:
    """Mixed second derivative ``d/dx [dL/dtheta]`` as a ``(P, N)`` matrix.

    ``loss_fn(params, x)`` must be scalar.  ``P`` = flattened parameter size,
    ``N`` = flattened input size.  This is the quantity the reference builds
    one column at a time with ``g[ci].backward()``
    (``autograd_tools.py:123-130``).
    """
    flat, unravel = ravel_pytree(params)

    def grad_wrt_params(x_flat):
        x_shaped = x_flat.reshape(x.shape)
        g = jax.grad(lambda p: loss_fn(unravel(p), x_shaped))(flat)
        return g

    # jacfwd over the (usually smaller) input axis: (P, N)
    return jax.jacfwd(grad_wrt_params)(jnp.ravel(x))


def influence_matrix(model_fn: Callable, params, x, labels,
                     hist: Optional[LBFGSHistory] = None,
                     taylor_iters: int = 10) -> jnp.ndarray:
    """Influence function of a model, shape ``(M_out, N_in)``.

    ``If[j, i] = (d model_j / d theta) . H^{-1} . (d^2 L / d x_i d theta)``
    with ``L`` the MSE between ``model_fn(params, x)`` and ``labels``.

    Mirrors reference ``influence_matrix`` (``autograd_tools.py:94-149``):
    inverse Hessian from L-BFGS curvature pairs when ``hist`` is given, else
    the Taylor-series approximation; the O(M*N) Python loop becomes
    jacrev/vmap/matmul.
    """
    flat, unravel = ravel_pytree(params)
    x_flat = jnp.ravel(x)
    y_flat = jnp.ravel(labels)

    def loss_fn(p, xx):
        pred = jnp.ravel(model_fn(p, xx))
        return jnp.mean((pred - y_flat) ** 2)

    # (P, N) mixed derivative
    cross = cross_derivative(loss_fn, params, x)

    if hist is not None:
        ihvp = jax.vmap(lambda col: inv_hessian_mult(hist, col),
                        in_axes=1, out_axes=1)(cross)
    else:
        def f_params(p_flat):
            return loss_fn(unravel(p_flat), x_flat.reshape(x.shape))

        ihvp = jax.vmap(
            lambda col: inverse_hessian_vec_prod(f_params, flat, col,
                                                 maxiter=taylor_iters),
            in_axes=1, out_axes=1)(cross)

    # model Jacobian (M, P)
    jac = jax.jacrev(
        lambda p_flat: jnp.ravel(model_fn(unravel(p_flat),
                                          x_flat.reshape(x.shape))))(flat)
    return jac @ ihvp
