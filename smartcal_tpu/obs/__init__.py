"""Run-scoped observability: RunLog (JSONL events), span tracing,
counters/gauges, jax compile/memory listeners.

Quick use::

    from smartcal_tpu import obs

    with obs.recording("run.jsonl", meta={"entry": "my_tool"}):
        obs.install_compile_listener()
        with obs.span("episode", episode=0):
            ...                       # nested spans record stage timings
        obs.active().log("episode", episode=0, score=1.2)

Everything is a strict no-op while no RunLog is active; aggregate runs
with ``tools/obs_report.py``.  The package imports neither jax nor numpy
— it reads jax lazily from ``sys.modules`` only, so importing obs can
never initialize (or wedge) an accelerator backend.
"""

from . import (baselines, collect, flightrec, regress,    # noqa: F401
               slo, tracectx)
from .baselines import (BF16_REL_BAND, BaselineStore,      # noqa: F401
                        host_fingerprint)
from .console import echo, emit_json                       # noqa: F401
from .costs import (device_peak, log_roofline_peak,        # noqa: F401
                    record_stage_cost, stage_cost)
from .diagnostics import (UpdateDiag, diag_steps,          # noqa: F401
                          diag_to_host, make_diag, zero_diag)
from .flightrec import (arm_flight_recorder,               # noqa: F401
                        flight_recorder_stats, flush_flight_recorder,
                        note_shed)
from .registry import (counter_add, counters_snapshot,     # noqa: F401
                       flush_counters, gauge_set, install_cache_listener,
                       install_compile_listener, log_memory_gauges,
                       reset_counters)
from .runlog import (SCHEMA_VERSION, RunLog, activate,     # noqa: F401
                     active, deactivate, recording, sanitize)
from .slo import SloBurnDetector                           # noqa: F401
from .spans import span                                    # noqa: F401
from .watchdog import Watchdog, WatchdogConfig             # noqa: F401


def log_solver_stats(stats: "object", **tags: object) -> None:
    """Record a ``solver`` event from a ``cal.solver.SolverStats`` (forces
    the small stat arrays to host — only called with telemetry on).

    Adds the analytic line-search evaluation model from ``ops.lbfgs``:
    the L-BFGS iteration counts are the dynamic factor threaded out of
    the jitted solve; evals-per-iteration is a static property of the
    compiled line-search loop structure."""
    rl = active()
    if rl is None or stats is None:
        return
    from smartcal_tpu.ops import lbfgs

    inner = [int(v) for v in list(stats.inner_iters)]
    total_inner = sum(inner) + int(stats.init_iters)
    per_ls = lbfgs.linesearch_phi_evals()
    rl.log("solver",
           admm_iters=int(stats.admm_iters),
           primal_resid=[float(v) for v in list(stats.primal_resid)],
           inner_iters=inner,
           init_iters=int(stats.init_iters),
           n_segments=int(stats.n_segments),
           lbfgs_iters_total=total_inner,
           phi_evals_per_linesearch=per_ls,
           phi_evals_est=total_inner * per_ls,
           **tags)
