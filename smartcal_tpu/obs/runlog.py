"""Run-scoped JSONL event log: the durable half of the observability layer.

One training/bench run = one ``RunLog``: a JSONL stream whose FIRST line is
a header record (run-id, schema version, host + device metadata) and whose
remaining lines are events (``episode``, ``span``, ``solver``, ``gauge``,
``probe``, ...).  Design points, each fixing a concrete failure of the old
``utils.metrics.JsonlLogger``:

* **Non-finite sanitization** — ``json.dumps`` happily writes bare ``NaN``
  / ``Infinity`` tokens, which are NOT JSON; every downstream reader
  (``tools/obs_report.py``, ``tools/summarize_demix_curves.py``, jq) then
  chokes on exactly the interesting lines (a diverged solve is when you
  need the record).  All floats are checked recursively; non-finite values
  serialize as ``null``.
* **Buffered writes with a bounded flush interval** — the old logger
  flushed per line; at per-span granularity that is a syscall per event on
  the hot path.  Events buffer up to ``flush_lines`` or ``flush_interval``
  seconds, whichever trips first, so a crash loses at most a couple of
  seconds of telemetry.
* **Size-based rotation** — long sweeps append forever; at ``max_bytes``
  the stream rotates to ``<path>.<n>`` and a fresh header (same run-id,
  incremented ``rotated``) opens the new segment, so a reader can always
  reassemble the run.
* **Thread safety** — spans are recorded from the episode-prefetch worker
  thread (envs/radio.run_pipelined) concurrently with the main thread; all
  writes serialize on one lock.

The module also owns the ACTIVE-run registry: ``activate``/``deactivate``
push/pop the process-wide current ``RunLog`` and ``active()`` reads it.
Every other obs primitive (spans, counters, listeners) checks ``active()``
first and is a strict no-op when no run is recording — instrumented code
pays one function call and one ``None`` check.
"""

from __future__ import annotations

import contextlib
import json
import math
import os
import socket
import sys
import threading
import time
from typing import Iterator, Optional

from . import flightrec, tracectx

# Schema history (the header's ``schema`` field; readers should accept
# >= their known version — every bump so far is purely additive):
#
# 1 — run_header / episode / span / solver / gauge / counters / memory /
#     jax_event / probe / log / result / multihost / run_end.
# 2 — training-internals telemetry: ``diag`` (per-update UpdateDiag
#     scalars, obs/diagnostics.py), ``replay_health`` (PER distribution
#     summary, rl.replay.replay_health), ``watchdog_trip`` (divergence
#     watchdog with ring-buffer context, obs/watchdog.py), ``cost``
#     (per-stage XLA flops/bytes, obs/costs.py) and ``roofline_peak``
#     (the fraction-of-peak denominator).
# 3 — fleet-wide tracing: any event may carry optional ``trace`` /
#     ``span`` / ``parent`` W3C-style ids (obs/tracectx.py; attached
#     automatically when a trace is adopted); new events
#     ``clock_offset`` (per-peer skew estimate from IPC envelope
#     send/recv timestamps), ``slo_burn`` (windowed burn-rate detector,
#     obs/slo.py) and ``blackbox_flush`` (flight-recorder dump header,
#     obs/flightrec.py).
SCHEMA_VERSION = 3


def _gen_run_id() -> str:
    return f"{int(time.time()):x}-{os.urandom(4).hex()}"


def sanitize(v: object) -> object:
    """Recursively convert ``v`` into JSON-safe data: non-finite floats ->
    None, numpy/jax scalars -> python scalars, arrays -> (sanitized)
    lists, unknown objects -> ``str``."""
    if v is None or isinstance(v, (bool, int, str)):
        return v
    if isinstance(v, float):                 # covers np.float64 (subclass)
        return v if math.isfinite(v) else None
    if isinstance(v, dict):
        return {str(k): sanitize(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [sanitize(x) for x in v]
    if getattr(v, "ndim", None) == 0 and hasattr(v, "item"):
        try:
            return sanitize(v.item())        # numpy / jax scalar
        except Exception:
            return str(v)
    if hasattr(v, "tolist"):
        try:
            return sanitize(v.tolist())      # numpy / jax array
        except Exception:
            return str(v)
    return str(v)


def _device_meta() -> dict:
    """Host/device metadata for the header.  Reads jax ONLY if it is
    already imported (never triggers the import, and a failure to
    initialize a backend must never kill the run being observed — the
    one-client TPU-tunnel rule).  SMARTCAL_OBS_NO_DEVICE_META=1 skips the
    device probe entirely, e.g. for side processes that must not touch
    the TPU client."""
    meta = {"host": socket.gethostname(), "pid": os.getpid(),
            "python": sys.version.split()[0]}
    jax_mod = sys.modules.get("jax")
    if jax_mod is None:
        return meta
    try:
        meta["jax"] = jax_mod.__version__
    except Exception:
        pass
    if os.environ.get("SMARTCAL_OBS_NO_DEVICE_META", "") == "1":
        return meta
    try:
        devs = jax_mod.devices()
        meta["platform"] = devs[0].platform
        meta["n_devices"] = len(devs)
        meta["devices"] = [str(d) for d in devs[:8]]
    except Exception as e:                   # wedged tunnel, no backend, ...
        meta["device_probe_error"] = repr(e)
    return meta


class RunLog:
    """Append-mode, buffered, rotating JSONL event stream (``None`` path
    disables it — every method is then a no-op)."""

    def __init__(self, path: Optional[str], run_id: Optional[str] = None,
                 flush_interval: float = 2.0, flush_lines: int = 64,
                 max_bytes: int = 256 * 1024 * 1024, header: bool = True,
                 meta: Optional[dict] = None):
        self.run_id = run_id or _gen_run_id()
        self._path = path
        self._lock = threading.RLock()
        self._buf: list = []
        self._flush_interval = max(0.0, float(flush_interval))
        self._flush_lines = max(1, int(flush_lines))
        self._max_bytes = int(max_bytes)
        self._header = header
        self._meta = dict(meta or {})
        self._rotations = 0
        self._last_flush = time.monotonic()
        if path:
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
            self._fh = open(path, "a")
            try:
                self._bytes = os.path.getsize(path)
            except OSError:
                self._bytes = 0
            if header:
                self._write_header()
        else:
            self._fh = None
            self._bytes = 0

    @property
    def path(self) -> Optional[str]:
        return self._path

    def _write_header(self):
        rec = {"t": round(time.time(), 3), "event": "run_header",
               "schema": SCHEMA_VERSION, "run_id": self.run_id,
               "rotated": self._rotations, "argv": sys.argv}
        rec.update(_device_meta())
        if self._meta:
            rec["meta"] = self._meta
        self._emit(rec, force_flush=True)

    def log(self, event: str, **fields: object) -> None:
        """Append one event record (buffered; see class docstring)."""
        if self._fh is None:
            return
        rec = {"t": round(time.time(), 3), "event": event}
        tf = tracectx.current_fields()
        if tf:
            rec.update(tf)       # explicit fields below may override
        rec.update(fields)
        self._emit(rec)

    def _emit(self, rec, force_flush: bool = False):
        line = json.dumps(sanitize(rec), allow_nan=False) + "\n"
        flightrec.record_line(line)   # flight-recorder tee (no-op unarmed)
        with self._lock:
            if self._fh is None:
                return
            self._buf.append(line)
            self._bytes += len(line)
            now = time.monotonic()
            if (force_flush or len(self._buf) >= self._flush_lines
                    or now - self._last_flush >= self._flush_interval):
                self._flush_locked()
            if self._bytes >= self._max_bytes:
                self._rotate_locked()

    def _flush_locked(self):
        if self._buf:
            self._fh.write("".join(self._buf))
            self._fh.flush()
            self._buf.clear()
        self._last_flush = time.monotonic()

    def _rotate_locked(self):
        """Close the full segment as ``<path>.<n>`` and reopen fresh (same
        run-id; the new header carries the incremented ``rotated``)."""
        self._flush_locked()
        self._fh.close()
        self._rotations += 1
        os.replace(self._path, f"{self._path}.{self._rotations}")
        self._fh = open(self._path, "a")
        self._bytes = 0
        if self._header:
            self._write_header()

    def flush(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._flush_locked()

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._flush_locked()
                self._fh.close()
                self._fh = None

    @property
    def closed(self) -> bool:
        return self._fh is None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# Active-run registry (process-wide; shared across threads on purpose — the
# prefetch worker must record into the run its parent opened)
# ---------------------------------------------------------------------------

_active_stack: list = []
_active_lock = threading.Lock()


def activate(runlog: RunLog) -> RunLog:
    """Make ``runlog`` the process-wide active run (stack discipline)."""
    with _active_lock:
        _active_stack.append(runlog)
    return runlog


def deactivate(runlog: Optional[RunLog] = None) -> None:
    """Pop the active run (or remove ``runlog`` specifically)."""
    with _active_lock:
        if not _active_stack:
            return
        if runlog is None:
            _active_stack.pop()
        elif runlog in _active_stack:
            _active_stack.remove(runlog)


def active() -> Optional[RunLog]:
    """The currently recording RunLog, or None (the no-op fast path)."""
    try:
        return _active_stack[-1]
    except IndexError:
        return None


@contextlib.contextmanager
def recording(path_or_runlog: "str | RunLog",
              **kwargs: object) -> Iterator[RunLog]:
    """``with recording("run.jsonl") as rl:`` — create (when given a
    path), activate, and on exit deactivate (and close only if created
    here)."""
    created = not isinstance(path_or_runlog, RunLog)
    rl = RunLog(path_or_runlog, **kwargs) if created else path_or_runlog
    activate(rl)
    try:
        yield rl
    finally:
        deactivate(rl)
        if created:
            rl.close()
