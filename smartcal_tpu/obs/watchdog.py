"""Divergence watchdog: trip on non-finite / exploding training internals.

Consumes the per-update :class:`~smartcal_tpu.obs.diagnostics.UpdateDiag`
stream (host dicts) and, optionally, replay-health summaries, and detects
the three ways a hint-constrained run dies silently:

* **non-finite** — NaN/Inf in any loss, gradient norm, or Q statistic
  (the canonical diverged-critic signature);
* **exploding gradients** — a gradient norm exceeding ``grad_mult`` x its
  own exponential moving average (after ``warmup`` observations, so the
  first noisy steps don't trip it);
* **Q blowup** — ``|q|`` beyond ``q_limit`` (a diverging critic's values
  race ahead of any reachable return long before the loss goes NaN).

On a trip the watchdog logs ONE structured ``watchdog_trip`` event into
the active RunLog — reason, offending step, the triggering values, and a
ring buffer of the last ``ring`` diagnostics (the context you need to see
*how* it died, not just that it died) — and latches ``tripped``.  Drivers
poll ``tripped`` (or get ``True`` back from ``observe``) and exit their
episode loop gracefully instead of burning the rest of the budget.

Host-side, stdlib-only: no jax, no numpy — values arrive as python
floats from ``diagnostics.diag_to_host``.
"""

from __future__ import annotations

import dataclasses
import math
from collections import deque
from typing import Optional

from .runlog import active

# fields whose non-finiteness constitutes a trip on its own
_FINITE_FIELDS = ("critic_loss", "actor_loss", "critic_grad_norm",
                  "actor_grad_norm", "q_mean", "q_min", "q_max")
# fields the EWMA explosion detector tracks
_GRAD_FIELDS = ("critic_grad_norm", "actor_grad_norm")


@dataclasses.dataclass(frozen=True)
class WatchdogConfig:
    grad_mult: float = 50.0     # trip at grad > grad_mult * EWMA(grad)
    ewma_alpha: float = 0.05    # EWMA smoothing (per observation)
    warmup: int = 20            # observations before the EWMA check arms
    grad_floor: float = 1e-3    # EWMA floor: tiny early grads must not
                                # make any normal step look explosive
    q_limit: float = 1e6        # |q_mean|/|q_max| beyond this trips
    ring: int = 32              # diagnostics kept for trip context


class Watchdog:
    """Streaming divergence detector (see module doc).

    One instance per run; feed it with ``observe(step_diag)`` per update
    and (optionally) ``observe_replay(health)`` per train block.
    """

    def __init__(self, cfg: Optional[WatchdogConfig] = None):
        self.cfg = cfg or WatchdogConfig()
        self.tripped = False
        self.trip_reason: Optional[str] = None
        self.trips = 0                  # lifetime count (survives reset())
        self._ring = deque(maxlen=self.cfg.ring)
        self._ewma = {k: None for k in _GRAD_FIELDS}
        self._n = {k: 0 for k in _GRAD_FIELDS}
        self._seen = 0

    def reset(self) -> None:
        """Un-latch after a recovery rollback (runtime.recovery): the trip
        state clears so the retried trajectory is monitored afresh, while
        the gradient EWMAs and the lifetime ``trips`` count survive — the
        healthy pre-trip baseline is exactly what the retry should be
        judged against."""
        self.tripped = False
        self.trip_reason = None
        self._ring.clear()

    # -- detectors --------------------------------------------------------
    def _check_finite(self, diag: dict) -> Optional[str]:
        for k in _FINITE_FIELDS:
            v = diag.get(k)
            if v is None:
                # sanitized-to-null upstream IS a non-finite sighting
                if k in diag:
                    return f"non_finite:{k}"
                continue
            if not math.isfinite(v):
                return f"non_finite:{k}"
        return None

    def _check_grads(self, diag: dict) -> Optional[str]:
        cfg = self.cfg
        reason = None
        for k in _GRAD_FIELDS:
            v = diag.get(k)
            # exact zeros are skipped entirely: a pre-buffer-fill no-learn
            # step and TD3's delayed-actor skip steps report 0.0, and
            # folding those into the EWMA would make the FIRST real
            # gradient look explosive
            if v is None or not math.isfinite(v) or v == 0.0:
                continue
            ewma = self._ewma[k]
            if (ewma is not None and self._n[k] > cfg.warmup
                    and v > cfg.grad_mult * max(ewma, cfg.grad_floor)):
                reason = (f"exploding_grad:{k} "
                          f"({v:.3e} > {cfg.grad_mult:g} x ewma "
                          f"{max(ewma, cfg.grad_floor):.3e})")
            # the EWMA keeps integrating even on the trip observation so a
            # non-halting consumer sees a decaying alarm, not a latch
            self._ewma[k] = (v if ewma is None
                             else (1 - cfg.ewma_alpha) * ewma
                             + cfg.ewma_alpha * v)
            self._n[k] += 1
        return reason

    def _check_q(self, diag: dict) -> Optional[str]:
        for k in ("q_mean", "q_max", "q_min"):
            v = diag.get(k)
            if v is not None and math.isfinite(v) \
                    and abs(v) > self.cfg.q_limit:
                return f"q_blowup:{k} (|{v:.3e}| > {self.cfg.q_limit:g})"
        return None

    # -- feed -------------------------------------------------------------
    def observe(self, diag: dict, step: Optional[int] = None,
                **tags) -> bool:
        """Feed one per-update diagnostics dict; returns ``tripped``."""
        self._seen += 1
        self._ring.append({"step": step, **diag})
        if self.tripped:
            return True
        reason = (self._check_finite(diag) or self._check_grads(diag)
                  or self._check_q(diag))
        if reason is not None:
            self._trip(reason, step, tags)
        return self.tripped

    def observe_replay(self, health: dict, **tags) -> bool:
        """Feed one replay-health summary; a non-finite priority mass or
        entropy means the PER distribution itself is poisoned."""
        if self.tripped:
            return True
        for k in ("priority_entropy", "priority_total", "is_weight_max"):
            v = health.get(k)
            if v is not None and isinstance(v, float) \
                    and not math.isfinite(v):
                self._trip(f"replay_non_finite:{k}", None, tags)
                break
        return self.tripped

    def _trip(self, reason: str, step, tags: dict):
        self.tripped = True
        self.trip_reason = reason
        self.trips += 1
        rl = active()
        if rl is not None:
            rl.log("watchdog_trip", reason=reason, step=step,
                   observations=self._seen, ring=list(self._ring), **tags)
            rl.flush()
        from . import flightrec
        flightrec.flush("watchdog_trip", {"reason": reason, "step": step})
