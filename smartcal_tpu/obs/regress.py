"""Noise-aware change detection over fingerprinted baselines.

The *compare* half of the regression radar (store half:
:mod:`smartcal_tpu.obs.baselines`).  Two design rules:

1. **Cross-fingerprint comparisons are refused, not attempted.**  A
   comparison between measurements taken on different hosts (core
   count, platform, jaxlib, dtype policy) raises
   :class:`FingerprintMismatch` — the exact failure mode of the
   2026-08-07 tier-1 budget incident (24-core numbers compared on a
   1-core container) made structurally impossible.

2. **A regression is a claim about distributions, not two numbers.**
   Sampled metrics (wall time) are compared with the bootstrap-CI
   machinery proven in ``tools/obs_report.py``'s learning-verdict
   section: resample both sample sets, take the ratio-of-means
   distribution, and FIRE only when the measured relative delta
   exceeds the threshold AND the CI is separated from the warn line —
   a single noisy sample cannot fire the gate, and every finding
   carries the measured delta plus the noise band it was judged
   against.

Deterministic metrics (peak bytes, flops, compile counts) compare as
scalars with their own relative thresholds; numeric-drift metrics
compare against the documented bf16 band (``BF16_REL_BAND``) as an
absolute ceiling.  Improvements never FIRE — the radar is one-sided by
design (bless a speedup with ``--update-baseline``).

Stdlib only (``random.Random`` bootstrap, deterministic seed), per the
obs package contract.
"""

from __future__ import annotations

import dataclasses
import random
import statistics
from typing import Dict, List, Optional, Tuple

from .baselines import (BF16_REL_BAND, BaselineStore,
                        fingerprint_digest, statics_digest)

FIRE = "FIRE"
WARN = "WARN"
OK = "OK"
NO_BASELINE = "NO BASELINE"


class FingerprintMismatch(ValueError):
    """Baseline and measurement come from different hosts/configs —
    comparing them would be the cross-host bug this subsystem exists
    to prevent, so the detector refuses by construction."""


@dataclasses.dataclass
class Policy:
    """Per-metric comparison policy.  ``kind``:

    * ``"sampled"`` — bootstrap ratio-of-means CI; FIRE needs delta >
      fire_rel AND ci_lo > 1 + warn_rel (CI separation from the warn
      line, so noise alone cannot fire).
    * ``"scalar"`` — deterministic value; plain relative thresholds.
    * ``"band"`` — absolute ceiling (numeric drift vs the documented
      bf16 band); FIRE when the measured value exceeds ``band``.
    """
    kind: str
    warn_rel: float = 0.15
    fire_rel: float = 0.40
    band: float = BF16_REL_BAND


#: Default policies by metric name.  wall_s thresholds are loose on
#: purpose: the 1-core CI container's tiny-stage timings have measured
#: cv up to ~10%, and the gate's job is catching 2x slowdowns, not 5%
#: drifts (those show up as WARN trend lines in the report).
DEFAULT_POLICIES: Dict[str, Policy] = {
    "wall_s": Policy("sampled", warn_rel=0.15, fire_rel=0.40),
    "peak_bytes": Policy("scalar", warn_rel=0.05, fire_rel=0.25),
    "flops": Policy("scalar", warn_rel=0.01, fire_rel=0.10),
    "compile_events": Policy("scalar", warn_rel=0.0, fire_rel=0.0),
    "rel_err": Policy("band"),
}


def policy_for(metric: str,
               overrides: Optional[Dict[str, Policy]] = None) -> Policy:
    table = dict(DEFAULT_POLICIES)
    if overrides:
        table.update(overrides)
    if metric in table:
        return table[metric]
    if metric.startswith("rel_err"):
        return table["rel_err"]
    return Policy("scalar")


@dataclasses.dataclass
class Finding:
    stage: str
    metric: str
    verdict: str
    delta_rel: Optional[float]        # (new - base) / base, None w/o base
    new_value: float
    base_value: Optional[float]
    noise_band: str                   # human-readable band it was judged in
    ci95: Optional[Tuple[float, float]] = None  # ratio CI (sampled only)

    def render(self) -> str:
        d = ("n/a" if self.delta_rel is None
             else f"{self.delta_rel:+.1%}")
        ci = (f" ci95=[{self.ci95[0]:.3f},{self.ci95[1]:.3f}]x"
              if self.ci95 else "")
        base = ("-" if self.base_value is None
                else f"{self.base_value:.6g}")
        return (f"[{self.verdict:>11s}] {self.stage}.{self.metric}: "
                f"{self.new_value:.6g} vs base {base} (delta {d}, "
                f"noise {self.noise_band}{ci})")


def bootstrap_ratio_ci(new: List[float], base: List[float],
                       n_boot: int = 2000, seed: int = 0,
                       pct: Tuple[float, float] = (2.5, 97.5),
                       ) -> Tuple[float, float]:
    """Percentile CI over mean(new*)/mean(base*) under paired
    resampling with replacement — the obs_report learning-verdict
    bootstrap applied to a ratio.  Deterministic for a given seed."""
    rng = random.Random(seed)
    nn, nb = len(new), len(base)
    ratios = []
    for _ in range(n_boot):
        mn = statistics.fmean(new[rng.randrange(nn)] for _ in range(nn))
        mb = statistics.fmean(base[rng.randrange(nb)] for _ in range(nb))
        ratios.append(mn / mb if mb else float("inf"))
    ratios.sort()

    def q(p: float) -> float:
        i = min(len(ratios) - 1, max(0, int(round(
            p / 100.0 * (len(ratios) - 1)))))
        return ratios[i]

    return q(pct[0]), q(pct[1])


def _compare_sampled(stage: str, metric: str, pol: Policy,
                     new_m: Dict[str, object], base_m: Dict[str, object],
                     seed: int) -> Finding:
    new_s = [float(x) for x in new_m["samples"]]
    base_s = [float(x) for x in base_m["samples"]]
    mean_new = statistics.fmean(new_s)
    mean_base = statistics.fmean(base_s)
    delta = mean_new / mean_base - 1.0 if mean_base else float("inf")
    lo, hi = bootstrap_ratio_ci(new_s, base_s, seed=seed)
    cv = float(base_m.get("cv", 0.0))
    band = f"base cv={cv:.1%}, warn>{pol.warn_rel:.0%}, fire>{pol.fire_rel:.0%}"
    if delta > pol.fire_rel and lo > 1.0 + pol.warn_rel:
        verdict = FIRE
    elif delta > pol.warn_rel and lo > 1.0:
        verdict = WARN
    else:
        verdict = OK
    return Finding(stage, metric, verdict, delta, mean_new, mean_base,
                   band, ci95=(lo, hi))


def _compare_scalar(stage: str, metric: str, pol: Policy,
                    new_v: float, base_v: float) -> Finding:
    delta = (new_v - base_v) / base_v if base_v else (
        0.0 if new_v == base_v else float("inf"))
    band = f"warn>{pol.warn_rel:.0%}, fire>{pol.fire_rel:.0%}"
    if delta > pol.fire_rel:
        verdict = FIRE
    elif delta > pol.warn_rel:
        verdict = WARN
    else:
        verdict = OK
    return Finding(stage, metric, verdict, delta, new_v, base_v, band)


def _compare_band(stage: str, metric: str, pol: Policy,
                  new_v: float, base_v: Optional[float]) -> Finding:
    delta = (None if base_v in (None, 0.0)
             else (new_v - base_v) / base_v)
    band = f"abs band<{pol.band:g}"
    if new_v > pol.band:
        verdict = FIRE
    elif new_v > 0.5 * pol.band:
        verdict = WARN
    else:
        verdict = OK
    return Finding(stage, metric, verdict, delta, new_v, base_v, band)


def compare_entry(entry: Dict[str, object], stage: str,
                  statics: Dict[str, object], fp: Dict[str, object],
                  measured: Dict[str, Dict[str, object]],
                  policies: Optional[Dict[str, Policy]] = None,
                  seed: int = 0) -> List[Finding]:
    """Judge ``measured`` metrics against one baseline entry.

    Raises :class:`FingerprintMismatch` unless the measurement's host
    fingerprint AND statics signature digest-match the entry's — the
    caller cannot accidentally compare across hosts or shapes.
    """
    fpd = fingerprint_digest(fp)
    if entry.get("fingerprint_digest") != fpd:
        raise FingerprintMismatch(
            f"stage {stage!r}: baseline fingerprint "
            f"{entry.get('fingerprint_digest')} != measurement {fpd} "
            f"(baseline host: {entry.get('fingerprint')}; this host: "
            f"{fp}) — re-record on this host with --update-baseline")
    if entry.get("statics_digest") != statics_digest(statics):
        raise FingerprintMismatch(
            f"stage {stage!r}: statics signature changed "
            f"({entry.get('statics')} -> {statics}) — a different "
            "problem shape is not comparable; re-record")
    findings: List[Finding] = []
    base_metrics = entry["metrics"]
    for metric in sorted(measured):
        new_m = measured[metric]
        pol = policy_for(metric, policies)
        base_m = base_metrics.get(metric)
        if pol.kind == "band":
            base_v = (float(base_m["value"])
                      if base_m and base_m.get("kind") == "scalar"
                      else None)
            findings.append(_compare_band(
                stage, metric, pol, float(new_m["value"]), base_v))
            continue
        if base_m is None:
            findings.append(Finding(
                stage, metric, NO_BASELINE, None,
                float(new_m.get("value", new_m.get("mean", 0.0))),
                None, "no baseline for this metric"))
            continue
        if pol.kind == "sampled" and new_m.get("kind") == "samples" \
                and base_m.get("kind") == "samples":
            findings.append(_compare_sampled(
                stage, metric, pol, new_m, base_m, seed))
        else:
            new_v = float(new_m.get("value", new_m.get("mean", 0.0)))
            base_v = float(base_m.get("value", base_m.get("mean", 0.0)))
            findings.append(_compare_scalar(
                stage, metric, pol, new_v, base_v))
    return findings


def compare(store: BaselineStore, stage: str,
            statics: Dict[str, object],
            fp: Dict[str, object],
            measured: Dict[str, Dict[str, object]],
            policies: Optional[Dict[str, Policy]] = None,
            seed: int = 0) -> List[Finding]:
    """Store-level compare: NO BASELINE findings (never FIRE) when this
    (stage, statics, host) was never blessed — a fresh host's first run
    is informative, not red."""
    entry = store.get(stage, statics, fp)
    if entry is None:
        out = []
        for metric in sorted(measured):
            m = measured[metric]
            pol = policy_for(metric, policies)
            if pol.kind == "band":
                # the band is absolute — it applies on a fresh host too
                out.append(_compare_band(stage, metric, pol,
                                         float(m["value"]), None))
                continue
            out.append(Finding(
                stage, metric, NO_BASELINE, None,
                float(m.get("value", m.get("mean", 0.0))), None,
                "no baseline for this host/shape — record with "
                "--update-baseline"))
        return out
    return compare_entry(entry, stage, statics, fp, measured,
                         policies=policies, seed=seed)


def worst_verdict(findings: List[Finding]) -> str:
    order = {FIRE: 3, WARN: 2, NO_BASELINE: 1, OK: 0}
    if not findings:
        return OK
    return max(findings, key=lambda f: order.get(f.verdict, 0)).verdict
