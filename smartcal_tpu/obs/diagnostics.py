"""Jit-safe training-internals diagnostics: the ``UpdateDiag`` pytree.

The RL update steps (``rl/ddpg.py``, ``rl/td3.py``, ``rl/sac.py``,
``rl/sac_discrete.py``) optionally thread an :class:`UpdateDiag` out of
the jitted learn step — the same ``collect_stats=`` pattern as
``cal.solver.solve_admm``: with ``collect_diag=False`` the traced program
is the EXACT pre-diagnostics computation (bit-identical outputs, asserted
by tests/test_diagnostics.py); with ``True`` the step additionally
returns per-update health scalars computed from intermediates the update
already holds (gradients, Q batches, fresh/target params).  Everything is
a scalar, so the pytree costs nothing against the update itself and scans
/ stacks cleanly.

Quantities (all () float32 unless noted):

* ``critic_loss`` / ``actor_loss`` — the step's losses (actor 0 on
  TD3's delayed-update skip steps);
* ``critic_grad_norm`` / ``actor_grad_norm`` — global (all-leaf) L2
  gradient norms, THE divergence leading indicator;
* ``critic_update_ratio`` / ``actor_update_ratio`` — ||update|| /
  ||params||: the effective step size Adam actually took (a healthy run
  sits around 1e-3; a collapse to 0 or jump toward 1 is pathological);
* ``q_mean`` / ``q_min`` / ``q_max`` — critic value batch statistics
  (Q blowup shows here before the loss goes non-finite);
* ``target_drift`` — global L2 norm of (critic - target critic): how far
  the Polyak target trails, in parameter space;
* ``alpha`` / ``entropy`` — SAC temperature and policy entropy estimate
  (-mean log pi); 0 where the agent has neither;
* ``hint_residual`` — mean squared actor-hint mismatch for the
  hint-constrained updates (the ADMM constraint residual); 0 otherwise.

The module reads jax lazily (inside functions, from the caller's already-
imported jax) so that importing ``smartcal_tpu.obs`` keeps its contract
of never touching an accelerator backend.
"""

from __future__ import annotations

from typing import Any, Iterator, NamedTuple


class UpdateDiag(NamedTuple):
    """Per-update diagnostics pytree (all scalar leaves; see module doc)."""

    critic_loss: Any
    actor_loss: Any
    critic_grad_norm: Any
    actor_grad_norm: Any
    critic_update_ratio: Any
    actor_update_ratio: Any
    q_mean: Any
    q_min: Any
    q_max: Any
    target_drift: Any
    alpha: Any
    entropy: Any
    hint_residual: Any


def _jnp():
    import jax.numpy as jnp
    return jnp


def tree_norm(tree: object) -> "object":
    """Global L2 norm over every leaf of ``tree`` (0.0 for empty trees)."""
    import jax
    jnp = _jnp()
    sq = [jnp.sum(jnp.square(leaf)) for leaf in jax.tree_util.tree_leaves(tree)]
    if not sq:
        return jnp.asarray(0.0, jnp.float32)
    return jnp.sqrt(sum(sq))


def update_ratio(update_tree: object, param_tree: object,
                 eps: float = 1e-12) -> "object":
    """||update|| / ||params|| — the relative step the optimizer took."""
    return tree_norm(update_tree) / (tree_norm(param_tree) + eps)


def target_drift(params: object, target_params: object) -> "object":
    """Global L2 norm of (params - target_params)."""
    import jax
    diff = jax.tree_util.tree_map(lambda a, b: a - b, params, target_params)
    return tree_norm(diff)


def make_diag(**fields: object) -> UpdateDiag:
    """Build an :class:`UpdateDiag`, defaulting unset fields to 0.0 —
    agents fill what they have (DDPG has no alpha, TD3's skip steps have
    no actor update, ...)."""
    jnp = _jnp()
    zero = jnp.asarray(0.0, jnp.float32)
    vals = {k: zero for k in UpdateDiag._fields}
    for k, v in fields.items():
        if k not in vals:
            raise TypeError(f"unknown UpdateDiag field {k!r}")
        vals[k] = jnp.asarray(v, jnp.float32)
    return UpdateDiag(**vals)


def zero_diag() -> UpdateDiag:
    """The no-learn branch's diag (lax.cond needs matching structures)."""
    return make_diag()


def diag_to_host(diag: UpdateDiag) -> dict:
    """One device->host transfer of a (possibly step-stacked) UpdateDiag
    into ``{field: float | [float, ...]}`` — the watchdog/RunLog form.
    Called only when diagnostics are on; NaN/Inf survive as-is here (the
    RunLog sanitizes to null at serialization, the watchdog checks
    finiteness BEFORE that happens)."""
    import jax
    host = jax.device_get(diag)
    out = {}
    for k, v in zip(UpdateDiag._fields, host):
        arr = getattr(v, "tolist", lambda: v)()
        out[k] = arr
    return out


def diag_steps(host_diag: dict) -> "Iterator[dict]":
    """Iterate a ``diag_to_host`` dict as per-step dicts.  Scalar fields
    (an unstacked single update) yield exactly one step."""
    first = next(iter(host_diag.values()))
    if not isinstance(first, list):
        yield dict(host_diag)
        return
    n = len(first)
    for i in range(n):
        yield {k: (v[i] if isinstance(v, list) else v)
               for k, v in host_diag.items()}
