"""Fleet timeline collection: merge per-process JSONL trees into one run.

A fleet run writes one RunLog stream per process — the router's own
stream plus ``replica<rid>-g<attempt>.jsonl`` per replica generation,
each possibly rotated into ``.1``/``.2`` segments — and each process
stamps events with ITS wall clock.  This module reassembles the run:

* :func:`discover_streams` groups a directory's segments per stream
  (rotation-aware, black-box dumps excluded);
* :class:`TimelineMerger` loads streams, reads the ``clock_offset``
  events the router's pump emitted (min over IPC frames of
  ``recv_wall - send_wall`` — the handshake in serve/fleet.py), applies
  each peer's offset to its stream, and merges everything into one
  time-ordered, process-tagged event list;
* :func:`assemble_traces` groups the merged stream by ``trace`` id;
* :func:`request_paths` reconstructs each request's cross-process
  chain — ``fleet_dispatch`` (router) -> ``serve_admit`` (replica) ->
  ``serve_request`` (replica) -> ``fleet_result`` (router) — and joins
  the replica's per-batch stage spans (``serve_pack`` .. ``serve_sigma``
  share one batch across member requests, so they are keyed by
  ``(process, batch)``, not by trace) into a per-request critical-path
  record: queue wait vs IPC vs pack vs policy vs solve vs influence;
* :func:`completeness` scores the run: the fraction of COMPLETED
  requests whose full span tree reconstructed (the >=99% acceptance
  bar of the tracing work).

Stdlib only, by the obs-package rule: importing this can never
initialize an accelerator backend (and the collector must run on a
host with no jax at all).
"""

from __future__ import annotations

import json
import os
import re
import threading
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

# span name -> critical-path column (serve/server.py batch stages)
STAGE_COLUMNS: Dict[str, str] = {
    "serve_pack": "pack_s",
    "serve_policy": "policy_s",
    "serve_solve": "solve_s",
    "serve_influence": "influence_s",
    "serve_sigma": "sigma_s",
}

_SEGMENT_RE = re.compile(r"^(?P<base>.+\.jsonl)(?:\.(?P<n>\d+))?$")


def discover_streams(directory: str) -> Dict[str, List[str]]:
    """Map stream name (base filename) -> ordered segment paths.

    Rotated segments (``<base>.jsonl.1`` .. ``.N``) come before the
    live ``<base>.jsonl`` tail, matching write order.  Flight-recorder
    dumps (``blackbox_*``) are a different artifact class and are
    excluded."""
    streams: Dict[str, List[Tuple[int, str]]] = {}
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return {}
    for name in names:
        if name.startswith("blackbox_"):
            continue
        m = _SEGMENT_RE.match(name)
        if m is None:
            continue
        seq = int(m.group("n")) if m.group("n") else 10 ** 9
        streams.setdefault(m.group("base"), []).append(
            (seq, os.path.join(directory, name)))
    return {base: [p for (_, p) in sorted(segs)]
            for base, segs in sorted(streams.items())}


def read_stream(paths: Sequence[str]) -> Tuple[str, List[Dict[str, Any]],
                                               int]:
    """Load one stream's segments in order; returns ``(proc, events,
    n_corrupt)``.  ``proc`` comes from the first ``run_header``'s
    run_id (the fleet names replica streams ``replica<rid>``), falling
    back to the first segment's filename stem.  Corrupt lines — a
    crashed writer's torn tail — are counted, never fatal."""
    events: List[Dict[str, Any]] = []
    proc: Optional[str] = None
    n_corrupt = 0
    for path in paths:
        try:
            fh = open(path, "r")
        except OSError:
            n_corrupt += 1
            continue
        with fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    n_corrupt += 1
                    continue
                if not isinstance(rec, dict):
                    n_corrupt += 1
                    continue
                if proc is None and rec.get("event") == "run_header":
                    rid = rec.get("run_id")
                    if isinstance(rid, str) and rid:
                        proc = rid
                events.append(rec)
    if proc is None:
        stem = os.path.basename(paths[0]) if paths else "stream"
        proc = stem.split(".jsonl")[0]
    return proc, events, n_corrupt


class TimelineMerger:
    """Accumulates per-process streams and merges them onto one clock.

    Thread-safe: a live tailer may ``add_stream`` from a reader thread
    while a reporter calls ``merge``/``stats`` — all shared merge state
    (streams, offsets, corrupt counter) mutates under ``_lock``."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._streams: Dict[str, List[Dict[str, Any]]] = {}
        self._offsets: Dict[str, float] = {}
        self._n_corrupt = 0

    def add_stream(self, proc: str,
                   events: Iterable[Dict[str, Any]],
                   n_corrupt: int = 0) -> None:
        """Add (or extend) one process's event stream.  Any
        ``clock_offset`` events in it update the peer offset table —
        each logged value is the sender's running minimum-delay
        estimate, so the last one per peer wins."""
        evs = list(events)
        with self._lock:
            self._streams.setdefault(proc, []).extend(evs)
            self._n_corrupt += int(n_corrupt)
            for rec in evs:
                if rec.get("event") != "clock_offset":
                    continue
                peer = rec.get("peer")
                off = rec.get("offset_s")
                if isinstance(peer, str) and isinstance(off, (int, float)):
                    self._offsets[peer] = float(off)

    def add_directory(self, directory: str) -> None:
        """Discover and load every stream under ``directory``."""
        for _base, paths in discover_streams(directory).items():
            proc, events, bad = read_stream(paths)
            self.add_stream(proc, events, bad)

    def offsets(self) -> Dict[str, float]:
        """Peer process -> seconds to ADD to its wall timestamps to
        land on the router's clock."""
        with self._lock:
            return dict(self._offsets)

    def procs(self) -> List[str]:
        with self._lock:
            return sorted(self._streams)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"procs": len(self._streams),
                    "events": sum(len(v) for v in self._streams.values()),
                    "offsets": dict(self._offsets),
                    "corrupt_lines": self._n_corrupt}

    def merge(self) -> List[Dict[str, Any]]:
        """One time-ordered event list for the whole run.  Every event
        gains ``proc`` (its stream) and ``t_corr`` (its wall time
        shifted by the stream's clock offset, if any); within one
        stream the original write order breaks timestamp ties."""
        with self._lock:
            streams = {p: list(evs) for p, evs in self._streams.items()}
            offsets = dict(self._offsets)
        tagged: List[Tuple[float, str, int, Dict[str, Any]]] = []
        for proc, evs in streams.items():
            off = offsets.get(proc, 0.0)
            for i, rec in enumerate(evs):
                t = rec.get("t")
                base = float(t) if isinstance(t, (int, float)) else 0.0
                out = dict(rec)
                out["proc"] = proc
                out["t_corr"] = round(base + off, 6)
                tagged.append((out["t_corr"], proc, i, out))
        tagged.sort(key=lambda item: (item[0], item[1], item[2]))
        return [rec for (_, _, _, rec) in tagged]


def merge_directory(directory: str) -> List[Dict[str, Any]]:
    """Convenience: discover + load + merge one fleet run directory."""
    m = TimelineMerger()
    m.add_directory(directory)
    return m.merge()


def assemble_traces(events: Iterable[Dict[str, Any]]
                    ) -> Dict[str, List[Dict[str, Any]]]:
    """Group a merged stream by ``trace`` id (events without a trace
    field — gauges, beats, headers — are not request-scoped and are
    skipped)."""
    out: Dict[str, List[Dict[str, Any]]] = {}
    for rec in events:
        tid = rec.get("trace")
        if isinstance(tid, str) and tid:
            out.setdefault(tid, []).append(rec)
    return out


def _stage_spans(events: Iterable[Dict[str, Any]]
                 ) -> Dict[Tuple[str, int], Dict[str, float]]:
    """(proc, batch) -> {stage column: dur_s} for the batch stage
    spans.  One batch serves several requests, so stage spans join to
    member requests by batch id, never by trace id."""
    out: Dict[Tuple[str, int], Dict[str, float]] = {}
    for rec in events:
        if rec.get("event") != "span":
            continue
        col = STAGE_COLUMNS.get(str(rec.get("name")))
        batch = rec.get("batch")
        if col is None or not isinstance(batch, int):
            continue
        dur = rec.get("dur_s")
        if isinstance(dur, (int, float)):
            key = (str(rec.get("proc", "")), batch)
            out.setdefault(key, {})[col] = float(dur)
    return out


def request_paths(events: Sequence[Dict[str, Any]]
                  ) -> List[Dict[str, Any]]:
    """Reconstruct each request's cross-process critical path from a
    MERGED stream (``merge()`` output: proc-tagged, skew-corrected).

    Per trace: the router's ``fleet_dispatch``, the replica's
    ``serve_admit`` + ``serve_request``, the router's ``fleet_result``,
    and the (proc, batch)-joined stage durations.  A requeued job keeps
    its original trace id, so its record uses the LAST admit/serve pair
    (the one that actually served) and carries the requeue count."""
    spans = _stage_spans(events)
    paths: List[Dict[str, Any]] = []
    for tid, evs in assemble_traces(events).items():
        dispatches = [e for e in evs if e.get("event") == "fleet_dispatch"]
        admits = [e for e in evs if e.get("event") == "serve_admit"]
        serves = [e for e in evs if e.get("event") == "serve_request"]
        results = [e for e in evs if e.get("event") == "fleet_result"]
        if not dispatches:
            continue
        first_d = dispatches[0]
        admit = admits[-1] if admits else None
        serve = serves[-1] if serves else None
        rec: Dict[str, Any] = {
            "trace": tid,
            "job_id": first_d.get("job_id"),
            "replica": (admit or {}).get("replica"),
            "proc": (serve or admit or {}).get("proc"),
            "t_dispatch": first_d.get("t_corr"),
            "requeues": max(
                [int(e.get("requeues") or 0) for e in admits] or [0]),
            "requeued": any(e.get("requeue") for e in dispatches),
            "dispatches": len(dispatches),
            "completed": bool(results),
        }
        if admit is not None:
            ipc = (float(admit["t_corr"])
                   - float(dispatches[-1]["t_corr"]))
            rec["ipc_s"] = round(max(0.0, ipc), 6)
        if serve is not None:
            for k_src, k_dst in (("queue_wait_s", "queue_s"),
                                 ("service_s", "service_s"),
                                 ("total_s", "total_s")):
                v = serve.get(k_src)
                if isinstance(v, (int, float)):
                    rec[k_dst] = float(v)
            batch = serve.get("batch")
            if isinstance(batch, int):
                rec["batch"] = batch
                rec.update(spans.get((str(serve.get("proc", "")), batch),
                                     {}))
        rec["complete"] = bool(admits and serves)
        paths.append(rec)
    paths.sort(key=lambda r: (r.get("t_dispatch") or 0.0))
    return paths


def completeness(paths: Sequence[Dict[str, Any]],
                 require_stages: bool = False) -> Dict[str, Any]:
    """Score a run's trace reconstruction: among COMPLETED requests
    (those whose router saw a result), what fraction rebuilt the full
    cross-process chain?  ``require_stages`` additionally demands at
    least the solve-stage span joined in (real-CalibServer fleets; the
    sleep-stub's minimal instrumentation has solve only)."""
    done = [p for p in paths if p.get("completed")]
    ok = [p for p in done
          if p.get("complete")
          and (not require_stages or "solve_s" in p)]
    return {"n_requests": len(paths),
            "n_completed": len(done),
            "n_complete_trees": len(ok),
            "fraction": round(len(ok) / len(done), 6) if done else 0.0}
