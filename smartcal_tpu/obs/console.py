"""The sanctioned console output site for smartcal_tpu.

Human diagnostics route through ``echo`` (stderr + a structured ``log``
event when a RunLog is active, suppressible with ``quiet``); machine
payloads route through ``emit_json`` (stdout stays the machine interface
— bench/capture tooling parses the last stdout JSON line).  This module
is the ONLY place in the package allowed to call bare ``print`` —
``tests/test_no_bare_print.py`` enforces it, so diagnostics cannot
silently regress to unstructured stdout noise.
"""

from __future__ import annotations

import json
import sys

from .runlog import active, sanitize


def echo(msg: object, quiet: bool = False,
         event: "str | None" = "log", **fields: object) -> None:
    """Human-facing diagnostic: structured event (when recording) plus a
    stderr echo (unless ``quiet``).  ``event=None`` skips the structured
    record — for echoes whose content was already logged under another
    event (e.g. the per-episode score line)."""
    rl = active()
    if rl is not None and event is not None:
        rl.log(event, msg=str(msg), **fields)
    if not quiet:
        print(msg, file=sys.stderr, flush=True)


def emit_json(payload: dict, event: str = "result") -> None:
    """Machine-facing result line: always printed to STDOUT (the contract
    bench/capture scripts parse), mirrored into the RunLog when active."""
    rl = active()
    if rl is not None:
        rl.log(event, **payload)
    print(json.dumps(sanitize(payload)), flush=True)
