"""Host-fingerprinted performance/numerics baseline store.

The repo's headline numbers (63x influence, 12.7x peak-memory, 14.1x
warm restart, 8.7x fleet scale-out) were all one-shot r-stamped
artifacts with nothing watching them afterwards — and the 2026-08-07
tier-1 budget incident (24-core numbers silently compared on a 1-core
container) showed cross-host comparisons already bite.  This module is
the *store* half of the regression radar: a schema'd JSON document of
per-stage baselines, each keyed on

    stage | statics digest | host fingerprint digest

so a measurement recorded on one host/shape/config can never be
compared against a measurement from another BY CONSTRUCTION — a lookup
with a different fingerprint simply finds no baseline (and the
comparison layer, :mod:`smartcal_tpu.obs.regress`, additionally refuses
explicit cross-fingerprint compares).

Each entry carries a per-metric noise model: *sampled* metrics (wall
time) store the K raw samples plus mean/std/cv so the detector can
bootstrap a confidence interval over the ratio; *deterministic* metrics
(peak bytes, flops, compile counts, numeric scalars) store a single
value.  Writes are atomic (``runtime/atomic.py``) and the record path
mirrors graftlint's ``--update-baseline`` workflow: measure, then
re-run with the flag to bless the new numbers.

Stdlib only, like the rest of the obs package — jax/jaxlib versions
are read lazily from ``sys.modules`` so importing this can never
initialize a backend.
"""

from __future__ import annotations

import hashlib
import json
import os
import platform as _platform
import statistics
import sys
import threading
import time
from typing import Dict, List, Optional

SCHEMA_VERSION = 1

#: Documented bf16 relative-error band for the mixed-precision kernels
#: (cal/precision.py; asserted by the tier-1 parity tests since PR 13).
#: Numeric sentinel verdicts and the perf gate's drift metrics compare
#: against this unless a caller narrows it.
BF16_REL_BAND = 2e-2


def _nproc() -> int:
    try:
        return len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        return os.cpu_count() or 1


def _lazy_version(modname: str) -> Optional[str]:
    """Version of an ALREADY-IMPORTED module (obs contract: never
    trigger a jax import from the observability layer)."""
    mod = sys.modules.get(modname)
    if mod is None:
        return None
    return getattr(mod, "__version__", None)


def host_fingerprint() -> Dict[str, object]:
    """The identity a measurement is only comparable within.

    nproc is the *effective* core count (sched_getaffinity — a 24-core
    box running the gate in a 1-core cgroup fingerprints as 1 core,
    which is exactly the distinction the 2026-08-07 incident needed).
    jax/jaxlib versions come from sys.modules when loaded, else from
    importlib.metadata — either way without importing jax here.
    """
    jax_v = _lazy_version("jax")
    jaxlib_v = _lazy_version("jaxlib")
    if jax_v is None or jaxlib_v is None:
        try:
            from importlib import metadata
            jax_v = jax_v or metadata.version("jax")
            jaxlib_v = jaxlib_v or metadata.version("jaxlib")
        except Exception:
            pass
    x64 = os.environ.get("JAX_ENABLE_X64", "").lower() in ("1", "true")
    jax_mod = sys.modules.get("jax")
    if jax_mod is not None:
        try:
            x64 = bool(jax_mod.config.jax_enable_x64)
        except Exception:
            pass
    return {
        "nproc": _nproc(),
        "platform": _platform.system().lower(),
        "machine": _platform.machine(),
        "python": _platform.python_version(),
        "jax": jax_v,
        "jaxlib": jaxlib_v,
        "dtype_policy": {"x64": x64, "bf16_rel_band": BF16_REL_BAND},
    }


def _digest(obj: object) -> str:
    blob = json.dumps(obj, sort_keys=True, separators=(",", ":"),
                      default=str)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:12]


def fingerprint_digest(fp: Dict[str, object]) -> str:
    return _digest(fp)


def statics_digest(statics: Dict[str, object]) -> str:
    return _digest(statics)


def baseline_key(stage: str, statics: Dict[str, object],
                 fp: Dict[str, object]) -> str:
    return f"{stage}|{statics_digest(statics)}|{fingerprint_digest(fp)}"


def summarize_samples(samples: List[float]) -> Dict[str, object]:
    """Noise model for a sampled metric: the raw K samples plus
    mean/std/cv (population std — the samples ARE the distribution the
    detector resamples from, not a subsample of something larger)."""
    xs = [float(x) for x in samples]
    if not xs:
        raise ValueError("summarize_samples: need at least one sample")
    mean = statistics.fmean(xs)
    std = statistics.pstdev(xs) if len(xs) > 1 else 0.0
    return {
        "kind": "samples",
        "samples": xs,
        "n": len(xs),
        "mean": mean,
        "std": std,
        "cv": (std / mean) if mean else 0.0,
    }


def scalar_metric(value: float) -> Dict[str, object]:
    return {"kind": "scalar", "value": float(value)}


class BaselineSchemaError(ValueError):
    """The on-disk baseline document doesn't match the schema — the
    store refuses to silently compare against garbage."""


class BaselineStore:
    """Load/record/save interface over one baseline JSON document.

    The in-memory document cache and dirty flag are shared between the
    recording caller and any concurrent reader (the serving sentinel
    polls baselines from the supervisor thread while a gate run
    records), so every access goes through ``_lock`` — the fields are
    registered in graftlint's SHARED_FIELD_SPECS.
    """

    def __init__(self, path: str) -> None:
        self._path = os.fspath(path)
        self._lock = threading.Lock()
        self._doc: Optional[Dict[str, object]] = None
        self._dirty = False

    @property
    def path(self) -> str:
        return self._path

    # -- document lifecycle -------------------------------------------

    def _load_locked(self) -> Dict[str, object]:
        if self._doc is not None:
            return self._doc
        if not os.path.exists(self._path):
            self._doc = {"schema": SCHEMA_VERSION, "entries": {}}
            return self._doc
        try:
            with open(self._path, "r", encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            raise BaselineSchemaError(
                f"baseline store {self._path!r} unreadable ({e!r}) — "
                "delete it or restore from git, then re-record with "
                "--update-baseline") from e
        self._validate(doc)
        self._doc = doc
        return doc

    @staticmethod
    def _validate(doc: object) -> None:
        if not isinstance(doc, dict) or not isinstance(
                doc.get("entries"), dict):
            raise BaselineSchemaError(
                "baseline document must be {schema, entries:{...}}")
        if doc.get("schema") != SCHEMA_VERSION:
            raise BaselineSchemaError(
                f"baseline schema {doc.get('schema')!r} != "
                f"{SCHEMA_VERSION} — re-record with --update-baseline")
        for key, ent in doc["entries"].items():
            for field in ("stage", "statics", "fingerprint", "metrics"):
                if field not in ent:
                    raise BaselineSchemaError(
                        f"baseline entry {key!r} missing {field!r}")
            for mname, m in ent["metrics"].items():
                kind = m.get("kind")
                if kind == "samples":
                    if not m.get("samples"):
                        raise BaselineSchemaError(
                            f"{key}:{mname} sampled metric has no "
                            "samples")
                elif kind == "scalar":
                    if "value" not in m:
                        raise BaselineSchemaError(
                            f"{key}:{mname} scalar metric has no value")
                else:
                    raise BaselineSchemaError(
                        f"{key}:{mname} unknown metric kind {kind!r}")

    # -- lookup / record ----------------------------------------------

    def get(self, stage: str, statics: Dict[str, object],
            fp: Dict[str, object]) -> Optional[Dict[str, object]]:
        """The baseline entry for exactly this (stage, statics, host)
        — None when this host/shape has never been blessed.  A
        different fingerprint CANNOT return another host's entry: the
        fingerprint digest is part of the key."""
        key = baseline_key(stage, statics, fp)
        with self._lock:
            doc = self._load_locked()
            ent = doc["entries"].get(key)
            return json.loads(json.dumps(ent)) if ent else None

    def record(self, stage: str, statics: Dict[str, object],
               fp: Dict[str, object],
               metrics: Dict[str, Dict[str, object]]) -> Dict[str, object]:
        """Bless new numbers for (stage, statics, host), replacing any
        prior entry under the same key (the --update-baseline path)."""
        for mname, m in metrics.items():
            if m.get("kind") not in ("samples", "scalar"):
                raise BaselineSchemaError(
                    f"metric {mname!r}: build it with summarize_samples"
                    "() or scalar_metric()")
        entry = {
            "stage": stage,
            "statics": dict(statics),
            "statics_digest": statics_digest(statics),
            "fingerprint": dict(fp),
            "fingerprint_digest": fingerprint_digest(fp),
            "recorded_unix": time.time(),
            "metrics": metrics,
        }
        key = baseline_key(stage, statics, fp)
        with self._lock:
            doc = self._load_locked()
            doc["entries"][key] = entry
            self._dirty = True
        return entry

    def entries(self) -> List[Dict[str, object]]:
        with self._lock:
            doc = self._load_locked()
            return [json.loads(json.dumps(e))
                    for e in doc["entries"].values()]

    def save(self) -> bool:
        """Atomically persist if dirty; returns whether a write
        happened (readers concurrently see old-or-new, never a torn
        prefix — runtime/atomic.py)."""
        from smartcal_tpu.runtime.atomic import atomic_write_text
        with self._lock:
            if not self._dirty or self._doc is None:
                return False
            text = json.dumps(self._doc, indent=1, sort_keys=True)
            self._dirty = False
        atomic_write_text(self._path, text + "\n")
        return True
