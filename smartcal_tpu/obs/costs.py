"""FLOPs/bytes accounting for the jitted hot paths + roofline peaks.

``record_stage_cost(stage, fn, *args)`` lowers the EXACT jitted callable
a hot path is about to run (shape-only — ``.lower(...).compile()
.cost_analysis()``, the same machinery as ``cal.solver.cost_eval_flops``)
and logs ONE ``cost`` event with the XLA-counted flops and bytes
accessed.  Results are cached per (stage, abstract-signature), so a
training run pays the accounting once per compiled program, not per
step — the dynamic factor (how many times the program runs) comes from
the span stream, and ``tools/obs_report.py`` joins the two into the
per-stage achieved-FLOPs/s roofline table.

Known caveat, inherited from HLO cost analysis itself: a ``while_loop``
body is counted ONCE, so loop-dominated programs (the fused ADMM solve)
under-report; the numbers are roofline *floors*, and the solver's
per-iteration truth stays with ``cost_eval_flops``.  The report labels
them accordingly.

Collection is OFF by default (``set_enabled``) — an AOT lower+compile is
not free, and must never sneak into a timed region of a run that didn't
ask for it; the train drivers enable it under ``--diag``.  Call sites
that sit INSIDE a timed ``obs.span`` region pass ``defer=True``: the
(deduped) work is queued and executed by ``flush_pending()``, which
``TrainObs`` calls between episodes and at close — so the compile never
inflates the very span totals the roofline report divides by.

Reads jax lazily from ``sys.modules`` (the package contract: importing
``smartcal_tpu.obs`` never initializes an accelerator backend).
"""

from __future__ import annotations

import sys
import threading
from typing import Callable, Mapping, Optional, Sequence, Union

from .runlog import active

_lock = threading.Lock()
_enabled = False
_cache: dict = {}      # (stage, signature) -> result dict
_pending: list = []    # deferred (sig, stage, fn, args, statics, kwargs)

# Peak FLOPs/s by device kind (substring-matched against jax's
# ``device_kind``/``str(device)``, e.g. "TPU v5 lite") — the chip-probe
# reference obs_report quotes fraction-of-peak against (v5e numbers,
# matching bench.py's MFU refs: bf16 systolic peak and the ~4x-lower
# fp32 estimate the split-real solver actually contends with).  CPU and
# unrecognized TPU generations have no entry: claiming the wrong chip's
# peak would silently mis-scale fraction-of-peak, so the report degrades
# to dashes instead.
PEAK_FLOPS = {
    "v5 lite": {"bf16": 197e12, "fp32_est": 49e12, "chip": "v5e"},
    "v5e": {"bf16": 197e12, "fp32_est": 49e12, "chip": "v5e"},
}


def set_enabled(on: bool) -> None:
    """Globally arm/disarm cost recording (drivers: ``--diag``)."""
    global _enabled
    _enabled = bool(on)


def enabled() -> bool:
    return _enabled


def reset_cache() -> None:
    with _lock:
        _cache.clear()
        _pending.clear()


def _signature(args, kwargs) -> str:
    """Hashable abstract signature: leaf shapes/dtypes, statics by repr."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    sig = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        sig.append(f"{shape}:{dtype}" if shape is not None else repr(leaf))
    return str(treedef) + "|" + ";".join(sig)


def stage_cost(fn: Callable, *args: object,
               static_argnames: Sequence[str] = (),
               **kwargs: object) -> dict:
    """XLA cost analysis of ``fn(*args, **kwargs)``: ``{"flops": ...,
    "bytes_accessed": ...}`` (floats; absent metrics -> 0.0).

    ``fn`` may already be jit-wrapped (used as-is, sharing its trace
    cache) or a plain traceable callable (wrapped here, with
    ``static_argnames`` forwarded).
    """
    jax_mod = sys.modules.get("jax")
    if jax_mod is None:
        raise RuntimeError("jax not imported")
    jitted = fn if hasattr(fn, "lower") else \
        jax_mod.jit(fn, static_argnames=static_argnames)
    compiled = jitted.lower(*args, **kwargs).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):        # older jax returns [dict]
        ca = ca[0] if ca else {}
    ca = ca or {}
    out = {"flops": float(ca.get("flops", 0.0) or 0.0),
           "bytes_accessed": float(ca.get("bytes accessed", 0.0) or 0.0)}
    out.update(_memory_footprint(compiled))
    return out


def _memory_footprint(compiled) -> dict:
    """Peak-live-bytes accounting from the compiled executable's
    ``memory_analysis()``: argument + output + XLA temp (minus aliased
    donation reuse) is the executable's peak live set — the quantity the
    N-scaling report bounds per device.  Degrades to {} on backends/
    versions without the API (the cost event then simply has no
    footprint fields; tools/obs_report.py prints dashes)."""
    try:
        ma = compiled.memory_analysis()
        arg = float(ma.argument_size_in_bytes)
        out_b = float(ma.output_size_in_bytes)
        tmp = float(ma.temp_size_in_bytes)
        alias = float(getattr(ma, "alias_size_in_bytes", 0.0) or 0.0)
    except Exception:  # noqa: BLE001 — footprint is best-effort extra
        return {}
    return {"arg_bytes": arg, "out_bytes": out_b, "temp_bytes": tmp,
            "peak_bytes": arg + out_b + tmp - alias}


def _compute_and_log(stage, fn, args, static_argnames, kwargs,
                     shards=1, compute_dtype=None) -> dict:
    rl = active()
    try:
        cost = stage_cost(fn, *args, static_argnames=static_argnames,
                          **kwargs)
    except Exception as e:  # noqa: BLE001 — never kill the observed run
        cost = {"error": f"{type(e).__name__}: {e}"}
    shard_axes = None
    if isinstance(shards, Mapping):
        # per-axis form {axis name: size} (composed meshes, ISSUE 17):
        # the total division is over the product, and the per-axis sizes
        # are logged so the report can break the footprint out by axis.
        shard_axes = {str(a): int(n) for a, n in shards.items()
                      if int(n) > 1}
        shards = 1
        for n in shard_axes.values():
            shards *= n
    if shards and shards > 1 and "peak_bytes" in cost:
        # sharding-aware division: the lowered program is the fused
        # single-device equivalent (shard_map programs don't AOT-lower
        # through the plain-args contract), so the per-DEVICE peak under
        # an n-way shard is the fused peak / n — the big (B, ...)/(Nf,
        # ...) operands and temporaries partition, and the replicated
        # leftovers (4N x 4N solves, images) are a rounding error at the
        # scales where sharding is on.  Both numbers are logged.
        cost = dict(cost, shards=int(shards),
                    peak_bytes_per_shard=cost["peak_bytes"] / shards)
        if shard_axes:
            # footprint if ONLY that axis were sharded — the report's
            # per-axis column, showing what each axis alone buys
            cost["shard_axes"] = shard_axes
            cost["peak_bytes_per_axis"] = {
                a: cost["peak_bytes"] / n for a, n in shard_axes.items()}
    if compute_dtype is not None:
        cost = dict(cost, compute_dtype=str(compute_dtype))
    if rl is not None:
        rl.log("cost", stage=stage, **cost)
    return cost


def record_stage_cost(stage: str, fn: Callable, *args: object,
                      static_argnames: Sequence[str] = (),
                      defer: bool = False,
                      shards: Union[int, Mapping[str, int]] = 1,
                      compute_dtype: Optional[str] = None,
                      **kwargs: object) -> Optional[dict]:
    """Log the ``cost`` event for ``stage`` once per abstract signature.

    Strict no-op unless BOTH a RunLog is active and collection is
    enabled.  Failures are recorded (``cost`` event with ``error``) and
    negatively cached — accounting must never kill or repeatedly slow
    the run being observed.  ``defer=True`` (for call sites inside a
    timed span) queues the lower+compile for ``flush_pending()`` instead
    of paying it here.  Returns the cached cost dict or None (always
    None for a just-deferred signature).

    ``shards``/``compute_dtype`` are ACCOUNTING metadata, never passed
    to ``fn``: ``shards`` > 1 adds the sharding-aware footprint division
    (``peak_bytes_per_shard``); a ``{axis name: size}`` mapping divides
    by the product and additionally logs ``shard_axes`` plus the
    per-axis ``peak_bytes_per_axis`` breakout (registry names from
    ``parallel/mesh.py``); ``compute_dtype`` tags the event with
    the kernel's policy dtype ("bf16"/"f32") so the roofline report can
    pick the matching device peak instead of assuming f32.
    """
    rl = active()
    if rl is None or not _enabled:
        return None
    try:
        sig = (stage, _signature(args, kwargs))
    except Exception:
        sig = (stage, repr((len(args), sorted(kwargs))))
    with _lock:
        if sig in _cache:
            return _cache[sig]
        _cache[sig] = None               # claim: concurrent callers skip
        if defer:
            _pending.append((sig, stage, fn, args, static_argnames,
                             kwargs, shards, compute_dtype))
            return None
    cost = _compute_and_log(stage, fn, args, static_argnames, kwargs,
                            shards, compute_dtype)
    with _lock:
        _cache[sig] = cost
    return cost


def flush_pending() -> int:
    """Run the deferred cost analyses (call OUTSIDE any timed span —
    ``TrainObs`` does, between episodes and at close).  Returns how many
    were processed; cheap no-op when nothing is queued."""
    n = 0
    while True:
        with _lock:
            if not _pending:
                return n
            (sig, stage, fn, args, statics, kwargs, shards,
             compute_dtype) = _pending.pop(0)
        cost = _compute_and_log(stage, fn, args, statics, kwargs, shards,
                                compute_dtype)
        with _lock:
            _cache[sig] = cost
        n += 1


def device_peak() -> dict | None:
    """Peak-FLOPs reference for the current device, or None (CPU,
    unrecognized chip generation, jax not imported, probe failure)."""
    jax_mod = sys.modules.get("jax")
    if jax_mod is None:
        return None
    try:
        dev = jax_mod.devices()[0]
        platform = dev.platform
    except Exception:
        return None
    kind = str(getattr(dev, "device_kind", "") or "")
    probe = f"{kind} {dev}".lower()
    for sub, peak in PEAK_FLOPS.items():
        if sub in probe:
            return {"platform": platform, "device_kind": kind or None,
                    **peak}
    return None


def log_roofline_peak() -> dict | None:
    """Record one ``roofline_peak`` event (the report's fraction-of-peak
    denominator) when the platform has a known peak; None-safe no-op
    otherwise."""
    rl = active()
    if rl is None:
        return None
    peak = device_peak()
    if peak is not None:
        rl.log("roofline_peak", **peak)
    return peak
