"""W3C-style trace context: the process-crossing half of tracing.

PR 3 spans time host stages *within* one process; the fleet (PR 12
process actors, PR 16 serving replicas) crosses process boundaries, so
a request's spans land in different JSONL streams with nothing joining
them.  This module owns the (trace_id, span_id, parent_span_id) lineage
that joins them:

* a **carrier** is the serializable form — ``{"trace": <32-hex>,
  "span": <16-hex>}`` — small enough to ride in a framed-IPC envelope
  (:mod:`smartcal_tpu.runtime.ipc`) or a Job payload dict;
* an **envelope** is a carrier plus the sender's wall-clock ``t``, the
  raw material of the clock-offset handshake that lets the collector
  (:mod:`smartcal_tpu.obs.collect`) merge per-process timelines
  skew-corrected;
* the thread-local **active trace** is what :func:`current_fields`
  reads; :meth:`RunLog.log <smartcal_tpu.obs.runlog.RunLog.log>`
  auto-attaches it to every event, and :class:`~smartcal_tpu.obs.spans.
  Span` allocates child span ids from it, so instrumented code needs no
  changes to become trace-aware.

Dependency-free on purpose (stdlib only, no runlog/spans import): both
runlog and spans import *this* module, never the reverse.

STRICT NO-OP CONTRACT (mirrors spans): with no adopted trace,
:func:`current_fields` returns the shared empty dict and
:func:`push_span` returns ``None`` — instrumentation costs one
thread-local read.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Dict, Iterator, Optional, Tuple

_tls = threading.local()

_EMPTY: Dict[str, object] = {}


def new_trace_id() -> str:
    """A fresh 16-byte (32 hex char) trace id, W3C traceparent sized."""
    return os.urandom(16).hex()


def new_span_id() -> str:
    """A fresh 8-byte (16 hex char) span id."""
    return os.urandom(8).hex()


def _trace() -> Optional[str]:
    return getattr(_tls, "trace", None)


def _stack() -> list:
    st = getattr(_tls, "spans", None)
    if st is None:
        st = _tls.spans = []
    return st


def new_root_carrier() -> Dict[str, str]:
    """Mint a root carrier for a new request (no thread state touched):
    the router stamps one onto each Job at admission."""
    return {"trace": new_trace_id(), "span": new_span_id()}


def current_fields() -> Dict[str, object]:
    """``{"trace": ..., "span": ...}`` of the adopted trace, or the
    shared empty dict.  RunLog.log merges this into every record."""
    tid = _trace()
    if tid is None:
        return _EMPTY
    st = _stack()
    if st:
        return {"trace": tid, "span": st[-1]}
    return {"trace": tid}


def carrier() -> Optional[Dict[str, str]]:
    """The adopted trace as a serializable carrier, or None."""
    tid = _trace()
    if tid is None:
        return None
    st = _stack()
    out = {"trace": tid}
    if st:
        out["span"] = st[-1]
    return out


def envelope() -> Optional[Dict[str, object]]:
    """Carrier + sender wall time ``t`` — what rides an IPC frame.  The
    receiver's recv time minus ``t`` (minimized over frames) estimates
    the per-peer clock offset."""
    car = carrier()
    if car is None:
        return {"t": round(time.time(), 6)}
    out: Dict[str, object] = dict(car)
    out["t"] = round(time.time(), 6)
    return out


def fields_of(car: Optional[Dict[str, str]]) -> Dict[str, object]:
    """Event fields naming the carrier's own span (no new ids): for
    events that ARE the carrier's point of origin (``fleet_dispatch``)."""
    if not car or "trace" not in car:
        return {}
    out: Dict[str, object] = {"trace": car["trace"]}
    if car.get("span"):
        out["span"] = car["span"]
    return out


def child_fields(car: Optional[Dict[str, str]]) -> Dict[str, object]:
    """Event fields for a NEW child span of the carrier: a fresh span id
    with ``parent`` pointing at the carrier's span.  For point events
    that mark a hop (``serve_admit``, ``serve_request``)."""
    if not car or "trace" not in car:
        return {}
    out: Dict[str, object] = {"trace": car["trace"],
                              "span": new_span_id()}
    if car.get("span"):
        out["parent"] = car["span"]
    return out


def push_span() -> Optional[Tuple[str, Optional[str]]]:
    """Allocate a child span id under the adopted trace and make it
    current.  Returns ``(span_id, parent_span_id)``, or None when no
    trace is adopted (the no-op fast path).  Span.__enter__ calls this;
    Span.__exit__ must pair it with :func:`pop_span`."""
    tid = _trace()
    if tid is None:
        return None
    st = _stack()
    parent = st[-1] if st else None
    sid = new_span_id()
    st.append(sid)
    return sid, parent


def pop_span(span_id: str) -> None:
    """Pop ``span_id`` off the current thread's span stack (tolerant of
    a mismatched top, same as the spans name stack)."""
    st = _stack()
    if st and st[-1] == span_id:
        st.pop()
    elif span_id in st:
        st.remove(span_id)


@contextlib.contextmanager
def use_trace(car: Optional[Dict[str, str]]) -> Iterator[None]:
    """Adopt a remote carrier for the current thread: events logged and
    spans opened inside become part of the caller's trace.  ``None`` (or
    a carrier-less dict) is a no-op, so call sites need no guard."""
    if not car or "trace" not in car:
        yield
        return
    prev_trace = getattr(_tls, "trace", None)
    prev_spans = getattr(_tls, "spans", None)
    _tls.trace = car["trace"]
    _tls.spans = [car["span"]] if car.get("span") else []
    try:
        yield
    finally:
        _tls.trace = prev_trace
        _tls.spans = prev_spans
