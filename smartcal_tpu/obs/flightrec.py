"""Crash flight recorder: a bounded ring of recent events per process.

Postmortems of fleet incidents (the PR 15 cold-run back-pressure halt,
PR 16 kill/requeue runs) were reconstructed by hand from whatever the
buffered RunLog happened to have flushed before the process died.  The
flight recorder closes that gap: when **armed**, every serialized event
line that passes through :meth:`RunLog._emit <smartcal_tpu.obs.runlog.
RunLog._emit>` is also teed into an in-memory ring (independent of the
flush cadence), and :func:`flush` dumps the ring to
``blackbox_<pid>.jsonl`` in the armed directory the moment something
goes wrong — crash, circuit-open, shed burst, watchdog trip.

Each dump is self-describing: a ``blackbox_flush`` header line
(reason, pid, wall time, ring depth) followed by the ring contents,
appended so repeated trips in one process life stay ordered.  Dumps of
the same reason are rate-limited (default one per 5 s) so a shed storm
does not turn the recorder into its own I/O incident.

Armed by default in fleet workers (replica + actor worker mains);
training/bench entry points stay disarmed unless they opt in.  The ring
is process-global on purpose — one process, one black box.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Deque, Dict, Optional

DEFAULT_CAPACITY = 512
_MIN_FLUSH_GAP_S = 5.0
# a shed BURST (>= _BURST_N sheds inside _BURST_WINDOW_S seconds)
# triggers a flush; isolated sheds are normal overload behavior
_BURST_N = 8
_BURST_WINDOW_S = 2.0


class FlightRecorder:
    """The per-process ring + dump machinery (module singleton below)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._ring: Optional[Deque[str]] = None
        self._dir: Optional[str] = None
        self._flushes: Dict[str, float] = {}
        self._n_flushes = 0
        self._shed_times: Deque[float] = collections.deque(maxlen=64)

    def arm(self, directory: str,
            capacity: int = DEFAULT_CAPACITY) -> None:
        """Start recording: tee every RunLog line into a ring of at most
        ``capacity`` events, dumping into ``directory`` on flush."""
        os.makedirs(directory, exist_ok=True)
        with self._lock:
            self._dir = directory
            self._ring = collections.deque(maxlen=max(1, int(capacity)))
            self._flushes.clear()

    def disarm(self) -> None:
        with self._lock:
            self._ring = None
            self._dir = None

    @property
    def armed(self) -> bool:
        return self._ring is not None

    def record_line(self, line: str) -> None:
        """Tee one serialized JSONL line (newline included) into the
        ring.  No-op when disarmed — one attribute read on the fast
        path, same bar as the spans null contract."""
        ring = self._ring
        if ring is None:
            return
        with self._lock:
            if self._ring is not None:
                self._ring.append(line)

    def flush(self, reason: str,
              extra: Optional[dict] = None) -> Optional[str]:
        """Dump the ring to ``blackbox_<pid>.jsonl``; returns the path
        (None when disarmed or rate-limited for this ``reason``)."""
        import json                      # stdlib; local to keep arm cheap

        with self._lock:
            if self._ring is None or self._dir is None:
                return None
            now = time.monotonic()
            last = self._flushes.get(reason)
            if last is not None and now - last < _MIN_FLUSH_GAP_S:
                return None
            self._flushes[reason] = now
            self._n_flushes += 1
            lines = list(self._ring)
            path = os.path.join(self._dir,
                                f"blackbox_{os.getpid()}.jsonl")
            header = {"t": round(time.time(), 3),
                      "event": "blackbox_flush", "reason": reason,
                      "pid": os.getpid(), "n_events": len(lines),
                      "flush_no": self._n_flushes}
            if extra:
                header.update(extra)
            with open(path, "a") as fh:
                fh.write(json.dumps(header) + "\n")
                fh.writelines(lines)
                fh.flush()
                os.fsync(fh.fileno())
            return path

    def note_shed(self, now: Optional[float] = None) -> None:
        """Count one shed toward burst detection; a burst flushes the
        ring with reason ``shed_burst`` (rate-limited like any flush)."""
        if self._ring is None:
            return
        t = time.monotonic() if now is None else float(now)
        with self._lock:
            self._shed_times.append(t)
            recent = sum(1 for x in self._shed_times
                         if t - x <= _BURST_WINDOW_S)
        if recent >= _BURST_N:          # flush takes the lock itself
            self.flush("shed_burst", {"sheds_in_window": recent})

    def stats(self) -> dict:
        with self._lock:
            return {"armed": self._ring is not None,
                    "depth": len(self._ring) if self._ring else 0,
                    "flushes": self._n_flushes}


_RECORDER = FlightRecorder()


def arm(directory: str, capacity: int = DEFAULT_CAPACITY) -> None:
    """Arm the process-wide flight recorder (see :class:`FlightRecorder`)."""
    _RECORDER.arm(directory, capacity)


def disarm() -> None:
    """Disarm and drop the ring."""
    _RECORDER.disarm()


def armed() -> bool:
    """Whether the process-wide recorder is currently armed."""
    return _RECORDER.armed


def record_line(line: str) -> None:
    """RunLog's tee point — one serialized event line into the ring."""
    _RECORDER.record_line(line)


def flush(reason: str, extra: Optional[dict] = None) -> Optional[str]:
    """Dump the ring now (crash / circuit_open / shed_burst /
    watchdog_trip); returns the blackbox path or None."""
    return _RECORDER.flush(reason, extra)


def note_shed(now: Optional[float] = None) -> None:
    """One shed toward the burst detector (see FlightRecorder)."""
    _RECORDER.note_shed(now)


def stats() -> dict:
    """Armed flag, current ring depth, lifetime flush count."""
    return _RECORDER.stats()


# unambiguous names for the obs package namespace (``obs.arm`` would
# read as nonsense at call sites; ``obs.arm_flight_recorder`` doesn't)
arm_flight_recorder = arm
flush_flight_recorder = flush
flight_recorder_stats = stats
