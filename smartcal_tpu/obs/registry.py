"""Counters/gauges registry + jax runtime listeners.

Counters accumulate in memory while a RunLog is active and are written as
one ``counters`` event by ``flush_counters()`` (the drivers call it at
episode boundaries); gauges log immediately as ``gauge`` events.  Both are
strict no-ops with no active RunLog.

Two jax hooks feed the registry from the runtime itself:

* ``install_compile_listener()`` registers a ``jax.monitoring`` duration
  listener and records every compilation-ish event (``.../compile``,
  backend init) as a ``jax_event`` record — surfacing the
  minutes-of-compile phases that otherwise hide inside "the first episode
  was slow".  Listeners cannot be unregistered portably, so the install is
  idempotent and the callback itself checks ``active()``.
* ``log_memory_gauges()`` samples per-device ``memory_stats()`` (bytes in
  use / peak / limit) into ``memory`` events — None-safe on backends that
  do not report (CPU).
"""

from __future__ import annotations

import sys
import threading

from .runlog import active

_lock = threading.Lock()
_counters: dict = {}


def counter_add(name: str, value: float = 1.0) -> None:
    """Accumulate ``value`` onto counter ``name`` (no-op when inactive)."""
    if active() is None:
        return
    with _lock:
        _counters[name] = _counters.get(name, 0.0) + value


def gauge_set(name: str, value: object, **tags: object) -> None:
    """Log gauge ``name`` as a ``gauge`` event (no-op when inactive)."""
    rl = active()
    if rl is None:
        return
    rl.log("gauge", name=name, value=value, **tags)


def counters_snapshot() -> dict:
    with _lock:
        return dict(_counters)


def flush_counters(reset: bool = False, **tags: object) -> None:
    """Write all accumulated counters as one ``counters`` event.

    ``reset=True`` clears them afterwards — run teardown uses it so a
    later run in the SAME process (e.g. tools/sweep_calib.py invoking a
    driver main() per seed) starts its counters from zero instead of
    inheriting the previous run's totals."""
    rl = active()
    if rl is None:
        return
    with _lock:
        snap = dict(_counters)
        if reset:
            _counters.clear()
    if snap:
        rl.log("counters", values=snap, **tags)


def reset_counters() -> None:
    with _lock:
        _counters.clear()


# ---------------------------------------------------------------------------
# jax.monitoring compile listener
# ---------------------------------------------------------------------------

_listener_installed = False

# jax_event records below this duration stay counter-only: jax fires a
# jaxpr_trace duration event for EVERY trace (a 2-episode calib run
# measured ~1.2k sub-millisecond ones), which would drown the stream
COMPILE_LOG_MIN_S = 0.01


def _on_event_duration(event, duration, **kw):
    rl = active()
    if rl is None:
        return
    # compile/lowering/backend-init phases only: per-dispatch execution
    # events would flood the stream at span granularity for no signal
    ev = str(event)
    if ("compil" in ev or "lower" in ev or "backend_init" in ev
            or "pjit" in ev):
        if float(duration) >= COMPILE_LOG_MIN_S:
            rl.log("jax_event", key=ev, dur_s=round(float(duration), 4))
        with _lock:
            _counters["jax_compile_events"] = \
                _counters.get("jax_compile_events", 0.0) + 1.0
            _counters["jax_compile_secs"] = \
                _counters.get("jax_compile_secs", 0.0) + float(duration)


def install_compile_listener() -> bool:
    """Idempotently register the jax.monitoring duration listener.
    Returns False when jax (or the monitoring API) is unavailable."""
    global _listener_installed
    if _listener_installed:
        return True
    jax_mod = sys.modules.get("jax")
    if jax_mod is None:
        return False
    try:
        from jax import monitoring
        monitoring.register_event_duration_secs_listener(_on_event_duration)
    except Exception:
        return False
    _listener_installed = True
    return True


# ---------------------------------------------------------------------------
# jax.monitoring persistent-compilation-cache listener
# ---------------------------------------------------------------------------

_cache_listener_installed = False

# plain (no-duration) monitoring events the persistent XLA compilation
# cache emits per compile request -> the obs counter each feeds
_CACHE_EVENT_COUNTERS = {
    "/jax/compilation_cache/cache_hits": "persistent_cache_hits",
    "/jax/compilation_cache/cache_misses": "persistent_cache_misses",
    "/jax/compilation_cache/compile_requests_use_cache":
        "persistent_cache_requests",
}


def _on_event(event, **kw):
    if active() is None:
        return
    name = _CACHE_EVENT_COUNTERS.get(str(event))
    if name is None:
        return
    with _lock:
        _counters[name] = _counters.get(name, 0.0) + 1.0


def install_cache_listener() -> bool:
    """Idempotently register the persistent-compilation-cache hit/miss
    listener (plain events, not durations — the cache emits
    ``/jax/compilation_cache/cache_{hits,misses}`` per compile request).
    Returns False when jax (or the monitoring API) is unavailable."""
    global _cache_listener_installed
    if _cache_listener_installed:
        return True
    jax_mod = sys.modules.get("jax")
    if jax_mod is None:
        return False
    try:
        from jax import monitoring
        monitoring.register_event_listener(_on_event)
    except Exception:
        return False
    _cache_listener_installed = True
    return True


def log_memory_gauges() -> int:
    """Per-device memory_stats() gauges into the active RunLog; returns
    the number of devices that reported stats (0 when inactive, when jax
    is not imported, or when the backend exposes none — CPU)."""
    rl = active()
    if rl is None:
        return 0
    jax_mod = sys.modules.get("jax")
    if jax_mod is None:
        return 0
    try:
        devs = jax_mod.local_devices()
    except Exception:
        return 0
    n = 0
    for d in devs:
        try:
            ms = d.memory_stats()
        except Exception:
            ms = None
        if not ms:
            continue
        rl.log("memory", device=d.id, platform=d.platform,
               bytes_in_use=ms.get("bytes_in_use"),
               peak_bytes_in_use=ms.get("peak_bytes_in_use"),
               bytes_limit=ms.get("bytes_limit"))
        n += 1
    return n
