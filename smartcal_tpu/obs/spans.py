"""Nestable, thread-safe stage tracing.

``span("solve")`` times a host-side code region and records one ``span``
event into the active RunLog on exit: name, nesting path (``/``-joined
ancestor names, per thread), wall duration, thread name, and any tags.
The region is additionally tagged with ``jax.profiler.TraceAnnotation``
when jax is importable, so the same stages show up on the xprof/
TensorBoard timeline when a profiler trace is running — including spans
entered from the episode-prefetch worker thread (TraceAnnotation is
per-thread, and so is the nesting stack here).

STRICT NO-OP CONTRACT: with no active RunLog, ``span()`` returns one
shared, stateless null context manager — no allocation, no clock read,
no annotation.  Instrumenting a hot path costs one function call and one
``None`` check per entry (asserted by tests/test_obs.py).
"""

from __future__ import annotations

import sys
import threading
import time

from . import tracectx
from .runlog import RunLog, active

_tls = threading.local()


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


_TRACE_ANNOTATION = None          # resolved lazily, cached per process


def _trace_annotation():
    """``jax.profiler.TraceAnnotation`` if jax is already imported (never
    triggers the jax import itself), else None.  Re-checks until jax
    appears — a span recorded before the first jax import must not latch
    annotations off for the rest of the process."""
    global _TRACE_ANNOTATION
    if _TRACE_ANNOTATION is None:
        jax_mod = sys.modules.get("jax")
        if jax_mod is not None:
            _TRACE_ANNOTATION = getattr(
                getattr(jax_mod, "profiler", None), "TraceAnnotation", None)
    return _TRACE_ANNOTATION


class _NullSpan:
    """Shared do-nothing context manager (the inactive fast path)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def tag(self, **tags: object) -> "_NullSpan":  # Span surface, no-op
        return self


_NULL_SPAN = _NullSpan()


class Span:
    __slots__ = ("_rl", "name", "tags", "path", "_t0", "_ann", "_ids")

    def __init__(self, rl: "RunLog", name: str,
                 tags: dict) -> None:
        self._rl = rl
        self.name = name
        self.tags = tags
        self.path = name
        self._t0 = 0.0
        self._ann = None
        self._ids = None

    def tag(self, **tags: object) -> "Span":
        """Attach/override tags after entry (e.g. a routing decision made
        mid-region)."""
        self.tags.update(tags)
        return self

    def __enter__(self):
        st = _stack()
        st.append(self.name)
        self.path = "/".join(st)
        # child span id under the adopted trace (None when no trace —
        # span events then carry no trace fields, exactly as before)
        self._ids = tracectx.push_span()
        ta = _trace_annotation()
        if ta is not None:
            try:
                self._ann = ta(self.name)
                self._ann.__enter__()
            except Exception:
                self._ann = None
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, et, ev, tb):
        dur = time.perf_counter() - self._t0
        if self._ann is not None:
            try:
                self._ann.__exit__(et, ev, tb)
            except Exception:
                pass
        st = _stack()
        if st and st[-1] == self.name:
            st.pop()
        rec = dict(self.tags)
        if et is not None:
            # a failed stage STILL records (the chip-tunnel probes failed
            # 87/87 with no structured trace of the error — never again)
            rec["error"] = repr(ev) if ev is not None else et.__name__
        if self._ids is not None and self._ids[1] is not None:
            rec["parent"] = self._ids[1]
        # log BEFORE popping the trace stack: the auto-attached ``span``
        # field must be this span's own id, not its parent's
        self._rl.log("span", name=self.name, path=self.path,
                     dur_s=round(dur, 6),
                     thread=threading.current_thread().name, **rec)
        if self._ids is not None:
            tracectx.pop_span(self._ids[0])
        return False


def span(name: str, **tags: object) -> "Span | _NullSpan":
    """Time a stage: ``with span("solve", route="sharded"): ...``.

    Returns the shared null context manager when no RunLog is active."""
    rl = active()
    if rl is None:
        return _NULL_SPAN
    return Span(rl, name, tags)
