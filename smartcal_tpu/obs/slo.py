"""Windowed SLO burn-rate detection for the serving fleet.

The PR 15/16 telemetry *measures* p99 and shed after the fact; nothing
watches them live.  :class:`SloBurnDetector` is the watcher: a
multi-window (fast + slow) burn-rate evaluator over a sliding stream of
per-request latency / shed observations, with hysteresis in the same
spirit as the PR 16 autoscale EWMA — a spike must *sustain* before the
alarm fires, and the alarm must *stay quiet* before it clears, so one
slow solve or one shed burst does not flap the detector.

Burn rate is measured against explicit targets: ``p99 / p99_target``
and ``shed_rate / shed_target`` (the worse of the two is the window's
burn).  The classic multi-window condition applies: FIRING requires the
fast window burning above ``burn_threshold`` AND the slow window above
1.0 (a long-running degradation, not a blip); CLEARED requires the fast
window back at or below ``clear_threshold`` for ``clear_sustain_s``.

State transitions surface as structured ``slo_burn`` event dicts —
the FleetRouter logs them live (and exposes a gauge), obs_report folds
them offline — carrying per-replica fast-window p99s so a burn is
*localized*, not just detected: the merged critical path then says
which stage of the worst replica is eating the budget.

Stdlib only; the clock is injectable (``now``) for deterministic tests,
same idiom as the fleet's ``clock`` parameter.
"""

from __future__ import annotations

import collections
import math
import threading
import time
from typing import Deque, Dict, List, Optional, Tuple


def _p99(values: List[float]) -> float:
    """p99 by the nearest-rank method (stdlib; no numpy in obs/)."""
    if not values:
        return 0.0
    xs = sorted(values)
    idx = min(len(xs) - 1, max(0, math.ceil(0.99 * len(xs)) - 1))
    return xs[idx]


class SloBurnDetector:
    """Fast+slow windowed p99/shed burn-rate evaluator with hysteresis.

    ``observe()`` from any thread per completed/shed request;
    ``evaluate()`` periodically (the router's poll tick) — returns a
    transition event dict exactly when the state flips, else None.
    """

    def __init__(self, p99_target_s: float,
                 shed_target: float = 0.02,
                 fast_window_s: float = 10.0,
                 slow_window_s: float = 60.0,
                 burn_threshold: float = 2.0,
                 clear_threshold: float = 1.0,
                 sustain_s: float = 2.0,
                 clear_sustain_s: float = 5.0,
                 min_samples: int = 20) -> None:
        self.p99_target_s = float(p99_target_s)
        self.shed_target = max(1e-9, float(shed_target))
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = max(float(slow_window_s),
                                 float(fast_window_s))
        self.burn_threshold = float(burn_threshold)
        self.clear_threshold = float(clear_threshold)
        self.sustain_s = float(sustain_s)
        self.clear_sustain_s = float(clear_sustain_s)
        self.min_samples = max(1, int(min_samples))
        self._lock = threading.Lock()
        # (t, latency_s or None, shed?, replica) — one deque, pruned to
        # the slow window on every observe/evaluate
        self._obs: Deque[Tuple[float, Optional[float], bool,
                               Optional[int]]] = collections.deque()
        self._state: Dict[str, object] = {
            "firing": False, "pending_since": None,
            "clear_since": None, "transitions": 0}

    def observe(self, latency_s: Optional[float] = None,
                shed: bool = False, replica: Optional[int] = None,
                now: Optional[float] = None) -> None:
        """Record one request outcome: a completion latency and/or a
        shed mark, attributed to ``replica`` when known."""
        t = time.monotonic() if now is None else float(now)
        with self._lock:
            self._obs.append((t, latency_s, bool(shed), replica))
            self._prune_locked(t)

    def _prune_locked(self, now: float) -> None:
        horizon = now - self.slow_window_s
        while self._obs and self._obs[0][0] < horizon:
            self._obs.popleft()

    def _window_locked(self, now: float,
                       window_s: float) -> Dict[str, object]:
        t0 = now - window_s
        lats: List[float] = []
        by_rep: Dict[int, List[float]] = {}
        n = shed = 0
        for (t, lat, was_shed, rep) in self._obs:
            if t < t0:
                continue
            n += 1
            if was_shed:
                shed += 1
            if lat is not None:
                lats.append(lat)
                if rep is not None:
                    by_rep.setdefault(int(rep), []).append(lat)
        p99 = _p99(lats)
        shed_rate = shed / n if n else 0.0
        burn = 0.0
        if n >= self.min_samples:
            burn = max(p99 / self.p99_target_s,
                       shed_rate / self.shed_target)
        return {"n": n, "p99_s": round(p99, 6),
                "shed_rate": round(shed_rate, 6),
                "burn": round(burn, 4),
                "replica_p99_s": {r: round(_p99(v), 6)
                                  for r, v in sorted(by_rep.items())}}

    def snapshot(self, now: Optional[float] = None) -> Dict[str, object]:
        """Current fast/slow window stats + firing flag (the router's
        gauge source); no state transition."""
        t = time.monotonic() if now is None else float(now)
        with self._lock:
            self._prune_locked(t)
            fast = self._window_locked(t, self.fast_window_s)
            slow = self._window_locked(t, self.slow_window_s)
            return {"firing": bool(self._state["firing"]),
                    "fast": fast, "slow": slow,
                    "transitions": self._state["transitions"]}

    @property
    def firing(self) -> bool:
        with self._lock:
            return bool(self._state["firing"])

    def evaluate(self, now: Optional[float] = None
                 ) -> Optional[Dict[str, object]]:
        """Advance the hysteresis state machine; returns the structured
        ``slo_burn`` transition event on a flip, else None."""
        t = time.monotonic() if now is None else float(now)
        with self._lock:
            self._prune_locked(t)
            fast = self._window_locked(t, self.fast_window_s)
            slow = self._window_locked(t, self.slow_window_s)
            firing = bool(self._state["firing"])
            burning = (fast["burn"] >= self.burn_threshold
                       and slow["burn"] >= 1.0)
            quiet = fast["burn"] <= self.clear_threshold
            if not firing:
                self._state["clear_since"] = None
                if burning:
                    since = self._state["pending_since"]
                    if since is None:
                        self._state["pending_since"] = t
                    elif t - float(since) >= self.sustain_s:  # type: ignore[arg-type]
                        self._state["firing"] = True
                        self._state["pending_since"] = None
                        self._state["transitions"] = \
                            int(self._state["transitions"]) + 1
                        return self._event_locked("firing", fast, slow)
                else:
                    self._state["pending_since"] = None
                return None
            # firing: wait for a sustained quiet fast window
            self._state["pending_since"] = None
            if quiet:
                since = self._state["clear_since"]
                if since is None:
                    self._state["clear_since"] = t
                elif t - float(since) >= self.clear_sustain_s:  # type: ignore[arg-type]
                    self._state["firing"] = False
                    self._state["clear_since"] = None
                    self._state["transitions"] = \
                        int(self._state["transitions"]) + 1
                    return self._event_locked("cleared", fast, slow)
            else:
                self._state["clear_since"] = None
            return None

    def _event_locked(self, state: str, fast: Dict[str, object],
                      slow: Dict[str, object]) -> Dict[str, object]:
        rep_p99 = fast["replica_p99_s"]
        worst = None
        if isinstance(rep_p99, dict) and rep_p99:
            worst = max(rep_p99, key=lambda r: rep_p99[r])
        return {"state": state,
                "burn_fast": fast["burn"], "burn_slow": slow["burn"],
                "p99_fast_s": fast["p99_s"],
                "shed_rate_fast": fast["shed_rate"],
                "p99_target_s": self.p99_target_s,
                "replica_p99_s": rep_p99, "worst_replica": worst}
