"""graftlint — the repo's JAX-aware static-analysis suite (ISSUE 11).

An AST-based lint framework that turns the invariants the test suite
only catches at runtime (same-seed RNG parity, donated-buffer
discipline, python-static flags, lock-guarded fleet state, atomic IO)
into cheap pre-runtime gates.  See :mod:`smartcal_tpu.analysis.core`
for the framework, :mod:`smartcal_tpu.analysis.rules` for the rules,
and ``tools/lint.py`` for the CLI.

Usage::

    python tools/lint.py smartcal_tpu tools tests          # the gate
    python tools/lint.py --json --changed                  # pre-commit
    python tools/lint.py --types                           # typed core

Programmatic::

    from smartcal_tpu import analysis
    findings = analysis.lint_paths(["smartcal_tpu"], root=repo_root)

Stdlib-only on purpose: the linter runs on boxes where jax does not
import (and in < 30 s over the whole package, so the tier-1 gate stays
cheap).
"""

from .core import (  # noqa: F401
    BAD_SUPPRESSION,
    PARSE_ERROR,
    FileContext,
    Finding,
    Rule,
    all_rules,
    iter_python_files,
    lint_file,
    lint_paths,
    register,
)
from . import baseline  # noqa: F401
from . import typecheck  # noqa: F401
