"""Checked-in baseline of grandfathered graftlint findings.

The gate must be adoptable on a mature tree: findings that predate the
linter are recorded here (with a MANDATORY reason each) and stop
failing the gate, while anything NEW still does.  Entries are keyed by
a line-number-free fingerprint — sha1 of (rule, path, stripped source
text) plus an occurrence index — so unrelated edits above a
grandfathered line don't invalidate the baseline, but changing or
duplicating the flagged line itself does (the finding resurfaces and
must be re-justified).

Stale entries (fingerprint no longer produced by the lint run) are
reported so the baseline shrinks as debt is paid instead of rotting.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, List, Sequence, Tuple

from .core import Finding

BASELINE_VERSION = 1
DEFAULT_BASELINE = "graftlint.baseline.json"

# (rule, path, fingerprint) -> reason
BaselineMap = Dict[Tuple[str, str, str], str]


def _fp_body(f: Finding) -> str:
    h = hashlib.sha1(
        f"{f.rule}|{f.path}|{f.source}".encode("utf-8")).hexdigest()
    return h[:16]


def fingerprints(findings: Sequence[Finding]) -> List[str]:
    """Occurrence-indexed fingerprint per finding (aligned list).

    Two identical flagged lines in one file get ``<hash>#0`` and
    ``<hash>#1`` (source order), so baselining one of them does not
    silently cover a copy-pasted second violation.
    """
    counts: Dict[str, int] = {}
    out = []
    for f in sorted(findings):
        body = _fp_body(f)
        k = counts.get(body, 0)
        counts[body] = k + 1
        out.append(f"{body}#{k}")
    # re-align to the caller's order
    order = {id(f): fp for f, fp in zip(sorted(findings), out)}
    return [order[id(f)] for f in findings]


class BaselineError(ValueError):
    pass


def load(path: str) -> BaselineMap:
    """Load a baseline file; every entry MUST carry a reason string."""
    if not os.path.exists(path):
        return {}
    try:
        with open(path, "r", encoding="utf-8") as fh:
            doc = json.load(fh)
    except (OSError, ValueError) as e:
        # a merge-conflicted/hand-mangled baseline is an infra error,
        # not "findings" — surface it as BaselineError so the CLI can
        # keep its exit-2 contract
        raise BaselineError(f"{path}: unreadable baseline ({e})") from e
    if not isinstance(doc, dict) or doc.get("version") != BASELINE_VERSION:
        raise BaselineError(
            f"{path}: unsupported baseline version "
            f"{doc.get('version') if isinstance(doc, dict) else doc!r}")
    out: BaselineMap = {}
    for i, e in enumerate(doc.get("entries", [])):
        if not isinstance(e, dict) or not all(
                k in e for k in ("rule", "path", "fingerprint")):
            raise BaselineError(
                f"{path}: entry {i} is missing rule/path/fingerprint")
        reason = (e.get("reason") or "").strip()
        if not reason:
            raise BaselineError(
                f"{path}: entry {i} ({e.get('rule')}:{e.get('path')}) has "
                "no reason — every baselined finding must say why it is "
                "grandfathered")
        out[(e["rule"], e["path"], e["fingerprint"])] = reason
    return out


# findings that may NEVER be grandfathered: a reasonless/unknown-rule
# suppression must be fixed at its comment, and an unparseable file has
# no stable fingerprint to pin
UNBASELINEABLE = ("bad-suppression", "parse-error")


def save(path: str, findings: Sequence[Finding],
         reasons: Dict[Tuple[str, str, str], str] | None = None,
         default_reason: str = "grandfathered: predates graftlint "
                               "(ISSUE 11); burn down, don't add") -> None:
    """Write ``findings`` as the new baseline (atomic), carrying forward
    per-entry reasons from ``reasons`` where keys match.
    :data:`UNBASELINEABLE` findings are dropped — they stay failing."""
    findings = [f for f in findings if f.rule not in UNBASELINEABLE]
    reasons = reasons or {}
    entries = []
    fps = fingerprints(findings)
    for f, fp in sorted(zip(findings, fps), key=lambda t: t[0]):
        key = (f.rule, f.path, fp)
        entries.append({
            "rule": f.rule, "path": f.path, "fingerprint": fp,
            "line": f.line, "source": f.source,
            "reason": reasons.get(key, default_reason),
        })
    doc = {"version": BASELINE_VERSION, "entries": entries}
    text = json.dumps(doc, indent=1, sort_keys=False) + "\n"
    # local tmp+replace (not runtime.atomic): the linter must stay
    # importable on boxes where the jax-importing package half doesn't
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        fh.write(text)
    os.replace(tmp, path)


def split(findings: Sequence[Finding], baseline: BaselineMap,
          scanned_paths: "Sequence[str] | None" = None,
          rules_run: "Sequence[str] | None" = None
          ) -> Tuple[List[Finding], List[Finding], List[dict]]:
    """(new, grandfathered, stale_entries).

    ``stale_entries`` are baseline rows whose fingerprint no longer
    matches any finding — paid-down debt that should be pruned with
    ``--update-baseline``.  A baseline entry pointing to a rule in
    :data:`UNBASELINEABLE` never grandfathers (a hand-edited baseline
    cannot launder those).  Staleness is only judged where this run
    actually looked: with ``scanned_paths`` (repo-relative, as in
    ``Finding.path``) entries for unlinted files are left alone, and
    with ``rules_run`` entries for rules that didn't execute are too —
    a ``--changed``/``--rules`` subset run must not call out-of-scope
    debt "fixed".
    """
    fps = fingerprints(findings)
    new, old = [], []
    seen = set()
    for f, fp in zip(findings, fps):
        key = (f.rule, f.path, fp)
        if key in baseline and f.rule not in UNBASELINEABLE:
            old.append(f)
            seen.add(key)
        else:
            new.append(f)
    scanned = None if scanned_paths is None else set(scanned_paths)
    ran = None if rules_run is None else set(rules_run)
    stale = [{"rule": r, "path": p, "fingerprint": fp,
              "reason": baseline[(r, p, fp)]}
             for (r, p, fp) in sorted(baseline)
             if (r, p, fp) not in seen
             and (scanned is None or p in scanned)
             and (ran is None or r in ran)]
    return new, old, stale
