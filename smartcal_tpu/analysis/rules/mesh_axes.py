"""mesh-axis-literal: bare mesh-axis name strings outside the registry.

PR 17's one-mesh contract (parallel/mesh.py): every device-mesh axis
name — "rp"/"dp"/"lane"/"fp"/"sp"/"bp" — has exactly one definition, the
``AXIS_*`` registry constants, and every consumer (PartitionSpecs,
``mesh.shape[...]`` lookups, collectives, mesh builders) spells the axis
through the registry.  A bare axis-name string literal is a silent fork
of the registry: rename or re-map an axis and the literal site keeps the
old spelling, compiling fine until the shard_map axis-binding error (or
worse, a wrong-axis collective that type-checks) fires at run time.

The rule flags axis-name string constants only in AXIS CONTEXTS —
``P("dp")``/mesh-builder calls, ``axis=``/``axis_name=``-style keywords
and parameter defaults, ``mesh.shape["dp"]`` subscripts — so ordinary
two-letter strings elsewhere ("sp" as a variable suffix, docstrings)
never trip it.

Sites that genuinely cannot import the registry (a module layered BELOW
``parallel/`` whose import would cycle through the package __init__)
carry a ``# graftlint: disable=mesh-axis-literal -- <reason>`` — the
stated reason is the audit trail, exactly like dtype-discipline pins.

Scope: ``smartcal_tpu/`` and ``tools/`` (tests may spell axes literally
— exercising the string contract IS part of their job); the registry
itself (parallel/mesh.py) is exempt — it is where the literals live.
"""

from __future__ import annotations

import ast
from typing import Iterator, List

from ..core import FileContext, Finding, Rule, register

#: axis value -> the registry constant that should spell it
AXIS_CONSTANTS = {
    "rp": "AXIS_REPLAY",
    "dp": "AXIS_DATA",
    "lane": "AXIS_LANE",
    "fp": "AXIS_FREQ",
    "sp": "AXIS_CHUNK",
    "bp": "AXIS_BASELINE",
}

#: keyword/parameter names whose string values are axis names
AXIS_KWARGS = ("axis", "axis_name", "axis_names", "lane_axis",
               "baseline_axis", "replay_axis")

#: callables whose positional string/tuple-of-string args are axis names
AXIS_CALLS = ("P", "PartitionSpec", "make_mesh", "compose_mesh",
              "psum", "pmean", "pmax", "pmin", "axis_index",
              "all_gather", "ppermute", "psum_scatter",
              "check_axis_divides")

POLICIED_PREFIXES = ("smartcal_tpu/", "tools/")
EXEMPT_PATHS = ("smartcal_tpu/parallel/mesh.py",)


def _axis_strings(node: ast.AST):
    """Yield (node, value) for axis-name string constants in ``node``,
    looking through one level of tuple/list (P(("fp", "sp")),
    make_mesh((2,), ("dp",)))."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        if node.value in AXIS_CONSTANTS:
            yield node, node.value
    elif isinstance(node, (ast.Tuple, ast.List)):
        for elt in node.elts:
            yield from _axis_strings(elt)


def _call_name(func: ast.AST) -> str:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


@register
class MeshAxisLiteral(Rule):
    name = "mesh-axis-literal"
    doc = ("bare mesh-axis name string in an axis context — spell it "
           "with the parallel/mesh.py AXIS_* registry constant")

    def _msg(self, value: str, where: str) -> str:
        return (f'bare mesh-axis literal "{value}" in {where} — use '
                f"parallel.mesh.{AXIS_CONSTANTS[value]} (one registry, "
                "one spelling per axis) or add a reasoned "
                "'# graftlint: disable=mesh-axis-literal'")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        prefixes = ctx.options.get("mesh_axis_policied_prefixes",
                                   POLICIED_PREFIXES)
        exempt = ctx.options.get("mesh_axis_exempt_paths", EXEMPT_PATHS)
        if any(ctx.rel.endswith(p) for p in exempt):
            return iter(())
        if not any(ctx.rel.startswith(p) for p in prefixes):
            return iter(())
        findings: List[Finding] = []

        def flag(container: ast.AST, where: str) -> None:
            for n, v in _axis_strings(container):
                findings.append(ctx.finding(self.name, n,
                                            self._msg(v, where)))

        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                fname = _call_name(node.func)
                if fname in AXIS_CALLS:
                    for arg in node.args:
                        flag(arg, f"a {fname}(...) call")
                for kw in node.keywords:
                    if kw.arg in AXIS_KWARGS:
                        flag(kw.value, f"keyword {kw.arg}=")
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                a = node.args
                pos = a.posonlyargs + a.args
                for prm, dfl in zip(pos[len(pos) - len(a.defaults):],
                                    a.defaults):
                    if prm.arg in AXIS_KWARGS:
                        flag(dfl, f"parameter default {prm.arg}=")
                for prm, dfl in zip(a.kwonlyargs, a.kw_defaults):
                    if dfl is not None and prm.arg in AXIS_KWARGS:
                        flag(dfl, f"parameter default {prm.arg}=")
            elif isinstance(node, ast.Subscript):
                if isinstance(node.value, ast.Attribute) and \
                        node.value.attr == "shape":
                    flag(node.slice, "a .shape[...] axis lookup")
        return iter(sorted(set(findings)))
