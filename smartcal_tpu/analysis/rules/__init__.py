"""graftlint rule modules — importing this package registers every rule
with :mod:`smartcal_tpu.analysis.core`.  One module per bug class; add a
new rule by creating a module here that defines a ``Rule`` subclass
decorated with ``@register`` and importing it below."""

from . import donation     # noqa: F401
from . import dtype_discipline  # noqa: F401
from . import jit_sync     # noqa: F401
from . import locks        # noqa: F401
from . import mesh_axes    # noqa: F401
from . import pickle_io    # noqa: F401
from . import prints       # noqa: F401
from . import rng          # noqa: F401
from . import static_flags  # noqa: F401
