"""traced-static-flag: a python-static flag fed a jax-derived value.

``collect_diag=``/``collect_stats=``/``optimized=``/``fused=`` (and
``vectorized=``) are python-static by contract (PRs 3-5/9): each value
selects a trace, so the argument must be a host bool known before
tracing.  Passing a traced value either recompiles per call or raises a
ConcretizationTypeError deep inside the callee — far from the cause.

The check is traced-ness-by-construction: the value expression (or a
local name it was assigned from) must not contain anything rooted at
``jnp.``/``jax.``/``lax.`` — host-side config (``args.diag``,
``diag_from_args(args)``, ``self.fused``) passes untouched."""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Set

from ..core import FileContext, Finding, Rule, register
from .. import flow

STATIC_FLAGS = ("collect_diag", "collect_stats", "optimized", "fused",
                "vectorized")

_JAX_ROOTS = ("jnp", "jax", "lax")


def _jax_rooted(expr: ast.AST, jaxy_names: Set[str]) -> bool:
    """True when any sub-expression is rooted at a jax module or a
    local name known to hold a jax-derived value."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            if node.id in _JAX_ROOTS or node.id in jaxy_names:
                return True
    return False


def _jaxy_locals(body: List[ast.stmt]) -> Set[str]:
    """Names assigned (once-level, no fixpoint) from jax-rooted
    expressions in this scope — catches ``flag = jnp.any(x);
    f(optimized=flag)``."""
    out: Set[str] = set()
    for node in flow.walk_in_scope(body):
        if isinstance(node, ast.Assign) and node.value is not None \
                and _jax_rooted(node.value, out):
            for t in node.targets:
                name = flow.dotted(t)
                if name and "." not in name:
                    out.add(name)
    return out


@register
class TracedStaticFlag(Rule):
    name = "traced-static-flag"
    doc = ("python-static flag (collect_diag/collect_stats/optimized/"
           "fused/vectorized) receiving a jax-derived (traced) value")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        findings: List[Finding] = []
        for _scope, body in flow.iter_scopes(ctx.tree):
            jaxy = _jaxy_locals(body)
            for node in flow.walk_in_scope(body):
                if not isinstance(node, ast.Call):
                    continue
                for kw in node.keywords:
                    if kw.arg in STATIC_FLAGS and \
                            _jax_rooted(kw.value, jaxy):
                        findings.append(ctx.finding(
                            self.name, kw.value,
                            f"{kw.arg}= is python-static by contract "
                            "but receives a jax-derived value — each "
                            "distinct value is a separate trace; pass "
                            "a host bool decided before tracing"))
        return iter(sorted(set(findings)))
