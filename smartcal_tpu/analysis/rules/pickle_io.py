"""unguarded-pickle-load: ``pickle.load`` outside the runtime IO layer.

PR 6's atomic-IO contract: every load of persisted state goes through
:mod:`smartcal_tpu.runtime.atomic` — ``safe_pickle_load`` (warn + default
for resumable state that may start fresh) or ``strict_pickle_load``
(clear CorruptStateError for state that must exist) — so a SIGTERM
mid-write never surfaces as an opaque ``EOFError`` three frames deep in
``pickle``.  A bare ``pickle.load(fh)`` bypasses both the corruption
message and the policy decision about what happens on a torn file.

Scope: ``smartcal_tpu/`` and ``tools/``; test code is exempt (tests
read files they just wrote inside one process — there is no torn-write
window to guard)."""

from __future__ import annotations

import ast
from typing import Iterator, List

from ..core import FileContext, Finding, Rule, register
from .. import flow

# the one sanctioned call site: the guard implementation itself
ALLOWED_PATHS = ("smartcal_tpu/runtime/atomic.py",)

_LOADERS = {"pickle.load", "cPickle.load", "dill.load", "joblib.load"}


@register
class UnguardedPickleLoad(Rule):
    name = "unguarded-pickle-load"
    doc = ("pickle.load outside runtime.atomic "
           "(safe_pickle_load/strict_pickle_load) — torn files become "
           "opaque EOFErrors")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        allowed = ctx.options.get("pickle_allowed_paths", ALLOWED_PATHS)
        if any(ctx.rel.endswith(p) for p in allowed):
            return iter(())
        if ctx.rel.startswith("tests/") or "/tests/" in ctx.rel:
            return iter(())
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and \
                    flow.call_func_name(node) in _LOADERS:
                findings.append(ctx.finding(
                    self.name, node,
                    "bare pickle.load — route through runtime.atomic."
                    "safe_pickle_load (resumable state: warn + start "
                    "fresh) or strict_pickle_load (must-exist state: "
                    "clear CorruptStateError) so torn writes fail "
                    "diagnosably"))
        return iter(sorted(findings))
