"""bare-print: diagnostics must flow through the obs layer.

The framework port of ``tests/test_no_bare_print.py`` (PR 3/4), same
allowlist semantics: ``obs.echo`` routes human output to stderr plus a
structured event, ``obs.emit_json`` is the stdout machine interface, so
a bare ``print(`` is either an unstructured diagnostic (breaks
``--quiet`` and the RunLog) or an undeclared stdout contract.
``smartcal_tpu/obs/console.py`` is the one sanctioned package site; in
``tools/`` an explicit allowlist names the CLIs whose stdout IS their
product.  Tokenizer-based so strings, comments and ``.print(`` method
calls never false-positive.  Test code is exempt."""

from __future__ import annotations

import io
import tokenize
from typing import Iterator, List

from ..core import FileContext, Finding, Rule, register

# relative paths (to smartcal_tpu/) allowed to call print()
PKG_ALLOWLIST = frozenset({
    "obs/console.py",
})

# tools/ files sanctioned to print to stdout directly: their stdout is
# the tool's interface (report/sweep/bench output that scripts parse or
# humans pipe).  A new tool must either route through
# smartcal_tpu.obs.console or be added here deliberately.
TOOLS_STDOUT_ALLOWLIST = frozenset({
    "bench_host_seg.py",
    "bench_per.py",
    "bench_solve_eval.py",
    "capture_calib_episode.py",
    "capture_kernel_roofline.py",
    "certify_batched.py",
    "chip_checks.py",
    "convert_ateam.py",
    "eig_mode_parity.py",
    "enet_hint_stats.py",
    "lint.py",
    "measure_reference.py",
    "obs_report.py",
    "obs_tail.py",
    "perf_gate.py",
    "results_index.py",
    "serve_calib.py",
    "serve_fleet.py",
    "serve_learn.py",
    "summarize_demix_curves.py",
    "sweep_calib.py",
    "sweep_demix.py",
    "trace_export.py",
    "sweep_enet.py",
})

_SKIP_TYPES = (tokenize.NL, tokenize.NEWLINE, tokenize.INDENT,
               tokenize.DEDENT, tokenize.COMMENT)


def bare_print_lines(src: str) -> List[int]:
    """Line numbers of bare ``print(`` calls (NAME 'print' followed by
    '(', not preceded by '.' or 'def')."""
    toks = list(tokenize.generate_tokens(io.StringIO(src).readline))
    hits = []
    for i, t in enumerate(toks):
        if t.type != tokenize.NAME or t.string != "print":
            continue
        prev = next((p for p in reversed(toks[:i])
                     if p.type not in _SKIP_TYPES), None)
        if prev is not None and prev.string in (".", "def"):
            continue
        nxt = next((n for n in toks[i + 1:] if n.type not in _SKIP_TYPES),
                   None)
        if nxt is not None and nxt.string == "(":
            hits.append(t.start[0])
    return hits


@register
class BarePrint(Rule):
    name = "bare-print"
    doc = ("bare print() in smartcal_tpu/ or an unlisted tool — route "
           "through obs.echo/obs.emit_json or extend the allowlist")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        rel = ctx.rel
        if rel.startswith("smartcal_tpu/"):
            if rel[len("smartcal_tpu/"):] in PKG_ALLOWLIST:
                return iter(())
            where = ("route human output through smartcal_tpu.obs.echo "
                     "(stderr + structured event) or obs.emit_json "
                     "(stdout machine payloads), or extend "
                     "PKG_ALLOWLIST deliberately")
        elif rel.startswith("tools/") and rel.count("/") == 1:
            if rel[len("tools/"):] in TOOLS_STDOUT_ALLOWLIST:
                return iter(())
            where = ("route output through smartcal_tpu.obs.console "
                     "(echo/emit_json) or add the file to "
                     "TOOLS_STDOUT_ALLOWLIST deliberately")
        else:
            return iter(())  # tests/, examples/, etc. are exempt
        findings = []
        for line in bare_print_lines(ctx.src):
            findings.append(ctx.finding(
                "bare-print", line, f"bare print() — {where}"))
        return iter(sorted(findings))
