"""host-sync-in-jit: host-side operations inside traced functions.

Inside a function reachable from ``jit``/``vmap``/``pmap``/``shard_map``,
host operations either fail at trace time in the best case or — the
dangerous case — silently force a device->host sync / constant-fold on
every call (``print``, ``time.time()``, ``np.asarray`` on a traced
value, ``float()``/``.item()`` on a traced value, python ``if`` on a
traced value which becomes a ConcretizationTypeError or a trace-time
constant).

Detection is deliberately conservative about what counts as *traced*:

* params named in ``static_argnums``/``static_argnames`` are static;
* params with a literal default are treated as python-static — that is
  this repo's documented flag convention (``collect_diag=False``,
  ``optimized=True``, ...), enforced separately by traced-static-flag;
* ``self``/``cls`` and closure variables are not tracked;
* ``x.shape``/``x.ndim``/``x.dtype``/``x.size`` accesses and
  ``len()``/``isinstance()`` results are static even on traced values;
* ``is``/``is not`` comparisons (structure checks like ``x is None``)
  are python-static.

Unconditionally host-side constructs (``print``, ``time.time()``,
``.item()``, ``jax.device_get``) are flagged regardless of operand."""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Set, Tuple

from ..core import FileContext, Finding, Rule, register
from .. import flow

_JIT_WRAPPERS = {"jax.jit", "jit", "pjit", "jax.pjit", "jax.vmap", "vmap",
                 "jax.pmap", "pmap", "shard_map", "jax.named_call",
                 "checkpoint", "jax.checkpoint", "jax.remat"}

# host-only calls, flagged unconditionally inside traced code
_HOST_CALLS = {
    "time.time", "time.perf_counter", "time.monotonic",
    "time.process_time", "time.sleep",
    "jax.device_get", "jax.block_until_ready",
}
_HOST_METHODS = {"item", "tolist", "block_until_ready"}

# numpy entry points that concretize a traced operand
_NP_SYNCS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
             "onp.asarray", "onp.array", "np.float32", "np.float64",
             "np.int32", "np.int64"}

_CONVERTERS = {"float", "int", "bool", "complex"}

_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "sharding",
                 "weak_type"}
_STATIC_CALLS = {"len", "isinstance", "getattr", "hasattr", "type",
                 "callable"}


def _is_partial_jit(call: ast.Call) -> bool:
    fname = flow.call_func_name(call)
    if fname in ("partial", "functools.partial") and call.args:
        return flow.dotted(call.args[0]) in _JIT_WRAPPERS
    return False


def _static_names_from_call(call: ast.Call) -> Set[str]:
    """static_argnames literals of a jit(...) call."""
    out: Set[str] = set()
    for kw in call.keywords:
        if kw.arg in ("static_argnames", "static_argnums"):
            node = kw.value
            elts = node.elts if isinstance(node, (ast.Tuple, ast.List)) \
                else [node]
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    out.add(e.value)
    return out


def _static_nums_from_call(call: ast.Call) -> Set[int]:
    out: Set[int] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            node = kw.value
            elts = node.elts if isinstance(node, (ast.Tuple, ast.List)) \
                else [node]
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    out.add(e.value)
    return out


def _collect_jitted(tree: ast.Module) -> Dict[ast.AST, Tuple[Set[str],
                                                             Set[int]]]:
    """Map of function-def node -> (static names, static argnums) for
    every def made traceable by a decorator or a same-file wrapper call
    like ``g = jax.jit(f)`` / ``jax.vmap(f)(xs)``."""
    defs: Dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, node)
    jitted: Dict[ast.AST, Tuple[Set[str], Set[int]]] = {}

    def mark(fn: ast.AST, names: Set[str], nums: Set[int]) -> None:
        if fn in jitted:
            old_names, old_nums = jitted[fn]
            jitted[fn] = (old_names | names, old_nums | nums)
        else:
            jitted[fn] = (set(names), set(nums))

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if flow.dotted(dec) in _JIT_WRAPPERS:
                    mark(node, set(), set())
                elif isinstance(dec, ast.Call) and (
                        flow.call_func_name(dec) in _JIT_WRAPPERS
                        or _is_partial_jit(dec)):
                    mark(node, _static_names_from_call(dec),
                         _static_nums_from_call(dec))
        elif isinstance(node, ast.Call):
            fname = flow.call_func_name(node)
            if fname in _JIT_WRAPPERS or _is_partial_jit(node):
                args = node.args[1:] if _is_partial_jit(node) else node.args
                if args and isinstance(args[0], ast.Name) \
                        and args[0].id in defs:
                    mark(defs[args[0].id], _static_names_from_call(node),
                         _static_nums_from_call(node))
    return jitted


def _traced_params(fn: ast.AST, static_names: Set[str],
                   static_nums: Set[int]) -> Set[str]:
    a = fn.args
    params = [p.arg for p in a.posonlyargs + a.args]
    traced: Set[str] = set()
    n_pos = len(params)
    defaults = a.defaults  # align right against positional params
    first_default = n_pos - len(defaults)
    for i, name in enumerate(params):
        if name in ("self", "cls") or name in static_names \
                or i in static_nums:
            continue
        if i >= first_default and isinstance(defaults[i - first_default],
                                             ast.Constant):
            continue  # literal default => python-static by repo convention
        traced.add(name)
    for p, d in zip(a.kwonlyargs, a.kw_defaults):
        if p.arg in static_names:
            continue
        if d is not None and isinstance(d, ast.Constant):
            continue
        traced.add(p.arg)
    return traced


def _traced_name_uses(expr: ast.AST, traced: Set[str]) -> List[ast.Name]:
    """Name nodes of traced params used as VALUES in ``expr`` —
    skipping static contexts (``x.shape``, ``len(x)``, ``x is None``)."""
    hits: List[ast.Name] = []
    skip: Set[int] = set()

    def mark_skip(node: ast.AST) -> None:
        for sub in ast.walk(node):
            skip.add(id(sub))

    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
            mark_skip(node)
        elif isinstance(node, ast.Call) and \
                flow.call_func_name(node) in _STATIC_CALLS:
            mark_skip(node)
        elif isinstance(node, ast.Compare) and \
                all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
            mark_skip(node)
        elif isinstance(node, (ast.Lambda, ast.FunctionDef,
                               ast.AsyncFunctionDef)):
            mark_skip(node)
    for node in ast.walk(expr):
        if id(node) in skip:
            continue
        if isinstance(node, ast.Name) and node.id in traced \
                and isinstance(node.ctx, ast.Load):
            hits.append(node)
    return hits


@register
class HostSyncInJit(Rule):
    name = "host-sync-in-jit"
    doc = ("host-side op (print/.item()/np.asarray/time.time()/python if "
           "on a traced value) inside a jit/vmap/shard_map-reachable "
           "function")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        findings: List[Finding] = []
        jitted = _collect_jitted(ctx.tree)

        def scan_fn(fn: ast.AST, traced: Set[str]) -> None:
            """Walk one traced function body; nested defs inherit the
            enclosing traced names plus their own params (they are
            traced when the outer trace calls them)."""
            for stmt in fn.body:
                self._scan_stmt(ctx, stmt, traced, findings)

        for fn, (snames, snums) in jitted.items():
            traced = _traced_params(fn, snames, snums)
            scan_fn(fn, traced)
        return iter(sorted(set(findings)))

    # -- per-statement scan, recursing into nested defs ---------------------
    def _scan_stmt(self, ctx: FileContext, stmt: ast.stmt,
                   traced: Set[str], findings: List[Finding]) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            inner = traced | _traced_params(stmt, set(), set())
            for s in stmt.body:
                self._scan_stmt(ctx, s, inner, findings)
            return
        if isinstance(stmt, ast.ClassDef):
            return
        # python `if`/`while` on a traced value
        if isinstance(stmt, (ast.If, ast.While)):
            for name in _traced_name_uses(stmt.test, traced):
                kind = "if" if isinstance(stmt, ast.If) else "while"
                findings.append(ctx.finding(
                    self.name, name,
                    f"python `{kind}` on traced value '{name.id}' — "
                    "inside jit this is a ConcretizationTypeError or a "
                    "silent trace-time constant; use lax.cond/jnp.where"))
        for expr in flow.stmt_expressions(stmt):
            self._scan_expr(ctx, expr, traced, findings)
        for sub in flow.child_bodies(stmt):
            for s in sub:
                self._scan_stmt(ctx, s, traced, findings)

    def _scan_expr(self, ctx: FileContext, expr: ast.AST,
                   traced: Set[str], findings: List[Finding]) -> None:
        for call in flow.iter_calls(expr):
            fname = flow.call_func_name(call)
            if fname == "print":
                findings.append(ctx.finding(
                    self.name, call,
                    "print() inside a traced function runs at trace "
                    "time only — use jax.debug.print or move it out"))
            elif fname in _HOST_CALLS:
                findings.append(ctx.finding(
                    self.name, call,
                    f"{fname}() inside a traced function executes once "
                    "at trace time (a frozen constant), not per call"))
            elif isinstance(call.func, ast.Attribute) \
                    and call.func.attr in _HOST_METHODS \
                    and not call.args:
                findings.append(ctx.finding(
                    self.name, call,
                    f".{call.func.attr}() forces a device->host sync — "
                    "illegal on traced values inside jit"))
            elif fname in _NP_SYNCS and call.args and \
                    _traced_name_uses(call.args[0], traced):
                findings.append(ctx.finding(
                    self.name, call,
                    f"{fname}() on traced value concretizes it at trace "
                    "time — use jnp equivalents inside jit"))
            elif fname in _CONVERTERS and call.args and \
                    _traced_name_uses(call.args[0], traced):
                findings.append(ctx.finding(
                    self.name, call,
                    f"{fname}() on a traced value forces concretization "
                    "inside jit — keep it an array (jnp.float32(...) / "
                    ".astype) or hoist to the host side"))
