"""dtype-discipline: bare float dtype literals in precision-policied
kernel modules.

PR 13's mixed-precision contract (cal/precision.py): the kernel modules
that take a static ``precision=`` decide their contraction dtypes
through the ONE policy table — ``contraction_dtype(kernel, precision)``
for policy-controlled sites, ``precision.F32`` for pinned ones — so
"where is bf16 allowed" has a single auditable answer backed by parity
tests.  A bare ``jnp.float32``/``jnp.float64`` literal inside a policied
module is a dtype decision the policy can't see: it silently pins a
site f32 (or worse, f64 on a platform that demotes it) with no recorded
reason and no oracle coverage.

Pinned-f32 sites that genuinely must stay literal (e.g. a Pallas
kernel's ``preferred_element_type``) carry a
``# graftlint: disable=dtype-discipline -- <pinning reason>`` — the
reason requirement is the point: every f32 pin in a policied module is
either the policy helper or a stated decision.

Scope: the policied module list (``POLICIED_PATHS``); the policy module
itself (cal/precision.py) is exempt — it is where the literals are
supposed to live.
"""

from __future__ import annotations

import ast
from typing import Iterator, List

from ..core import FileContext, Finding, Rule, register

#: modules whose kernels take the static ``precision=`` policy argument
POLICIED_PATHS = (
    "smartcal_tpu/cal/imager.py",
    "smartcal_tpu/cal/influence.py",
    "smartcal_tpu/cal/kernels.py",
    "smartcal_tpu/ops/pallas_hessian.py",
    "smartcal_tpu/ops/pallas_imager.py",
)

#: the policy helper module — dtype literals are its job
EXEMPT_PATHS = ("smartcal_tpu/cal/precision.py",)

_BARE = {"float32", "float64"}
_ROOTS = {"jnp", "jax"}


def _dtype_literal(node: ast.AST) -> str | None:
    """'jnp.float32' for a bare dtype attribute (jnp.float32 or
    jax.numpy.float32), else None."""
    if not isinstance(node, ast.Attribute) or node.attr not in _BARE:
        return None
    base = node.value
    if isinstance(base, ast.Name) and base.id in _ROOTS:
        return f"{base.id}.{node.attr}"
    if isinstance(base, ast.Attribute) and base.attr == "numpy" and \
            isinstance(base.value, ast.Name) and base.value.id == "jax":
        return f"jax.numpy.{node.attr}"
    return None


@register
class DtypeDiscipline(Rule):
    name = "dtype-discipline"
    doc = ("bare jnp.float32/float64 literal in a precision=-policied "
           "kernel module — route through cal/precision.py or pin with "
           "a reasoned disable")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        policied = ctx.options.get("dtype_policied_paths", POLICIED_PATHS)
        exempt = ctx.options.get("dtype_exempt_paths", EXEMPT_PATHS)
        if any(ctx.rel.endswith(p) for p in exempt):
            return iter(())
        if not any(ctx.rel.endswith(p) for p in policied):
            return iter(())
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            lit = _dtype_literal(node)
            if lit is not None:
                findings.append(ctx.finding(
                    self.name, node,
                    f"bare {lit} in a precision=-policied kernel module "
                    "— use cal/precision.py (contraction_dtype for "
                    "policy-controlled sites, precision.F32 for pinned "
                    "ones) or add a reasoned "
                    "'# graftlint: disable=dtype-discipline' so the pin "
                    "is a recorded decision"))
        return iter(sorted(set(findings)))
