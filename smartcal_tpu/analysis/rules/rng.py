"""rng-key-reuse: a jax.random key consumed twice without a split.

Every sampling call (and ``split``/``fold_in`` themselves) CONSUMES the
key passed to it: sampling from the same key twice yields correlated
draws, and — worse for this repo — one accidental extra consumption
shifts every downstream stream, breaking the same-seed bit-parity the
kill/resume and batched-vs-sequential certifications depend on
(lane i ≙ ``CalibEnv(seed+i)`` holds only while each stream advances by
exactly the same splits).

Tracked per scope in source order with branch-clone semantics; a key
consumed inside a loop body that the body never re-splits is reported
as loop-carried reuse (the same key every iteration)."""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Tuple

from ..core import FileContext, Finding, Rule, register
from .. import flow

# jax.random functions whose first argument is a consumed PRNG key
SAMPLERS = frozenset({
    "ball", "bernoulli", "beta", "binomial", "bits", "categorical",
    "cauchy", "chisquare", "choice", "dirichlet", "double_sided_maxwell",
    "exponential", "gamma", "generalized_normal", "geometric", "gumbel",
    "laplace", "loggamma", "logistic", "lognormal", "maxwell",
    "multivariate_normal", "normal", "orthogonal", "pareto", "permutation",
    "poisson", "rademacher", "randint", "rayleigh", "t",
    "triangular", "truncated_normal", "uniform", "wald", "weibull_min",
})
# split consumes its key exactly like a sampler (split(key) twice
# yields identical children).  fold_in is deliberately NOT a consumer:
# fold_in(key, i) with varying data is the documented derive-a-stream
# idiom — the guard graftlint checks for is rebinding, and
# `key = jax.random.fold_in(key, i)` clears the state like any
# assignment.
KEY_CONSUMERS = SAMPLERS | {"split"}

# call prefixes that mean "this is the jax PRNG module".  The bare
# stdlib-colliding prefix "random" is deliberately NOT accepted
# (stdlib random.choice/randint/uniform take no key and would track
# their first argument); numpy's np.random.* likewise has no key.
_JAX_RANDOM_PREFIXES = ("jax.random", "jrandom", "jr")


def _consume_event(call: ast.Call) -> Optional[Tuple[str, ast.AST]]:
    """(key dotted name, node) when ``call`` consumes a named key."""
    fname = flow.call_func_name(call)
    if fname is None or "." not in fname:
        return None
    prefix, tail = fname.rsplit(".", 1)
    if tail not in KEY_CONSUMERS:
        return None
    if prefix not in _JAX_RANDOM_PREFIXES:
        return None
    key_arg: Optional[ast.AST] = None
    if call.args:
        key_arg = call.args[0]
    else:
        for kw in call.keywords:
            if kw.arg == "key":
                key_arg = kw.value
                break
    if key_arg is None:
        return None
    name = flow.dotted(key_arg)
    if name is None:
        return None
    return name, call


def _events_of_stmt(stmt: ast.stmt) -> List[Tuple[str, ast.AST]]:
    out = []
    for expr in flow.stmt_expressions(stmt):
        for call in flow.iter_calls(expr):
            ev = _consume_event(call)
            if ev is not None:
                out.append(ev)
    return out


@register
class RngKeyReuse(Rule):
    name = "rng-key-reuse"
    doc = ("jax.random key consumed by two sampling/split calls with no "
           "split/fold_in between them in the same scope")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        findings: List[Finding] = []

        def run_scope(body: List[ast.stmt]) -> None:
            state: Dict[str, ast.AST] = {}

            def visit(stmt: ast.stmt, st: Dict[str, ast.AST]) -> None:
                for name, node in _events_of_stmt(stmt):
                    prev = st.get(name)
                    if prev is not None:
                        findings.append(ctx.finding(
                            self.name, node,
                            f"key '{name}' was already consumed at line "
                            f"{prev.lineno} — split/fold_in before reusing "
                            "it (reuse correlates draws and breaks "
                            "same-seed stream parity)"))
                    st[name] = node
                for t in flow.assigned_targets(stmt):
                    st.pop(t, None)
                    pref = t + "."
                    for k in [k for k in st if k.startswith(pref)]:
                        st.pop(k)

            def on_loop_carry(name: str, node: ast.AST) -> None:
                findings.append(ctx.finding(
                    self.name, node,
                    f"key '{name}' is consumed every loop iteration but "
                    "never re-split in the loop body — each iteration "
                    "samples from the SAME key"))

            flow.walk_scope_linear(body, state, visit,
                                   loop_extract=_events_of_stmt,
                                   on_loop_carry=on_loop_carry)

        for _scope, body in flow.iter_scopes(ctx.tree):
            run_scope(body)
        return iter(sorted(set(findings)))
