"""read-after-donation: a buffer donated to a jitted call is read again.

``donate_argnums``/``donate_argnames`` hands the argument's buffer to
XLA for reuse: after the call, the caller-side array is INVALID on
accelerators — and silently fine on CPU, where donation is a no-op,
which is exactly why this bug class survives the CPU-only tier-1 suite
(the ``_lane_splice``/``_seg_resume``/``_img_acc`` donation pattern from
PRs 1/5/9).  The safe idiom rebinds the result over the operand::

    acc = _img_acc(acc, img)        # ok: donated name is reassigned
    x = _img_acc(acc, img); acc[0]  # BAD: acc's buffer was donated

Donating callables are discovered per file (``jax.jit(...,
donate_argnums=...)`` assignments and ``@partial(jax.jit,
donate_argnames=...)`` decorated defs) and seeded with the repo's known
cross-module donating helpers."""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Tuple

from ..core import FileContext, Finding, Rule, register
from .. import flow

# cross-module donating helpers (callee basename -> donated positional
# indices); in-file definitions are discovered and take precedence.
KNOWN_DONATING: Dict[str, Tuple[int, ...]] = {
    "_lane_splice": (0,),   # envs/radio.py: batched-lane reset splice
    "_img_acc": (0,),       # envs/radio.py: per-band image accumulator
    "_seg_start": (0,),     # cal/solver.py: donated x0 carry
    "_seg_resume": (0,),    # cal/solver.py: donated L-BFGS state carry
    "_host_consensus": (1,),  # cal/solver.py: donated dual Y
}

_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit"}


def _literal_ints(node: ast.AST) -> Optional[Tuple[int, ...]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
            else:
                return None
        return tuple(out)
    return None


def _literal_strs(node: ast.AST) -> Optional[Tuple[str, ...]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append(e.value)
            else:
                return None
        return tuple(out)
    return None


def _param_positions(fn: ast.AST, names: Tuple[str, ...]) -> Tuple[int, ...]:
    """Positional indices of ``names`` in a def's signature."""
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return ()
    params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    return tuple(params.index(n) for n in names if n in params)


def _jit_donations(call: ast.Call) -> Optional[dict]:
    """For a ``jax.jit(...)``/``partial(jax.jit, ...)`` call, the
    donate kwargs: {'argnums': (...) or None, 'argnames': (...) or None}
    (None when absent; returns None if this isn't a jit call)."""
    fname = flow.call_func_name(call)
    if fname in ("partial", "functools.partial") and call.args:
        inner = flow.dotted(call.args[0])
        if inner not in _JIT_NAMES:
            return None
    elif fname not in _JIT_NAMES:
        return None
    out = {"argnums": None, "argnames": None}
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            out["argnums"] = _literal_ints(kw.value)
        elif kw.arg == "donate_argnames":
            out["argnames"] = _literal_strs(kw.value)
    if out["argnums"] is None and out["argnames"] is None:
        return None
    return out


def donating_functions(tree: ast.Module,
                       seed: Optional[Dict[str, Tuple[int, ...]]] = None
                       ) -> Dict[str, Tuple[int, ...]]:
    """basename -> donated positional indices, seeded + file-discovered."""
    out = dict(KNOWN_DONATING if seed is None else seed)
    for node in ast.walk(tree):
        # NAME = jax.jit(fn, donate_argnums=(0,))
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            d = _jit_donations(node.value)
            if d and d["argnums"]:
                for t in node.targets:
                    name = flow.dotted(t)
                    if name:
                        out[name.split(".")[-1]] = d["argnums"]
        # @partial(jax.jit, donate_argnames=("x0",)) / @jax.jit(...)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if not isinstance(dec, ast.Call):
                    continue
                d = _jit_donations(dec)
                if not d:
                    continue
                pos: Tuple[int, ...] = d["argnums"] or ()
                if d["argnames"]:
                    pos = pos + _param_positions(node, d["argnames"])
                if pos:
                    out[node.name] = tuple(sorted(set(pos)))
    return out


@register
class ReadAfterDonation(Rule):
    name = "read-after-donation"
    doc = ("argument passed at a donate_argnums/argnames position and "
           "then read again in the caller before reassignment")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        donators = donating_functions(
            ctx.tree, seed=ctx.options.get("donating_funcs"))
        findings: List[Finding] = []

        def donation_events(stmt: ast.stmt) -> List[Tuple[str, ast.AST]]:
            out = []
            for expr in flow.stmt_expressions(stmt):
                for call in flow.iter_calls(expr):
                    fname = flow.call_func_name(call)
                    if fname is None:
                        continue
                    base = fname.split(".")[-1]
                    pos = donators.get(base)
                    if not pos:
                        continue
                    for p in pos:
                        if p < len(call.args):
                            name = flow.dotted(call.args[p])
                            if name:
                                out.append((name, call))
            return out

        def run_scope(body: List[ast.stmt]) -> None:
            state: Dict[str, ast.AST] = {}

            def visit(stmt: ast.stmt, st: Dict[str, ast.AST]) -> None:
                if st:
                    # reads are checked against the PRE-statement state:
                    # the donating use itself must not self-flag
                    for expr in flow.stmt_expressions(stmt):
                        for name, node in flow.read_names(expr):
                            don = st.get(name)
                            if don is None:  # attr read of a donated var
                                for d, n in st.items():
                                    if name.startswith(d + "."):
                                        don, name = n, d
                                        break
                            if don is not None:
                                findings.append(ctx.finding(
                                    self.name, node,
                                    f"'{name}' was donated to "
                                    f"{flow.call_func_name(don)}() at line "
                                    f"{don.lineno} and read again — its "
                                    "buffer is invalid on accelerators "
                                    "(donation is a silent no-op on CPU)"))
                for name, node in donation_events(stmt):
                    st[name] = node
                for t in flow.assigned_targets(stmt):
                    st.pop(t, None)
                    pref = t + "."
                    for k in [k for k in st if k.startswith(pref)]:
                        st.pop(k)

            def on_loop_carry(name: str, node: ast.AST) -> None:
                findings.append(ctx.finding(
                    self.name, node,
                    f"'{name}' is donated inside this loop but never "
                    "reassigned in the loop body — the next iteration "
                    "re-reads a donated buffer (rebind the result: "
                    f"{name} = {flow.call_func_name(node)}(...))"))

            flow.walk_scope_linear(body, state, visit,
                                   loop_extract=donation_events,
                                   on_loop_carry=on_loop_carry)

        for _scope, body in flow.iter_scopes(ctx.tree):
            run_scope(body)
        return iter(sorted(set(findings)))
