"""unlocked-shared-write: writes to fleet-shared state outside its lock.

The async fleet (PR 10) shares host state across actor threads and the
learner: the published weights snapshot in
:class:`~smartcal_tpu.runtime.supervisor.Fleet` and the buffered RunLog
internals every thread logs through.  Those objects declare a lock and
the contract is lexical: every write to a shared field happens inside a
``with <lock>:`` block (or in a method whose name ends ``_locked``,
the repo's "caller holds the lock" convention, or in ``__init__``,
which runs before the object is shared).

The rule is SEEDED from :data:`SHARED_FIELD_SPECS` — an annotated list
of (file, class, shared fields, lock attrs).  Declaring a new shared
field means adding a row here; the rule then enforces the lock
discipline on every write forever after.  Detected writes: attribute
assignment/aug-assignment/deletion, subscript stores through the field,
and calls to mutating container methods on the field."""

from __future__ import annotations

import ast
from typing import Iterator, List, Set

from ..core import FileContext, Finding, Rule, register
from .. import flow

# The annotated shared-state registry.  ``path`` is a repo-relative
# suffix; ``fields`` are attribute names shared across threads; every
# write must be under a ``with`` on one of ``locks``.
SHARED_FIELD_SPECS = [
    {
        "path": "smartcal_tpu/runtime/supervisor.py",
        "class": "Fleet",
        "fields": ["_weights", "_version"],
        "locks": ["_wlock"],
        "why": "weights snapshot + version read by every actor thread "
               "per rollout (get_weights) while the learner publishes",
    },
    {
        "path": "smartcal_tpu/runtime/supervisor.py",
        "class": "Fleet",
        "fields": ["_shard_qs", "_slot_shard"],
        "locks": ["_wlock"],
        "why": "cross-process ingest-shard directory + slot->shard map "
               "read concurrently by every pump thread (shard_queue) "
               "and the learner (collect/queue_depths); built once in "
               "__init__ and immutable after — any later write must "
               "take the lock",
    },
    {
        "path": "smartcal_tpu/runtime/supervisor.py",
        "class": "_ProcessActor",
        "fields": ["_outbox"],
        "locks": ["_outbox_lock"],
        "why": "latest-wins weights outbox written by the learner "
               "(publish) and drained by the slot's sender thread — "
               "an unlocked write can ship a torn frame reference",
    },
    {
        "path": "smartcal_tpu/obs/runlog.py",
        "class": "RunLog",
        "fields": ["_buf", "_bytes", "_fh", "_rotations", "_last_flush"],
        "locks": ["_lock"],
        "why": "every thread (actors, prefetch worker, watchdog) logs "
               "through the active RunLog's shared buffer",
    },
    {
        "path": "smartcal_tpu/serve/server.py",
        "class": "CalibServer",
        "fields": ["_programs", "_circuit_open", "_stats",
                   "_sentinel_pending", "_sentinel_stats",
                   "_policy", "_policy_version"],
        "locks": ["_lock"],
        "why": "latest-executable table swapped by warmup while the "
               "batch worker reads it per batch; breaker flag written "
               "by the supervisor thread and read on every submit; "
               "stats written by worker + breaker, read by stats(); "
               "the numerics-sentinel snapshot is handed off "
               "latest-wins from the batch worker to the supervisor's "
               "sentinel_poll and its counters are read by stats(); "
               "the policy (params, version) pair is hot-swapped by "
               "the publisher thread (swap_policy) while the batch "
               "worker snapshots it per batch — a torn write serves a "
               "request on mismatched params/version",
    },
    {
        "path": "smartcal_tpu/serve/lifecycle.py",
        "class": "TransitionStage",
        "fields": ["_items", "_dropped", "_staged"],
        "locks": ["_lock"],
        "why": "replay-tee staging ring written by the server's batch "
               "worker (transition_sink) while the learner loop drains "
               "it — an unlocked extend/clear race loses or duplicates "
               "served transitions",
    },
    {
        "path": "smartcal_tpu/serve/router.py",
        "class": "MicroBatcher",
        "fields": ["_accepted", "_shed", "_service_est_s"],
        "locks": ["_lock"],
        "why": "admission counters written by every client thread and "
               "the service-time EWMA written by the batch worker while "
               "next_batch reads it for the deadline pull",
    },
    {
        "path": "smartcal_tpu/serve/fleet.py",
        "class": "FleetRouter",
        "fields": ["_replicas", "_stats", "_next_rid", "_retired"],
        "locks": ["_lock"],
        "why": "replica table + fleet counters written by the "
               "supervision thread (spawn/reap/respawn) and every "
               "client thread (submit/dispatch accounting) while "
               "stats()/_live() read them from anywhere",
    },
    {
        "path": "smartcal_tpu/serve/fleet.py",
        "class": "_Replica",
        "fields": ["_pending", "_gauges", "_frames"],
        "locks": ["_lock"],
        "why": "in-flight job table written by dispatching client "
               "threads and the pump thread (result/shed/crash "
               "reclaim) — a torn read double-completes or leaks a "
               "job; gauges written by the pump, read by the ranking "
               "dispatcher; the received-frame ring (parent-side black "
               "box) written by the pump and dumped by the supervision "
               "thread on replica death",
    },
    {
        "path": "smartcal_tpu/serve/fleet.py",
        "class": "_WeightsPublisher",
        "fields": ["_slot"],
        "locks": ["_lock"],
        "why": "latest-wins policy-snapshot slot written by the "
               "replica's frame-dispatch loop (offer) and drained by "
               "the swap worker — an unlocked write can tear the "
               "(version, params) pair and swap mismatched weights",
    },
    {
        "path": "smartcal_tpu/obs/flightrec.py",
        "class": "FlightRecorder",
        "fields": ["_ring", "_dir", "_flushes", "_n_flushes",
                   "_shed_times"],
        "locks": ["_lock"],
        "why": "the crash ring is teed from every thread that logs "
               "(RunLog._emit) while flush() snapshots it from "
               "supervisor/watchdog threads and arm/disarm swap it "
               "from the worker main — an unlocked write can dump a "
               "torn ring or race the rate-limit table",
    },
    {
        "path": "smartcal_tpu/obs/slo.py",
        "class": "SloBurnDetector",
        "fields": ["_obs", "_state"],
        "locks": ["_lock"],
        "why": "burn-rate windows fed by every client thread "
               "(observe on each result/shed) while the router's "
               "supervision thread prunes + evaluates them and "
               "snapshot() reads from anywhere — racing the deque "
               "prune corrupts the percentile windows",
    },
    {
        "path": "smartcal_tpu/obs/baselines.py",
        "class": "BaselineStore",
        "fields": ["_doc", "_dirty"],
        "locks": ["_lock"],
        "why": "the perf-baseline document is read by every gate/test "
               "thread (get) while record()/save() rewrite entries and "
               "the dirty flag — a torn swap can bless a half-written "
               "baseline or drop a recorded stage",
    },
    {
        "path": "smartcal_tpu/obs/collect.py",
        "class": "TimelineMerger",
        "fields": ["_streams", "_offsets", "_n_corrupt"],
        "locks": ["_lock"],
        "why": "merge state grown by live-tailer reader threads "
               "(add_stream) while a reporter thread calls "
               "merge()/stats() — an unlocked extend tears the "
               "per-stream event lists mid-sort",
    },
]

_MUTATORS = {"append", "add", "extend", "update", "insert", "pop",
             "popleft", "remove", "discard", "clear", "setdefault",
             "appendleft"}

_EXEMPT_METHODS = ("__init__", "__new__")


def _lock_exprs(node: ast.With) -> List[str]:
    out = []
    for item in node.items:
        name = flow.dotted(item.context_expr)
        if name is None and isinstance(item.context_expr, ast.Call):
            name = flow.dotted(item.context_expr.func)
        if name:
            out.append(name)
    return out


@register
class UnlockedSharedWrite(Rule):
    name = "unlocked-shared-write"
    doc = ("write to an annotated fleet-shared field outside its "
           "`with <lock>:` block (see SHARED_FIELD_SPECS)")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        specs = ctx.options.get("shared_specs", SHARED_FIELD_SPECS)
        mine = [s for s in specs if ctx.rel.endswith(s["path"])]
        if not mine:
            return iter(())
        findings: List[Finding] = []
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            for spec in mine:
                want = spec.get("class")
                if want and cls.name != want:
                    continue
                self._check_class(ctx, cls, set(spec["fields"]),
                                  set(spec["locks"]), findings)
        return iter(sorted(set(findings)))

    def _check_class(self, ctx: FileContext, cls: ast.ClassDef,
                     fields: Set[str], locks: Set[str],
                     findings: List[Finding]) -> None:
        for meth in cls.body:
            if not isinstance(meth, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if meth.name in _EXEMPT_METHODS or meth.name.endswith("_locked"):
                continue  # construction / caller-holds-lock convention
            self._scan(ctx, meth.name, meth.body, fields, locks,
                       held=False, findings=findings)

    def _scan(self, ctx: FileContext, meth: str, body: List[ast.stmt],
              fields: Set[str], locks: Set[str], held: bool,
              findings: List[Finding]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            now_held = held
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                names = _lock_exprs(stmt)
                if any(n.split(".")[-1] in locks for n in names):
                    now_held = True
            if not held:
                for field, node in self._writes_of(stmt, fields):
                    findings.append(ctx.finding(
                        self.name, node,
                        f"write to shared field '{field}' in {meth}() "
                        f"outside a `with {'/'.join(sorted(locks))}` "
                        "block — racing every thread that reads it (take "
                        "the lock, or rename the method *_locked if the "
                        "caller holds it)"))
            for sub in flow.child_bodies(stmt):
                self._scan(ctx, meth, sub, fields, locks, now_held,
                           findings)

    def _writes_of(self, stmt: ast.stmt, fields: Set[str]):
        """(field, node) for shared-field writes in THIS statement only
        (header of compound statements)."""
        out = []

        def target_hit(t: ast.AST) -> None:
            if isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    target_hit(e)
                return
            if isinstance(t, ast.Starred):
                target_hit(t.value)
                return
            if isinstance(t, ast.Subscript):
                # self._buf[i] = x writes THROUGH the field
                t = t.value
            if isinstance(t, ast.Attribute) and t.attr in fields:
                out.append((t.attr, t))

        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                target_hit(t)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            target_hit(stmt.target)
        elif isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                target_hit(t)
        # mutating container-method calls on the field, in any
        # value-position expression of this statement
        for expr in flow.stmt_expressions(stmt):
            for call in flow.iter_calls(expr):
                f = call.func
                if isinstance(f, ast.Attribute) and f.attr in _MUTATORS \
                        and isinstance(f.value, ast.Attribute) \
                        and f.value.attr in fields:
                    out.append((f.value.attr, call))
        return out
