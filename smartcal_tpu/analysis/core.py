"""graftlint core: findings, rule registry, suppressions, file driver.

The repo's correctness story rests on invariants that only fail at
runtime — same-seed bit-parity (one extra RNG consumption perturbs every
stream after it), donated-buffer splices (a read after donation is
undefined on accelerators and silently fine on CPU), python-static diag
flags, lock-guarded fleet state — and the tier-1 suite costs ~15 min per
signal.  graftlint is the cheap pre-runtime gate: AST-based rules over
the package that catch those bug classes at review time.

Contracts:

* **Rules** subclass :class:`Rule` and register with :func:`register`;
  each sees a parsed :class:`FileContext` and yields
  :class:`Finding`\\ s.  Rules must be deterministic (two runs over the
  same tree produce byte-identical output) and side-effect free.
* **Suppressions** are comments on the flagged line::

      bad_call()  # graftlint: disable=rng-key-reuse -- reason why

  or file-wide (anywhere in the file, conventionally near the top)::

      # graftlint: disable-file=host-sync-in-jit -- reason why

  The ``-- reason`` is MANDATORY: a disable comment without one (or
  naming an unknown rule) is itself a finding (``bad-suppression``)
  that cannot be suppressed — every silenced finding must say why.
* **Baseline**: grandfathered findings live in a checked-in JSON file
  (see :mod:`smartcal_tpu.analysis.baseline`); the gate fails only on
  NEW findings.

Stdlib only — the linter must run on a box where jax does not import.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

# path snippets never scanned by directory walks: the
# intentional-violation fixture corpus and junk dirs.  Matched against
# "/"-joined path components, so ".git" cannot catch "legit.py".
EXCLUDE_PARTS = (
    "tests/fixtures/lint",
    "__pycache__",
    ".git",
)

# meta-rule names emitted by the driver itself (not in the registry)
BAD_SUPPRESSION = "bad-suppression"
PARSE_ERROR = "parse-error"


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    """One lint hit.  ``path`` is repo-relative with forward slashes."""

    path: str
    line: int
    col: int
    rule: str
    message: str
    source: str = ""  # stripped source text of the flagged line

    def as_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "source": self.source}

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] " \
               f"{self.message}"


class FileContext:
    """Parsed view of one file, shared by every rule (parse once)."""

    def __init__(self, path: str, src: str, rel: str,
                 options: Optional[dict] = None):
        self.path = path          # absolute
        self.rel = rel            # repo-relative, forward slashes
        self.src = src
        self.lines = src.splitlines()
        self.tree = ast.parse(src)  # may raise SyntaxError
        self.options: dict = options or {}

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(self, rule: str, node_or_line, message: str,
                col: Optional[int] = None) -> Finding:
        if isinstance(node_or_line, int):
            line, c = node_or_line, 0 if col is None else col
        else:
            line = getattr(node_or_line, "lineno", 1)
            c = getattr(node_or_line, "col_offset", 0) if col is None else col
        return Finding(path=self.rel, line=line, col=c, rule=rule,
                       message=message, source=self.line_text(line))


class Rule:
    """Base class: subclass, set ``name``/``doc``, implement ``check``."""

    name: str = ""
    doc: str = ""

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        raise NotImplementedError
        yield  # pragma: no cover


_REGISTRY: Dict[str, Rule] = {}


def register(cls):
    """Class decorator: instantiate and add to the global registry."""
    inst = cls()
    if not inst.name:
        raise ValueError(f"rule {cls.__name__} has no name")
    if inst.name in _REGISTRY:
        raise ValueError(f"duplicate rule name {inst.name!r}")
    _REGISTRY[inst.name] = inst
    return cls


def all_rules() -> Dict[str, Rule]:
    """name -> rule instance, with the rule modules imported."""
    from smartcal_tpu.analysis import rules as _rules  # noqa: F401
    return dict(_REGISTRY)


# ---------------------------------------------------------------------------
# Suppression comments
# ---------------------------------------------------------------------------

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*(disable|disable-file)\s*=\s*"
    r"([A-Za-z0-9_,\- ]+?)\s*(?:--\s*(.*\S)\s*)?$")


@dataclasses.dataclass
class Suppression:
    kind: str          # "disable" | "disable-file"
    rules: Tuple[str, ...]
    reason: str        # "" when missing (a bad-suppression finding)
    line: int


def parse_suppressions(src: str) -> List[Suppression]:
    """Suppressions from COMMENT tokens only — a docstring or string
    literal that quotes the disable syntax (rule docs do) must never
    become a live suppression."""
    import io
    import tokenize
    out = []
    try:
        toks = tokenize.generate_tokens(io.StringIO(src).readline)
        comments = [(t.start[0], t.string) for t in toks
                    if t.type == tokenize.COMMENT]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return []  # unparseable files already carry a parse-error finding
    for lineno, text in comments:
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        kind, names, reason = m.group(1), m.group(2), m.group(3) or ""
        rules = tuple(r.strip() for r in names.split(",") if r.strip())
        out.append(Suppression(kind=kind, rules=rules,
                               reason=reason.strip(), line=lineno))
    return out


def apply_suppressions(ctx: FileContext, findings: List[Finding],
                       known_rules: Iterable[str]) -> List[Finding]:
    """Drop suppressed findings; emit ``bad-suppression`` meta-findings
    for disables with no reason or an unknown rule name."""
    sups = parse_suppressions(ctx.src)
    known = set(known_rules) | {BAD_SUPPRESSION, PARSE_ERROR}
    out: List[Finding] = []
    file_off: set = set()
    line_off: Dict[int, set] = {}
    for s in sups:
        if not s.reason:
            out.append(ctx.finding(
                BAD_SUPPRESSION, s.line,
                "suppression without a reason — write "
                "'# graftlint: disable=<rule> -- <why>'"))
            continue  # a reasonless disable does not disable anything
        bad = [r for r in s.rules if r not in known]
        if bad:
            out.append(ctx.finding(
                BAD_SUPPRESSION, s.line,
                f"suppression names unknown rule(s) {', '.join(bad)} "
                f"(known: use tools/lint.py --list-rules)"))
        good = [r for r in s.rules if r in known]
        if s.kind == "disable-file":
            file_off.update(good)
        else:
            line_off.setdefault(s.line, set()).update(good)
    for f in findings:
        if f.rule == BAD_SUPPRESSION:  # never suppressible
            out.append(f)
            continue
        if f.rule in file_off:
            continue
        if f.rule in line_off.get(f.line, ()):
            continue
        out.append(f)
    return out


# ---------------------------------------------------------------------------
# File driver
# ---------------------------------------------------------------------------

def is_excluded(path: str) -> bool:
    """Public twin of the walk-time exclusion — callers assembling
    their own file lists (``--changed``) must apply the same policy."""
    return _excluded(path)


def _excluded(path: str) -> bool:
    comps = os.path.abspath(path).replace(os.sep, "/").split("/")
    # component-boundary matching: "tests/fixtures/lint" must not catch
    # "tests/fixtures/linty.py" or "tests/fixtures/lint_utils/"
    bounded = "/" + "/".join(comps) + "/"
    for part in EXCLUDE_PARTS:
        if "/" in part:
            if "/" + part + "/" in bounded:
                return True
        elif part in comps:
            return True
    return False


def iter_python_files(paths: Sequence[str], root: str,
                      include_excluded: bool = False) -> Iterator[str]:
    """Yield absolute paths of ``.py`` files under ``paths`` (files or
    directories), sorted, skipping :data:`EXCLUDE_PARTS`."""
    seen = set()
    for p in paths:
        ap = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(ap):
            # an explicitly-named file is always linted — the exclusion
            # list only protects directory walks from the
            # intentional-violation fixture corpus
            cands = [ap] if ap.endswith(".py") else []
            explicit = True
        else:
            cands = []
            explicit = False
            for d, subdirs, files in os.walk(ap):
                subdirs.sort()
                for fn in sorted(files):
                    if fn.endswith(".py"):
                        cands.append(os.path.join(d, fn))
        for c in cands:
            c = os.path.abspath(c)
            if c in seen:
                continue
            if not (explicit or include_excluded) and _excluded(c):
                continue
            seen.add(c)
            yield c


def relpath(path: str, root: str) -> str:
    rel = os.path.relpath(os.path.abspath(path), os.path.abspath(root))
    return rel.replace(os.sep, "/")


def lint_file(path: str, root: str,
              rules: Optional[Dict[str, Rule]] = None,
              options: Optional[dict] = None) -> List[Finding]:
    """All (post-suppression) findings for one file, sorted."""
    rules = rules if rules is not None else all_rules()
    rel = relpath(path, root)
    try:
        with open(path, "rb") as fh:
            src = fh.read().decode("utf-8")
    except (OSError, UnicodeDecodeError) as e:
        return [Finding(path=rel, line=1, col=0, rule=PARSE_ERROR,
                        message=f"file is unreadable: {e}")]
    try:
        ctx = FileContext(path, src, rel, options=options)
    except (SyntaxError, ValueError) as e:
        lineno = int(getattr(e, "lineno", 1) or 1)
        msg = getattr(e, "msg", None) or str(e)
        return [Finding(path=rel, line=lineno, col=0, rule=PARSE_ERROR,
                        message=f"file does not parse: {msg}")]
    findings: List[Finding] = []
    for rule in rules.values():
        findings.extend(rule.check(ctx))
    # suppressions validate against the FULL registry, not the subset
    # being run — `--rules rng-key-reuse` must not call a valid
    # disable=read-after-donation comment "unknown"
    findings = apply_suppressions(ctx, findings,
                                  set(all_rules()) | set(rules))
    return sorted(findings)


def lint_paths(paths: Sequence[str], root: str,
               rules: Optional[Dict[str, Rule]] = None,
               options: Optional[dict] = None,
               include_excluded: bool = False) -> List[Finding]:
    """Lint every python file under ``paths``; deterministic order."""
    rules = rules if rules is not None else all_rules()
    out: List[Finding] = []
    for f in iter_python_files(paths, root,
                               include_excluded=include_excluded):
        out.extend(lint_file(f, root, rules=rules, options=options))
    return sorted(out)
