"""The ``tools/lint.py --types`` entry point: a typed core for the repo.

Strictness is tiered the way the invariants are: the modules whose
payloads cross checkpoint/restore and device/host boundaries —
``rl/replay.py``, ``runtime/checkpoint.py``, and all of ``obs/`` — form
the STRICT CORE; the rest of the package rides a permissive baseline
(see ``mypy.ini``).

Two execution modes, same entry point:

* **mypy available** (not baked into this container, but present on dev
  boxes): run ``python -m mypy --config-file mypy.ini`` over the strict
  core and report its findings verbatim.
* **mypy absent**: degrade to the built-in ANNOTATION AUDIT — an
  AST-level check that every public function/method in the strict core
  declares parameter and return annotations (``self``/``cls`` and
  ``*args/**kwargs`` excepted, ``__init__`` needs params only).  This
  keeps the ``--types`` gate meaningful in hermetic CI: un-annotated
  code cannot land in the strict core even where mypy cannot run.
"""

from __future__ import annotations

import ast
import os
import shutil
import subprocess
import sys
from typing import List, Optional, Tuple

from .core import Finding, relpath

UNTYPED_DEF = "untyped-def"
MYPY_ERROR = "mypy-error"

# the strict core: checkpoint/restore payload types and the obs layer
STRICT_TARGETS = (
    "smartcal_tpu/rl/replay.py",
    "smartcal_tpu/runtime/checkpoint.py",
    "smartcal_tpu/obs",
)


def mypy_available() -> bool:
    if shutil.which("mypy"):
        return True
    try:
        import mypy  # noqa: F401
        return True
    except ImportError:
        return False


def run_mypy(root: str, targets: Tuple[str, ...] = STRICT_TARGETS
             ) -> Tuple[List[Finding], str]:
    """(findings, raw output) from a real mypy run over the strict core."""
    cmd = [sys.executable, "-m", "mypy", "--config-file",
           os.path.join(root, "mypy.ini"), "--no-error-summary",
           *[os.path.join(root, t) for t in targets]]
    proc = subprocess.run(cmd, capture_output=True, text=True, cwd=root)
    findings: List[Finding] = []
    for line in proc.stdout.splitlines():
        # mypy format: path:line: severity: message
        parts = line.split(":", 3)
        if len(parts) < 4 or not parts[1].strip().isdigit():
            continue
        if "error" not in parts[2]:
            continue  # notes/warnings don't gate
        findings.append(Finding(
            path=relpath(parts[0], root), line=int(parts[1]), col=0,
            rule=MYPY_ERROR, message=parts[3].strip()))
    return findings, proc.stdout + proc.stderr


def _params_needing_annotation(fn: ast.AST) -> List[ast.arg]:
    a = fn.args
    out = []
    for p in a.posonlyargs + a.args + a.kwonlyargs:
        if p.arg in ("self", "cls"):
            continue
        if p.annotation is None:
            out.append(p)
    return out


def audit_file(path: str, root: str) -> List[Finding]:
    """Annotation audit of one file (see module doc for the contract)."""
    rel = relpath(path, root)
    with open(path, "rb") as fh:
        src = fh.read().decode("utf-8")
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding(path=rel, line=int(e.lineno or 1), col=0,
                        rule=UNTYPED_DEF,
                        message=f"file does not parse: {e.msg}")]
    findings: List[Finding] = []

    def is_public(name: str) -> bool:
        return not name.startswith("_") or name == "__init__"

    def scan(body, depth: int) -> None:
        for node in body:
            if isinstance(node, ast.ClassDef):
                scan(node.body, depth)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if is_public(node.name):
                    for p in _params_needing_annotation(node):
                        findings.append(Finding(
                            path=rel, line=p.lineno, col=p.col_offset,
                            rule=UNTYPED_DEF,
                            message=f"{node.name}(): parameter "
                                    f"'{p.arg}' missing a type "
                                    "annotation (strict-core module)"))
                    if node.returns is None and node.name != "__init__":
                        findings.append(Finding(
                            path=rel, line=node.lineno,
                            col=node.col_offset, rule=UNTYPED_DEF,
                            message=f"{node.name}(): missing return "
                                    "annotation (strict-core module)"))
                # nested defs are implementation detail: not scanned

    scan(tree.body, 0)
    return findings


def run_audit(root: str, targets: Tuple[str, ...] = STRICT_TARGETS
              ) -> List[Finding]:
    findings: List[Finding] = []
    for t in targets:
        ap = os.path.join(root, t)
        if os.path.isfile(ap):
            findings.extend(audit_file(ap, root))
        else:
            for d, subdirs, files in os.walk(ap):
                subdirs.sort()
                for fn in sorted(files):
                    if fn.endswith(".py"):
                        findings.extend(audit_file(os.path.join(d, fn),
                                                   root))
    return sorted(findings)


def run_types(root: str, targets: Tuple[str, ...] = STRICT_TARGETS,
              force_audit: bool = False
              ) -> Tuple[List[Finding], str]:
    """The --types gate: mypy when available, else the built-in audit.
    Returns (findings, mode) where mode is 'mypy' or 'audit'."""
    if not force_audit and mypy_available():
        findings, _raw = run_mypy(root, targets)
        return findings, "mypy"
    return run_audit(root, targets), "audit"
