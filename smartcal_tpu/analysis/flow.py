"""Tiny source-order dataflow scaffolding for the graftlint rules.

The rng-key-reuse and read-after-donation rules both need the same
shape of analysis: walk ONE function scope's statements in source
order, tracking a per-variable state machine (fresh -> consumed/donated
-> cleared on reassignment), with two structural caveats:

* **branches** (``if``/``elif``/``else``, ``try`` arms) are walked on
  CLONED state and merged conservatively — a variable counts as
  consumed after the branch only if EVERY arm consumed it, so mutually
  exclusive uses never false-positive;
* **loops** get a second look: a variable consumed inside a ``for``/
  ``while`` body that the body never reassigns is consumed again on
  the next iteration — the classic same-key-every-iteration bug — and
  is reported once per loop.

Scopes are module bodies and function bodies; nested ``def``/``class``
bodies are separate scopes (closures get no cross-scope tracking —
graftlint is a single-pass lint, not an escape analysis).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Tuple

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)
_SCOPE_NODES = _FUNC_NODES + (ast.ClassDef, ast.Lambda)


def dotted(node: ast.AST) -> Optional[str]:
    """'a', 'self._key', 'a.b.c' for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_func_name(call: ast.Call) -> Optional[str]:
    """Dotted name of the called function ('jax.random.split', 'f')."""
    return dotted(call.func)


def iter_scopes(tree: ast.Module) -> Iterator[Tuple[ast.AST, List[ast.stmt]]]:
    """Yield (scope_node, body) for the module and every function (any
    nesting depth), each exactly once.  Callers walk each yielded body
    flat — never descending into nested scope nodes — so every
    statement is analyzed in exactly one scope."""
    yield tree, tree.body
    for node in ast.walk(tree):
        if isinstance(node, _FUNC_NODES):
            yield node, node.body


def walk_in_scope(body: List[ast.stmt]) -> Iterator[ast.AST]:
    """Every AST node under these statements in a deterministic order,
    NOT descending into nested function/class/lambda scopes (those are
    separate scopes, yielded separately by :func:`iter_scopes`)."""
    queue: List[ast.AST] = list(body)
    i = 0
    while i < len(queue):
        node = queue[i]
        i += 1
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _SCOPE_NODES):
                continue
            queue.append(child)


def assigned_targets(stmt: ast.stmt) -> List[str]:
    """Dotted names (re)bound by this single statement: assignment
    targets, aug-assign, ``del``, ``with ... as``, and for-loop targets
    (the loop header rebinds on every iteration)."""
    out: List[str] = []

    def add_target(t: ast.AST) -> None:
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                add_target(e)
        elif isinstance(t, ast.Starred):
            add_target(t.value)
        else:
            name = dotted(t)
            if name:
                out.append(name)

    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            add_target(t)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        add_target(stmt.target)
    elif isinstance(stmt, ast.Delete):
        for t in stmt.targets:
            add_target(t)
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        add_target(stmt.target)
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            if item.optional_vars is not None:
                add_target(item.optional_vars)
    return out


def stmt_expressions(stmt: ast.stmt) -> List[ast.AST]:
    """The value-position expression roots of one statement (headers of
    compound statements; full body of simple ones), EXCLUDING nested
    compound bodies — the walkers recurse into those themselves."""
    if isinstance(stmt, ast.Assign):
        return [stmt.value]
    if isinstance(stmt, ast.AugAssign):
        return [stmt.target, stmt.value]
    if isinstance(stmt, ast.AnnAssign):
        return [stmt.value] if stmt.value is not None else []
    if isinstance(stmt, (ast.Expr, ast.Return)):
        return [stmt.value] if stmt.value is not None else []
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [i.context_expr for i in stmt.items]
    if isinstance(stmt, ast.Assert):
        return [stmt.test] + ([stmt.msg] if stmt.msg else [])
    if isinstance(stmt, ast.Raise):
        return [e for e in (stmt.exc, stmt.cause) if e is not None]
    if isinstance(stmt, ast.Delete):
        return []
    return []


def iter_calls(expr: ast.AST) -> Iterator[ast.Call]:
    """Calls inside an expression, source order, not entering nested
    scopes (lambda bodies are separate scopes)."""
    calls = []
    for node in ast.walk(expr):
        if isinstance(node, ast.Lambda):
            continue
        if isinstance(node, ast.Call):
            calls.append(node)
    calls.sort(key=lambda c: (c.lineno, c.col_offset))
    for c in calls:
        yield c


def read_names(expr: ast.AST) -> List[Tuple[str, ast.AST]]:
    """Dotted names in Load context inside ``expr`` (maximal chains:
    ``self._key`` reports once, not also ``self``)."""
    out: List[Tuple[str, ast.AST]] = []
    covered: set = set()

    class V(ast.NodeVisitor):
        def _try(self, node: ast.AST) -> bool:
            name = dotted(node)
            if name is not None:
                if id(node) not in covered:
                    out.append((name, node))
                    for sub in ast.walk(node):
                        covered.add(id(sub))
                return True
            return False

        def visit_Attribute(self, node: ast.Attribute) -> None:
            if id(node) in covered:
                return
            if not self._try(node):
                self.generic_visit(node)

        def visit_Name(self, node: ast.Name) -> None:
            if id(node) in covered:
                return
            self._try(node)

        def visit_Lambda(self, node: ast.Lambda) -> None:
            pass  # separate scope

    V().visit(expr)
    return [(n, node) for n, node in out
            if isinstance(getattr(node, "ctx", ast.Load()), ast.Load)]


def body_consumes_and_assigns(body: List[ast.stmt],
                              consume_names_of_stmt) -> Tuple[dict, set]:
    """For the loop-carry check: walk a loop body flat (not entering
    nested scopes) and report {name: first_consuming_node} plus the set
    of names the body ever (re)assigns."""
    consumed: Dict[str, ast.AST] = {}
    assigned: set = set()

    def walk(stmts: List[ast.stmt]) -> None:
        for stmt in stmts:
            if isinstance(stmt, _SCOPE_NODES):
                continue
            for name, node in consume_names_of_stmt(stmt):
                consumed.setdefault(name, node)
            assigned.update(assigned_targets(stmt))
            for sub in child_bodies(stmt):
                walk(sub)

    walk(body)
    return consumed, assigned


def walk_scope_linear(body: List[ast.stmt], state: Dict[str, ast.AST],
                      visit_stmt, loop_extract=None,
                      on_loop_carry=None) -> None:
    """Source-order walk of one scope's statements (see module doc).

    ``visit_stmt(stmt, state)`` handles one statement's own expressions
    and assignments (compound statements pass their HEADER here; their
    bodies are recursed into with branch-clone / loop-carry semantics).
    ``loop_extract(stmt) -> [(name, node)]`` names the consume events of
    one statement for the loop-carry check; ``on_loop_carry(name, node)``
    fires for names consumed in a loop body that the body never
    reassigns.
    """
    def recurse(sub, st):
        walk_scope_linear(sub, st, visit_stmt, loop_extract, on_loop_carry)

    def merge_into(state, arm_states):
        merged = {k: v for k, v in arm_states[0].items()
                  if all(k in s for s in arm_states[1:])}
        state.clear()
        state.update(merged)

    for stmt in body:
        if isinstance(stmt, _SCOPE_NODES):
            continue  # nested scopes are analyzed independently
        visit_stmt(stmt, state)
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            if loop_extract is not None and on_loop_carry is not None:
                consumed, assigned = body_consumes_and_assigns(
                    stmt.body, loop_extract)
                for name, node in consumed.items():
                    if name not in assigned:
                        on_loop_carry(name, node)
            st = dict(state)
            recurse(stmt.body, st)
            state.clear()
            state.update(st)
            if stmt.orelse:
                recurse(stmt.orelse, state)
        elif isinstance(stmt, ast.If):
            arms = []
            for arm in (stmt.body, stmt.orelse):
                st = dict(state)
                if arm:
                    recurse(arm, st)
                arms.append(st)
            merge_into(state, arms)
        elif isinstance(stmt, ast.Try):
            main = dict(state)
            recurse(stmt.body + stmt.orelse, main)
            arms = [main]
            for h in stmt.handlers:
                st = dict(state)
                recurse(h.body, st)
                arms.append(st)
            merge_into(state, arms)
            if stmt.finalbody:
                recurse(stmt.finalbody, state)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            recurse(stmt.body, state)


def child_bodies(stmt: ast.stmt) -> List[List[ast.stmt]]:
    """Nested statement lists of a compound statement (branch arms,
    loop bodies, with bodies, try arms)."""
    out: List[List[ast.stmt]] = []
    for field in ("body", "orelse", "finalbody"):
        sub = getattr(stmt, field, None)
        if sub and isinstance(sub, list) \
                and all(isinstance(s, ast.stmt) for s in sub):
            out.append(sub)
    for h in getattr(stmt, "handlers", []) or []:
        out.append(h.body)
    return out
