"""Fault-tolerant training runtime shared by the train drivers and the
parallel learners.

Seven small, composable pieces:

* :mod:`~smartcal_tpu.runtime.atomic` — crash-safe file writes
  (tmp + ``os.replace``) and corruption-tolerant pickle loads.  Every
  score/model/replay ``pickle.dump`` in the repo routes through these so
  a mid-write SIGKILL can no longer leave a truncated checkpoint behind.
* :mod:`~smartcal_tpu.runtime.checkpoint` — the versioned run
  checkpoint store: ``ckpt_<step>/`` dirs holding ONE pickled payload
  (agent params + optimizer state + targets + replay contents incl. PER
  priorities + RNG key streams + episode counters), sha256-validated,
  with a ``LATEST`` pointer and a retain-last-K policy.
* :mod:`~smartcal_tpu.runtime.backoff` — deterministic exponential
  backoff with jitter and a bounded budget, shared by actor restarts
  and the chip-probe retry loops.
* :mod:`~smartcal_tpu.runtime.faults` — the deterministic
  fault-injection harness (NaN into a named update field at step s,
  kill actor i at iteration n, delay a named dispatch) that makes the
  recovery paths testable on CPU.
* :mod:`~smartcal_tpu.runtime.recovery` — the watchdog escalation
  policy: roll back to the last good checkpoint, apply a mitigation
  (LR shrink / exploration reseed), retry within a bounded budget.
* :mod:`~smartcal_tpu.runtime.supervisor` — heartbeat-monitored actor
  slots (threads or spawned worker processes) with restart-on-death
  (exponential backoff + jitter) for the parallel learners.
* :mod:`~smartcal_tpu.runtime.ipc` — framed, CRC-validated pickle
  transport for the process-backed fleet (truncated mid-send payloads
  surface as droppable :class:`CorruptPayloadError`, never a poisoned
  learner iteration).

Import cost: stdlib only at package import; jax is read lazily inside
the functions that move device arrays.
"""

from .atomic import (CorruptStateError, atomic_pickle,       # noqa: F401
                     atomic_write_bytes, atomic_write_text,
                     safe_pickle_load, strict_pickle_load)
from .backoff import Backoff, BackoffPolicy                  # noqa: F401
from .checkpoint import (Checkpointer, load_latest,          # noqa: F401
                         pack_env_state, pack_replay, restore_env_state,
                         save_checkpoint, unpack_replay)
from .faults import (FaultInjected, FaultPlan,               # noqa: F401
                     clear as clear_faults, install as install_faults,
                     plan_from_env)
from .ipc import (CorruptPayloadError, frame_payload,        # noqa: F401
                  unframe_payload)
from .recovery import (RecoveryAction, RecoveryManager,      # noqa: F401
                       RecoveryPolicy)
from .supervisor import Fleet                                # noqa: F401
