"""Crash-safe file writes and corruption-tolerant loads.

The repo's original persistence was ``open(path, "wb"); pickle.dump`` —
a SIGTERM/preemption mid-write leaves a truncated file at the final
path, and the next ``--load`` run dies inside ``pickle.load`` with an
opaque ``EOFError``.  Two rules fix both halves:

* **writes** go to a same-directory temp file, ``fsync``, then one
  ``os.replace`` — readers see either the old bytes or the new bytes,
  never a prefix;
* **loads** of resumable state go through :func:`safe_pickle_load`,
  which turns a missing/truncated/corrupt file into a warning plus a
  caller-supplied default (start fresh) instead of a crash.

Stdlib only — no jax, no numpy.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import tempfile
from typing import Any, Callable, Optional


def _fsync_dir(path: str) -> None:
    """Best-effort directory fsync so the rename itself is durable."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_bytes(path: str, data: bytes, fsync: bool = True) -> None:
    """Write ``data`` to ``path`` atomically (tmp file + ``os.replace``).

    The temp file lives in the SAME directory so the final rename never
    crosses a filesystem boundary (cross-device rename is a copy, which
    reintroduces the torn-write window).
    """
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(prefix=f".{os.path.basename(path)}.",
                               suffix=".tmp", dir=d)
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            if fsync:
                os.fsync(f.fileno())
        os.replace(tmp, path)
        if fsync:
            _fsync_dir(path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def atomic_write_text(path: str, text: str, fsync: bool = True) -> None:
    atomic_write_bytes(path, text.encode("utf-8"), fsync=fsync)


def atomic_pickle(obj: Any, path: str, fsync: bool = True) -> int:
    """Atomically pickle ``obj`` at ``path``; returns the byte count."""
    data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    atomic_write_bytes(path, data, fsync=fsync)
    return len(data)


def sha256_file(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


class CorruptStateError(RuntimeError):
    """A must-exist persisted payload is missing, truncated or
    unreadable — the torn-write kill signature surfaced as a clear
    error instead of an opaque ``EOFError`` deep inside pickle."""


def strict_pickle_load(path: str) -> Any:
    """Load a pickle that MUST exist and parse.

    The counterpart of :func:`safe_pickle_load` for state with no
    sane fresh-start (trained models, eval payloads): failures raise
    :class:`CorruptStateError` naming the file and the likely cause so
    the operator sees "restore or regenerate", not a pickle traceback.
    """
    if not os.path.exists(path):
        raise CorruptStateError(
            f"required state file {path!r} does not exist")
    try:
        with open(path, "rb") as f:
            return pickle.load(f)
    except Exception as e:
        raise CorruptStateError(
            f"required state file {path!r} is unreadable ({e!r}) — "
            "likely a torn write from a mid-save kill; restore from a "
            "checkpoint or regenerate it") from e


def safe_pickle_load(path: str, default: Any = None,
                     warn: Optional[Callable[[str], None]] = None) -> Any:
    """Load a pickle, degrading to ``default`` on ANY corruption.

    Missing file, truncated stream (the mid-write kill signature),
    or an unpicklable payload all warn (via ``warn``, default: the
    obs echo so the message reaches stderr + the RunLog) and return
    ``default`` — resume paths start fresh instead of crashing.
    """
    if warn is None:
        def warn(msg):
            try:
                from smartcal_tpu import obs
                obs.echo(msg, event="log")
            except Exception:
                import sys
                sys.stderr.write(msg + "\n")
    if not os.path.exists(path):
        warn(f"resume file {path!r} missing; starting fresh")
        return default
    try:
        with open(path, "rb") as f:
            return pickle.load(f)
    except Exception as e:
        warn(f"resume file {path!r} unreadable ({e!r}); starting fresh")
        return default
