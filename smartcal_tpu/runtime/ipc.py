"""Framed, integrity-checked IPC for the cross-process actor fleet.

The process-backed fleet (:mod:`smartcal_tpu.runtime.supervisor`,
``actor_mode="process"``) moves versioned transition batches, weight
snapshots and heartbeats between the learner process and spawned actor
worker processes over ``multiprocessing.Pipe`` connections.  A worker
can die at ANY byte of a send (SIGKILL, OOM, preemption), so every
payload travels as a self-validating frame::

    MAGIC(4) | payload_len(4, BE) | crc32(4, BE) | pickle(payload)

and the receiving side treats a bad magic, a length mismatch, a CRC
mismatch or an unpicklable body as :class:`CorruptPayloadError` — a
subclass of :class:`~smartcal_tpu.runtime.atomic.CorruptStateError`, so
it rides the same drop-and-log discipline as a torn checkpoint file:
the learner drops the one broken batch and keeps training, instead of
letting a half-serialized pytree poison the ingest iteration.

Message vocabulary (tuples, first element is the kind):

* parent -> worker: ``("weights", version, host_pytree)``, ``("stop",)``
* worker -> parent: ``("beat", iteration)``,
  ``("result", iteration, weights_version, host_transitions)``,
  ``("error", iteration, repr_str)``

Stdlib only — workers exchange plain host pytrees; device placement is
the learner's business.
"""

from __future__ import annotations

import importlib
import os
import pickle
import struct
import zlib
from typing import Any, Callable, Optional

from .atomic import CorruptStateError

MAGIC = b"SCF1"
_HEADER = struct.Struct("!4sII")


class CorruptPayloadError(CorruptStateError):
    """An IPC frame failed validation (bad magic / length / CRC /
    unpicklable body) — the mid-send-death signature of a worker
    process, surfaced as droppable corruption instead of a crash."""


def frame_payload(obj: Any) -> bytes:
    """Serialize ``obj`` into one self-validating frame."""
    body = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    return _HEADER.pack(MAGIC, len(body), zlib.crc32(body)) + body


def unframe_payload(data: bytes) -> Any:
    """Validate + deserialize one frame; raises
    :class:`CorruptPayloadError` on any integrity failure."""
    if len(data) < _HEADER.size:
        raise CorruptPayloadError(
            f"IPC frame truncated: {len(data)} bytes < "
            f"{_HEADER.size}-byte header")
    magic, length, crc = _HEADER.unpack_from(data)
    body = data[_HEADER.size:]
    if magic != MAGIC:
        raise CorruptPayloadError(f"IPC frame bad magic {magic!r}")
    if len(body) != length:
        raise CorruptPayloadError(
            f"IPC frame length mismatch: header says {length}, "
            f"got {len(body)} payload bytes (mid-send death?)")
    if zlib.crc32(body) != crc:
        raise CorruptPayloadError("IPC frame CRC mismatch")
    try:
        return pickle.loads(body)
    except Exception as e:
        raise CorruptPayloadError(
            f"IPC frame body unpicklable ({e!r})") from e


def send_msg(conn, obj: Any) -> None:
    """Frame + send one message on a Connection."""
    conn.send_bytes(frame_payload(obj))


def send_blob(conn, blob: bytes) -> None:
    """Send an already-framed payload (one serialization, N workers)."""
    conn.send_bytes(blob)


def recv_msg(conn) -> Any:
    """Receive + validate one message.  Raises ``EOFError``/``OSError``
    when the peer is gone, :class:`CorruptPayloadError` on a bad frame."""
    return unframe_payload(conn.recv_bytes())


def resolve_factory(spec: str) -> Callable:
    """``"pkg.module:callable"`` -> the callable (the picklable form a
    spawned worker uses to rebuild its work function)."""
    mod_name, _, fn_name = spec.partition(":")
    if not mod_name or not fn_name:
        raise ValueError(
            f"worker factory spec {spec!r} must be 'module:callable'")
    mod = importlib.import_module(mod_name)
    fn = getattr(mod, fn_name, None)
    if fn is None:
        raise ValueError(f"worker factory {fn_name!r} not found in "
                         f"{mod_name!r}")
    return fn


def worker_main(conn, actor_id: int, start_iteration: int,
                factory: str, factory_kwargs: dict,
                host_id: int = 0, n_hosts: int = 1,
                platform: Optional[str] = "cpu") -> None:
    """Entry point of a spawned actor worker process.

    Pins the worker's jax platform (default ``"cpu"``: workers are
    host-side rollout engines feeding a device-resident learner, and an
    accelerator like a TPU is a SINGLE-client device the learner
    already holds — a worker initializing the same backend would crash
    or wedge it; pass ``platform=None`` via ``worker_spec["platform"]``
    to inherit the environment instead), attaches to the (simulated)
    multi-host runtime, re-arms the deterministic fault plan from
    ``SMARTCAL_FAULTS`` (inherited env), rebuilds the work function
    from its picklable factory spec, then loops: drain control frames
    (keep the NEWEST weights), beat, run one rollout iteration, ship
    the versioned result.  Any work-fn exception is reported as an
    ``error`` frame naming the killing iteration (the supervisor's
    poison-pill skip currency) before the process exits.
    """
    if platform:
        os.environ["JAX_PLATFORMS"] = platform

    from smartcal_tpu.parallel import multihost
    from smartcal_tpu.runtime import faults as rt_faults

    if platform:
        # a sitecustomize may pin jax_platforms at interpreter start,
        # overriding the env var — repeat the pin on the config once
        # the jax module is in (backends have not initialized yet:
        # nothing above touches devices)
        try:
            import jax

            jax.config.update("jax_platforms", platform)
        except Exception:
            pass
    multihost.attach_simulated(host_id, n_hosts)
    rt_faults.install_from_env()
    work_fn = resolve_factory(factory)(**(factory_kwargs or {}))

    iteration = int(start_iteration)
    weights: Any = None
    version = 0
    have_weights = False
    test_corrupt = _test_corrupt_plan()
    try:
        while True:
            # drain the control inbox; the newest weights frame wins.
            # Block (short ticks) until the FIRST weights arrive so the
            # initial rollout never runs against nothing.
            while conn.poll(0 if have_weights else 0.2):
                try:
                    msg = recv_msg(conn)
                except CorruptPayloadError:
                    continue            # parent->worker corruption: skip
                if msg[0] == "stop":
                    return
                if msg[0] == "weights":
                    version, weights = int(msg[1]), msg[2]
                    have_weights = True
            if not have_weights:
                send_msg(conn, ("beat", iteration))
                continue
            send_msg(conn, ("beat", iteration))
            try:
                out = work_fn(actor_id, iteration, weights)
            except BaseException as e:  # noqa: BLE001 — death IS the signal
                send_msg(conn, ("error", iteration, repr(e)))
                return
            if test_corrupt is not None and iteration == test_corrupt:
                # test hook (SMARTCAL_IPC_TEST_CORRUPT=<iteration>):
                # emulate a death mid-send — ship a deliberately
                # corrupted frame instead of the result, then die, so
                # the drop-and-log path is exercisable end to end
                blob = bytearray(frame_payload(
                    ("result", iteration, version, out)))
                blob[-1] ^= 0xFF
                send_blob(conn, bytes(blob))
                return
            send_msg(conn, ("result", iteration, version, out))
            iteration += 1
    except (EOFError, OSError, BrokenPipeError):
        return                          # parent gone: nothing to report


def _test_corrupt_plan() -> Optional[int]:
    raw = os.environ.get("SMARTCAL_IPC_TEST_CORRUPT", "").strip()
    if not raw:
        return None
    try:
        return int(raw)
    except ValueError:
        return None
