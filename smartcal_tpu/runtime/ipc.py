"""Framed, integrity-checked IPC for the cross-process actor fleet.

The process-backed fleet (:mod:`smartcal_tpu.runtime.supervisor`,
``actor_mode="process"``) moves versioned transition batches, weight
snapshots and heartbeats between the learner process and spawned actor
worker processes over ``multiprocessing.Pipe`` connections.  A worker
can die at ANY byte of a send (SIGKILL, OOM, preemption), so every
payload travels as a self-validating frame::

    MAGIC(4) | payload_len(4, BE) | crc32(4, BE) | pickle(payload)

and the receiving side treats a bad magic, a length mismatch, a CRC
mismatch or an unpicklable body as :class:`CorruptPayloadError` — a
subclass of :class:`~smartcal_tpu.runtime.atomic.CorruptStateError`, so
it rides the same drop-and-log discipline as a torn checkpoint file:
the learner drops the one broken batch and keeps training, instead of
letting a half-serialized pytree poison the ingest iteration.

Message vocabulary (tuples, first element is the kind):

* parent -> worker: ``("weights", version, host_pytree)``, ``("stop",)``
* worker -> parent: ``("beat", iteration)``,
  ``("result", iteration, weights_version, host_transitions)``,
  ``("error", iteration, repr_str)``

Stdlib only — workers exchange plain host pytrees; device placement is
the learner's business.
"""

from __future__ import annotations

import importlib
import json
import os
import pickle
import struct
import zlib
from typing import Any, Callable, Dict, Optional, Tuple

from .atomic import CorruptStateError

MAGIC = b"SCF1"
TRACED_MAGIC = b"SCT1"
_HEADER = struct.Struct("!4sII")
# traced-frame prelude: TRACED_MAGIC | trace_len(4, BE) | trace_json |
# <embedded standard SCF1 frame>.  The trace envelope rides OUTSIDE the
# CRC'd pickle body on purpose: when a worker dies mid-send and the
# body arrives corrupt, the intact prelude still names the trace the
# frame belonged to, so the drop is reported against its request
# instead of vanishing from the merged timeline.
_THEADER = struct.Struct("!4sI")
_MAX_TRACE_BYTES = 4096


class CorruptPayloadError(CorruptStateError):
    """An IPC frame failed validation (bad magic / length / CRC /
    unpicklable body) — the mid-send-death signature of a worker
    process, surfaced as droppable corruption instead of a crash.

    ``trace`` carries the traced-frame envelope (dict) when the broken
    frame's prelude survived, else None."""

    trace: Optional[Dict[str, Any]] = None


def frame_payload(obj: Any,
                  trace: Optional[Dict[str, Any]] = None) -> bytes:
    """Serialize ``obj`` into one self-validating frame, optionally
    prefixed with a trace envelope (see module docstring)."""
    body = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    frame = _HEADER.pack(MAGIC, len(body), zlib.crc32(body)) + body
    if trace is None:
        return frame
    tbody = json.dumps(trace).encode("utf-8")
    if len(tbody) > _MAX_TRACE_BYTES:   # never let tags starve payloads
        tbody = json.dumps({k: trace[k] for k in ("trace", "span", "t")
                            if k in trace}).encode("utf-8")
    return _THEADER.pack(TRACED_MAGIC, len(tbody)) + tbody + frame


def _split_traced(data: bytes) -> Tuple[bytes, Optional[Dict[str, Any]]]:
    """Strip a traced-frame prelude, returning (inner_frame, trace).
    A mangled prelude degrades to (data, None) — the inner validation
    then reports the corruption."""
    if len(data) < _THEADER.size or data[:4] != TRACED_MAGIC:
        return data, None
    _, tlen = _THEADER.unpack_from(data)
    end = _THEADER.size + tlen
    if tlen > _MAX_TRACE_BYTES or len(data) < end:
        return data[_THEADER.size:], None
    try:
        trace = json.loads(data[_THEADER.size:end].decode("utf-8"))
    except (UnicodeDecodeError, ValueError):
        trace = None
    if not isinstance(trace, dict):
        trace = None
    return data[end:], trace


def unframe_payload_traced(
        data: bytes) -> Tuple[Any, Optional[Dict[str, Any]]]:
    """Validate + deserialize one frame, returning ``(obj, trace)``
    (trace None for plain frames).  On any integrity failure raises
    :class:`CorruptPayloadError` with ``.trace`` set from the prelude
    when it survived."""
    inner, trace = _split_traced(data)
    if len(inner) < _HEADER.size:
        raise _corrupt(
            f"IPC frame truncated: {len(inner)} bytes < "
            f"{_HEADER.size}-byte header", trace)
    magic, length, crc = _HEADER.unpack_from(inner)
    body = inner[_HEADER.size:]
    if magic != MAGIC:
        raise _corrupt(f"IPC frame bad magic {magic!r}", trace)
    if len(body) != length:
        raise _corrupt(
            f"IPC frame length mismatch: header says {length}, "
            f"got {len(body)} payload bytes (mid-send death?)", trace)
    if zlib.crc32(body) != crc:
        raise _corrupt("IPC frame CRC mismatch", trace)
    try:
        return pickle.loads(body), trace
    except Exception as e:
        raise _corrupt(
            f"IPC frame body unpicklable ({e!r})", trace) from e


def _corrupt(msg: str,
             trace: Optional[Dict[str, Any]]) -> CorruptPayloadError:
    err = CorruptPayloadError(msg)
    err.trace = trace
    return err


def unframe_payload(data: bytes) -> Any:
    """Validate + deserialize one frame (trace prelude, if any,
    discarded); raises :class:`CorruptPayloadError` on any integrity
    failure."""
    return unframe_payload_traced(data)[0]


def send_msg(conn, obj: Any,
             trace: Optional[Dict[str, Any]] = None) -> None:
    """Frame + send one message on a Connection."""
    conn.send_bytes(frame_payload(obj, trace=trace))


def send_blob(conn, blob: bytes) -> None:
    """Send an already-framed payload (one serialization, N workers)."""
    conn.send_bytes(blob)


def recv_msg(conn) -> Any:
    """Receive + validate one message.  Raises ``EOFError``/``OSError``
    when the peer is gone, :class:`CorruptPayloadError` on a bad frame."""
    return unframe_payload(conn.recv_bytes())


def recv_msg_traced(conn) -> Tuple[Any, Optional[Dict[str, Any]]]:
    """Receive + validate one message, returning ``(obj, trace)`` —
    the trace-aware pump's receive path (fleet replica / actor pumps
    use the envelope's ``t`` for the clock-offset handshake)."""
    return unframe_payload_traced(conn.recv_bytes())


def resolve_factory(spec: str) -> Callable:
    """``"pkg.module:callable"`` -> the callable (the picklable form a
    spawned worker uses to rebuild its work function)."""
    mod_name, _, fn_name = spec.partition(":")
    if not mod_name or not fn_name:
        raise ValueError(
            f"worker factory spec {spec!r} must be 'module:callable'")
    mod = importlib.import_module(mod_name)
    fn = getattr(mod, fn_name, None)
    if fn is None:
        raise ValueError(f"worker factory {fn_name!r} not found in "
                         f"{mod_name!r}")
    return fn


def worker_main(conn, actor_id: int, start_iteration: int,
                factory: str, factory_kwargs: dict,
                host_id: int = 0, n_hosts: int = 1,
                platform: Optional[str] = "cpu") -> None:
    """Entry point of a spawned actor worker process.

    Pins the worker's jax platform (default ``"cpu"``: workers are
    host-side rollout engines feeding a device-resident learner, and an
    accelerator like a TPU is a SINGLE-client device the learner
    already holds — a worker initializing the same backend would crash
    or wedge it; pass ``platform=None`` via ``worker_spec["platform"]``
    to inherit the environment instead), attaches to the (simulated)
    multi-host runtime, re-arms the deterministic fault plan from
    ``SMARTCAL_FAULTS`` (inherited env), rebuilds the work function
    from its picklable factory spec, then loops: drain control frames
    (keep the NEWEST weights), beat, run one rollout iteration, ship
    the versioned result.  Any work-fn exception is reported as an
    ``error`` frame naming the killing iteration (the supervisor's
    poison-pill skip currency) before the process exits.
    """
    if platform:
        os.environ["JAX_PLATFORMS"] = platform

    import time

    from smartcal_tpu.obs import tracectx
    from smartcal_tpu.parallel import multihost
    from smartcal_tpu.runtime import faults as rt_faults

    if platform:
        # a sitecustomize may pin jax_platforms at interpreter start,
        # overriding the env var — repeat the pin on the config once
        # the jax module is in (backends have not initialized yet:
        # nothing above touches devices)
        try:
            import jax

            jax.config.update("jax_platforms", platform)
        except Exception:
            pass
    multihost.attach_simulated(host_id, n_hosts)
    rt_faults.install_from_env()
    work_fn = resolve_factory(factory)(**(factory_kwargs or {}))

    iteration = int(start_iteration)
    weights: Any = None
    version = 0
    have_weights = False
    ctl_trace: Optional[Dict[str, Any]] = None
    test_corrupt = _test_corrupt_plan()

    def beat_env() -> Dict[str, Any]:
        # beats always carry the send wall time: the parent pump's
        # recv-minus-send minimum is the clock-offset handshake
        return {"t": round(time.time(), 6)}

    try:
        while True:
            # drain the control inbox; the newest weights frame wins.
            # Block (short ticks) until the FIRST weights arrive so the
            # initial rollout never runs against nothing.
            while conn.poll(0 if have_weights else 0.2):
                try:
                    msg, msg_trace = recv_msg_traced(conn)
                except CorruptPayloadError:
                    continue            # parent->worker corruption: skip
                if msg[0] == "stop":
                    return
                if msg[0] == "weights":
                    version, weights = int(msg[1]), msg[2]
                    have_weights = True
                    if msg_trace and "trace" in msg_trace:
                        ctl_trace = msg_trace
            if not have_weights:
                send_msg(conn, ("beat", iteration), trace=beat_env())
                continue
            send_msg(conn, ("beat", iteration), trace=beat_env())
            try:
                # rollout spans/events become children of the learner's
                # publishing span when the weights frame carried one
                with tracectx.use_trace(ctl_trace):
                    out = work_fn(actor_id, iteration, weights)
            except BaseException as e:  # noqa: BLE001 — death IS the signal
                send_msg(conn, ("error", iteration, repr(e)),
                         trace=beat_env())
                return
            if test_corrupt is not None and iteration == test_corrupt:
                # test hook (SMARTCAL_IPC_TEST_CORRUPT=<iteration>):
                # emulate a death mid-send — ship a deliberately
                # corrupted frame instead of the result, then die, so
                # the drop-and-log path is exercisable end to end
                blob = bytearray(frame_payload(
                    ("result", iteration, version, out),
                    trace=beat_env()))
                blob[-1] ^= 0xFF
                send_blob(conn, bytes(blob))
                return
            send_msg(conn, ("result", iteration, version, out),
                     trace=beat_env())
            iteration += 1
    except (EOFError, OSError, BrokenPipeError):
        return                          # parent gone: nothing to report


def _test_corrupt_plan() -> Optional[int]:
    raw = os.environ.get("SMARTCAL_IPC_TEST_CORRUPT", "").strip()
    if not raw:
        return None
    try:
        return int(raw)
    except ValueError:
        return None
