"""Deterministic exponential backoff with jitter and a bounded budget.

One policy object shared by every retry loop in the repo — actor
restarts (:mod:`~smartcal_tpu.runtime.supervisor`), the chip-probe
loops (``tools/chip_probe.py``, ``bench.probe_backend``) — so "retry
forever with a fixed sleep" can't creep back in.  Jitter is drawn from
a caller-seeded :class:`random.Random`, so tests (and same-seed reruns)
see the exact same delay sequence.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Optional


@dataclasses.dataclass(frozen=True)
class BackoffPolicy:
    base_s: float = 1.0          # first delay
    factor: float = 2.0          # multiplier per attempt
    max_s: float = 300.0         # per-delay cap (pre-jitter)
    jitter: float = 0.25         # +/- fraction of the computed delay
    max_attempts: Optional[int] = None   # None = unbounded count
    budget_s: Optional[float] = None     # total-sleep bound; None = unbounded

    def delay(self, attempt: int, rng: Optional[random.Random] = None
              ) -> float:
        """Delay before retry ``attempt`` (0-based), jittered."""
        d = min(self.base_s * (self.factor ** attempt), self.max_s)
        if self.jitter > 0.0 and rng is not None:
            d *= 1.0 + self.jitter * (2.0 * rng.random() - 1.0)
        return max(0.0, d)


class Backoff:
    """Stateful walk through a :class:`BackoffPolicy`.

    ``next_delay()`` returns the next sleep (clipped into the remaining
    budget) or ``None`` once the policy says give up; the caller does
    the actual sleeping so the class stays trivially testable.
    """

    def __init__(self, policy: BackoffPolicy, seed: int = 0):
        self.policy = policy
        self.attempt = 0
        self.spent_s = 0.0
        self._rng = random.Random(seed)

    @property
    def exhausted(self) -> bool:
        p = self.policy
        if p.max_attempts is not None and self.attempt >= p.max_attempts:
            return True
        if p.budget_s is not None and self.spent_s >= p.budget_s:
            return True
        return False

    def next_delay(self) -> Optional[float]:
        """The delay to sleep before the next retry, or None to give up."""
        if self.exhausted:
            return None
        d = self.policy.delay(self.attempt, self._rng)
        if self.policy.budget_s is not None:
            d = min(d, max(0.0, self.policy.budget_s - self.spent_s))
        self.attempt += 1
        self.spent_s += d
        return d
