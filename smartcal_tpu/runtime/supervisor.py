"""Heartbeat-supervised actor-thread fleet for the parallel learners.

The SPMD learners (:mod:`smartcal_tpu.parallel.learner`,
``demix_learner``) fuse actors into one jitted program — nothing there
can die independently.  The supervised mode instead runs each actor as
a host thread (the IMPACT-shaped split: actors roll out against a
possibly-stale weights snapshot, the learner consumes whatever arrives)
and THIS module is the part that survives faults:

* each actor thread beats a heartbeat before every rollout and pushes
  its result onto the shared queue;
* :meth:`Fleet.poll` (called from the learner loop) detects dead
  threads (work_fn raised — e.g. an injected
  :class:`~smartcal_tpu.runtime.faults.FaultInjected`) and HUNG threads
  (heartbeat older than ``heartbeat_timeout``; the thread is abandoned
  as a daemon and a replacement spawned);
* restarts happen after an exponential backoff with jitter
  (:class:`~smartcal_tpu.runtime.backoff.BackoffPolicy`), at most
  ``max_restarts`` times per actor slot; a replacement resumes at the
  iteration AFTER the one that killed its predecessor, so a
  deterministic poison-pill iteration cannot crash-loop the slot;
* the learner keeps training from whatever subset of the fleet is
  alive; ``Fleet.stop(join=True)`` is the one call a tripping watchdog
  needs to leave no actor running against a dead learner.

Telemetry: ``actor_down`` / ``actor_restart`` / ``actor_failed`` RunLog
events, an ``actors_alive`` gauge and an ``actor_restarts`` counter via
the existing obs registry.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Optional

from .backoff import BackoffPolicy
from .faults import FaultInjected  # noqa: F401  (re-export for callers)

# work_fn(actor_id, iteration, weights) -> host result pushed to the queue
WorkFn = Callable[[int, int, Any], Any]


class _Actor(threading.Thread):
    def __init__(self, fleet: "Fleet", actor_id: int, start_iteration: int):
        super().__init__(name=f"{fleet.name}-{actor_id}", daemon=True)
        self.fleet = fleet
        self.actor_id = actor_id
        self.iteration = start_iteration
        self.last_beat = time.monotonic()
        self.stop_event = threading.Event()
        self.error: Optional[BaseException] = None

    def run(self):
        f = self.fleet
        while not self.stop_event.is_set():
            self.last_beat = time.monotonic()
            weights, version = f.get_weights()
            try:
                out = f.work_fn(self.actor_id, self.iteration, weights)
            except BaseException as e:   # noqa: BLE001 — death IS the signal
                self.error = e
                return
            # bounded ingest queue: when the learner falls behind, the
            # put blocks (back-pressure — actors must not free-run
            # arbitrarily far ahead of the policy they feed).  Re-beat
            # the heartbeat while waiting so back-pressure is never
            # mistaken for a hung rollout.
            item = (self.actor_id, self.iteration, version, out)
            while not self.stop_event.is_set():
                try:
                    # short tick: re-beat the heartbeat and re-check the
                    # stop flag while waiting, so shutdown never stalls
                    # behind a full queue
                    f._q.put(item, timeout=0.2)
                    break
                except queue.Full:
                    self.last_beat = time.monotonic()
            self.iteration += 1


class Fleet:
    """A supervised set of ``n_actors`` worker threads (see module doc)."""

    def __init__(self, n_actors: int, work_fn: WorkFn, *,
                 name: str = "actor", heartbeat_timeout: float = 60.0,
                 max_restarts: int = 3,
                 backoff: Optional[BackoffPolicy] = None, seed: int = 0,
                 queue_depth: int = 2):
        self.n_actors = int(n_actors)
        self.work_fn = work_fn
        self.name = name
        self.heartbeat_timeout = float(heartbeat_timeout)
        self.max_restarts = int(max_restarts)
        self.backoff = backoff or BackoffPolicy(base_s=0.25, factor=2.0,
                                                max_s=30.0, jitter=0.25)
        self._seed = seed
        # bounded to queue_depth results per actor slot: actors block
        # (with heartbeat) when the learner lags — staleness stays
        # bounded by the queue depth plus the publication cadence
        # instead of growing with every learner hiccup
        self._q: "queue.Queue" = queue.Queue(
            maxsize=max(1, int(queue_depth)) * self.n_actors)
        self._weights: Any = None
        self._version = 0
        self._wlock = threading.Lock()
        self._actors: dict = {}              # slot -> _Actor (current)
        self._restarts = {i: 0 for i in range(self.n_actors)}
        self._pending: dict = {}             # slot -> (due_monotonic, iter)
        self._failed: set = set()            # slots past max_restarts
        self._stopped = False
        import random
        self._rng = random.Random(seed)

    # -- weights snapshot --------------------------------------------------
    def set_weights(self, weights: Any, version: Optional[int] = None
                    ) -> int:
        """Publish a fresh snapshot.  ``version`` pins the snapshot's
        version explicitly (the async learner stamps its own
        learner-round counter so staleness-in-versions is measured in
        learner rounds, and a resumed run continues its predecessor's
        version stream); default keeps the auto-increment."""
        with self._wlock:
            self._weights = weights
            if version is not None:
                self._version = int(version)
            else:
                self._version += 1
            return self._version

    def get_weights(self):
        with self._wlock:
            return self._weights, self._version

    @property
    def version(self) -> int:
        with self._wlock:
            return self._version

    # -- lifecycle ---------------------------------------------------------
    def start(self, weights: Any, start_iterations: Optional[dict] = None,
              version: Optional[int] = None) -> None:
        """Spawn every actor slot.  ``start_iterations`` (slot -> first
        rollout iteration; default 0) lets a resumed run continue each
        slot's deterministic key stream where its predecessor stopped —
        the fleet half of the checkpoint payload (``slot_iterations``)."""
        self.set_weights(weights, version=version)
        start_iterations = start_iterations or {}
        for i in range(self.n_actors):
            self._spawn(i, start_iteration=int(start_iterations.get(i, 0)))
        self._gauge()

    def slot_iterations(self) -> dict:
        """slot -> the next rollout iteration that slot would run — what
        a checkpoint must record so a resumed fleet continues every
        per-(actor, iteration) key stream instead of replaying it.
        Pending restarts report their scheduled resume iteration; a DEAD
        actor reports the iteration AFTER the one that killed it (the
        same poison-pill skip the live restart path applies — resuming
        at the killing iteration would crash-loop the slot on every
        resume)."""
        out = {}
        for slot in range(self.n_actors):
            if slot in self._pending:
                out[slot] = int(self._pending[slot][1])
            elif slot in self._actors:
                a = self._actors[slot]
                it = int(a.iteration)
                if not a.is_alive() and a.error is not None:
                    it += 1
                out[slot] = it
            else:
                out[slot] = 0
        return out

    def _spawn(self, slot: int, start_iteration: int) -> None:
        a = _Actor(self, slot, start_iteration)
        self._actors[slot] = a
        a.start()

    def stop(self, join: bool = True, timeout: float = 10.0) -> int:
        """Signal every actor to stop; with ``join`` wait for each thread
        (hung threads are daemons and are abandoned after ``timeout``).
        Returns the number of threads that actually joined.  Idempotent —
        a second call (trip path, then the driver's finally) is a no-op."""
        if self._stopped:
            return 0
        self._stopped = True
        for a in self._actors.values():
            a.stop_event.set()
        joined = 0
        if join:
            deadline = time.monotonic() + timeout
            for a in self._actors.values():
                a.join(timeout=max(0.0, deadline - time.monotonic()))
                joined += 0 if a.is_alive() else 1
        self._log("actors_stopped", joined=joined,
                  total=len(self._actors))
        self._gauge()
        return joined

    # -- collection --------------------------------------------------------
    def collect(self, max_items: int, timeout: float) -> list:
        """Up to ``max_items`` queued results, waiting at most ``timeout``
        seconds TOTAL for the first one (later ones are taken only if
        already queued).  Returns [(actor_id, iteration, weights_version,
        result), ...] — possibly empty when the whole fleet is down."""
        out = []
        deadline = time.monotonic() + timeout
        while len(out) < max_items:
            remaining = deadline - time.monotonic()
            try:
                if not out and remaining > 0:
                    out.append(self._q.get(timeout=remaining))
                else:
                    out.append(self._q.get_nowait())
            except queue.Empty:
                break
        return out

    # -- supervision -------------------------------------------------------
    @property
    def alive_count(self) -> int:
        return sum(1 for a in self._actors.values() if a.is_alive())

    @property
    def failed_slots(self) -> set:
        return set(self._failed)

    def restarts_total(self) -> int:
        return sum(self._restarts.values())

    def poll(self) -> list:
        """One supervision pass: detect dead/hung actors, schedule and
        perform backoff-delayed restarts.  Returns the list of event
        dicts emitted this pass (also logged to the RunLog)."""
        if self._stopped:
            return []
        now = time.monotonic()
        events = []
        for slot in range(self.n_actors):
            if slot in self._failed or slot in self._pending:
                continue
            a = self._actors.get(slot)
            if a is None:
                continue
            dead = not a.is_alive()
            hung = (not dead and not a.stop_event.is_set()
                    and now - a.last_beat > self.heartbeat_timeout)
            if not dead and not hung:
                continue
            if hung:
                # can't kill a python thread: abandon it (daemon) and
                # make sure it exits if it ever wakes up
                a.stop_event.set()
            reason = (f"error:{a.error!r}" if dead and a.error is not None
                      else ("exited" if dead else "hung"))
            n = self._restarts[slot]
            if n >= self.max_restarts:
                self._failed.add(slot)
                ev = {"event": "actor_failed", "actor": slot,
                      "reason": reason, "restarts": n}
                events.append(ev)
                self._log(**ev)
                continue
            delay = self.backoff.delay(n, self._rng)
            # the replacement skips the iteration that killed its
            # predecessor (poison-pill protection)
            self._pending[slot] = (now + delay, a.iteration + 1)
            ev = {"event": "actor_down", "actor": slot, "reason": reason,
                  "iteration": a.iteration, "restart_in_s": round(delay, 3),
                  "attempt": n + 1}
            events.append(ev)
            self._log(**ev)
        for slot in list(self._pending):
            due, it = self._pending[slot]
            if now >= due:
                del self._pending[slot]
                self._restarts[slot] += 1
                self._spawn(slot, start_iteration=it)
                ev = {"event": "actor_restart", "actor": slot,
                      "iteration": it, "attempt": self._restarts[slot]}
                events.append(ev)
                self._log(**ev)
                self._counter("actor_restarts")
        if events:
            self._gauge()
        return events

    def wait_pending(self, timeout: float = 30.0) -> None:
        """Block until no restart is pending (tests; bounded)."""
        deadline = time.monotonic() + timeout
        while self._pending and time.monotonic() < deadline:
            time.sleep(0.01)
            self.poll()

    # -- telemetry ---------------------------------------------------------
    def _log(self, event: str = "actor_event", **fields) -> None:
        try:
            from smartcal_tpu import obs
            rl = obs.active()
            if rl is not None:
                rl.log(fields.pop("event", event), **fields)
        except Exception:
            pass

    def _gauge(self) -> None:
        try:
            from smartcal_tpu import obs
            obs.gauge_set("actors_alive", self.alive_count)
        except Exception:
            pass

    def _counter(self, name: str) -> None:
        try:
            from smartcal_tpu import obs
            obs.counter_add(name)
        except Exception:
            pass
