"""Heartbeat-supervised actor fleet (threads OR processes) for the
parallel learners.

The SPMD learners (:mod:`smartcal_tpu.parallel.learner`,
``demix_learner``) fuse actors into one jitted program — nothing there
can die independently.  The supervised mode instead runs each actor as
an independent host execution unit (the IMPACT-shaped split: actors
roll out against a possibly-stale weights snapshot, the learner
consumes whatever arrives) and THIS module is the part that survives
faults:

* each actor thread beats a heartbeat before every rollout and pushes
  its result onto the shared queue;
* :meth:`Fleet.poll` (called from the learner loop) detects dead
  threads (work_fn raised — e.g. an injected
  :class:`~smartcal_tpu.runtime.faults.FaultInjected`) and HUNG threads
  (heartbeat older than ``heartbeat_timeout``; the thread is abandoned
  as a daemon and a replacement spawned);
* restarts happen after an exponential backoff with jitter
  (:class:`~smartcal_tpu.runtime.backoff.BackoffPolicy`), at most
  ``max_restarts`` times per actor slot; a replacement resumes at the
  iteration AFTER the one that killed its predecessor, so a
  deterministic poison-pill iteration cannot crash-loop the slot;
* the learner keeps training from whatever subset of the fleet is
  alive; ``Fleet.stop(join=True)`` is the one call a tripping watchdog
  needs to leave no actor running against a dead learner.

Two actor backends share the whole supervision contract
(``actor_mode``):

* ``"thread"`` (default, the PR 10 shape, bit-identical to it): each
  slot is a :class:`_Actor` host thread calling ``work_fn`` in-process
  and pushing onto ONE bounded global ingest queue;
* ``"process"``: each slot is a :class:`_ProcessActor` — a spawned
  worker process (``multiprocessing`` spawn context, so jax state is
  never forked) running :func:`smartcal_tpu.runtime.ipc.worker_main`
  with a picklable ``worker_spec`` factory, exchanging versioned
  transition batches / weight snapshots / heartbeats over a framed,
  CRC-checked duplex pipe, plus a parent-side pump thread that relays
  worker frames into the slot's OWN bounded ingest shard (per-slot
  queues instead of one global queue — ``collect`` drains them
  round-robin so a single hot slot cannot starve the rest, and
  ``queue_depths()`` exposes per-slot depth for the obs gauges).  A
  frame that fails validation (a worker died mid-send) is DROPPED and
  logged (``ipc_corrupt_payload``), never handed to the learner.  A
  ``hosts > 1`` fleet tags contiguous slot blocks with simulated host
  ids (``multihost.attach_simulated`` in each worker) — the
  single-machine rehearsal of a real multi-host fleet.

Telemetry: ``actor_down`` / ``actor_restart`` / ``actor_failed`` /
``ipc_corrupt_payload`` RunLog events, ``actors_alive`` gauges and
``actor_restarts`` / ``ipc_corrupt_payloads`` counters via the
existing obs registry.
"""

from __future__ import annotations

import queue
import sys
import threading
import time
from typing import Any, Callable, Optional

from . import ipc
from .backoff import BackoffPolicy
from .faults import FaultInjected  # noqa: F401  (re-export for callers)

# work_fn(actor_id, iteration, weights) -> host result pushed to the queue
WorkFn = Callable[[int, int, Any], Any]


class _Actor(threading.Thread):
    def __init__(self, fleet: "Fleet", actor_id: int, start_iteration: int):
        super().__init__(name=f"{fleet.name}-{actor_id}", daemon=True)
        self.fleet = fleet
        self.actor_id = actor_id
        self.iteration = start_iteration
        self.last_beat = time.monotonic()
        self.stop_event = threading.Event()
        self.error: Optional[BaseException] = None

    def run(self):
        f = self.fleet
        while not self.stop_event.is_set():
            self.last_beat = time.monotonic()
            weights, version = f.get_weights()
            try:
                out = f.work_fn(self.actor_id, self.iteration, weights)
            except BaseException as e:   # noqa: BLE001 — death IS the signal
                self.error = e
                return
            # bounded ingest queue: when the learner falls behind, the
            # put blocks (back-pressure — actors must not free-run
            # arbitrarily far ahead of the policy they feed).  Re-beat
            # the heartbeat while waiting so back-pressure is never
            # mistaken for a hung rollout.
            item = (self.actor_id, self.iteration, version, out)
            while not self.stop_event.is_set():
                try:
                    # short tick: re-beat the heartbeat and re-check the
                    # stop flag while waiting, so shutdown never stalls
                    # behind a full queue
                    f._q.put(item, timeout=0.2)
                    break
                except queue.Full:
                    self.last_beat = time.monotonic()
            self.iteration += 1


def _to_host(weights: Any) -> Any:
    """Pull device arrays to host before pickling for a worker process.
    Identity when jax was never imported (stdlib-only callers)."""
    jax_mod = sys.modules.get("jax")
    if jax_mod is None:
        return weights
    try:
        return jax_mod.device_get(weights)
    except Exception:
        return weights


class _ProcessActor(threading.Thread):
    """A process-backed actor slot: a spawned worker process plus this
    parent-side pump thread relaying the worker's framed messages into
    the slot's ingest shard.  Duck-types :class:`_Actor`'s supervision
    surface (``iteration`` / ``last_beat`` / ``stop_event`` / ``error``
    / ``is_alive``) so :class:`Fleet` supervises both backends through
    one contract."""

    def __init__(self, fleet: "Fleet", actor_id: int, start_iteration: int):
        super().__init__(name=f"{fleet.name}-{actor_id}-pump", daemon=True)
        self.fleet = fleet
        self.actor_id = actor_id
        self.iteration = start_iteration
        self.last_beat = time.monotonic()
        self.stop_event = threading.Event()
        self.error: Optional[BaseException] = None
        self.proc = None
        self.conn = None
        # latest-wins outbox: the learner's publish() NEVER blocks on
        # the pipe (a full pipe toward a busy worker must not stall the
        # learner — that closes a learner->worker->pump->learner
        # deadlock cycle); a dedicated sender thread drains it
        self._outbox: Optional[bytes] = None
        self._outbox_lock = threading.Lock()
        self._outbox_ev = threading.Event()
        self._sender: Optional[threading.Thread] = None

    def _launch(self) -> None:
        """Spawn the worker process + duplex channel (spawn context:
        never fork a process that may hold jax runtime threads)."""
        import multiprocessing as mp

        f = self.fleet
        ctx = mp.get_context("spawn")
        self.conn, child = ctx.Pipe(duplex=True)
        self.proc = ctx.Process(
            target=ipc.worker_main,
            args=(child, self.actor_id, self.iteration,
                  f.worker_spec["factory"],
                  f.worker_spec.get("kwargs", {}),
                  f.slot_host(self.actor_id), f.hosts,
                  f.worker_spec.get("platform", "cpu")),
            name=f"{f.name}-{self.actor_id}", daemon=True)
        self.proc.start()
        child.close()                    # parent keeps one end only
        # stage the current snapshot for the fresh worker so a
        # restarted slot never rolls out against nothing (the sender
        # thread ships it once the worker starts draining)
        weights, version = f.get_weights()
        self.publish(ipc.frame_payload(("weights", version,
                                        _to_host(weights))))

    def start(self) -> None:
        self._launch()
        self._sender = threading.Thread(
            target=self._send_loop,
            name=f"{self.fleet.name}-{self.actor_id}-send", daemon=True)
        self._sender.start()
        super().start()

    def publish(self, blob: bytes) -> None:
        """Stage an already-framed message for the worker — latest
        wins, never blocks (only the NEWEST weights snapshot matters)."""
        with self._outbox_lock:
            self._outbox = blob
        self._outbox_ev.set()

    def _take_outbox(self) -> Optional[bytes]:
        with self._outbox_lock:
            blob, self._outbox = self._outbox, None
            self._outbox_ev.clear()
        return blob

    def _send_loop(self):
        """Sole WRITER of the parent-side connection (the pump is the
        sole reader, so the duplex pipe never sees two concurrent users
        of one direction)."""
        while not self.stop_event.is_set():
            if not self._outbox_ev.wait(timeout=0.2):
                continue
            blob = self._take_outbox()
            if blob is None:
                continue
            try:
                ipc.send_blob(self.conn, blob)
            except (OSError, BrokenPipeError, ValueError):
                return
        blob = self._take_outbox()       # final frame (the stop message)
        if blob is not None:
            try:
                ipc.send_blob(self.conn, blob)
            except (OSError, BrokenPipeError, ValueError):
                pass

    def request_stop(self) -> None:
        self.publish(ipc.frame_payload(("stop",)))
        self.stop_event.set()

    def hard_kill(self) -> None:
        """Unlike a hung thread, a hung PROCESS can be killed."""
        try:
            if self.proc is not None and self.proc.is_alive():
                self.proc.terminate()
        except Exception:
            pass

    def finalize(self, timeout: float = 2.0) -> None:
        """Reap the worker process after the pump thread is done."""
        if self.proc is None:
            return
        try:
            self.proc.join(timeout=timeout)
            if self.proc.is_alive():
                self.proc.terminate()
                self.proc.join(timeout=1.0)
        except Exception:
            pass

    def run(self):
        f = self.fleet
        shard = f.shard_queue(self.actor_id)
        while not self.stop_event.is_set():
            try:
                if not self.conn.poll(0.2):
                    if self.proc is not None and not self.proc.is_alive() \
                            and not self.conn.poll(0):
                        # silently-dead worker (SIGKILL'd mid-rollout):
                        # nothing buffered, channel will never speak —
                        # the last beat frame named the killing iteration
                        if self.error is None:
                            self.error = RuntimeError(
                                f"actor process exited (code "
                                f"{self.proc.exitcode})")
                        return
                    continue
                msg = ipc.recv_msg(self.conn)
            except ipc.CorruptPayloadError as e:
                # a worker died mid-send (or shipped garbage): drop the
                # one broken frame, log it, keep pumping — the learner
                # iteration is never poisoned by a truncated payload
                f._log("ipc_corrupt_payload", actor=self.actor_id,
                       error=repr(e))
                f._counter("ipc_corrupt_payloads")
                continue
            except (EOFError, OSError):
                if not self.stop_event.is_set() and self.error is None:
                    code = (self.proc.exitcode if self.proc is not None
                            else None)
                    self.error = RuntimeError(
                        f"actor process channel closed (exit code {code})")
                return
            kind = msg[0]
            if kind == "beat":
                self.iteration = int(msg[1])
                self.last_beat = time.monotonic()
            elif kind == "result":
                it, version, out = int(msg[1]), int(msg[2]), msg[3]
                self.last_beat = time.monotonic()
                item = (self.actor_id, it, version, out)
                while not self.stop_event.is_set():
                    try:
                        # bounded shard: back-pressure blocks HERE (and
                        # transitively the worker, once the pipe buffer
                        # fills); re-beat so back-pressure is never
                        # mistaken for a hung worker
                        shard.put(item, timeout=0.2)
                        break
                    except queue.Full:
                        self.last_beat = time.monotonic()
                self.iteration = it + 1
            elif kind == "error":
                self.iteration = int(msg[1])
                self.error = RuntimeError(msg[2])
                return

    def join(self, timeout: Optional[float] = None) -> None:
        if self.ident is not None:       # pump thread actually started
            super().join(timeout=timeout)
        if not self.is_alive():
            self.finalize()


class RestartTracker:
    """Per-slot backoff-restart accounting, extracted from
    :meth:`Fleet.poll` so the serving replica fleet
    (:mod:`smartcal_tpu.serve.fleet`) shares the actor semantics
    verbatim instead of reimplementing them:

    * :meth:`note_down` schedules a backoff-delayed respawn for a slot
      (carrying an opaque resume ``token`` — the actor fleet's next
      iteration, the serve fleet's replica spec) or, when the slot has
      exhausted ``max_restarts``, moves it to :attr:`failed`
      permanently;
    * :meth:`due` pops the respawns whose backoff has elapsed,
      incrementing each slot's restart count.

    Time is always an explicit ``now`` (monotonic seconds) so callers
    with an injected clock — the router's autoscale tests — drive the
    schedule deterministically.  NOT thread-safe by itself: callers
    serialize access (Fleet polls from one loop; the router holds its
    supervision to one thread)."""

    def __init__(self, max_restarts: int, backoff: BackoffPolicy,
                 rng=None):
        import random

        self.max_restarts = int(max_restarts)
        self.backoff = backoff
        self._rng = rng if rng is not None else random.Random(0)
        self.pending: dict = {}        # slot -> (due_monotonic, token)
        self.failed: set = set()       # slots past max_restarts
        self.restarts: dict = {}       # slot -> completed restart count

    def tracked(self, slot) -> bool:
        """True while the slot is awaiting respawn or permanently down
        (a supervision pass must not re-handle it)."""
        return slot in self.pending or slot in self.failed

    def attempts(self, slot) -> int:
        return int(self.restarts.get(slot, 0))

    def restarts_total(self) -> int:
        return sum(self.restarts.values())

    def note_down(self, slot, token=None,
                  now: Optional[float] = None) -> Optional[float]:
        """Record a down slot.  Returns the backoff delay (seconds)
        until its scheduled respawn, or None when the slot just
        exhausted ``max_restarts`` and joined :attr:`failed`."""
        now = time.monotonic() if now is None else now
        n = self.attempts(slot)
        if n >= self.max_restarts:
            self.failed.add(slot)
            return None
        delay = self.backoff.delay(n, self._rng)
        self.pending[slot] = (now + delay, token)
        return delay

    def due(self, now: Optional[float] = None) -> list:
        """Pop and return ``[(slot, token), ...]`` whose backoff has
        elapsed, counting each as one completed restart."""
        now = time.monotonic() if now is None else now
        out = []
        for slot in list(self.pending):
            due_t, token = self.pending[slot]
            if now >= due_t:
                del self.pending[slot]
                self.restarts[slot] = self.attempts(slot) + 1
                out.append((slot, token))
        return out


class Fleet:
    """A supervised set of ``n_actors`` worker threads or processes
    (see module doc).

    ``actor_mode="process"`` requires ``worker_spec`` — a picklable
    ``{"factory": "module:callable", "kwargs": {...}}`` description
    that each spawned worker resolves into its work function (closures
    cannot cross a process boundary); ``work_fn`` is then unused in the
    workers and may be None.  An optional ``worker_spec["platform"]``
    pins each worker's jax platform (default ``"cpu"`` — a worker must
    never contend for the single-client accelerator the learner holds;
    ``None`` inherits the environment).  ``hosts > 1`` splits the slots
    into contiguous simulated-host blocks (``slot_host``)."""

    def __init__(self, n_actors: int, work_fn: Optional[WorkFn], *,
                 name: str = "actor", heartbeat_timeout: float = 60.0,
                 max_restarts: int = 3,
                 backoff: Optional[BackoffPolicy] = None, seed: int = 0,
                 queue_depth: int = 2, actor_mode: str = "thread",
                 worker_spec: Optional[dict] = None, hosts: int = 1):
        if actor_mode not in ("thread", "process"):
            raise ValueError(f"actor_mode must be 'thread' or 'process', "
                             f"got {actor_mode!r}")
        if actor_mode == "process" and not worker_spec:
            raise ValueError("actor_mode='process' requires worker_spec "
                             "({'factory': 'module:callable', 'kwargs': "
                             "{...}}) — closures cannot cross a process "
                             "boundary")
        if actor_mode == "thread" and hosts != 1:
            raise ValueError("multi-host (simulated) fleets require "
                             "actor_mode='process'")
        self.n_actors = int(n_actors)
        self.work_fn = work_fn
        self.name = name
        self.actor_mode = actor_mode
        self.worker_spec = worker_spec
        self.hosts = max(1, int(hosts))
        self.heartbeat_timeout = float(heartbeat_timeout)
        self.max_restarts = int(max_restarts)
        self.backoff = backoff or BackoffPolicy(base_s=0.25, factor=2.0,
                                                max_s=30.0, jitter=0.25)
        self._seed = seed
        if actor_mode == "process":
            # per-slot ingest shards: each slot owns a bounded queue, so
            # one hot producer cannot occupy the whole ingest budget and
            # per-slot depth is observable (the obs gauges); the shard
            # directory and slot->shard map are built once here and
            # never rewritten (graftlint SHARED_FIELD_SPECS covers them)
            self._q = None
            self._shard_qs = [queue.Queue(maxsize=max(1, int(queue_depth)))
                              for _ in range(self.n_actors)]
            self._slot_shard = {i: i for i in range(self.n_actors)}
        else:
            # bounded to queue_depth results per actor slot: actors
            # block (with heartbeat) when the learner lags — staleness
            # stays bounded by the queue depth plus the publication
            # cadence instead of growing with every learner hiccup
            self._q = queue.Queue(
                maxsize=max(1, int(queue_depth)) * self.n_actors)
            self._shard_qs = None
            self._slot_shard = None
        self._rr = 0                         # collect()'s round-robin cursor
        self._weights: Any = None
        self._version = 0
        self._wlock = threading.Lock()
        self._actors: dict = {}              # slot -> _Actor (current)
        self._stopped = False
        import random
        self._rng = random.Random(seed)
        # restart schedule + failed set + counts live in the tracker
        # (shared with the serving replica fleet); the pending token is
        # the resume iteration
        self._tracker = RestartTracker(self.max_restarts, self.backoff,
                                       rng=self._rng)

    # -- sharded ingest ----------------------------------------------------
    def slot_host(self, slot: int) -> int:
        """Simulated host id of ``slot`` — contiguous blocks, so a
        2-host 8-actor fleet is slots 0-3 on host 0, 4-7 on host 1."""
        return (slot * self.hosts) // self.n_actors

    def shard_queue(self, slot: int) -> "queue.Queue":
        """The bounded ingest queue slot ``slot`` produces into (the
        global queue in thread mode)."""
        if self._shard_qs is None:
            return self._q
        return self._shard_qs[self._slot_shard[slot]]

    def queue_depths(self) -> dict:
        """Current ingest depth per shard plus the aggregate — the
        single-slow-shard visibility the global-queue gauge lacked.
        Thread mode reports only the aggregate (one global queue)."""
        if self._shard_qs is None:
            return {"aggregate": self._q.qsize()}
        depths = {i: q.qsize() for i, q in enumerate(self._shard_qs)}
        return {"aggregate": sum(depths.values()), "per_slot": depths}

    # -- weights snapshot --------------------------------------------------
    def set_weights(self, weights: Any, version: Optional[int] = None
                    ) -> int:
        """Publish a fresh snapshot.  ``version`` pins the snapshot's
        version explicitly (the async learner stamps its own
        learner-round counter so staleness-in-versions is measured in
        learner rounds, and a resumed run continues its predecessor's
        version stream); default keeps the auto-increment."""
        with self._wlock:
            self._weights = weights
            if version is not None:
                self._version = int(version)
            else:
                self._version += 1
            v = self._version
        if self.actor_mode == "process":
            # serialize ONCE, fan the framed snapshot out to every live
            # worker (a dead worker's publish is a no-op; its
            # replacement receives the current snapshot at spawn)
            blob = ipc.frame_payload(("weights", v, _to_host(weights)))
            for a in self._actors.values():
                if isinstance(a, _ProcessActor) and a.is_alive():
                    a.publish(blob)
        return v

    def get_weights(self):
        with self._wlock:
            return self._weights, self._version

    @property
    def version(self) -> int:
        with self._wlock:
            return self._version

    # -- lifecycle ---------------------------------------------------------
    def start(self, weights: Any, start_iterations: Optional[dict] = None,
              version: Optional[int] = None) -> None:
        """Spawn every actor slot.  ``start_iterations`` (slot -> first
        rollout iteration; default 0) lets a resumed run continue each
        slot's deterministic key stream where its predecessor stopped —
        the fleet half of the checkpoint payload (``slot_iterations``)."""
        self.set_weights(weights, version=version)
        start_iterations = start_iterations or {}
        for i in range(self.n_actors):
            self._spawn(i, start_iteration=int(start_iterations.get(i, 0)))
        self._gauge()

    def slot_iterations(self) -> dict:
        """slot -> the next rollout iteration that slot would run — what
        a checkpoint must record so a resumed fleet continues every
        per-(actor, iteration) key stream instead of replaying it.
        Pending restarts report their scheduled resume iteration; a DEAD
        actor reports the iteration AFTER the one that killed it (the
        same poison-pill skip the live restart path applies — resuming
        at the killing iteration would crash-loop the slot on every
        resume)."""
        out = {}
        for slot in range(self.n_actors):
            if slot in self._tracker.pending:
                out[slot] = int(self._tracker.pending[slot][1])
            elif slot in self._actors:
                a = self._actors[slot]
                it = int(a.iteration)
                if not a.is_alive() and a.error is not None:
                    it += 1
                out[slot] = it
            else:
                out[slot] = 0
        return out

    def _spawn(self, slot: int, start_iteration: int) -> None:
        cls = _ProcessActor if self.actor_mode == "process" else _Actor
        a = cls(self, slot, start_iteration)
        self._actors[slot] = a
        a.start()

    def stop(self, join: bool = True, timeout: float = 10.0) -> int:
        """Signal every actor to stop; with ``join`` wait for each thread
        (hung threads are daemons and are abandoned after ``timeout``).
        Returns the number of threads that actually joined.  Idempotent —
        a second call (trip path, then the driver's finally) is a no-op."""
        if self._stopped:
            return 0
        self._stopped = True
        for a in self._actors.values():
            if isinstance(a, _ProcessActor):
                a.request_stop()
            else:
                a.stop_event.set()
        joined = 0
        if join:
            deadline = time.monotonic() + timeout
            for a in self._actors.values():
                a.join(timeout=max(0.0, deadline - time.monotonic()))
                joined += 0 if a.is_alive() else 1
        self._log("actors_stopped", joined=joined,
                  total=len(self._actors))
        self._gauge()
        return joined

    # -- collection --------------------------------------------------------
    def collect(self, max_items: int, timeout: float) -> list:
        """Up to ``max_items`` queued results, waiting at most ``timeout``
        seconds TOTAL for the first one (later ones are taken only if
        already queued).  Returns [(actor_id, iteration, weights_version,
        result), ...] — possibly empty when the whole fleet is down.

        Process mode drains the per-slot ingest shards round-robin
        (rotating the starting shard every call) so one hot slot can
        never monopolize a collection round while another shard backs
        up unseen."""
        deadline = time.monotonic() + timeout
        if self._shard_qs is None:
            out = []
            while len(out) < max_items:
                remaining = deadline - time.monotonic()
                try:
                    if not out and remaining > 0:
                        out.append(self._q.get(timeout=remaining))
                    else:
                        out.append(self._q.get_nowait())
                except queue.Empty:
                    break
            return out
        out: list = []
        n = len(self._shard_qs)
        start = self._rr
        self._rr = (self._rr + 1) % n
        while len(out) < max_items:
            got = False
            for k in range(n):
                if len(out) >= max_items:
                    break
                try:
                    out.append(
                        self._shard_qs[(start + k) % n].get_nowait())
                    got = True
                except queue.Empty:
                    continue
            if got:
                continue
            if out or time.monotonic() >= deadline:
                break
            time.sleep(0.01)
        return out

    # -- supervision -------------------------------------------------------
    @property
    def alive_count(self) -> int:
        return sum(1 for a in self._actors.values() if a.is_alive())

    @property
    def failed_slots(self) -> set:
        return set(self._tracker.failed)

    def restarts_total(self) -> int:
        return self._tracker.restarts_total()

    def poll(self) -> list:
        """One supervision pass: detect dead/hung actors, schedule and
        perform backoff-delayed restarts.  Returns the list of event
        dicts emitted this pass (also logged to the RunLog)."""
        if self._stopped:
            return []
        now = time.monotonic()
        events = []
        for slot in range(self.n_actors):
            if self._tracker.tracked(slot):
                continue
            a = self._actors.get(slot)
            if a is None:
                continue
            dead = not a.is_alive()
            hung = (not dead and not a.stop_event.is_set()
                    and now - a.last_beat > self.heartbeat_timeout)
            if not dead and not hung:
                continue
            if hung:
                # can't kill a python thread: abandon it (daemon) and
                # make sure it exits if it ever wakes up.  A hung
                # PROCESS, unlike a thread, can actually be killed.
                a.stop_event.set()
                if isinstance(a, _ProcessActor):
                    a.hard_kill()
            if isinstance(a, _ProcessActor):
                # reap the dead/killed worker NOW — _spawn() replaces
                # the slot entry, and a slot past max_restarts never
                # respawns, so without this the zombie (and its pipe
                # fds) would linger until interpreter exit
                a.finalize(timeout=1.0)
            reason = (f"error:{a.error!r}" if dead and a.error is not None
                      else ("exited" if dead else "hung"))
            n = self._tracker.attempts(slot)
            # the replacement skips the iteration that killed its
            # predecessor (poison-pill protection)
            delay = self._tracker.note_down(slot, token=a.iteration + 1,
                                            now=now)
            if delay is None:
                ev = {"event": "actor_failed", "actor": slot,
                      "reason": reason, "restarts": n}
                # a slot past max_restarts is this fleet's circuit
                # opening: dump the parent's flight-recorder ring (the
                # last events before the fleet gave up on the slot)
                try:
                    from smartcal_tpu import obs
                    obs.flush_flight_recorder(
                        "circuit_open", {"actor": slot, "reason": reason})
                except Exception:
                    pass
            else:
                ev = {"event": "actor_down", "actor": slot,
                      "reason": reason, "iteration": a.iteration,
                      "restart_in_s": round(delay, 3), "attempt": n + 1}
            events.append(ev)
            self._log(**ev)
        for slot, it in self._tracker.due(now):
            self._spawn(slot, start_iteration=int(it))
            ev = {"event": "actor_restart", "actor": slot,
                  "iteration": int(it),
                  "attempt": self._tracker.attempts(slot)}
            events.append(ev)
            self._log(**ev)
            self._counter("actor_restarts")
        if events:
            self._gauge()
        return events

    def wait_pending(self, timeout: float = 30.0) -> None:
        """Block until no restart is pending (tests; bounded)."""
        deadline = time.monotonic() + timeout
        while self._tracker.pending and time.monotonic() < deadline:
            time.sleep(0.01)
            self.poll()

    # -- telemetry ---------------------------------------------------------
    def _log(self, event: str = "actor_event", **fields) -> None:
        try:
            from smartcal_tpu import obs
            rl = obs.active()
            if rl is not None:
                rl.log(fields.pop("event", event), **fields)
        except Exception:
            pass

    def _gauge(self) -> None:
        try:
            from smartcal_tpu import obs
            obs.gauge_set("actors_alive", self.alive_count)
        except Exception:
            pass

    def _counter(self, name: str) -> None:
        try:
            from smartcal_tpu import obs
            obs.counter_add(name)
        except Exception:
            pass
