"""Deterministic fault injection: the testing harness for the recovery
paths.

A single process-wide :class:`FaultPlan` (installed programmatically or
via the ``SMARTCAL_FAULTS`` env var, a JSON object) arms up to three
fault sites, each keyed on an exact deterministic index so injected
runs are reproducible and a post-recovery retry does NOT re-fire:

* ``nan_field``/``nan_step`` — overwrite the named field of the
  per-update diagnostics dict with NaN at global update ``nan_step``
  (the watchdog's input; this is how the rollback-and-retry path is
  exercised end-to-end on CPU without poisoning real device state).
* ``kill_actor``/``kill_at`` — raise :class:`FaultInjected` inside
  actor ``kill_actor``'s work function at rollout iteration
  ``kill_at`` (the supervisor must detect the death and restart; the
  replacement resumes AFTER the poisoned iteration, so a deterministic
  kill cannot crash-loop the fleet).
* ``delay_stage``/``delay_at``/``delay_s``/``delay_span`` — sleep
  ``delay_s`` seconds inside the named stage at every index in
  ``[delay_at, delay_at + delay_span)`` (default span 1, the original
  single-shot).  A span > 1 makes the slowdown SUSTAINED — what the
  SLO burn-rate detector needs to see before it may fire (a one-batch
  blip must not trip a multi-window alarm).
* ``perturb_stage``/``perturb_at``/``perturb_rel``/``perturb_span`` —
  multiply the scalar passed through :func:`maybe_perturb` at the
  named stage by ``(1 + perturb_rel)`` for every index in
  ``[perturb_at, perturb_at + perturb_span)``.  This is the NUMERIC
  twin of the delay hook: the regression radar (tools/perf_gate.py)
  and the serving numerics sentinel route their measured values
  through it, so an out-of-band numeric drift can be rehearsed
  end-to-end without editing a kernel.

Each firing is recorded once as a ``fault_injected`` RunLog event (when
a run is recording).  With no plan installed every hook is one ``None``
check.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Optional


class FaultInjected(RuntimeError):
    """Raised by an injected actor kill (see module doc)."""


@dataclasses.dataclass
class FaultPlan:
    nan_field: Optional[str] = None
    nan_step: Optional[int] = None
    kill_actor: Optional[int] = None
    kill_at: Optional[int] = None
    delay_stage: Optional[str] = None
    delay_at: Optional[int] = None
    delay_s: float = 0.0
    delay_span: int = 1
    perturb_stage: Optional[str] = None
    perturb_at: Optional[int] = None
    perturb_rel: float = 0.0
    perturb_span: int = 1


_plan: Optional[FaultPlan] = None
_lock = threading.Lock()
_fired: set = set()


def install(plan: Optional[FaultPlan]) -> None:
    """Install ``plan`` process-wide (None clears)."""
    global _plan
    with _lock:
        _plan = plan
        _fired.clear()


def clear() -> None:
    install(None)


def active() -> Optional[FaultPlan]:
    return _plan


def plan_from_env(env=None) -> Optional[FaultPlan]:
    """Parse ``SMARTCAL_FAULTS`` (JSON with FaultPlan field names) —
    lets the smoke scripts inject faults into unmodified driver CLIs."""
    env = os.environ if env is None else env
    raw = env.get("SMARTCAL_FAULTS", "").strip()
    if not raw:
        return None
    try:
        d = json.loads(raw)
        fields = {f.name for f in dataclasses.fields(FaultPlan)}
        return FaultPlan(**{k: v for k, v in d.items() if k in fields})
    except (ValueError, TypeError) as e:
        import sys
        sys.stderr.write(f"SMARTCAL_FAULTS unparseable ({e!r}); "
                         "ignoring\n")
        return None


def install_from_env() -> Optional[FaultPlan]:
    plan = plan_from_env()
    if plan is not None:
        install(plan)
    return plan


def _record(site: str, **fields) -> None:
    key = (site, tuple(sorted(fields.items())))
    with _lock:
        if key in _fired:
            return
        _fired.add(key)
    try:
        from smartcal_tpu import obs
        rl = obs.active()
        if rl is not None:
            rl.log("fault_injected", site=site, **fields)
    except Exception:
        pass


def mutate_diag(step_diag: dict, step: int) -> dict:
    """Apply the NaN fault to one per-update diagnostics dict (a copy);
    identity when the plan doesn't target this step."""
    p = _plan
    if p is None or p.nan_field is None or p.nan_step != step:
        return step_diag
    out = dict(step_diag)
    out[p.nan_field] = float("nan")
    _record("diag_nan", field=p.nan_field, step=step)
    return out


def should_kill_actor(actor_id: int, iteration: int) -> bool:
    p = _plan
    if p is None or p.kill_actor is None:
        return False
    if p.kill_actor == actor_id and p.kill_at == iteration:
        _record("actor_kill", actor=actor_id, iteration=iteration)
        return True
    return False


def maybe_delay(stage: str, index: int) -> float:
    """Sleep the planned delay when (stage, index) falls inside the
    plan's delay window; returns seconds slept.  Each firing index
    records its own ``fault_injected`` event."""
    p = _plan
    if (p is None or p.delay_stage != stage or p.delay_at is None
            or p.delay_s <= 0.0):
        return 0.0
    if not p.delay_at <= index < p.delay_at + max(1, int(p.delay_span)):
        return 0.0
    _record("delay", stage=stage, index=index, delay_s=p.delay_s)
    time.sleep(p.delay_s)
    return p.delay_s


def maybe_perturb(stage: str, index: int, value: float) -> float:
    """Multiply ``value`` by ``(1 + perturb_rel)`` when (stage, index)
    falls inside the plan's perturb window; identity otherwise.  Each
    firing index records its own ``fault_injected`` event."""
    p = _plan
    if (p is None or p.perturb_stage != stage or p.perturb_at is None
            or p.perturb_rel == 0.0):
        return value
    if not p.perturb_at <= index < p.perturb_at + max(1, int(p.perturb_span)):
        return value
    _record("perturb", stage=stage, index=index, rel=p.perturb_rel)
    return value * (1.0 + p.perturb_rel)
