"""Versioned, checksummed run checkpoints with atomic publication.

Layout under a run's checkpoint root::

    <root>/
      ckpt_000040/
        payload.pkl      # ONE pickle: the whole host-side run state
        meta.json        # {"step", "sha256", "payload_bytes", ...}
      ckpt_000080/
      LATEST             # json {"step", "dir", "sha256"}

Publication protocol (all failure windows leave a loadable store):

1. the payload pickles into a hidden temp dir next to the target;
2. ``meta.json`` (with the payload's sha256) lands inside it;
3. ONE ``os.replace`` renames the temp dir to ``ckpt_<step>`` — a
   checkpoint either exists completely or not at all;
4. ``LATEST`` updates via the atomic text write;
5. retention prunes to the newest K (never the one just written).

``load_latest`` validates the sha256 before unpickling and falls back —
corrupt/missing LATEST degrades to a directory scan, a corrupt newest
checkpoint degrades to the next older one — so a mid-write kill costs
at most one checkpoint interval, never the run.

The payload is an ordinary host dict; :func:`pack_replay` /
:func:`unpack_replay` give replay buffers (both the HBM pytree and the
native C++ sum-tree buffer) a uniform in-payload form that round-trips
PER priorities exactly.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import tempfile
import time
from typing import Any, Optional, Tuple

from .atomic import atomic_pickle, atomic_write_text, sha256_file

CKPT_PREFIX = "ckpt_"
LATEST = "LATEST"
PAYLOAD = "payload.pkl"
META = "meta.json"
_DIR_RE = re.compile(r"^ckpt_(\d+)$")


def _ckpt_dirname(step: int) -> str:
    return f"{CKPT_PREFIX}{int(step):06d}"


def list_checkpoints(root: str) -> "list[Tuple[int, str]]":
    """[(step, absolute dir)] sorted ascending by step."""
    if not os.path.isdir(root):
        return []
    out = []
    for name in os.listdir(root):
        m = _DIR_RE.match(name)
        if m and os.path.isdir(os.path.join(root, name)):
            out.append((int(m.group(1)), os.path.join(root, name)))
    return sorted(out)


def save_checkpoint(root: str, step: int, payload: dict,
                    keep: int = 3, fsync: bool = True) -> str:
    """Write ``payload`` as ``ckpt_<step>`` (see module doc); returns the
    published directory path.  ``payload`` must already be host data
    (callers ``jax.device_get`` before handing it over)."""
    os.makedirs(root, exist_ok=True)
    final = os.path.join(root, _ckpt_dirname(step))
    tmp = tempfile.mkdtemp(prefix=f".{_ckpt_dirname(step)}.", dir=root)
    try:
        nbytes = atomic_pickle(payload, os.path.join(tmp, PAYLOAD),
                               fsync=fsync)
        sha = sha256_file(os.path.join(tmp, PAYLOAD))
        meta = {"step": int(step), "sha256": sha, "payload_bytes": nbytes,
                "wrote_unix": round(time.time(), 3),
                "fields": sorted(payload) if isinstance(payload, dict)
                else None}
        atomic_write_text(os.path.join(tmp, META), json.dumps(meta),
                          fsync=fsync)
        if os.path.isdir(final):
            # re-checkpointing the same step (a rolled-back run walking
            # past it again): retire the old dir first so the rename
            # can't collide.  LATEST still points at a valid older
            # checkpoint throughout.
            shutil.rmtree(final)
        os.replace(tmp, final)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    atomic_write_text(os.path.join(root, LATEST),
                      json.dumps({"step": int(step),
                                  "dir": _ckpt_dirname(step),
                                  "sha256": sha}), fsync=fsync)
    _prune(root, keep, protect=final)
    _log_event("checkpoint", root=root, step=int(step), bytes=nbytes,
               kept=keep)
    return final


def _prune(root: str, keep: int, protect: str) -> None:
    if keep <= 0:
        return
    entries = list_checkpoints(root)
    for step, path in entries[:-keep]:
        if os.path.abspath(path) != os.path.abspath(protect):
            shutil.rmtree(path, ignore_errors=True)
    # stale hidden temp dirs from killed writers
    for name in os.listdir(root):
        if name.startswith(f".{CKPT_PREFIX}"):
            shutil.rmtree(os.path.join(root, name), ignore_errors=True)


def _validate(path: str) -> bool:
    """True when ``path`` holds a complete, checksum-clean checkpoint."""
    payload, meta = os.path.join(path, PAYLOAD), os.path.join(path, META)
    try:
        with open(meta) as f:
            m = json.load(f)
        return sha256_file(payload) == m.get("sha256")
    except (OSError, ValueError):
        return False


def load_latest(root: str) -> Optional[Tuple[dict, int]]:
    """(payload, step) of the newest VALID checkpoint, or None.

    The LATEST pointer is the fast path; a corrupt pointer or a failed
    checksum falls back to scanning ``ckpt_*`` newest-first.
    """
    import pickle

    candidates = []
    latest = os.path.join(root, LATEST)
    if os.path.exists(latest):
        try:
            with open(latest) as f:
                rec = json.load(f)
            candidates.append((int(rec["step"]),
                               os.path.join(root, rec["dir"])))
        except (OSError, ValueError, KeyError, TypeError):
            pass
    for step, path in reversed(list_checkpoints(root)):
        if (step, path) not in candidates:
            candidates.append((step, path))
    for step, path in candidates:
        if not _validate(path):
            continue
        try:
            with open(os.path.join(path, PAYLOAD), "rb") as f:
                # checksum-validated above + except->older-candidate
                # fallback IS this loader's corruption guard
                return pickle.load(f), step  # graftlint: disable=unguarded-pickle-load -- _validate checksum + newest-to-oldest fallback scan is a stronger guard than safe_pickle_load
        except Exception:
            continue
    return None


def _log_event(event: str, **fields) -> None:
    try:
        from smartcal_tpu import obs
        rl = obs.active()
        if rl is not None:
            rl.log(event, **fields)
    except Exception:
        pass


# ---------------------------------------------------------------------------
# Replay-buffer payload forms (HBM pytree + native sum tree)
# ---------------------------------------------------------------------------

def pack_replay(buf: object) -> dict:
    """Uniform host form of a replay buffer for the checkpoint payload.

    HBM :class:`~smartcal_tpu.rl.replay.ReplayState` pytrees pull to
    host; the native buffer contributes its ``state_dict`` (ring arrays
    + sum-tree leaves/cursor + beta + the sampling RNG state), so PER
    priorities round-trip bit-exactly for BOTH backends.
    """
    import jax

    from smartcal_tpu.rl import replay as rp
    from smartcal_tpu.rl import replay_sharded as rps

    if isinstance(buf, rps.ShardedReplayState):
        return {"kind": "hbm_sharded", "state": jax.device_get(buf)}
    if isinstance(buf, rp.ReplayState):
        return {"kind": "hbm", "state": jax.device_get(buf)}
    if hasattr(buf, "state_dict"):                 # NativePER
        return {"kind": "native", "state": buf.state_dict()}
    raise TypeError(f"unsupported replay buffer {type(buf)!r}")


def unpack_replay(obj: dict) -> object:
    import jax
    import jax.numpy as jnp

    kind = obj.get("kind")
    if kind == "hbm":
        return jax.tree_util.tree_map(jnp.asarray, obj["state"])
    if kind == "hbm_sharded":
        # the NamedTuple type survives device_get/pickle, so the
        # restored tree IS a ShardedReplayState; mesh placement is the
        # resuming learner's business (place_on_mesh)
        from smartcal_tpu.rl import replay_sharded as rps

        return rps.place_on_mesh(
            jax.tree_util.tree_map(jnp.asarray, obj["state"]))
    if kind == "native":
        from smartcal_tpu.rl.replay_native import NativePER

        return NativePER.from_state_dict(obj["state"])
    raise ValueError(f"unknown replay payload kind {kind!r}")


# ---------------------------------------------------------------------------
# Env-state payload forms (sequential key chain + batched lane state)
# ---------------------------------------------------------------------------

def pack_env_state(env: object) -> Optional[dict]:
    """Uniform host form of an env's RNG/episode state for the checkpoint
    payload.

    Batched envs (``BatchedCalibEnv``/``BatchedDemixingEnv``) carry a
    per-lane key ARRAY plus per-lane episode/step counters and expose
    them through ``state_dict()`` — the sequential single-key form
    (``env._key``) cannot represent them, which is why a batched
    ``--resume`` needs this hook to keep the same-seed bit-parity
    guarantee.  Sequential envs fall back to the single-key form;
    stateless envs return None."""
    import jax

    if hasattr(env, "state_dict"):
        return {"kind": "env_state_dict", "state": env.state_dict()}
    if hasattr(env, "_key"):
        return {"kind": "env_key", "key": jax.device_get(env._key)}
    return None


def restore_env_state(env: object, obj: Optional[dict]) -> None:
    """Inverse of :func:`pack_env_state`: no-op on None, but a payload
    whose kind does not match the env (e.g. a batched checkpoint resumed
    into a sequential run, or vice versa) raises ValueError — silently
    continuing with the wrong RNG state would void the same-seed
    bit-parity guarantee the checkpoint exists to keep."""
    import jax.numpy as jnp

    if obj is None or env is None:
        return
    kind = obj.get("kind")
    if kind == "env_state_dict" and hasattr(env, "load_state_dict"):
        env.load_state_dict(obj["state"])
    elif kind == "env_key" and hasattr(env, "_key"):
        env._key = jnp.asarray(obj["key"])
    else:
        raise ValueError(
            f"env payload kind {kind!r} does not match env {type(env)!r}")


class Checkpointer:
    """Bound (root, keep) pair with cadence bookkeeping for a run."""

    def __init__(self, root: str, keep: int = 3, every: int = 0):
        self.root = root
        self.keep = max(1, int(keep))
        self.every = max(0, int(every))
        self.last_step: Optional[int] = None

    def due(self, step: int) -> bool:
        # a rolled-back run re-crossing an already-saved step SHOULD
        # re-save: post-mitigation state differs from the poisoned walk
        return self.every > 0 and step > 0 and step % self.every == 0

    def save(self, step: int, payload: dict) -> str:
        path = save_checkpoint(self.root, step, payload, keep=self.keep)
        self.last_step = int(step)
        return path

    def load_latest(self) -> Optional[Tuple[dict, int]]:
        return load_latest(self.root)
