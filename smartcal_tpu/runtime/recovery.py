"""Watchdog escalation: rollback-and-retry before the graceful halt.

PR 4's divergence watchdog could only trip and kill the driver.  The
:class:`RecoveryManager` turns a trip into a bounded retry loop:

1. load the last GOOD checkpoint (sha-validated; the poisoned episodes
   since it are discarded);
2. hand the driver a :class:`RecoveryAction` carrying the payload plus
   the mitigation the policy prescribes — a learning-rate shrink
   (``lr_scale = lr_shrink ** attempt``, applied by rebuilding the
   jitted update at the scaled config) and/or an exploration reseed
   (fold a fresh constant into the run's key stream so the retry
   explores a different trajectory out of the divergence basin);
3. emit ONE structured ``recovery`` RunLog event per rollback;
4. after ``max_recoveries`` attempts (or with no checkpoint to roll
   back to) return ``None`` — the driver falls through to the existing
   graceful halt.

The manager owns policy + counting only; restoring state and applying
the mitigation stay in the driver, which knows its own pytrees.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from .checkpoint import Checkpointer


@dataclasses.dataclass(frozen=True)
class RecoveryPolicy:
    max_recoveries: int = 0      # 0 = recovery disabled (halt on trip)
    lr_shrink: float = 0.5       # per-attempt LR multiplier (1.0 = off)
    reseed: bool = True          # fold a fresh offset into the key stream


@dataclasses.dataclass
class RecoveryAction:
    payload: dict                # the checkpoint to restore
    step: int                    # its step (episodes completed)
    attempt: int                 # 1-based recovery attempt
    lr_scale: float              # cumulative LR multiplier to apply
    reseed: bool


class RecoveryManager:
    def __init__(self, policy: RecoveryPolicy,
                 ckpt: Optional[Checkpointer]):
        self.policy = policy
        self.ckpt = ckpt
        self.attempts = 0

    @property
    def armed(self) -> bool:
        return self.policy.max_recoveries > 0 and self.ckpt is not None

    def on_trip(self, reason: Optional[str] = None,
                episode: Optional[int] = None) -> Optional[RecoveryAction]:
        """Trip handler; None means halt (budget spent / nothing saved)."""
        if not self.armed or self.attempts >= self.policy.max_recoveries:
            self._log(action="halt", reason=reason, episode=episode,
                      attempt=self.attempts,
                      budget=self.policy.max_recoveries)
            return None
        loaded = self.ckpt.load_latest()
        if loaded is None:
            self._log(action="halt_no_checkpoint", reason=reason,
                      episode=episode, attempt=self.attempts)
            return None
        payload, step = loaded
        self.attempts += 1
        act = RecoveryAction(
            payload=payload, step=step, attempt=self.attempts,
            lr_scale=self.policy.lr_shrink ** self.attempts,
            reseed=self.policy.reseed)
        self._log(action="rollback", reason=reason, episode=episode,
                  rollback_step=step, attempt=self.attempts,
                  budget=self.policy.max_recoveries,
                  lr_scale=act.lr_scale, reseed=act.reseed)
        return act

    def _log(self, **fields) -> None:
        try:
            from smartcal_tpu import obs
            rl = obs.active()
            if rl is not None:
                rl.log("recovery", **fields)
                rl.flush()
        except Exception:
            pass
