"""Mesh-sharded device-resident replay (the cluster form of
:mod:`smartcal_tpu.rl.replay`).

One HBM ring buffer bounds the async fleet long before the hardware
does: every actor's transitions funnel into one device's memory and
every sample reads it.  This module generalizes the PR 10 buffer to a
buffer SHARDED over a mesh axis — the "In-Network Experience Sampling"
direction (arXiv:2110.13506): the store and sample paths themselves
move onto the mesh so no transition or sampled batch ever needs a
single-owner hop.

Layout: all arrays carry a leading shard axis — ``data[field]`` is
``(S, local, ...)``, ``priority`` is ``(S, local)`` — sharded over the
mesh (``place_on_mesh``), with a replicated GLOBAL store counter.  The
global ring is interleaved round-robin across shards: store number
``t`` lands at ring slot ``r = t % size``, which is shard ``r % S``,
local slot ``r // S``.  Consequences:

* **store is shard-local**: a batch scatter decomposes into S
  independent local scatters (each shard takes exactly the rows whose
  ring slot it owns — no cross-shard traffic);
* **ring parity**: slot ``(s, j)`` holds exactly what ring slot
  ``j*S + s`` of the equivalent single buffer holds, so ages, ERE
  weights and fill state match the flat
  :class:`~smartcal_tpu.rl.replay.ReplayState` EXACTLY, and the
  round-robin interleave keeps every shard balanced to within one
  transition;
* **sampling draws per-shard then merges via collectives**: the
  stratified PER draw runs against per-shard local cumsums plus an
  S-scalar shard-total prefix (the only cross-shard reduction on the
  hot path); each shard gathers its own rows and the batch materializes
  as a masked sum over the shard axis — on a real mesh, a psum over
  ICI/DCN, never a host hop;
* **priority update is a shard-local scatter**: every shard writes the
  sampled rows it owns and drops the rest.

Sampling-distribution note: per-transition EXPECTED sample counts under
the stratified draw are ``batch * p_i / total`` — identical to the flat
buffer and the native sum tree — but the stratification ORDER is
shard-concatenated rather than ring-ordered, so individual draws are
not bitwise those of the flat buffer (distribution parity is what
tests/test_sharded_replay.py certifies, against both oracles).

The module mirrors :mod:`~smartcal_tpu.rl.replay`'s function names and
signatures so the agents' fused learn steps dispatch between the two by
buffer type (:func:`smartcal_tpu.rl.replay.backend_for`).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import replay as rp
# the canonical axis-name registry (ISSUE 17): the replay axis is a
# submesh of the composed topology, so learner and sharded episode can
# share one mesh (mesh.py has no package-internal imports — no cycle)
from ..parallel.mesh import (AXIS_REPLAY, MeshFactorizationError,
                             check_axis_divides, largest_divisor)


class ShardedReplayState(NamedTuple):
    """Pytree of the sharded buffer (leading shard axis everywhere)."""

    data: dict                 # field -> (S, local, ...) arrays
    cntr: jnp.ndarray          # () int32 GLOBAL store counter
    priority: jnp.ndarray      # (S, local)
    beta: jnp.ndarray          # () PER beta

    @property
    def n_shards(self) -> int:
        return self.priority.shape[0]

    @property
    def local_size(self) -> int:
        return self.priority.shape[1]

    @property
    def size(self) -> int:
        return self.priority.shape[0] * self.priority.shape[1]

    def health(self) -> dict:
        """Replay-health summary in the flat buffer's vocabulary
        (ring-slot order reconstructed from the interleave) plus the
        per-shard occupancy profile."""
        return replay_health(self)


def replay_init(size: int, spec: dict, n_shards: int) -> ShardedReplayState:
    if n_shards < 1:
        raise ValueError(f"n_shards must be >= 1, got {n_shards}")
    if size % n_shards != 0:
        raise ValueError(
            f"buffer size {size} must be divisible by n_shards "
            f"{n_shards} (the round-robin ring needs equal shards)")
    local = size // n_shards
    data = {k: jnp.zeros((n_shards, local) + tuple(shape), dtype)
            for k, (shape, dtype) in spec.items()}
    return ShardedReplayState(
        data=data,
        cntr=jnp.asarray(0, jnp.int32),
        priority=jnp.zeros((n_shards, local), jnp.float32),
        beta=jnp.asarray(rp.PER_BETA0, jnp.float32),
    )


def shardings(buf: ShardedReplayState, mesh, axis: str = AXIS_REPLAY):
    """The buffer's sharding pytree: leading-axis sharded data +
    priority, replicated counters.  ``mesh`` may be a COMPOSED
    multi-axis mesh (parallel/mesh.compose_mesh) — the buffer shards
    over ``axis`` and replicates over every other axis, which is how
    the learner's replay rides alongside a lane x baseline episode on
    one topology."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    if axis not in mesh.shape:
        raise MeshFactorizationError(
            f"replay shardings: mesh has no axis {axis!r} "
            f"(mesh axes: {tuple(mesh.shape)})")
    shard = NamedSharding(mesh, P(axis))
    repl = NamedSharding(mesh, P())
    return ShardedReplayState(
        data={k: shard for k in buf.data},
        cntr=repl, priority=shard, beta=repl)


def place_on_mesh(buf: ShardedReplayState, mesh=None,
                  axis: str = AXIS_REPLAY):
    """Commit the buffer to the device mesh, shard axis leading.

    Default mesh: the largest divisor of ``n_shards`` that the local
    device count supports, over all devices — so an S=4 buffer on the
    8-device virtual test mesh occupies 4 devices, and on a single-CPU
    host degenerates (gracefully) to one device still carrying the
    sharded LAYOUT the cluster run uses.  (The pre-registry code used
    ``gcd`` here, which silently under-used devices — S=6 on 4 devices
    landed on 2 instead of the documented 3.)

    An EXPLICIT mesh is a contract, not a hint: if its ``axis`` size
    does not divide ``n_shards``, this raises
    :class:`~smartcal_tpu.parallel.mesh.MeshFactorizationError` naming
    the nearest valid size instead of letting XLA fail opaquely (or
    silently mis-sharding the ring).
    """
    if mesh is None:
        from jax.sharding import Mesh

        devs = jax.devices()
        n = largest_divisor(buf.n_shards, len(devs))
        mesh = Mesh(np.asarray(devs[:n]), (axis,))
    else:
        if axis not in mesh.shape:
            raise MeshFactorizationError(
                f"place_on_mesh: mesh has no axis {axis!r} "
                f"(mesh axes: {tuple(mesh.shape)})")
        check_axis_divides(buf.n_shards, mesh.shape[axis], axis=axis,
                           what="place_on_mesh n_shards")
    return jax.device_put(buf, shardings(buf, mesh, axis))


# ---------------------------------------------------------------------------
# store (shard-local scatter)
# ---------------------------------------------------------------------------

def replay_add_batch(buf: ShardedReplayState, transitions: dict,
                     priority: Optional[jnp.ndarray] = None,
                     errors: Optional[jnp.ndarray] = None,
                     error_clip: float = 100.0) -> ShardedReplayState:
    """Store a leading-axis batch at consecutive GLOBAL ring slots.

    Row ``b`` is store number ``cntr + b`` -> ring slot ``(cntr + b) %
    size`` -> shard ``(cntr + b) % S``.  Each shard independently
    gathers the (at most ``ceil(B/S)``) rows it owns and scatters them
    into its local ring — transitions land shard-local, no collective.
    Priority defaults follow :func:`~smartcal_tpu.rl.replay
    .replay_add_batch` (explicit > per-row errors > global max/clip).
    """
    S, L = buf.priority.shape
    B = next(iter(transitions.values())).shape[0]
    nmax = -(-B // S)                     # rows per shard, padded
    C = buf.cntr
    if priority is None:
        if errors is None:
            pmax = jnp.max(buf.priority)
            priority = jnp.full((B,), jnp.where(pmax == 0.0, error_clip,
                                                pmax))
        else:
            priority = rp.priority_from_errors(errors, error_clip)
    else:
        priority = jnp.broadcast_to(jnp.asarray(priority, jnp.float32),
                                    (B,))

    def upd_shard(s, data_s, prio_s):
        # rows this shard owns: b with (C + b) % S == s
        b = (s - C) % S + S * jnp.arange(nmax)
        valid = b < B
        bg = jnp.minimum(b, B - 1)
        j = ((C + b) // S) % L
        idx = jnp.where(valid, j, L)      # L is out of range -> dropped
        new_data = {
            k: v.at[idx].set(
                jnp.asarray(transitions[k], v.dtype)[bg], mode="drop")
            for k, v in data_s.items()}
        return new_data, prio_s.at[idx].set(priority[bg], mode="drop")

    data, prio = jax.vmap(upd_shard)(jnp.arange(S), buf.data, buf.priority)
    return ShardedReplayState(data=data, cntr=C + B, priority=prio,
                              beta=buf.beta)


def replay_add(buf: ShardedReplayState, transition: dict,
               priority: Optional[jnp.ndarray] = None,
               error: Optional[jnp.ndarray] = None,
               error_clip: float = 100.0) -> ShardedReplayState:
    """Single-transition store (the batch path with B=1)."""
    one = {k: jnp.asarray(v)[None] for k, v in transition.items()}
    err = None if error is None else jnp.asarray(error)[None]
    pri = None if priority is None else priority
    return replay_add_batch(buf, one, priority=pri, errors=err,
                            error_clip=error_clip)


# ---------------------------------------------------------------------------
# ages / ERE / fill
# ---------------------------------------------------------------------------

def _filled(buf: ShardedReplayState):
    return jnp.minimum(buf.cntr, buf.size)


def _global_slots(buf: ShardedReplayState) -> jnp.ndarray:
    """(S, local) map of each cell to its global ring-slot id
    ``g = j*S + s`` — the interleave that makes ages/ERE/fill match the
    flat buffer exactly."""
    S, L = buf.priority.shape
    s = jnp.arange(S)[:, None]
    j = jnp.arange(L)[None, :]
    return j * S + s


def ere_weights(buf: ShardedReplayState, eta: float) -> jnp.ndarray:
    """(S, local) emphasizing-recent-experience weights — numerically
    identical to :func:`~smartcal_tpu.rl.replay.ere_weights` on the
    equivalent flat ring (slot ``(s, j)`` == flat slot ``j*S + s``)."""
    size = buf.size
    filled = _filled(buf)
    g = _global_slots(buf)
    ages = jnp.mod(buf.cntr - 1 - g, jnp.maximum(size, 1))
    x = ages.astype(jnp.float32) / jnp.maximum(filled - 1, 1)
    w = jnp.asarray(eta, jnp.float32) ** (rp.ERE_SPAN * x)
    return jnp.where(g < filled, w, 0.0)


# ---------------------------------------------------------------------------
# sampling (per-shard draw, collective merge)
# ---------------------------------------------------------------------------

def _stratified_gather(buf: ShardedReplayState, weights: jnp.ndarray,
                       key: jnp.ndarray, batch_size: int):
    """Stratified draw of ``batch_size`` rows from the ``weights``
    distribution ((S, local), zero on unfilled slots).

    Per-shard local cumsums + an S-scalar shard-total prefix route each
    stratified value to (shard, local slot); every shard gathers the
    rows it owns and the batch merges as a masked sum over the shard
    axis (the collective).  Returns ``(batch, gidx, p_sel, total)``
    with ``gidx`` the GLOBAL ring-slot ids (priority-update currency).
    """
    S, L = weights.shape
    csum = jnp.cumsum(weights, axis=1)        # (S, L) shard-local
    totals = csum[:, -1]                      # (S,)
    t_csum = jnp.cumsum(totals)
    total = t_csum[-1]
    off = t_csum - totals                     # exclusive shard offsets

    seg = total / batch_size
    u = jax.random.uniform(key, (batch_size,))
    values = (jnp.arange(batch_size) + u) * seg
    shard_of = jnp.clip(jnp.searchsorted(t_csum, values, side="left"),
                        0, S - 1)
    local_v = values - off[shard_of]

    def shard_gather(s, csum_s, data_s, w_s):
        li = jnp.clip(jnp.searchsorted(csum_s, local_v, side="left"),
                      0, L - 1)
        mine = shard_of == s

        def sel(v):
            g = v[li]
            m = mine.reshape((batch_size,) + (1,) * (g.ndim - 1))
            return jnp.where(m, g, jnp.zeros_like(g))

        rows = {k: sel(v) for k, v in data_s.items()}
        p = jnp.where(mine, w_s[li], 0.0)
        gidx = jnp.where(mine, li * S + s, 0)
        return rows, p, gidx

    rows, p, gidx = jax.vmap(shard_gather)(
        jnp.arange(S), csum, buf.data, weights)
    batch = {k: jnp.sum(v, axis=0).astype(buf.data[k].dtype)
             for k, v in rows.items()}
    return batch, jnp.sum(gidx, axis=0), jnp.sum(p, axis=0), total


def replay_sample_per(
        buf: ShardedReplayState, key: jnp.ndarray, batch_size: int,
        recency_eta: Optional[float] = None,
) -> "tuple[dict, jnp.ndarray, jnp.ndarray, ShardedReplayState]":
    """Sharded stratified PER (+ optional ERE modulation) with IS
    weights computed against the distribution actually sampled from —
    the flat :func:`~smartcal_tpu.rl.replay.replay_sample_per` contract
    on the mesh.  Returns ``(batch, gidx, is_weights, new_buf)``."""
    weights = buf.priority
    if recency_eta is not None and recency_eta < 1.0:
        weights = weights * ere_weights(buf, recency_eta)
    beta = jnp.minimum(1.0, buf.beta + rp.PER_BETA_INCREMENT)
    batch, gidx, p_sel, total = _stratified_gather(buf, weights, key,
                                                   batch_size)
    probs = p_sel / total
    is_w = (batch_size * probs) ** (-beta)
    is_w = is_w / jnp.max(is_w)
    return batch, gidx, is_w.astype(jnp.float32), buf._replace(beta=beta)


def replay_sample_ere(buf: ShardedReplayState, key: jnp.ndarray,
                      batch_size: int,
                      eta: float) -> "tuple[dict, jnp.ndarray]":
    """Recency-weighted sampling for UNIFORM sharded buffers (no IS
    correction, per the ERE paper — the flat contract)."""
    w = ere_weights(buf, eta)
    batch, gidx, _, _ = _stratified_gather(buf, w, key, batch_size)
    return batch, gidx


def replay_sample_uniform(buf: ShardedReplayState, key: jnp.ndarray,
                          batch_size: int) -> "tuple[dict, jnp.ndarray]":
    """Uniform sample w/o replacement over the filled prefix: the flat
    path's Gumbel-top-k, scored shard-local, ranked globally (top-k
    over the S*local score vector is the one collective)."""
    S, L = buf.priority.shape
    filled = _filled(buf)
    g = _global_slots(buf)
    gumb = jax.random.gumbel(key, (S, L))
    score = jnp.where(g < filled, gumb, -jnp.inf)
    _, flat_idx = jax.lax.top_k(score.reshape(-1), batch_size)
    shard_of = flat_idx // L
    li = flat_idx % L

    def shard_gather(s, data_s):
        mine = shard_of == s

        def sel(v):
            rows = v[jnp.clip(li, 0, L - 1)]
            m = mine.reshape((batch_size,) + (1,) * (rows.ndim - 1))
            return jnp.where(m, rows, jnp.zeros_like(rows))

        return {k: sel(v) for k, v in data_s.items()}

    rows = jax.vmap(shard_gather)(jnp.arange(S), buf.data)
    batch = {k: jnp.sum(v, axis=0).astype(buf.data[k].dtype)
             for k, v in rows.items()}
    return batch, li * S + shard_of


def replay_update_priorities(buf: ShardedReplayState, gidx: jnp.ndarray,
                             errors: jnp.ndarray,
                             error_clip: float = 100.0
                             ) -> ShardedReplayState:
    """Shard-local scatter of the re-computed priorities: each shard
    writes the sampled rows it owns (``gidx % S == s``) and drops the
    rest — same clip-then-exponent rule as the flat buffer."""
    S, L = buf.priority.shape
    clipped = jnp.minimum(jnp.abs(errors) + rp.PER_EPSILON, error_clip)
    newp = clipped ** rp.PER_ALPHA

    def upd(s, prio_s):
        mine = (gidx % S) == s
        li = jnp.where(mine, gidx // S, L)   # L -> dropped
        return prio_s.at[li].set(newp, mode="drop")

    return buf._replace(
        priority=jax.vmap(upd)(jnp.arange(S), buf.priority))


# ---------------------------------------------------------------------------
# telemetry / persistence
# ---------------------------------------------------------------------------

def shard_occupancy(cntr: int, n_shards: int, local_size: int) -> list:
    """Filled slots per shard from the GLOBAL counter alone (host ints;
    one cheap scalar pull per telemetry round, no array transfer).
    Round-robin keeps shards balanced to within one transition."""
    filled = min(int(cntr), n_shards * local_size)
    return [max(0, (filled - s + n_shards - 1) // n_shards)
            for s in range(n_shards)]


def version_staleness(buf: ShardedReplayState, learner_version: int) -> dict:
    """Host-side staleness profile of a VERSIONED buffer (one built from
    ``replay.versioned_spec``): how far behind the learner the stored
    behavior snapshots are, over the filled prefix.

    This is the lifecycle run's staleness gauge source — the learner
    publishes, versions in the ring age, and the IMPACT clip
    (``replay.staleness_clip_weights``) starts biting; this summary is
    what obs_report's lifecycle section plots next to the clip-saturation
    aux.  Returns zeros when the buffer is empty or unversioned."""
    if "version" not in buf.data:
        return {"filled": 0, "staleness_mean": 0.0, "staleness_max": 0,
                "stale_frac": 0.0}
    S, L = buf.priority.shape
    ver = np.asarray(jax.device_get(buf.data["version"])).T.reshape(-1)
    filled = min(int(jax.device_get(buf.cntr)), S * L)
    if filled <= 0:
        return {"filled": 0, "staleness_mean": 0.0, "staleness_max": 0,
                "stale_frac": 0.0}
    stale = np.maximum(0, int(learner_version) - ver[:filled].astype(np.int64))
    return {"filled": filled,
            "staleness_mean": round(float(stale.mean()), 4),
            "staleness_max": int(stale.max()),
            "stale_frac": round(float((stale > 0).mean()), 4)}


def replay_health(buf: ShardedReplayState) -> dict:
    """Host-side health summary — the flat ring reconstructed from the
    interleave (slot ``g = j*S + s``), run through the shared
    :func:`~smartcal_tpu.rl.replay._health_from_arrays` math, plus the
    per-shard occupancy profile."""
    S, L = buf.priority.shape
    prio = np.asarray(jax.device_get(buf.priority))
    # (S, L) -> ring order g = j*S + s  ==  transpose then flatten
    flat = prio.T.reshape(-1)
    cntr = int(jax.device_get(buf.cntr))
    out = rp._health_from_arrays(flat, cntr, S * L,
                                 float(jax.device_get(buf.beta)))
    out["n_shards"] = S
    out["shard_occupancy"] = shard_occupancy(cntr, S, L)
    return out
