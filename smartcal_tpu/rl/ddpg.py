"""DDPG as pure jitted functions.

Re-expresses the reference DDPG agent (``elasticnet/enet_ddpg.py``,
``calibration/calib_ddpg.py``): deterministic actor + single critic with
target copies, Ornstein-Uhlenbeck exploration noise (``enet_ddpg.py:23-43``)
carried as functional state, critic loss ``||q - y||^2`` (summed, as the
reference's ``T.norm(...)**2``, ``:281-284``), actor loss
``-mean(critic(s, actor(s)))`` (``:291-297``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax

from ..obs import diagnostics as dg
from . import replay as rp
from .networks import (MLPCritic, MLPDeterministicActor,
                       SplitImageMetaCritic,
                       SplitImageMetaDeterministicActor)


@dataclasses.dataclass(frozen=True)
class DDPGConfig:
    obs_dim: int
    n_actions: int
    gamma: float = 0.99
    tau: float = 0.001
    lr_a: float = 1e-3
    lr_c: float = 1e-3
    batch_size: int = 64
    mem_size: int = 1024
    ou_sigma: float = 0.15
    ou_theta: float = 0.2
    ou_dt: float = 1e-2
    img_shape: Optional[Tuple[int, int]] = None   # see sac.SACConfig
    use_image: bool = True


class OUState(NamedTuple):
    x_prev: jnp.ndarray


def ou_init(n_actions: int) -> OUState:
    return OUState(x_prev=jnp.zeros((n_actions,), jnp.float32))


def ou_sample(cfg: DDPGConfig, st: OUState, key) -> Tuple[jnp.ndarray, OUState]:
    """One Ornstein-Uhlenbeck draw (enet_ddpg.py:30-35), mu = 0."""
    x = (st.x_prev - cfg.ou_theta * st.x_prev * cfg.ou_dt
         + cfg.ou_sigma * jnp.sqrt(cfg.ou_dt)
         * jax.random.normal(key, st.x_prev.shape))
    return x, OUState(x_prev=x)


class DDPGState(NamedTuple):
    actor_params: Any
    critic_params: Any
    t_actor_params: Any
    t_critic_params: Any
    actor_opt: Any
    critic_opt: Any
    noise: OUState


def _nets(cfg: DDPGConfig):
    if cfg.img_shape is not None:
        return (SplitImageMetaDeterministicActor(
                    img_shape=cfg.img_shape, n_actions=cfg.n_actions,
                    use_image=cfg.use_image),
                SplitImageMetaCritic(img_shape=cfg.img_shape,
                                     use_image=cfg.use_image))
    return MLPDeterministicActor(cfg.n_actions), MLPCritic()


def ddpg_init(key, cfg: DDPGConfig) -> DDPGState:
    actor, critic = _nets(cfg)
    ka, kc = jax.random.split(key)
    obs = jnp.zeros((1, cfg.obs_dim))
    act = jnp.zeros((1, cfg.n_actions))
    actor_params = actor.init(ka, obs)["params"]
    critic_params = critic.init(kc, obs, act)["params"]
    copy = lambda t: jax.tree_util.tree_map(jnp.copy, t)
    return DDPGState(
        actor_params=actor_params, critic_params=critic_params,
        t_actor_params=copy(actor_params),
        t_critic_params=copy(critic_params),
        actor_opt=optax.adam(cfg.lr_a).init(actor_params),
        critic_opt=optax.adam(cfg.lr_c).init(critic_params),
        noise=ou_init(cfg.n_actions),
    )


def choose_action(cfg: DDPGConfig, st: DDPGState, obs, key
                  ) -> Tuple[jnp.ndarray, DDPGState]:
    """actor(obs) + OU noise (enet_ddpg.py:243-249); not clamped, matching
    the reference (the env clamps/penalises out-of-range actions)."""
    actor, _ = _nets(cfg)
    mu = actor.apply({"params": st.actor_params}, obs)
    n, noise = ou_sample(cfg, st.noise, key)
    return mu + n, st._replace(noise=noise)


def learn(cfg: DDPGConfig, st: DDPGState, buf: rp.ReplayState,
          key, collect_diag: bool = False
          ) -> Tuple[DDPGState, rp.ReplayState, dict]:
    """One DDPG learn step (enet_ddpg.py:251-302).

    ``collect_diag`` (python-static) adds ``metrics['diag']`` — an
    :class:`~smartcal_tpu.obs.diagnostics.UpdateDiag`; with it False the
    traced program is the exact pre-diagnostics computation."""
    actor, critic = _nets(cfg)
    opt_a, opt_c = optax.adam(cfg.lr_a), optax.adam(cfg.lr_c)

    def do_learn(args):
        st, buf, key = args
        batch, _ = rp.replay_sample_uniform(buf, key, cfg.batch_size)
        s, a = batch["state"], batch["action"]
        r, s2 = batch["reward"], batch["new_state"]
        done = batch["done"].astype(jnp.float32)

        ta = actor.apply({"params": st.t_actor_params}, s2)
        qt = critic.apply({"params": st.t_critic_params}, s2, ta).squeeze(-1)
        y = (r + cfg.gamma * qt * (1.0 - done))[:, None]
        y = lax.stop_gradient(y)

        def critic_loss(p):
            q = critic.apply({"params": p}, s, a)
            return jnp.sum((q - y) ** 2)  # T.norm(.,2)**2 — summed

        closs, gc = jax.value_and_grad(critic_loss)(st.critic_params)
        # q stats recomputed OUTSIDE the grad: auxing q out of the loss
        # would change the AD graph (and bit-drift the update); a separate
        # forward is deterministic and CSE-dedupes under jit
        q_batch = (critic.apply({"params": st.critic_params}, s, a)
                   if collect_diag else None)
        uc, critic_opt = opt_c.update(gc, st.critic_opt, st.critic_params)
        critic_params = optax.apply_updates(st.critic_params, uc)

        def actor_loss(p):
            mu = actor.apply({"params": p}, s)
            return -jnp.mean(critic.apply({"params": critic_params}, s, mu))

        aloss, ga = jax.value_and_grad(actor_loss)(st.actor_params)
        ua, actor_opt = opt_a.update(ga, st.actor_opt, st.actor_params)
        actor_params = optax.apply_updates(st.actor_params, ua)

        lerp = lambda t, o: jax.tree_util.tree_map(
            lambda a_, b_: cfg.tau * a_ + (1.0 - cfg.tau) * b_, o, t)
        st_new = DDPGState(
            actor_params=actor_params, critic_params=critic_params,
            t_actor_params=lerp(st.t_actor_params, actor_params),
            t_critic_params=lerp(st.t_critic_params, critic_params),
            actor_opt=actor_opt, critic_opt=critic_opt, noise=st.noise)
        metrics = {"critic_loss": closs, "actor_loss": aloss}
        if collect_diag:
            metrics["diag"] = dg.make_diag(
                critic_loss=closs, actor_loss=aloss,
                critic_grad_norm=dg.tree_norm(gc),
                actor_grad_norm=dg.tree_norm(ga),
                critic_update_ratio=dg.update_ratio(uc, st.critic_params),
                actor_update_ratio=dg.update_ratio(ua, st.actor_params),
                q_mean=jnp.mean(q_batch), q_min=jnp.min(q_batch),
                q_max=jnp.max(q_batch),
                target_drift=dg.target_drift(critic_params,
                                             st_new.t_critic_params))
        return st_new, buf, metrics

    def no_learn(args):
        st, buf, _ = args
        zeros = {"critic_loss": jnp.asarray(0.0),
                 "actor_loss": jnp.asarray(0.0)}
        if collect_diag:
            zeros["diag"] = dg.zero_diag()
        return st, buf, zeros

    return lax.cond(buf.cntr >= cfg.batch_size, do_learn, no_learn,
                    (st, buf, key))


class DDPGAgent:
    """Host-driven wrapper with the reference Agent API."""

    def __init__(self, cfg: DDPGConfig, seed: int = 0, name_prefix: str = "",
                 collect_diag: bool = False):
        self.cfg = cfg
        self.key = jax.random.PRNGKey(seed)
        self.key, k0 = jax.random.split(self.key)
        self.state = ddpg_init(k0, cfg)
        self.buffer = rp.replay_init(
            cfg.mem_size, rp.transition_spec(cfg.obs_dim, cfg.n_actions))
        self.name_prefix = name_prefix
        self.collect_diag = collect_diag
        self._choose = jax.jit(
            lambda st, obs, key: choose_action(cfg, st, obs, key))
        self._learn = jax.jit(lambda st, buf, key: learn(
            cfg, st, buf, key, collect_diag=collect_diag))
        self._add = jax.jit(
            lambda buf, tr: rp.replay_add(buf, tr, priority=jnp.asarray(1.0)))
        self.last_metrics = {}
        self.last_diag = None

    def _next_key(self):
        self.key, sub = jax.random.split(self.key)
        return sub

    def choose_action(self, observation):
        obs = jnp.asarray(observation, jnp.float32)
        a, self.state = self._choose(self.state, obs, self._next_key())
        return jax.device_get(a)

    def store_transition(self, state, action, reward, state_, done,
                         hint=None):
        tr = {"state": state, "action": action, "reward": reward,
              "new_state": state_, "done": done,
              "hint": jnp.zeros((self.cfg.n_actions,), jnp.float32)
              if hint is None else hint}
        self.buffer = self._add(self.buffer, tr)

    def learn(self):
        from smartcal_tpu.obs import costs
        from smartcal_tpu.obs.spans import span

        k = self._next_key()
        # span name == cost stage ('/'-free) -> obs_report roofline join;
        # cost analysis after the span (see td3.TD3Agent.learn)
        with span("agent_update_ddpg"):
            self.state, self.buffer, m = self._learn(self.state,
                                                     self.buffer, k)
        costs.record_stage_cost("agent_update_ddpg", self._learn,
                                self.state, self.buffer, k, defer=True)
        self.last_metrics = m
        self.last_diag = m.pop("diag", None)

    def save_models(self, prefix: Optional[str] = None):
        from smartcal_tpu.runtime.atomic import atomic_pickle

        prefix = prefix if prefix is not None else self.name_prefix
        atomic_pickle(jax.device_get(self.state), f"{prefix}ddpg_state.pkl")
        rp.save_replay(self.buffer, f"{prefix}replaymem_ddpg.pkl")

    def load_models(self, prefix: Optional[str] = None):
        """Corruption-tolerant resume: warn + keep the fresh init when a
        checkpoint file is missing/truncated (see SACAgent.load_models)."""
        from smartcal_tpu.runtime.atomic import safe_pickle_load

        prefix = prefix if prefix is not None else self.name_prefix
        host = safe_pickle_load(f"{prefix}ddpg_state.pkl")
        if host is None:
            return False
        self.state = jax.tree_util.tree_map(jnp.asarray, host)
        mem = safe_pickle_load(f"{prefix}replaymem_ddpg.pkl")
        if mem is not None:
            self.buffer = jax.tree_util.tree_map(jnp.asarray, mem)
        return True
