"""Host-side prioritized replay on the native C++ sum tree.

The framework's default PER lives in HBM and samples with a vectorised
prefix-sum search (:mod:`smartcal_tpu.rl.replay`).  SURVEY.md §7 ("PER on
TPU") names the alternative design — a host-side tree with device-side
storage — and asks that both be measured.  This module is that
alternative: transitions stay in host numpy ring arrays, priorities in the
O(log n) C++ sum tree of :mod:`smartcal_tpu.native` (the reference's
SumTree, elasticnet/enet_sac.py:82-200, minus the python interpreter), and
only the sampled minibatch crosses to the device each learn step.

Semantics mirror ``rl.replay`` exactly (same constants, same priority
rules, same stratified segments + IS weights + beta annealing), so the two
backends are drop-in comparable — ``tools/bench_per.py`` does the measuring.

Trade-off, measured and documented in tools/bench_per.py: the HBM variant
fuses store+sample into the jitted train step (no host<->device hop, wins
whenever the rest of the step is device-resident); the host tree wins when
the replay payload is too large for HBM or the loop is host-driven anyway
(the distributed learner ingesting actor buffers).
"""

from __future__ import annotations


import numpy as np

from smartcal_tpu import native
from smartcal_tpu.rl.replay import (PER_ALPHA, PER_BETA0, PER_BETA_INCREMENT,
                                    PER_EPSILON)


class NativePER:
    """Prioritized replay: numpy ring storage + native sum-tree priorities.

    ``spec`` is the same ``{field: (shape, dtype)}`` layout
    :func:`smartcal_tpu.rl.replay.transition_spec` produces.
    """

    def __init__(self, size: int, spec: dict, error_clip: float = 100.0):
        if native.lib() is None:
            raise RuntimeError(
                "native library unavailable (no g++?); use rl.replay")
        self.size = int(size)
        self.error_clip = float(error_clip)
        self.spec = dict(spec)
        self.data = {k: np.zeros((self.size,) + tuple(shape),
                                 np.dtype(dtype))
                     for k, (shape, dtype) in spec.items()}
        self.tree = native.SumTree(self.size)
        if self.tree.capacity != self.size:
            raise ValueError(
                f"size must be a power of two (got {size}); the tree "
                f"rounds to {self.tree.capacity}")
        self.cntr = 0
        self.beta = PER_BETA0

    # -- storing ----------------------------------------------------------
    def _priority_from_error(self, error) -> float:
        # pure-python twin of replay.priority_from_errors (a jnp call per
        # store would defeat the host-side design; drift is caught by
        # tests/test_native.py::test_native_per_priority_rules_and_checkpoint)
        return float(min((abs(float(error)) + PER_EPSILON) ** PER_ALPHA,
                         self.error_clip))

    def store(self, transition: dict, error=None) -> int:
        """Store one transition; returns its slot.  Priority defaults to the
        current max (or clip when empty) like ``PER.store_transition``."""
        if error is None:
            pmax = self.tree.max_priority()
            p = self.error_clip if pmax == 0.0 else pmax
        else:
            p = self._priority_from_error(error)
        idx = self.cntr % self.size
        for k, v in self.data.items():
            v[idx] = np.asarray(transition[k], v.dtype)
        leaf = self.tree.add(p)
        assert leaf == idx
        self.cntr += 1
        return idx

    def store_batch(self, transitions: dict, errors=None) -> None:
        """Bulk ingestion (the learner's ``store_transition_from_buffer``
        role) — transitions enter one by one, preserving priority-init
        semantics."""
        n = len(next(iter(transitions.values())))
        for i in range(n):
            t = {k: v[i] for k, v in transitions.items()}
            e = None if errors is None else errors[i]
            self.store(t, e)

    @property
    def filled(self) -> int:
        return min(self.cntr, self.size)

    def ready(self, batch_size: int) -> bool:
        return self.filled >= batch_size

    # -- sampling ---------------------------------------------------------
    def sample(self, batch_size: int, rng: np.random.Generator,
               uniforms=None):
        """(batch, idx, is_weights) with the same stratified scheme and
        beta annealing as ``replay.replay_sample_per``.  ``uniforms``
        overrides the per-segment draws (testing/replay determinism)."""
        self.beta = min(1.0, self.beta + PER_BETA_INCREMENT)
        u = rng.random(batch_size) if uniforms is None else \
            np.asarray(uniforms, np.float64)
        idx, pri = self.tree.sample_stratified(batch_size, u)
        # A stratified walk can overshoot into the unfilled suffix of a
        # partially-filled buffer (fp rounding in the tree descent), landing
        # on a zero-priority leaf whose probs=0 would make the IS weight
        # infinite and poison the loss with NaNs.  Clamp the leaf into the
        # filled prefix and re-read its true priority, then floor priorities
        # so probs stays strictly positive even if total is degenerate.
        filled = self.filled
        over = idx >= filled
        if np.any(over) or np.any(pri <= 0.0):
            idx = np.minimum(idx, max(filled - 1, 0))
            leaves = self.tree.leaves()
            pri = leaves[idx]
        total = self.tree.total()
        probs = np.maximum(pri / max(total, 1e-300), 1e-12)
        is_w = (batch_size * probs) ** (-self.beta)
        is_w = is_w / np.max(is_w)
        batch = {k: v[idx] for k, v in self.data.items()}
        return batch, idx, is_w.astype(np.float32)

    def update_priorities(self, idx, errors) -> None:
        """``batch_update``: p = min(|e|+eps, clip)^alpha."""
        clipped = np.minimum(np.abs(np.asarray(errors, np.float64))
                             + PER_EPSILON, self.error_clip)
        self.tree.update_batch(np.asarray(idx, np.int64),
                               clipped ** PER_ALPHA)

    def health(self) -> dict:
        """Same replay-health summary as ``replay.replay_health`` (shared
        math, host tree leaves — no device involved)."""
        from smartcal_tpu.rl.replay import _health_from_arrays

        return _health_from_arrays(self.tree.leaves(), self.cntr,
                                   self.size, self.beta)

    # -- checkpoint -------------------------------------------------------
    def state_dict(self) -> dict:
        """The complete host state — ring arrays, sum-tree leaves/cursor
        (the priorities), beta — as one picklable dict; the in-payload
        form runtime.checkpoint.pack_replay uses."""
        return {
            "data": self.data, "cntr": self.cntr, "beta": self.beta,
            "leaves": self.tree.leaves(), "cursor": self.tree.cursor,
            "filled": self.tree.filled, "size": self.size,
            "error_clip": self.error_clip, "spec": self.spec,
        }

    @classmethod
    def from_state_dict(cls, state: dict) -> "NativePER":
        buf = cls(state["size"], state["spec"],
                  error_clip=state["error_clip"])
        buf.data = state["data"]
        buf.cntr = state["cntr"]
        buf.beta = state["beta"]
        buf.tree.set_state(state["leaves"], state["cursor"], state["filled"])
        return buf

    def save(self, path: str) -> None:
        from smartcal_tpu.runtime.atomic import atomic_pickle

        atomic_pickle(self.state_dict(), path)

    @classmethod
    def load(cls, path: str) -> "NativePER":
        from smartcal_tpu.runtime.atomic import strict_pickle_load

        state = strict_pickle_load(path)
        return cls.from_state_dict(state)
