"""Twin-Delayed DDPG (TD3) as pure jitted functions.

Re-expresses the reference TD3 agent (``elasticnet/enet_td3.py``; CNN
variants ``calibration/calib_td3.py``, ``demixing_rl/demix_td3.py``):

* deterministic tanh actor + twin critics + target actor/critics
  (``enet_td3.py:124-159``); warmup phase of pure exploration noise before
  the actor is consulted (``:207-220``);
* target-policy smoothing: a clipped scalar Gaussian perturbation of the
  target action (``:247-251`` — the reference draws ONE scalar per learn
  call, clamped to [-0.5, 0.5]; reproduced faithfully);
* delayed actor updates every ``update_actor_interval`` critic steps
  (``:298``);
* PER: priority initialised with the reward on store (``:199-205``),
  refreshed with the mean twin TD error before the critic step (``:263-269``);
* hint constraint via a full inner ADMM loop (``Nadmm=5``): Lagrange vector
  over the (batch x actions) residual, per-iteration actor Adam step, dual
  ascent, and the adaptive-rho Barzilai-Borwein / spectral step rule with a
  correlation gate (``:310-361``) — here a ``lax.fori_loop`` whose carry is
  (actor params, opt state, lagrange y, y0, a0, rho).

One deliberate deviation: the reference steps the two critic Adam optimizers
sequentially with a shared closure (the second step sees the first's
update); here both critics update from one joint gradient evaluation — the
standard TD3 formulation, one fused XLA step.
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax

from ..obs import diagnostics as dg
from . import replay as rp
from .networks import (MLPCritic, MLPDeterministicActor,
                       SplitImageMetaCritic,
                       SplitImageMetaDeterministicActor)


@dataclasses.dataclass(frozen=True)
class TD3Config:
    obs_dim: int
    n_actions: int
    gamma: float = 0.99
    tau: float = 0.005
    lr_a: float = 1e-3
    lr_c: float = 1e-3
    batch_size: int = 64
    mem_size: int = 1024
    warmup: int = 100             # main_td3.py:20
    noise: float = 0.1            # exploration noise scale
    update_actor_interval: int = 2
    use_hint: bool = False
    admm_rho: float = 1.0         # main_td3.py:22 override of the 0.1 default
    n_admm: int = 5               # enet_td3.py:141
    adaptive_admm: bool = True
    corr_min: float = 0.5         # enet_td3.py:143
    prioritized: bool = False
    error_clip: float = 100.0
    img_shape: Optional[Tuple[int, int]] = None   # see sac.SACConfig
    use_image: bool = True
    # staleness-clipped update weighting for the async fleet.  TD3's
    # deterministic policy admits no likelihood ratio (the IMPACT weight
    # SAC uses), so the weight is an exponential staleness decay
    # ``clip(is_decay**staleness, 1/is_clip, 1)`` — same clip constant,
    # same exactly-1.0-at-staleness-0 bit-identity contract.  Armed
    # buffers carry 'version' (replay.versioned_spec); learn() must be
    # given the learner's policy version.
    is_clip: float = 0.0
    is_decay: float = 0.9
    # emphasizing-recent-experience sampling knob (see sac.SACConfig)
    ere_eta: float = 1.0

    def __post_init__(self):
        rp.validate_fleet_knobs(self.is_clip, self.ere_eta)
        if not 0.0 < self.is_decay <= 1.0:
            raise ValueError(
                f"is_decay must be in (0, 1], got {self.is_decay}")


class TD3State(NamedTuple):
    actor_params: Any
    c1_params: Any
    c2_params: Any
    t_actor_params: Any
    t1_params: Any
    t2_params: Any
    actor_opt: Any
    c1_opt: Any
    c2_opt: Any
    learn_counter: jnp.ndarray
    time_step: jnp.ndarray


def _nets(cfg: TD3Config):
    if cfg.img_shape is not None:
        return (SplitImageMetaDeterministicActor(
                    img_shape=cfg.img_shape, n_actions=cfg.n_actions,
                    use_image=cfg.use_image),
                SplitImageMetaCritic(img_shape=cfg.img_shape,
                                     use_image=cfg.use_image))
    return MLPDeterministicActor(cfg.n_actions), MLPCritic()


def td3_init(key, cfg: TD3Config) -> TD3State:
    actor, critic = _nets(cfg)
    ka, k1, k2 = jax.random.split(key, 3)
    obs = jnp.zeros((1, cfg.obs_dim))
    act = jnp.zeros((1, cfg.n_actions))
    actor_params = actor.init(ka, obs)["params"]
    c1 = critic.init(k1, obs, act)["params"]
    c2 = critic.init(k2, obs, act)["params"]
    copy = lambda t: jax.tree_util.tree_map(jnp.copy, t)
    return TD3State(
        actor_params=actor_params, c1_params=c1, c2_params=c2,
        t_actor_params=copy(actor_params), t1_params=copy(c1),
        t2_params=copy(c2),
        actor_opt=optax.adam(cfg.lr_a).init(actor_params),
        c1_opt=optax.adam(cfg.lr_c).init(c1),
        c2_opt=optax.adam(cfg.lr_c).init(c2),
        learn_counter=jnp.asarray(0, jnp.int32),
        time_step=jnp.asarray(0, jnp.int32),
    )


def choose_action(cfg: TD3Config, st: TD3State, obs, key
                  ) -> Tuple[jnp.ndarray, TD3State]:
    """Warmup-noise / actor action + exploration noise, clamped to [-1, 1]
    (enet_td3.py:207-220).  Returns (action, state with bumped time_step)."""
    actor, _ = _nets(cfg)
    k1, k2 = jax.random.split(key)
    shape = obs.shape[:-1] + (cfg.n_actions,)
    random_mu = cfg.noise * jax.random.normal(k1, shape)
    actor_mu = actor.apply({"params": st.actor_params}, obs)
    mu = jnp.where(st.time_step < cfg.warmup, random_mu, actor_mu)
    mu_prime = mu + cfg.noise * jax.random.normal(k2, shape)
    action = jnp.clip(mu_prime, -1.0, 1.0)
    return action, st._replace(time_step=st.time_step + 1)


def staleness_weights(cfg: TD3Config, batch: dict, learner_version
                      ) -> Tuple[jnp.ndarray, dict]:
    """Clipped staleness-decay weights for a versioned batch (the
    deterministic-policy stand-in for :func:`smartcal_tpu.rl.sac.
    impact_weights`): ``clip(is_decay**staleness, 1/is_clip, ...)``,
    exactly 1.0 at staleness <= 0.  With ``is_decay <= 1`` (validated)
    the raw weight never exceeds 1, so the shared two-sided clip core
    is effectively ``[1/is_clip, 1]``.  Returns ``(weights, aux)``."""
    decay = jnp.asarray(cfg.is_decay, jnp.float32)
    return rp.staleness_clip_weights(lambda stale: decay ** stale,
                                     batch["version"], learner_version,
                                     cfg.is_clip)


def store_priority(cfg: TD3Config, reward):
    """TD3 PER initialises priority with the reward (enet_td3.py:199-205)."""
    if not cfg.prioritized:
        return None
    return jnp.minimum((jnp.abs(reward) + rp.PER_EPSILON) ** rp.PER_ALPHA,
                       cfg.error_clip)


def _actor_admm_update(cfg: TD3Config, st: TD3State, c1_params, s, hint,
                       is_w, collect_diag: bool = False):
    """Hint-constrained actor update: inner ADMM loop with adaptive rho
    (enet_td3.py:310-361).

    ``collect_diag`` additionally returns the LAST ADMM iteration's
    (loss, global grad norm, constraint mse) by widening the fori_loop
    carry — with it False the carry (and the traced program) is exactly
    the pre-diagnostics one."""
    actor, critic = _nets(cfg)
    opt_a = optax.adam(cfg.lr_a)
    numel = jnp.asarray(s.shape[0] * cfg.n_actions, jnp.float32)

    def one_iter(admm, carry):
        if collect_diag:
            (params, opt_state, y, y0, a0, rho, _extras) = carry
        else:
            (params, opt_state, y, y0, a0, rho) = carry

        def loss_fn(p):
            actions = actor.apply({"params": p}, s)
            q1 = critic.apply({"params": c1_params}, s, actions)
            if cfg.prioritized:
                aloss = -jnp.mean(q1 * is_w[:, None])
            else:
                aloss = -jnp.mean(q1)
            diff = (actions - hint).reshape(-1)
            mse = jnp.mean((actions - hint) ** 2)
            lagr = (jnp.dot(y, diff) + rho / 2.0 * mse)
            if cfg.prioritized:
                # reference :327 multiplies the scalar by is_weight then means
                lagr = jnp.mean(lagr * is_w)
            return aloss + lagr / numel, actions

        (aloss, actions), g = jax.value_and_grad(loss_fn, has_aux=True)(params)
        upd, opt_state = opt_a.update(g, opt_state, params)
        params = optax.apply_updates(params, upd)

        diff = (actions - hint).reshape(-1)
        y_new = y + rho * diff

        if collect_diag:
            # last iteration wins — the converged constraint/gradient state
            extras = (aloss, dg.tree_norm(g),
                      jnp.mean((actions - hint) ** 2))

        if not cfg.adaptive_admm:
            if collect_diag:
                return (params, opt_state, y_new, y0, a0, rho, extras)
            return (params, opt_state, y_new, y0, a0, rho)

        # adaptive rho (Barzilai-Borwein spectral / steepest-descent rule
        # with correlation gate, enet_td3.py:334-359)
        a_flat = actions.reshape(-1)

        def init_anchor(_):
            # the reference anchors the FIRST dual iterate y0 to the flat
            # actions, not to the dual vector (enet_td3.py:336-338) — a
            # quirk, reproduced here so adaptive-rho trajectories match
            return (a_flat, a_flat, rho)

        def maybe_adapt(_):
            y1 = y_new + rho * diff
            dy = y1 - y0
            du = a_flat - a0
            d11 = jnp.dot(dy, dy)
            d12 = jnp.dot(dy, du)
            d22 = jnp.dot(du, du)
            alpha = d12 / jnp.sqrt(jnp.maximum(d11 * d22, 1e-30))
            alpha_sd = d11 / jnp.where(d12 == 0, 1.0, d12)
            alpha_mg = d12 / jnp.where(d22 == 0, 1.0, d22)
            alpha_hat = jnp.where(2.0 * alpha_mg > alpha_sd, alpha_mg,
                                  alpha_sd - 0.5 * alpha_mg)
            ok = ((d11 > 0) & (d12 > 0) & (d22 > 0)
                  & (alpha > cfg.corr_min)
                  & (alpha_hat < 10.0 * cfg.admm_rho)
                  & (alpha_hat > 0.1 * cfg.admm_rho))
            return (y1, a_flat, jnp.where(ok, alpha_hat, rho))

        adapt_now = (admm % 3 == 0) & (admm < cfg.n_admm - 1) & (admm > 0)
        y0_new, a0_new, rho_new = lax.cond(
            admm == 0, init_anchor,
            lambda _: lax.cond(adapt_now, maybe_adapt,
                               lambda __: (y0, a0, rho), operand=None),
            operand=None)
        if collect_diag:
            return (params, opt_state, y_new, y0_new, a0_new, rho_new,
                    extras)
        return (params, opt_state, y_new, y0_new, a0_new, rho_new)

    y_init = jnp.zeros((s.shape[0] * cfg.n_actions,), jnp.float32)
    carry = (st.actor_params, st.actor_opt, y_init, y_init,
             jnp.zeros_like(y_init), jnp.asarray(cfg.admm_rho, jnp.float32))
    if collect_diag:
        zero = jnp.asarray(0.0, jnp.float32)
        carry = carry + ((zero, zero, zero),)
        out = lax.fori_loop(0, cfg.n_admm, one_iter, carry)
        return out[0], out[1], out[6]
    params, opt_state, _, _, _, _ = lax.fori_loop(0, cfg.n_admm, one_iter,
                                                  carry)
    return params, opt_state


def learn(cfg: TD3Config, st: TD3State, buf: rp.ReplayState,
          key, collect_diag: bool = False, learner_version=None
          ) -> Tuple[TD3State, rp.ReplayState, dict]:
    """One TD3 learn step (enet_td3.py:222-364).

    ``collect_diag`` (python-static) adds ``metrics['diag']`` — an
    :class:`~smartcal_tpu.obs.diagnostics.UpdateDiag`; with it False the
    traced program is the exact pre-diagnostics computation.  Actor
    fields report 0 on delayed-update skip steps (the watchdog treats
    exact zeros as skips).

    ``cfg.is_clip`` + ``learner_version`` arm the staleness-clipped
    critic weighting (:func:`staleness_weights`); ``cfg.ere_eta < 1``
    switches the device-side sample distribution to (or modulates it by)
    the emphasizing-recent-experience weights."""
    actor, critic = _nets(cfg)
    opt_c = optax.adam(cfg.lr_c)
    opt_a = optax.adam(cfg.lr_a)
    ere = cfg.ere_eta if cfg.ere_eta < 1.0 else None

    def do_learn(args):
        st, buf, key = args
        k_samp, k_noise = jax.random.split(key)

        if cfg.prioritized:
            batch, idx, is_w, buf2 = rp.replay_sample_per(
                buf, k_samp, cfg.batch_size, recency_eta=ere)
        elif ere is not None:
            batch, idx = rp.replay_sample_ere(buf, k_samp, cfg.batch_size,
                                              ere)
            is_w, buf2 = jnp.ones((cfg.batch_size,), jnp.float32), buf
        else:
            batch, idx = rp.replay_sample_uniform(buf, k_samp, cfg.batch_size)
            is_w, buf2 = jnp.ones((cfg.batch_size,), jnp.float32), buf

        clip_aux = {}
        if cfg.is_clip > 0:
            if learner_version is None:
                raise ValueError("cfg.is_clip armed but learn was not "
                                 "given the learner_version")
            w_clip, clip_aux = staleness_weights(cfg, batch,
                                                 learner_version)
            # staleness 0 -> w_clip exactly 1.0 -> is_w bitwise unchanged
            is_w = is_w * w_clip

        s, a = batch["state"], batch["action"]
        r = batch["reward"]
        s2, done = batch["new_state"], batch["done"]
        hint = batch["hint"]

        # target with clipped scalar smoothing noise (enet_td3.py:247-251)
        ta = actor.apply({"params": st.t_actor_params}, s2)
        smooth = jnp.clip(0.2 * jax.random.normal(k_noise, ()), -0.5, 0.5)
        ta = jnp.clip(ta + smooth, -1.0, 1.0)
        q1t = critic.apply({"params": st.t1_params}, s2, ta).squeeze(-1)
        q2t = critic.apply({"params": st.t2_params}, s2, ta).squeeze(-1)
        q1t = jnp.where(done, 0.0, q1t)
        q2t = jnp.where(done, 0.0, q2t)
        y = (r + cfg.gamma * jnp.minimum(q1t, q2t))[:, None]
        y = lax.stop_gradient(y)

        # PER priorities refreshed from current critics (enet_td3.py:263-269)
        if cfg.prioritized:
            q1c = critic.apply({"params": st.c1_params}, s, a)
            q2c = critic.apply({"params": st.c2_params}, s, a)
            err = 0.5 * (jnp.abs(q1c - y) + jnp.abs(q2c - y)).squeeze(-1)
            buf2 = rp.replay_update_priorities(buf2, idx, err, cfg.error_clip)

        def critic_loss(c1p, c2p):
            q1 = critic.apply({"params": c1p}, s, a)
            q2 = critic.apply({"params": c2p}, s, a)
            if cfg.prioritized or cfg.is_clip > 0:
                return rp.per_mse(q1, y, is_w) + rp.per_mse(q2, y, is_w)
            return jnp.mean((q1 - y) ** 2) + jnp.mean((q2 - y) ** 2)

        closs, (g1, g2) = jax.value_and_grad(critic_loss, argnums=(0, 1))(
            st.c1_params, st.c2_params)
        # q stats recomputed OUTSIDE the grad (auxing q out of the loss
        # would change the AD graph and bit-drift the update; a separate
        # forward is deterministic and CSE-dedupes under jit)
        q_batch = (critic.apply({"params": st.c1_params}, s, a)
                   if collect_diag else None)
        u1, c1_opt = opt_c.update(g1, st.c1_opt, st.c1_params)
        c1_params = optax.apply_updates(st.c1_params, u1)
        u2, c2_opt = opt_c.update(g2, st.c2_opt, st.c2_params)
        c2_params = optax.apply_updates(st.c2_params, u2)

        counter = st.learn_counter + 1

        # delayed actor + target update (enet_td3.py:298-364)
        def actor_update(_):
            if cfg.use_hint:
                if collect_diag:
                    params, opt_state, (aloss, agn, hres) = \
                        _actor_admm_update(cfg, st, c1_params, s, hint,
                                           is_w, collect_diag=True)
                else:
                    params, opt_state = _actor_admm_update(
                        cfg, st, c1_params, s, hint, is_w)
            else:
                def loss_fn(p):
                    q1 = critic.apply({"params": c1_params}, s,
                                      actor.apply({"params": p}, s))
                    if cfg.prioritized:
                        return -jnp.mean(q1 * is_w[:, None])
                    return -jnp.mean(q1)

                g = jax.grad(loss_fn)(st.actor_params)
                if collect_diag:
                    # recomputed outside the grad — see the q_batch note
                    aloss = loss_fn(st.actor_params)
                    agn = dg.tree_norm(g)
                    hres = jnp.asarray(0.0, jnp.float32)
                upd, opt_state = opt_a.update(g, st.actor_opt,
                                              st.actor_params)
                params = optax.apply_updates(st.actor_params, upd)

            lerp = lambda t, o: jax.tree_util.tree_map(
                lambda a_, b_: cfg.tau * a_ + (1.0 - cfg.tau) * b_, o, t)
            out = (params, opt_state,
                   lerp(st.t_actor_params, params),
                   lerp(st.t1_params, c1_params),
                   lerp(st.t2_params, c2_params))
            if collect_diag:
                # the ADMM path's net step over the whole inner loop; the
                # plain path's single Adam step — both ||new - old||/||old||
                aur = dg.update_ratio(
                    jax.tree_util.tree_map(lambda n_, o_: n_ - o_, params,
                                           st.actor_params),
                    st.actor_params)
                out = out + ((aloss, agn, aur, hres),)
            return out

        def no_actor_update(_):
            out = (st.actor_params, st.actor_opt, st.t_actor_params,
                   st.t1_params, st.t2_params)
            if collect_diag:
                zero = jnp.asarray(0.0, jnp.float32)
                out = out + ((zero, zero, zero, zero),)
            return out

        cond_out = lax.cond(
            counter % cfg.update_actor_interval == 0, actor_update,
            no_actor_update, operand=None)
        (actor_params, actor_opt, t_actor, t1, t2) = cond_out[:5]

        st_new = TD3State(
            actor_params=actor_params, c1_params=c1_params,
            c2_params=c2_params, t_actor_params=t_actor, t1_params=t1,
            t2_params=t2, actor_opt=actor_opt, c1_opt=c1_opt, c2_opt=c2_opt,
            learn_counter=counter, time_step=st.time_step)
        metrics = {"critic_loss": closs, **clip_aux}
        if collect_diag:
            aloss, agn, aur, hres = cond_out[5]
            metrics["diag"] = dg.make_diag(
                critic_loss=closs, actor_loss=aloss,
                critic_grad_norm=dg.tree_norm((g1, g2)),
                actor_grad_norm=agn,
                critic_update_ratio=dg.update_ratio(
                    (u1, u2), (st.c1_params, st.c2_params)),
                actor_update_ratio=aur,
                q_mean=jnp.mean(q_batch), q_min=jnp.min(q_batch),
                q_max=jnp.max(q_batch),
                target_drift=dg.target_drift(c1_params, t1),
                hint_residual=hres)
        return st_new, buf2, metrics

    def no_learn(args):
        st, buf, _ = args
        zeros = {"critic_loss": jnp.asarray(0.0)}
        if cfg.is_clip > 0:
            zeros.update(rp.zero_clip_aux())
        if collect_diag:
            zeros["diag"] = dg.zero_diag()
        return st, buf, zeros

    return lax.cond(buf.cntr >= cfg.batch_size, do_learn, no_learn,
                    (st, buf, key))


class TD3Agent:
    """Host-driven wrapper with the reference Agent API."""

    def __init__(self, cfg: TD3Config, seed: int = 0, name_prefix: str = "",
                 collect_diag: bool = False):
        self.cfg = cfg
        self.key = jax.random.PRNGKey(seed)
        self.key, k0 = jax.random.split(self.key)
        self.state = td3_init(k0, cfg)
        self.buffer = rp.replay_init(
            cfg.mem_size, rp.transition_spec(cfg.obs_dim, cfg.n_actions))
        self.name_prefix = name_prefix
        self.collect_diag = collect_diag
        self._choose = jax.jit(
            lambda st, obs, key: choose_action(cfg, st, obs, key))
        self._learn = jax.jit(lambda st, buf, key: learn(
            cfg, st, buf, key, collect_diag=collect_diag))
        self._add = jax.jit(
            lambda buf, tr, pri: rp.replay_add(buf, tr, priority=pri))
        self.last_metrics = {}
        self.last_diag = None

    def _next_key(self):
        self.key, sub = jax.random.split(self.key)
        return sub

    def choose_action(self, observation):
        obs = jnp.asarray(observation, jnp.float32)
        a, self.state = self._choose(self.state, obs, self._next_key())
        return jax.device_get(a)

    def store_transition(self, state, action, reward, state_, done, hint):
        tr = {"state": state, "action": action, "reward": reward,
              "new_state": state_, "done": done, "hint": hint}
        pri = store_priority(self.cfg, jnp.asarray(reward))
        if pri is None:
            pri = jnp.asarray(1.0)
        self.buffer = self._add(self.buffer, tr, pri)

    def learn(self):
        from smartcal_tpu.obs import costs
        from smartcal_tpu.obs.spans import span

        k = self._next_key()
        # span + cost stage share one '/'-free name so obs_report can
        # join them into the roofline's achieved-FLOPs/s row; the cost
        # analysis is deferred (learn() runs inside the drivers' episode
        # span — TrainObs flushes the AOT compile between episodes)
        with span("agent_update_td3"):
            self.state, self.buffer, m = self._learn(self.state,
                                                     self.buffer, k)
        costs.record_stage_cost("agent_update_td3", self._learn,
                                self.state, self.buffer, k, defer=True)
        self.last_metrics = m
        self.last_diag = m.pop("diag", None)

    def save_models(self, prefix: Optional[str] = None):
        from smartcal_tpu.runtime.atomic import atomic_pickle

        prefix = prefix if prefix is not None else self.name_prefix
        atomic_pickle(jax.device_get(self.state), f"{prefix}td3_state.pkl")
        rp.save_replay(self.buffer, f"{prefix}replaymem_td3.pkl")

    def load_models(self, prefix: Optional[str] = None):
        """Corruption-tolerant resume: warn + keep the fresh init when a
        checkpoint file is missing/truncated (see SACAgent.load_models)."""
        from smartcal_tpu.runtime.atomic import safe_pickle_load

        prefix = prefix if prefix is not None else self.name_prefix
        host = safe_pickle_load(f"{prefix}td3_state.pkl")
        if host is None:
            return False
        self.state = jax.tree_util.tree_map(jnp.asarray, host)
        mem = safe_pickle_load(f"{prefix}replaymem_td3.pkl")
        if mem is not None:
            self.buffer = jax.tree_util.tree_map(jnp.asarray, mem)
        return True
