"""Actor/critic networks (flax.linen).

Architectures follow the reference agents so learning dynamics match:

* MLP actor/critic for the elastic-net workload
  (``elasticnet/enet_sac.py:352-444``): LayerNorm + ELU stacks, state path
  512->256, action path 128->64 concatenated into the Q head; actor
  512->256->128 -> (mu, logsigma) with logsigma clamped to [-20, 2].
* CNN encoder tower for the calibration/demixing workloads
  (``calibration/calib_sac.py:99-118``, ``demixing_rl/demix_sac.py:381-386``):
  Conv(1->16->32->32, kernel 5, stride 2) + norm on the 128x128 influence
  map, merged with a metadata MLP (->128->16).

Weight init mirrors the reference ``init_layer`` (``enet_sac.py:18-21``):
uniform(+-1/sqrt(out_features)) — note the reference scales by
``weight.size()[0]`` which for ``torch.nn.Linear`` is the *output* dimension —
and +-0.003 on final layers.  The reference normalises CNN activations with
BatchNorm; we use GroupNorm (batch-statistics-free, so the jitted train step
stays a pure function — no running-stats side state), which is the standard
JAX-native substitute and behaves identically at batch size O(32).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

LOG_SIG_MIN, LOG_SIG_MAX = -20.0, 2.0
FINAL_INIT_SCALE = 0.003


def _out_dim_uniform(key, shape, dtype=jnp.float32):
    """uniform(+-1/sqrt(out_features)) for kernels (in, out) and biases (out,)."""
    sc = 1.0 / jnp.sqrt(jnp.asarray(shape[-1], jnp.float32))
    return jax.random.uniform(key, shape, dtype, -sc, sc)


def _final_uniform(key, shape, dtype=jnp.float32):
    return jax.random.uniform(key, shape, dtype, -FINAL_INIT_SCALE,
                              FINAL_INIT_SCALE)


def _dense(features, final=False):
    init = _final_uniform if final else _out_dim_uniform
    return nn.Dense(features, kernel_init=init, bias_init=init)


class MLPActor(nn.Module):
    """Gaussian policy head (reference ``ActorNetwork``, enet_sac.py:407-444)."""

    n_actions: int
    hidden: Sequence[int] = (512, 256, 128)

    @nn.compact
    def __call__(self, x) -> Tuple[jnp.ndarray, jnp.ndarray]:
        for h in self.hidden:
            x = _dense(h)(x)
            x = nn.LayerNorm()(x)
            x = nn.elu(x)
        mu = _dense(self.n_actions, final=True)(x)
        logsigma = _dense(self.n_actions, final=True)(x)
        logsigma = jnp.clip(logsigma, LOG_SIG_MIN, LOG_SIG_MAX)
        return mu, logsigma


class MLPCritic(nn.Module):
    """Two-tower Q network (reference ``CriticNetwork``, enet_sac.py:352-394)."""

    state_hidden: Sequence[int] = (512, 256)
    action_hidden: Sequence[int] = (128, 64)

    @nn.compact
    def __call__(self, state, action) -> jnp.ndarray:
        x = state
        for h in self.state_hidden:
            x = _dense(h)(x)
            x = nn.LayerNorm()(x)
            x = nn.elu(x)
        y = action
        for h in self.action_hidden:
            y = _dense(h)(y)
            y = nn.LayerNorm()(y)
            y = nn.elu(y)
        z = jnp.concatenate([x, y], axis=-1)
        return _dense(1, final=True)(z)


class MLPDeterministicActor(nn.Module):
    """Deterministic tanh policy for TD3/DDPG (reference enet_td3.py /
    enet_ddpg.py actor shape: 512->256->128->n_actions, tanh output)."""

    n_actions: int
    hidden: Sequence[int] = (512, 256, 128)

    @nn.compact
    def __call__(self, x) -> jnp.ndarray:
        for h in self.hidden:
            x = _dense(h)(x)
            x = nn.LayerNorm()(x)
            x = nn.elu(x)
        return jnp.tanh(_dense(self.n_actions, final=True)(x))


class InfluenceCNN(nn.Module):
    """Conv tower over a (H, W) influence map.

    Reference: Conv2d(1->16->32->32, kernel 5, stride 2) + BatchNorm
    (``calib_sac.py:99-104``); GroupNorm here (see module docstring).
    Returns a flat feature vector.
    """

    channels: Sequence[int] = (16, 32, 32)

    @nn.compact
    def __call__(self, img) -> jnp.ndarray:
        # img: (..., H, W) -> add channel axis
        x = img[..., None]
        for ch in self.channels:
            x = nn.Conv(ch, kernel_size=(5, 5), strides=(2, 2))(x)
            x = nn.GroupNorm(num_groups=min(8, ch))(x)
            x = nn.elu(x)
        return x.reshape(*x.shape[:-3], -1)


class ImageMetaActor(nn.Module):
    """CNN(map) + MLP(metadata) -> Gaussian policy.

    Reference calibration/demixing actor (``calib_sac.py:155-199``,
    ``demix_sac.py:371-430``): the influence-map CNN features and a
    metadata MLP (->128->16) are merged before the policy head.  When
    ``use_image=False`` the CNN branch is dropped (the demixing_fuzzy
    variant, ``demixing_fuzzy/demix_sac.py:96-135``).
    """

    n_actions: int
    use_image: bool = True
    meta_hidden: Sequence[int] = (128, 16)
    head_hidden: Sequence[int] = (256, 128)

    @nn.compact
    def __call__(self, img, meta) -> Tuple[jnp.ndarray, jnp.ndarray]:
        feats = []
        if self.use_image:
            feats.append(InfluenceCNN()(img))
        m = meta
        for h in self.meta_hidden:
            m = _dense(h)(m)
            m = nn.LayerNorm()(m)
            m = nn.elu(m)
        feats.append(m)
        x = jnp.concatenate(feats, axis=-1)
        for h in self.head_hidden:
            x = _dense(h)(x)
            x = nn.LayerNorm()(x)
            x = nn.elu(x)
        mu = _dense(self.n_actions, final=True)(x)
        logsigma = jnp.clip(_dense(self.n_actions, final=True)(x),
                            LOG_SIG_MIN, LOG_SIG_MAX)
        return mu, logsigma


class ImageMetaCritic(nn.Module):
    """CNN(map) + MLP(metadata) + MLP(action) -> Q value."""

    use_image: bool = True
    meta_hidden: Sequence[int] = (128, 16)
    action_hidden: Sequence[int] = (128, 64)
    head_hidden: Sequence[int] = (256,)

    @nn.compact
    def __call__(self, img, meta, action) -> jnp.ndarray:
        feats = []
        if self.use_image:
            feats.append(InfluenceCNN()(img))
        m = meta
        for h in self.meta_hidden:
            m = _dense(h)(m)
            m = nn.LayerNorm()(m)
            m = nn.elu(m)
        feats.append(m)
        a = action
        for h in self.action_hidden:
            a = _dense(h)(a)
            a = nn.LayerNorm()(a)
            a = nn.elu(a)
        feats.append(a)
        x = jnp.concatenate(feats, axis=-1)
        for h in self.head_hidden:
            x = _dense(h)(x)
            x = nn.LayerNorm()(x)
            x = nn.elu(x)
        return _dense(1, final=True)(x)


class SplitObs(nn.Module):
    """Carve a FLAT observation vector into (img, meta) for the image+meta
    towers.  The dict observations of the radio envs ({'img'/'infmap',
    'sky'/'metadata'}) are flattened at the env-agent boundary
    (``flatten_obs``) so the replay buffer and every agent keep a single
    flat obs array; the network re-splits here."""

    img_shape: Tuple[int, int]

    def split(self, obs):
        h, w = self.img_shape
        img = obs[..., :h * w].reshape(*obs.shape[:-1], h, w)
        meta = obs[..., h * w:]
        return img, meta


class SplitImageMetaActor(SplitObs):
    """ImageMetaActor over a flat obs (Gaussian policy head for SAC)."""

    img_shape: Tuple[int, int] = (128, 128)
    n_actions: int = 1
    use_image: bool = True

    @nn.compact
    def __call__(self, obs):
        img, meta = self.split(obs)
        return ImageMetaActor(self.n_actions, use_image=self.use_image)(
            img, meta)


class SplitImageMetaDeterministicActor(SplitObs):
    """Deterministic tanh variant for TD3/DDPG (reference calib_td3.py)."""

    img_shape: Tuple[int, int] = (128, 128)
    n_actions: int = 1
    use_image: bool = True

    @nn.compact
    def __call__(self, obs):
        img, meta = self.split(obs)
        mu, _ = ImageMetaActor(self.n_actions, use_image=self.use_image)(
            img, meta)
        return jnp.tanh(mu)


class SplitImageMetaCritic(SplitObs):
    """ImageMetaCritic over a flat obs."""

    img_shape: Tuple[int, int] = (128, 128)
    use_image: bool = True

    @nn.compact
    def __call__(self, obs, action):
        img, meta = self.split(obs)
        return ImageMetaCritic(use_image=self.use_image)(img, meta, action)


class SplitImageMetaCategoricalActor(SplitObs):
    """image+meta towers -> one dense vector over a DISCRETE action set.

    As the actor this is the categorical policy (logits) of the distributed
    demixing learner, whose action space is the 2^(K-1) direction subsets
    (``demixing_rl/distributed_per_sac.py:34,180-184``: the reference
    treats the actor output as a probability vector over the subset index
    and samples it with ``np.random.choice``)."""

    img_shape: Tuple[int, int] = (128, 128)
    n_actions: int = 32
    use_image: bool = True
    meta_hidden: Sequence[int] = (128, 16)
    head_hidden: Sequence[int] = (256, 128)

    @nn.compact
    def __call__(self, obs) -> jnp.ndarray:
        img, meta = self.split(obs)
        feats = []
        if self.use_image:
            feats.append(InfluenceCNN()(img))
        m = meta
        for h in self.meta_hidden:
            m = _dense(h)(m)
            m = nn.LayerNorm()(m)
            m = nn.elu(m)
        feats.append(m)
        x = jnp.concatenate(feats, axis=-1)
        for h in self.head_hidden:
            x = _dense(h)(x)
            x = nn.LayerNorm()(x)
            x = nn.elu(x)
        return _dense(self.n_actions, final=True)(x)      # (..., n_actions)


class SplitImageMetaQVector(SplitImageMetaCategoricalActor):
    """Same towers/head, read as a state-only critic: Q(s, .) per discrete
    action — one forward gives every action's value, so the discrete-SAC
    soft value is an exact expectation (no action tower needed)."""


def flatten_obs(obs_dict, img_key=None, meta_key=None):
    """Dict observation -> flat vector [img.ravel(), meta.ravel()].

    Works for both radio envs: CalibEnv {'img', 'sky'} and DemixingEnv
    {'infmap', 'metadata'}."""
    import numpy as np

    if img_key is None:
        img_key = "img" if "img" in obs_dict else "infmap"
    if meta_key is None:
        meta_key = "sky" if "sky" in obs_dict else "metadata"
    return np.concatenate([np.asarray(obs_dict[img_key]).ravel(),
                           np.asarray(obs_dict[meta_key]).ravel()])


def flatten_obs_batch(obs_dict, img_key=None, meta_key=None):
    """Batched :func:`flatten_obs`: dict of (E, ...) stacked observations
    -> (E, obs_dim) flat matrix (the batched radio envs' form; row e is
    exactly ``flatten_obs`` of lane e)."""
    import numpy as np

    if img_key is None:
        img_key = "img" if "img" in obs_dict else "infmap"
    if meta_key is None:
        meta_key = "sky" if "sky" in obs_dict else "metadata"
    img = np.asarray(obs_dict[img_key])
    meta = np.asarray(obs_dict[meta_key])
    E = img.shape[0]
    return np.concatenate([img.reshape(E, -1), meta.reshape(E, -1)],
                          axis=1)


def gaussian_sample(mu, logsigma, key):
    """Tanh-squashed reparameterised sample + log-prob.

    Reference ``sample_normal`` (enet_sac.py:446-466) with max_action=1:
    ``a = tanh(z)``, ``log pi = log N(z; mu, sigma) - log(1 - tanh(z)^2 + 1e-6)``.
    """
    sigma = jnp.exp(logsigma)
    z = mu + sigma * jax.random.normal(key, mu.shape, mu.dtype)
    a = jnp.tanh(z)
    log_probs = (-0.5 * ((z - mu) / sigma) ** 2 - logsigma
                 - 0.5 * jnp.log(2.0 * jnp.pi))
    log_probs = log_probs - jnp.log(1.0 - a ** 2 + 1e-6)
    return a, jnp.sum(log_probs, axis=-1, keepdims=True)


def tanh_gaussian_log_prob_np(mu, logsigma, actions):
    """Host-numpy port of :func:`tanh_gaussian_log_prob`, term for term.

    The serving batch worker evaluates ``behavior_logp`` per completed
    request from the policy heads it already holds on host (the exported
    program returns ``(action, mu, logsigma)``) — paying a jax dispatch
    per lane just to score a log-density would put device round-trips on
    the hot path.  Parity with the jax version is pinned by
    tests/test_lifecycle.py.
    """
    import numpy as np

    mu = np.asarray(mu, np.float64)
    logsigma = np.asarray(logsigma, np.float64)
    a = np.clip(np.asarray(actions, np.float64), -1.0 + 1e-6, 1.0 - 1e-6)
    z = np.arctanh(a)
    sigma = np.exp(logsigma)
    log_probs = (-0.5 * ((z - mu) / sigma) ** 2 - logsigma
                 - 0.5 * np.log(2.0 * np.pi))
    log_probs = log_probs - np.log(1.0 - a ** 2 + 1e-6)
    return np.sum(log_probs, axis=-1)


def tanh_gaussian_log_prob(mu, logsigma, actions):
    """log pi(a|s) of an ALREADY-SQUASHED action under a tanh-gaussian
    policy head — the evaluation counterpart of :func:`gaussian_sample`.

    Inverts the squash (``z = atanh(a)``, clipped away from the
    saturation poles where atanh diverges) and applies the same density
    + change-of-variables correction, so a freshly sampled action
    round-trips to its sampled log-prob up to the atanh(tanh(z))
    reconstruction error.  This is the learner-side half of the
    IMPACT-style clipped importance ratio: the actor stores
    ``behavior_logp`` at sample time, the learner re-evaluates the
    stored action under ITS current parameters with this function.
    """
    a = jnp.clip(actions, -1.0 + 1e-6, 1.0 - 1e-6)
    z = jnp.arctanh(a)
    sigma = jnp.exp(logsigma)
    log_probs = (-0.5 * ((z - mu) / sigma) ** 2 - logsigma
                 - 0.5 * jnp.log(2.0 * jnp.pi))
    log_probs = log_probs - jnp.log(1.0 - a ** 2 + 1e-6)
    return jnp.sum(log_probs, axis=-1)
