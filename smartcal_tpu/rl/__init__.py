from .networks import (  # noqa: F401
    ImageMetaActor,
    ImageMetaCritic,
    InfluenceCNN,
    MLPActor,
    MLPCritic,
    MLPDeterministicActor,
    gaussian_sample,
)
from . import replay  # noqa: F401
from .replay import (  # noqa: F401
    ReplayState,
    replay_add,
    replay_init,
    replay_sample_per,
    replay_sample_uniform,
    replay_update_priorities,
    transition_spec,
)
from .sac import SACAgent, SACConfig, SACState, sac_init  # noqa: F401
from .sac import choose_action as sac_choose_action  # noqa: F401
from .sac import learn as sac_learn  # noqa: F401
from .td3 import TD3Agent, TD3Config, TD3State, td3_init  # noqa: F401
from .td3 import choose_action as td3_choose_action  # noqa: F401
from .td3 import learn as td3_learn  # noqa: F401
from .ddpg import DDPGAgent, DDPGConfig, DDPGState, ddpg_init  # noqa: F401
from .ddpg import choose_action as ddpg_choose_action  # noqa: F401
from .ddpg import learn as ddpg_learn  # noqa: F401
