"""Discrete-action Soft Actor-Critic (categorical policy).

Parity target: the distributed demixing learner's agent
(``demixing_rl/distributed_per_sac.py:34,144,180-184``): actions are the
``2^(K-1)`` direction subsets, the actor emits a probability vector over
the subset index, actors sample it (``np.random.choice(p=probs)``) and
evaluation takes the argmax.  The reference reuses its continuous
``DemixingAgent`` under the hood; here the discrete case gets the standard
discrete-SAC form (the clean re-expression of the same intent):

* actor: categorical logits pi(a|s) (softmax);
* critics: Q(s, .) vectors over all actions (one forward gives every
  action's value, so the soft value is an exact expectation — no
  reparameterised sampling needed);
* targets: V(s') = sum_a pi(a|s') [min_i Q_i(s', a) - alpha log pi(a|s')];
* actor loss: E_s sum_a pi(a|s) [alpha log pi(a|s) - min_i Q_i(s, a)];
* PER priorities from |TD error| as in the continuous agent.

Everything is a pure jitted function over a :class:`DSACState` pytree,
matching the structure of :mod:`smartcal_tpu.rl.sac` so the distributed
runtime can swap agents freely.
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax

from ..obs import diagnostics as dg
from . import replay as rp
from .networks import SplitImageMetaCategoricalActor, SplitImageMetaQVector


@dataclasses.dataclass(frozen=True)
class DSACConfig:
    obs_dim: int
    n_actions: int                 # 2^(K-1) subset configurations
    gamma: float = 0.99
    tau: float = 0.005
    lr_a: float = 1e-3
    lr_c: float = 1e-3
    alpha: float = 0.03
    reward_scale: float = 1.0
    batch_size: int = 64
    mem_size: int = 1024
    prioritized: bool = True       # the reference variant is distributed PER
    error_clip: float = 1.0        # demix_sac.py:160
    img_shape: Optional[Tuple[int, int]] = None
    use_image: bool = True
    # IMPACT staleness-clipped weighting + ERE sampling knob — the
    # categorical twin of sac.SACConfig.is_clip/ere_eta (the importance
    # ratio is pi_now(a|s)/pi_behavior(a|s) from the stored action index
    # and behavior_logp; see impact_weights below)
    is_clip: float = 0.0
    ere_eta: float = 1.0

    def __post_init__(self):
        rp.validate_fleet_knobs(self.is_clip, self.ere_eta)


class DSACState(NamedTuple):
    actor_params: Any
    c1_params: Any
    c2_params: Any
    t1_params: Any
    t2_params: Any
    actor_opt: Any
    c1_opt: Any
    c2_opt: Any
    alpha: jnp.ndarray
    learn_counter: jnp.ndarray


def _nets(cfg: DSACConfig):
    if cfg.img_shape is None:
        raise ValueError("discrete SAC serves the radio dict-obs envs; "
                         "set img_shape (use_image=False drops the CNN)")
    actor = SplitImageMetaCategoricalActor(
        img_shape=cfg.img_shape, n_actions=cfg.n_actions,
        use_image=cfg.use_image)
    critic = SplitImageMetaQVector(
        img_shape=cfg.img_shape, n_actions=cfg.n_actions,
        use_image=cfg.use_image)
    return actor, critic


def transition_spec(obs_dim: int):
    """Replay layout: discrete action stored as a single int32 index."""
    return {
        "state": ((obs_dim,), jnp.float32),
        "action": ((), jnp.int32),
        "reward": ((), jnp.float32),
        "new_state": ((obs_dim,), jnp.float32),
        "done": ((), jnp.bool_),
    }


def dsac_init(key, cfg: DSACConfig) -> DSACState:
    actor, critic = _nets(cfg)
    ka, k1, k2 = jax.random.split(key, 3)
    obs = jnp.zeros((1, cfg.obs_dim))
    actor_params = actor.init(ka, obs)["params"]
    c1_params = critic.init(k1, obs)["params"]
    c2_params = critic.init(k2, obs)["params"]
    return DSACState(
        actor_params=actor_params, c1_params=c1_params, c2_params=c2_params,
        t1_params=jax.tree_util.tree_map(jnp.copy, c1_params),
        t2_params=jax.tree_util.tree_map(jnp.copy, c2_params),
        actor_opt=optax.adam(cfg.lr_a).init(actor_params),
        c1_opt=optax.adam(cfg.lr_c).init(c1_params),
        c2_opt=optax.adam(cfg.lr_c).init(c2_params),
        alpha=jnp.asarray(cfg.alpha, jnp.float32),
        learn_counter=jnp.asarray(0, jnp.int32))


def choose_action(cfg: DSACConfig, st: DSACState, obs, key,
                  deterministic: bool = False):
    """Sample the categorical policy (Actor.choose_action,
    distributed_per_sac.py:155-176; argmax when evaluating)."""
    actor, _ = _nets(cfg)
    logits = actor.apply({"params": st.actor_params}, obs)
    if deterministic:
        return jnp.argmax(logits, axis=-1)
    return jax.random.categorical(key, logits, axis=-1)


def choose_action_logp(cfg: DSACConfig, st: DSACState, obs, key):
    """:func:`choose_action` that also returns ``log pi(a|s)`` of the
    sampled index — the behavior log-prob the fleet actors store per
    transition (same key usage, bitwise the same action)."""
    actor, _ = _nets(cfg)
    logits = actor.apply({"params": st.actor_params}, obs)
    a = jax.random.categorical(key, logits, axis=-1)
    logpi = jax.nn.log_softmax(logits, axis=-1)
    return a, jnp.take_along_axis(logpi, a[..., None], -1)[..., 0]


def impact_weights(cfg: DSACConfig, actor_params, batch: dict,
                   learner_version) -> Tuple[jnp.ndarray, dict]:
    """Clipped categorical importance weights (the discrete twin of
    :func:`smartcal_tpu.rl.sac.impact_weights`): ratio =
    ``pi_now(a|s) / pi_behavior(a|s)`` with the numerator re-evaluated
    under the current actor logits, clipped to ``[1/is_clip, is_clip]``,
    exactly 1.0 at staleness <= 0."""
    actor, _ = _nets(cfg)
    logits = actor.apply({"params": actor_params}, batch["state"])
    logpi = jax.nn.log_softmax(logits, axis=-1)
    lp_now = jnp.take_along_axis(logpi, batch["action"][:, None], -1)[:, 0]
    ratio = jnp.exp(lp_now - batch["behavior_logp"])
    return rp.staleness_clip_weights(ratio, batch["version"],
                                     learner_version, cfg.is_clip)


def learn(cfg: DSACConfig, st: DSACState, buf: rp.ReplayState,
          key, collect_diag: bool = False, learner_version=None
          ) -> Tuple[DSACState, rp.ReplayState, dict]:
    """One discrete-SAC learn step (no-op below batch_size, scannable).

    ``collect_diag`` (python-static) adds ``metrics['diag']`` — an
    :class:`~smartcal_tpu.obs.diagnostics.UpdateDiag`; with it False the
    traced program is the exact pre-diagnostics computation.
    ``cfg.is_clip`` + ``learner_version`` arm the IMPACT weighting,
    ``cfg.ere_eta < 1`` the recency-emphasized sampling (see sac.learn)."""
    actor, critic = _nets(cfg)
    opt_a, opt_c = optax.adam(cfg.lr_a), optax.adam(cfg.lr_c)
    ere = cfg.ere_eta if cfg.ere_eta < 1.0 else None
    rpb = rp.backend_for(buf)              # flat vs mesh-sharded buffer

    def do_learn(args):
        st, buf, key = args
        k_samp, _ = jax.random.split(key)
        if cfg.prioritized:
            batch, idx, is_w, buf2 = rpb.replay_sample_per(
                buf, k_samp, cfg.batch_size, recency_eta=ere)
        elif ere is not None:
            batch, idx = rpb.replay_sample_ere(buf, k_samp, cfg.batch_size,
                                               ere)
            is_w, buf2 = jnp.ones((cfg.batch_size,), jnp.float32), buf
        else:
            batch, idx = rpb.replay_sample_uniform(buf, k_samp,
                                                   cfg.batch_size)
            is_w, buf2 = jnp.ones((cfg.batch_size,), jnp.float32), buf

        clip_aux = {}
        if cfg.is_clip > 0:
            if learner_version is None:
                raise ValueError("cfg.is_clip armed but learn was not "
                                 "given the learner_version")
            w_clip, clip_aux = impact_weights(cfg, st.actor_params, batch,
                                              learner_version)
            is_w = is_w * w_clip

        s, a = batch["state"], batch["action"]
        r = cfg.reward_scale * batch["reward"]
        s2, done = batch["new_state"], batch["done"]

        # soft target value: exact expectation over the action set
        logits2 = actor.apply({"params": st.actor_params}, s2)
        pi2 = jax.nn.softmax(logits2, axis=-1)
        logpi2 = jax.nn.log_softmax(logits2, axis=-1)
        q1t = critic.apply({"params": st.t1_params}, s2)
        q2t = critic.apply({"params": st.t2_params}, s2)
        v2 = jnp.sum(pi2 * (jnp.minimum(q1t, q2t) - st.alpha * logpi2),
                     axis=-1)
        y = lax.stop_gradient(r + cfg.gamma * jnp.where(done, 0.0, v2))

        def critic_loss(c1p, c2p):
            q1 = jnp.take_along_axis(
                critic.apply({"params": c1p}, s), a[:, None], -1)[:, 0]
            q2 = jnp.take_along_axis(
                critic.apply({"params": c2p}, s), a[:, None], -1)[:, 0]
            if cfg.prioritized or cfg.is_clip > 0:
                l = (rp.per_mse(q1[:, None], y[:, None], is_w)
                     + rp.per_mse(q2[:, None], y[:, None], is_w))
            else:
                l = jnp.mean((q1 - y) ** 2) + jnp.mean((q2 - y) ** 2)
            return l, q1

        (closs, q1v), (g1, g2) = jax.value_and_grad(
            critic_loss, argnums=(0, 1), has_aux=True)(st.c1_params,
                                                       st.c2_params)
        u1, c1_opt = opt_c.update(g1, st.c1_opt, st.c1_params)
        c1_params = optax.apply_updates(st.c1_params, u1)
        u2, c2_opt = opt_c.update(g2, st.c2_opt, st.c2_params)
        c2_params = optax.apply_updates(st.c2_params, u2)

        def actor_loss(ap):
            logits = actor.apply({"params": ap}, s)
            pi = jax.nn.softmax(logits, axis=-1)
            logpi = jax.nn.log_softmax(logits, axis=-1)
            qmin = jnp.minimum(critic.apply({"params": c1_params}, s),
                               critic.apply({"params": c2_params}, s))
            return jnp.mean(jnp.sum(
                pi * (st.alpha * logpi - lax.stop_gradient(qmin)), axis=-1))

        aloss, ga = jax.value_and_grad(actor_loss)(st.actor_params)
        if collect_diag:
            # exact categorical entropy, recomputed OUTSIDE the grad so
            # the AD graph (and the update bits) stay identical to the
            # diagnostics-off program; CSE dedupes the forward under jit
            logits_pi = actor.apply({"params": st.actor_params}, s)
            pi_d = jax.nn.softmax(logits_pi, axis=-1)
            logpi_d = jax.nn.log_softmax(logits_pi, axis=-1)
            entropy = -jnp.mean(jnp.sum(pi_d * logpi_d, axis=-1))
        else:
            entropy = None
        ua, actor_opt = opt_a.update(ga, st.actor_opt, st.actor_params)
        actor_params = optax.apply_updates(st.actor_params, ua)

        if cfg.prioritized:
            td = jnp.abs(q1v - y)
            buf2 = rpb.replay_update_priorities(buf2, idx, td,
                                                cfg.error_clip)

        lerp = lambda t, o: jax.tree_util.tree_map(
            lambda a_, b_: cfg.tau * a_ + (1.0 - cfg.tau) * b_, o, t)
        st_new = DSACState(
            actor_params=actor_params, c1_params=c1_params,
            c2_params=c2_params,
            t1_params=lerp(st.t1_params, c1_params),
            t2_params=lerp(st.t2_params, c2_params),
            actor_opt=actor_opt, c1_opt=c1_opt, c2_opt=c2_opt,
            alpha=st.alpha, learn_counter=st.learn_counter + 1)
        metrics = {"critic_loss": closs, "actor_loss": aloss, **clip_aux}
        if collect_diag:
            metrics["diag"] = dg.make_diag(
                critic_loss=closs, actor_loss=aloss,
                critic_grad_norm=dg.tree_norm((g1, g2)),
                actor_grad_norm=dg.tree_norm(ga),
                critic_update_ratio=dg.update_ratio(
                    (u1, u2), (st.c1_params, st.c2_params)),
                actor_update_ratio=dg.update_ratio(ua, st.actor_params),
                q_mean=jnp.mean(q1v), q_min=jnp.min(q1v),
                q_max=jnp.max(q1v),
                target_drift=dg.target_drift(c1_params, st_new.t1_params),
                alpha=st.alpha, entropy=entropy)
        return st_new, buf2, metrics

    def no_learn(args):
        st, buf, _ = args
        zeros = {"critic_loss": jnp.asarray(0.0),
                 "actor_loss": jnp.asarray(0.0)}
        if cfg.is_clip > 0:
            zeros.update(rp.zero_clip_aux())
        if collect_diag:
            zeros["diag"] = dg.zero_diag()
        return st, buf, zeros

    return lax.cond(buf.cntr >= cfg.batch_size, do_learn, no_learn,
                    (st, buf, key))
