"""Replay buffers resident in device memory (HBM).

The reference keeps replay in host numpy ring buffers (``enet_sac.py:23-73``)
and a sequential binary sum tree for prioritized replay
(``enet_sac.py:82-323``).  On TPU both live in HBM as fixed-shape array
pytrees so that store/sample fuse into the jitted training step:

* uniform sampling without replacement — Gumbel-top-k over the filled prefix
  (exact equivalent of ``np.random.choice(max_mem, batch, replace=False)``,
  ``enet_sac.py:48``);
* prioritized sampling — the sum-tree walk (``SumTree.get_leaf``,
  ``enet_sac.py:164-196``) is a prefix-sum search: ``searchsorted(cumsum(p),
  v)`` draws from the identical distribution, and a cumsum over 16k leaves is
  a single vectorised pass on the VPU, vs. the reference's O(log n) *serial*
  pointer chase per sample.  Stratified segments + IS weights + beta annealing
  follow ``PER.sample_buffer`` (``enet_sac.py:270-312``).

Transitions are stored as a dict pytree so dict-observation workloads
(image + metadata, ``calib_sac.py:26-87`` / ``demix_sac.py:310-369``) reuse
the same machinery with extra keys.
"""

from __future__ import annotations

import math
import types
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

# PER constants (reference enet_sac.py:208-212)
PER_EPSILON = 0.01
PER_ALPHA = 0.6
PER_BETA0 = 0.4
PER_BETA_INCREMENT = 1e-4


def priority_from_errors(errors: jnp.ndarray,
                         error_clip: float = 100.0) -> jnp.ndarray:
    """Store-time priority rule min((|e|+eps)^alpha, clip)
    (``PER.store_transition``, enet_sac.py:237-243).  NOTE the deliberate
    asymmetry with :func:`replay_update_priorities`, which follows the
    reference's ``batch_update`` in clipping the ERROR before the exponent
    (enet_sac.py:314-323)."""
    errors = jnp.asarray(errors, jnp.float32)
    return jnp.minimum((jnp.abs(errors) + PER_EPSILON) ** PER_ALPHA,
                       error_clip)


class ReplayState(NamedTuple):
    data: dict                 # field -> (size, ...) arrays
    cntr: jnp.ndarray          # () int32 total stores
    priority: jnp.ndarray      # (size,) — all-ones for uniform buffers
    beta: jnp.ndarray          # () PER beta (unused for uniform)

    @property
    def size(self) -> int:
        return next(iter(self.data.values())).shape[0]


def _zeros_like_spec(size, spec):
    return {k: jnp.zeros((size,) + tuple(shape), dtype)
            for k, (shape, dtype) in spec.items()}


def transition_spec(obs_dim: int, n_actions: int) -> dict:
    """Flat-observation transition layout (reference enet_sac.py:27-32)."""
    return {
        "state": ((obs_dim,), jnp.float32),
        "new_state": ((obs_dim,), jnp.float32),
        "action": ((n_actions,), jnp.float32),
        "reward": ((), jnp.float32),
        "done": ((), jnp.bool_),
        "hint": ((n_actions,), jnp.float32),
    }


def versioned_spec(spec: dict) -> dict:
    """``spec`` extended with the async-fleet provenance fields:
    ``version`` (the policy-snapshot version the acting actor held — the
    learner's staleness currency) and ``behavior_logp`` (log pi_behavior
    of the stored action at sample time, the denominator of the
    IMPACT-style clipped importance ratio).  ``replay_add_batch`` writes
    only the keys the buffer was initialised with, so versioned buffers
    and plain buffers share every other code path."""
    return {**spec, "version": ((), jnp.int32),
            "behavior_logp": ((), jnp.float32)}


def backend_for(buf: object) -> "types.ModuleType":
    """The replay module implementing ``buf``'s layout: this module for
    the flat single-device :class:`ReplayState`, the mesh-sharded twin
    (:mod:`smartcal_tpu.rl.replay_sharded`) for its
    ``ShardedReplayState``.  Both expose the same store/sample/update
    function names, so the agents' fused learn steps dispatch on buffer
    type with one call (the choice is python-static under jit — the
    buffer's pytree TYPE, not a traced value)."""
    import sys

    from . import replay_sharded as rps

    if isinstance(buf, rps.ShardedReplayState):
        return rps
    return sys.modules[__name__]


def replay_init(size: int, spec: dict) -> ReplayState:
    return ReplayState(
        data=_zeros_like_spec(size, spec),
        cntr=jnp.asarray(0, jnp.int32),
        priority=jnp.zeros((size,), jnp.float32),
        beta=jnp.asarray(PER_BETA0, jnp.float32),
    )


def replay_add(buf: ReplayState, transition: dict,
               priority: Optional[jnp.ndarray] = None,
               error: Optional[jnp.ndarray] = None,
               error_clip: float = 100.0) -> ReplayState:
    """Store one transition at ``cntr % size``.

    Priority-on-store follows ``PER.store_transition`` (enet_sac.py:237-243):
    with ``error`` given, ``min((|e|+eps)^alpha, clip)``; otherwise the max
    current priority (or ``clip`` when the buffer is untouched).  Uniform
    buffers simply pass ``priority=1``.
    """
    idx = buf.cntr % buf.size
    data = {k: v.at[idx].set(jnp.asarray(transition[k], v.dtype))
            for k, v in buf.data.items()}
    if priority is None:
        if error is None:
            pmax = jnp.max(buf.priority)
            priority = jnp.where(pmax == 0.0, error_clip, pmax)
        else:
            priority = priority_from_errors(error, error_clip)
    return ReplayState(
        data=data,
        cntr=buf.cntr + 1,
        priority=buf.priority.at[idx].set(jnp.asarray(priority, jnp.float32)),
        beta=buf.beta,
    )


def replay_add_batch(buf: ReplayState, transitions: dict,
                     priority: Optional[jnp.ndarray] = None,
                     errors: Optional[jnp.ndarray] = None,
                     error_clip: float = 100.0) -> ReplayState:
    """Store a leading-axis batch of transitions at consecutive ring slots.

    TPU-native extension for synchronous parallel actors (the reference
    ingests actor buffers transition-by-transition under a lock,
    ``distributed_per_sac.py:44-57``); one scatter stores the whole batch.
    Priorities follow ``replay_add``'s rules: explicit ``priority`` wins,
    else per-transition ``errors`` -> ``min((|e|+eps)^alpha, clip)``, else
    the max current priority (clip when the buffer is untouched).
    """
    B = next(iter(transitions.values())).shape[0]
    idx = (buf.cntr + jnp.arange(B)) % buf.size
    data = {k: v.at[idx].set(jnp.asarray(transitions[k], v.dtype))
            for k, v in buf.data.items()}
    if priority is None:
        if errors is None:
            pmax = jnp.max(buf.priority)
            priority = jnp.full((B,), jnp.where(pmax == 0.0, error_clip,
                                                pmax))
        else:
            priority = priority_from_errors(errors, error_clip)
    else:
        priority = jnp.broadcast_to(jnp.asarray(priority, jnp.float32), (B,))
    return ReplayState(
        data=data,
        cntr=buf.cntr + B,
        priority=buf.priority.at[idx].set(priority),
        beta=buf.beta,
    )


def _filled(buf: ReplayState):
    return jnp.minimum(buf.cntr, buf.size)


def replay_sample_uniform(buf: ReplayState, key: jnp.ndarray,
                          batch_size: int) -> "tuple[dict, jnp.ndarray]":
    """Uniform sample w/o replacement over the filled prefix.

    Gumbel-top-k: add iid Gumbel noise to a 0/-inf mask and take the top
    ``batch_size`` — an exact draw of a uniform subset of the filled slots,
    with traced fill count (``np.random.choice(..., replace=False)`` needs a
    concrete size; this doesn't).
    """
    n = buf.size
    filled = _filled(buf)
    g = jax.random.gumbel(key, (n,))
    score = jnp.where(jnp.arange(n) < filled, g, -jnp.inf)
    _, idx = jax.lax.top_k(score, batch_size)
    batch = {k: v[idx] for k, v in buf.data.items()}
    return batch, idx


def replay_sample_per(
        buf: ReplayState, key: jnp.ndarray, batch_size: int,
        recency_eta: Optional[float] = None,
) -> "tuple[dict, jnp.ndarray, jnp.ndarray, ReplayState]":
    """Stratified priority sampling + IS weights (enet_sac.py:270-312).

    ``recency_eta`` (python-static; None/1.0 = off) modulates the
    sampling distribution by the emphasizing-recent-experience weights
    (:func:`ere_weights`): the effective priority is ``p_i * eta_w_i``,
    and the IS correction is computed against the distribution actually
    sampled from, so PER and ERE compose without bias bookkeeping.

    Returns ``(batch, idx, is_weights, new_buf)`` — ``new_buf`` carries the
    annealed beta.
    """
    priority = buf.priority
    if recency_eta is not None and recency_eta < 1.0:
        priority = priority * ere_weights(buf, recency_eta)
    csum = jnp.cumsum(priority)
    total = csum[-1]
    beta = jnp.minimum(1.0, buf.beta + PER_BETA_INCREMENT)

    seg = total / batch_size
    u = jax.random.uniform(key, (batch_size,))
    values = (jnp.arange(batch_size) + u) * seg
    idx = jnp.searchsorted(csum, values, side="left")
    idx = jnp.clip(idx, 0, buf.size - 1)

    p = priority[idx]
    probs = p / total
    is_w = (batch_size * probs) ** (-beta)
    is_w = is_w / jnp.max(is_w)

    batch = {k: v[idx] for k, v in buf.data.items()}
    return batch, idx, is_w.astype(jnp.float32), buf._replace(beta=beta)


# exponent span of the ERE recency weighting: the oldest filled slot is
# down-weighted by eta**ERE_SPAN relative to the newest, independent of
# the buffer fill level (so the knob's strength does not drift as the
# ring fills)
ERE_SPAN = 100.0


def ere_weights(buf: ReplayState, eta: float) -> jnp.ndarray:
    """Emphasizing-recent-experience weights over the ring slots
    (Wang & Ross, arXiv:1906.04009, re-expressed as a stateless
    per-slot weighting so it fuses into the jitted sample step).

    Slot weight = ``eta ** (ERE_SPAN * age / (filled-1))`` with age the
    write recency (0 = newest) — a smooth device-side stand-in for the
    paper's shrinking-window schedule.  ``eta=1`` gives exactly uniform
    weights (the identity knob); unfilled slots weigh 0.
    """
    n = buf.size
    filled = _filled(buf)
    slots = jnp.arange(n)
    ages = jnp.mod(buf.cntr - 1 - slots, jnp.maximum(n, 1))
    x = ages.astype(jnp.float32) / jnp.maximum(filled - 1, 1)
    w = jnp.asarray(eta, jnp.float32) ** (ERE_SPAN * x)
    return jnp.where(slots < filled, w, 0.0)


def replay_sample_ere(buf: ReplayState, key: jnp.ndarray,
                      batch_size: int,
                      eta: float) -> "tuple[dict, jnp.ndarray]":
    """Recency-weighted sampling for UNIFORM buffers (the ERE knob of the
    async fleet's device-resident replay path; prioritized buffers get
    the same knob through ``replay_sample_per(recency_eta=...)``).

    Stratified draw (with replacement) from the :func:`ere_weights`
    distribution — at ``eta=1`` the weights are uniform over the filled
    prefix.  Returns ``(batch, idx)``; following the ERE paper, no IS
    correction is applied on the uniform path.
    """
    w = ere_weights(buf, eta)
    csum = jnp.cumsum(w)
    total = csum[-1]
    seg = total / batch_size
    u = jax.random.uniform(key, (batch_size,))
    values = (jnp.arange(batch_size) + u) * seg
    idx = jnp.searchsorted(csum, values, side="left")
    idx = jnp.clip(idx, 0, buf.size - 1)
    batch = {k: v[idx] for k, v in buf.data.items()}
    return batch, idx


def replay_update_priorities(buf: ReplayState, idx: jnp.ndarray,
                             errors: jnp.ndarray,
                             error_clip: float = 100.0) -> ReplayState:
    """``batch_update`` (enet_sac.py:314-323): p = min(|e|+eps, clip)^alpha."""
    clipped = jnp.minimum(jnp.abs(errors) + PER_EPSILON, error_clip)
    return buf._replace(
        priority=buf.priority.at[idx].set(clipped ** PER_ALPHA))


def staleness_clip_weights(raw: jnp.ndarray, versions: jnp.ndarray,
                           learner_version: jnp.ndarray,
                           clip_c: float) -> jnp.ndarray:
    """The staleness-gated clipped-weight core shared by the agents'
    IMPACT-style weightings (``sac.impact_weights``, the discrete twin,
    ``td3.staleness_weights``): clip the raw per-transition weight to
    ``[1/clip_c, clip_c]`` and gate to EXACTLY 1.0 at staleness <= 0 —
    the bit-identity contract every agent shares.

    ``raw`` is the unclipped weight per transition (a policy ratio), or
    a callable ``raw(staleness)`` for weights that are functions of the
    staleness itself (TD3's exponential decay).  Returns ``(weights,
    aux)`` with the shared staleness/saturation telemetry scalars
    (``is_clip_saturation`` = fraction of STALE transitions whose raw
    weight hit a clip bound)."""
    stale = (jnp.asarray(learner_version, jnp.int32)
             - jnp.asarray(versions, jnp.int32)).astype(jnp.float32)
    if callable(raw):
        raw = raw(stale)
    is_stale = stale > 0
    lo, hi = 1.0 / clip_c, clip_c
    w = jnp.where(is_stale, jnp.clip(raw, lo, hi), 1.0)
    n_stale = jnp.maximum(jnp.sum(is_stale.astype(jnp.float32)), 1.0)
    saturated = is_stale & ((raw >= hi) | (raw <= lo))
    aux = {
        "staleness_mean": jnp.mean(stale),
        "is_clip_mean": jnp.mean(w),
        "is_clip_saturation": jnp.sum(saturated.astype(jnp.float32))
        / n_stale,
    }
    return w, aux


def zero_clip_aux() -> dict:
    """The no-learn branch's counterpart of the ``staleness_clip_weights``
    aux dict (identity weights, nothing stale)."""
    return {"staleness_mean": jnp.asarray(0.0),
            "is_clip_mean": jnp.asarray(1.0),
            "is_clip_saturation": jnp.asarray(0.0)}


def validate_fleet_knobs(is_clip: float, ere_eta: float,
                         replay_backend: str = "hbm") -> None:
    """Config-time validation of the async-fleet knobs, shared by the
    agent configs' ``__post_init__``.  Rejects the native sum-tree
    backend combinations outright: ERE and the IS-clip live in the fused
    device-resident sample/learn step, which the native host-side
    sampler never runs — silently ignoring the knob (ERE) or failing at
    the first learn step (is_clip) would be worse than refusing here."""
    if is_clip != 0.0 and is_clip < 1.0:
        raise ValueError(
            f"is_clip must be 0 (off) or >= 1, got {is_clip}")
    if not 0.0 < ere_eta <= 1.0:
        raise ValueError(f"ere_eta must be in (0, 1], got {ere_eta}")
    if replay_backend == "native" and (is_clip > 0 or ere_eta < 1.0):
        raise ValueError(
            "is_clip/ere_eta are features of the device-resident (hbm) "
            "replay path; the native sum-tree backend does not apply "
            "them — use replay_backend='hbm'")


def per_mse(expected: jnp.ndarray, targets: jnp.ndarray,
            is_weights: jnp.ndarray) -> jnp.ndarray:
    """IS-weighted MSE (reference ``PER.mse``, enet_sac.py:326-329)."""
    td = expected - targets
    w = is_weights.reshape(is_weights.shape + (1,) * (td.ndim - 1))
    return jnp.sum(w * td * td) / td.size


def _health_from_arrays(p, cntr: int, size: int, beta: float,
                        n_age_bins: int = 4) -> dict:
    """Shared replay-health math over a host priority array (the filled
    prefix); see :func:`replay_health` for the field meanings."""
    filled = int(min(cntr, size))
    out = {"filled": filled, "cntr": int(cntr), "size": int(size),
           "beta": float(beta)}
    if filled == 0:
        return out
    p = np.asarray(p[:filled], np.float64)
    total = float(p.sum())
    out["priority_total"] = total
    out["priority_max"] = float(p.max())
    if total <= 0.0:
        # degenerate all-zero distribution (the pmax-fallback edge the
        # first store repairs); entropy/weights are undefined — report
        # the collapse explicitly instead of dividing by zero
        out["priority_entropy"] = 0.0
        out["max_mean_priority_ratio"] = 0.0
        return out
    probs = p / total
    nz = probs[probs > 0]
    h = float(-(nz * np.log(nz)).sum())
    # normalized to [0, 1]: 1 = uniform sampling, ->0 = a handful of
    # transitions own the whole priority mass (Actor-PER's collapse axis)
    out["priority_entropy"] = (h / math.log(filled) if filled > 1 else 1.0)
    out["max_mean_priority_ratio"] = float(p.max() / p.mean())
    # IS-weight extremes at the CURRENT beta (unnormalized, filled*prob
    # form): their ratio is the spread the per_mse weighting must absorb
    w = (filled * np.maximum(probs, 1e-12)) ** (-float(beta))
    out["is_weight_min"] = float(w.min())
    out["is_weight_max"] = float(w.max())
    # sample-age profile: slot i was written at the latest t < cntr with
    # t % size == i, so age = (cntr - 1 - i) mod size — and the
    # priority-weighted mean age vs the uniform mean exposes age skew
    # (stale transitions hoarding priority mass)
    ages = (int(cntr) - 1 - np.arange(filled)) % max(size, 1)
    out["age_mean_uniform"] = float(ages.mean())
    out["age_mean_weighted"] = float((probs * ages).sum())
    edges = np.linspace(0, max(float(ages.max()), 1.0), n_age_bins + 1)
    which = np.minimum(np.searchsorted(edges, ages, side="right") - 1,
                       n_age_bins - 1)
    out["age_priority_hist"] = [round(float(probs[which == b].sum()), 6)
                                for b in range(n_age_bins)]
    return out


def replay_health(buf: ReplayState) -> dict:
    """Host-side PER/replay distribution summary for telemetry.

    One device->host pull of the priority vector (call at train-block
    cadence, not per step).  Fields: ``priority_entropy`` (normalized,
    1 = uniform), ``max_mean_priority_ratio``, ``is_weight_min/max`` at
    the current beta, ``beta``, fill counters, and a sample-age profile —
    uniform vs priority-weighted mean age plus ``age_priority_hist``
    (priority mass per age quartile, young to old).  Uniform buffers
    report trivially healthy numbers (entropy 1, ratio 1)."""
    return _health_from_arrays(np.asarray(jax.device_get(buf.priority)),
                               int(jax.device_get(buf.cntr)), buf.size,
                               float(jax.device_get(buf.beta)))


def save_replay(buf: ReplayState, path: str) -> None:
    """Whole-buffer checkpoint (reference pickles the object, :59-73);
    atomic (tmp + os.replace) so a mid-write kill cannot truncate it."""
    from smartcal_tpu.runtime.atomic import atomic_pickle

    atomic_pickle(jax.device_get(buf), path)


def load_replay(path: str) -> ReplayState:
    from smartcal_tpu.runtime.atomic import strict_pickle_load

    host = strict_pickle_load(path)
    return jax.tree_util.tree_map(jnp.asarray, host)


def merge_from_buffer(dst: ReplayState, src_host: dict,
                      n: int) -> ReplayState:
    """Learner-side bulk ingestion of an actor's host buffer
    (reference ``store_transition_from_buffer``, enet_sac.py:254-268):
    transitions enter one by one with max-priority initialisation."""
    buf = dst
    for i in range(n):
        t = {k: np.asarray(v[i]) for k, v in src_host.items()}
        buf = replay_add(buf, t)
    return buf
