"""Soft Actor-Critic as pure jitted functions over a state pytree.

Re-expresses the reference SAC agent (``elasticnet/enet_sac.py:478-658``;
CNN variants ``calibration/calib_sac.py``, ``demixing_rl/demix_sac.py``):

* the torch modules + per-module Adam optimizers + in-place soft target
  updates become a :class:`SACState` pytree and one jitted ``learn`` step;
* the hint-constrained actor loss — augmented-Lagrangian penalty
  ``0.5 rho_admm g^2 + rho g`` with ``g = max(0, mse(a, hint) - thresh)^2``
  and dual ascent ``rho += rho_admm g`` every 10 learn calls
  (``enet_sac.py:600-617``) — is carried in the state (``rho``);
* optional learned temperature vs. target entropy (``enet_sac.py:506-513,
  608-613``);
* replay (uniform or prioritized) lives in HBM and its sample/update fuses
  into the same jitted step (see :mod:`smartcal_tpu.rl.replay`); the
  ``mem_cntr < batch_size`` early-return (``enet_sac.py:556-557``) becomes a
  ``lax.cond`` no-op so the whole trainer can live inside ``lax.scan``.

KLD-vs-MSE hint distance: the calibration/demixing agents measure the
actor-hint mismatch with a KL divergence on softmaxed vectors
(``calib_sac.py:361-366``, ``demix_sac.py:636-641``); select with
``hint_distance='kld'``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax

from ..obs import diagnostics as dg
from . import replay as rp
from .networks import (MLPActor, MLPCritic, SplitImageMetaActor,
                       SplitImageMetaCritic, gaussian_sample)


@dataclasses.dataclass(frozen=True)
class SACConfig:
    obs_dim: int
    n_actions: int
    gamma: float = 0.99
    tau: float = 0.005
    lr_a: float = 1e-3
    lr_c: float = 1e-3
    alpha: float = 0.03           # entropy temperature (enet main_sac.py:36)
    reward_scale: float = 20.0    # reference reward_scale=N
    batch_size: int = 64
    mem_size: int = 1024
    use_hint: bool = False
    hint_threshold: float = 0.1   # enet_sac.py:514
    admm_rho: float = 0.01        # enet_sac.py:516
    hint_distance: str = "mse"    # 'mse' | 'kld'
    learn_alpha: bool = False
    alpha_lr: float = 1e-4
    # 'reference': clamped SGD directly on alpha, exactly the reference rule
    #   alpha = max(0, alpha + alpha_lr*mean(target_entropy + logpi))
    #   starting from the ``alpha`` argument (enet_sac.py:500,613).
    # 'sac_v2': Adam on log_alpha with alpha = exp(log_alpha) starting at 1
    #   — a DELIBERATE DEVIATION from the reference (no log_alpha/Adam exists
    #   there); kept because it cannot collapse to alpha=0 and is the
    #   standard Haarnoja et al. v2 formulation.
    alpha_rule: str = "reference"
    prioritized: bool = False
    error_clip: float = 100.0     # PER absolute_error_upper (enet_sac.py:212)
    # PER backend (measured both ways, results/per_bench.json): 'hbm' =
    # fused device prefix-sum — sample + learn + priority update in ONE
    # jitted step, the default whenever an accelerator is present (no
    # host<->device hop per learn; scan-able).  'native' = host C++ sum
    # tree + learn_from_batch — wins on no-accelerator hosts (CPU e2e
    # 0.49x the fused step's wall; the O(log n) walk beats a 16k cumsum
    # on one core) and suits host-driven ingestion loops or payloads too
    # large for HBM.  Chip-regime e2e capture: tools/chip_session.sh.
    replay_backend: str = "hbm"
    # dict-obs (radio) variants: when img_shape is set, obs_dim must equal
    # H*W + meta_dim and the CNN+metadata towers are used (calib_sac.py,
    # demix_sac.py); use_image=False drops the CNN branch (demixing_fuzzy)
    img_shape: Optional[Tuple[int, int]] = None
    use_image: bool = True
    # IMPACT-style staleness-clipped importance weighting for the async
    # actor-learner fleet (arXiv:1912.00167): 0 = off; c >= 1 arms it —
    # transitions must then carry 'version'/'behavior_logp'
    # (replay.versioned_spec) and learn() must be given the learner's
    # policy version.  The critic TD loss is weighted by
    # clip(pi_now(a|s)/pi_behavior(a|s), 1/c, c) for STALE transitions;
    # same-version transitions get weight exactly 1.0, so a zero-staleness
    # run is bit-identical to the unweighted path (tested).
    is_clip: float = 0.0
    # emphasizing-recent-experience sampling knob (replay.ere_weights):
    # 1.0 = off (uniform/PER unchanged); eta < 1 biases the device-side
    # sample step toward recent slots
    ere_eta: float = 1.0

    def __post_init__(self):
        if self.alpha_rule not in ("reference", "sac_v2"):
            raise ValueError(
                f"alpha_rule must be 'reference' or 'sac_v2', got "
                f"{self.alpha_rule!r}")
        if self.replay_backend not in ("hbm", "native"):
            raise ValueError(
                f"replay_backend must be 'hbm' or 'native', got "
                f"{self.replay_backend!r}")
        rp.validate_fleet_knobs(self.is_clip, self.ere_eta,
                                self.replay_backend)


class SACState(NamedTuple):
    actor_params: Any
    c1_params: Any
    c2_params: Any
    t1_params: Any
    t2_params: Any
    actor_opt: Any
    c1_opt: Any
    c2_opt: Any
    alpha: jnp.ndarray
    rho: jnp.ndarray            # hint-constraint dual variable
    learn_counter: jnp.ndarray
    log_alpha: Any = None       # learned-temperature parameter + its Adam
    alpha_opt: Any = None       # state (reference enet_sac.py:506-510)


def _nets(cfg: SACConfig):
    if cfg.img_shape is not None:
        return (SplitImageMetaActor(img_shape=cfg.img_shape,
                                    n_actions=cfg.n_actions,
                                    use_image=cfg.use_image),
                SplitImageMetaCritic(img_shape=cfg.img_shape,
                                     use_image=cfg.use_image))
    return MLPActor(cfg.n_actions), MLPCritic()


def sac_init(key, cfg: SACConfig) -> SACState:
    actor, critic = _nets(cfg)
    ka, k1, k2 = jax.random.split(key, 3)
    obs = jnp.zeros((1, cfg.obs_dim))
    act = jnp.zeros((1, cfg.n_actions))
    actor_params = actor.init(ka, obs)["params"]
    c1_params = critic.init(k1, obs, act)["params"]
    c2_params = critic.init(k2, obs, act)["params"]
    opt_a = optax.adam(cfg.lr_a)
    opt_c = optax.adam(cfg.lr_c)
    # learned temperature: under the 'reference' rule alpha itself is the
    # optimized variable, initialized from the alpha argument
    # (enet_sac.py:500) and updated by clamped SGD (enet_sac.py:613); the
    # log_alpha/Adam pair below is only used by the 'sac_v2' deviation,
    # where alpha starts at exp(0) = 1.
    log_alpha = jnp.asarray(0.0, jnp.float32)
    if cfg.learn_alpha and cfg.alpha_rule == "sac_v2":
        alpha0 = 1.0
    else:
        alpha0 = cfg.alpha
    return SACState(
        actor_params=actor_params,
        c1_params=c1_params,
        c2_params=c2_params,
        t1_params=jax.tree_util.tree_map(jnp.copy, c1_params),
        t2_params=jax.tree_util.tree_map(jnp.copy, c2_params),
        actor_opt=opt_a.init(actor_params),
        c1_opt=opt_c.init(c1_params),
        c2_opt=opt_c.init(c2_params),
        alpha=jnp.asarray(alpha0, jnp.float32),
        rho=jnp.asarray(0.0, jnp.float32),
        learn_counter=jnp.asarray(0, jnp.int32),
        log_alpha=log_alpha,
        alpha_opt=optax.adam(cfg.alpha_lr).init(log_alpha),
    )


def choose_action(cfg: SACConfig, st: SACState, obs, key,
                  deterministic: bool = False):
    """Sample an action (reference ``choose_action``, enet_sac.py:547-553)."""
    actor, _ = _nets(cfg)
    mu, logsigma = actor.apply({"params": st.actor_params}, obs)
    if deterministic:
        return jnp.tanh(mu)
    a, _ = gaussian_sample(mu, logsigma, key)
    return a


def policy_apply(cfg: SACConfig, actor_params, obs):
    """Deterministic policy head only — ``tanh(mu)`` from the actor
    params (the SERVING forward: no sampling key, no critic/optimizer
    state, so the AOT export closes over nothing but the net shape)."""
    actor, _ = _nets(cfg)
    mu, _ = actor.apply({"params": actor_params}, obs)
    return jnp.tanh(mu)


def policy_heads(cfg: SACConfig, actor_params, obs):
    """:func:`policy_apply` that ALSO returns the distribution heads:
    ``(tanh(mu), mu, logsigma)``.

    The lifecycle server exports THIS forward so the batch worker can
    score ``behavior_logp`` of whatever action was actually taken
    (policy or pinned rho) under the snapshot that acted — host-side via
    :func:`~smartcal_tpu.rl.networks.tanh_gaussian_log_prob_np` — without
    a second device dispatch.  Same export contract as ``policy_apply``:
    no sampling key, nothing closed over but the net shape, and
    ``actor_params`` is a traced operand, so ONE exported executable
    serves every weight version (the zero-compile hot-swap hinge)."""
    actor, _ = _nets(cfg)
    mu, logsigma = actor.apply({"params": actor_params}, obs)
    return jnp.tanh(mu), mu, logsigma


def choose_action_logp(cfg: SACConfig, st: SACState, obs, key):
    """:func:`choose_action` that ALSO returns ``log pi(a|s)`` (shape
    ``obs.shape[:-1]``) — the behavior log-prob the fleet actors store
    per transition for the IMPACT importance ratio.  Same key usage as
    ``choose_action``, so the sampled action is bitwise the one the
    plain path would have drawn."""
    actor, _ = _nets(cfg)
    mu, logsigma = actor.apply({"params": st.actor_params}, obs)
    a, lp = gaussian_sample(mu, logsigma, key)
    return a, lp[..., 0]


def impact_weights(cfg: SACConfig, actor_params, batch: dict,
                   learner_version) -> Tuple[jnp.ndarray, dict]:
    """Clipped importance weights for a versioned batch (IMPACT,
    arXiv:1912.00167 eq. 2, adapted to one-step TD).

    Ratio = ``pi_now(a|s) / pi_behavior(a|s)`` with the numerator
    re-evaluated under the CURRENT actor parameters
    (:func:`~smartcal_tpu.rl.networks.tanh_gaussian_log_prob`) and the
    denominator the stored ``behavior_logp``; clipped to
    ``[1/is_clip, is_clip]``.  Transitions whose ``version`` matches (or
    exceeds) ``learner_version`` get weight EXACTLY 1.0 — the staleness-0
    bit-identity contract.  Returns ``(weights, aux)`` with aux carrying
    the staleness / clip-saturation telemetry scalars.
    """
    from .networks import tanh_gaussian_log_prob

    actor, _ = _nets(cfg)
    mu, logsigma = actor.apply({"params": actor_params}, batch["state"])
    lp_now = tanh_gaussian_log_prob(mu, logsigma, batch["action"])
    ratio = jnp.exp(lp_now - batch["behavior_logp"])
    return rp.staleness_clip_weights(ratio, batch["version"],
                                     learner_version, cfg.is_clip)


def _hint_gap(cfg: SACConfig, actions, hints):
    """g = max(0, D(a, hint) - thresh)^2 with D mse or kld.

    MSE form: enet_sac.py:601; KLD form: calib_sac.py:361-366 (softmax both,
    sum p log p/q)."""
    if cfg.hint_distance == "kld":
        p = jax.nn.softmax(hints, axis=-1)
        q = jax.nn.softmax(actions, axis=-1)
        d = jnp.mean(jnp.sum(p * (jnp.log(p + 1e-9) - jnp.log(q + 1e-9)),
                             axis=-1))
    else:
        d = jnp.mean((actions - hints) ** 2)
    return jnp.maximum(0.0, d - cfg.hint_threshold) ** 2


def learn_from_batch(cfg: SACConfig, st: SACState, batch: dict, is_w,
                     key, collect_diag: bool = False, learner_version=None
                     ) -> Tuple[SACState, dict]:
    """The SAC learn core on an ALREADY-SAMPLED batch.

    The integration point for external replay backends (the host-side
    native sum tree of :mod:`smartcal_tpu.rl.replay_native`, the
    distributed learner's ingestion stream): callers sample wherever the
    priorities live, run this jitted core, then push ``metrics['td']``
    (|Q1 - y| per transition) back into their priority store.
    :func:`learn` wraps it with the fused HBM replay sample/update.

    ``collect_diag`` (python-static, same contract as the solver's
    ``collect_stats``) additionally returns ``metrics['diag']`` — an
    :class:`~smartcal_tpu.obs.diagnostics.UpdateDiag` of per-update
    health scalars computed from intermediates the step already holds.
    With it False the traced program is the exact pre-diagnostics
    computation (bit-identical outputs, tested).

    ``learner_version`` (traced int, required when ``cfg.is_clip`` is
    armed) drives the IMPACT staleness-clipped weighting
    (:func:`impact_weights`): the critic TD loss is importance-weighted
    per transition, same-version transitions at exactly 1.0.
    """
    actor, critic = _nets(cfg)
    opt_a = optax.adam(cfg.lr_a)
    opt_c = optax.adam(cfg.lr_c)
    k_next, k_pi, k_dual = jax.random.split(key, 3)
    s = batch["state"]
    a = batch["action"]
    r = cfg.reward_scale * batch["reward"][:, None]
    s2 = batch["new_state"]
    done = batch["done"][:, None]
    hint = batch["hint"]

    clip_aux = {}
    if cfg.is_clip > 0:
        if learner_version is None:
            raise ValueError("cfg.is_clip armed but learn_from_batch was "
                             "not given the learner_version")
        w_clip, clip_aux = impact_weights(cfg, st.actor_params, batch,
                                          learner_version)
        # fold into the PER IS weights: with every transition at the
        # learner's version w_clip is exactly 1.0 and is_w * 1.0 is
        # bitwise is_w — the staleness-0 identity contract
        is_w = is_w * w_clip

    # --- target value (enet_sac.py:569-575)
    mu2, ls2 = actor.apply({"params": st.actor_params}, s2)
    a2, lp2 = gaussian_sample(mu2, ls2, k_next)
    q1t = critic.apply({"params": st.t1_params}, s2, a2)
    q2t = critic.apply({"params": st.t2_params}, s2, a2)
    min_t = jnp.minimum(q1t, q2t) - st.alpha * lp2
    y = r + cfg.gamma * jnp.where(done, 0.0, min_t)
    y = lax.stop_gradient(y)

    # --- critic update (enet_sac.py:577-587)
    def critic_loss(c1p, c2p):
        q1 = critic.apply({"params": c1p}, s, a)
        q2 = critic.apply({"params": c2p}, s, a)
        if cfg.prioritized or cfg.is_clip > 0:
            l = rp.per_mse(q1, y, is_w) + rp.per_mse(q2, y, is_w)
        else:
            l = jnp.mean((q1 - y) ** 2) + jnp.mean((q2 - y) ** 2)
        return l, (q1, q2)

    (closs, (q1, q2)), (g1, g2) = jax.value_and_grad(
        critic_loss, argnums=(0, 1), has_aux=True)(st.c1_params,
                                                   st.c2_params)
    u1, c1_opt = opt_c.update(g1, st.c1_opt, st.c1_params)
    c1_params = optax.apply_updates(st.c1_params, u1)
    u2, c2_opt = opt_c.update(g2, st.c2_opt, st.c2_params)
    c2_params = optax.apply_updates(st.c2_params, u2)

    # --- actor update with hint ADMM penalty (enet_sac.py:589-605)
    def actor_loss(ap):
        mu, ls = actor.apply({"params": ap}, s)
        acts, lp = gaussian_sample(mu, ls, k_pi)
        qa = jnp.minimum(critic.apply({"params": c1_params}, s, acts),
                         critic.apply({"params": c2_params}, s, acts))
        loss = jnp.mean(st.alpha * lp - qa)
        if cfg.use_hint:
            gfun = _hint_gap(cfg, acts, hint)
            loss = (loss + 0.5 * cfg.admm_rho * gfun * gfun
                    + st.rho * gfun)
        return loss

    aloss, ga = jax.value_and_grad(actor_loss)(st.actor_params)
    if collect_diag:
        # entropy/constraint stats recomputed OUTSIDE the grad with the
        # SAME key: auxing them through value_and_grad would change the
        # AD graph (and bit-drift the update); this forward is the
        # identical deterministic computation and CSE-dedupes under jit
        mu_pi, ls_pi = actor.apply({"params": st.actor_params}, s)
        acts_pi, lp_pi = gaussian_sample(mu_pi, ls_pi, k_pi)
    else:
        acts_pi = lp_pi = None
    ua, actor_opt = opt_a.update(ga, st.actor_opt, st.actor_params)
    actor_params = optax.apply_updates(st.actor_params, ua)

    # --- dual/temperature updates every 10 learn calls (enet_sac.py:608-617)
    alpha, rho = st.alpha, st.rho
    log_alpha, alpha_opt = st.log_alpha, st.alpha_opt
    if cfg.use_hint or cfg.learn_alpha:
        opt_alpha = optax.adam(cfg.alpha_lr)

        def dual_update(_):
            mu, ls = actor.apply({"params": actor_params}, s)
            acts, lp = gaussian_sample(mu, ls, k_dual)
            new_alpha, new_la, new_aopt = alpha, log_alpha, alpha_opt
            new_rho = rho
            if cfg.learn_alpha:
                target_entropy = -float(cfg.n_actions)
                if cfg.alpha_rule == "reference":
                    # the reference's clamped SGD directly on alpha:
                    # alpha = max(0, alpha + lr*mean(target_entropy -
                    # (-logpi))) (enet_sac.py:613)
                    new_alpha = jnp.maximum(
                        0.0, alpha + cfg.alpha_lr
                        * jnp.mean(target_entropy + lp))
                else:
                    # 'sac_v2' deviation: Adam on log_alpha against
                    # alpha_loss = -(log_alpha*(logp + target_entropy)),
                    # alpha = exp(log_alpha) — not in the reference
                    g_la = -jnp.mean(lp + target_entropy)
                    upd, new_aopt = opt_alpha.update(g_la, alpha_opt,
                                                     log_alpha)
                    new_la = optax.apply_updates(log_alpha, upd)
                    new_alpha = jnp.exp(new_la)
            if cfg.use_hint:
                new_rho = rho + cfg.admm_rho * _hint_gap(cfg, acts, hint)
            return new_alpha, new_rho, new_la, new_aopt

        alpha, rho, log_alpha, alpha_opt = lax.cond(
            st.learn_counter % 10 == 0, dual_update,
            lambda _: (alpha, rho, log_alpha, alpha_opt), operand=None)

    # --- TD error (the PER priority signal; callers with external
    # priority stores consume metrics['td'])
    td = jnp.abs(q1 - y).squeeze(-1)

    # --- soft target update (enet_sac.py:523-542)
    lerp = lambda t, o: jax.tree_util.tree_map(
        lambda a_, b_: cfg.tau * a_ + (1.0 - cfg.tau) * b_, o, t)
    st_new = SACState(
        actor_params=actor_params,
        c1_params=c1_params, c2_params=c2_params,
        t1_params=lerp(st.t1_params, c1_params),
        t2_params=lerp(st.t2_params, c2_params),
        actor_opt=actor_opt, c1_opt=c1_opt, c2_opt=c2_opt,
        alpha=alpha, rho=rho,
        learn_counter=st.learn_counter + 1,
        log_alpha=log_alpha, alpha_opt=alpha_opt,
    )
    metrics = {"critic_loss": closs, "actor_loss": aloss,
               "alpha": alpha, "rho": rho, "td": td, **clip_aux}
    if collect_diag:
        metrics["diag"] = dg.make_diag(
            critic_loss=closs, actor_loss=aloss,
            critic_grad_norm=dg.tree_norm((g1, g2)),
            actor_grad_norm=dg.tree_norm(ga),
            critic_update_ratio=dg.update_ratio(
                (u1, u2), (st.c1_params, st.c2_params)),
            actor_update_ratio=dg.update_ratio(ua, st.actor_params),
            q_mean=jnp.mean(q1), q_min=jnp.min(q1), q_max=jnp.max(q1),
            target_drift=dg.target_drift(c1_params, st_new.t1_params),
            alpha=alpha, entropy=-jnp.mean(lp_pi),
            hint_residual=(jnp.mean((acts_pi - hint) ** 2)
                           if cfg.use_hint else 0.0))
    return st_new, metrics


def learn(cfg: SACConfig, st: SACState, buf: rp.ReplayState,
          key, collect_diag: bool = False, learner_version=None
          ) -> Tuple[SACState, rp.ReplayState, dict]:
    """One SAC learn step, sampling from (and possibly re-prioritising) ``buf``.

    No-op (identity state) while the buffer holds fewer than ``batch_size``
    transitions, so it can sit unconditionally inside a scanned train loop.
    ``collect_diag`` threads ``metrics['diag']`` out (see
    :func:`learn_from_batch`; the no-learn branch reports a zero diag).

    The whole sample -> learn -> priority-update chain is device-resident
    — ONE jitted step with no host round-trip of the sampled batch
    (asserted under ``jax.transfer_guard`` in tests/test_fleet.py).
    ``cfg.ere_eta < 1`` switches the sample distribution to (or, with
    PER, modulates it by) the emphasizing-recent-experience weights;
    ``learner_version`` (traced int) is required when ``cfg.is_clip``
    arms the IMPACT staleness weighting.  ``buf`` may be the flat
    :class:`~smartcal_tpu.rl.replay.ReplayState` or the mesh-sharded
    :class:`~smartcal_tpu.rl.replay_sharded.ShardedReplayState` — the
    sample/priority-update calls dispatch on the buffer type and the
    whole step stays device-resident either way.
    """
    ere = cfg.ere_eta if cfg.ere_eta < 1.0 else None
    rpb = rp.backend_for(buf)

    def do_learn(args):
        st, buf, key = args
        k_samp, k_core = jax.random.split(key)

        if cfg.prioritized:
            batch, idx, is_w, buf2 = rpb.replay_sample_per(
                buf, k_samp, cfg.batch_size, recency_eta=ere)
        elif ere is not None:
            batch, idx = rpb.replay_sample_ere(buf, k_samp, cfg.batch_size,
                                               ere)
            is_w, buf2 = jnp.ones((cfg.batch_size,), jnp.float32), buf
        else:
            batch, idx = rpb.replay_sample_uniform(buf, k_samp,
                                                   cfg.batch_size)
            is_w, buf2 = jnp.ones((cfg.batch_size,), jnp.float32), buf

        st_new, metrics = learn_from_batch(cfg, st, batch, is_w, k_core,
                                           collect_diag=collect_diag,
                                           learner_version=learner_version)
        if cfg.prioritized:
            buf2 = rpb.replay_update_priorities(buf2, idx, metrics["td"],
                                                cfg.error_clip)
        return st_new, buf2, {k: v for k, v in metrics.items() if k != "td"}

    def no_learn(args):
        st, buf, _ = args
        zeros = {"critic_loss": jnp.asarray(0.0),
                 "actor_loss": jnp.asarray(0.0),
                 "alpha": st.alpha, "rho": st.rho}
        if cfg.is_clip > 0:
            zeros.update(rp.zero_clip_aux())
        if collect_diag:
            zeros["diag"] = dg.zero_diag()
        return st, buf, zeros

    return lax.cond(buf.cntr >= cfg.batch_size, do_learn, no_learn,
                    (st, buf, key))


class SACAgent:
    """Stateful wrapper with the reference ``Agent`` API
    (choose_action / store_transition / learn / save_models / load_models)
    around the pure jitted functions, for host-driven training loops."""

    def __init__(self, cfg: SACConfig, seed: int = 0,
                 name_prefix: str = "", collect_diag: bool = False):
        self.cfg = cfg
        self.key = jax.random.PRNGKey(seed)
        self.key, k0 = jax.random.split(self.key)
        self.state = sac_init(k0, cfg)
        self.native = cfg.prioritized and cfg.replay_backend == "native"
        self.collect_diag = collect_diag
        spec = rp.transition_spec(cfg.obs_dim, cfg.n_actions)
        if self.native:
            from .replay_native import NativePER

            self.buffer = NativePER(cfg.mem_size, spec,
                                    error_clip=cfg.error_clip)
            self._rng = np.random.default_rng(seed + 1)
            self._core = jax.jit(
                lambda st, b, w, k: learn_from_batch(
                    cfg, st, b, w, k, collect_diag=collect_diag))
        else:
            self.buffer = rp.replay_init(cfg.mem_size, spec)
            self._learn = jax.jit(
                lambda st, buf, key: learn(cfg, st, buf, key,
                                           collect_diag=collect_diag))
            self._add = jax.jit(
                lambda buf, tr: rp.replay_add(buf, tr,
                                              priority=None if cfg.prioritized
                                              else jnp.asarray(1.0)))
        self.name_prefix = name_prefix
        self._choose = jax.jit(
            lambda st, obs, key: choose_action(cfg, st, obs, key))
        self.last_metrics = {}
        self.last_diag = None

    def _next_key(self):
        self.key, sub = jax.random.split(self.key)
        return sub

    def choose_action(self, observation):
        obs = jnp.asarray(observation, jnp.float32)
        return jax.device_get(self._choose(self.state, obs, self._next_key()))

    def store_transition(self, state, action, reward, state_, done, hint):
        tr = {"state": state, "action": action, "reward": reward,
              "new_state": state_, "done": done, "hint": hint}
        if self.native:
            self.buffer.store(tr)      # max-priority init (enet_sac.py:63-64)
        else:
            self.buffer = self._add(self.buffer, tr)

    def learn(self):
        from smartcal_tpu.obs import costs
        from smartcal_tpu.obs.spans import span

        if self.native:
            if not self.buffer.ready(self.cfg.batch_size):
                # same metrics contract as the HBM path's no_learn branch
                self.last_metrics = {
                    "critic_loss": jnp.asarray(0.0),
                    "actor_loss": jnp.asarray(0.0),
                    "alpha": self.state.alpha, "rho": self.state.rho}
                return
            batch, idx, is_w = self.buffer.sample(self.cfg.batch_size,
                                                  self._rng)
            batch = {k: jnp.asarray(v) for k, v in batch.items()}
            is_w, k = jnp.asarray(is_w), self._next_key()
            # span name == cost stage ('/'-free) -> obs_report roofline
            # join; cost analysis deferred (see td3.TD3Agent.learn)
            with span("agent_update_sac"):
                self.state, m = self._core(self.state, batch, is_w, k)
            costs.record_stage_cost("agent_update_sac", self._core,
                                    self.state, batch, is_w, k, defer=True)
            self.buffer.update_priorities(idx, jax.device_get(m["td"]))
            m = {k_: v for k_, v in m.items() if k_ != "td"}
        else:
            k = self._next_key()
            with span("agent_update_sac"):
                self.state, self.buffer, m = self._learn(
                    self.state, self.buffer, k)
            costs.record_stage_cost("agent_update_sac", self._learn,
                                    self.state, self.buffer, k, defer=True)
        self.last_metrics = m
        self.last_diag = m.pop("diag", None)

    def save_models(self, prefix: Optional[str] = None):
        from smartcal_tpu.runtime.atomic import atomic_pickle

        prefix = prefix if prefix is not None else self.name_prefix
        atomic_pickle(jax.device_get(self.state), f"{prefix}sac_state.pkl")
        if self.native:
            self.buffer.save(f"{prefix}replaymem_sac.pkl")
        else:
            rp.save_replay(self.buffer, f"{prefix}replaymem_sac.pkl")

    def load_models(self, prefix: Optional[str] = None):
        """Resume from ``save_models`` files; a missing/truncated/corrupt
        pair warns and keeps the fresh init instead of crashing (the
        mid-write-kill case the atomic saves make rare but old files can
        still exhibit)."""
        from smartcal_tpu.runtime.atomic import safe_pickle_load

        prefix = prefix if prefix is not None else self.name_prefix
        host = safe_pickle_load(f"{prefix}sac_state.pkl")
        if host is None:
            return False
        st = jax.tree_util.tree_map(jnp.asarray, host)
        if st.log_alpha is None:
            # checkpoint predates the optimizer-on-log-alpha state: resume
            # the temperature from the saved alpha with a fresh Adam state
            log_alpha = jnp.log(jnp.maximum(st.alpha, 1e-8))
            st = st._replace(
                log_alpha=log_alpha,
                alpha_opt=optax.adam(self.cfg.alpha_lr).init(log_alpha))
        self.state = st
        from smartcal_tpu.runtime.atomic import safe_pickle_load
        mem = safe_pickle_load(f"{prefix}replaymem_sac.pkl")
        if mem is not None:
            if self.native:
                from .replay_native import NativePER

                self.buffer = NativePER.from_state_dict(mem)
            else:
                self.buffer = jax.tree_util.tree_map(jnp.asarray, mem)
        return True
