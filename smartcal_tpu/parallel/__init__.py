from .mesh import (  # noqa: F401
    AXIS_BASELINE,
    AXIS_CHUNK,
    AXIS_DATA,
    AXIS_FREQ,
    AXIS_LANE,
    AXIS_REPLAY,
    MESH_AXES,
    MeshFactorizationError,
    compose_mesh,
    make_mesh,
    nearest_factorization,
    replicated,
    sharded_batch,
)
from . import multihost  # noqa: F401
from .trainer import (  # noqa: F401
    ParallelTrainState,
    episode_scores,
    make_parallel_sac,
)
