from .mesh import make_mesh, replicated, sharded_batch  # noqa: F401
from . import multihost  # noqa: F401
from .trainer import (  # noqa: F401
    ParallelTrainState,
    episode_scores,
    make_parallel_sac,
)
