"""Synchronous data-parallel SAC trainer over a device mesh.

TPU-native replacement for the reference's distributed learner/actor runtime
(``elasticnet/distributed_per_sac.py``): there, a rank-0 Learner holds the
agent, fires ``rpc_async`` rollouts on remote Actors, ships CPU weight dicts
out and whole replay buffers back, and serialises ingestion behind a
``threading.Lock`` (``:44-57,:60-74,:123-146``).

Here the learner/actor split collapses into one SPMD program over a
``Mesh``:

* a batch of environments lives sharded over the ``dp`` axis (one or more
  env states per device) — the "actors";
* agent parameters are replicated; action sampling and env stepping run
  devicewise with no weight shipping (the broadcast is the sharding);
* the transition batch scatters into the (replicated) HBM replay buffer —
  the lock-free equivalent of ``download_replaybuffer``;
* the learn step consumes a minibatch; XLA inserts the gradient
  all-reduce over ICI where the batch sharding demands it (the pmap-psum
  "north star" of BASELINE.json).

Everything is one jitted function of pure pytrees, so the same code runs on
1 chip, an 8-device virtual CPU mesh (tests), or a real pod slice.
"""

from __future__ import annotations

import time
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from smartcal_tpu import obs

from ..envs import enet
from ..rl import replay as rp
from ..rl import sac
from .mesh import AXIS_DATA


def _instrument(fn, kind: str, env_steps_per_call: int,
                gauge_every: int = 50):
    """Wrap a jitted train function with dispatch telemetry.

    With no RunLog active the wrapper is one function call + one ``None``
    check; with one active it records a ``dispatch`` event (submission
    wall time — NOT compute time: the call is async and deliberately not
    synchronized, so instrumentation never serializes the pipeline) and
    accumulates env-step/dispatch counters.  Every ``gauge_every``
    dispatches it also emits an ``env_steps_per_s`` gauge over the
    window — the aggregate-throughput number the async-fleet gauges use,
    here for the synchronous SPMD trainer so the two architectures read
    off the same telemetry name."""
    window = {"n": 0, "t0": None}

    def wrapped(*args, **kwargs):
        rl = obs.active()
        if rl is None:
            return fn(*args, **kwargs)
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        rl.log("dispatch", kind=kind,
               submit_s=round(time.perf_counter() - t0, 6),
               env_steps=env_steps_per_call)
        obs.counter_add("train_dispatches")
        obs.counter_add("env_steps", env_steps_per_call)
        if window["t0"] is None:
            window["t0"] = t0
        window["n"] += 1
        if window["n"] >= gauge_every:
            wall = time.perf_counter() - window["t0"]
            obs.gauge_set(
                "env_steps_per_s",
                round(window["n"] * env_steps_per_call / max(wall, 1e-9),
                      2), kind=kind)
            window["n"], window["t0"] = 0, None
        return out

    wrapped.__wrapped__ = fn
    return wrapped


class ParallelTrainState(NamedTuple):
    agent: sac.SACState
    buf: rp.ReplayState
    env_states: enet.EnetState      # batched leading axis (n_envs)
    obs: jnp.ndarray                # (n_envs, obs_dim)
    hints: jnp.ndarray              # (n_envs, n_actions)
    step_in_episode: jnp.ndarray    # () int32


def make_parallel_sac(env_cfg: enet.EnetConfig, agent_cfg: sac.SACConfig,
                      mesh: Mesh, n_envs: int, use_hint: bool = False,
                      episode_block=None):
    """Build (init_fn, train_step_fn, reset_envs_fn) with shardings bound
    to ``mesh``.

    ``n_envs`` must be divisible by the ``dp`` axis size.  One train step =
    every env advances one step (vmapped, dp-sharded), the transition batch
    is stored, and one SAC learn step runs.

    ``episode_block=(steps_per_episode, episodes_per_dispatch)`` appends a
    fourth return value: a jitted ``run_block(st, key) -> (st, scores)``
    that scans whole episodes (reset + steps, exactly the host cadence of
    the per-step API) inside ONE dispatch — the dp-sharded analogue of
    ``train.blocks`` (dispatch round trips dominate the small enet
    programs on the chip; see bench.py round-3 capture).  ``scores`` has
    shape (episodes_per_dispatch,), each the mean step reward of that
    episode across the env batch.
    """
    if n_envs % mesh.shape[AXIS_DATA] != 0:
        raise ValueError(f"n_envs={n_envs} not divisible by dp axis "
                         f"{mesh.shape[AXIS_DATA]}")

    repl = NamedSharding(mesh, P())
    shard = NamedSharding(mesh, P(AXIS_DATA))

    def _fresh_envs(k_envs):
        """Reset all envs, draw the first noisy observation, compute hints.

        The hint must see the first step's noise draw (reference: get_hint
        uses self.y set inside step(), enetenv.py:87-90,156-158), so the
        draw happens here and step 0 of each episode keeps it.
        """
        k_reset, k_noise = jax.random.split(k_envs)
        env_states, obs = jax.vmap(lambda k: enet.reset(env_cfg, k))(
            jax.random.split(k_reset, n_envs))
        env_states = jax.vmap(lambda s, k: enet.draw_noise(env_cfg, s, k))(
            env_states, jax.random.split(k_noise, n_envs))
        if use_hint:
            hints = jax.vmap(lambda s: enet.get_hint(env_cfg, s))(env_states)
        else:
            hints = jnp.zeros((n_envs, agent_cfg.n_actions), jnp.float32)
        return env_states, obs, hints

    def init_fn(key) -> ParallelTrainState:
        k_agent, k_envs = jax.random.split(key)
        agent = sac.sac_init(k_agent, agent_cfg)
        buf = rp.replay_init(
            agent_cfg.mem_size,
            rp.transition_spec(env_cfg.obs_dim, agent_cfg.n_actions))
        env_states, obs, hints = _fresh_envs(k_envs)
        st = ParallelTrainState(agent=agent, buf=buf, env_states=env_states,
                                obs=obs, hints=hints, step_in_episode=jnp.asarray(0, jnp.int32))
        return jax.device_put(st, _state_shardings(st))

    def _state_shardings(st: ParallelTrainState):
        return ParallelTrainState(
            agent=jax.tree_util.tree_map(lambda _: repl, st.agent),
            buf=jax.tree_util.tree_map(lambda _: repl, st.buf),
            env_states=jax.tree_util.tree_map(lambda _: shard, st.env_states),
            obs=shard,
            hints=shard,
            step_in_episode=repl,
        )

    def train_step(st: ParallelTrainState, key):
        k_act, k_env, k_learn = jax.random.split(key, 3)

        # actors: sample + step, devicewise over dp; step 0 of an episode
        # keeps the noise drawn at reset (the hint's data)
        actions = sac.choose_action(agent_cfg, st.agent, st.obs, k_act)
        env_keys = jax.random.split(k_env, n_envs)
        first = st.step_in_episode == 0
        env_states, obs2, rewards, dones = jax.vmap(
            lambda s, a, k: enet.step(env_cfg, s, a, k, keepnoise=first))(
            st.env_states, actions, env_keys)

        transitions = {
            "state": st.obs, "action": actions, "reward": rewards,
            "new_state": obs2, "done": dones, "hint": st.hints,
        }
        buf = rp.replay_add_batch(
            st.buf, transitions,
            priority=None if agent_cfg.prioritized else jnp.asarray(1.0))

        agent, buf, metrics = sac.learn(agent_cfg, st.agent, buf, k_learn)
        metrics["mean_reward"] = jnp.mean(rewards)

        new_st = ParallelTrainState(agent=agent, buf=buf,
                                    env_states=env_states, obs=obs2,
                                    hints=st.hints,
                                    step_in_episode=st.step_in_episode + 1)
        return new_st, metrics

    def reset_envs(st: ParallelTrainState, key):
        """Start a new episode on every env (host calls this every
        steps-per-episode train steps, mirroring the reference's per-episode
        env.reset)."""
        env_states, obs, hints = _fresh_envs(key)
        return st._replace(env_states=env_states, obs=obs, hints=hints,
                           step_in_episode=jnp.asarray(0, jnp.int32))

    dummy = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    shardings = _state_shardings(dummy)
    train_step_jit = _instrument(
        jax.jit(train_step, in_shardings=(shardings, repl),
                out_shardings=(shardings, repl)),
        "train_step", n_envs)
    reset_envs_jit = jax.jit(reset_envs,
                             in_shardings=(shardings, repl),
                             out_shardings=shardings)
    if episode_block is None:
        return init_fn, train_step_jit, reset_envs_jit

    steps_pe, eps_pd = (int(v) for v in episode_block)

    def run_block(st: ParallelTrainState, key):
        def one_episode(carry, k):
            st = carry
            k_reset, k_steps = jax.random.split(k)
            st = reset_envs(st, k_reset)

            def one_step(st, ks):
                st, metrics = train_step(st, ks)
                return st, metrics["mean_reward"]

            st, mean_rs = jax.lax.scan(
                one_step, st, jax.random.split(k_steps, steps_pe))
            return st, jnp.mean(mean_rs)

        keys = jax.random.split(key, eps_pd)
        return jax.lax.scan(one_episode, st, keys)

    run_block_jit = _instrument(
        jax.jit(run_block, in_shardings=(shardings, repl),
                out_shardings=(shardings, repl)),
        "episode_block", n_envs * steps_pe * eps_pd)
    return init_fn, train_step_jit, reset_envs_jit, run_block_jit


def episode_scores(metrics_list, steps_per_episode: int):
    """Aggregate per-step mean rewards into per-episode scores."""
    rewards = [float(m["mean_reward"]) for m in metrics_list]
    return [sum(rewards[i:i + steps_per_episode]) / steps_per_episode
            for i in range(0, len(rewards), steps_per_episode)]
