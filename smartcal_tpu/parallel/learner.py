"""Distributed prioritized-experience-replay learner/actor training.

Parity target: ``elasticnet/distributed_per_sac.py`` (and the demixing
variant ``demixing_rl/distributed_per_sac.py``): a rank-0 Learner owns the
SAC agent + PER buffer; per episode it fires ``rpc_async`` rollouts on N
remote Actors; each Actor pulls a CPU copy of the actor weights (:84-90,
:123-128), runs ``epochs x steps`` env steps into a small local buffer
(:130-141), and ``rpc_sync`` uploads the whole buffer; the Learner ingests
transition by transition under a ``threading.Lock``, calling ``learn()``
per transition (:44-57).

TPU-native re-expression: the RPC fan-out becomes one SPMD program over the
mesh's ``dp`` axis —

* actor envs are sharded over ``dp``; the "weight pull" is parameter
  replication (zero copies, the broadcast IS the sharding);
* the rollout is a ``lax.scan`` over epochs x steps, vmapped over the
  actor axis — every actor uses the episode-frozen actor params exactly
  like the reference's stale CPU snapshot;
* the "buffer upload" is the resharding of the transition batch from
  dp-sharded to replicated (an all-gather over ICI inserted by XLA);
* ingestion + learning runs replicated (identical on every device — the
  lock disappears because the learner is deterministic SPMD, not a
  thread).  ``learn_per_transition=True`` reproduces the reference's
  learn-per-ingested-transition cadence; ``False`` does one batched learn
  per actor-buffer (faster, recommended at scale).

The same program runs multi-host under ``jax.distributed`` — ``dp`` spans
all hosts' devices and the transition all-gather rides ICI/DCN, replacing
TensorPipe/Gloo.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..envs import enet
from ..rl import replay as rp
from ..rl import sac
from .mesh import AXIS_DATA


class DistPERState(NamedTuple):
    agent: sac.SACState
    buf: rp.ReplayState
    episode: jnp.ndarray    # () int32


def make_actor_rollout(env_cfg: enet.EnetConfig, agent_cfg: sac.SACConfig,
                       rollout_epochs: int, rollout_steps: int,
                       use_hint: bool = False, record_logp: bool = False):
    """One actor's rollout as a pure function ``(agent_state, key) ->
    transitions`` with leading axis ``rollout_epochs * rollout_steps``
    (reference Actor.run_observations, :123-146).  Shared by the SPMD
    learner (vmapped over the actor axis) and the supervised
    actor-thread fleet (jitted per thread).

    ``record_logp`` adds a ``behavior_logp`` field (log pi of the sampled
    action under the rollout's frozen params — the denominator of the
    learner's IMPACT importance ratio); the action stream is bitwise the
    plain path's (same keys, same sampler)."""
    n_trans = rollout_epochs * rollout_steps

    def _actor_rollout(agent_state, key):
        def epoch_body(carry, k_epoch):
            k_reset, k_noise, k_scan = jax.random.split(k_epoch, 3)
            env_state, obs = enet.reset(env_cfg, k_reset)
            env_state = enet.draw_noise(env_cfg, env_state, k_noise)
            hint = (enet.get_hint(env_cfg, env_state) if use_hint
                    else jnp.zeros((agent_cfg.n_actions,), jnp.float32))

            def step_body(scarry, inp):
                k, first = inp
                env_state, obs = scarry
                k_act, k_env = jax.random.split(k)
                if record_logp:
                    a, lp = sac.choose_action_logp(agent_cfg, agent_state,
                                                   obs[None], k_act)
                    a, lp = a[0], lp[0]
                else:
                    a = sac.choose_action(agent_cfg, agent_state, obs[None],
                                          k_act)[0]
                env_state, obs2, r, done = enet.step(env_cfg, env_state, a,
                                                     k_env, keepnoise=first)
                tr = {"state": obs, "action": a, "reward": r,
                      "new_state": obs2, "done": done, "hint": hint}
                if record_logp:
                    tr["behavior_logp"] = lp
                return (env_state, obs2), tr

            keys = jax.random.split(k_scan, rollout_steps)
            first = jnp.arange(rollout_steps) == 0
            _, trs = jax.lax.scan(step_body, (env_state, obs), (keys, first))
            return carry, trs

        _, trs = jax.lax.scan(epoch_body, 0,
                              jax.random.split(key, rollout_epochs))
        # (epochs, steps, ...) -> (epochs*steps, ...)
        return jax.tree_util.tree_map(
            lambda x: x.reshape((n_trans,) + x.shape[2:]), trs)

    return _actor_rollout


def lane_keys(key, n_lanes: int):
    """The fleet's per-lane key derivation — lane i follows the stream
    ``fold_in(key, i)``.  ONE definition shared by the enet and demix
    lane fan-outs so the derivation can never drift between workloads."""
    return jax.vmap(lambda i: jax.random.fold_in(key, i))(
        jnp.arange(n_lanes))


def flatten_lanes(trs, n_trans: int):
    """Collapse a ``(lanes, per_lane, ...)`` transition pytree into the
    single ``(n_trans, ...)`` block the learner's ingest queue carries."""
    return jax.tree_util.tree_map(
        lambda x: x.reshape((n_trans,) + x.shape[2:]), trs)


def make_fleet_rollout(env_cfg: enet.EnetConfig, agent_cfg: sac.SACConfig,
                       batch_envs: int, rollout_epochs: int,
                       rollout_steps: int, use_hint: bool = False,
                       record_logp: bool = True):
    """A fleet actor's program: ``batch_envs`` env lanes vmapped through
    :func:`make_actor_rollout` as ONE batched dispatch (the PR 9 regime,
    lane streams from :func:`lane_keys`), flattened to a single
    ``(batch_envs * epochs * steps, ...)`` transition block for the
    learner's ingest queue."""
    single = make_actor_rollout(env_cfg, agent_cfg, rollout_epochs,
                                rollout_steps, use_hint=use_hint,
                                record_logp=record_logp)
    n_trans = batch_envs * rollout_epochs * rollout_steps

    def _fleet_rollout(agent_state, key):
        trs = jax.vmap(lambda k: single(agent_state, k))(
            lane_keys(key, batch_envs))
        return flatten_lanes(trs, n_trans)

    return _fleet_rollout if batch_envs > 1 else (
        lambda agent_state, key: single(agent_state, key))


def _enet_fleet_work_fn(env_kwargs=None, agent_kwargs=None, use_hint=False,
                        is_clip=0.0, ere_eta=1.0, batch_envs=1,
                        rollout_epochs=2, rollout_steps=5, seed=0):
    """Build the enet fleet actor's work function from PICKLABLE
    primitives — the one definition shared by actor THREADS (called
    in-process by ``train_supervised``) and actor PROCESSES (named as
    the ``worker_spec`` factory and called inside each spawned worker
    by :func:`smartcal_tpu.runtime.ipc.worker_main`).  Identical inputs
    produce identical per-(actor, iteration) key streams in both modes,
    so switching ``--actor-mode`` changes WHERE rollouts run, never
    WHAT they compute."""
    env_cfg = enet.EnetConfig(**(env_kwargs or {}))
    agent_kwargs = dict(agent_kwargs or {})
    agent_kwargs.setdefault("prioritized", True)
    agent_cfg = sac.SACConfig(obs_dim=env_cfg.obs_dim, n_actions=2,
                              use_hint=use_hint, is_clip=is_clip,
                              ere_eta=ere_eta, **agent_kwargs)
    rollout = jax.jit(make_fleet_rollout(
        env_cfg, agent_cfg, batch_envs, rollout_epochs, rollout_steps,
        use_hint=use_hint, record_logp=is_clip > 0))
    # per-(actor, iteration) rollout keys: a restarted actor continues
    # its predecessor's deterministic stream from the next iteration
    base_key = jax.random.PRNGKey(seed ^ 0x0AC7035)

    from smartcal_tpu.runtime import faults as rt_faults

    def work_fn(actor_id, iteration, weights):
        rt_faults.maybe_delay("actor_rollout", iteration)
        if rt_faults.should_kill_actor(actor_id, iteration):
            raise rt_faults.FaultInjected(
                f"actor {actor_id} killed at iteration {iteration}")
        k = jax.random.fold_in(jax.random.fold_in(base_key, actor_id),
                               iteration)
        return jax.device_get(rollout(weights, k))

    return work_fn


def make_sharded_fleet_buffer(mem_size: int, spec: dict,
                              replay_shards: int):
    """The fleet's mesh-sharded replay buffer, committed to the device
    mesh (see :mod:`smartcal_tpu.rl.replay_sharded`); validates the
    shard count against the ring size at config time."""
    from ..rl import replay_sharded as rps

    if mem_size % replay_shards != 0:
        raise ValueError(
            f"--replay-shards {replay_shards} must divide mem_size "
            f"{mem_size} (equal round-robin ring shards)")
    return rps.place_on_mesh(rps.replay_init(mem_size, spec,
                                             replay_shards))


def make_distributed_per_sac(env_cfg: enet.EnetConfig,
                             agent_cfg: sac.SACConfig, mesh: Mesh,
                             n_actors: int, rollout_epochs: int = 10,
                             rollout_steps: int = 10,
                             use_hint: bool = False,
                             learn_per_transition: bool = False):
    """Build (init_fn, run_episode_fn) bound to ``mesh``.

    One ``run_episode`` = the reference Learner's ``run_episodes`` body
    (:60-74): all actors roll out with frozen weights, the learner ingests
    everything and trains.  ``agent_cfg.prioritized`` should be True for
    parity (distributed PER).
    """
    if n_actors % mesh.shape[AXIS_DATA] != 0:
        raise ValueError(f"n_actors={n_actors} not divisible by dp axis "
                         f"{mesh.shape[AXIS_DATA]}")
    repl = NamedSharding(mesh, P())
    shard = NamedSharding(mesh, P(AXIS_DATA))
    n_trans = rollout_epochs * rollout_steps

    def init_fn(key) -> DistPERState:
        k_agent, _ = jax.random.split(key)
        agent = sac.sac_init(k_agent, agent_cfg)
        buf = rp.replay_init(
            agent_cfg.mem_size,
            rp.transition_spec(env_cfg.obs_dim, agent_cfg.n_actions))
        st = DistPERState(agent=agent, buf=buf,
                          episode=jnp.asarray(0, jnp.int32))
        return jax.device_put(st, _shardings(st))

    def _shardings(st: DistPERState):
        return DistPERState(
            agent=jax.tree_util.tree_map(lambda _: repl, st.agent),
            buf=jax.tree_util.tree_map(lambda _: repl, st.buf),
            episode=repl)

    _actor_rollout = make_actor_rollout(env_cfg, agent_cfg, rollout_epochs,
                                        rollout_steps, use_hint=use_hint)

    def run_episode(st: DistPERState, key):
        k_roll, k_learn = jax.random.split(key)
        actor_keys = jax.random.split(k_roll, n_actors)
        # actors sharded over dp; params frozen for the whole episode
        trs = jax.vmap(lambda k: _actor_rollout(st.agent, k))(actor_keys)
        # flatten actor axis -> the learner's ingestion stream (XLA
        # all-gathers here because the learner state is replicated)
        flat = jax.tree_util.tree_map(
            lambda x: x.reshape((n_actors * n_trans,) + x.shape[2:]), trs)

        if learn_per_transition:
            def ingest(carry, inp):
                agent, buf = carry
                tr, k = inp
                buf = rp.replay_add(buf, tr)
                agent, buf, m = sac.learn(agent_cfg, agent, buf, k)
                return (agent, buf), m["critic_loss"]

            keys = jax.random.split(k_learn, n_actors * n_trans)
            (agent, buf), losses = jax.lax.scan(ingest, (st.agent, st.buf),
                                                (flat, keys))
            metrics = {"critic_loss": losses[-1]}
        else:
            buf = rp.replay_add_batch(st.buf, flat)
            agent, buf, metrics = sac.learn(agent_cfg, st.agent, buf,
                                            k_learn)
        metrics["mean_reward"] = jnp.mean(flat["reward"])
        return DistPERState(agent=agent, buf=buf, episode=st.episode + 1), \
            metrics

    dummy = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    sh = _shardings(dummy)
    run_episode_jit = jax.jit(run_episode, in_shardings=(sh, repl),
                              out_shardings=(sh, repl))
    return init_fn, run_episode_jit


def train_distributed(seed=0, episodes=100, n_actors=None, mesh=None,
                      env_kwargs=None, agent_kwargs=None, use_hint=False,
                      learn_per_transition=False, quiet=False,
                      rollout_epochs=10, rollout_steps=10, metrics=None,
                      diag=False, watchdog=False, ckpt_dir=None,
                      ckpt_every=0, resume=False):
    """Host driver mirroring ``run_process`` + ``Learner.run_episodes``
    (distributed_per_sac.py:60-82, :154-174).

    ``metrics`` records an obs run: per learner-episode actor throughput
    (transitions/s through the SPMD rollout+ingest program) and the
    weight-staleness bound — actor params are episode-frozen, so the last
    transition of a rollout acts on weights ``rollout_epochs x
    rollout_steps`` env steps old (the SPMD analogue of the reference's
    stale CPU weight snapshot; IMPACT-style systems track the same
    quantity as a distribution)."""
    import time

    from smartcal_tpu import obs
    from smartcal_tpu.train.blocks import train_obs

    from . import make_mesh

    mesh = mesh or make_mesh()
    n_actors = n_actors or mesh.shape[AXIS_DATA]
    env_cfg = enet.EnetConfig(**(env_kwargs or {}))
    agent_kwargs = dict(agent_kwargs or {})
    agent_kwargs.setdefault("prioritized", True)
    agent_cfg = sac.SACConfig(obs_dim=env_cfg.obs_dim, n_actions=2,
                              use_hint=use_hint, **agent_kwargs)
    init_fn, run_episode = make_distributed_per_sac(
        env_cfg, agent_cfg, mesh, n_actors, use_hint=use_hint,
        rollout_epochs=rollout_epochs, rollout_steps=rollout_steps,
        learn_per_transition=learn_per_transition)
    from smartcal_tpu.train.blocks import TrainRuntime

    from smartcal_tpu.runtime import pack_replay, unpack_replay

    key = jax.random.PRNGKey(seed)
    key, k0 = jax.random.split(key)
    st = init_fn(k0)
    scores = []
    n_trans = n_actors * rollout_epochs * rollout_steps
    tob = train_obs("parallel_learner", metrics=metrics, quiet=quiet,
                    diag=diag, watchdog=watchdog, seed=seed,
                    n_actors=n_actors)
    rt = TrainRuntime("parallel_learner", ckpt_dir=ckpt_dir,
                      ckpt_every=ckpt_every, resume=resume, tob=tob)
    ep0 = 0
    restored = rt.restore()
    if restored is not None:
        st = DistPERState(
            agent=jax.tree_util.tree_map(jnp.asarray,
                                         restored["agent_state"]),
            buf=unpack_replay(restored["replay"]),
            episode=jnp.asarray(restored["episode"], jnp.int32))
        key = jnp.asarray(restored["key"])
        scores = list(restored["scores"])
        ep0 = int(restored["episode"])

    def ckpt_payload(ep, key):
        return {"kind": "dist_per", "episode": ep + 1,
                "scores": list(scores),
                "agent_state": jax.device_get(st.agent),
                "replay": pack_replay(st.buf),
                "key": jax.device_get(key)}

    try:
        for ep in range(ep0, episodes):
            key, k = jax.random.split(key)
            t0 = time.perf_counter()
            with tob.span("learner_episode", episode=ep):
                st, metrics_out = run_episode(st, k)
                score = float(metrics_out["mean_reward"])
            wall = time.perf_counter() - t0
            scores.append(score)
            obs.gauge_set("actor_transitions_per_s",
                          round(n_trans / max(wall, 1e-9), 2))
            # PER distribution health next to the staleness gauge — the
            # Actor-PER signal pair (priority entropy vs weight
            # staleness) for the learner/actor split; --diag-gated like
            # every other replay_health producer
            tripped = False
            if tob.collect_diag:
                # the SPMD update surfaces only the episode's last
                # critic loss on host — enough for the watchdog's
                # non-finite (diverged-critic) check
                tripped = tob.record_diag(
                    {"critic_loss": float(metrics_out["critic_loss"])},
                    episode=ep)
            tripped = tob.log_replay_health(st.buf, episode=ep) or tripped
            # echo=False: keep the reference driver's own wording below
            tob.episode(ep, score, scores, echo=False, transitions=n_trans,
                        weight_staleness_steps=rollout_epochs
                        * rollout_steps)
            tob.echo(f"episode {ep} mean reward {scores[-1]:.4f}",
                     event=None)
            if tripped:
                # never checkpoint the tripped episode's (possibly
                # poisoned) state — a --resume must restart from the
                # last GOOD checkpoint
                break
            rt.maybe_checkpoint(ep + 1, lambda: ckpt_payload(ep, key))
    finally:
        tob.close()
    return st, scores


def train_supervised(seed=0, episodes=50, n_actors=2, env_kwargs=None,
                     agent_kwargs=None, use_hint=False, rollout_epochs=2,
                     rollout_steps=5, metrics=None, quiet=False, diag=False,
                     watchdog=False, heartbeat_timeout=60.0, max_restarts=3,
                     queue_timeout=30.0, max_empty_rounds=20,
                     restart_backoff=None, batch_envs=1, is_clip=0.0,
                     ere_eta=1.0, publish_every=1, ckpt_dir=None,
                     ckpt_every=0, keep_ckpts=3, resume=False,
                     actor_mode="thread", replay_shards=0, sim_hosts=1):
    """Supervised actor fleet: the scale-out async sibling of
    :func:`train_distributed`.

    Where the SPMD learner fuses all actors into one jitted program
    (nothing can die independently), here each actor is an independent
    host execution unit driving ``batch_envs`` env lanes as ONE batched
    jitted rollout (:func:`make_fleet_rollout`, the PR 9 regime)
    against an episode-frozen weights snapshot, shipping
    version-stamped transition blocks; the learner ingests whatever
    arrived through one fused device-resident step (store -> PER/ERE
    sample -> learn -> priority update, no host round-trip of the
    sampled batch), and a
    :class:`~smartcal_tpu.runtime.supervisor.Fleet` restarts dead/hung
    actors with exponential backoff + jitter.  Learning continues from
    the surviving fleet; a watchdog trip stops AND joins every actor
    before the driver exits.  Deterministic faults (kill actor i at
    iteration n, delay a rollout) come from
    :mod:`smartcal_tpu.runtime.faults`.

    ``actor_mode`` picks the fleet backend: ``"thread"`` (default, the
    PR 10 shape, bit-identical to it) runs each actor as a host thread
    in this process; ``"process"`` spawns each actor as a WORKER
    PROCESS (its own interpreter, its own GIL) exchanging framed
    batches/heartbeats over IPC, with per-slot ingest shards instead of
    one global queue — same work function, same key streams, so the
    mode changes where rollouts run, never what they compute.
    ``sim_hosts > 1`` (process mode only) tags contiguous slot blocks
    with simulated host ids (the single-machine multi-host rehearsal).
    ``replay_shards > 0`` swaps the learner's flat HBM buffer for the
    mesh-sharded one (:mod:`smartcal_tpu.rl.replay_sharded`): stores
    land shard-local, sampling merges per-shard draws via collectives,
    priority updates scatter shard-local.

    ``is_clip`` arms the IMPACT staleness-clipped importance weighting
    (transitions carry the actor's snapshot version + behavior log-prob;
    see :func:`smartcal_tpu.rl.sac.impact_weights`), ``ere_eta`` the
    emphasizing-recent-experience sampling knob, and ``publish_every``
    the weight-publication cadence in learner rounds (> 1 forces
    staleness — the ablation knob of tools/ablate_isclip.py).
    Checkpoints (``ckpt_every``/``resume``) capture the fleet state
    including every actor slot's next rollout iteration, so a resumed
    fleet continues each per-(actor, iteration) key stream.

    Returns ``((agent_state, buf), scores, fleet_summary)`` — the
    summary carries restart counts plus the steady-state aggregate
    ``env_steps_per_s`` (measured after the warmup rounds) and, when
    the IS-clip is armed, the steady-state mean
    ``transition_staleness_mean`` / ``is_clip_saturation``.
    """
    from smartcal_tpu.runtime import Fleet
    from smartcal_tpu.train.blocks import TrainRuntime, train_obs

    env_cfg = enet.EnetConfig(**(env_kwargs or {}))
    agent_kwargs = dict(agent_kwargs or {})
    agent_kwargs.setdefault("prioritized", True)
    agent_cfg = sac.SACConfig(obs_dim=env_cfg.obs_dim, n_actions=2,
                              use_hint=use_hint, is_clip=is_clip,
                              ere_eta=ere_eta, **agent_kwargs)
    n_trans = batch_envs * rollout_epochs * rollout_steps

    factory_kwargs = dict(env_kwargs=dict(env_kwargs or {}),
                          agent_kwargs=agent_kwargs, use_hint=use_hint,
                          is_clip=is_clip, ere_eta=ere_eta,
                          batch_envs=batch_envs,
                          rollout_epochs=rollout_epochs,
                          rollout_steps=rollout_steps, seed=seed)
    # thread mode calls the SAME factory in-process; process mode ships
    # the picklable spec and each worker rebuilds the identical program
    work_fn = (None if actor_mode == "process"
               else _enet_fleet_work_fn(**factory_kwargs))
    worker_spec = {"factory":
                   "smartcal_tpu.parallel.learner:_enet_fleet_work_fn",
                   "kwargs": factory_kwargs}

    def _ingest(agent, buf, flat, key, learner_version):
        buf = rp.backend_for(buf).replay_add_batch(buf, flat)
        return sac.learn(agent_cfg, agent, buf, key,
                         learner_version=learner_version)

    ingest = jax.jit(_ingest)

    key = jax.random.PRNGKey(seed)
    key, k0 = jax.random.split(key)
    agent = sac.sac_init(k0, agent_cfg)
    spec = rp.transition_spec(env_cfg.obs_dim, agent_cfg.n_actions)
    if is_clip > 0:
        spec = rp.versioned_spec(spec)
    if replay_shards:
        buf = make_sharded_fleet_buffer(agent_cfg.mem_size, spec,
                                        replay_shards)
    else:
        buf = rp.replay_init(agent_cfg.mem_size, spec)

    def ingest_batch(agent, buf, host_trs, kl, weights_version,
                     learner_version):
        flat = {k2: jnp.asarray(v) for k2, v in host_trs.items()}
        if is_clip > 0:
            # the learner stamps the actor's snapshot version onto the
            # whole block (the queue tuple carries it) — the staleness
            # currency of the fused IS-clipped learn
            flat["version"] = jnp.full((flat["reward"].shape[0],),
                                       weights_version, jnp.int32)
        return ingest(agent, buf, flat, kl,
                      jnp.asarray(learner_version, jnp.int32))

    tob = train_obs("parallel_learner_supervised", metrics=metrics,
                    quiet=quiet, diag=diag, watchdog=watchdog, seed=seed,
                    n_actors=n_actors, batch_envs=batch_envs,
                    is_clip=is_clip, ere_eta=ere_eta,
                    actor_mode=actor_mode, replay_shards=replay_shards,
                    sim_hosts=sim_hosts)
    rt = TrainRuntime("parallel_learner_supervised", ckpt_dir=ckpt_dir,
                      ckpt_every=ckpt_every, keep=keep_ckpts,
                      resume=resume, tob=tob)
    fleet = Fleet(n_actors, work_fn, name="enet-actor",
                  heartbeat_timeout=heartbeat_timeout,
                  max_restarts=max_restarts, backoff=restart_backoff,
                  seed=seed, actor_mode=actor_mode,
                  worker_spec=worker_spec if actor_mode == "process"
                  else None, hosts=sim_hosts)
    return run_supervised_loop(fleet, ingest_batch, agent, buf, key,
                               episodes, n_trans, tob,
                               queue_timeout=queue_timeout,
                               max_empty_rounds=max_empty_rounds,
                               rt=rt, publish_every=publish_every)


def run_supervised_loop(fleet, ingest_batch, agent, buf, key, episodes,
                        n_trans, tob, queue_timeout=30.0,
                        max_empty_rounds=20, rt=None, publish_every=1,
                        warmup_rounds=2):
    """The supervised learners' shared ingest loop (enet + demix fleets).

    Per learner episode: collect whatever actor batches arrived (at most
    one per actor slot), ingest + learn each through the fused
    device-resident step, bump the learner's policy version, publish
    fresh weights every ``publish_every`` rounds, run one supervision
    pass (restarts), and feed the watchdog.  A trip stops AND joins the
    actor fleet before the loop exits.  Owns the fleet and the TrainObs
    handle (always stopped/closed on the way out).

    ``ingest_batch(agent, buf, host_trs, key, weights_version,
    learner_version)`` is the fused learn entry; the loop stamps each
    block with the version the producing actor held, so the IS-clip
    weighting and the staleness gauges share one currency (learner
    rounds).  ``rt`` (a TrainRuntime) arms checkpoint/resume: payloads
    capture agent + replay + key + scores + the learner version + every
    actor slot's next rollout iteration (``fleet.slot_iterations``).

    Telemetry per round: aggregate + per-actor ``transitions_per_s``
    gauges, ``weight_staleness_versions`` (max), per-slot
    ``ingest_queue_depth`` gauges (process fleets — the single-slow-
    shard visibility the aggregate hid) plus the aggregate, per-shard
    ``replay_shard_occupancy`` gauges (sharded buffers; derived from
    the global counter, no array pull) and, when the IS-clip is armed,
    the ``staleness_mean``/``is_clip_saturation``/``is_clip_mean``
    gauges off the fused step's metrics.  The summary reports the
    steady-state aggregate env-steps/s measured AFTER ``warmup_rounds``
    (compile excluded — the actor-scaling bench's metric) and the
    steady-state means of the staleness/saturation gauges.
    """
    import time

    import numpy as np

    from smartcal_tpu import obs
    from smartcal_tpu.runtime import pack_replay, unpack_replay

    scores = []
    ep0 = 0
    start_iters = None
    version0 = None
    if rt is not None:
        restored = rt.restore()
        if restored is not None and restored.get("kind") != "fleet":
            # a foreign payload (e.g. an SPMD dist_per checkpoint dir)
            # cannot restore per-actor iterations — refuse loudly rather
            # than resume with every key stream silently replayed
            raise ValueError(
                f"checkpoint kind {restored.get('kind')!r} is not a "
                "supervised-fleet payload; point --ckpt-dir at a fleet "
                "run's checkpoints")
        if restored is not None:
            agent = jax.tree_util.tree_map(jnp.asarray,
                                           restored["agent_state"])
            buf = unpack_replay(restored["replay"])
            key = jnp.asarray(restored["key"])
            scores = list(restored["scores"])
            ep0 = int(restored["episode"])
            start_iters = {int(k): int(v) for k, v
                           in restored["actor_iterations"].items()}
            version0 = int(restored["learner_version"])
    # steady-state throughput window: CONTINUOUS wall clock from the end
    # of the warmup rounds (compile amortization) to loop exit — counting
    # everything (ingest, gauges, logging, checkpoints), so the reported
    # aggregate env-steps/s is the sustained pipeline rate, not just the
    # queue-drain burst rate
    meas_trans, meas_t0, rounds = 0, None, 0
    stale_means, clip_sats, critic_losses = [], [], []
    sharded = hasattr(buf, "n_shards")
    try:
        fleet.start(agent, start_iterations=start_iters, version=version0)
        learner_version = fleet.version
        ep, empty_rounds = ep0, 0
        while ep < episodes:
            t0 = time.perf_counter()
            batches = fleet.collect(max_items=fleet.n_actors,
                                    timeout=queue_timeout)
            fleet.poll()
            if not batches:
                empty_rounds += 1
                if len(fleet.failed_slots) == fleet.n_actors:
                    tob.echo("all actor slots permanently failed "
                             f"(after {fleet.restarts_total()} restarts); "
                             "stopping")
                    break
                if empty_rounds >= max_empty_rounds:
                    tob.echo(f"no actor output for {empty_rounds} rounds; "
                             "stopping")
                    break
                continue
            empty_rounds = 0
            staleness = 0
            per_actor = {}
            with tob.span("learner_episode", episode=ep,
                          batches=len(batches)):
                for actor_id, iteration, wv, host_trs in batches:
                    key, kl = jax.random.split(key)
                    agent, buf, metrics_out = ingest_batch(
                        agent, buf, host_trs, kl, wv, learner_version)
                    staleness = max(staleness, learner_version - wv)
                    per_actor[actor_id] = per_actor.get(actor_id, 0) \
                        + n_trans
            # the learner's policy advanced this round: bump ITS version;
            # actors only see it when the publication cadence says so
            # (publish_every > 1 is the forced-staleness ablation knob)
            learner_version += 1
            if publish_every <= 1 or (ep + 1) % publish_every == 0:
                fleet.set_weights(agent, version=learner_version)
            wall = time.perf_counter() - t0
            rounds += 1
            if rounds == warmup_rounds:
                meas_t0 = time.perf_counter()
            elif rounds > warmup_rounds:
                meas_trans += len(batches) * n_trans
            score = float(np.mean([np.mean(b[3]["reward"])
                                   for b in batches]))
            scores.append(score)
            obs.gauge_set("actor_transitions_per_s",
                          round(len(batches) * n_trans / max(wall, 1e-9),
                                2))
            for aid, tr_n in sorted(per_actor.items()):
                obs.gauge_set("per_actor_transitions_per_s",
                              round(tr_n / max(wall, 1e-9), 2), actor=aid)
            obs.gauge_set("weight_staleness_versions", staleness)
            # per-slot ingest depth: one gauge per shard (process
            # fleets) so a single backed-up slot is visible, plus the
            # aggregate every mode reports
            depths = fleet.queue_depths()
            obs.gauge_set("ingest_queue_depth", depths["aggregate"])
            for slot, d in sorted(depths.get("per_slot", {}).items()):
                obs.gauge_set("ingest_queue_depth", d, slot=slot)
            if sharded:
                # occupancy per replay shard, derived from the global
                # store counter alone (round-robin keeps shards within
                # one transition of each other — a skew here means the
                # interleave broke)
                from smartcal_tpu.rl import replay_sharded as rps

                occ = rps.shard_occupancy(int(buf.cntr), buf.n_shards,
                                          buf.local_size)
                for sh_i, o in enumerate(occ):
                    obs.gauge_set("replay_shard_occupancy", o, shard=sh_i)
            if "staleness_mean" in metrics_out:
                # the fused step's IS-clip telemetry (batch-level means,
                # already on device): the staleness distribution the
                # clipped weights absorbed and how often the clip bound
                # did real work
                obs.gauge_set("transition_staleness_mean",
                              round(float(metrics_out["staleness_mean"]),
                                    4))
                obs.gauge_set("is_clip_saturation",
                              round(float(
                                  metrics_out["is_clip_saturation"]), 4))
                obs.gauge_set("is_clip_mean",
                              round(float(metrics_out["is_clip_mean"]), 4))
                if rounds > warmup_rounds:
                    stale_means.append(
                        float(metrics_out["staleness_mean"]))
                    clip_sats.append(
                        float(metrics_out["is_clip_saturation"]))
            if rounds > warmup_rounds and "critic_loss" in metrics_out:
                critic_losses.append(float(metrics_out["critic_loss"]))
            tripped = False
            if tob.collect_diag:
                tripped = tob.record_diag(
                    {"critic_loss": float(metrics_out["critic_loss"])},
                    episode=ep)
            tripped = tob.log_replay_health(buf, episode=ep) or tripped
            tob.episode(ep, score, scores, echo=False,
                        transitions=len(batches) * n_trans,
                        actors_alive=fleet.alive_count,
                        restarts=fleet.restarts_total(),
                        staleness_versions=staleness)
            tob.echo(f"episode {ep} mean reward {score:.4f} "
                     f"(batches {len(batches)}, alive {fleet.alive_count})",
                     event=None)
            ep += 1
            if tripped:
                # watchdog trip: stop AND join the actor threads before
                # leaving the loop — no actor may keep rolling out
                # against a dead learner.  Never checkpoint the tripped
                # round's (possibly poisoned) state.
                joined = fleet.stop(join=True)
                tob.echo(f"watchdog trip: stopped fleet "
                         f"({joined} actor thread(s) joined)")
                break
            if rt is not None:
                rt.maybe_checkpoint(ep, lambda: {
                    "kind": "fleet", "episode": ep, "scores": list(scores),
                    "agent_state": jax.device_get(agent),
                    "replay": pack_replay(buf),
                    "key": jax.device_get(key),
                    "learner_version": learner_version,
                    "actor_iterations": fleet.slot_iterations()})
    finally:
        meas_wall = (time.perf_counter() - meas_t0
                     if meas_t0 is not None else 0.0)
        fleet.stop(join=True)
        tob.close()
    summary = {"restarts": fleet.restarts_total(),
               "failed_slots": sorted(fleet.failed_slots),
               "alive_at_exit": fleet.alive_count,
               "rounds": rounds,
               "transitions_steady": meas_trans,
               "wall_steady_s": round(meas_wall, 4),
               "env_steps_per_s": (round(meas_trans / meas_wall, 2)
                                   if meas_wall > 0 and meas_trans
                                   else None)}
    if stale_means:
        # steady-state staleness the IS-clip absorbed (the curve the
        # actor-scaling bench records at every point)
        summary["transition_staleness_mean"] = round(
            float(np.mean(stale_means)), 4)
        summary["is_clip_saturation"] = round(
            float(np.mean(clip_sats)), 4)
    if critic_losses:
        # next to the staleness: did the clipped TD loss stay bounded?
        summary["critic_loss_mean"] = round(
            float(np.mean(critic_losses)), 4)
    return (agent, buf), scores, summary


def main(argv=None):
    """CLI (run_process of elasticnet/distributed_per_sac.py:154-194 —
    the mesh IS the world; multi-host runs pass --coordinator/--num_processes
    /--process_id on every host, the jax.distributed replacement for the
    reference's MASTER_ADDR/world_size/rank plumbing).

    Usage: python -m smartcal_tpu.parallel.learner --episodes 100
        [--n-actors 8] [--batch-envs 4] [--is-clip 2.0] [--ere 0.98]
        [--actor-mode process] [--replay-shards 4] [--sim-hosts 2]
        [--use_hint] [--learn_per_transition]
        [--coordinator host:port --num_processes N --process_id i]
    """
    import argparse

    from . import multihost

    from smartcal_tpu import obs
    from smartcal_tpu.train.blocks import (add_batched_args, add_fleet_args,
                                           add_obs_args, add_runtime_args,
                                           diag_from_args)

    p = argparse.ArgumentParser(description=main.__doc__)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--episodes", type=int, default=100)
    p.add_argument("--actors", type=int, default=None,
                   help="deprecated alias of --n-actors")
    p.add_argument("--use_hint", action="store_true")
    p.add_argument("--learn_per_transition", action="store_true")
    p.add_argument("--supervised", action="store_true",
                   help="actor-THREAD fleet with heartbeat supervision, "
                        "restart backoff and clean shutdown on watchdog "
                        "trip (see train_supervised) instead of the fused "
                        "SPMD program")
    p.add_argument("--heartbeat_timeout", type=float, default=60.0,
                   help="supervised mode: seconds without an actor "
                        "heartbeat before it counts as hung")
    p.add_argument("--max_restarts", type=int, default=3,
                   help="supervised mode: restarts per actor slot before "
                        "it is abandoned")
    add_fleet_args(p)
    add_batched_args(p)
    add_obs_args(p)
    add_runtime_args(p)
    multihost.add_cli_args(p)
    args = p.parse_args(argv)
    n_actors = args.n_actors or args.actors
    if multihost.initialize_from_args(args):
        obs.echo(f"multihost: {multihost.runtime_summary()}",
                 event="multihost")
    if args.actor_mode == "process" or args.replay_shards \
            or args.sim_hosts > 1:
        # the process fleet / sharded replay are supervised-mode
        # features; flip the switch rather than silently ignoring them
        args.supervised = True
    if args.supervised:
        _, scores, _ = train_supervised(
            seed=args.seed, episodes=args.episodes,
            n_actors=n_actors or 2, use_hint=args.use_hint,
            quiet=args.quiet, metrics=args.metrics,
            diag=diag_from_args(args),
            watchdog=getattr(args, "watchdog", False),
            heartbeat_timeout=args.heartbeat_timeout,
            max_restarts=args.max_restarts,
            batch_envs=args.batch_envs, is_clip=args.is_clip,
            ere_eta=args.ere_eta, publish_every=args.publish_every,
            ckpt_dir=args.ckpt_dir, ckpt_every=args.ckpt_every,
            keep_ckpts=args.keep_ckpts, resume=args.resume,
            actor_mode=args.actor_mode,
            replay_shards=args.replay_shards, sim_hosts=args.sim_hosts)
        return scores
    _, scores = train_distributed(
        seed=args.seed, episodes=args.episodes, n_actors=n_actors,
        use_hint=args.use_hint,
        learn_per_transition=args.learn_per_transition,
        quiet=args.quiet, metrics=args.metrics,
        diag=diag_from_args(args),
        watchdog=getattr(args, "watchdog", False),
        ckpt_dir=args.ckpt_dir, ckpt_every=args.ckpt_every,
        resume=args.resume)
    return scores


if __name__ == "__main__":
    main()
