"""Mesh-sharded calibration: frequency-parallel ADMM + chunk-parallel
influence.

The reference distributes calibration across frequency sub-bands with MPI
ranks inside ``sagecal-mpi`` (consensus ADMM, ``calibration/docal.sh:12``)
and parallelizes influence over calibration time-chunks with
multiprocessing pools (``analysis_torch.py:160-170``).  Here both become
``shard_map`` programs:

* ``solve_admm_sharded`` — the frequency axis of (V, C, freqs) is sharded
  over the mesh axis ``fp``; cal/solver.solve_admm's Z consensus update
  psums over ``fp`` (the MPI allreduce as an ICI collective).
* ``influence_sharded`` — the calibration-interval axis is sharded over
  ``sp``; chunks are embarrassingly parallel (the pool had no
  communication either), so the only collective is the output gather.
"""

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:                                  # jax >= 0.6: top-level export
    from jax import shard_map as _shard_map_impl
    _SM_CHECK_KW = "check_vma"
except ImportError:                   # older pins: experimental module
    from jax.experimental.shard_map import shard_map as _shard_map_impl
    _SM_CHECK_KW = "check_rep"

from ..cal import influence as influence_mod
from ..cal import solver
from . import mesh as mesh_registry
from .mesh import (AXIS_BASELINE, AXIS_CHUNK, AXIS_DATA, AXIS_FREQ,
                   AXIS_LANE)

# jitted baseline-sharded influence programs, keyed on (mesh, statics) —
# see influence_baseline_sharded
_BSHARD_CACHE: dict = {}

# jitted lane x baseline composed batched-influence programs, keyed on
# (mesh, statics) — see influence_images_batched_sharded
_COMPOSE_CACHE: dict = {}


def shard_map(f, mesh, in_specs, out_specs, check_vma=False):
    """Version-tolerant ``shard_map``: newer jax renamed the replication
    check kwarg (check_rep -> check_vma) and moved the function out of
    experimental; the solver must run on both pins."""
    return _shard_map_impl(f, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs,
                           **{_SM_CHECK_KW: check_vma})


def solve_admm_sharded(mesh: Mesh, V, C, freqs, f0, rho,
                       cfg: solver.SolverConfig, axis: str = AXIS_FREQ,
                       n_chunks: Optional[int] = None,
                       admm_iters=None, freq_range=None,
                       collect_stats: bool = False):
    """Consensus-ADMM solve with the frequency axis sharded over ``axis``.

    V (Nf, T, B, 2, 2, 2), C (Nf, K, T*B, 4, 2), freqs (Nf,) are global;
    Nf must divide by the axis size.  Returns a SolveResult with J /
    residual / final_cost frequency-sharded and Z / sigmas replicated —
    bitwise the same math as the single-device solve (the psum IS the
    global sum).

    ``collect_stats`` threads the solver telemetry out (SolverStats —
    consensus residuals are psummed over ``axis`` inside the solve, so
    the stats come out replicated/global).
    """
    nfp = mesh.shape[axis]
    mesh_registry.check_axis_divides(V.shape[0], nfp, axis=axis,
                                     what="solve_admm_sharded Nf")
    if cfg.polytype == 1 and freq_range is None:
        import numpy as np
        fr = np.asarray(freqs)
        freq_range = (float(fr.min()), float(fr.max()))

    fn = partial(solver.solve_admm, cfg=cfg, axis_name=axis,
                 n_chunks=n_chunks, freq_range=freq_range,
                 collect_stats=collect_stats)
    stats_spec = (solver.SolverStats(admm_iters=P(), primal_resid=P(),
                                     inner_iters=P(), init_iters=P(),
                                     n_segments=P())
                  if collect_stats else None)
    out_specs = solver.SolveResult(
        J=P(axis), Z=P(), residual=P(axis), sigma_res=P(),
        sigma_data=P(), final_cost=P(axis), stats=stats_spec)
    if admm_iters is None:
        sharded = shard_map(
            lambda v, c, f, r: fn(v, c, f, f0, r),
            mesh=mesh,
            in_specs=(P(axis), P(axis), P(axis), P()),
            out_specs=out_specs,
            check_vma=False)
        return sharded(V, C, jnp.asarray(freqs), jnp.asarray(rho))
    # dynamic iteration count (the demixing action's maxiter) rides as a
    # replicated OPERAND, not a closure: a closed-over python int would be
    # baked into the trace (and a closed-over array is not portable across
    # shard_map versions), while an operand reuses one compiled program
    # for every maxiter value
    sharded = shard_map(
        lambda v, c, f, r, it: fn(v, c, f, f0, r, admm_iters=it),
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis), P(), P()),
        out_specs=out_specs,
        check_vma=False)
    return sharded(V, C, jnp.asarray(freqs), jnp.asarray(rho),
                   jnp.asarray(admm_iters))


def solve_admm_sharded2d(mesh: Mesh, Vb, Cb, freqs_b, f0_b, rho,
                         cfg: solver.SolverConfig, dp_axis: str = AXIS_DATA,
                         fp_axis: str = AXIS_FREQ,
                         n_chunks: Optional[int] = None,
                         admm_iters=None, freq_range=None):
    """Batched frequency-consensus solves on a 2D (dp x fp) mesh.

    The v5e-16 operating point (BASELINE.md): a BATCH of independent
    episodes sharded over ``dp`` while each episode's frequency axis is
    sharded over ``fp`` — the ADMM Z-update psums over ``fp`` only, so
    consensus never crosses episode boundaries.  Vb (E, Nf, T, B, 2, 2, 2),
    Cb (E, Nf, K, T*B, 4, 2), freqs_b (E, Nf), f0_b (E,); E must divide by
    the dp size and Nf by the fp size; rho (K,) is shared.

    The reference reaches this regime by scheduling one sagecal-mpi job
    per episode side by side (calibration/docal.sh); here it is one SPMD
    program on one mesh.
    """
    ndp, nfp = mesh.shape[dp_axis], mesh.shape[fp_axis]
    mesh_registry.check_axis_divides(Vb.shape[0], ndp, axis=dp_axis,
                                     what="solve_admm_sharded2d E")
    mesh_registry.check_axis_divides(Vb.shape[1], nfp, axis=fp_axis,
                                     what="solve_admm_sharded2d Nf")
    # Bernstein basis band edges are PER EPISODE (each episode's own
    # global band — a single shared range would build every episode a
    # different basis than its own per-episode solve uses), carried as
    # vmapped scalars; an explicit freq_range applies to all episodes.
    E = Vb.shape[0]
    if cfg.polytype == 1:
        if freq_range is not None:
            flo = jnp.full((E,), freq_range[0], jnp.float32)
            fhi = jnp.full((E,), freq_range[1], jnp.float32)
        else:
            fa = jnp.asarray(freqs_b, jnp.float32)
            flo, fhi = fa.min(axis=1), fa.max(axis=1)
    else:
        flo = fhi = jnp.zeros((E,), jnp.float32)  # unused by polytype 0

    fn = partial(solver.solve_admm, cfg=cfg, axis_name=fp_axis,
                 n_chunks=n_chunks, admm_iters=admm_iters)
    use_range = cfg.polytype == 1

    def one(v, c, f, f0, lo, hi, r):
        return fn(v, c, f, f0, r,
                  freq_range=(lo, hi) if use_range else None)

    # per-episode outputs batch over the leading dp axis; within an
    # episode the layout matches solve_admm_sharded
    out_specs = solver.SolveResult(
        J=P(dp_axis, fp_axis), Z=P(dp_axis), residual=P(dp_axis, fp_axis),
        sigma_res=P(dp_axis), sigma_data=P(dp_axis),
        final_cost=P(dp_axis, fp_axis))
    sharded = shard_map(
        jax.vmap(one, in_axes=(0, 0, 0, 0, 0, 0, None)),
        mesh=mesh,
        in_specs=(P(dp_axis, fp_axis), P(dp_axis, fp_axis),
                  P(dp_axis, fp_axis), P(dp_axis), P(dp_axis), P(dp_axis),
                  P()),
        out_specs=out_specs,
        check_vma=False)
    return sharded(Vb, Cb, jnp.asarray(freqs_b),
                   jnp.asarray(f0_b, jnp.float32), flo, fhi,
                   jnp.asarray(rho))


def influence_sharded(mesh: Mesh, R, C, J, hadd, n_stations: int,
                      n_chunks: int, axis: str = AXIS_CHUNK, fullpol=False,
                      perdir=False, optimized=True, block_baselines=0,
                      precision: str = "f32"):
    """Influence visibilities with the calibration-interval (chunk) axis
    sharded over ``axis`` (the reference's process pool as a mesh axis).

    Same signature/semantics as cal/influence.influence_visibilities,
    including the ``optimized`` formulation switch (default: the
    scatter-free/adjoint chain; False = the retained oracle kernels) and
    the SKA-tier statics (``block_baselines``/``precision`` — the
    chunk-sharded route must run the SAME kernels the accounting layer
    records); ``n_chunks`` must divide by the axis size.
    """
    nsp = mesh.shape[axis]
    mesh_registry.check_axis_divides(n_chunks, nsp, axis=axis,
                                     what="influence_sharded n_chunks")
    B = n_stations * (n_stations - 1) // 2
    T = C.shape[1] // B
    Td = T // n_chunks
    K = C.shape[0]
    local_chunks = n_chunks // nsp

    # pre-chunk so the shard axis is leading
    R4 = R.reshape(n_chunks, 2 * B * Td, 2, 2)
    C4 = jnp.moveaxis(C.reshape(K, n_chunks, B * Td, 4, 2), 1, 0)

    def local(r4, c4, j):
        r = r4.reshape(local_chunks * 2 * B * Td, 2, 2)
        c = jnp.moveaxis(c4, 0, 1).reshape(K, local_chunks * B * Td, 4, 2)
        # use_pallas=False: pallas_call has no GSPMD partitioning rule
        return influence_mod.influence_visibilities(
            r, c, j, hadd, n_stations, local_chunks, fullpol=fullpol,
            perdir=perdir, optimized=optimized,
            block_baselines=block_baselines, precision=precision,
            use_pallas=False)

    out_specs = influence_mod.InfluenceResult(
        vis=P(None, axis) if perdir else P(axis), llr=P(axis))
    sharded = shard_map(local, mesh=mesh,
                       in_specs=(P(axis), P(axis), P(axis)),
                       out_specs=out_specs, check_vma=False)
    res = sharded(R4, C4, J)
    # local results concatenate along the chunk-major sample axis, which is
    # exactly the global time-major order
    return res


def influence_baseline_sharded(mesh: Mesh, R, C, J, hadd, n_stations: int,
                               n_chunks: int, axis: str = AXIS_BASELINE,
                               fullpol=False, perdir=False,
                               precision: str = "f32"):
    """Influence visibilities with the BASELINE axis sharded over
    ``axis`` — the B ~ N^2 (SKA-scale) partition: the (B, ...)
    coherency/residual/lhs tensors and every per-baseline einsum
    temporary live 1/n-th per device, while the per-direction 4N x 4N
    solves run replicated.  Collectives happen ONLY at the per-direction
    reductions (one psum of the assembled partial Hessian, one of the
    adjoint chain's per-station G sum, scalar LLR norms) — verified
    host-transfer-free under ``jax.transfer_guard`` in
    tests/test_nscale_kernels.py, the PR 12 sharded-replay pattern.

    Same signature/semantics as cal/influence.influence_visibilities on
    the optimized chain (``precision`` selects the bf16 policy rows);
    B = N(N-1)/2 must divide by the axis size.  Equal to the
    single-device optimized chain to float round-off (the shard psum
    reassociates the station/Hessian sums).
    """
    import numpy as np

    nbp = mesh.shape[axis]
    B = n_stations * (n_stations - 1) // 2
    mesh_registry.check_axis_divides(B, nbp, axis=axis,
                                     what="influence_baseline_sharded B")
    T = C.shape[1] // B
    Td = T // n_chunks
    K = C.shape[0]

    # pre-chunk with the baseline axis exposed for sharding
    R3 = R.reshape(n_chunks, Td, B, 2, 2, 2)
    C5 = jnp.moveaxis(jnp.swapaxes(
        C.reshape(K, n_chunks, Td, B, 2, 2, 2), -3, -2), 1, 0)
    # host numpy here; the indices reach the device only through the
    # explicit device_put below (legal under transfer_guard "disallow")
    p_np, q_np = np.triu_indices(n_stations, 1)
    p_idx = np.asarray(p_np, np.int32)
    q_idx = np.asarray(q_np, np.int32)

    in_specs = (P(None, None, axis), P(None, None, None, axis), P(), P(),
                P(axis), P(axis))
    # one JITTED program per (mesh, statics): a fresh shard_map closure
    # per call would retrace every time — paying trace cost per episode
    # AND pulling trace-time constants through the transfer guard the
    # steady state is tested under
    cache_key = (mesh, axis, n_stations, fullpol, perdir, precision)
    sharded = _BSHARD_CACHE.get(cache_key)
    if sharded is None:
        def local(r3, c5, j, h, pi, qi):
            return influence_mod.influence_visibilities_blocal(
                r3, c5, j, pi, qi, h, n_stations, B, fullpol=fullpol,
                perdir=perdir, axis_name=axis, precision=precision)

        out_specs = influence_mod.InfluenceResult(
            vis=P(None, None, axis) if perdir else P(None, axis),
            llr=P())
        sharded = jax.jit(shard_map(local, mesh=mesh, in_specs=in_specs,
                                    out_specs=out_specs,
                                    check_vma=False))
        _BSHARD_CACHE[cache_key] = sharded
    # explicit placement onto THIS mesh: upstream operands may arrive
    # committed to a different mesh (e.g. a frequency-sharded solve's
    # residual), which jit refuses to mix implicitly — and the explicit
    # device_put keeps the steady-state call legal under
    # jax.transfer_guard("disallow") (tested)
    operands = [
        jax.device_put(x, NamedSharding(mesh, spec)) for x, spec in
        zip((R3, C5, jnp.asarray(J), jnp.asarray(hadd), p_idx, q_idx),
            in_specs)]
    res = sharded(*operands)
    # the concatenated baseline axis restores the global time-major
    # (ck = t*B + b) sample order
    if perdir:
        vis = res.vis.reshape(K, T * B, 4, 2)
    else:
        vis = res.vis.reshape(T * B, 4, 2)
    return influence_mod.InfluenceResult(vis=vis, llr=res.llr)


def influence_images_sharded(mesh: Mesh, residual, C, J, hadd_all, freqs,
                             uvw, cell, n_stations: int, n_chunks: int,
                             npix: int, axis: str = AXIS_FREQ, optimized=True,
                             block_baselines=0, imager_block_r=0,
                             precision: str = "f32"):
    """Mean influence dirty image with the FREQUENCY axis sharded over
    ``axis``: each shard runs :func:`cal.influence.influence_images_multi`
    on its local sub-bands and the mean is one psum.

    residual (Nf, T, B, 2, 2, 2); C (Nf, K, T*B, 4, 2);
    J (Nf, Ts, K, 2N, 2, 2); hadd_all (Nf, K); freqs (Nf,);
    uvw (T*B, 3).  Nf must divide by the axis size.  Returns the
    replicated (npix, npix) mean image — the doinfluence.sh average the
    envs observe, with sub-bands fanned out over devices.  The default
    ``optimized`` chain is matmul-only end to end (scatter-free Hessian,
    adjoint transpose solve, rank-factored DFT imager), so every stage
    partitions cleanly under GSPMD.
    """
    nfp = mesh.shape[axis]
    Nf = residual.shape[0]
    mesh_registry.check_axis_divides(Nf, nfp, axis=axis,
                                     what="influence_images_sharded Nf")

    def local(r, c, j, h, f, uvw_):
        imgs = influence_mod.influence_images_multi(
            r, c, j, h, f, uvw_, cell, n_stations, n_chunks, npix,
            use_pallas=False,           # pallas_call has no partitioning rule
            optimized=optimized, block_baselines=block_baselines,
            imager_block_r=imager_block_r, precision=precision)
        return jax.lax.psum(jnp.sum(imgs, axis=0), axis)

    sharded = shard_map(local, mesh=mesh,
                        in_specs=(P(axis), P(axis), P(axis), P(axis),
                                  P(axis), P()),
                        out_specs=P(), check_vma=False)
    return sharded(residual, C, J, hadd_all, jnp.asarray(freqs),
                   uvw) / Nf


def influence_images_batched_sharded(mesh: Mesh, residual_b, Cb, Jb, rho_b,
                                     alpha_b, freqs_b, f0_b, uvw_b, cell_b,
                                     n_stations: int, n_chunks: int,
                                     npix: int, n_poly: int = 2,
                                     polytype: int = 0,
                                     lane_axis: str = AXIS_LANE,
                                     baseline_axis: str = AXIS_BASELINE,
                                     imager_block_r: int = 0,
                                     precision: str = "f32"):
    """Batched mean influence images with the LANE axis **and** the
    BASELINE axis sharded in ONE ``shard_map`` program on the composed
    registry mesh (ISSUE 17 tentpole): each device holds E/n_lane lanes
    by B/n_baseline baselines, runs the shard-local influence engine
    (:func:`cal.influence.influence_visibilities_blocal`) per lane per
    sub-band, and images its local baselines' partial DFT.  Collectives
    stay CONFINED to their axis: the Hessian / adjoint-G / LLR psums and
    the final partial-image sum ride ``baseline_axis`` only; the lane
    axis never carries a collective (lanes are independent episodes).

    Operand layout mirrors ``RadioBackend.batched_influence_operands``:
    residual_b (E, Nf, T, B, 2, 2, 2); Cb (E, Nf, K, T*B, 4, 2);
    Jb (E, Nf, Ts, K, 2N, 2, 2); rho_b/alpha_b (E, K); freqs_b (E, Nf);
    f0_b (E,); uvw_b (E, T*B, 3); cell_b (E,).  Returns (E, npix, npix)
    lane-sharded mean images — equal to the vmapped unsharded chain to
    float round-off (the baseline psum reassociates the station sums).

    Either axis may have size 1 (a P(axis) spec on it is a no-op), so
    the SAME program expresses the lane-only, baseline-only and composed
    arms of the route matrix.  The per-baseline-block imager runs the
    plain/blocked XLA factored DFT (never pallas: shapes are already
    local here, and the promotion gate routes pallas only outside
    shard_map until the hardware flag-flip).
    """
    import numpy as np

    nl = mesh.shape[lane_axis]
    nb = mesh.shape[baseline_axis]
    E = residual_b.shape[0]
    B = n_stations * (n_stations - 1) // 2
    mesh_registry.check_axis_divides(
        E, nl, axis=lane_axis, what="influence_images_batched_sharded E")
    mesh_registry.check_axis_divides(
        B, nb, axis=baseline_axis,
        what="influence_images_batched_sharded B")
    Nf = residual_b.shape[1]
    T = residual_b.shape[2]
    K = Cb.shape[2]

    # expose the baseline axis for sharding: the (T*B,) sample axis is
    # t-major, so a bare P on it would split TIMES across shards — the
    # (T, B) unfold makes the shard slice a contiguous baseline range
    C7 = Cb.reshape(E, Nf, K, T, B, 4, 2)
    U4 = jnp.asarray(uvw_b).reshape(E, T, B, 3)
    p_np, q_np = np.triu_indices(n_stations, 1)
    p_idx = np.asarray(p_np, np.int32)
    q_idx = np.asarray(q_np, np.int32)

    in_specs = (P(lane_axis, None, None, baseline_axis),
                P(lane_axis, None, None, None, baseline_axis),
                P(lane_axis), P(lane_axis), P(lane_axis), P(lane_axis),
                P(lane_axis), P(lane_axis, None, baseline_axis),
                P(lane_axis), P(baseline_axis), P(baseline_axis))
    cache_key = (mesh, lane_axis, baseline_axis, n_stations, n_chunks,
                 npix, n_poly, polytype, imager_block_r, precision)
    sharded = _COMPOSE_CACHE.get(cache_key)
    if sharded is None:
        from ..cal import imager as imager_mod

        Ts = n_chunks

        def lane(res, c7, j, r, a, f, f0_, u4, cl, pi, qi):
            hadd = influence_mod.consensus_hadd_all(
                r, a, f, f0_, n_poly=n_poly, polytype=polytype)  # (Nf, K)
            Bl = res.shape[2]
            Td = res.shape[1] // Ts

            def band(args):
                rk, c, jj, h, ff = args
                R3 = rk.reshape(Ts, Td, Bl, 2, 2, 2)
                C5 = jnp.moveaxis(jnp.swapaxes(
                    c.reshape(K, Ts, Td, Bl, 2, 2, 2), -3, -2), 1, 0)
                inf = influence_mod.influence_visibilities_blocal(
                    R3, C5, jj, pi, qi, h, n_stations, B,
                    axis_name=baseline_axis, precision=precision)
                ivis = influence_mod.stokes_i_influence(
                    inf.vis.reshape(Ts * Td * Bl, 4, 2))
                ul = u4.reshape(-1, 3)
                if imager_block_r:
                    return imager_mod.dirty_image_factored_blocked_sr(
                        ul, ivis, ff, cl, npix=npix,
                        block_r=imager_block_r, precision=precision)
                return imager_mod.dirty_image_factored_sr(
                    ul, ivis, ff, cl, npix=npix, precision=precision)

            imgs = jax.lax.map(band, (res, c7, j, hadd, f))
            return jnp.mean(imgs, axis=0)

        def local(res, c7, j, r, a, f, f0_, u4, cl, pi, qi):
            per_lane = jax.vmap(
                lane, in_axes=(0,) * 9 + (None, None))(
                    res, c7, j, r, a, f, f0_, u4, cl, pi, qi)
            # each shard imaged its local baselines with a local-R
            # normalization; the psum over the baseline axis plus the
            # 1/nb rescale restores the global (1/R_total) * sum image
            return jax.lax.psum(per_lane, baseline_axis) / nb

        sharded = jax.jit(shard_map(
            local, mesh=mesh, in_specs=in_specs,
            out_specs=P(lane_axis), check_vma=False))
        _COMPOSE_CACHE[cache_key] = sharded
    # explicit placement onto the composed mesh (the _BSHARD_CACHE
    # pattern): operands arrive committed to the solve's sharding or the
    # host, and the explicit device_put keeps the steady-state call legal
    # under jax.transfer_guard("disallow")
    operands = [
        jax.device_put(x, NamedSharding(mesh, spec)) for x, spec in
        zip((residual_b, C7, jnp.asarray(Jb),
             jnp.asarray(rho_b, jnp.float32),
             jnp.asarray(alpha_b, jnp.float32),
             jnp.asarray(freqs_b), jnp.asarray(f0_b, jnp.float32),
             U4, jnp.asarray(cell_b, jnp.float32), p_idx, q_idx),
            in_specs)]
    return sharded(*operands)
