"""Distributed PER learner/actors for the DEMIXING workload (discrete
actions).

Parity target: ``demixing_rl/distributed_per_sac.py`` — the demixing
variant of the learner/actor RPC runtime: actions are the 2^(K-1) direction
subsets (``:34`` n_actions=2**(K-1), ``:180-184`` scalar_to_kvec), each
actor runs ``epochs`` episodes of ``steps`` env steps with frozen weights
and uploads its buffer; the learner ingests and trains a PER SAC agent on
{infmap, metadata} observations.

TPU-native re-expression (same shape as
:mod:`smartcal_tpu.parallel.learner`, which covers the elasticnet variant):

* episode SIMULATION (sky draws, uvw synthesis) is host-side numpy — the
  irreducibly sequential/choice-heavy part — batched into a
  :class:`DemixWorkload` pytree with a leading (actors, epochs) axis;
* everything after simulation is ONE jitted SPMD program over the mesh's
  ``dp`` axis: per actor, a ``lax.scan`` over epochs of a ``lax.scan`` over
  steps, each step = categorical action -> masked ADMM calibrate ->
  AIC reward (the reference's per-step ``mpirun sagecal-mpi`` becomes an
  in-framework batched solve);
* the actor->learner "buffer upload" is the dp->replicated resharding of
  the transition batch (an XLA all-gather over ICI);
* the learner (discrete SAC + PER) runs replicated; the reference's
  ``threading.Lock`` disappears because ingestion is deterministic SPMD.

The direction-subset decode table (scalar_to_kvec for every action index)
is a precomputed (2^(K-1), K) constant — the branchy per-sample bit loop
of the reference becomes one gather.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..cal import imager, influence as influence_mod, solver
from ..envs import radio
from ..envs.demixing import (EPS, INF_SCALE, META_SCALE, REWARD_MEAN,
                             REWARD_STD, scalar_to_kvec)
from ..rl import replay as rp
from ..rl import sac_discrete as dsac
from .mesh import AXIS_DATA


class DemixWorkload(NamedTuple):
    """Device form of (actors, epochs) simulated demixing episodes."""

    V: jnp.ndarray          # (A, E, Nf, T, B, 2, 2, 2)
    Ccal: jnp.ndarray       # (A, E, Nf, K, T*B, 4, 2)
    freqs: jnp.ndarray      # (A, E, Nf)
    f0: jnp.ndarray         # (A, E)
    rho: jnp.ndarray        # (A, E, K)
    metadata: jnp.ndarray   # (A, E, 3K+2) raw (unscaled)
    uvw: jnp.ndarray        # (A, E, T, B, 3)
    cell: jnp.ndarray       # (A, E) imaging cell size


def mask_table(K: int) -> np.ndarray:
    """(2^(K-1), K) float32: row i = scalar_to_kvec(i) outlier bits plus the
    always-selected target (demixingenv.py:114-118 selection semantics)."""
    n = 2 ** (K - 1)
    tbl = np.zeros((n, K), np.float32)
    for i in range(n):
        tbl[i, :K - 1] = scalar_to_kvec(i, K - 1)
        tbl[i, K - 1] = 1.0
    return tbl


def make_workloads(backend: radio.RadioBackend, K: int, n_actors: int,
                   n_epochs: int, key) -> DemixWorkload:
    """Host-side episode batch: n_actors x n_epochs simulated observations
    (the reference's per-epoch ``env.reset()``, distributed_per_sac.py:131)."""
    Vs, Cs, fqs, f0s, rhos, mds, uvws, cells = ([] for _ in range(8))
    keys = jax.random.split(key, n_actors * n_epochs)
    for k in keys:
        ep, mdl = backend.new_demixing_episode(k, K)
        freqs = np.asarray(ep.obs.freqs)
        md = np.zeros(3 * K + 2, np.float32)
        md[:K] = mdl.separations
        md[K:2 * K] = mdl.azimuth
        md[2 * K:3 * K] = mdl.elevation
        md[-2] = np.log(freqs[0] / 1e6)
        md[-1] = backend.n_stations
        Vs.append(np.asarray(ep.V))
        Cs.append(np.asarray(ep.Ccal))
        fqs.append(freqs)
        f0s.append(ep.f0)
        rhos.append(mdl.rho.astype(np.float32))
        mds.append(md)
        uvws.append(np.asarray(ep.obs.uvw))
        cells.append(imager.default_cell(ep.obs.uvw, float(freqs[-1])))

    def pack(xs):
        a = np.stack([np.asarray(x, np.float32) for x in xs])
        return jnp.asarray(a.reshape((n_actors, n_epochs) + a.shape[1:]))

    return DemixWorkload(V=pack(Vs), Ccal=pack(Cs), freqs=pack(fqs),
                         f0=pack(f0s), rho=pack(rhos), metadata=pack(mds),
                         uvw=pack(uvws), cell=pack(cells))


class DistDemixState(NamedTuple):
    agent: dsac.DSACState
    buf: rp.ReplayState
    episode: jnp.ndarray


def make_demix_actor_rollout(backend: radio.RadioBackend, K: int,
                             agent_cfg: dsac.DSACConfig,
                             rollout_epochs: int, rollout_steps: int,
                             provide_influence: bool = False,
                             maxiter: int = 10, record_logp: bool = False):
    """One demixing actor's rollout as a pure function ``(agent_state,
    wl, key) -> transitions`` — ``wl`` a :class:`DemixWorkload` slice
    with leading axis ``rollout_epochs``, output leading axis
    ``rollout_epochs * rollout_steps``.  Shared by the SPMD learner
    (vmapped over the actor axis) and the supervised actor-thread
    fleet (jitted per thread).  ``record_logp`` adds the categorical
    ``behavior_logp`` field for the learner's IMPACT importance ratio
    (same keys, bitwise the same action stream)."""
    n_actions = 2 ** (K - 1)
    if agent_cfg.n_actions != n_actions:
        raise ValueError(f"agent n_actions={agent_cfg.n_actions} != "
                         f"2^(K-1)={n_actions}")
    npix = backend.npix
    N = backend.n_stations
    tbl = jnp.asarray(mask_table(K))
    n_trans = rollout_epochs * rollout_steps

    def _calibrate(wl_ep, mask):
        C = wl_ep.Ccal * mask[None, :, None, None, None]
        cfg = solver.SolverConfig(
            n_stations=N, n_dirs=K, n_poly=backend.n_poly,
            admm_iters=backend.admm_iters, lbfgs_iters=backend.lbfgs_iters,
            init_iters=backend.init_iters, polytype=backend.polytype)
        return solver.solve_admm(wl_ep.V, C, wl_ep.freqs, wl_ep.f0,
                                 wl_ep.rho, cfg, n_chunks=backend.n_chunks,
                                 admm_iters=jnp.asarray(maxiter))

    # backend.noise_std is pure JAX (vmapped stokes_i_std), traceable here
    _noise_std = backend.noise_std

    def _infmap(wl_ep, res, mask):
        """Jitted re-expression of RadioBackend.influence_image with traced
        rho (rho*mask + (1-mask), alpha=0 — DemixingEnv._influence_map)."""
        if not provide_influence:
            return jnp.zeros((npix, npix), jnp.float32)
        rho_m = wl_ep.rho * mask + (1.0 - mask)
        alpha = jnp.zeros((K,), jnp.float32)
        uvw_flat = wl_ep.uvw.reshape(-1, 3)
        imgs = []
        for fi in range(backend.n_freqs):
            hadd = influence_mod.consensus_hadd_scalars(
                rho_m, alpha, wl_ep.freqs, wl_ep.f0, fi,
                n_poly=backend.n_poly, polytype=backend.polytype)
            Rk = solver.residual_to_kernel(res.residual[fi])
            inf = influence_mod.influence_visibilities(
                Rk, wl_ep.Ccal[fi], res.J[fi], hadd, N, backend.n_chunks)
            ivis = influence_mod.stokes_i_influence(inf.vis)
            # explicitly the XLA formulation: this runs inside the
            # dp-sharded jitted rollout and pallas_call has no GSPMD
            # partitioning rule (imager.dirty_image_sr's pallas dispatch
            # would fail to shard or replicate the kernel per chip)
            imgs.append(imager.dirty_image_sr_xla(
                uvw_flat, ivis, wl_ep.freqs[fi], wl_ep.cell, npix=npix))
        return jnp.mean(jnp.stack(imgs), axis=0)

    def _aic_reward(std_res, std_data, ksel):
        """demixingenv.py:338-355 with fixed maxiter (the distributed
        reference variant does not tune it)."""
        r = (-N * N * std_res ** 2 / (std_data ** 2 + EPS) - ksel * N)
        return (r - REWARD_MEAN) / REWARD_STD - maxiter / 100.0

    def _obs(wl_ep, res, mask):
        img = _infmap(wl_ep, res, mask) * INF_SCALE
        md = wl_ep.metadata
        md = md.at[:K].set(jnp.where(mask > 0, 0.0, md[:K]))
        return jnp.concatenate([img.reshape(-1), md * META_SCALE])

    def _actor_rollout(agent_state, wl, key):
        """rollout_epochs episodes x rollout_steps transitions with
        frozen params (Actor.run_observations, :123-146)."""

        def epoch_body(carry, inp):
            wl_ep, k_epoch = inp
            std_data = _noise_std(wl_ep.V)
            mask0 = tbl[0]                       # target only
            res0 = _calibrate(wl_ep, mask0)
            r0 = _aic_reward(_noise_std(res0.residual), std_data, 1.0)
            obs0 = _obs(wl_ep, res0, mask0)

            def step_body(scarry, k):
                obs = scarry
                k_act, _ = jax.random.split(k)
                if record_logp:
                    a, lp = dsac.choose_action_logp(
                        agent_cfg, agent_state, obs[None], k_act)
                    a, lp = a[0], lp[0]
                else:
                    a = dsac.choose_action(agent_cfg, agent_state,
                                           obs[None], k_act)[0]
                mask = tbl[a]
                res = _calibrate(wl_ep, mask)
                std_res = _noise_std(res.residual)
                reward = _aic_reward(std_res, std_data,
                                     jnp.sum(mask)) - r0
                obs2 = _obs(wl_ep, res, mask)
                tr = {"state": obs, "action": a, "reward": reward,
                      "new_state": obs2, "done": jnp.asarray(False)}
                if record_logp:
                    tr["behavior_logp"] = lp
                return obs2, tr

            _, trs = jax.lax.scan(step_body, obs0,
                                  jax.random.split(k_epoch, rollout_steps))
            return carry, trs

        _, trs = jax.lax.scan(
            epoch_body, 0,
            (wl, jax.random.split(key, rollout_epochs)))
        return jax.tree_util.tree_map(
            lambda x: x.reshape((n_trans,) + x.shape[2:]), trs)

    return _actor_rollout


def make_distributed_demix_sac(backend: radio.RadioBackend, K: int,
                               agent_cfg: dsac.DSACConfig, mesh: Mesh,
                               n_actors: int, rollout_epochs: int = 2,
                               rollout_steps: int = 5,
                               provide_influence: bool = False,
                               maxiter: int = 10,
                               learn_per_transition: bool = False):
    """Build (init_fn, make_workloads_fn, run_episode_fn) on ``mesh``.

    ``provide_influence`` populates the infmap block of the observation
    (the reference variant's [1, Ninf, Ninf] input) — with False the block
    is zeros and ``agent_cfg.use_image`` should be False too."""
    if n_actors % mesh.shape[AXIS_DATA] != 0:
        raise ValueError(f"n_actors={n_actors} not divisible by dp axis "
                         f"{mesh.shape[AXIS_DATA]}")
    repl = NamedSharding(mesh, P())
    shard = NamedSharding(mesh, P(AXIS_DATA))
    n_trans = rollout_epochs * rollout_steps
    spec = dsac.transition_spec(agent_cfg.obs_dim)
    _actor_rollout = make_demix_actor_rollout(
        backend, K, agent_cfg, rollout_epochs, rollout_steps,
        provide_influence=provide_influence, maxiter=maxiter)

    def init_fn(key) -> DistDemixState:
        agent = dsac.dsac_init(key, agent_cfg)
        buf = rp.replay_init(agent_cfg.mem_size, spec)
        st = DistDemixState(agent=agent, buf=buf,
                            episode=jnp.asarray(0, jnp.int32))
        return jax.device_put(st, _shardings(st))

    def _shardings(st):
        return jax.tree_util.tree_map(lambda _: repl, st)

    def run_episode(st: DistDemixState, wl: DemixWorkload, key):
        k_roll, k_learn = jax.random.split(key)
        actor_keys = jax.random.split(k_roll, n_actors)
        trs = jax.vmap(lambda w, k: _actor_rollout(st.agent, w, k))(
            wl, actor_keys)
        flat = jax.tree_util.tree_map(
            lambda x: x.reshape((n_actors * n_trans,) + x.shape[2:]), trs)

        if learn_per_transition:
            def ingest(carry, inp):
                agent, buf = carry
                tr, k = inp
                buf = rp.replay_add(buf, tr)
                agent, buf, m = dsac.learn(agent_cfg, agent, buf, k)
                return (agent, buf), m["critic_loss"]

            keys = jax.random.split(k_learn, n_actors * n_trans)
            (agent, buf), losses = jax.lax.scan(ingest, (st.agent, st.buf),
                                                (flat, keys))
            metrics = {"critic_loss": losses[-1]}
        else:
            buf = rp.replay_add_batch(st.buf, flat)
            agent, buf, metrics = dsac.learn(agent_cfg, st.agent, buf,
                                             k_learn)
        metrics["mean_reward"] = jnp.mean(flat["reward"])
        return DistDemixState(agent=agent, buf=buf,
                              episode=st.episode + 1), metrics

    dummy = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    sh = _shardings(dummy)
    wl_shard = DemixWorkload(*[shard] * len(DemixWorkload._fields))
    run_episode_jit = jax.jit(run_episode,
                              in_shardings=(sh, wl_shard, repl),
                              out_shardings=(sh, repl))

    def make_workloads_fn(key):
        wl = make_workloads(backend, K, n_actors, rollout_epochs, key)
        return jax.device_put(wl, wl_shard)

    return init_fn, make_workloads_fn, run_episode_jit


def train_distributed_demix(seed=0, episodes=10, n_actors=None, mesh=None,
                            K=4, backend=None, provide_influence=False,
                            agent_kwargs=None, quiet=False,
                            rollout_epochs=2, rollout_steps=5,
                            metrics=None, diag=False, watchdog=False,
                            ckpt_dir=None, ckpt_every=0, resume=False):
    """Host driver (run_process + Learner.run_episodes parity,
    distributed_per_sac.py:193-229)."""
    import time

    from smartcal_tpu import obs
    from smartcal_tpu.runtime import pack_replay, unpack_replay
    from smartcal_tpu.train.blocks import TrainRuntime, train_obs

    from . import make_mesh

    mesh = mesh or make_mesh()
    n_actors = n_actors or mesh.shape[AXIS_DATA]
    backend = backend or radio.RadioBackend()
    md_dim = 3 * K + 2
    agent_cfg = dsac.DSACConfig(
        obs_dim=backend.npix * backend.npix + md_dim,
        n_actions=2 ** (K - 1), img_shape=(backend.npix, backend.npix),
        use_image=provide_influence, **(agent_kwargs or {}))
    init_fn, make_wl, run_episode = make_distributed_demix_sac(
        backend, K, agent_cfg, mesh, n_actors,
        rollout_epochs=rollout_epochs, rollout_steps=rollout_steps,
        provide_influence=provide_influence)
    key = jax.random.PRNGKey(seed)
    key, k0 = jax.random.split(key)
    st = init_fn(k0)
    scores = []
    n_trans = n_actors * rollout_epochs * rollout_steps
    tob = train_obs("demix_learner", metrics=metrics, quiet=quiet,
                    diag=diag, watchdog=watchdog, seed=seed,
                    n_actors=n_actors, K=K)
    rt = TrainRuntime("demix_learner", ckpt_dir=ckpt_dir,
                      ckpt_every=ckpt_every, resume=resume, tob=tob)
    ep0 = 0
    restored = rt.restore()
    if restored is not None:
        st = DistDemixState(
            agent=jax.tree_util.tree_map(jnp.asarray,
                                         restored["agent_state"]),
            buf=unpack_replay(restored["replay"]),
            episode=jnp.asarray(restored["episode"], jnp.int32))
        key = jnp.asarray(restored["key"])
        scores = list(restored["scores"])
        ep0 = int(restored["episode"])

    def ckpt_payload(ep, key):
        return {"kind": "dist_demix", "episode": ep + 1,
                "scores": list(scores),
                "agent_state": jax.device_get(st.agent),
                "replay": pack_replay(st.buf),
                "key": jax.device_get(key)}

    try:
        for ep in range(ep0, episodes):
            key, kw, kr = jax.random.split(key, 3)
            with tob.span("learner_episode", episode=ep):
                with tob.span("make_workloads"):
                    wl = make_wl(kw)
                t0 = time.perf_counter()
                st, metrics_out = run_episode(st, wl, kr)
                score = float(metrics_out["mean_reward"])
                wall = time.perf_counter() - t0
            scores.append(score)
            obs.gauge_set("actor_transitions_per_s",
                          round(n_trans / max(wall, 1e-9), 2))
            # PER distribution health next to the staleness gauge
            # (see parallel/learner.py); --diag-gated, feeds the watchdog
            tripped = False
            if tob.collect_diag:
                tripped = tob.record_diag(
                    {"critic_loss": float(metrics_out["critic_loss"])},
                    episode=ep)
            tripped = tob.log_replay_health(st.buf, episode=ep) or tripped
            # echo=False: keep the reference driver's own wording below
            tob.episode(ep, score, scores, echo=False, transitions=n_trans,
                        weight_staleness_steps=rollout_epochs
                        * rollout_steps)
            tob.echo(f"episode {ep} mean reward {scores[-1]:.4f}",
                     event=None)
            if tripped:
                # never checkpoint the tripped episode's state (see
                # parallel.learner.train_distributed)
                break
            rt.maybe_checkpoint(ep + 1, lambda: ckpt_payload(ep, key))
    finally:
        tob.close()
    return st, scores


def _demix_agent_cfg(backend: radio.RadioBackend, K: int,
                     provide_influence: bool, is_clip: float,
                     ere_eta: float, agent_kwargs) -> dsac.DSACConfig:
    md_dim = 3 * K + 2
    return dsac.DSACConfig(
        obs_dim=backend.npix * backend.npix + md_dim,
        n_actions=2 ** (K - 1), img_shape=(backend.npix, backend.npix),
        use_image=provide_influence, is_clip=is_clip, ere_eta=ere_eta,
        **(agent_kwargs or {}))


def _demix_fleet_work_fn(backend_kwargs=None, K=4, agent_kwargs=None,
                         provide_influence=False, is_clip=0.0,
                         ere_eta=1.0, batch_envs=1, rollout_epochs=1,
                         rollout_steps=3, seed=0, _backend=None):
    """Build the demix fleet actor's work function from PICKLABLE
    primitives (the enet twin is
    :func:`smartcal_tpu.parallel.learner._enet_fleet_work_fn`): shared
    by actor threads (called in-process, optionally with an already-
    built ``_backend``) and spawned actor processes (named as the
    ``worker_spec`` factory; each worker reconstructs the backend from
    ``backend_kwargs``).  Same per-(actor, iteration) key streams in
    both modes."""
    from .learner import flatten_lanes, lane_keys

    backend = _backend or radio.RadioBackend(**(backend_kwargs or {}))
    agent_cfg = _demix_agent_cfg(backend, K, provide_influence, is_clip,
                                 ere_eta, agent_kwargs)
    n_trans = batch_envs * rollout_epochs * rollout_steps
    rollout_one = make_demix_actor_rollout(
        backend, K, agent_cfg, rollout_epochs, rollout_steps,
        provide_influence=provide_influence, record_logp=is_clip > 0)
    if batch_envs > 1:
        # the demix twin of learner.make_fleet_rollout: same lane-key
        # derivation + flatten, with the per-lane workload slice as the
        # extra vmapped operand (enet lanes need no per-lane data)
        def _rollout(weights, wl, key):
            trs = jax.vmap(lambda w, k: rollout_one(weights, w, k))(
                wl, lane_keys(key, batch_envs))
            return flatten_lanes(trs, n_trans)

        rollout = jax.jit(_rollout)
    else:
        rollout = jax.jit(rollout_one)

    base_key = jax.random.PRNGKey(seed ^ 0x0AC7D32)

    from smartcal_tpu.runtime import faults as rt_faults

    def work_fn(actor_id, iteration, weights):
        rt_faults.maybe_delay("actor_rollout", iteration)
        if rt_faults.should_kill_actor(actor_id, iteration):
            raise rt_faults.FaultInjected(
                f"actor {actor_id} killed at iteration {iteration}")
        k = jax.random.fold_in(jax.random.fold_in(base_key, actor_id),
                               iteration)
        k_wl, k_roll = jax.random.split(k)
        # the actor simulates its own episode lanes (the host-side half
        # the SPMD mode batches up front)
        wl = make_workloads(backend, K, batch_envs, rollout_epochs, k_wl)
        if batch_envs > 1:
            return jax.device_get(rollout(weights, wl, k_roll))
        wl_one = jax.tree_util.tree_map(lambda x: x[0], wl)
        return jax.device_get(rollout(weights, wl_one, k_roll))

    return work_fn


def train_supervised_demix(seed=0, episodes=5, n_actors=2, K=4,
                           backend=None, provide_influence=False,
                           agent_kwargs=None, quiet=False,
                           rollout_epochs=1, rollout_steps=3, metrics=None,
                           diag=False, watchdog=False,
                           heartbeat_timeout=300.0, max_restarts=3,
                           queue_timeout=300.0, max_empty_rounds=10,
                           restart_backoff=None, batch_envs=1,
                           is_clip=0.0, ere_eta=1.0, publish_every=1,
                           ckpt_dir=None, ckpt_every=0, keep_ckpts=3,
                           resume=False, actor_mode="thread",
                           replay_shards=0, sim_hosts=1,
                           backend_kwargs=None):
    """Supervised actor fleet for the demixing workload (the scale-out
    async sibling of :func:`train_distributed_demix`; see
    parallel.learner.train_supervised for the architecture).

    Each actor simulates ITS OWN workload lanes on the host
    (``make_workloads`` with ``batch_envs`` lanes) and runs the jitted
    per-actor rollout — vmapped over the lane axis into ONE batched
    dispatch — against the latest weights snapshot; the supervisor
    restarts dead/hung actors with backoff and a watchdog trip joins the
    fleet cleanly.  ``is_clip``/``ere_eta``/``publish_every`` and the
    checkpoint flags behave as in ``train_supervised``; so do
    ``actor_mode``/``replay_shards``/``sim_hosts`` — with the demixing
    caveat that ``actor_mode="process"`` needs ``backend_kwargs`` (the
    picklable RadioBackend constructor form; a pre-built ``backend``
    object cannot cross the process boundary).
    Returns ``((agent_state, buf), scores, fleet_summary)``.
    """
    from smartcal_tpu.runtime import Fleet
    from smartcal_tpu.train.blocks import TrainRuntime, train_obs

    from .learner import make_sharded_fleet_buffer, run_supervised_loop

    if actor_mode == "process" and backend is not None \
            and backend_kwargs is None:
        raise ValueError(
            "actor_mode='process' needs backend_kwargs (the picklable "
            "RadioBackend constructor kwargs) — a pre-built backend "
            "object cannot be shipped to worker processes")
    backend = backend or radio.RadioBackend(**(backend_kwargs or {}))
    agent_cfg = _demix_agent_cfg(backend, K, provide_influence, is_clip,
                                 ere_eta, agent_kwargs)
    n_trans = batch_envs * rollout_epochs * rollout_steps

    factory_kwargs = dict(backend_kwargs=dict(backend_kwargs or {}), K=K,
                          agent_kwargs=dict(agent_kwargs or {}),
                          provide_influence=provide_influence,
                          is_clip=is_clip, ere_eta=ere_eta,
                          batch_envs=batch_envs,
                          rollout_epochs=rollout_epochs,
                          rollout_steps=rollout_steps, seed=seed)
    work_fn = (None if actor_mode == "process"
               else _demix_fleet_work_fn(_backend=backend,
                                         **factory_kwargs))
    worker_spec = {
        "factory":
            "smartcal_tpu.parallel.demix_learner:_demix_fleet_work_fn",
        "kwargs": factory_kwargs}

    def _ingest(agent, buf, flat, key, learner_version):
        buf = rp.backend_for(buf).replay_add_batch(buf, flat)
        return dsac.learn(agent_cfg, agent, buf, key,
                          learner_version=learner_version)

    ingest = jax.jit(_ingest)

    def ingest_batch(agent, buf, host_trs, kl, weights_version,
                     learner_version):
        flat = {k2: jnp.asarray(v) for k2, v in host_trs.items()}
        if is_clip > 0:
            flat["version"] = jnp.full((flat["reward"].shape[0],),
                                       weights_version, jnp.int32)
        return ingest(agent, buf, flat, kl,
                      jnp.asarray(learner_version, jnp.int32))

    key = jax.random.PRNGKey(seed)
    key, k0 = jax.random.split(key)
    agent = dsac.dsac_init(k0, agent_cfg)
    spec = dsac.transition_spec(agent_cfg.obs_dim)
    if is_clip > 0:
        spec = rp.versioned_spec(spec)
    if replay_shards:
        buf = make_sharded_fleet_buffer(agent_cfg.mem_size, spec,
                                        replay_shards)
    else:
        buf = rp.replay_init(agent_cfg.mem_size, spec)

    tob = train_obs("demix_learner_supervised", metrics=metrics,
                    quiet=quiet, diag=diag, watchdog=watchdog, seed=seed,
                    n_actors=n_actors, K=K, batch_envs=batch_envs,
                    is_clip=is_clip, ere_eta=ere_eta,
                    actor_mode=actor_mode, replay_shards=replay_shards,
                    sim_hosts=sim_hosts)
    rt = TrainRuntime("demix_learner_supervised", ckpt_dir=ckpt_dir,
                      ckpt_every=ckpt_every, keep=keep_ckpts,
                      resume=resume, tob=tob)
    fleet = Fleet(n_actors, work_fn, name="demix-actor",
                  heartbeat_timeout=heartbeat_timeout,
                  max_restarts=max_restarts, backoff=restart_backoff,
                  seed=seed, actor_mode=actor_mode,
                  worker_spec=worker_spec if actor_mode == "process"
                  else None, hosts=sim_hosts)
    return run_supervised_loop(fleet, ingest_batch, agent, buf, key,
                               episodes, n_trans, tob,
                               queue_timeout=queue_timeout,
                               max_empty_rounds=max_empty_rounds,
                               rt=rt, publish_every=publish_every)


def main(argv=None):
    """CLI (the run_process entry of distributed_per_sac.py:193-229 —
    the mesh IS the world; multi-host runs pass --coordinator/
    --num_processes/--process_id on every host, the jax.distributed
    replacement for the reference's MASTER_ADDR/world_size/rank plumbing).

    Usage: python -m smartcal_tpu.parallel.demix_learner --episodes 10
        [--actors 8] [--K 4] [--small] [--provide_influence]
        [--coordinator host:port --num_processes N --process_id i]
    """
    import argparse

    from . import multihost

    p = argparse.ArgumentParser(description=main.__doc__)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--episodes", type=int, default=10)
    p.add_argument("--actors", type=int, default=None,
                   help="deprecated alias of --n-actors")
    p.add_argument("--K", type=int, default=6)
    p.add_argument("--stations", type=int, default=14)
    p.add_argument("--npix", type=int, default=128)
    p.add_argument("--small", action="store_true")
    p.add_argument("--provide_influence", action="store_true")
    p.add_argument("--rollout_epochs", type=int, default=2,
                   help="episodes per actor per learner episode")
    p.add_argument("--rollout_steps", type=int, default=5)
    p.add_argument("--supervised", action="store_true",
                   help="actor-THREAD fleet with heartbeat supervision + "
                        "restart backoff (train_supervised_demix) instead "
                        "of the fused SPMD program")
    p.add_argument("--heartbeat_timeout", type=float, default=300.0)
    p.add_argument("--max_restarts", type=int, default=3)
    from smartcal_tpu import obs
    from smartcal_tpu.train.blocks import (add_batched_args, add_fleet_args,
                                           add_obs_args, add_runtime_args,
                                           diag_from_args)

    add_fleet_args(p)
    add_batched_args(p)
    add_obs_args(p)
    add_runtime_args(p)
    multihost.add_cli_args(p)
    args = p.parse_args(argv)
    n_actors = args.n_actors or args.actors
    if multihost.initialize_from_args(args):
        obs.echo(f"multihost: {multihost.runtime_summary()}",
                 event="multihost")
    if args.small:
        backend_kwargs = dict(n_stations=6, n_times=4, tdelta=2,
                              npix=16, admm_iters=2, lbfgs_iters=3,
                              init_iters=4)
    else:
        backend_kwargs = dict(n_stations=args.stations, npix=args.npix)
    backend = radio.RadioBackend(**backend_kwargs)
    if args.actor_mode == "process" or args.replay_shards \
            or args.sim_hosts > 1:
        args.supervised = True
    if args.supervised:
        _, scores, _ = train_supervised_demix(
            seed=args.seed, episodes=args.episodes,
            n_actors=n_actors or 2, K=args.K, backend=backend,
            backend_kwargs=backend_kwargs,
            provide_influence=args.provide_influence,
            rollout_epochs=args.rollout_epochs,
            rollout_steps=args.rollout_steps,
            quiet=args.quiet, metrics=args.metrics,
            diag=diag_from_args(args),
            watchdog=getattr(args, "watchdog", False),
            heartbeat_timeout=args.heartbeat_timeout,
            max_restarts=args.max_restarts,
            batch_envs=args.batch_envs, is_clip=args.is_clip,
            ere_eta=args.ere_eta, publish_every=args.publish_every,
            ckpt_dir=args.ckpt_dir, ckpt_every=args.ckpt_every,
            keep_ckpts=args.keep_ckpts, resume=args.resume,
            actor_mode=args.actor_mode,
            replay_shards=args.replay_shards, sim_hosts=args.sim_hosts)
        return scores
    _, scores = train_distributed_demix(
        seed=args.seed, episodes=args.episodes, n_actors=n_actors,
        K=args.K, backend=backend,
        provide_influence=args.provide_influence,
        rollout_epochs=args.rollout_epochs,
        rollout_steps=args.rollout_steps,
        quiet=args.quiet, metrics=args.metrics,
        diag=diag_from_args(args),
        watchdog=getattr(args, "watchdog", False),
        ckpt_dir=args.ckpt_dir, ckpt_every=args.ckpt_every,
        resume=args.resume)
    return scores


if __name__ == "__main__":
    main()
