"""Multi-host runtime initialisation (the MASTER_ADDR / world-size edge).

The reference brings its distributed runtime up through torch RPC env
conventions — ``MASTER_ADDR``/``MASTER_PORT``, ``GLOO_SOCKET_IFNAME``/
``TP_SOCKET_IFNAME``, explicit ``world_size``/``rank`` CLI args
(``elasticnet/distributed_per_sac.py:154-190``, ``elasticnet/README.md:
6-18``).  The TPU-native equivalent is single-controller-per-host JAX:
every host runs the same program, ``jax.distributed.initialize`` wires the
hosts together, and from then on all communication is XLA collectives —
psum/all_gather riding ICI inside a slice and DCN across slices.  No RPC,
no weight shipping, no locks: the mesh IS the communication backend.

``initialize()`` below is the one call a driver needs before touching
``jax.devices()``.  It is a no-op for single-host runs, so every CLI can
call it unconditionally (the ``--coordinator`` flag mirrors the
reference's ``--master_addr``/``--master_port`` pair).
"""

from __future__ import annotations

import os
from typing import Optional

_initialized = False


def initialize(coordinator: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> bool:
    """Bring up multi-host JAX if configured; returns True when distributed.

    Sources, in order: explicit args, then the standard env vars
    (``JAX_COORDINATOR_ADDRESS``/``JAX_NUM_PROCESSES``/``JAX_PROCESS_ID``,
    or the cloud-TPU auto-detection built into jax.distributed).  With no
    configuration at all this is a no-op single-host run.

    Call BEFORE the first ``jax.devices()``/jit of the process.
    """
    global _initialized
    if _initialized:
        return True
    coordinator = coordinator or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if num_processes is None and os.environ.get("JAX_NUM_PROCESSES"):
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and os.environ.get("JAX_PROCESS_ID"):
        process_id = int(os.environ["JAX_PROCESS_ID"])
    if coordinator is None and num_processes is None:
        return False                       # single-host: nothing to do
    import jax

    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    _initialized = True
    return True


# ---------------------------------------------------------------------------
# Simulated multi-host attach (the process-fleet rehearsal mode)
# ---------------------------------------------------------------------------

# "<host_id>/<n_hosts>" marker a simulated-host actor process runs under
SIM_HOST_ENV = "SMARTCAL_SIM_HOST"


def simulated_host_env(host_id: int, n_hosts: int) -> dict:
    """Env-var form of a simulated host assignment (what a spawner sets
    for a worker when it cannot pass arguments directly)."""
    return {SIM_HOST_ENV: f"{int(host_id)}/{int(n_hosts)}"}


def attach_simulated(host_id: Optional[int] = None,
                     n_hosts: Optional[int] = None) -> dict:
    """Attach this process to the SIMULATED multi-host runtime.

    The process-backed actor fleet rehearses the multi-host topology on
    one machine: each spawned actor process calls this with its
    assigned ``(host_id, n_hosts)`` (or inherits them from
    ``SMARTCAL_SIM_HOST``), records the assignment in the environment
    (so nested tooling and the RunLog header can see it) and returns a
    summary.  It deliberately does NOT call
    ``jax.distributed.initialize`` — there is only one real host; a
    REAL multi-host job still goes through :func:`initialize`, and this
    marker documents which rehearsal host the process was playing.
    """
    if host_id is None:
        raw = os.environ.get(SIM_HOST_ENV, "").strip()
        if raw:
            try:
                host_id, n_hosts = (int(x) for x in raw.split("/", 1))
            except ValueError:
                host_id = None
    if host_id is None:
        return {"simulated": False, "host_id": 0, "n_hosts": 1}
    n_hosts = int(n_hosts or 1)
    host_id = int(host_id)
    os.environ.update(simulated_host_env(host_id, n_hosts))
    return {"simulated": n_hosts > 1, "host_id": host_id,
            "n_hosts": n_hosts}


def simulated_summary() -> dict:
    """The current process's simulated-host assignment (default: the
    single real host)."""
    return attach_simulated()


def add_cli_args(parser) -> None:
    """Attach the multi-host flags every parallel CLI shares
    (the reference's --master_addr/--master_port/--world_size/--rank,
    distributed_per_sac.py:176-190)."""
    parser.add_argument("--coordinator", default=None,
                        help="coordinator host:port (all hosts pass the "
                             "same value; host 0 must be reachable there)")
    parser.add_argument("--num_processes", type=int, default=None,
                        help="total participating hosts")
    parser.add_argument("--process_id", type=int, default=None,
                        help="this host's rank in [0, num_processes)")


def initialize_from_args(args) -> bool:
    return initialize(coordinator=getattr(args, "coordinator", None),
                      num_processes=getattr(args, "num_processes", None),
                      process_id=getattr(args, "process_id", None))


def runtime_summary() -> dict:
    """One-line visibility into the process's place in the job."""
    import jax

    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": jax.device_count(),
        "platform": jax.devices()[0].platform,
    }
