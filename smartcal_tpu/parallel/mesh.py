"""Device-mesh helpers.

The framework's parallel axes (SURVEY.md section 2.4 mapping):

* ``dp``  — data parallel: parallel environment rollouts + learn-batch
  sharding (replaces the reference's torch-RPC learner/actor fan-out,
  ``elasticnet/distributed_per_sac.py``).
* ``fp``  — frequency parallel: consensus-ADMM calibration across frequency
  sub-bands (replaces sagecal-mpi's MPI ranks, ``calibration/docal.sh:12``);
  the Z-polynomial consensus update is a ``psum`` over this axis.
* ``sp``  — sequence/baseline parallel: the time x baseline axis of the
  influence kernels (the reference chunks it over multiprocessing pools,
  ``calibration/analysis.py:54-62``).

All collectives ride ICI within a host and DCN across hosts — placement is
XLA's job once shardings are annotated.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: F401


def make_mesh(axis_sizes: Optional[Tuple[int, ...]] = None,
              axis_names: Sequence[str] = ("dp",),
              devices=None) -> Mesh:
    """Build a mesh over the available devices.

    Default: all devices on one ``dp`` axis.  ``axis_sizes`` reshapes the
    device list (row-major) for multi-axis meshes, e.g.
    ``make_mesh((4, 2), ("dp", "fp"))``.
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    if axis_sizes is None:
        axis_sizes = (len(devices),)
    n = int(np.prod(axis_sizes))
    if n > len(devices):
        raise ValueError(
            f"mesh wants {n} devices, only {len(devices)} available")
    dev_array = np.asarray(devices[:n]).reshape(axis_sizes)
    return Mesh(dev_array, tuple(axis_names))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def sharded_batch(mesh: Mesh, axis: str = "dp") -> NamedSharding:
    """Leading-axis sharding over ``axis``."""
    return NamedSharding(mesh, P(axis))
