"""Device-mesh helpers and THE canonical mesh-axis registry (ISSUE 17).

Every mesh axis name in the tree comes from here.  Until PR 16 the system
grew three *separate* 1-D meshes (episode lanes, replay shards, baselines),
each hard-coding its own axis string — the lane mesh even reused the
historical ``"fp"`` name for a baseline-partition role.  The registry plus
``compose_mesh`` turn those point-solutions into one topology: a single
2-D/3-D mesh whose axes a sharded learner, a lane-batched episode, and a
baseline-sharded influence program can share.

The framework's parallel axes (SURVEY.md section 2.4 mapping):

* ``AXIS_REPLAY``/``rp``   — replay-buffer shards (PR 12's ring parity;
  the reference's per-actor replay processes).
* ``AXIS_DATA``/``dp``     — data parallel: parallel environment rollouts +
  learn-batch sharding (replaces the reference's torch-RPC learner/actor
  fan-out, ``elasticnet/distributed_per_sac.py``).
* ``AXIS_LANE``/``lane``   — batched-episode lanes (PR 9's lane-packed
  vectorized episodes; one lane = one live episode).
* ``AXIS_FREQ``/``fp``     — frequency parallel: consensus-ADMM calibration
  across sub-bands (replaces sagecal-mpi's MPI ranks,
  ``calibration/docal.sh:12``); the Z consensus update is a ``psum`` here.
* ``AXIS_CHUNK``/``sp``    — calibration-interval (chunk) axis of the
  influence kernels (the reference chunks it over multiprocessing pools,
  ``calibration/analysis.py:54-62``).
* ``AXIS_BASELINE``/``bp`` — station-pair (baseline) axis of the blocked
  Hessian/influence kernels (PR 13); Hessian assembly is a ``psum`` here.

Collectives must stay confined to their own axis: consensus psums ride
``AXIS_FREQ``, Hessian/imager partial sums ride ``AXIS_BASELINE``, and the
lane/replay/data axes never carry a collective (they only batch).

All collectives ride ICI within a host and DCN across hosts — placement is
XLA's job once shardings are annotated.  graftlint's ``mesh-axis-literal``
rule keeps bare axis strings out of every other module.
"""

from __future__ import annotations

from typing import Dict, Mapping, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: F401

# --- the axis-name registry -------------------------------------------------
# The string VALUES are frozen ABI: checkpoints, serving signatures and the
# dryrun drivers all reference meshes by these names.  Add axes here (and to
# MESH_AXES in canonical order); never inline the strings elsewhere.
AXIS_REPLAY = "rp"
AXIS_DATA = "dp"
AXIS_LANE = "lane"
AXIS_FREQ = "fp"
AXIS_CHUNK = "sp"
AXIS_BASELINE = "bp"

#: Canonical axis order for composed meshes: batching axes (replay/data/lane)
#: lead, collective-bearing axes (freq/chunk/baseline) trail, so the
#: innermost (fastest-wire) device dimension carries the chattiest psum.
MESH_AXES: Tuple[str, ...] = (AXIS_REPLAY, AXIS_DATA, AXIS_LANE,
                              AXIS_FREQ, AXIS_CHUNK, AXIS_BASELINE)


class MeshFactorizationError(ValueError):
    """Axis sizes do not factor over the available devices / data.

    Raised instead of the opaque XLA sharding error (or a silent gcd
    degrade) when a requested mesh shape cannot be honored; the message
    always names the offending axis and suggests the nearest valid
    factorization so the caller can fix the request, not guess.
    """


def largest_divisor(n: int, cap: int) -> int:
    """Largest divisor of ``n`` that is <= ``cap`` (>=1 for n >= 1)."""
    n, cap = int(n), max(1, int(cap))
    for d in range(min(n, cap), 0, -1):
        if n % d == 0:
            return d
    return 1


def nearest_factorization(axis_sizes: Mapping[str, int],
                          n_devices: int) -> Dict[str, int]:
    """Nearest valid shrink of ``axis_sizes`` onto ``n_devices`` devices.

    Greedy in mapping order: each axis keeps the largest divisor of its
    requested size that still fits the remaining device budget.  The
    result's product always divides into ``n_devices`` and every suggested
    size divides the requested one (so data that divided before still
    divides).  Deterministic — used verbatim in error messages.
    """
    left = max(1, int(n_devices))
    out: Dict[str, int] = {}
    for name, size in axis_sizes.items():
        d = largest_divisor(size, left)
        out[name] = d
        left //= d
    return out


def check_axis_divides(n_items: int, n_shards: int, *, axis: str,
                       what: str) -> None:
    """Raise :class:`MeshFactorizationError` unless n_shards | n_items."""
    if n_shards <= 0 or n_items % n_shards != 0:
        hint = largest_divisor(n_items, n_shards)
        raise MeshFactorizationError(
            f"{what}: axis {axis!r} wants {n_shards} shards but "
            f"{n_items} items do not divide; nearest valid size is "
            f"{hint} (divisors of {n_items} only)")


def make_mesh(axis_sizes: Optional[Tuple[int, ...]] = None,
              axis_names: Sequence[str] = (AXIS_DATA,),
              devices=None) -> Mesh:
    """Build a mesh over the available devices.

    Default: all devices on one ``AXIS_DATA`` axis.  ``axis_sizes``
    reshapes the device list (row-major) for multi-axis meshes, e.g.
    ``make_mesh((4, 2), (AXIS_DATA, AXIS_FREQ))``.
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    if axis_sizes is None:
        axis_sizes = (len(devices),)
    n = int(np.prod(axis_sizes))
    if n > len(devices):
        req = dict(zip(axis_names, axis_sizes))
        raise MeshFactorizationError(
            f"mesh wants {n} devices ({req}), only {len(devices)} "
            f"available; nearest valid factorization: "
            f"{nearest_factorization(req, len(devices))}")
    dev_array = np.asarray(devices[:n]).reshape(axis_sizes)
    return Mesh(dev_array, tuple(axis_names))


def compose_mesh(axis_sizes: Mapping[str, int], devices=None) -> Mesh:
    """Build the unified multi-axis mesh from ``{axis name: size}``.

    Axes are laid out in :data:`MESH_AXES` canonical order regardless of
    mapping order, so ``compose_mesh({AXIS_BASELINE: 4, AXIS_LANE: 2})``
    and ``compose_mesh({AXIS_LANE: 2, AXIS_BASELINE: 4})`` are the SAME
    topology — callers can share one composed mesh (learner beside sharded
    episode) without coordinating dict order.  Unknown axis names are an
    error; size-1 axes are kept (a P(axis) spec on them is a no-op, which
    lets one program serve every arm of the route matrix).
    """
    for name in axis_sizes:
        if name not in MESH_AXES:
            raise MeshFactorizationError(
                f"unknown mesh axis {name!r}; registry axes are "
                f"{MESH_AXES} (add new axes in parallel/mesh.py)")
    names = tuple(a for a in MESH_AXES if a in axis_sizes)
    sizes = tuple(int(axis_sizes[a]) for a in names)
    return make_mesh(sizes, names, devices=devices)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def sharded_batch(mesh: Mesh, axis: str = AXIS_DATA) -> NamedSharding:
    """Leading-axis sharding over ``axis``."""
    return NamedSharding(mesh, P(axis))
