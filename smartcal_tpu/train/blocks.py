"""Shared train-driver plumbing: episode-block dispatch + observability.

Episode blocks: one jitted program runs ``block`` strictly-sequential
episodes (the scan carry chains agent + replay state and reproduces the
drivers' host key chain ``key, k = split(key)`` per episode).  Identical
learning dynamics to per-episode dispatch — this amortizes the device
round trip, which dominates the small elastic-net programs on the chip
(round-3 capture: 33 env-steps/s at 1 dispatch/episode over the tunnel);
it is NOT a batched-env mode (that is ``parallel.make_parallel_sac``).

Observability: ``add_obs_args`` + ``train_obs``/``train_obs_from_args``
are the ONE wiring shared by all nine train entry points — a ``TrainObs``
owns the run's RunLog (activated for the process so env/backend spans and
solver telemetry record into it), the jax compile listener, an optional
profiler trace, and the per-episode "episode N score ..." echo (stderr,
``--quiet``-able; the JSONL stream is the machine interface).
"""

import os
import time

import jax

from smartcal_tpu import obs


def add_obs_args(p):
    """Attach the shared observability flags to an argparse parser."""
    p.add_argument("--metrics", type=str, default=None,
                   help="obs run JSONL path (header + episode/span/solver "
                        "events; aggregate with tools/obs_report.py)")
    p.add_argument("--run_id", type=str, default=None,
                   help="run id recorded in the JSONL header "
                        "(default: generated)")
    p.add_argument("--trace", type=str, default=None,
                   help="jax profiler trace dir (view with TensorBoard/"
                        "xprof; spans appear as TraceAnnotations)")
    p.add_argument("--quiet", action="store_true",
                   help="suppress the per-episode stderr echo")
    return p


class TrainObs:
    """Per-run observability handle for a train driver (see module doc).

    With neither ``metrics`` nor ``trace`` set, everything here is a
    no-op passthrough — the driver's hot loop is unchanged."""

    MEM_EVERY = 10          # episodes between device-memory gauge samples

    def __init__(self, entry, metrics=None, run_id=None, trace=None,
                 quiet=False, **meta):
        self.entry = entry
        self.quiet = quiet
        self._t0 = time.time()
        self._episodes = 0
        self._tracing = False
        path = metrics
        if path is None and trace:
            # a profiler trace without a metrics stream still wants the
            # span/solver record alongside the xprof dump
            path = os.path.join(trace, f"{entry}_run.jsonl")
        self.runlog = None
        if path:
            self.runlog = obs.RunLog(path, run_id=run_id,
                                     meta={"entry": entry, **meta})
            obs.activate(self.runlog)
            obs.install_compile_listener()
        if trace:
            try:
                jax.profiler.start_trace(trace)
                self._tracing = True
            except Exception as e:
                self.echo(f"profiler trace unavailable: {e!r}")

    def span(self, name, **tags):
        return obs.span(name, **tags)

    def episode(self, i, score, scores=None, echo=True, **fields):
        """Record one ``episode`` event + the classic stderr echo
        (``echo=False`` for drivers that print their own wording)."""
        if self.runlog is not None:
            self.runlog.log("episode", episode=i, score=score, **fields)
            self._episodes += 1
            if self._episodes % self.MEM_EVERY == 0:
                obs.log_memory_gauges()
        if echo and not self.quiet:
            if scores:
                tail = scores[-100:]
                avg = sum(float(s) for s in tail) / len(tail)
            else:
                avg = float(score)
            # event=None: the structured record is the episode event above
            obs.echo(f"episode {i} score {float(score):.2f} "
                     f"average score {avg:.2f}", event=None)

    def echo(self, msg, **fields):
        obs.echo(msg, quiet=self.quiet, **fields)

    def close(self):
        if self._tracing:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._tracing = False
        if self.runlog is not None:
            # reset: a later run in the same process (sweep drivers call
            # main() per seed) must not inherit this run's totals
            obs.flush_counters(reset=True)
            self.runlog.log("run_end", episodes=self._episodes,
                            wall_s=round(time.time() - self._t0, 3))
            obs.deactivate(self.runlog)
            self.runlog.close()
            self.runlog = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def train_obs(entry, metrics=None, run_id=None, trace=None, quiet=False,
              **meta) -> TrainObs:
    return TrainObs(entry, metrics=metrics, run_id=run_id, trace=trace,
                    quiet=quiet, **meta)


def train_obs_from_args(args, entry, **meta) -> TrainObs:
    """Build the run handle from the ``add_obs_args`` flags (getattr-safe
    so programmatic Namespace callers without the new flags keep
    working)."""
    return TrainObs(entry,
                    metrics=getattr(args, "metrics", None),
                    run_id=getattr(args, "run_id", None),
                    trace=getattr(args, "trace", None),
                    quiet=getattr(args, "quiet", False),
                    seed=getattr(args, "seed", None), **meta)


def make_block_fn(episode_body, block: int):
    """Jit a scan of ``block`` calls of ``episode_body(agent_state, buf,
    key) -> (agent_state, buf, score)``.

    Returns ``run_block(agent_state, buf, key) -> (agent_state, buf,
    advanced_key, scores[block])``; the advanced key lets a driver continue
    the exact same chain across blocks.
    """

    @jax.jit
    def run_block(agent_state, buf, key):
        def one(carry, _):
            agent_state, buf, key = carry
            key, k = jax.random.split(key)
            agent_state, buf, score = episode_body(agent_state, buf, k)
            return (agent_state, buf, key), score

        (agent_state, buf, key), scores = jax.lax.scan(
            one, (agent_state, buf, key), None, length=block)
        return agent_state, buf, key, scores

    return run_block
