"""Episode-block dispatch, shared by the enet SAC/TD3/DDPG drivers.

One jitted program runs ``block`` strictly-sequential episodes (the scan
carry chains agent + replay state and reproduces the drivers' host key
chain ``key, k = split(key)`` per episode).  Identical learning dynamics
to per-episode dispatch — this amortizes the device round trip, which
dominates the small elastic-net programs on the chip (round-3 capture:
33 env-steps/s at 1 dispatch/episode over the tunnel); it is NOT a
batched-env mode (that is ``parallel.make_parallel_sac``).
"""

import jax


def make_block_fn(episode_body, block: int):
    """Jit a scan of ``block`` calls of ``episode_body(agent_state, buf,
    key) -> (agent_state, buf, score)``.

    Returns ``run_block(agent_state, buf, key) -> (agent_state, buf,
    advanced_key, scores[block])``; the advanced key lets a driver continue
    the exact same chain across blocks.
    """

    @jax.jit
    def run_block(agent_state, buf, key):
        def one(carry, _):
            agent_state, buf, key = carry
            key, k = jax.random.split(key)
            agent_state, buf, score = episode_body(agent_state, buf, k)
            return (agent_state, buf, key), score

        (agent_state, buf, key), scores = jax.lax.scan(
            one, (agent_state, buf, key), None, length=block)
        return agent_state, buf, key, scores

    return run_block
