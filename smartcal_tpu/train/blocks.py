"""Shared train-driver plumbing: episode-block dispatch + observability.

Episode blocks: one jitted program runs ``block`` strictly-sequential
episodes (the scan carry chains agent + replay state and reproduces the
drivers' host key chain ``key, k = split(key)`` per episode).  Identical
learning dynamics to per-episode dispatch — this amortizes the device
round trip, which dominates the small elastic-net programs on the chip
(round-3 capture: 33 env-steps/s at 1 dispatch/episode over the tunnel);
it is NOT a batched-env mode (that is ``parallel.make_parallel_sac``).

Observability: ``add_obs_args`` + ``train_obs``/``train_obs_from_args``
are the ONE wiring shared by all nine train entry points — a ``TrainObs``
owns the run's RunLog (activated for the process so env/backend spans and
solver telemetry record into it), the jax compile listener, an optional
profiler trace, and the per-episode "episode N score ..." echo (stderr,
``--quiet``-able; the JSONL stream is the machine interface).
"""

import os
import time

import jax

from smartcal_tpu import obs


def add_obs_args(p):
    """Attach the shared observability flags to an argparse parser."""
    p.add_argument("--metrics", type=str, default=None,
                   help="obs run JSONL path (header + episode/span/solver "
                        "events; aggregate with tools/obs_report.py)")
    p.add_argument("--run_id", type=str, default=None,
                   help="run id recorded in the JSONL header "
                        "(default: generated)")
    p.add_argument("--trace", type=str, default=None,
                   help="jax profiler trace dir (view with TensorBoard/"
                        "xprof; spans appear as TraceAnnotations)")
    p.add_argument("--quiet", action="store_true",
                   help="suppress the per-episode stderr echo")
    p.add_argument("--diag", action="store_true",
                   help="collect per-update agent diagnostics (UpdateDiag "
                        "grad norms/Q stats/entropy), replay health, and "
                        "per-stage FLOPs costs into the metrics stream")
    p.add_argument("--watchdog", action="store_true",
                   help="arm the divergence watchdog on the diagnostics "
                        "stream (implies --diag): on NaN losses, exploding "
                        "grad norms or Q blowup, emit watchdog_trip and "
                        "halt the run gracefully")
    return p


def diag_from_args(args) -> bool:
    """True when the run will actually CONSUME update diagnostics:
    ``--diag``/``--watchdog`` requested and there is somewhere for them
    to go (a metrics/trace stream, or the watchdog itself).  Drivers
    pass this as the agents' ``collect_diag``; it mirrors TrainObs's
    disarm rule so a ``--diag`` with no sink doesn't leave the agent
    compiling and computing an UpdateDiag nobody reads."""
    wd = bool(getattr(args, "watchdog", False))
    want = bool(getattr(args, "diag", False) or wd)
    sink = (getattr(args, "metrics", None) is not None
            or getattr(args, "trace", None) is not None or wd)
    return want and sink


class TrainObs:
    """Per-run observability handle for a train driver (see module doc).

    With neither ``metrics`` nor ``trace`` set, everything here is a
    no-op passthrough — the driver's hot loop is unchanged."""

    MEM_EVERY = 10          # episodes between device-memory gauge samples
    DIAG_LOG_EVERY = 1      # update-diag events logged every N updates

    def __init__(self, entry, metrics=None, run_id=None, trace=None,
                 quiet=False, diag=False, watchdog=False,
                 watchdog_cfg=None, **meta):
        self.entry = entry
        self.quiet = quiet
        self._t0 = time.time()
        self._episodes = 0
        self._tracing = False
        self._updates = 0
        self.diag = bool(diag or watchdog)
        self.watchdog = obs.Watchdog(watchdog_cfg) if watchdog else None
        path = metrics
        if path is None and trace:
            # a profiler trace without a metrics stream still wants the
            # span/solver record alongside the xprof dump
            path = os.path.join(trace, f"{entry}_run.jsonl")
        self.runlog = None
        if path:
            self.runlog = obs.RunLog(path, run_id=run_id,
                                     meta={"entry": entry, **meta})
            obs.activate(self.runlog)
            obs.install_compile_listener()
            if self.diag:
                # arm per-stage FLOPs accounting (cached once per
                # compiled signature) + the fraction-of-peak denominator
                from smartcal_tpu.obs import costs
                costs.set_enabled(True)
                costs.log_roofline_peak()
        if self.diag and self.runlog is None and self.watchdog is None:
            # --diag with neither a metrics stream nor an armed watchdog
            # has no consumer: disarm rather than silently paying the
            # per-update host sync for diagnostics nobody reads
            self.diag = False
            self.echo("--diag has no effect without --metrics or "
                      "--watchdog; diagnostics disabled")
        if trace:
            try:
                jax.profiler.start_trace(trace)
                self._tracing = True
            except Exception as e:
                self.echo(f"profiler trace unavailable: {e!r}")

    @property
    def collect_diag(self) -> bool:
        """Should the driver's agents thread UpdateDiag out of their
        jitted updates?  (diag stream or an armed watchdog.)"""
        return self.diag

    @property
    def tripped(self) -> bool:
        return self.watchdog is not None and self.watchdog.tripped

    def span(self, name, **tags):
        return obs.span(name, **tags)

    def record_diag(self, diag, **tags) -> bool:
        """Feed one (possibly step-stacked) UpdateDiag — or an already-
        host dict — into the diag stream + watchdog; the update index is
        the handle's running counter.  Returns True when the watchdog has
        tripped (the driver should exit its loop gracefully).
        ``diag=None`` (an agent that has not learned yet) just reports
        the current trip state."""
        if self.tripped:
            return True
        if diag is None or not self.diag:
            return self.tripped
        host = diag if isinstance(diag, dict) else obs.diag_to_host(diag)
        for stepd in obs.diag_steps(host):
            i = self._updates
            self._updates += 1
            if self.runlog is not None \
                    and i % self.DIAG_LOG_EVERY == 0:
                self.runlog.log("diag", step=i, **stepd, **tags)
            if self.watchdog is not None \
                    and self.watchdog.observe(stepd, step=i, **tags):
                self.echo(f"watchdog tripped at update {i}: "
                          f"{self.watchdog.trip_reason} — halting run")
                return True
        return False

    def log_replay_health(self, buf, **tags) -> bool:
        """Log one ``replay_health`` event for ``buf`` (a ReplayState, a
        NativePER, or anything with ``.health()``); feeds the watchdog.
        No-op unless diagnostics are on.  Returns the trip state."""
        if not self.diag:
            return self.tripped
        try:
            health = buf.health() if hasattr(buf, "health") else None
            if health is None:
                from smartcal_tpu.rl import replay as rp
                health = rp.replay_health(buf)
        except Exception as e:  # telemetry must never kill the run
            self.echo(f"replay_health unavailable: {e!r}")
            return self.tripped
        if self.runlog is not None:
            self.runlog.log("replay_health", **health, **tags)
        if self.watchdog is not None \
                and self.watchdog.observe_replay(health, **tags):
            self.echo(f"watchdog tripped on replay health: "
                      f"{self.watchdog.trip_reason} — halting run")
        return self.tripped

    def record_cost(self, stage, fn, *args, **kwargs):
        """Per-stage FLOPs/bytes accounting (see obs.costs) — cached per
        compiled signature, armed only under ``--diag``."""
        from smartcal_tpu.obs import costs
        return costs.record_stage_cost(stage, fn, *args, **kwargs)

    def episode(self, i, score, scores=None, echo=True, **fields):
        """Record one ``episode`` event + the classic stderr echo
        (``echo=False`` for drivers that print their own wording)."""
        if self.runlog is not None:
            self.runlog.log("episode", episode=i, score=score, **fields)
            self._episodes += 1
            if self._episodes % self.MEM_EVERY == 0:
                obs.log_memory_gauges()
            if self.diag:
                # between-episode gap = outside every span: run the cost
                # analyses the in-span sites deferred
                from smartcal_tpu.obs import costs
                costs.flush_pending()
        if echo and not self.quiet:
            if scores:
                tail = scores[-100:]
                avg = sum(float(s) for s in tail) / len(tail)
            else:
                avg = float(score)
            # event=None: the structured record is the episode event above
            obs.echo(f"episode {i} score {float(score):.2f} "
                     f"average score {avg:.2f}", event=None)

    def echo(self, msg, **fields):
        obs.echo(msg, quiet=self.quiet, **fields)

    def close(self):
        if self._tracing:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._tracing = False
        if self.runlog is not None:
            # reset: a later run in the same process (sweep drivers call
            # main() per seed) must not inherit this run's totals
            obs.flush_counters(reset=True)
            if self.diag:
                from smartcal_tpu.obs import costs
                costs.flush_pending()   # drain before the stream closes
                costs.set_enabled(False)
                costs.reset_cache()     # next run re-logs into ITS stream
            self.runlog.log("run_end", episodes=self._episodes,
                            updates=self._updates,
                            watchdog_tripped=self.tripped,
                            wall_s=round(time.time() - self._t0, 3))
            obs.deactivate(self.runlog)
            self.runlog.close()
            self.runlog = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def train_obs(entry, metrics=None, run_id=None, trace=None, quiet=False,
              diag=False, watchdog=False, **meta) -> TrainObs:
    return TrainObs(entry, metrics=metrics, run_id=run_id, trace=trace,
                    quiet=quiet, diag=diag, watchdog=watchdog, **meta)


def train_obs_from_args(args, entry, **meta) -> TrainObs:
    """Build the run handle from the ``add_obs_args`` flags (getattr-safe
    so programmatic Namespace callers without the new flags keep
    working)."""
    return TrainObs(entry,
                    metrics=getattr(args, "metrics", None),
                    run_id=getattr(args, "run_id", None),
                    trace=getattr(args, "trace", None),
                    quiet=getattr(args, "quiet", False),
                    diag=getattr(args, "diag", False),
                    watchdog=getattr(args, "watchdog", False),
                    seed=getattr(args, "seed", None), **meta)


def make_block_fn(episode_body, block: int):
    """Jit a scan of ``block`` calls of ``episode_body(agent_state, buf,
    key) -> (agent_state, buf, score)``.

    Returns ``run_block(agent_state, buf, key) -> (agent_state, buf,
    advanced_key, scores[block])``; the advanced key lets a driver continue
    the exact same chain across blocks.
    """

    @jax.jit
    def run_block(agent_state, buf, key):
        def one(carry, _):
            agent_state, buf, key = carry
            key, k = jax.random.split(key)
            agent_state, buf, score = episode_body(agent_state, buf, k)
            return (agent_state, buf, key), score

        (agent_state, buf, key), scores = jax.lax.scan(
            one, (agent_state, buf, key), None, length=block)
        return agent_state, buf, key, scores

    return run_block
