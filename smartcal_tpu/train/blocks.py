"""Shared train-driver plumbing: episode-block dispatch + observability.

Episode blocks: one jitted program runs ``block`` strictly-sequential
episodes (the scan carry chains agent + replay state and reproduces the
drivers' host key chain ``key, k = split(key)`` per episode).  Identical
learning dynamics to per-episode dispatch — this amortizes the device
round trip, which dominates the small elastic-net programs on the chip
(round-3 capture: 33 env-steps/s at 1 dispatch/episode over the tunnel);
it is NOT a batched-env mode (that is ``parallel.make_parallel_sac``).

Observability: ``add_obs_args`` + ``train_obs``/``train_obs_from_args``
are the ONE wiring shared by all nine train entry points — a ``TrainObs``
owns the run's RunLog (activated for the process so env/backend spans and
solver telemetry record into it), the jax compile listener, an optional
profiler trace, and the per-episode "episode N score ..." echo (stderr,
``--quiet``-able; the JSONL stream is the machine interface).
"""

import os
import time

import jax

from smartcal_tpu import obs
from smartcal_tpu.runtime import faults as rt_faults


def add_runtime_args(p):
    """Attach the shared fault-tolerance flags (checkpoint / resume /
    watchdog recovery) to an argparse parser — the companion of
    ``add_obs_args``, wired through every train entry point."""
    p.add_argument("--resume", action="store_true",
                   help="restore the run from the newest valid checkpoint "
                        "in --ckpt-dir and continue bit-continuably")
    p.add_argument("--ckpt-dir", dest="ckpt_dir", type=str, default=None,
                   help="checkpoint root (versioned ckpt_<episode>/ dirs + "
                        "LATEST pointer; default <entry>_ckpt)")
    p.add_argument("--ckpt-every", dest="ckpt_every", type=int, default=0,
                   help="checkpoint every N episodes (0 = none, except "
                        "--max-recoveries arms a default cadence of "
                        "10 so recovery has something to roll back to)")
    p.add_argument("--keep-ckpts", dest="keep_ckpts", type=int, default=3,
                   help="retained checkpoints (older ones are pruned)")
    p.add_argument("--max-recoveries", dest="max_recoveries", type=int,
                   default=0,
                   help="on a watchdog trip, roll back to the last good "
                        "checkpoint and retry up to N times before the "
                        "graceful halt (implies --watchdog; needs "
                        "--ckpt-every)")
    p.add_argument("--recovery-lr-shrink", dest="recovery_lr_shrink",
                   type=float, default=0.5,
                   help="learning-rate multiplier applied per recovery "
                        "attempt (1.0 disables the LR mitigation)")
    p.add_argument("--no-recovery-reseed", dest="recovery_reseed",
                   action="store_false", default=True,
                   help="do NOT fold a fresh offset into the exploration "
                        "key stream on recovery")
    return p


def add_obs_args(p):
    """Attach the shared observability flags to an argparse parser."""
    p.add_argument("--metrics", type=str, default=None,
                   help="obs run JSONL path (header + episode/span/solver "
                        "events; aggregate with tools/obs_report.py)")
    p.add_argument("--run_id", type=str, default=None,
                   help="run id recorded in the JSONL header "
                        "(default: generated)")
    p.add_argument("--trace", type=str, default=None,
                   help="jax profiler trace dir (view with TensorBoard/"
                        "xprof; spans appear as TraceAnnotations)")
    p.add_argument("--quiet", action="store_true",
                   help="suppress the per-episode stderr echo")
    p.add_argument("--diag", action="store_true",
                   help="collect per-update agent diagnostics (UpdateDiag "
                        "grad norms/Q stats/entropy), replay health, and "
                        "per-stage FLOPs costs into the metrics stream")
    p.add_argument("--watchdog", action="store_true",
                   help="arm the divergence watchdog on the diagnostics "
                        "stream (implies --diag): on NaN losses, exploding "
                        "grad norms or Q blowup, emit watchdog_trip and "
                        "halt the run gracefully")
    p.add_argument("--compile-cache", dest="compile_cache", type=str,
                   default=os.environ.get("SMARTCAL_COMPILE_CACHE") or None,
                   help="persistent XLA compilation cache dir (env "
                        "SMARTCAL_COMPILE_CACHE): repeat runs skip the "
                        "first-episode compile; hit/miss counters land in "
                        "the metrics stream")
    return p


def diag_from_args(args) -> bool:
    """True when the run will actually CONSUME update diagnostics:
    ``--diag``/``--watchdog`` requested and there is somewhere for them
    to go (a metrics/trace stream, or the watchdog itself).  Drivers
    pass this as the agents' ``collect_diag``; it mirrors TrainObs's
    disarm rule so a ``--diag`` with no sink doesn't leave the agent
    compiling and computing an UpdateDiag nobody reads."""
    wd = bool(getattr(args, "watchdog", False)
              or getattr(args, "max_recoveries", 0))
    want = bool(getattr(args, "diag", False) or wd)
    sink = (getattr(args, "metrics", None) is not None
            or getattr(args, "trace", None) is not None or wd)
    return want and sink


class TrainObs:
    """Per-run observability handle for a train driver (see module doc).

    With neither ``metrics`` nor ``trace`` set, everything here is a
    no-op passthrough — the driver's hot loop is unchanged."""

    MEM_EVERY = 10          # episodes between device-memory gauge samples
    DIAG_LOG_EVERY = 1      # update-diag events logged every N updates

    def __init__(self, entry, metrics=None, run_id=None, trace=None,
                 quiet=False, diag=False, watchdog=False,
                 watchdog_cfg=None, compile_cache=None, **meta):
        self.entry = entry
        self.quiet = quiet
        if compile_cache:
            # persistent XLA compilation cache (+ the obs hit/miss
            # listener): repeat runs stop paying the first compile
            from smartcal_tpu.serve.export import enable_compile_cache
            if not enable_compile_cache(compile_cache):
                self.echo(f"compile cache unavailable at {compile_cache}")
        self._t0 = time.time()
        self._episodes = 0
        self._tracing = False
        self._updates = 0
        self.diag = bool(diag or watchdog)
        self.watchdog = obs.Watchdog(watchdog_cfg) if watchdog else None
        # arm any SMARTCAL_FAULTS plan (deterministic injection for the
        # recovery smoke paths; no-op without the env var)
        rt_faults.install_from_env()
        path = metrics
        if path is None and trace:
            # a profiler trace without a metrics stream still wants the
            # span/solver record alongside the xprof dump
            path = os.path.join(trace, f"{entry}_run.jsonl")
        self.runlog = None
        if path:
            self.runlog = obs.RunLog(path, run_id=run_id,
                                     meta={"entry": entry, **meta})
            obs.activate(self.runlog)
            obs.install_compile_listener()
            if self.diag:
                # arm per-stage FLOPs accounting (cached once per
                # compiled signature) + the fraction-of-peak denominator
                from smartcal_tpu.obs import costs
                costs.set_enabled(True)
                costs.log_roofline_peak()
        if self.diag and self.runlog is None and self.watchdog is None:
            # --diag with neither a metrics stream nor an armed watchdog
            # has no consumer: disarm rather than silently paying the
            # per-update host sync for diagnostics nobody reads
            self.diag = False
            self.echo("--diag has no effect without --metrics or "
                      "--watchdog; diagnostics disabled")
        if trace:
            try:
                jax.profiler.start_trace(trace)
                self._tracing = True
            except Exception as e:
                self.echo(f"profiler trace unavailable: {e!r}")

    @property
    def collect_diag(self) -> bool:
        """Should the driver's agents thread UpdateDiag out of their
        jitted updates?  (diag stream or an armed watchdog.)"""
        return self.diag

    @property
    def tripped(self) -> bool:
        return self.watchdog is not None and self.watchdog.tripped

    def span(self, name, **tags):
        return obs.span(name, **tags)

    def record_diag(self, diag, **tags) -> bool:
        """Feed one (possibly step-stacked) UpdateDiag — or an already-
        host dict — into the diag stream + watchdog; the update index is
        the handle's running counter.  Returns True when the watchdog has
        tripped (the driver should exit its loop gracefully).
        ``diag=None`` (an agent that has not learned yet) just reports
        the current trip state."""
        if self.tripped:
            return True
        if diag is None or not self.diag:
            return self.tripped
        host = diag if isinstance(diag, dict) else obs.diag_to_host(diag)
        for stepd in obs.diag_steps(host):
            i = self._updates
            self._updates += 1
            # deterministic fault injection (runtime.faults): identity
            # unless a plan targets exactly this update index — the
            # CPU-testable path into the watchdog/rollback machinery
            stepd = rt_faults.mutate_diag(stepd, i)
            if self.runlog is not None \
                    and i % self.DIAG_LOG_EVERY == 0:
                self.runlog.log("diag", step=i, **stepd, **tags)
            if self.watchdog is not None \
                    and self.watchdog.observe(stepd, step=i, **tags):
                self.echo(f"watchdog tripped at update {i}: "
                          f"{self.watchdog.trip_reason} — halting run")
                return True
        return False

    def log_replay_health(self, buf, **tags) -> bool:
        """Log one ``replay_health`` event for ``buf`` (a ReplayState, a
        NativePER, or anything with ``.health()``); feeds the watchdog.
        No-op unless diagnostics are on.  Returns the trip state."""
        if not self.diag:
            return self.tripped
        try:
            health = buf.health() if hasattr(buf, "health") else None
            if health is None:
                from smartcal_tpu.rl import replay as rp
                health = rp.replay_health(buf)
        except Exception as e:  # telemetry must never kill the run
            self.echo(f"replay_health unavailable: {e!r}")
            return self.tripped
        if self.runlog is not None:
            self.runlog.log("replay_health", **health, **tags)
        if self.watchdog is not None \
                and self.watchdog.observe_replay(health, **tags):
            self.echo(f"watchdog tripped on replay health: "
                      f"{self.watchdog.trip_reason} — halting run")
        return self.tripped

    def record_cost(self, stage, fn, *args, **kwargs):
        """Per-stage FLOPs/bytes accounting (see obs.costs) — cached per
        compiled signature, armed only under ``--diag``."""
        from smartcal_tpu.obs import costs
        return costs.record_stage_cost(stage, fn, *args, **kwargs)

    def episode(self, i, score, scores=None, echo=True, **fields):
        """Record one ``episode`` event + the classic stderr echo
        (``echo=False`` for drivers that print their own wording)."""
        if self.runlog is not None:
            self.runlog.log("episode", episode=i, score=score, **fields)
            self._episodes += 1
            if self._episodes % self.MEM_EVERY == 0:
                obs.log_memory_gauges()
            if self.diag:
                # between-episode gap = outside every span: run the cost
                # analyses the in-span sites deferred
                from smartcal_tpu.obs import costs
                costs.flush_pending()
        if echo and not self.quiet:
            if scores:
                tail = scores[-100:]
                avg = sum(float(s) for s in tail) / len(tail)
            else:
                avg = float(score)
            # event=None: the structured record is the episode event above
            obs.echo(f"episode {i} score {float(score):.2f} "
                     f"average score {avg:.2f}", event=None)

    def echo(self, msg, **fields):
        obs.echo(msg, quiet=self.quiet, **fields)

    def close(self):
        if self._tracing:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._tracing = False
        if self.runlog is not None:
            # reset: a later run in the same process (sweep drivers call
            # main() per seed) must not inherit this run's totals
            obs.flush_counters(reset=True)
            if self.diag:
                from smartcal_tpu.obs import costs
                costs.flush_pending()   # drain before the stream closes
                costs.set_enabled(False)
                costs.reset_cache()     # next run re-logs into ITS stream
            self.runlog.log("run_end", episodes=self._episodes,
                            updates=self._updates,
                            watchdog_tripped=self.tripped,
                            wall_s=round(time.time() - self._t0, 3))
            obs.deactivate(self.runlog)
            self.runlog.close()
            self.runlog = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def train_obs(entry, metrics=None, run_id=None, trace=None, quiet=False,
              diag=False, watchdog=False, **meta) -> TrainObs:
    return TrainObs(entry, metrics=metrics, run_id=run_id, trace=trace,
                    quiet=quiet, diag=diag, watchdog=watchdog, **meta)


def train_obs_from_args(args, entry, **meta) -> TrainObs:
    """Build the run handle from the ``add_obs_args`` flags (getattr-safe
    so programmatic Namespace callers without the new flags keep
    working)."""
    return TrainObs(entry,
                    metrics=getattr(args, "metrics", None),
                    run_id=getattr(args, "run_id", None),
                    trace=getattr(args, "trace", None),
                    quiet=getattr(args, "quiet", False),
                    diag=getattr(args, "diag", False),
                    # --max-recoveries implies the watchdog: recovery
                    # without the detector would never fire
                    watchdog=(getattr(args, "watchdog", False)
                              or getattr(args, "max_recoveries", 0) > 0),
                    compile_cache=getattr(args, "compile_cache", None),
                    seed=getattr(args, "seed", None), **meta)


# salt folded into the exploration key stream by the recovery reseed
# mitigation (offset by the attempt so successive recoveries diverge)
RESEED_SALT = 0x5EED0


class TrainRuntime:
    """Per-run fault-tolerance handle: the checkpoint cadence, the
    ``--resume`` restore, and the watchdog rollback-and-retry policy —
    the ONE wiring shared by the train drivers (the companion of
    :class:`TrainObs`, built from ``add_runtime_args`` flags).

    With none of the flags set every method is a no-op/None, so a
    driver's hot loop is unchanged.
    """

    DEFAULT_RECOVERY_CKPT_EVERY = 10

    def __init__(self, entry, ckpt_dir=None, ckpt_every=0, keep=3,
                 resume=False, max_recoveries=0, lr_shrink=0.5,
                 reseed=True, tob=None):
        from smartcal_tpu.runtime import (Checkpointer, RecoveryManager,
                                          RecoveryPolicy)

        self.entry = entry
        self.tob = tob
        self.resume = bool(resume)
        if max_recoveries > 0 and ckpt_every <= 0:
            # recovery without a cadence would have nothing to roll back
            # to (the '0 = only what --max-recoveries needs' contract of
            # the --ckpt-every help)
            ckpt_every = self.DEFAULT_RECOVERY_CKPT_EVERY
            self._echo(f"--max-recoveries without --ckpt-every: "
                       f"checkpointing every {ckpt_every} episodes")
        enabled = bool(resume or ckpt_every or max_recoveries)
        self.ckpt = None
        if enabled:
            self.ckpt = Checkpointer(ckpt_dir or f"{entry}_ckpt",
                                     keep=keep, every=ckpt_every)
        self.recovery = RecoveryManager(
            RecoveryPolicy(max_recoveries=max_recoveries,
                           lr_shrink=lr_shrink, reseed=reseed), self.ckpt)

    @classmethod
    def from_args(cls, args, entry, tob=None) -> "TrainRuntime":
        """getattr-safe construction (programmatic Namespace callers
        without the runtime flags keep working)."""
        return cls(entry,
                   ckpt_dir=getattr(args, "ckpt_dir", None),
                   ckpt_every=getattr(args, "ckpt_every", 0),
                   keep=getattr(args, "keep_ckpts", 3),
                   resume=getattr(args, "resume", False),
                   max_recoveries=getattr(args, "max_recoveries", 0),
                   lr_shrink=getattr(args, "recovery_lr_shrink", 0.5),
                   reseed=getattr(args, "recovery_reseed", True), tob=tob)

    @property
    def enabled(self) -> bool:
        return self.ckpt is not None

    def _echo(self, msg):
        if self.tob is not None:
            self.tob.echo(msg)
        else:
            obs.echo(msg)

    def restore(self):
        """The ``--resume`` payload (newest valid checkpoint), or None."""
        if self.ckpt is None or not self.resume:
            return None
        loaded = self.ckpt.load_latest()
        if loaded is None:
            self._echo(f"--resume: no valid checkpoint under "
                       f"{self.ckpt.root!r}; starting fresh")
            return None
        payload, step = loaded
        rl = obs.active()
        if rl is not None:
            rl.log("resume", step=step, root=self.ckpt.root)
        self._echo(f"resumed from checkpoint step {step} "
                   f"({self.ckpt.root})")
        return payload

    def maybe_checkpoint(self, step, build_payload) -> bool:
        """Save when the cadence says so; ``build_payload`` (a zero-arg
        callable returning the host payload dict) runs only then."""
        if self.ckpt is None or not self.ckpt.due(step):
            return False
        self.ckpt.save(step, build_payload())
        return True

    def on_trip(self):
        """Watchdog-trip escalation: a RecoveryAction to apply (the
        caller restores the payload, applies the mitigation, and
        continues), or None → graceful halt.  Un-latches the watchdog
        when a rollback is granted."""
        reason = None
        if self.tob is not None and self.tob.watchdog is not None:
            reason = self.tob.watchdog.trip_reason
        act = self.recovery.on_trip(reason=reason)
        if act is None:
            return None
        if self.tob is not None and self.tob.watchdog is not None:
            self.tob.watchdog.reset()
        self._echo(f"watchdog recovery {act.attempt}/"
                   f"{self.recovery.policy.max_recoveries}: rolled back to "
                   f"episode {act.step} (lr x{act.lr_scale:g}, "
                   f"reseed={act.reseed})")
        return act


def rollback_fused(act, rebuild=None):
    """Restore an enet fused-driver checkpoint payload and apply the
    recovery mitigation — the ONE rollback implementation shared by the
    enet SAC/TD3/DDPG drivers.  ``rebuild(lr_scale)`` (optional) re-jits
    the driver's episode program(s) at the scaled config when the LR
    mitigation applies.  Returns ``(agent_state, buf, key, scores,
    episode)``; driver-specific payload extras (e.g. enet_sac's
    ``saved_marker``) stay with the caller."""
    import jax.numpy as jnp

    from smartcal_tpu.runtime import unpack_replay

    p = act.payload
    agent_state = jax.tree_util.tree_map(jnp.asarray, p["agent_state"])
    buf = unpack_replay(p["replay"])
    key = jnp.asarray(p["key"])
    if act.reseed:
        key = jax.random.fold_in(key, RESEED_SALT + act.attempt)
    if act.lr_scale != 1.0 and rebuild is not None:
        rebuild(act.lr_scale)
    return agent_state, buf, key, list(p["scores"]), int(p["episode"])


# ---------------------------------------------------------------------------
# Checkpoint payload helpers for the host-driven agent loops (SACAgent /
# TD3Agent / DDPGAgent drivers: calib_*, demix_*)
# ---------------------------------------------------------------------------

def pack_agent_loop(agent, env, scores, episode, extra=None) -> dict:
    """Host payload capturing EVERYTHING a host-driven agent loop needs
    to restart bit-continuably: agent pytree (params + opt + targets +
    alpha/rho counters + per-lane exploration state like DDPG's OU
    noise, all inside ``agent.state``), the agent's jax key stream, the
    replay buffer (incl. PER priorities, both backends), the env's
    episode RNG state — the single key chain for sequential envs, the
    per-lane key ARRAY + episode/step counters for batched envs
    (runtime.pack_env_state) — the native sampler's numpy RNG, scores,
    and the episode counter."""
    from smartcal_tpu.runtime import pack_env_state, pack_replay

    payload = {
        "kind": "agent_loop",
        "episode": int(episode),
        "scores": list(scores),
        "agent_state": jax.device_get(agent.state),
        "agent_key": jax.device_get(agent.key),
        "replay": pack_replay(agent.buffer),
    }
    if getattr(agent, "_rng", None) is not None:
        payload["agent_sample_rng"] = agent._rng.bit_generator.state
    if env is not None:
        env_state = pack_env_state(env)
        if env_state is not None:
            payload["env_state"] = env_state
    if extra:
        payload["extra"] = dict(extra)
    return payload


def restore_agent_loop(agent, env, payload):
    """Inverse of :func:`pack_agent_loop`: load the payload into
    ``agent``/``env`` in place; returns (scores, episode, extra)."""
    import jax.numpy as jnp

    from smartcal_tpu.runtime import restore_env_state, unpack_replay

    agent.state = jax.tree_util.tree_map(jnp.asarray,
                                         payload["agent_state"])
    agent.key = jnp.asarray(payload["agent_key"])
    agent.buffer = unpack_replay(payload["replay"])
    if "agent_sample_rng" in payload and getattr(agent, "_rng", None) \
            is not None:
        agent._rng.bit_generator.state = payload["agent_sample_rng"]
    if env is not None and "env_state" in payload:
        restore_env_state(env, payload["env_state"])
    elif env is not None and "env_key" in payload and hasattr(env, "_key"):
        # pre-batched-mode payloads carried the bare key
        env._key = jnp.asarray(payload["env_key"])
    return list(payload["scores"]), int(payload["episode"]), \
        payload.get("extra") or {}


def apply_agent_recovery(agent, base_cfg, act):
    """Apply a RecoveryAction's mitigation to a host agent wrapper:
    exploration reseed folds into the agent's key stream; an LR shrink
    rebuilds the agent's jitted updates at ``base_cfg`` with the
    CUMULATIVE scale (base_cfg is the driver's original config, so
    repeated recoveries don't compound twice).  Returns the (possibly
    new) agent — state/key/buffer carry over untouched."""
    import dataclasses

    if act.reseed:
        agent.key = jax.random.fold_in(agent.key, RESEED_SALT + act.attempt)
    if act.lr_scale != 1.0:
        cfg = dataclasses.replace(base_cfg,
                                  lr_a=base_cfg.lr_a * act.lr_scale,
                                  lr_c=base_cfg.lr_c * act.lr_scale)
        new = type(agent)(cfg, seed=0, name_prefix=agent.name_prefix,
                          collect_diag=agent.collect_diag)
        new.state, new.key, new.buffer = agent.state, agent.key, agent.buffer
        if getattr(agent, "_rng", None) is not None \
                and getattr(new, "_rng", None) is not None:
            new._rng = agent._rng
        agent = new
    return agent


def add_fleet_args(p):
    """Attach the async actor-learner fleet flags shared by the parallel
    learner CLIs (and any driver that spawns a supervised fleet): actor
    count, the IMPACT IS-clip constant, the ERE sampling knob, and the
    weight-publication cadence (the forced-staleness ablation knob)."""
    p.add_argument("--n-actors", dest="n_actors", type=int, default=None,
                   help="actor threads in the supervised fleet / logical "
                        "actors in the SPMD program (default: 2 "
                        "supervised, the mesh dp size SPMD)")
    p.add_argument("--is-clip", dest="is_clip", type=float, default=0.0,
                   help="IMPACT staleness-clipped importance weighting "
                        "constant c >= 1 (0 = off): stale transitions' "
                        "TD updates are weighted by the policy ratio "
                        "clipped to [1/c, c]; same-version transitions "
                        "are bit-identical to the unweighted path")
    add_ere_arg(p)
    p.add_argument("--publish-every", dest="publish_every", type=int,
                   default=1,
                   help="supervised fleet: publish learner weights every "
                        "N learner rounds (N > 1 forces actor staleness "
                        "— the IS-clip ablation knob)")
    p.add_argument("--actor-mode", dest="actor_mode",
                   choices=("thread", "process"), default="thread",
                   help="supervised fleet backend: 'thread' (default; "
                        "actors share this process and its GIL — the "
                        "PR 10 shape, bit-identical to it) or 'process' "
                        "(each actor is a spawned worker process "
                        "shipping framed transition batches over IPC "
                        "into per-slot ingest shards — scales past the "
                        "GIL)")
    p.add_argument("--replay-shards", dest="replay_shards", type=int,
                   default=0,
                   help="shard the learner's device-resident replay "
                        "ring over N mesh shards (0 = the flat "
                        "single-buffer layout): stores land "
                        "shard-local, sampling merges per-shard draws "
                        "via collectives, priority updates scatter "
                        "shard-local")
    p.add_argument("--sim-hosts", dest="sim_hosts", type=int, default=1,
                   help="process fleet: rehearse a multi-host topology "
                        "by tagging contiguous actor-slot blocks with N "
                        "simulated host ids (single machine; real "
                        "multi-host runs use --coordinator)")
    return p


def add_lifecycle_args(p):
    """Attach the online-lifecycle flags (tools/serve_learn.py — the
    learn-from-served-traffic loop of serve.lifecycle).  Shares the
    IMPACT/ERE spellings with ``add_fleet_args`` but with the lifecycle
    defaults ARMED: served traffic is off-policy and ages across policy
    hot-swaps, so staleness-clipped IS weighting and recency-biased
    sampling are the baseline here, not an ablation."""
    p.add_argument("--is-clip", dest="is_clip", type=float, default=2.0,
                   help="IMPACT staleness-clipped importance weighting "
                        "constant c >= 1 (0 = off; default ON at 2.0): "
                        "transitions teed under an older policy version "
                        "get their TD update weighted by the clipped "
                        "policy ratio; current-version transitions are "
                        "bit-identical to the unweighted path")
    add_ere_arg(p)
    p.set_defaults(ere_eta=0.996)        # recency bias ON by default here
    p.add_argument("--learn-every-s", dest="learn_every_s", type=float,
                   default=0.25,
                   help="learner loop tick: drain the transition stage, "
                        "ingest, and run one fused SAC step every S "
                        "seconds of serving")
    p.add_argument("--publish-every", dest="publish_every", type=int,
                   default=8,
                   help="publish (versioned re-export + atomic hot-swap) "
                        "the learner's policy every N learn steps")
    p.add_argument("--replay-shards", dest="replay_shards", type=int,
                   default=4,
                   help="mesh shards of the learner's device-resident "
                        "versioned replay ring")
    p.add_argument("--mem-size", dest="mem_size", type=int, default=1024,
                   help="replay ring capacity (divisible by "
                        "--replay-shards)")
    p.add_argument("--batch-size", dest="batch_size", type=int, default=64,
                   help="SAC learn batch size (the learn step no-ops "
                        "until the ring holds this many transitions)")
    p.add_argument("--stage-cap", dest="stage_cap", type=int, default=4096,
                   help="transition staging-ring capacity between the "
                        "batch worker and the learner (overflow drops "
                        "oldest, counted)")
    p.add_argument("--keep-versions", dest="keep_versions", type=int,
                   default=8,
                   help="published policy exports retained in the AOT "
                        "cache (older versions pruned)")
    return p


def add_ere_arg(p):
    """Just the ERE knob, for single-learner drivers (the fleet CLIs get
    it through ``add_fleet_args``)."""
    p.add_argument("--ere", dest="ere_eta", type=float, default=1.0,
                   help="emphasizing-recent-experience sampling knob "
                        "eta in (0, 1]: 1 = off, smaller biases replay "
                        "sampling toward recent transitions "
                        "(composes with PER)")
    return p


def add_batched_args(p):
    """Attach the batched-env flag shared by the radio train drivers."""
    p.add_argument("--batch-envs", dest="batch_envs", type=int, default=1,
                   help="run N env lanes as one batched program "
                        "(vmapped/lane-sharded episode batch; 1 = the "
                        "sequential reference loop).  Each vector step "
                        "stores N transitions and runs ONE learn — the "
                        "1:N learn:env-step regime of the enet batched "
                        "mode, certified by tools/certify_batched.py")
    return p


def run_batched_agent_loop(env, agent, agent_cfg, args, tob, rt,
                           scale_reward, use_hint=False, warmup=0,
                           warmup_rng=None, episodes=None, to_flat=None,
                           scores=None):
    """Vector-episode driver loop for the batched radio envs: each vector
    episode resets all E lanes, each vector step advances all lanes in
    ONE batched program, stores the E transitions, and runs ONE learn on
    the fat batch (the 1:E learn:env-step regime of the enet batched
    mode — certified against the sequential 1:1 loop by
    tools/certify_batched.py).

    ``scores`` keeps the sequential drivers' format: E per-lane
    mean-step-reward entries per vector episode, so the learning-curve
    tooling (summarize/obs_report) reads batched runs unchanged.
    ``warmup`` vector episodes act randomly (the demixing drivers'
    warmup phase) through ``warmup_rng``.  Checkpoint/resume and
    watchdog rollback ride the same TrainRuntime wiring as the
    sequential loops — payloads carry the per-lane env key array and
    counters (runtime.pack_env_state), so --resume keeps the same-seed
    bit-parity guarantee at B>1.
    """
    import numpy as np

    from smartcal_tpu.rl.networks import flatten_obs_batch
    from smartcal_tpu.runtime import atomic_pickle

    if to_flat is None:
        to_flat = flatten_obs_batch
    E = env.n_envs
    if episodes is None:
        episodes = getattr(args, "episodes", None)
        if episodes is None:
            episodes = args.iteration      # the demixing drivers' name
    n_vec = -(-episodes // E)              # ceil: full lane coverage
    # --load callers pass their pickled score history in; a checkpoint
    # restore below replaces it (same precedence as run_warmup_loop)
    scores = list(scores) if scores else []
    i = 0
    restored = rt.restore()
    if restored is not None:
        scores, i, extra = restore_agent_loop(agent, env, restored)
        if warmup_rng is not None and "np_rng" in extra:
            warmup_rng.bit_generator.state = extra["np_rng"]

    def ckpt_payload():
        # the warmup numpy RNG rides in extra (as in run_warmup_loop):
        # a kill/resume inside the warmup window must replay the same
        # random actions or the bit-parity guarantee breaks at B>1
        extra = ({"np_rng": warmup_rng.bit_generator.state}
                 if warmup_rng is not None else None)
        return pack_agent_loop(agent, env, scores, i, extra=extra)

    try:
        while i < n_vec:
            with tob.span("episode", episode=i, lanes=E):
                ob = env.reset()
                flat = to_flat(ob)
                score = np.zeros(E, np.float64)
                loop, done = 0, False
                while not done and loop < args.steps:
                    if i < warmup and warmup_rng is not None:
                        actions = warmup_rng.uniform(
                            -1.0, 1.0, (E, agent.cfg.n_actions)).astype(
                                np.float32)
                    else:
                        actions = np.asarray(
                            agent.choose_action(flat)).reshape(E, -1)
                    out = env.step(actions)
                    if use_hint:
                        ob2, rewards, dones, hints, _ = out
                    else:
                        ob2, rewards, dones, _ = out
                        hints = np.zeros((E, agent.cfg.n_actions),
                                         np.float32)
                    flat2 = to_flat(ob2)
                    for e in range(E):
                        agent.store_transition(
                            flat[e], actions[e],
                            scale_reward(float(rewards[e])), flat2[e],
                            bool(dones[e]), hints[e])
                    agent.learn()          # one fat learn per vector step
                    if tob.record_diag(agent.last_diag, episode=i):
                        done = True
                    score += np.asarray(rewards, np.float64)
                    flat = flat2
                    loop += 1
            if tob.tripped:
                act = rt.on_trip()
                if act is not None:
                    scores, i, _ = restore_agent_loop(agent, env,
                                                      act.payload)
                    agent = apply_agent_recovery(agent, agent_cfg, act)
                    continue
            per_lane = score / max(loop, 1)
            scores.extend(float(s) for s in per_lane)
            tob.log_replay_health(agent.buffer, episode=i)
            tob.episode(i, float(per_lane.mean()), scores,
                        seed=getattr(args, "seed", None), lanes=E)
            agent.save_models()
            atomic_pickle(scores, f"{args.prefix}_scores.pkl")
            if tob.tripped:
                break
            i += 1
            rt.maybe_checkpoint(i, ckpt_payload)
    finally:
        tob.close()
    return scores


def make_block_fn(episode_body, block: int):
    """Jit a scan of ``block`` calls of ``episode_body(agent_state, buf,
    key) -> (agent_state, buf, score)``.

    Returns ``run_block(agent_state, buf, key) -> (agent_state, buf,
    advanced_key, scores[block])``; the advanced key lets a driver continue
    the exact same chain across blocks.
    """

    @jax.jit
    def run_block(agent_state, buf, key):
        def one(carry, _):
            agent_state, buf, key = carry
            key, k = jax.random.split(key)
            agent_state, buf, score = episode_body(agent_state, buf, k)
            return (agent_state, buf, key), score

        (agent_state, buf, key), scores = jax.lax.scan(
            one, (agent_state, buf, key), None, length=block)
        return agent_state, buf, key, scores

    return run_block
