"""Supervised pipelines: data generation + training for the aux models.

Parity targets:
  * ``demixing_rl/makedata.py`` — (metadata, exhaustive-AIC hint) pairs
    into a TrainingBuffer (:27-37);
  * ``demixing_rl/train_regressor.py`` — Adam MLP regression with a
    train/test split and ||.||^2 loss (:36-84);
  * ``demixing_rl/train_tsk.py`` — TSK fuzzy regressor on the same buffer;
  * ``calibration/generate_data.py:519-615`` (generate_training_data) —
    per-direction features (normalized influence image + 8 scalars) and
    binary demix labels for the transformer classifier;
  * ``demixing/train_model.py`` — BCE transformer training;
  * ``demixing_rl/evaluate_tsk_msp.py`` — MLP vs TSK vs hint reward
    comparison on live env episodes.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from smartcal_tpu.cal import influence as influence_mod
from smartcal_tpu.cal import solver
from smartcal_tpu.envs.demixing import DemixingEnv
from smartcal_tpu.envs.radio import RadioBackend
from smartcal_tpu.models.regressor import RegressorNet, TrainingBuffer
from smartcal_tpu.models.transformer import TransformerEncoder, XYBuffer
from smartcal_tpu.models.tsk import train_tsk

META_SCALE = 1e-3


def make_hint_dataset(n_iter=40, K=6, backend: Optional[RadioBackend] = None,
                      seed=0, buffer_path=None, n_samples=3000):
    """(metadata, hint[:-1]) pairs from env resets (makedata.py:27-37)."""
    env = DemixingEnv(K=K, provide_hint=True, provide_influence=False,
                      backend=backend, seed=seed)
    M = 3 * K + 2
    buf = TrainingBuffer(n_samples, M, K - 1)
    for ci in range(n_iter):
        obs = env.reset()
        hint = env.get_hint()
        buf.store(obs["metadata"], hint[:-1])
        if buffer_path:
            buf.save_checkpoint(buffer_path)
    return buf


def train_regressor(buf: TrainingBuffer, n_iter=1000, batch_size=32,
                    lr=1e-3, test_frac=0.2, seed=0, hidden=32):
    """Adam MLP training (train_regressor.py:36-84).  Returns
    (params, history dict)."""
    x, y = buf.filled()
    rng = np.random.default_rng(seed)
    idx = rng.permutation(x.shape[0])
    n_test = max(1, int(test_frac * x.shape[0]))
    test_idx, train_idx = idx[:n_test], idx[n_test:]
    x_train = jnp.asarray(x[train_idx])
    y_train = jnp.asarray(y[train_idx])
    x_test = jnp.asarray(x[test_idx])
    y_test = jnp.asarray(y[test_idx])

    net = RegressorNet(n_outputs=y.shape[1], hidden=hidden)
    params = net.init(jax.random.PRNGKey(seed), x_train[:1])["params"]
    opt = optax.adam(lr)
    opt_state = opt.init(params)
    bs = min(batch_size, x_train.shape[0])

    @jax.jit
    def step(carry, k):
        params, opt_state = carry
        i = jax.random.choice(k, x_train.shape[0], (bs,), replace=False)

        def loss_fn(p):
            pred = net.apply({"params": p}, x_train[i])
            return jnp.sum((pred - y_train[i]) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state)
        return (optax.apply_updates(params, updates), opt_state), loss

    keys = jax.random.split(jax.random.PRNGKey(seed + 1), n_iter)
    (params, _), losses = jax.lax.scan(step, (params, opt_state), keys)
    test_mse = float(jnp.mean(jnp.sum(
        (net.apply({"params": params}, x_test) - y_test) ** 2, axis=-1)))
    return params, {"losses": np.asarray(losses), "test_mse": test_mse,
                    "net": net}


def train_tsk_on_buffer(buf: TrainingBuffer, seed=0, **kw):
    """TSK regressor on the same hint buffer (train_tsk.py)."""
    x, y = buf.filled()
    rng = np.random.default_rng(seed)
    idx = rng.permutation(x.shape[0])
    n_test = max(1, int(0.2 * x.shape[0]))
    return train_tsk(jax.random.PRNGKey(seed), x[idx[n_test:]],
                     y[idx[n_test:]], x_test=x[idx[:n_test]],
                     y_test=y[idx[:n_test]], **kw)


# ---------------------------------------------------------------------------
# Transformer classifier data + training
# ---------------------------------------------------------------------------

def generate_training_data(key, backend: RadioBackend, K=6,
                           flux_floor=1.0, el_floor=3.0):
    """One (x, y) sample for the demix transformer.

    x: K blocks of [normalized per-direction influence image (npix^2),
    separation, azimuth, elevation, log||J||, log||C||, log|Inf|, LLR,
    log(f_0)] (generate_data.py:586-615).  y: K-1 binary labels.

    Labels: the reference images each cluster with the beam and thresholds
    masked pixel sums (generate_data.py:535-580); here apparent fluxes are
    known exactly from the simulation, so y = apparent flux above
    ``flux_floor`` and elevation above ``el_floor`` — same decision, no
    imaging round-trip.
    """
    from smartcal_tpu.cal.dataset import assemble_features

    ep, mdl = backend.new_demixing_episode(key, K)
    res = backend.calibrate(ep, mdl.rho, mask=np.ones(K, np.float32))

    freqs = np.asarray(ep.obs.freqs)
    hadd = influence_mod.consensus_hadd_scalars(
        mdl.rho, np.full(K, 0.001, np.float32), freqs, ep.f0, 0,
        n_poly=backend.n_poly, polytype=backend.polytype)
    Rk = solver.residual_to_kernel(res.residual[0])
    inf = influence_mod.influence_visibilities(
        Rk, ep.Ccal[0], res.J[0], hadd, backend.n_stations,
        backend.n_chunks, perdir=True)
    summary = influence_mod.perdir_summary(inf.vis, inf.llr, ep.Ccal[0],
                                           res.J[0])
    x = assemble_features(inf.vis, summary, ep.obs.uvw, freqs,
                          mdl.separations, mdl.azimuth, mdl.elevation,
                          npix=backend.npix)

    y = ((mdl.fluxes[:-1] > flux_floor)
         & (mdl.elevation[:-1] >= el_floor)).astype(np.float32)
    return x, y


def make_transformer_dataset(n_iter=30, K=6,
                             backend: Optional[RadioBackend] = None,
                             seed=0, buffer_path=None):
    """demixing/simulate_data.py: n_iter samples into an XYBuffer."""
    backend = backend or RadioBackend()
    npix = backend.npix
    buf = XYBuffer(max(n_iter, 8), (K * (npix * npix + 8),), (K - 1,))
    key = jax.random.PRNGKey(seed)
    for ci in range(n_iter):
        key, k = jax.random.split(key)
        x, y = generate_training_data(k, backend, K=K)
        buf.store(x, y)
        if buffer_path:
            buf.save(buffer_path)
    return buf


def train_transformer(buf: XYBuffer, K=6, model_dim=66, epochs=2000,
                      batch_size=8, lr=1e-3, dropout=0.6, seed=0):
    """BCE training of the K-head classifier (demixing/train_model.py:26-57;
    Nmodel=66, dropout 0.6, heads=K)."""
    n = min(buf.mem_cntr, buf.mem_size)
    x = jnp.asarray(buf.x[:n])
    y = jnp.asarray(buf.y[:n])
    model = TransformerEncoder(num_layers=1, input_dim=x.shape[1],
                               model_dim=model_dim * K, num_classes=K - 1,
                               num_heads=K, dropout=dropout)
    k0, kd = jax.random.split(jax.random.PRNGKey(seed))
    params = model.init({"params": k0, "dropout": kd}, x[:1],
                        train=True)["params"]
    opt = optax.adam(lr)
    opt_state = opt.init(params)
    bs = min(batch_size, n)

    @jax.jit
    def step(carry, k):
        params, opt_state = carry
        ki, kd = jax.random.split(k)
        i = jax.random.choice(ki, n, (bs,), replace=False)

        def loss_fn(p):
            pred = model.apply({"params": p}, x[i], train=True,
                               rngs={"dropout": kd})
            pred = jnp.clip(pred, 1e-6, 1 - 1e-6)
            return -jnp.mean(y[i] * jnp.log(pred)
                             + (1 - y[i]) * jnp.log(1 - pred))

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state)
        return (optax.apply_updates(params, updates), opt_state), loss

    keys = jax.random.split(jax.random.PRNGKey(seed + 1), epochs)
    (params, _), losses = jax.lax.scan(step, (params, opt_state), keys)
    return params, {"losses": np.asarray(losses), "model": model}


# ---------------------------------------------------------------------------
# Transformer dataset maintenance: merge + class balancing
# ---------------------------------------------------------------------------

def merge_xy_buffers(*bufs: XYBuffer) -> XYBuffer:
    """Concatenate the filled parts of several datasets into one
    (demixing/mergebuffers.py:25-35)."""
    xs, ys = [], []
    for b in bufs:
        n = min(b.mem_cntr, b.mem_size)
        xs.append(b.x[:n])
        ys.append(b.y[:n])
    x = np.concatenate(xs)
    y = np.concatenate(ys)
    out = XYBuffer(x.shape[0], x.shape[1:], y.shape[1:])
    for xi, yi in zip(x, y):
        out.store(xi, yi)
    return out


def label_combination_counts(buf: XYBuffer):
    """Bit-encode each multi-label row into a class integer and count
    occurrences (populatebuffer.py:31-42's imbalance inspection).
    Returns (codes (n,), {code: count})."""
    n = min(buf.mem_cntr, buf.mem_size)
    codes = np.zeros(n, dtype=int)
    for ci in range(n):
        for bit in buf.y[ci]:
            codes[ci] = (codes[ci] << 1) | int(bit > 0.5)
    uniq, cnt = np.unique(codes, return_counts=True)
    return codes, dict(zip(uniq.tolist(), cnt.tolist()))


def balance_xy_buffer(buf: XYBuffer, seed: int = 0,
                      jitter: float = 1e-3) -> XYBuffer:
    """SMOTE-style oversampling of minority label combinations.

    The reference balances the transformer dataset with imblearn's
    SMOTETomek (populatebuffer.py:45-50); the essential mechanism —
    synthesize minority-class samples by convex interpolation between
    same-class neighbours — is ~20 lines of numpy, done here directly
    (no imblearn in the image).  Singleton combinations get jittered
    copies (no partner to interpolate with); the Tomek-link cleaning
    step is omitted (it removes boundary pairs, immaterial for the BCE
    training path).  Every combination is raised to the majority count.
    """
    rng = np.random.default_rng(seed)
    n = min(buf.mem_cntr, buf.mem_size)
    codes, counts = label_combination_counts(buf)
    target = max(counts.values())
    xs = [buf.x[:n]]
    ys = [buf.y[:n]]
    for code, cnt in counts.items():
        need = target - cnt
        if need <= 0:
            continue
        idx = np.where(codes == code)[0]
        i = rng.choice(idx, size=need)
        if len(idx) > 1:
            j = rng.choice(idx, size=need)
            resample = (j == i)
            j[resample] = idx[(np.searchsorted(idx, j[resample]) + 1)
                              % len(idx)]
            u = rng.random((need, 1)).astype(buf.x.dtype)
            x_new = buf.x[i] + u * (buf.x[j] - buf.x[i])
        else:
            scale = jitter * max(float(np.abs(buf.x[idx]).max()), 1.0)
            x_new = buf.x[i] + scale * rng.standard_normal(
                (need,) + buf.x.shape[1:]).astype(buf.x.dtype)
        xs.append(x_new)
        ys.append(buf.y[i])
    x = np.concatenate(xs)
    y = np.concatenate(ys)
    perm = rng.permutation(x.shape[0])
    out = XYBuffer(x.shape[0], x.shape[1:], y.shape[1:])
    for k in perm:
        out.store(x[k], y[k])
    return out


def evaluate_tsk_msp(buf: TrainingBuffer, mlp_params, mlp_net, tsk_params,
                     env: DemixingEnv, episodes=3):
    """MLP vs TSK vs data-driven hint rewards over live episodes
    (evaluate_tsk_msp.py:62-89).  Returns dict of per-episode rewards."""
    from smartcal_tpu.models.tsk import tsk_forward

    out = {"mlp": [], "tsk": [], "hint": []}
    for _ in range(episodes):
        obs = env.reset()
        md = jnp.asarray(obs["metadata"])[None]
        hint = env.get_hint()
        iter_act = hint[-1]
        for name, sel in (
                ("mlp", np.asarray(mlp_net.apply({"params": mlp_params},
                                                 md))[0]),
                ("tsk", np.asarray(tsk_forward(tsk_params, md))[0]),
                ("hint", hint[:-1])):
            action = np.concatenate([sel, [iter_act]]).astype(np.float32)
            _, reward, _, _ = env.step(action)[:4]
            out[name].append(float(reward))
    return out
