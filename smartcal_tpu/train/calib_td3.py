"""Calibration (ADMM-rho tuning) TD3 training driver.

Mirrors ``calibration/main_td3.py``: CNN+metadata TD3 agent (warmup random
phase, delayed actor updates every 2 learn calls, exploration noise 0.1)
stepping CalibEnv episodes of up to 10 steps, per-episode checkpointing.
The hint path uses TD3's adaptive-rho ADMM inner loop (enet_td3.py:310-361)
rather than SAC's penalty form.

Usage:
    python -m smartcal_tpu.train.calib_td3 --episodes 30 [--use_hint]
        [--small]
"""

from __future__ import annotations

import argparse

import numpy as np

from ..envs import CalibEnv
from ..envs.radio import RadioBackend
from ..rl import td3
from ..rl.networks import flatten_obs


def run(env, agent, episodes, steps, use_hint, prefix, metrics_path=None,
        obs_run=None, args=None):
    """Shared episode loop of the radio TD3/DDPG drivers
    (main_td3.py:23-48 / main_ddpg.py).

    ``args`` (the driver's parsed namespace) arms the shared
    fault-tolerance surface — ``--ckpt-every``/``--resume``/
    ``--max-recoveries`` (see train.blocks.add_runtime_args)."""
    from smartcal_tpu.runtime import atomic_pickle

    from .blocks import (TrainRuntime, apply_agent_recovery,
                         pack_agent_loop, restore_agent_loop, train_obs)

    scores = []
    tob = obs_run or train_obs(prefix, metrics=metrics_path)
    rt = TrainRuntime.from_args(args, prefix, tob=tob) if args is not None \
        else TrainRuntime(prefix, tob=tob)
    base_cfg = agent.cfg
    i = 0
    restored = rt.restore()
    if restored is not None:
        scores, i, _ = restore_agent_loop(agent, env, restored)

    def ckpt_payload():
        return pack_agent_loop(agent, env, scores, i)

    try:
        while i < episodes:
            with tob.span("episode", episode=i):
                obs = env.reset()
                flat = flatten_obs(obs)
                score, loop, done = 0.0, 0, False
                while not done and loop < steps:
                    action = np.asarray(agent.choose_action(flat)).squeeze()
                    out = env.step(action)
                    if use_hint:
                        obs2, reward, done, hint, info = out
                    else:
                        obs2, reward, done, info = out
                        hint = np.zeros_like(action)
                    flat2 = flatten_obs(obs2)
                    agent.store_transition(flat, action, reward, flat2,
                                           done, hint)
                    agent.learn()
                    if tob.record_diag(getattr(agent, "last_diag", None),
                                       episode=i):
                        done = True
                    score += reward
                    flat = flat2
                    loop += 1
            if tob.tripped:
                act = rt.on_trip()
                if act is not None:
                    scores, i, _ = restore_agent_loop(agent, env,
                                                      act.payload)
                    agent = apply_agent_recovery(agent, base_cfg, act)
                    continue
            scores.append(score / max(loop, 1))
            tob.log_replay_health(agent.buffer, episode=i)
            tob.episode(i, scores[-1], scores, use_hint=use_hint)
            agent.save_models()
            atomic_pickle(scores, f"{prefix}_scores.pkl")
            if tob.tripped:
                break
            i += 1
            rt.maybe_checkpoint(i, ckpt_payload)
    finally:
        tob.close()
    return scores


def build_backend(args):
    if args.small:
        return RadioBackend(n_stations=6, n_freqs=2, n_times=4, tdelta=2,
                            admm_iters=2, lbfgs_iters=3, init_iters=5,
                            npix=32)
    return RadioBackend(n_stations=args.stations, npix=args.npix)


def add_common_args(p):
    from .blocks import add_obs_args, add_runtime_args

    add_runtime_args(p)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--episodes", type=int, default=30)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--M", type=int, default=10)
    p.add_argument("--use_hint", action="store_true")
    p.add_argument("--stations", type=int, default=14)
    p.add_argument("--npix", type=int, default=128)
    p.add_argument("--small", action="store_true")
    p.add_argument("--load", action="store_true")
    add_obs_args(p)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    add_common_args(p)
    p.add_argument("--prefix", type=str, default="calib_td3")
    args = p.parse_args(argv)

    backend = build_backend(args)
    env = CalibEnv(M=args.M, provide_hint=args.use_hint, backend=backend,
                   seed=args.seed)
    npix = backend.npix
    cfg = td3.TD3Config(
        obs_dim=npix * npix + (args.M + 1) * 7, n_actions=2 * args.M,
        gamma=0.99, tau=0.005, batch_size=32, mem_size=1000, lr_a=1e-3,
        lr_c=1e-3, warmup=100, noise=0.1, update_actor_interval=2,
        use_hint=args.use_hint, img_shape=(npix, npix))
    from .blocks import diag_from_args, train_obs_from_args
    agent = td3.TD3Agent(cfg, seed=args.seed, name_prefix=args.prefix,
                         collect_diag=diag_from_args(args))
    if args.load:
        agent.load_models()
    return run(env, agent, args.episodes, args.steps, args.use_hint,
               args.prefix, obs_run=train_obs_from_args(args, "calib_td3"),
               args=args)


if __name__ == "__main__":
    main()
