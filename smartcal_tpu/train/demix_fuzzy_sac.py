"""Fuzzy-controller demixing SAC training driver.

Mirrors ``demixing_fuzzy/main_sac.py``: the action is the 24(K-1)+8
membership-trapezoid parameter vector of the Mamdani controller; the env
(FuzzyDemixingEnv) updates the controller, evaluates per-direction
priority vs cutoff to select directions, and calibrates.  Metadata is
5K+2 (adds log-fluxes + selected flags); influence maps are optional
(``--use_influence``; without it the CNN branch is dropped,
demixing_fuzzy/demix_sac.py:96-135) — the reward-shaping scale (x10 on
rewards above 0.01) and warmup-random phase follow the reference
(main_sac.py:70-99).

Usage:
    python -m smartcal_tpu.train.demix_fuzzy_sac --iteration 1000
        [--use_hint] [--use_influence] [--small]
"""

from __future__ import annotations

import argparse

import numpy as np

from ..envs.demixing_fuzzy import FuzzyDemixingEnv
from ..rl import sac
from ..rl.networks import flatten_obs
from .blocks import add_obs_args, add_runtime_args
from .calib_td3 import build_backend
from .demix_sac import run_warmup_loop

MIN_POSITIVE_REWARD = 0.01      # reference main_sac.py:70
REWARD_SCALE_POS = 10.0


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--iteration", type=int, default=1000)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--warmup", type=int, default=30)
    p.add_argument("--K", type=int, default=6)
    p.add_argument("--memory", type=int, default=30000)
    p.add_argument("--batch_size", type=int, default=256)
    p.add_argument("--use_hint", action="store_true")
    p.add_argument("--use_influence", action="store_true")
    p.add_argument("--stations", type=int, default=14)
    p.add_argument("--npix", type=int, default=128)
    p.add_argument("--small", action="store_true")
    p.add_argument("--load", action="store_true")
    p.add_argument("--prefix", type=str, default="demix_fuzzy_sac")
    add_obs_args(p)
    add_runtime_args(p)
    args = p.parse_args(argv)

    rng = np.random.default_rng(args.seed)
    backend = build_backend(args)
    env = FuzzyDemixingEnv(K=args.K, provide_hint=args.use_hint,
                           provide_influence=args.use_influence,
                           backend=backend, seed=args.seed)
    npix = backend.npix
    n_meta = env.n_metadata
    n_actions = env.n_actions
    if args.use_influence:
        obs_dim, img_shape = npix * npix + n_meta, (npix, npix)
    else:
        obs_dim, img_shape = n_meta, None
    agent_cfg = sac.SACConfig(
        obs_dim=obs_dim, n_actions=n_actions, gamma=0.99, tau=0.005,
        batch_size=args.batch_size, mem_size=args.memory, lr_a=3e-4,
        lr_c=3e-4, alpha=0.03, hint_threshold=0.01, admm_rho=1.0,
        use_hint=args.use_hint, hint_distance="kld", img_shape=img_shape,
        use_image=args.use_influence)
    from .blocks import diag_from_args
    agent = sac.SACAgent(agent_cfg, seed=args.seed, name_prefix=args.prefix,
                         collect_diag=diag_from_args(args))
    scores = []
    if args.load:
        # corruption-tolerant resume (see demix_sac.main)
        from smartcal_tpu.runtime import safe_pickle_load
        agent.load_models()
        scores = safe_pickle_load(f"{args.prefix}_scores.pkl", default=[])

    def to_flat(o):
        return (flatten_obs(o) if args.use_influence
                else np.asarray(o["metadata"], np.float32))

    return run_warmup_loop(
        env, agent, args, scores, to_flat, n_actions=n_actions,
        scale_reward=lambda r: (r * REWARD_SCALE_POS
                                if r > MIN_POSITIVE_REWARD else r),
        rng=rng)


if __name__ == "__main__":
    main()
