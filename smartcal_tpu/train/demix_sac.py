"""Demixing (direction selection) SAC training driver.

Mirrors ``demixing_rl/main_sac.py``: K=6 directions (5 outliers + target),
K actions (K-1 selections + max ADMM iterations), 7 steps per episode,
warmup episodes with random actions, positive rewards scaled by 10,
per-episode checkpointing.  Runs on the hermetic in-framework backend.

Usage:
    python -m smartcal_tpu.train.demix_sac --iteration 1000 --seed 0
        [--use_hint] [--provide_influence] [--small]
"""

from __future__ import annotations

import argparse

import jax
import numpy as np

from ..envs import DemixingEnv
from ..envs.radio import RadioBackend
from ..rl import sac
from ..rl.networks import flatten_obs
from .blocks import (add_batched_args, add_ere_arg, add_obs_args,
                     add_runtime_args,
                     diag_from_args,
                     train_obs_from_args)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--iteration", type=int, default=1000,
                   help="max episodes")
    p.add_argument("--warmup", type=int, default=30,
                   help="warmup episodes (random actions)")
    p.add_argument("--steps", type=int, default=7)
    p.add_argument("--K", type=int, default=6)
    p.add_argument("--use_hint", action="store_true")
    p.add_argument("--provide_influence", action="store_true")
    p.add_argument("--stations", type=int, default=14)
    p.add_argument("--npix", type=int, default=128)
    p.add_argument("--small", action="store_true")
    p.add_argument("--medium", action="store_true",
                   help="N=stations but thinner time/freq axes + lighter "
                   "inner solves — the learning dynamics of the default "
                   "config at ~8x less compute (CPU-tractable sweeps)")
    p.add_argument("--light", action="store_true",
                   help="see make_backend: one solution interval, "
                        "minimum useful solver iterations")
    p.add_argument("--load", action="store_true")
    p.add_argument("--prefix", type=str, default="demix_sac")
    add_obs_args(p)
    add_runtime_args(p)
    add_batched_args(p)
    add_ere_arg(p)
    args = p.parse_args(argv)

    rng = np.random.default_rng(args.seed)
    backend = make_backend(args)
    batched = getattr(args, "batch_envs", 1) > 1
    if batched:
        if args.use_hint:
            raise SystemExit("--use_hint is not supported with "
                             "--batch-envs (the exhaustive hint sweep "
                             "stays per-lane; run it sequentially)")
        from ..envs import BatchedDemixingEnv
        env = BatchedDemixingEnv(K=args.K, n_envs=args.batch_envs,
                                 provide_influence=args.provide_influence,
                                 backend=backend, seed=args.seed)
    else:
        env = DemixingEnv(K=args.K, provide_hint=args.use_hint,
                          provide_influence=args.provide_influence,
                          backend=backend, seed=args.seed)
    npix = backend.npix
    # without influence maps the observation is metadata-only: storing the
    # all-zero npix^2 image in replay would waste ~2 GB at mem_size=16000
    if args.provide_influence:
        obs_dim = npix * npix + 3 * args.K + 2
        img_shape = (npix, npix)
    else:
        obs_dim = 3 * args.K + 2
        img_shape = None
    agent_cfg = sac.SACConfig(
        obs_dim=obs_dim, n_actions=args.K, gamma=0.99, tau=0.005,
        batch_size=256, mem_size=16000, lr_a=3e-4, lr_c=1e-3, alpha=0.03,
        hint_threshold=0.01, admm_rho=1.0, use_hint=args.use_hint,
        hint_distance="kld", img_shape=img_shape,
        ere_eta=args.ere_eta)
    agent = sac.SACAgent(agent_cfg, seed=args.seed, name_prefix=args.prefix,
                         collect_diag=diag_from_args(args))
    scores = []
    if args.load:
        # corruption-tolerant resume: a truncated/corrupt file (e.g. a
        # pre-atomic-write kill) warns and starts fresh instead of crashing
        from smartcal_tpu.runtime import safe_pickle_load
        agent.load_models()
        scores = safe_pickle_load(f"{args.prefix}_scores.pkl", default=[])

    def to_flat(o):
        return (flatten_obs(o) if args.provide_influence
                else np.asarray(o["metadata"], np.float32))

    if batched:
        from ..rl.networks import flatten_obs_batch
        from .blocks import (TrainRuntime, run_batched_agent_loop,
                             train_obs_from_args)

        def to_flat_b(o):
            return (flatten_obs_batch(o) if args.provide_influence
                    else np.asarray(o["metadata"], np.float32))

        tob = train_obs_from_args(args, args.prefix)
        rt = TrainRuntime.from_args(args, args.prefix, tob=tob)
        return run_batched_agent_loop(
            env, agent, agent_cfg, args, tob, rt,
            scale_reward=lambda r: r * 10 if r > 0 else r,
            warmup=-(-args.warmup // args.batch_envs), warmup_rng=rng,
            episodes=args.iteration, to_flat=to_flat_b, scores=scores)

    # rewards > 0 scaled by 10 (demixing_rl/main_sac.py reward shaping)
    return run_warmup_loop(
        env, agent, args, scores, to_flat, n_actions=args.K,
        scale_reward=lambda r: r * 10 if r > 0 else r, rng=rng)


def make_backend(args):
    """Backend-size tiers shared by the demixing-family drivers (SAC,
    TD3, fuzzy): ``--small`` (test-speed), ``--light`` (N=stations, one
    solution interval, minimum useful inner solves — measured 1.3 s/solve
    on the single-core host, the only tier whose 32-config hint sweep
    allows multi-seed paired sweeps there), ``--medium`` (N=stations with
    thinner time/freq axes — the default config's learning dynamics at
    ~8x less compute; 3.35 s/solve measured), default (reference-like
    N/Nf/T)."""
    if getattr(args, "small", False):
        return RadioBackend(n_stations=6, n_freqs=2, n_times=4, tdelta=2,
                            admm_iters=30, lbfgs_iters=3, init_iters=5,
                            npix=32)
    if getattr(args, "light", False):
        return RadioBackend(n_stations=args.stations, n_freqs=2,
                            n_times=5, tdelta=5, admm_iters=30,
                            lbfgs_iters=3, init_iters=8, npix=args.npix,
                            hint_batch=1)
    if getattr(args, "medium", False):
        return RadioBackend(n_stations=args.stations, n_freqs=2,
                            n_times=10, tdelta=5, admm_iters=30,
                            lbfgs_iters=4, init_iters=10, npix=args.npix,
                            hint_batch=1)
    return RadioBackend(n_stations=args.stations, admm_iters=30,
                        npix=args.npix)


def _clear_every(default=20):
    import os

    try:
        return max(1, int(os.environ.get("SMARTCAL_CLEAR_EVERY", default)))
    except ValueError:
        return default


def run_warmup_loop(env, agent, args, scores, to_flat, n_actions,
                    scale_reward, rng):
    """Shared warmup/step/store/learn episode loop of the demixing-family
    drivers (demixing_rl/main_sac.py:54-98, demixing_fuzzy/main_sac.py:
    70-99 — identical control flow, differing only in the reward-shaping
    rule and the observation flattening).

    Fault tolerance (``add_runtime_args`` flags): ``--ckpt-every`` writes
    an atomic versioned checkpoint capturing agent + replay (incl. PER
    priorities) + the agent/env key streams + the warmup numpy RNG +
    scores, ``--resume`` restarts from it bit-continuably, and a
    watchdog trip with ``--max-recoveries`` rolls back and retries with
    the policy's mitigation before the graceful halt."""
    from smartcal_tpu.runtime import atomic_pickle

    from .blocks import (TrainRuntime, apply_agent_recovery,
                         pack_agent_loop, restore_agent_loop)

    tob = train_obs_from_args(args, getattr(args, "prefix", "demix"))
    rt = TrainRuntime.from_args(args, getattr(args, "prefix", "demix"),
                                tob=tob)
    base_cfg = agent.cfg
    total_steps = 0
    warmup_steps = args.warmup * args.steps
    i = 0
    restored = rt.restore()
    if restored is not None:
        scores_r, i, extra = restore_agent_loop(agent, env, restored)
        scores[:] = scores_r
        total_steps = int(extra.get("total_steps", 0))
        if "np_rng" in extra:
            rng.bit_generator.state = extra["np_rng"]

    def ckpt_payload():
        return pack_agent_loop(
            agent, env, scores, i,
            extra={"total_steps": total_steps,
                   "np_rng": rng.bit_generator.state})

    try:
        while i < args.iteration:
            with tob.span("episode", episode=i):
                obs = env.reset()
                flat = to_flat(obs)
                score, loop, done = 0.0, 0, False
                while not done and loop < args.steps:
                    if total_steps < warmup_steps:
                        action = rng.uniform(-1, 1,
                                             n_actions).astype(np.float32)
                    else:
                        action = np.asarray(
                            agent.choose_action(flat)).squeeze()
                    out = env.step(action)
                    if args.use_hint:
                        obs2, reward, done, hint, info = out
                    else:
                        obs2, reward, done, info = out
                        hint = np.zeros(n_actions, np.float32)
                    flat2 = to_flat(obs2)
                    agent.store_transition(flat, action,
                                           scale_reward(reward),
                                           flat2, done, hint)
                    agent.learn()
                    if tob.record_diag(getattr(agent, "last_diag", None),
                                       episode=i):
                        done = True
                    score += reward
                    flat = flat2
                    loop += 1
                    total_steps += 1
            if tob.tripped:
                act = rt.on_trip()
                if act is not None:
                    # rollback-and-retry: discard the poisoned episodes,
                    # restore the checkpoint, apply the mitigation
                    scores_r, i, extra = restore_agent_loop(agent, env,
                                                            act.payload)
                    scores[:] = scores_r
                    total_steps = int(extra.get("total_steps", 0))
                    if "np_rng" in extra:
                        rng.bit_generator.state = extra["np_rng"]
                    agent = apply_agent_recovery(agent, base_cfg, act)
                    continue
            scores.append(score / max(loop, 1))
            tob.log_replay_health(agent.buffer, episode=i)
            tob.episode(i, scores[-1], scores, seed=args.seed,
                        use_hint=args.use_hint,
                        warmup=total_steps <= warmup_steps)
            agent.save_models()
            atomic_pickle(scores, f"{args.prefix}_scores.pkl")
            if tob.tripped:
                break
            i += 1
            rt.maybe_checkpoint(i, ckpt_payload)
            if i % _clear_every() == 0:
                # bound live compiled executables: long hint-mode runs
                # segfault the XLA CPU client near episode ~43 otherwise
                # (the same deterministic crash the test suite hit in
                # round 1 — tests/conftest.py clears per module for the
                # same reason); costs one recompile pass per clear.
                # SMARTCAL_CLEAR_EVERY widens the interval for long sweeps
                # where the recompile tax dominates (the crash rate scales
                # with live-executable count, which stays bounded either
                # way).
                jax.clear_caches()
    finally:
        tob.close()
    return scores


if __name__ == "__main__":
    main()
