"""Evaluate a trained elasticnet agent against classic grid search.

Re-expresses the reference evaluation-as-integration-test
(``elasticnet/enet_eval.py:85-112``): a trained agent picks regularisation
via RL on fixed-noise episodes; grid search (the env's hint machinery — the
same 5x5 lambda grid with 2-fold CV the reference runs through sklearn
``GridSearchCV``) picks its best; both solutions are compared to the ground
truth by relative L1 error.

    python -m smartcal_tpu.train.enet_eval --games 2 --agent sac_state.pkl
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..envs import enet
from ..ops.lbfgs import lbfgs_solve
from ..rl import sac


def solve_enet(A, y, lam1, lam2, M):
    """Plain elastic-net solve at given regularisation (SKEnet.fit path)."""
    def fun(x):
        err = y - A @ x
        return (jnp.sum(err ** 2) + lam2 * jnp.sum(x ** 2)
                + lam1 * jnp.sum(jnp.abs(x)))

    return lbfgs_solve(fun, jnp.zeros((M,), jnp.float32), max_iters=200).x


def evaluate(agent_path: str = "sac_state.pkl", games: int = 2, steps: int = 4,
             M: int = 20, N: int = 20, seed: int = 0):
    env_cfg = enet.EnetConfig(M=M, N=N)
    agent_cfg = sac.SACConfig(obs_dim=env_cfg.obs_dim, n_actions=2)
    from smartcal_tpu.runtime.atomic import strict_pickle_load

    agent_state = jax.tree_util.tree_map(jnp.asarray,
                                         strict_pickle_load(agent_path))

    key = jax.random.PRNGKey(seed)
    results = []
    for i in range(games):
        key, k_reset, k_noise = jax.random.split(key, 3)
        st, obs = enet.reset(env_cfg, k_reset)
        st = enet.draw_noise(env_cfg, st, k_noise)

        # RL rollout on fixed noise
        rho = None
        for _ in range(steps):
            key, k_act, k_step = jax.random.split(key, 3)
            action = sac.choose_action(agent_cfg, agent_state, obs, k_act,
                                       deterministic=True)
            rho, _ = enet.action_to_rho(action)
            st, obs, reward, _ = enet.step(env_cfg, st, action, k_step,
                                           keepnoise=True)

        # grid search on the same data; hint[0]=lambda1 (L1), hint[1]=lambda2
        # (L2) in the SKEnet objective (enetenv.py:237-239,275-280)
        hint_action = enet.get_hint(env_cfg, st)
        lam_grid, _ = enet.action_to_rho(hint_action)
        x_grid = solve_enet(st.A, st.y, lam_grid[0], lam_grid[1], M)

        x0 = np.asarray(st.x0)
        rel = lambda x: (np.linalg.norm(x0 - np.asarray(x), 1)
                         / np.linalg.norm(x0, 1))
        row = {"game": i,
               "rl_rho": np.asarray(rho).tolist(),
               "grid_rho": np.asarray(lam_grid).tolist(),
               "rl_rel_err": float(rel(st.x)),
               "grid_rel_err": float(rel(x_grid))}
        results.append(row)
        obs.echo(f"{i} RL {row['rl_rho'][0]:.4f},{row['rl_rho'][1]:.4f} "
                 f"GR {row['grid_rho'][0]:.4f},{row['grid_rho'][1]:.4f}",
                 event=None)
        obs.echo(f"RL {row['rl_rel_err']:.4f} GR {row['grid_rel_err']:.4f}",
                 event="eval_game", **row)
    return results


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--agent", default="sac_state.pkl")
    p.add_argument("--games", default=2, type=int)
    p.add_argument("--steps", default=4, type=int)
    p.add_argument("--seed", default=0, type=int)
    args = p.parse_args()
    evaluate(agent_path=args.agent, games=args.games, steps=args.steps,
             seed=args.seed)


if __name__ == "__main__":
    main()
