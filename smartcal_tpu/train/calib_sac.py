"""Calibration (ADMM-rho tuning) SAC training driver.

Mirrors ``calibration/main_sac.py``: M=10 max directions, 2M actions,
episodes of up to 4 steps, rewards > 1 scaled by 10, per-episode model
checkpointing, score moving average.  The env runs hermetically on the
in-framework backend (envs/radio.py) instead of shelling to
dosimul/docal/doinfluence.

Usage:
    python -m smartcal_tpu.train.calib_sac --episodes 50 --seed 0
        [--use_hint] [--stations 14] [--small]
"""

from __future__ import annotations

import argparse

import numpy as np

from ..envs import CalibEnv
from ..envs.radio import RadioBackend
from ..rl import sac
from ..rl.networks import flatten_obs
from .blocks import (add_batched_args, add_ere_arg, add_obs_args,
                     add_runtime_args,
                     diag_from_args, train_obs_from_args)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--episodes", type=int, default=50)
    p.add_argument("--steps", type=int, default=4)
    p.add_argument("--M", type=int, default=10)
    p.add_argument("--use_hint", action="store_true")
    p.add_argument("--stations", type=int, default=14)
    p.add_argument("--npix", type=int, default=128)
    p.add_argument("--small", action="store_true",
                   help="tiny shapes for smoke runs")
    p.add_argument("--medium", action="store_true",
                   help="N=stations, thinner time/freq axes + lighter "
                        "inner solves (CPU-tractable sweeps; see "
                        "demix_sac.make_backend)")
    p.add_argument("--light", action="store_true",
                   help="one solution interval, minimum useful solver "
                        "iterations (multi-seed CPU sweeps)")
    p.add_argument("--load", action="store_true")
    p.add_argument("--prefix", type=str, default="calib_sac")
    p.add_argument("--fixed_K", type=int, default=None,
                   help="pin the per-episode direction count (sweep "
                        "variance reduction; default: reference draw "
                        "in [2, M])")
    p.add_argument("--baseline_reward", action="store_true",
                   help="subtract each episode's own reset-calibration "
                        "reward from step rewards (demixing reward0 "
                        "pattern; sweep variance reduction)")
    add_obs_args(p)
    add_runtime_args(p)
    add_batched_args(p)
    add_ere_arg(p)
    args = p.parse_args(argv)

    if args.small:
        backend = RadioBackend(n_stations=6, n_freqs=2, n_times=4, tdelta=2,
                               admm_iters=2, lbfgs_iters=3, init_iters=5,
                               npix=32)
    elif args.light or args.medium:
        # same CPU-tractable tiers as the demixing sweep (the two envs
        # share the backend, so the measured per-solve costs in
        # results/demix_curves_r3/README.md apply here too)
        from .demix_sac import make_backend
        backend = make_backend(args)
    else:
        backend = RadioBackend(n_stations=args.stations, npix=args.npix)
    batched = getattr(args, "batch_envs", 1) > 1
    if batched:
        from ..envs import BatchedCalibEnv
        env = BatchedCalibEnv(M=args.M, n_envs=args.batch_envs,
                              provide_hint=args.use_hint, backend=backend,
                              seed=args.seed, fixed_K=args.fixed_K,
                              baseline_reward=args.baseline_reward)
    else:
        env = CalibEnv(M=args.M, provide_hint=args.use_hint,
                       backend=backend, seed=args.seed,
                       fixed_K=args.fixed_K,
                       baseline_reward=args.baseline_reward)
    npix = backend.npix
    obs_dim = npix * npix + (args.M + 1) * 7
    agent_cfg = sac.SACConfig(
        obs_dim=obs_dim, n_actions=2 * args.M, gamma=0.99, tau=0.005,
        batch_size=32, mem_size=10000, lr_a=1e-3, lr_c=1e-3,
        reward_scale=args.M, alpha=0.03, hint_threshold=0.01, admm_rho=1.0,
        use_hint=args.use_hint, hint_distance="kld",
        img_shape=(npix, npix), ere_eta=args.ere_eta)
    agent = sac.SACAgent(agent_cfg, seed=args.seed, name_prefix=args.prefix,
                         collect_diag=diag_from_args(args))
    if args.load:
        agent.load_models()

    from smartcal_tpu.runtime import atomic_pickle

    from .blocks import (TrainRuntime, apply_agent_recovery,
                         pack_agent_loop, restore_agent_loop)

    scores = []
    tob = train_obs_from_args(args, "calib_sac")
    rt = TrainRuntime.from_args(args, args.prefix, tob=tob)
    if batched:
        # batched-episode mode: E lanes per vector step, one fat learn
        # per vector step; rewards keep the main_sac.py >1 x10 scaling
        from .blocks import run_batched_agent_loop
        return run_batched_agent_loop(
            env, agent, agent_cfg, args, tob, rt,
            scale_reward=lambda r: r * 10 if r > 1 else r,
            use_hint=args.use_hint)
    i = 0
    restored = rt.restore()
    if restored is not None:
        scores, i, _ = restore_agent_loop(agent, env, restored)

    def ckpt_payload():
        return pack_agent_loop(agent, env, scores, i)

    try:
        while i < args.episodes:
            with tob.span("episode", episode=i):
                obs = env.reset()
                flat = flatten_obs(obs)
                score, loop, done = 0.0, 0, False
                while not done and loop < args.steps:
                    action = np.asarray(agent.choose_action(flat)).squeeze()
                    out = env.step(action)
                    if args.use_hint:
                        obs2, reward, done, hint, info = out
                    else:
                        obs2, reward, done, info = out
                        hint = np.zeros(2 * args.M, np.float32)
                    flat2 = flatten_obs(obs2)
                    # rewards > 1 scaled by 10 (main_sac.py:24,49)
                    scaled = reward * 10 if reward > 1 else reward
                    agent.store_transition(flat, action, scaled, flat2,
                                           done, hint)
                    agent.learn()
                    if tob.record_diag(agent.last_diag, episode=i):
                        done = True
                    score += reward
                    flat = flat2
                    loop += 1
            if tob.tripped:
                act = rt.on_trip()
                if act is not None:
                    scores, i, _ = restore_agent_loop(agent, env,
                                                      act.payload)
                    agent = apply_agent_recovery(agent, agent_cfg, act)
                    continue
            scores.append(score / max(loop, 1))
            tob.log_replay_health(agent.buffer, episode=i)
            tob.episode(i, scores[-1], scores, seed=args.seed,
                        use_hint=args.use_hint)
            agent.save_models()
            atomic_pickle(scores, f"{args.prefix}_scores.pkl")
            if tob.tripped:
                break
            i += 1
            rt.maybe_checkpoint(i, ckpt_payload)
    finally:
        tob.close()
    return scores


if __name__ == "__main__":
    main()
