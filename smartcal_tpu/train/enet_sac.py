"""Elastic-net SAC training driver.

Mirrors ``elasticnet/main_sac.py`` (episode loop, per-step learn, moving
average of scores, periodic checkpointing) with two execution modes:

* ``--mode fused`` (default): each episode — reset, optional hint grid
  search, then a ``lax.scan`` over steps where action sampling, env step
  (L-BFGS solve + influence), replay store and the SAC learn step are one
  XLA computation.  This is the TPU-native hot path measured by bench.py.
* ``--mode loop``: host-driven loop through the gym-like wrapper, matching
  the reference control flow piecewise (useful for debugging).

Usage:
    python -m smartcal_tpu.train.enet_sac --episodes 1000 --steps 5 --seed 0
        [--use_hint] [--mode fused|loop]
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp

from ..envs import enet
from ..rl import replay as rp
from ..rl import sac
from .blocks import make_block_fn


def _make_episode_body(env_cfg: enet.EnetConfig, agent_cfg: sac.SACConfig,
                       steps: int, use_hint: bool,
                       collect_diag: bool = False):
    """The traceable one-episode computation (reset + scan over steps),
    shared by the per-episode jit and the episode-block scan.

    ``collect_diag`` (python-static, the agents' UpdateDiag plumbing)
    makes the episode ADDITIONALLY return the step-stacked diagnostics;
    with it False the traced program is the exact pre-diagnostics one."""

    def run_episode(agent_state, buf, key):
        k_reset, k_noise, k_scan = jax.random.split(key, 3)
        env_state, obs = enet.reset(env_cfg, k_reset)
        # the episode hint is computed from the FIRST step's noisy draw
        # (reference: step() draws noise, then get_hint uses self.y,
        # enetenv.py:87-90,156-158) — draw it now, reuse it on step 0
        env_state = enet.draw_noise(env_cfg, env_state, k_noise)
        hint = (enet.get_hint(env_cfg, env_state) if use_hint
                else jnp.zeros((agent_cfg.n_actions,), jnp.float32))

        def step_fn(carry, inp):
            k, first = inp
            agent_state, buf, env_state, obs = carry
            k_act, k_env, k_learn = jax.random.split(k, 3)
            action = sac.choose_action(agent_cfg, agent_state, obs, k_act)
            env_state, obs2, reward, done = enet.step(env_cfg, env_state,
                                                      action, k_env,
                                                      keepnoise=first)
            tr = {"state": obs, "action": action, "reward": reward,
                  "new_state": obs2, "done": done, "hint": hint}
            buf = rp.replay_add(buf, tr,
                                priority=None if agent_cfg.prioritized
                                else jnp.asarray(1.0))
            agent_state, buf, metrics = sac.learn(agent_cfg, agent_state,
                                                  buf, k_learn,
                                                  collect_diag=collect_diag)
            ys = ((reward, metrics["diag"]) if collect_diag else reward)
            return (agent_state, buf, env_state, obs2), ys

        keys = jax.random.split(k_scan, steps)
        first = jnp.arange(steps) == 0
        (agent_state, buf, env_state, _), ys = jax.lax.scan(
            step_fn, (agent_state, buf, env_state, obs), (keys, first))
        if collect_diag:
            rewards, diag = ys
            return agent_state, buf, jnp.mean(rewards), diag
        return agent_state, buf, jnp.mean(ys)

    return run_episode


def make_episode_fn(env_cfg: enet.EnetConfig, agent_cfg: sac.SACConfig,
                    steps: int, use_hint: bool, collect_diag: bool = False):
    """Build the jitted one-episode function (reset + scan over steps)."""
    return jax.jit(_make_episode_body(env_cfg, agent_cfg, steps, use_hint,
                                      collect_diag))


def make_episode_block_fn(env_cfg: enet.EnetConfig, agent_cfg: sac.SACConfig,
                          steps: int, use_hint: bool, block: int):
    """Scan ``block`` strictly-sequential episodes inside ONE jitted program.

    Identical learning dynamics to ``block`` successive calls of
    ``make_episode_fn`` with the driver's key chain (``key, k = split(key)``
    per episode — reproduced inside the scan carry), but a single device
    dispatch per block.  On the chip the per-episode dispatch over the
    tunnel dominates this small program (round-3 capture: 33 env-steps/s
    with 1 dispatch/episode); the block scan amortizes the round trip
    without changing the 1:1 env-step:learn protocol.  NOT a batched-env
    mode — agent and replay state chain episode to episode.

    Returns ``(agent_state, buf, key, scores[block])`` with the advanced
    key, so a driver can continue the exact same chain across blocks.
    """
    return make_block_fn(
        _make_episode_body(env_cfg, agent_cfg, steps, use_hint), block)


def train_fused(seed=0, episodes=1000, steps=5, use_hint=False,
                M=20, N=20, log_every=1, save_every=500, prefix="",
                quiet=False, metrics_path=None, block=1, run_id=None,
                trace=None, diag=False, watchdog=False, ckpt_dir=None,
                ckpt_every=0, keep_ckpts=3, resume=False, max_recoveries=0,
                recovery_lr_shrink=0.5, recovery_reseed=True):
    import dataclasses

    from smartcal_tpu.runtime import pack_replay, unpack_replay

    from .blocks import TrainRuntime, train_obs

    env_cfg = enet.EnetConfig(M=M, N=N)
    agent_cfg = sac.SACConfig(
        obs_dim=env_cfg.obs_dim, n_actions=2, gamma=0.99, tau=0.005,
        batch_size=64, mem_size=1024, lr_a=1e-3, lr_c=1e-3,
        reward_scale=float(N), alpha=0.03, use_hint=use_hint)

    key = jax.random.PRNGKey(seed)
    key, k0 = jax.random.split(key)
    agent_state = sac.sac_init(k0, agent_cfg)
    buf = rp.replay_init(agent_cfg.mem_size,
                         rp.transition_spec(env_cfg.obs_dim, 2))
    block = max(1, min(int(block), episodes))

    scores = []
    t0 = time.time()
    tob = train_obs("enet_sac", metrics=metrics_path, run_id=run_id,
                    trace=trace, quiet=quiet, diag=diag,
                    watchdog=watchdog or max_recoveries > 0,
                    seed=seed, block=block)
    rt = TrainRuntime("enet_sac", ckpt_dir=ckpt_dir, ckpt_every=ckpt_every,
                      keep=keep_ckpts, resume=resume,
                      max_recoveries=max_recoveries,
                      lr_shrink=recovery_lr_shrink, reseed=recovery_reseed,
                      tob=tob)
    collect = tob.collect_diag
    if collect and block > 1:
        # diagnostics stream at per-episode cadence: the watchdog must
        # see updates before committing to a whole block's compute
        tob.echo("diag/watchdog: forcing block=1")
        block = 1

    def build_fns(lr_scale=1.0):
        # recovery's LR mitigation rebuilds the jitted programs at the
        # scaled config (optimizer state structure is unchanged — the
        # learning rate lives in the update closure, not the moments)
        cfg = (agent_cfg if lr_scale == 1.0 else dataclasses.replace(
            agent_cfg, lr_a=agent_cfg.lr_a * lr_scale,
            lr_c=agent_cfg.lr_c * lr_scale))
        bf = (make_episode_block_fn(env_cfg, cfg, steps, use_hint, block)
              if block > 1 else None)
        ef = (make_episode_fn(env_cfg, cfg, steps, use_hint,
                              collect_diag=collect)
              if block == 1 or episodes % block else None)
        return bf, ef

    block_fn, episode_fn = build_fns()

    def _log_one(i, score):
        scores.append(float(score))
        # episode echo honors log_every (the block path logs in bursts)
        tob.episode(i, scores[-1], scores, echo=(i % log_every == 0),
                    seed=seed, use_hint=use_hint)

    i, saved_marker = 0, 0
    restored = rt.restore()
    if restored is not None:
        agent_state = jax.tree_util.tree_map(jnp.asarray,
                                             restored["agent_state"])
        buf = unpack_replay(restored["replay"])
        key = jnp.asarray(restored["key"])
        scores = list(restored["scores"])
        i = int(restored["episode"])
        saved_marker = int(restored.get("saved_marker", 0))

    def ckpt_payload():
        return {"kind": "enet_fused", "entry": "enet_sac", "seed": seed,
                "episode": i, "scores": list(scores),
                "agent_state": jax.device_get(agent_state),
                "replay": pack_replay(buf), "key": jax.device_get(key),
                "saved_marker": saved_marker}

    def _rollback(act):
        nonlocal agent_state, buf, key, scores, i, saved_marker
        nonlocal block_fn, episode_fn

        def rebuild(scale):
            nonlocal block_fn, episode_fn
            block_fn, episode_fn = build_fns(scale)

        from .blocks import rollback_fused
        agent_state, buf, key, scores, i = rollback_fused(act, rebuild)
        saved_marker = int(act.payload.get("saved_marker", 0))

    try:
        while i < episodes:
            if block_fn is not None and episodes - i >= block:
                # same key chain as the per-episode path: the split happens
                # inside the scan carry, one split per episode
                with tob.span("episode_block", episodes=block):
                    agent_state, buf, key, blk = block_fn(agent_state, buf,
                                                          key)
                for s in blk:
                    _log_one(i, s)
                    i += 1
            else:
                key, k = jax.random.split(key)
                with tob.span("episode", episode=i):
                    out = episode_fn(agent_state, buf, k)
                if collect:
                    agent_state, buf, score, ep_diag = out
                    tob.record_cost("episode_update", episode_fn,
                                    agent_state, buf, k)
                    halted = tob.record_diag(ep_diag, episode=i)
                    tob.log_replay_health(buf, episode=i)
                    if halted or tob.tripped:
                        act = rt.on_trip()
                        if act is None:
                            _log_one(i, score)
                            i += 1
                            break
                        # rollback-and-retry: the poisoned episodes since
                        # the checkpoint are discarded (not logged)
                        _rollback(act)
                        continue
                else:
                    agent_state, buf, score = out
                _log_one(i, score)
                i += 1
            rt.maybe_checkpoint(i, ckpt_payload)
            # classic side-files cadence: save whenever a save_every
            # multiple was crossed since the last save (block mode
            # crosses in strides)
            if save_every and i < episodes and i // save_every > saved_marker:
                _save(agent_state, buf, scores, prefix)
                saved_marker = i // save_every
        wall = time.time() - t0
    finally:
        tob.close()
    _save(agent_state, buf, scores, prefix)
    return scores, wall, agent_state, buf


def _save(agent_state, buf, scores, prefix):
    from smartcal_tpu.runtime import atomic_pickle

    atomic_pickle(jax.device_get(agent_state), f"{prefix}sac_state.pkl")
    rp.save_replay(buf, f"{prefix}replaymem_sac.pkl")
    atomic_pickle(scores, f"{prefix}scores.pkl")


def train_loop(seed=0, episodes=1000, steps=5, use_hint=False, M=20, N=20):
    """Reference-style host loop (main_sac.py:47-76)."""
    import numpy as np

    from smartcal_tpu import obs as smartcal_obs

    env = enet.EnetEnv(M, N, provide_hint=use_hint, seed=seed)
    agent = sac.SACAgent(sac.SACConfig(
        obs_dim=env.cfg.obs_dim, n_actions=2, tau=0.005, batch_size=64,
        mem_size=1024, reward_scale=float(N), alpha=0.03, use_hint=use_hint),
        seed=seed)
    scores = []
    for i in range(episodes):
        obs = env.reset()
        score, loop = 0.0, 0
        done = False
        while not done and loop < steps:
            action = agent.choose_action(obs)
            if use_hint:
                obs2, reward, done, hint, _ = env.step(action)
            else:
                obs2, reward, done, _ = env.step(action)
                hint = np.zeros_like(action)
            agent.store_transition(obs, action, reward, obs2, done, hint)
            score += reward
            agent.learn()
            obs = obs2
            loop += 1
        scores.append(score / loop)
        avg = sum(scores[-100:]) / len(scores[-100:])
        smartcal_obs.echo(f"episode {i} score {scores[-1]:.2f} "
                          f"average score {avg:.2f}", event=None)
    return scores


def main():
    from smartcal_tpu import obs as smartcal_obs

    from .blocks import add_obs_args, add_runtime_args

    p = argparse.ArgumentParser(
        description="Elastic net regression hyperparameter tuning (SAC, TPU)")
    p.add_argument("--seed", default=0, type=int)
    p.add_argument("--episodes", default=1000, type=int)
    p.add_argument("--steps", default=5, type=int)
    p.add_argument("--use_hint", action="store_true", default=False)
    p.add_argument("--mode", default="fused", choices=["fused", "loop"])
    p.add_argument("--block", default=1, type=int,
                   help="episodes per device dispatch (lax.scan of whole "
                        "episodes; 1 = reference per-episode cadence)")
    add_obs_args(p)
    add_runtime_args(p)
    args = p.parse_args()

    if args.mode == "fused":
        scores, wall, _, _ = train_fused(
            seed=args.seed, episodes=args.episodes, steps=args.steps,
            use_hint=args.use_hint, metrics_path=args.metrics,
            block=args.block, run_id=args.run_id, trace=args.trace,
            quiet=args.quiet, diag=args.diag, watchdog=args.watchdog,
            ckpt_dir=args.ckpt_dir, ckpt_every=args.ckpt_every,
            keep_ckpts=args.keep_ckpts, resume=args.resume,
            max_recoveries=args.max_recoveries,
            recovery_lr_shrink=args.recovery_lr_shrink,
            recovery_reseed=args.recovery_reseed)
        smartcal_obs.emit_json({"episodes": args.episodes,
                                "steps_per_episode": args.steps,
                                "wall_s": round(wall, 2),
                                "env_steps_per_sec": round(
                                    args.episodes * args.steps / wall, 2),
                                "final_avg_score": sum(scores[-100:])
                                / len(scores[-100:])})
    else:
        train_loop(seed=args.seed, episodes=args.episodes, steps=args.steps,
                   use_hint=args.use_hint)


if __name__ == "__main__":
    main()
