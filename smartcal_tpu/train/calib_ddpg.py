"""Calibration (ADMM-rho tuning) DDPG training driver.

Mirrors ``calibration/main_ddpg.py``: CNN+metadata DDPG agent
(Ornstein-Uhlenbeck exploration noise, single critic, target actor+critic)
on CalibEnv episodes; per-episode checkpointing.

Usage:
    python -m smartcal_tpu.train.calib_ddpg --episodes 30 [--small]
"""

from __future__ import annotations

import argparse

from ..envs import CalibEnv
from ..rl import ddpg
from .calib_td3 import add_common_args, build_backend, run


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    add_common_args(p)
    p.add_argument("--prefix", type=str, default="calib_ddpg")
    args = p.parse_args(argv)

    backend = build_backend(args)
    env = CalibEnv(M=args.M, provide_hint=args.use_hint, backend=backend,
                   seed=args.seed)
    npix = backend.npix
    cfg = ddpg.DDPGConfig(
        obs_dim=npix * npix + (args.M + 1) * 7, n_actions=2 * args.M,
        gamma=0.99, tau=0.005, batch_size=32, mem_size=1000, lr_a=1e-3,
        lr_c=1e-3, img_shape=(npix, npix))
    from .blocks import diag_from_args, train_obs_from_args
    agent = ddpg.DDPGAgent(cfg, seed=args.seed, name_prefix=args.prefix,
                           collect_diag=diag_from_args(args))
    if args.load:
        agent.load_models()
    return run(env, agent, args.episodes, args.steps, args.use_hint,
               args.prefix, obs_run=train_obs_from_args(args, "calib_ddpg"),
               args=args)


if __name__ == "__main__":
    main()
