"""Demixing recommendation CLI for real observations.

Reference: ``demixing/evaluate.py:51-61`` — given an MS glob pattern and a
time duration, featurize the observation (``get_info_from_dataset``) and run
the trained transformer classifier to print per-direction demixing
recommendations.

The MSs may be real casacore MSs (when python-casacore is installed) or the
in-framework npz stores written by :func:`cal.ms_io.observation_to_ms_set`
— the featurization path is identical (VERDICT r1 item 2: the synthetic
stand-in goes through the same code path as real data).

Usage:
  python -m smartcal_tpu.train.evaluate 'L_SB*.MS' 600 --model net.pkl
  python -m smartcal_tpu.train.evaluate --selftest   # synthesize + run

Checkpoint format: pickle {"params": pytree, "K": int, "npix": int,
"model_dim": int} — written by :func:`save_model` (the counterpart of the
reference's net.model state-dict file, demixing/train_model.py:77-85).
"""

from __future__ import annotations

import argparse
import glob
import pickle

import jax.numpy as jnp
import numpy as np

from smartcal_tpu import obs
from smartcal_tpu.cal import dataset
from smartcal_tpu.models.transformer import TransformerEncoder


def save_model(path, params, K=6, npix=64, model_dim=66):
    with open(path, "wb") as fh:
        pickle.dump({"params": params, "K": K, "npix": npix,
                     "model_dim": model_dim}, fh)


def load_model(path):
    from smartcal_tpu.runtime.atomic import strict_pickle_load

    ck = strict_pickle_load(path)
    K = ck["K"]
    npix = ck["npix"]
    model = TransformerEncoder(
        num_layers=1, input_dim=K * (npix * npix + 8),
        model_dim=ck["model_dim"] * K, num_classes=K - 1, num_heads=K)
    return model, ck["params"], K, npix


def evaluate_model(x, model, params):
    """Transformer forward on one feature vector -> (K-1,) probabilities
    (demixing/evaluate.py:21-46)."""
    out = model.apply({"params": params}, jnp.asarray(x)[None], train=False)
    return np.asarray(out)[0]


def recommend(mslist, timesec, model_path, tdelta=10, sky_path=None,
              cluster_path=None, workdir=".", seed=0):
    """``seed`` picks the random time window (and interior sub-bands) of
    extract_dataset — vary it to sample independent slices of the same
    observation."""
    model, params, K, npix = load_model(model_path)
    x = dataset.get_info_from_dataset(
        mslist, timesec, Ninf=npix, K=K, tdelta=tdelta, sky_path=sky_path,
        cluster_path=cluster_path, workdir=workdir,
        rng=np.random.default_rng(seed))
    return evaluate_model(x, model, params)


def _selftest(args):
    """End-to-end demo without external data: simulate an observation,
    write it through the MS edge, train a tiny transformer on synthetic
    features, then run the real-data path on the MS files."""
    import tempfile

    import jax

    from smartcal_tpu.cal import ms_io
    from smartcal_tpu.envs.radio import RadioBackend
    from smartcal_tpu.train import supervised

    backend = RadioBackend(n_stations=args.stations, n_times=args.times,
                           tdelta=args.tdelta, npix=args.npix,
                           admm_iters=4, lbfgs_iters=4, init_iters=8)
    K = args.K
    with tempfile.TemporaryDirectory() as tmp:
        ep, _ = backend.new_demixing_episode(jax.random.PRNGKey(0), K)
        mslist = ms_io.observation_to_ms_set(tmp, ep.obs, np.asarray(ep.V))
        buf = supervised.make_transformer_dataset(
            n_iter=2, K=K, backend=backend, seed=0)
        params, _ = supervised.train_transformer(buf, K=K, epochs=20,
                                                 model_dim=12)
        save_model(f"{tmp}/net.pkl", params, K=K, npix=args.npix,
                   model_dim=12)
        probs = recommend(mslist, timesec=args.times * 0.8,
                          model_path=f"{tmp}/net.pkl", tdelta=args.tdelta,
                          workdir=tmp)
    obs.echo(f"selftest recommendation: {probs}",
             event="recommendation")
    return probs


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("pattern", nargs="?", help="MS glob pattern")
    p.add_argument("timesec", nargs="?", type=float,
                   help="time duration to sample (seconds)")
    p.add_argument("--model", default="net.pkl")
    p.add_argument("--seed", default=0, type=int,
                   help="random time-window / sub-band draw")
    p.add_argument("--tdelta", default=10, type=int)
    p.add_argument("--sky", default=None, help="sky model text file")
    p.add_argument("--cluster", default=None, help="cluster text file")
    p.add_argument("--selftest", action="store_true")
    p.add_argument("--stations", default=8, type=int)
    p.add_argument("--times", default=20, type=int)
    p.add_argument("--npix", default=16, type=int)
    p.add_argument("--K", default=6, type=int)
    args = p.parse_args(argv)

    if args.selftest:
        _selftest(args)
        return
    if not args.pattern or args.timesec is None:
        p.error("usage: evaluate.py 'MS*pattern' time(seconds) "
                "[--model net.pkl]  (or --selftest)")
    mslist = glob.glob(args.pattern)
    if not mslist:
        p.error(f"no MS matched {args.pattern!r}")
    probs = recommend(mslist, args.timesec, args.model, tdelta=args.tdelta,
                      sky_path=args.sky, cluster_path=args.cluster,
                      seed=args.seed)
    obs.echo("Demixing recommendation (probability per outlier direction):",
             event=None)
    for i, v in enumerate(probs):
        obs.echo(f"  direction {i}: {v:.4f}  ->  "
                 f"{'DEMIX' if v > 0.5 else 'skip'}",
                 event="recommendation", direction=i, prob=float(v))


if __name__ == "__main__":
    main()
