"""Plot / inspect utilities for training artifacts.

Parity targets (SURVEY §2.6 row "Plot/inspect utilities"):

* ``demixing_rl/plot_databuffer.py`` — per-direction metadata scatter from
  a TrainingBuffer (un-scaled by META_SCALE) + reward traces rescaled back
  to raw AIC units (``rewards*3559+859`` un-does the empirical
  normalization, :50-52 — note the reference adds +859 although the
  normalization subtracted -859; the faithful inverse is
  ``r*REWARD_STD + REWARD_MEAN`` with REWARD_MEAN = -859, used here);
* ``calibration/inspect_replaybuffer.py`` — grid PNG of influence-map
  states from a replay buffer (gray -> unit-range tiles);
* ``demixing_rl/plot_tsk.py`` — dump/plot of trained TSK parameters.

All functions write PNG via matplotlib (Agg) and return the arrays they
plotted so tests don't need to parse images.
"""

from __future__ import annotations

import numpy as np

from smartcal_tpu.envs.demixing import (META_SCALE, REWARD_MEAN, REWARD_STD)


def _plt():
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    return plt


def plot_databuffer(buf, K, field="azimuth", out_png="databuffer.png"):
    """Per-direction metadata scatter (plot_databuffer.py:30-43).

    ``buf`` is a TrainingBuffer whose x rows are META_SCALE-scaled
    3K+2 metadata vectors; ``field`` selects the block."""
    offset = {"separation": 0, "azimuth": 1, "elevation": 2}[field]
    n = min(buf.mem_cntr, buf.mem_size)
    X = np.asarray(buf.x[:n]) / META_SCALE
    cols = X[:, offset * K:(offset + 1) * K]
    plt = _plt()
    fig, axs = plt.subplots(K, sharex=True)
    for d in range(K):
        axs[d].plot(cols[:, d], ".")
        axs[d].set_ylabel(f"dir {d}")
    axs[-1].set_xlabel("Simulation number")
    fig.suptitle(f"{field}/deg")
    fig.savefig(out_png, dpi=100)
    plt.close(fig)
    return cols


def plot_rewards(rewards, out_png="rewards.png", labels=None,
                 rescale=True):
    """Reward traces, un-normalized back to raw AIC units
    (plot_databuffer.py:46-56)."""
    rewards = np.atleast_2d(np.asarray(rewards, np.float64))
    if rescale:
        rewards = rewards * REWARD_STD + REWARD_MEAN
    plt = _plt()
    fig = plt.figure()
    for row in rewards:
        plt.plot(row)
    if labels:
        plt.legend(labels)
    plt.xlabel("Trial")
    plt.ylabel("Reward")
    fig.savefig(out_png, dpi=100)
    plt.close(fig)
    return rewards


def gray_to_unit(x):
    """Per-tile normalization into [0.1, 0.9].

    The reference (inspect_replaybuffer.py:5-16) scales by the range but
    never subtracts the minimum, so non-zero-mean tiles land outside
    [0, 1] and wreck the shared grid autoscale; the corrected affine map
    is used here."""
    x = np.asarray(x, np.float32)
    if x.ndim == 2:
        x = x[None]
    out = np.zeros_like(x)
    for i, z in enumerate(x):
        rng = float(z.max() - z.min())
        out[i] = 0.8 * (z - z.min()) / (rng if rng > 0 else 1.0) + 0.1
    return out


def inspect_replaybuffer(buf, img_shape, out_png="replay_states.png",
                         stride=10, max_tiles=54):
    """Tile the image block of replay states into one PNG grid
    (inspect_replaybuffer.py:19-27).  ``buf`` is an rl.replay.ReplayState
    whose 'state' rows start with a flattened (H, W) influence map."""
    h, w = img_shape
    n = int(min(np.asarray(buf.cntr), buf.size))
    states = np.asarray(buf.data["state"][:n:stride])[:max_tiles]
    tiles = gray_to_unit(states[:, :h * w].reshape(-1, h, w))
    cols = max(1, int(np.ceil(np.sqrt(tiles.shape[0]))))
    rows = int(np.ceil(tiles.shape[0] / cols))
    grid = np.zeros((rows * h, cols * w), np.float32)
    for i, t in enumerate(tiles):
        r, c = divmod(i, cols)
        grid[r * h:(r + 1) * h, c * w:(c + 1) * w] = t
    plt = _plt()
    fig = plt.figure(figsize=(cols, rows))
    plt.imshow(grid, cmap="gray")
    plt.axis("off")
    fig.savefig(out_png, dpi=100, bbox_inches="tight")
    plt.close(fig)
    return tiles


def plot_tsk(params, out_png="tsk_params.png"):
    """Trained TSK parameter dump: rule centers/sigmas heatmaps + consequent
    weights (plot_tsk.py role)."""
    plt = _plt()
    fig, axs = plt.subplots(1, 3, figsize=(12, 3))
    for ax, arr, title in (
            (axs[0], np.asarray(params.center), "antecedent centers (M,R)"),
            (axs[1], np.asarray(params.sigma), "antecedent sigmas (M,R)"),
            (axs[2], np.asarray(params.A).reshape(
                np.asarray(params.A).shape[0], -1),
             "order-1 consequents (R, M*out)")):
        im = ax.imshow(arr, aspect="auto")
        ax.set_title(title)
        fig.colorbar(im, ax=ax)
    fig.tight_layout()
    fig.savefig(out_png, dpi=100)
    plt.close(fig)
    return {"center": np.asarray(params.center),
            "sigma": np.asarray(params.sigma)}
