"""Elastic-net TD3 training driver (reference ``elasticnet/main_td3.py``:
prioritized replay + hint-constrained adaptive-ADMM actor updates,
1000 episodes x 4 steps, warmup 100)."""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp

from ..envs import enet
from ..rl import replay as rp
from ..rl import td3
from .blocks import make_block_fn


def _make_episode_body(env_cfg: enet.EnetConfig, cfg: td3.TD3Config,
                       steps: int, use_hint: bool,
                       collect_diag: bool = False):
    def run_episode(agent_state, buf, key):
        k_reset, k_noise, k_scan = jax.random.split(key, 3)
        env_state, obs = enet.reset(env_cfg, k_reset)
        # hint from the first step's noisy draw (see enet_sac.make_episode_fn)
        env_state = enet.draw_noise(env_cfg, env_state, k_noise)
        hint = (enet.get_hint(env_cfg, env_state) if use_hint
                else jnp.zeros((cfg.n_actions,), jnp.float32))

        def step_fn(carry, inp):
            k, first = inp
            agent_state, buf, env_state, obs = carry
            k_act, k_env, k_learn = jax.random.split(k, 3)
            action, agent_state = td3.choose_action(cfg, agent_state, obs,
                                                    k_act)
            env_state, obs2, reward, done = enet.step(env_cfg, env_state,
                                                      action, k_env,
                                                      keepnoise=first)
            tr = {"state": obs, "action": action, "reward": reward,
                  "new_state": obs2, "done": done, "hint": hint}
            pri = td3.store_priority(cfg, reward)
            buf = rp.replay_add(buf, tr,
                                priority=jnp.asarray(1.0) if pri is None
                                else pri)
            agent_state, buf, m = td3.learn(cfg, agent_state, buf, k_learn,
                                            collect_diag=collect_diag)
            ys = (reward, m["diag"]) if collect_diag else reward
            return (agent_state, buf, env_state, obs2), ys

        keys = jax.random.split(k_scan, steps)
        first = jnp.arange(steps) == 0
        (agent_state, buf, _, _), ys = jax.lax.scan(
            step_fn, (agent_state, buf, env_state, obs), (keys, first))
        if collect_diag:
            rewards, diag = ys
            return agent_state, buf, jnp.mean(rewards), diag
        return agent_state, buf, jnp.mean(ys)

    return run_episode


def make_episode_fn(env_cfg: enet.EnetConfig, cfg: td3.TD3Config,
                    steps: int, use_hint: bool, collect_diag: bool = False):
    return jax.jit(_make_episode_body(env_cfg, cfg, steps, use_hint,
                                      collect_diag))


def make_episode_block_fn(env_cfg: enet.EnetConfig, cfg: td3.TD3Config,
                          steps: int, use_hint: bool, block: int):
    """``block`` sequential episodes per dispatch (see train.blocks)."""
    return make_block_fn(_make_episode_body(env_cfg, cfg, steps, use_hint),
                         block)


def train_fused(seed=0, episodes=1000, steps=4, use_hint=True,
                prioritized=True, M=20, N=20, quiet=False, save_every=500,
                prefix="", metrics_path=None, run_id=None, trace=None,
                diag=False, watchdog=False, ckpt_dir=None, ckpt_every=0,
                keep_ckpts=3, resume=False, max_recoveries=0,
                recovery_lr_shrink=0.5, recovery_reseed=True):
    import dataclasses

    from smartcal_tpu.runtime import pack_replay, unpack_replay

    from .blocks import TrainRuntime, train_obs

    env_cfg = enet.EnetConfig(M=M, N=N)
    cfg = td3.TD3Config(
        obs_dim=env_cfg.obs_dim, n_actions=2, gamma=0.99, tau=0.005,
        batch_size=64, mem_size=1024, lr_a=1e-3, lr_c=1e-3,
        update_actor_interval=2, warmup=100, noise=0.1,
        prioritized=prioritized, use_hint=use_hint, admm_rho=1.0)

    key = jax.random.PRNGKey(seed)
    key, k0 = jax.random.split(key)
    agent_state = td3.td3_init(k0, cfg)
    buf = rp.replay_init(cfg.mem_size, rp.transition_spec(env_cfg.obs_dim, 2))

    scores = []
    t0 = time.time()
    tob = train_obs("enet_td3", metrics=metrics_path, run_id=run_id,
                    trace=trace, quiet=quiet, diag=diag,
                    watchdog=watchdog or max_recoveries > 0, seed=seed)
    rt = TrainRuntime("enet_td3", ckpt_dir=ckpt_dir, ckpt_every=ckpt_every,
                      keep=keep_ckpts, resume=resume,
                      max_recoveries=max_recoveries,
                      lr_shrink=recovery_lr_shrink, reseed=recovery_reseed,
                      tob=tob)
    collect = tob.collect_diag

    def build_fn(lr_scale=1.0):
        c = (cfg if lr_scale == 1.0 else dataclasses.replace(
            cfg, lr_a=cfg.lr_a * lr_scale, lr_c=cfg.lr_c * lr_scale))
        return make_episode_fn(env_cfg, c, steps, use_hint,
                               collect_diag=collect)

    episode_fn = build_fn()

    i = 0
    restored = rt.restore()
    if restored is not None:
        agent_state = jax.tree_util.tree_map(jnp.asarray,
                                             restored["agent_state"])
        buf = unpack_replay(restored["replay"])
        key = jnp.asarray(restored["key"])
        scores = list(restored["scores"])
        i = int(restored["episode"])

    def ckpt_payload():
        return {"kind": "enet_fused", "entry": "enet_td3", "seed": seed,
                "episode": i, "scores": list(scores),
                "agent_state": jax.device_get(agent_state),
                "replay": pack_replay(buf), "key": jax.device_get(key)}

    try:
        while i < episodes:
            key, k = jax.random.split(key)
            with tob.span("episode", episode=i):
                out = episode_fn(agent_state, buf, k)
            if collect:
                agent_state, buf, score, ep_diag = out
                tob.record_cost("episode_update", episode_fn,
                                agent_state, buf, k)
                halted = tob.record_diag(ep_diag, episode=i)
                tob.log_replay_health(buf, episode=i)
            else:
                agent_state, buf, score = out
                halted = False
            if halted or tob.tripped:
                act = rt.on_trip()
                if act is None:
                    scores.append(float(score))
                    tob.episode(i, scores[-1], scores, seed=seed,
                                use_hint=use_hint)
                    break
                # rollback-and-retry (shared restore+mitigation helper)
                from .blocks import rollback_fused

                def rebuild(scale):
                    nonlocal episode_fn
                    episode_fn = build_fn(scale)

                agent_state, buf, key, scores, i = rollback_fused(act,
                                                                  rebuild)
                continue
            scores.append(float(score))
            tob.episode(i, scores[-1], scores, seed=seed, use_hint=use_hint)
            i += 1
            rt.maybe_checkpoint(i, ckpt_payload)
            if save_every and i < episodes and i % save_every == 0:
                _save(agent_state, buf, scores, prefix)
        wall = time.time() - t0
    finally:
        tob.close()
    _save(agent_state, buf, scores, prefix)
    return scores, wall, agent_state, buf


def _save(agent_state, buf, scores, prefix):
    from smartcal_tpu.runtime import atomic_pickle

    atomic_pickle(jax.device_get(agent_state), f"{prefix}td3_state.pkl")
    rp.save_replay(buf, f"{prefix}replaymem_td3.pkl")
    atomic_pickle(scores, f"{prefix}scores_td3.pkl")


def main():
    from smartcal_tpu import obs as smartcal_obs

    from .blocks import add_obs_args, add_runtime_args

    p = argparse.ArgumentParser(
        description="Elastic net TD3 + PER + hint-ADMM (TPU)")
    p.add_argument("--seed", default=0, type=int)
    p.add_argument("--episodes", default=1000, type=int)
    p.add_argument("--steps", default=4, type=int)
    p.add_argument("--no_hint", action="store_true", default=False)
    p.add_argument("--no_per", action="store_true", default=False)
    add_obs_args(p)
    add_runtime_args(p)
    args = p.parse_args()
    scores, wall, _, _ = train_fused(
        seed=args.seed, episodes=args.episodes, steps=args.steps,
        use_hint=not args.no_hint, prioritized=not args.no_per,
        metrics_path=args.metrics, run_id=args.run_id, trace=args.trace,
        quiet=args.quiet, diag=args.diag, watchdog=args.watchdog,
        ckpt_dir=args.ckpt_dir, ckpt_every=args.ckpt_every,
        keep_ckpts=args.keep_ckpts, resume=args.resume,
        max_recoveries=args.max_recoveries,
        recovery_lr_shrink=args.recovery_lr_shrink,
        recovery_reseed=args.recovery_reseed)
    smartcal_obs.emit_json(
        {"episodes": args.episodes, "wall_s": round(wall, 2),
         "env_steps_per_sec": round(args.episodes * args.steps / wall, 2),
         "final_avg_score": sum(scores[-100:]) / len(scores[-100:])})


if __name__ == "__main__":
    main()
