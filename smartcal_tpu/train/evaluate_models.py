"""Lockstep agent comparison on the demixing env.

Parity target: ``demixing_rl/evaluate_models.py:32-86`` — three SAC agents
(trained without hint, trained with hint, untrained) step the SAME env
episodes; per episode the best-reward action of each is reported, plus the
reward of the exhaustive-AIC hint itself.

Usage:
    python -m smartcal_tpu.train.evaluate_models --games 10
        [--nohint PREFIX] [--withhint PREFIX] [--small]
"""

from __future__ import annotations

import argparse

import numpy as np

from .. import obs
from ..envs import DemixingEnv
from ..envs.radio import RadioBackend
from ..rl import sac
from ..rl.networks import flatten_obs


def evaluate(env: DemixingEnv, agents: dict, n_steps: int, n_games: int,
             quiet=False):
    """Returns {name: [best reward per episode]} plus 'hint' rewards."""
    results = {name: [] for name in agents}
    results["hint"] = []
    for cn in range(n_games):
        obs0 = env.reset()
        flats = {name: flatten_obs(obs0) for name in agents}
        best = {name: -np.inf for name in agents}
        hint = None
        for ci in range(n_steps):
            for name, agent in agents.items():
                action = np.asarray(
                    agent.choose_action(flats[name])).squeeze()
                out = env.step(action)
                obs_, reward, done, hint, info = out
                flats[name] = flatten_obs(obs_)
                best[name] = max(best[name], reward)
                obs.echo(f"Iter {cn}:{ci} {name} reward {reward:.3f}",
                         quiet=quiet, event="eval_step", game=cn,
                         step=ci, agent=name, reward=float(reward))
        for name in agents:
            results[name].append(best[name])
        _, reward_hint, *_ = env.step(hint)
        results["hint"].append(reward_hint)
        obs.echo(f"Episode {cn}: rewards "
                 + " ".join(f"{n}={results[n][-1]:.3f}" for n in results),
                 quiet=quiet, event="eval_episode", game=cn)
    return results


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--games", type=int, default=10)
    p.add_argument("--K", type=int, default=6)
    p.add_argument("--nohint", type=str, default="")
    p.add_argument("--withhint", type=str, default="")
    p.add_argument("--small", action="store_true")
    args = p.parse_args(argv)

    if args.small:
        backend = RadioBackend(n_stations=6, n_freqs=2, n_times=4, tdelta=2,
                               admm_iters=30, lbfgs_iters=3, init_iters=5,
                               npix=32)
    else:
        backend = RadioBackend(admm_iters=30)
    env = DemixingEnv(K=args.K, provide_hint=True, backend=backend)
    npix = backend.npix
    obs_dim = npix * npix + 3 * args.K + 2

    def make_agent(prefix, use_hint):
        cfg = sac.SACConfig(obs_dim=obs_dim, n_actions=args.K,
                            batch_size=256, mem_size=4096, alpha=0.03,
                            use_hint=use_hint, img_shape=(npix, npix))
        a = sac.SACAgent(cfg, name_prefix=prefix)
        if prefix and not a.load_models():
            # an evaluation of a fresh random agent under a trained name
            # would be silently misleading — fail loudly instead
            raise FileNotFoundError(
                f"no loadable checkpoint for prefix {prefix!r}")
        return a

    agents = {"nohint": make_agent(args.nohint, False),
              "withhint": make_agent(args.withhint, True),
              "untrained": make_agent("", False)}
    results = evaluate(env, agents, n_steps=args.K, n_games=args.games)
    for name, vals in results.items():
        obs.echo(f"{name}: mean best reward {np.mean(vals):.4f}",
                 event="eval_summary", agent=name,
                 mean_best_reward=float(np.mean(vals)))
    return results


if __name__ == "__main__":
    main()
