"""Influence functions OF the trained aux models.

The reference's flagship "influence" story ends with two pipelines that
apply the influence machinery to the *trained recommender models*
themselves (how sensitive is the model's output to each input coordinate,
through the trained weights):

* ``demixing/eval_model.py:51-118`` — transformer: run a few epochs of
  batch-mode L-BFGS on the trained net (only to accumulate curvature pairs
  approximating the loss Hessian), reload the trained weights, then
  ``influence_matrix`` of one sample; reshape each output class's row into
  per-direction (Ninf^2 + 8) blocks and save influence MAPS per
  (class, direction).
* ``demixing_rl/influence_tsk.py:64-72`` — TSK fuzzy regressor: average
  ``influence_matrix`` (Taylor inverse-HVP, no optimizer history) over 100
  inputs.

Both sit on :func:`smartcal_tpu.ops.autodiff.influence_matrix`; the M x N
python loop of the reference is already a jacfwd/vmap there.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree

from smartcal_tpu.models.transformer import TransformerEncoder, XYBuffer
from smartcal_tpu.models.tsk import TSKParams, tsk_forward
from smartcal_tpu.ops.autodiff import influence_matrix
from smartcal_tpu.ops.lbfgs import lbfgs_init, lbfgs_step


def _bce(pred, y):
    pred = jnp.clip(pred, 1e-6, 1 - 1e-6)
    return -jnp.mean(y * jnp.log(pred) + (1 - y) * jnp.log(1 - pred))


def transformer_influence(params, model: TransformerEncoder, buf: XYBuffer,
                          K: int, npix: int, warmup_epochs: int = 30,
                          batch_size: int = 4, seed: int = 0,
                          outdir: Optional[str] = None):
    """Per-(class, direction) influence maps of a trained transformer.

    Reference ``demixing/eval_model.py:52-118``: L-BFGS warmup in batch
    mode over the training buffer builds the curvature history whose
    two-loop recursion is the inverse-Hessian applied inside
    ``influence_matrix``; the TRAINED weights (not the warmup iterate) are
    what the influence is evaluated at.

    Returns ``(If, maps)``: If (K-1, K*(npix^2+8)); maps a dict
    ``(class ci, direction ck) -> (npix, npix) array`` plus
    ``('meta', ci, ck) -> (8,)`` metadata-influence vectors.
    """
    n = min(buf.mem_cntr, buf.mem_size)
    x_all = jnp.asarray(buf.x[:n])
    y_all = jnp.asarray(buf.y[:n])

    flat, unravel = ravel_pytree(params)

    # --- L-BFGS warmup: batch-mode steps on the BCE loss, collecting
    # curvature pairs (eval_model.py:52-70; LBFGSNew(history_size=7,
    # max_iter=4, batch_mode=True), 30 epochs x batch 4)
    rng = np.random.default_rng(seed)
    st = lbfgs_init(flat, history_size=7)
    for _ in range(warmup_epochs):
        idx = jnp.asarray(rng.integers(0, n, size=min(batch_size, n)))

        def loss_fn(p_flat):
            pred = model.apply({"params": unravel(p_flat)}, x_all[idx],
                               train=False)
            return _bce(pred, y_all[idx])

        st, _ = lbfgs_step(loss_fn, st, max_iter=4)

    # --- influence of ONE sample at the trained weights (:76-96)
    x0, y0 = x_all[0], y_all[0]

    def model_fn(p, xx):
        return model.apply({"params": p}, xx[None], train=False)[0]

    If = influence_matrix(model_fn, params, x0, y0, hist=st.hist)
    If = np.asarray(If)

    nout = npix * npix + 8
    maps = {}
    for ci in range(If.shape[0]):                     # output classes (K-1)
        Z = If[ci].reshape(K, nout)                   # per direction blocks
        for ck in range(K):
            maps[(ci, ck)] = Z[ck, :npix * npix].reshape(npix, npix)
            maps[("meta", ci, ck)] = Z[ck, npix * npix:]
    if outdir is not None:
        import os

        os.makedirs(outdir, exist_ok=True)
        np.savez(os.path.join(outdir, "transformer_influence.npz"),
                 If=If, **{f"map_{ci}_{ck}": maps[(ci, ck)]
                           for ci in range(If.shape[0]) for ck in range(K)})
        try:                                          # PNG maps, like the
            import matplotlib                         # reference If_*.png
            matplotlib.use("Agg")
            import matplotlib.pyplot as plt

            for (key, m) in maps.items():
                if key[0] == "meta":
                    continue
                ci, ck = key
                plt.imsave(os.path.join(outdir, f"If_{ci}_{ck}.png"), m)
        except Exception:
            pass
    return If, maps


def tsk_influence(params: TSKParams, X, y, n_avg: int = 100,
                  taylor_iters: int = 10):
    """Mean influence matrix of the trained TSK regressor over ``n_avg``
    inputs (influence_tsk.py:64-72; Taylor inverse-HVP, opt=None)."""
    X = np.asarray(X, np.float32)
    y = np.asarray(y, np.float32)
    n_avg = min(n_avg, X.shape[0])

    def model_fn(p, xx):
        return tsk_forward(p, xx[None])[0]

    If = None
    for ci in range(n_avg):
        one = influence_matrix(model_fn, params, jnp.asarray(X[ci]),
                               jnp.asarray(y[ci]), hist=None,
                               taylor_iters=taylor_iters)
        If = one if If is None else If + one
    return np.asarray(If) / n_avg
