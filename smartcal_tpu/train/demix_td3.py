"""Demixing (direction selection) TD3 training driver.

Mirrors ``demixing_rl/main_td3.py`` + ``demix_td3.py``: CNN+metadata TD3
with prioritized replay (always on in the reference, demix_td3.py:381) and
the full adaptive-rho ADMM hint loop in the actor update
(demix_td3.py:547-600 — the enet_td3.py:310-361 machinery on the demixing
env).  Reference hyperparameters (main_td3.py:18-20): gamma 0.99, batch 64,
tau 0.005, mem 4096, lr_a/lr_c 1e-3, actor interval 2, warmup 200 steps,
noise 0.1, admm_rho 0.1 (demix_td3.py:400).

One deliberate repair: the reference driver constructs the agent with
``n_actions=K-1`` (main_td3.py:18) while its own env consumes
``action[K-1]`` as the max-ADMM-iterations channel (demixingenv.py:104-113)
— an out-of-range read if ever stepped.  Here the agent emits the env's
full K-dimensional action like the SAC driver does, and the TD3 warmup is
the agent's own ``time_step < warmup`` phase (rl/td3.py:choose_action), so
the driver loop never injects driver-level random actions.

Usage:
    python -m smartcal_tpu.train.demix_td3 --iteration 30 --seed 0
        [--use_hint] [--provide_influence] [--small]
"""

from __future__ import annotations

import argparse

import numpy as np

from ..envs import DemixingEnv
from ..rl import td3
from ..rl.networks import flatten_obs
from .blocks import add_obs_args, add_runtime_args
from .demix_sac import make_backend, run_warmup_loop


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--iteration", type=int, default=30,
                   help="max episodes (reference n_games=30)")
    p.add_argument("--steps", type=int, default=7)
    p.add_argument("--K", type=int, default=6)
    p.add_argument("--warmup", type=int, default=200,
                   help="agent warmup steps (pure noise actions)")
    p.add_argument("--use_hint", action="store_true")
    p.add_argument("--provide_influence", action="store_true")
    p.add_argument("--stations", type=int, default=14)
    p.add_argument("--npix", type=int, default=128)
    p.add_argument("--small", action="store_true")
    p.add_argument("--light", action="store_true",
                   help="see make_backend: one solution interval, "
                        "minimum useful solver iterations")
    p.add_argument("--medium", action="store_true",
                   help="see demix_sac --medium")
    p.add_argument("--load", action="store_true")
    p.add_argument("--prefix", type=str, default="demix_td3")
    p.add_argument("--batch_size", type=int, default=64)
    p.add_argument("--memory", type=int, default=4096)
    add_obs_args(p)
    add_runtime_args(p)
    args = p.parse_args(argv)

    rng = np.random.default_rng(args.seed)
    backend = make_backend(args)
    env = DemixingEnv(K=args.K, provide_hint=args.use_hint,
                      provide_influence=args.provide_influence,
                      backend=backend, seed=args.seed)
    npix = backend.npix
    if args.provide_influence:
        obs_dim = npix * npix + 3 * args.K + 2
        img_shape = (npix, npix)
    else:
        obs_dim = 3 * args.K + 2
        img_shape = None
    agent_cfg = td3.TD3Config(
        obs_dim=obs_dim, n_actions=args.K, gamma=0.99, tau=0.005,
        batch_size=args.batch_size, mem_size=args.memory,
        lr_a=1e-3, lr_c=1e-3,
        update_actor_interval=2, warmup=args.warmup, noise=0.1,
        use_hint=args.use_hint, admm_rho=0.1, prioritized=True,
        error_clip=100.0, img_shape=img_shape)
    from .blocks import diag_from_args
    agent = td3.TD3Agent(agent_cfg, seed=args.seed, name_prefix=args.prefix,
                         collect_diag=diag_from_args(args))
    scores = []
    if args.load:
        # corruption-tolerant resume (see demix_sac.main)
        from smartcal_tpu.runtime import safe_pickle_load
        agent.load_models()
        scores = safe_pickle_load(f"{args.prefix}_scores.pkl", default=[])

    def to_flat(o):
        return (flatten_obs(o) if args.provide_influence
                else np.asarray(o["metadata"], np.float32))

    # the agent's own warmup phase supplies the exploration noise
    # (td3.choose_action) — no driver-level random-action window
    args.warmup = 0
    return run_warmup_loop(
        env, agent, args, scores, to_flat, n_actions=args.K,
        scale_reward=lambda r: r, rng=rng)


if __name__ == "__main__":
    main()
