"""AOT export cache: serialized jax.export programs keyed on trace
signature.

Cold start of the calibration service pays twice: tracing (python) and
XLA compilation (minutes at scale — ``first_episode_incl_compile_s:
255.6`` in the r6 results).  This module removes both for a RESTARTED
server:

* the traced+lowered program is exported once per trace signature
  (``jax.export``), serialized, and persisted under the cache dir —
  a restart deserializes the StableHLO instead of re-tracing;
* :func:`enable_compile_cache` arms JAX's persistent compilation cache
  in the same directory tree, so the XLA compile of the deserialized
  module is a disk hit too (including the ``jit_call_exported``
  executable) — a warm restart compiles NOTHING.

The signature (see ``RadioBackend.serve_signature``) carries every
static program selector: geometry (N, T, Nf), K/lanes, npix, precision,
blocking knobs.  Per-request values (rho, masks, maxiter) are TRACED
operands since PR 9, so one cached program serves every request mix.

Obs counters: ``export_cache_hit`` / ``export_cache_miss`` /
``export_cache_store`` (plus ``persistent_cache_hits/misses`` from the
registry listener) — the smoke asserts a warm restart is all hits.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Callable, Optional, Sequence

import jax
from jax import export as jax_export

from smartcal_tpu import obs
from smartcal_tpu.cal import solver as _solver
from smartcal_tpu.runtime import atomic

# jax.export refuses unregistered pytree node types in program
# signatures; the solve program returns a SolveResult (stats=None on the
# batched route, but register both).  Idempotent across re-imports.
for _nt in (_solver.SolveResult, _solver.SolverStats):
    try:
        jax_export.register_namedtuple_serialization(
            _nt, serialized_name=f"smartcal_tpu.cal.solver.{_nt.__name__}")
    except ValueError:
        pass


_lapack_primed = False


def prime_backend_kernels() -> None:
    """Run one tiny ``eigh`` before any deserialized program executes.

    jaxlib registers its CPU LAPACK custom-call kernels lazily, as a
    side effect of the first NORMAL lowering of a linalg primitive in
    the process.  A deserialized exported module never goes through
    that lowering — its StableHLO already names the custom-call
    targets — so in a fresh process (exactly the warm-restart case this
    cache exists for) the call segfaults inside XLA on the unresolved
    target.  One 2x2 ``eigh`` registers the whole LAPACK family
    (eigh/svd/qr/solve all resolve afterwards); idempotent and ~ms."""
    global _lapack_primed
    if _lapack_primed:
        return
    import jax.numpy as jnp
    jax.block_until_ready(jnp.linalg.eigh(jnp.eye(2, dtype=jnp.float32)))
    _lapack_primed = True


def sig_digest(sig: dict) -> str:
    """Stable short digest of a signature dict (sorted-key JSON)."""
    blob = json.dumps(sig, sort_keys=True, default=str).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def abstract_like(tree: Any):
    """Pytree of ``ShapeDtypeStruct`` mirroring ``tree``'s arrays — the
    export-time stand-ins for the runtime operands."""
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(jax.numpy.shape(x),
                                       jax.numpy.result_type(x)), tree)


class ServeProgram:
    """A deserialized/freshly-exported program: call it like the jitted
    original.  ``source`` records where it came from ("export" = traced
    this process, "cache" = deserialized from disk)."""

    def __init__(self, exported, sig: dict, source: str):
        self.exported = exported
        self.sig = dict(sig)
        self.source = source

    def __call__(self, *args):
        return self.exported.call(*args)


class ExportCache:
    """Persist/load serialized ``jax.export`` programs keyed on a
    signature dict.  Layout: ``<dir>/<kind>-<digest>.jaxexp`` (the
    serialized bytes) + ``.json`` sidecar (the human-readable signature,
    for cache forensics).  Writes are atomic (tmp + rename), so a killed
    server never leaves a torn blob for the next boot to trip on."""

    def __init__(self, cache_dir: str):
        self.dir = cache_dir
        os.makedirs(cache_dir, exist_ok=True)

    def _base(self, sig: dict) -> str:
        kind = sig.get("kind", "program")
        return os.path.join(self.dir, f"{kind}-{sig_digest(sig)}")

    def load(self, sig: dict) -> Optional[ServeProgram]:
        """Deserialize the persisted program for ``sig``, or None (and
        count a miss).  A corrupt blob counts as a miss — the caller
        rebuilds and overwrites it."""
        path = self._base(sig) + ".jaxexp"
        try:
            with open(path, "rb") as fh:
                blob = fh.read()
            exported = jax_export.deserialize(blob)
        except FileNotFoundError:
            obs.counter_add("export_cache_miss")
            self._log("miss", sig, path)
            return None
        except Exception as e:     # torn/incompatible blob: rebuild
            obs.counter_add("export_cache_miss")
            self._log("corrupt", sig, path, error=repr(e))
            return None
        prime_backend_kernels()
        obs.counter_add("export_cache_hit")
        self._log("hit", sig, path, bytes=len(blob))
        return ServeProgram(exported, sig, source="cache")

    def store(self, sig: dict, exported) -> str:
        path = self._base(sig) + ".jaxexp"
        blob = exported.serialize()
        atomic.atomic_write_bytes(path, bytes(blob))
        atomic.atomic_write_text(
            self._base(sig) + ".json",
            json.dumps(sig, sort_keys=True, default=str, indent=1))
        obs.counter_add("export_cache_store")
        self._log("store", sig, path, bytes=len(blob))
        return path

    def build(self, sig: dict, fn: Callable,
              abstract_args: Sequence[Any]) -> ServeProgram:
        """Trace+lower ``fn`` at the abstract operands, persist, return."""
        with obs.span("serve_export", kind=sig.get("kind")):
            exported = jax_export.export(jax.jit(fn))(*abstract_args)
            self.store(sig, exported)
        return ServeProgram(exported, sig, source="export")

    def get_or_build(self, sig: dict, fn: Callable,
                     abstract_args: Sequence[Any]) -> ServeProgram:
        prog = self.load(sig)
        if prog is None:
            prog = self.build(sig, fn, abstract_args)
        return prog

    def publish(self, sig: dict, program: ServeProgram) -> ServeProgram:
        """Persist an ALREADY-EXPORTED program under a new signature and
        return it rebadged (``source="publish"``).

        The hot-swap publication path: the serving policy program takes
        ``actor_params`` as a traced operand, so a weight update needs
        no re-trace/re-lower — identical StableHLO, one executable for
        every version.  Publication is therefore a re-serialization
        keyed on the NEW ``(version, serve_signature)`` (provenance + a
        restartable per-version artifact) with zero compile events, not
        a rebuild.  Skips the write when the versioned entry already
        exists (idempotent republish)."""
        path = self._base(sig) + ".jaxexp"
        if not os.path.exists(path):
            self.store(sig, program.exported)
        return ServeProgram(program.exported, sig, source="publish")

    def prune(self, kind: str, keep: int) -> int:
        """Drop all but the ``keep`` most-recent entries of ``kind``
        (mtime order) — the per-version publication stream would
        otherwise grow the cache without bound.  Returns the number of
        entries removed; never raises on a concurrent unlink."""
        base = []
        for name in os.listdir(self.dir):
            if name.startswith(f"{kind}-") and name.endswith(".jaxexp"):
                p = os.path.join(self.dir, name)
                try:
                    base.append((os.path.getmtime(p), p))
                except OSError:
                    continue
        base.sort(reverse=True)
        removed = 0
        for _, p in base[max(0, int(keep)):]:
            for victim in (p, p[:-len(".jaxexp")] + ".json"):
                try:
                    os.remove(victim)
                except OSError:
                    continue
            removed += 1
        if removed:
            obs.counter_add("export_cache_pruned", removed)
        return removed

    def _log(self, action: str, sig: dict, path: str, **extra) -> None:
        rl = obs.active()
        if rl is not None:
            rl.log("export_cache", action=action,
                   kind=sig.get("kind"), digest=sig_digest(sig),
                   path=os.path.basename(path), **extra)


def enable_compile_cache(cache_dir: str) -> bool:
    """Arm JAX's persistent compilation cache at ``cache_dir`` (and the
    obs hit/miss listener).  Thresholds are zeroed so even the small
    CPU-tier programs of the tests/smokes are cached — at TPU scale the
    defaults would admit everything anyway.  Safe to call repeatedly;
    returns False when the running jax lacks the config knobs."""
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:
        return False
    obs.install_cache_listener()
    return True
