"""CalibServer: calibration-as-a-service over the batched substrate.

One persistent ``BatchedEpisode`` of ``lanes`` lanes is the serving
buffer: each micro-batch splices its jobs' episodes into lanes (the
donated ``_lane_splice``, in place on accelerators), then runs the
AOT-exported (policy ->) solve -> influence triple — per-request K/rho/
maxiter are traced operands, so EVERY request mix rides the programs
exported once at warmup (zero per-request compiles; the smoke asserts
it).

Supervision reuses the PR 6/10 Fleet machinery as the circuit breaker:

* the batch worker runs as a 1-slot supervised Fleet — a crash (beyond
  the solver's own ``solve_admm_safe`` degradation ladder) fails the
  in-flight jobs' futures with a structured ``serve_batch_failed``
  event and restarts the worker with backoff;
* a slot past ``max_restarts`` OPENS the circuit: ``submit`` sheds with
  ``ShedError("circuit_open")`` instead of queueing work nobody will
  drain;
* overload sheds at the bounded admission queue (router.MicroBatcher).

Solver degradation inside a batch is handled per LANE: a non-finite
batched solve result re-routes that job through the sequential robust
``calibrate`` (rho-boost retries -> host-segmented fallback — the
``solve_admm_safe`` path), marking the job ``degraded`` instead of
failing the batch.

Telemetry is the obs stack verbatim: spans ``serve_batch`` /
``serve_pack`` / ``serve_policy`` / ``serve_solve`` /
``serve_influence`` (per-stage p50/p99 in tools/obs_report.py), a
``serve_request`` event per job (queue wait / service / total), queue-
depth + batch-fill gauges, shed/admit/compile counters.

Numerics sentinel (``sentinel_every`` > 0): every Nth batch snapshots
one sampled non-warm lane (inputs + fused outputs, latest-wins) and the
breaker loop replays it through the sequential parity oracle (the PR 9
``fused=False`` path behind ``calibrate``/``influence_image``) OFF the
hot path, emitting a ``numerics_drift`` event with per-stage relative
error vs the documented bf16 band.  Drift beyond the band feeds a
dedicated :class:`~smartcal_tpu.obs.slo.SloBurnDetector` (stages as
"replicas", the band as the p99 target) so numeric drift gets the same
burn-rate alerting + flight-recorder blackbox as latency.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import jax
import numpy as np

from smartcal_tpu import obs
from smartcal_tpu.envs import calib as calib_env
from smartcal_tpu.obs import tracectx
from smartcal_tpu.runtime import faults as rt_faults
from smartcal_tpu.runtime import supervisor

from .export import ExportCache, abstract_like, enable_compile_cache
from .router import Job, JobResult, MicroBatcher, ShedError


def _event(name: str, **fields) -> None:
    rl = obs.active()
    if rl is not None:
        rl.log(name, **fields)


#: Sentinel-checked stages, in the SloBurnDetector "replica" index
#: order used to localize which stage is drifting.
SENTINEL_STAGES = ("solve", "influence", "sigma")


class CalibServer:
    """See module doc.  Lifecycle::

        srv = CalibServer(backend, M=5, lanes=8, cache_dir=...)
        srv.warmup(seed=0)      # AOT export (or cache load) + first batch
        srv.start()             # supervised batch worker + breaker loop
        fut = srv.submit(Job(episode=ep, k=3, maxiter=12))
        res = fut.result(timeout=...)   # JobResult
        srv.stop()

    ``policy`` (optional) is ``(SACConfig, actor_params)`` — jobs with
    ``rho=None`` get their regularization from the exported
    deterministic actor forward on their ``obs_vec``.
    """

    def __init__(self, backend, M: int, lanes: int, cache_dir: str,
                 policy: Optional[tuple] = None, npix: Optional[int] = None,
                 max_wait_s: float = 0.05, max_queue: int = 64,
                 heartbeat_timeout: float = 300.0, max_restarts: int = 3,
                 backoff: Optional[supervisor.BackoffPolicy] = None,
                 poll_s: float = 0.05, idle_tick_s: float = 0.2,
                 compile_cache: bool = True, sentinel_every: int = 0,
                 sentinel_band: Optional[float] = None,
                 sentinel_slo: Optional[obs.SloBurnDetector] = None,
                 transition_sink=None):
        self.backend = backend
        self.M = int(M)
        self.lanes = int(lanes)
        self.npix = int(npix or backend.npix)
        self.cache_dir = cache_dir
        self.cache = ExportCache(f"{cache_dir}/programs")
        if compile_cache:
            # the XLA half of the zero-recompile restart: the exported
            # modules' backend compiles become disk hits too
            enable_compile_cache(f"{cache_dir}/xla")
        self.batcher = MicroBatcher(lanes, max_wait_s=max_wait_s,
                                    max_queue=max_queue)
        self._policy = policy
        # monotone policy snapshot version: 0 = the warmup export;
        # swap_policy bumps it atomically with the params/program under
        # _lock, so the batch worker's per-batch snapshot is consistent
        self._policy_version = 0
        # optional lifecycle tee: callable(list[transition dict]) invoked
        # per batch (batch-worker thread, AFTER futures resolve) with the
        # one-step transitions of every non-warm obs_vec-bearing job —
        # the online learner's ingestion hook.  Immutable after init.
        self._transition_sink = transition_sink
        self._base_sig = None           # serve_signature, set at warmup
        self._lock = threading.Lock()
        self._programs: dict = {}       # latest-executable table
        self._circuit_open = False
        self._stats = {"batches": 0, "served": 0, "degraded": 0,
                       "failed": 0, "deadline_miss": 0, "swaps": 0}
        self._bep = None                # worker-owned serving buffer
        self._batch_id = 0
        self._fleet: Optional[supervisor.Fleet] = None
        self._sup: Optional[threading.Thread] = None
        self._stop_ev = threading.Event()
        self._hb = float(heartbeat_timeout)
        self._max_restarts = int(max_restarts)
        self._backoff = backoff
        self._poll_s = float(poll_s)
        self._idle_tick_s = float(idle_tick_s)
        # numerics sentinel: 0 disables sampling entirely (the default
        # keeps the non-sentinel server byte-identical in behavior)
        self.sentinel_every = int(sentinel_every)
        self.sentinel_band = float(obs.BF16_REL_BAND
                                   if sentinel_band is None
                                   else sentinel_band)
        self._sentinel_pending: Optional[dict] = None  # latest-wins
        self._sentinel_stats = {"sampled": 0, "replayed": 0, "drift": 0}
        # stages observe as "replicas" so a burn localizes to the
        # drifting stage; the band is the p99 target, so burn =
        # rel_err / band and one out-of-band replay can fire
        self._sentinel_slo = sentinel_slo or obs.SloBurnDetector(
            p99_target_s=self.sentinel_band, shed_target=1.0,
            fast_window_s=30.0, slow_window_s=120.0,
            burn_threshold=1.0, clear_threshold=1.0, sustain_s=0.0,
            clear_sustain_s=30.0, min_samples=len(SENTINEL_STAGES))

    # -- warmup / AOT ------------------------------------------------------
    def warmup(self, seed: int = 0) -> dict:
        """Build (or load) the exported program triple and run one full
        warmup batch through it — after this returns, steady state
        compiles nothing.  Returns the timing/counter summary that the
        restart measurement compares cold vs warm."""
        t0 = time.time()
        c0 = obs.counters_snapshot()
        with obs.span("serve_warmup", lanes=self.lanes):
            key = jax.random.PRNGKey(seed)
            eps = []
            for _ in range(self.lanes):
                key, k = jax.random.split(key)
                ep, _ = self.backend.new_calib_episode(k, self.M, self.M)
                eps.append(ep)
            self._bep = self.backend.stack_episodes(eps)
            E, M = self.lanes, self.M
            rho = np.ones((E, M), np.float32)
            alpha = np.zeros((E, M), np.float32)
            base = self.backend.serve_signature(M, E, self.npix)
            self._base_sig = dict(base)   # swap_policy's re-export key

            ops = self.backend.batched_solve_operands(self._bep, rho)
            solve = self.cache.get_or_build(
                dict(base, kind="solve"),
                self.backend.batched_solve_callable(M), abstract_like(ops))
            res = solve(*ops)

            iops = self.backend.batched_influence_operands(
                self._bep, res, rho, alpha)
            influence = self.cache.get_or_build(
                dict(base, kind="influence"),
                self.backend.batched_influence_callable(M, self.npix),
                abstract_like(iops))
            imgs = influence(*iops)

            progs = {"solve": solve, "influence": influence}
            if self._policy is not None:
                progs["policy"] = self._export_policy(base)
            jax.block_until_ready((res.sigma_res, imgs))
            with self._lock:
                self._programs = progs
            # one full batch through the REQUEST path (splice, lane
            # params, sigmas, all the jnp glue) so steady state compiles
            # nothing — the warm jobs are tagged out of the SLO stats
            warm_jobs = [
                Job(episode=ep, k=self.M,
                    rho=np.ones(self.M, np.float32),
                    maxiter=int(self.backend.admm_iters), warm=True)
                for ep in eps]
            self._process_batch(warm_jobs)
            for job in warm_jobs:
                job.future.result()
        c1 = obs.counters_snapshot()
        summary = {
            "wall_s": round(time.time() - t0, 3),
            "sources": {k: p.source for k, p in progs.items()},
            **{k: c1.get(k, 0.0) - c0.get(k, 0.0)
               for k in ("export_cache_hit", "export_cache_miss",
                         "jax_compile_events", "jax_compile_secs",
                         "persistent_cache_hits",
                         "persistent_cache_misses")},
        }
        _event("serve_warmup", **summary)
        return summary

    def _policy_sig(self, base_sig: dict, version: int) -> dict:
        """The policy program's cache signature, keyed on (version,
        serve_signature): every published version is a distinct,
        restartable ExportCache entry."""
        import hashlib

        cfg, _ = self._policy
        obs_dim = self.npix * self.npix + (self.M + 1) * 7
        return dict(base_sig, kind="policy", obs_dim=obs_dim,
                    act_dim=2 * self.M, heads=True, version=int(version),
                    cfg_digest=hashlib.sha256(
                        repr(cfg).encode()).hexdigest()[:12])

    def _export_policy(self, base_sig: dict):
        from smartcal_tpu.rl import sac

        cfg, actor_params = self._policy
        obs_dim = self.npix * self.npix + (self.M + 1) * 7
        sig = self._policy_sig(base_sig, self._policy_version)
        aargs = (abstract_like(actor_params),
                 jax.ShapeDtypeStruct((self.lanes, obs_dim), np.float32))
        prog = self.cache.get_or_build(
            sig, lambda ap, o: sac.policy_heads(cfg, ap, o), aargs)
        # warm the backend compile of the deserialized module
        zeros = np.zeros((self.lanes, obs_dim), np.float32)
        jax.block_until_ready(prog(actor_params, zeros))
        return prog

    def _program(self, kind: str):
        with self._lock:
            prog = self._programs.get(kind)
        if prog is None:
            raise RuntimeError(f"no {kind!r} program — call warmup() first")
        return prog

    # -- zero-downtime policy hot-swap -------------------------------------
    @property
    def policy_version(self) -> int:
        with self._lock:
            return self._policy_version

    def swap_policy(self, actor_params, version: int, program=None) -> dict:
        """Atomically install a new policy snapshot between micro-batch
        flushes.

        The batch worker reads ONE consistent (params, program, version)
        snapshot per batch under ``_lock`` (see ``_process_batch``), so
        the swap here — a few dict/ref assignments under the same lock —
        never tears a batch: every request completes on exactly one
        policy version, and requests admitted under version V that
        execute after the swap report both versions in their
        ``serve_request`` event.

        ``program=None`` (the common case) keeps the installed
        executable: the exported policy takes ``actor_params`` as a
        traced operand, so one program serves every weight version —
        the swap costs one warm forward (first dispatch with the new
        params, paid HERE rather than on the serving path) plus the
        locked pointer flip.  Publication through the ExportCache
        (the per-version re-export) is the publisher's job
        (:class:`~smartcal_tpu.serve.lifecycle.PolicyPublisher`).
        """
        if self._policy is None:
            raise RuntimeError("swap_policy on a server with no policy "
                               "armed")
        t0 = time.monotonic()
        cfg, _ = self._policy
        if program is None:
            with self._lock:
                program = self._programs.get("policy")
            if program is None:
                raise RuntimeError("no policy program — call warmup() "
                                   "first")
        # warm OUTSIDE the lock: the first dispatch with the new params
        # must not run on the batch worker's clock
        obs_dim = self.npix * self.npix + (self.M + 1) * 7
        zeros = np.zeros((self.lanes, obs_dim), np.float32)
        jax.block_until_ready(program(actor_params, zeros))
        with self._lock:
            old = self._policy_version
            self._policy = (cfg, actor_params)
            self._policy_version = int(version)
            self._programs["policy"] = program
            self._stats["swaps"] += 1
        swap_s = time.monotonic() - t0
        obs.counter_add("policy_swaps")
        obs.gauge_set("policy_version", int(version))
        _event("policy_swap", version=int(version), version_prev=old,
               swap_s=round(swap_s, 6))
        return {"version": int(version), "version_prev": old,
                "swap_s": swap_s}

    # -- request path ------------------------------------------------------
    @property
    def circuit_open(self) -> bool:
        with self._lock:
            return self._circuit_open

    def submit(self, job: Job):
        """Admit a job (returns its future) or shed: circuit open /
        stopped server / queue full raise :class:`ShedError` with a
        structured event."""
        if self._stop_ev.is_set() and self._fleet is None:
            # a stopped server has no worker: admitting would strand
            # the job in the batcher forever (start() re-opens)
            obs.counter_add("serve_shed")
            _event("serve_shed", job_id=job.job_id, reason="shutdown")
            raise ShedError("shutdown")
        if self.circuit_open:
            obs.counter_add("serve_shed")
            obs.note_shed()
            _event("serve_shed", job_id=job.job_id, reason="circuit_open")
            raise ShedError("circuit_open")
        if job.episode.n_dirs != self.M:
            raise ValueError(f"job episode padded to {job.episode.n_dirs} "
                             f"directions, server expects M={self.M}")
        if not 1 <= job.k <= self.M:
            raise ValueError(f"job.k={job.k} outside [1, M={self.M}]")
        if self._policy is not None and job.version_admitted is None:
            # the stale-version contract: remember which snapshot was
            # live at ADMISSION — a hot-swap can land before execution
            job.version_admitted = self.policy_version
        return self.batcher.submit(job)

    # -- batch execution ---------------------------------------------------
    def _lane_params(self, batch, batch_id: int = 0, policy=None,
                     policy_prog=None):
        """(rho, mask, alpha, iters, heads) lane arrays for this batch.
        Idle lanes re-run their stale (valid) episode under the default
        rho — the program shape is fixed at ``lanes``.  Jobs with
        rho=None and an armed policy get theirs from the exported actor
        forward.

        ``policy``/``policy_prog`` are the per-batch ACTING snapshot
        captured under ``_lock`` by ``_process_batch`` (never read live
        here — a hot-swap mid-batch must not tear the lane params).
        ``heads`` is the host ``(act, mu, logsigma)`` triple of the
        exported forward (None when it didn't run): the behavior-logp
        source for the replay tee.  With a transition sink armed, the
        forward also runs for PINNED-rho lanes carrying an obs_vec so
        their off-policy actions can be scored under the same snapshot.
        """
        E, M = self.lanes, self.M
        rho = np.ones((E, M), np.float32)
        mask = np.zeros((E, M), np.float32)
        alpha = np.zeros((E, M), np.float32)
        iters = np.full((E,), self.backend.admm_iters, np.int32)
        mask[:, :2] = 1.0               # idle lanes: 2 live dirs, rho=1
        want_policy = []
        want_heads = []
        for lane, job in enumerate(batch):
            mask[lane] = 0.0
            mask[lane, :job.k] = 1.0
            if job.maxiter is not None:
                iters[lane] = int(job.maxiter)
            if job.rho is not None:
                rho[lane, :job.k] = np.asarray(job.rho,
                                               np.float32)[:job.k]
                if job.rho_spatial is not None:
                    alpha[lane, :job.k] = np.asarray(job.rho_spatial,
                                                     np.float32)[:job.k]
                if (policy is not None and self._transition_sink is not None
                        and not job.warm and job.obs_vec is not None):
                    want_heads.append(lane)
            elif policy is not None:
                want_policy.append(lane)
        heads = None
        if want_policy or want_heads:
            with obs.span("serve_policy", lanes=len(want_policy),
                          batch=batch_id):
                obs_dim = self.npix * self.npix + (self.M + 1) * 7
                ovec = np.zeros((E, obs_dim), np.float32)
                for lane in want_policy + want_heads:
                    if batch[lane].obs_vec is not None:
                        ovec[lane] = np.asarray(batch[lane].obs_vec,
                                                np.float32)
                _, actor_params = policy
                prog = (policy_prog if policy_prog is not None
                        else self._program("policy"))
                act, mu, logsigma = (np.asarray(a) for a in
                                     prog(actor_params, ovec))
                heads = (act, mu, logsigma)
                lo, hi = calib_env.LOW, calib_env.HIGH
                mapped = act * (hi - lo) / 2 + (hi + lo) / 2
                for lane in want_policy:
                    k = batch[lane].k
                    rho[lane, :k] = np.clip(mapped[lane, :k], lo, hi)
                    alpha[lane, :k] = np.clip(
                        mapped[lane, M:M + k], lo, hi)
        return rho, mask, alpha, iters, heads

    def _behavior_logp(self, job, lane, rho, alpha, heads):
        """(log pi(a|s), action) of the action actually SERVED on
        ``lane``, under the acting snapshot's distribution heads.

        Policy lanes score their own emitted action; pinned-rho lanes
        score the pinned values mapped back to unit coordinates
        (``calib_env._to_unit``) — off-policy data the learner's IMPACT
        ratio corrects for.  Dead entries (beyond ``job.k``) keep the
        policy's own output so they contribute the same density mass a
        pure policy action would — ratio-neutral padding."""
        from smartcal_tpu.rl.networks import tanh_gaussian_log_prob_np

        act_row, mu_row, ls_row = (h[lane] for h in heads)
        action = np.asarray(act_row, np.float32).copy()
        if job.rho is not None:
            k, M = job.k, self.M
            action[:k] = calib_env._to_unit(rho[lane, :k])
            action[M:M + k] = calib_env._to_unit(alpha[lane, :k])
            np.clip(action, -1.0, 1.0, out=action)
        lp = float(tanh_gaussian_log_prob_np(mu_row, ls_row, action))
        return lp, action

    def _oracle_result(self, episode, rho_row, mask_row, alpha_row, it):
        """Sequential re-solve of one lane: the ``solve_admm_safe``
        ladder (rho-boost retries -> host-segmented fallback) behind the
        per-episode ``calibrate`` route.  Both the degraded-lane rescue
        and the numerics sentinel's parity oracle run through here."""
        r = self.backend.calibrate(episode, rho_row, mask=mask_row,
                                   admm_iters=int(it))
        img = np.asarray(self.backend.influence_image(
            episode, r, rho_row, alpha_row, npix=self.npix))
        sig_d = float(np.std(np.asarray(self.backend.data_image(
            episode, npix=self.npix))))
        sig_r = float(np.std(np.asarray(self.backend.residual_image(
            episode, r, npix=self.npix))))
        return (float(np.asarray(r.sigma_res)), sig_d, sig_r,
                float(np.std(img)))

    def _process_batch(self, batch) -> int:
        t_start = time.monotonic()
        E = self.lanes
        with self._lock:
            self._batch_id += 1
            batch_id = self._batch_id
            # ONE consistent acting snapshot per batch: params, program
            # and version move together under the lock, so a concurrent
            # swap_policy lands between batches, never inside one
            policy = self._policy
            ver_acted = self._policy_version
            policy_prog = self._programs.get("policy")
        with obs.span("serve_batch", jobs=len(batch), batch=batch_id):
            # chaos hook: a planned serve_batch delay (runtime/faults)
            # inflates this replica's service time — the injected-
            # slowdown demonstration the SLO burn detector must catch
            rt_faults.maybe_delay("serve_batch", batch_id)
            with obs.span("serve_pack", jobs=len(batch), batch=batch_id):
                for lane, job in enumerate(batch):
                    self._bep = self.backend.splice_episode(
                        self._bep, lane, job.episode)
                rho, mask, alpha, iters, heads = self._lane_params(
                    batch, batch_id, policy, policy_prog)
            ops = self.backend.batched_solve_operands(
                self._bep, rho, mask, iters)
            with obs.span("serve_solve", lanes=E, batch=batch_id):
                res = self._program("solve")(*ops)
                sig = np.asarray(res.sigma_res)
            with obs.span("serve_influence", lanes=E, batch=batch_id):
                imgs = np.asarray(self._program("influence")(
                    *self.backend.batched_influence_operands(
                        self._bep, res, rho, alpha)))
            with obs.span("serve_sigma", batch=batch_id):
                sig_d, sig_r = (np.asarray(a) for a in
                                self.backend.image_sigmas_batched(
                                    self._bep, res, npix=self.npix))
        t_done = time.monotonic()
        service = t_done - t_start
        self.batcher.note_service_time(service)
        obs.gauge_set("serve_batch_fill", len(batch) / E)
        n_degraded = 0
        n_missed = 0
        sentinel_due = (self.sentinel_every > 0
                        and batch_id % self.sentinel_every == 0)
        sent_candidates = []
        transitions = []
        for lane, job in enumerate(batch):
            degraded = not np.isfinite(sig[lane])
            if degraded:
                n_degraded += 1
                obs.counter_add("serve_degraded")
                _event("serve_degraded", job_id=job.job_id, lane=lane,
                       batch=batch_id)
                vals = self._oracle_result(job.episode, rho[lane],
                                           mask[lane], alpha[lane],
                                           iters[lane])
            else:
                vals = (float(sig[lane]), float(sig_d[lane]),
                        float(sig_r[lane]), float(np.std(imgs[lane])))
                if sentinel_due and not job.warm:
                    sent_candidates.append((lane, job, vals))
            total = time.monotonic() - job.t_submit
            missed = (job.deadline_s is not None and total > job.deadline_s)
            if missed:
                n_missed += 1
                obs.counter_add("serve_deadline_miss")
            version_fields = {}
            behavior_logp = None
            if policy is not None:
                # stale-version contract: BOTH the admission-time and
                # acting versions ride the event — a swap between them
                # is visible, never silently the new version alone
                version_fields = {
                    "version": ver_acted,
                    "version_admitted": (job.version_admitted
                                         if job.version_admitted is not None
                                         else ver_acted)}
                if heads is not None and job.obs_vec is not None \
                        and not job.warm:
                    behavior_logp = self._behavior_logp(
                        job, lane, rho, alpha, heads)
                    version_fields["behavior_logp"] = round(
                        behavior_logp[0], 6)
            result = JobResult(
                job_id=job.job_id, lane=lane, batch_id=batch_id,
                sigma_res=vals[0], sigma_data_img=vals[1],
                sigma_res_img=vals[2], img_std=vals[3], degraded=degraded,
                queue_wait_s=round(t_start - job.t_submit, 6),
                service_s=round(service, 6), total_s=round(total, 6),
                deadline_miss=missed)
            _event("serve_request", job_id=job.job_id, lane=lane,
                   batch=batch_id, k=job.k, maxiter=job.maxiter,
                   degraded=degraded, deadline_miss=missed,
                   queue_wait_s=result.queue_wait_s,
                   service_s=result.service_s, total_s=result.total_s,
                   sigma_res=vals[0], **version_fields,
                   **tracectx.child_fields(job.trace),
                   **({"warm": True} if job.warm else {}))
            obs.counter_add("serve_jobs_warm" if job.warm
                            else "serve_jobs")
            if (behavior_logp is not None
                    and self._transition_sink is not None):
                lp, action = behavior_logp
                ov = np.asarray(job.obs_vec, np.float32)
                reward = (vals[1] / max(vals[2], 1e-12)
                          + 1e-4 / (vals[3] + calib_env.EPS))
                transitions.append({
                    "state": ov, "action": action,
                    "reward": np.float32(reward), "new_state": ov,
                    "done": True,
                    "hint": np.zeros(2 * self.M, np.float32),
                    "version": np.int32(ver_acted),
                    "behavior_logp": np.float32(lp)})
            job.future.set_result(result)
        if transitions:
            try:
                self._transition_sink(transitions)
                obs.counter_add("serve_teed", len(transitions))
            except Exception as e:   # tee must never fail the batch
                obs.counter_add("serve_tee_errors")
                _event("serve_tee_error", batch=batch_id, error=repr(e))
        snap = None
        if sent_candidates:
            # deterministic pick, latest-wins: the breaker loop replays
            # at its own pace; an unpolled snapshot is simply replaced
            lane, job, vals = sent_candidates[
                batch_id % len(sent_candidates)]
            snap = {"batch": batch_id, "lane": lane,
                    "job_id": job.job_id, "episode": job.episode,
                    "rho": rho[lane].copy(), "mask": mask[lane].copy(),
                    "alpha": alpha[lane].copy(),
                    "iters": int(iters[lane]),
                    # fused outputs in SENTINEL_STAGES order
                    "fused": {"solve": vals[0], "influence": vals[3],
                              "sigma": vals[2]}}
        with self._lock:
            self._stats["batches"] += 1
            self._stats["served"] += len(batch)
            self._stats["degraded"] += n_degraded
            self._stats["deadline_miss"] += n_missed
            if snap is not None:
                self._sentinel_pending = snap
                self._sentinel_stats["sampled"] += 1
        return len(batch)

    # -- numerics sentinel -------------------------------------------------
    def sentinel_poll(self) -> Optional[dict]:
        """Replay the pending sampled lane through the sequential parity
        oracle and judge the fused outputs against the documented band.

        Runs on the breaker/supervisor thread (or a test's thread) —
        never on the batch worker, so the hot path only pays the
        latest-wins snapshot copy.  Returns the ``numerics_drift``
        event dict when a replay happened, else None (still advancing
        the burn detector's hysteresis so a past alarm can clear)."""
        with self._lock:
            snap = self._sentinel_pending
            self._sentinel_pending = None
            seq = self._sentinel_stats["replayed"]
        if snap is None:
            ev = self._sentinel_slo.evaluate()
            if ev is not None:
                self._emit_sentinel_burn(ev)
            return None
        with obs.span("serve_sentinel", batch=snap["batch"]):
            oracle = self._oracle_result(
                snap["episode"], snap["rho"], snap["mask"],
                snap["alpha"], snap["iters"])
        oracle_by = {"solve": oracle[0], "influence": oracle[3],
                     "sigma": oracle[2]}
        rels = {}
        n_drift = 0
        for idx, stage in enumerate(SENTINEL_STAGES):
            # chaos hook: a planned perturbation (runtime/faults)
            # shifts the FUSED value, rehearsing out-of-band drift
            # without touching a kernel
            fused = rt_faults.maybe_perturb(
                f"sentinel_{stage}", seq, snap["fused"][stage])
            ref = oracle_by[stage]
            rel = abs(fused - ref) / max(abs(ref), 1e-12)
            rels[stage] = rel
            if rel > self.sentinel_band:
                n_drift += 1
            self._sentinel_slo.observe(rel, replica=idx)
        worst = max(rels, key=lambda s: rels[s])
        event = {"batch": snap["batch"], "lane": snap["lane"],
                 "job_id": snap["job_id"], "seq": seq,
                 "band": self.sentinel_band,
                 "worst_stage": worst, "drift": n_drift > 0,
                 **{f"rel_err_{s}": round(r, 9)
                    for s, r in rels.items()}}
        _event("numerics_drift", **event)
        obs.counter_add("sentinel_replays")
        if n_drift:
            obs.counter_add("sentinel_drift")
        with self._lock:
            self._sentinel_stats["replayed"] += 1
            self._sentinel_stats["drift"] += (1 if n_drift else 0)
        ev = self._sentinel_slo.evaluate()
        if ev is not None:
            self._emit_sentinel_burn(ev)
        return event

    def _emit_sentinel_burn(self, ev: dict) -> None:
        """Surface a sentinel burn transition exactly like a latency
        burn: a structured ``slo_burn`` event (kind="numerics", the
        drifting STAGE named) plus a flight-recorder dump on firing."""
        worst = ev.get("worst_replica")
        stage = (SENTINEL_STAGES[int(worst)]
                 if worst is not None else None)
        _event("slo_burn", kind="numerics", stage=stage, **ev)
        obs.counter_add("sentinel_burn_transitions")
        if ev.get("state") == "firing":
            obs.flush_flight_recorder(
                "numerics_drift",
                {"stage": stage, "burn_fast": ev.get("burn_fast"),
                 "band": self.sentinel_band})

    def process_once(self, jobs, timeout: float = 0.0) -> int:
        """Synchronously pack+serve up to ``lanes`` queued/given jobs on
        the CALLER's thread (tests, warmup probes).  Only valid while
        the supervised worker is NOT running."""
        if self._fleet is not None:
            raise RuntimeError("process_once with a running fleet would "
                               "race the batch worker")
        for job in jobs:
            if self._policy is not None and job.version_admitted is None:
                job.version_admitted = self.policy_version
            self.batcher.submit(job)
        batch = self.batcher.next_batch(timeout=max(timeout, 0.001))
        return self._process_batch(batch) if batch else 0

    # -- supervised worker + breaker loop ----------------------------------
    def _work(self, actor_id, iteration, weights):
        batch = self.batcher.next_batch(timeout=self._idle_tick_s)
        if not batch:
            return {"served": 0}
        try:
            n = self._process_batch(batch)
        except BaseException as e:    # noqa: BLE001 — death IS the signal
            _event("serve_batch_failed", jobs=[j.job_id for j in batch],
                   error=repr(e))
            with self._lock:
                self._stats["failed"] += len(batch)
            for job in batch:
                if not job.future.done():
                    job.future.set_exception(e)
            raise
        return {"served": n}

    def start(self) -> None:
        """Start the supervised batch worker and the breaker loop."""
        if self._fleet is not None:
            raise RuntimeError("server already started")
        self._stop_ev.clear()
        kw = {"name": "serve", "heartbeat_timeout": self._hb,
              "max_restarts": self._max_restarts, "queue_depth": 4}
        if self._backoff is not None:
            kw["backoff"] = self._backoff
        fleet = supervisor.Fleet(1, self._work, **kw)
        fleet.start(None)
        sup = threading.Thread(target=self._supervise, name="serve-breaker",
                               daemon=True)
        with self._lock:
            self._fleet = fleet
            self._sup = sup
        sup.start()

    def _supervise(self) -> None:
        """The breaker loop: poll the fleet (death detection + backoff
        restarts), drain its summary queue, open/close the circuit on
        slot failure, and emit the queue-depth gauge stream."""
        while not self._stop_ev.wait(self._poll_s):
            fleet = self._fleet
            if fleet is None:
                return
            try:
                fleet.poll()
                # drain the worker's summary queue: an undrained bounded
                # queue back-pressures the batch worker to a HALT (the
                # cold-run postmortem that added this try/except)
                fleet.collect(max_items=64, timeout=0.0)
                open_now = bool(fleet.failed_slots)
                with self._lock:
                    changed = open_now != self._circuit_open
                    self._circuit_open = open_now
                if changed:
                    obs.counter_add("serve_circuit_transitions")
                    _event("serve_circuit", open=open_now,
                           restarts=fleet.restarts_total())
                    if open_now:
                        # circuit OPEN is a postmortem moment: dump the
                        # flight-recorder ring with the lead-up events
                        obs.flush_flight_recorder(
                            "circuit_open",
                            {"restarts": fleet.restarts_total()})
                obs.gauge_set("serve_queue_depth", self.batcher.depth())
                if self.sentinel_every > 0:
                    self.sentinel_poll()
            except Exception as e:   # breaker must outlive a bad pass
                obs.counter_add("serve_breaker_errors")
                _event("serve_breaker_error", error=repr(e))

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._stats)
            sent = dict(self._sentinel_stats)
            ver = self._policy_version
        out.update(self.batcher.stats())
        out["circuit_open"] = self.circuit_open
        if self._policy is not None:
            out["policy_version"] = ver
        if self.sentinel_every > 0:
            out["sentinel"] = dict(sent,
                                   firing=self._sentinel_slo.firing)
        return out

    def stop(self, timeout: float = 10.0) -> None:
        """Stop the worker, fail any stranded queued jobs explicitly."""
        self._stop_ev.set()
        with self._lock:
            fleet, sup = self._fleet, self._sup
            self._fleet, self._sup = None, None
        if sup is not None:
            sup.join(timeout=timeout)
        if fleet is not None:
            fleet.stop(join=True, timeout=timeout)
        for job in self.batcher.drain():
            if not job.future.done():
                job.future.set_exception(ShedError("shutdown"))
