"""Calibration-as-a-service: AOT-exported serving over the batched
substrate.

* :mod:`~smartcal_tpu.serve.export` — jax.export program cache keyed on
  trace signature + the persistent XLA compilation cache hookup (a warm
  server restart neither re-traces nor re-compiles);
* :mod:`~smartcal_tpu.serve.router` — bounded admission + deadline-aware
  micro-batching of heterogeneous jobs into ``BatchedEpisode`` lanes;
* :mod:`~smartcal_tpu.serve.server` — the supervised ``CalibServer``
  driver (Fleet-backed circuit breaker, ``solve_admm_safe`` degradation,
  SLO telemetry through the obs stack);
* :mod:`~smartcal_tpu.serve.loadgen` — synthetic open-loop (Poisson)
  load generator for the jobs/s-vs-offered-load curve.

Entry point: ``tools/serve_calib.py``; smoke: ``tools/smoke_serve.sh``.
"""

from .export import (ExportCache, ServeProgram,            # noqa: F401
                     abstract_like, enable_compile_cache,
                     prime_backend_kernels, sig_digest)
from .router import (Job, JobResult, MicroBatcher,         # noqa: F401
                     ShedError)
from .server import CalibServer                            # noqa: F401
