"""Calibration-as-a-service: AOT-exported serving over the batched
substrate.

* :mod:`~smartcal_tpu.serve.export` — jax.export program cache keyed on
  trace signature + the persistent XLA compilation cache hookup (a warm
  server restart neither re-traces nor re-compiles);
* :mod:`~smartcal_tpu.serve.router` — bounded admission + deadline-aware
  micro-batching of heterogeneous jobs into ``BatchedEpisode`` lanes;
* :mod:`~smartcal_tpu.serve.server` — the supervised ``CalibServer``
  driver (Fleet-backed circuit breaker, ``solve_admm_safe`` degradation,
  SLO telemetry through the obs stack);
* :mod:`~smartcal_tpu.serve.loadgen` — synthetic open-loop (Poisson)
  load generator for the jobs/s-vs-offered-load curve;
* :mod:`~smartcal_tpu.serve.fleet` — horizontal scale-out: replicated
  ``CalibServer`` processes (shared AOT + XLA cache, so replica N
  warm-starts) behind the deadline-aware least-loaded ``FleetRouter``
  front door, with per-replica circuits and load-driven autoscale;
* :mod:`~smartcal_tpu.serve.lifecycle` — the closed loop: tee served
  transitions into the sharded versioned replay, learn beside the
  server, publish zero-compile policy hot-swaps through the export
  cache (``TransitionStage`` / ``ServingLearner`` / ``PolicyPublisher``).

Entry points: ``tools/serve_calib.py`` (one server),
``tools/serve_fleet.py`` (replica topology sweep),
``tools/serve_learn.py`` (online learning lifecycle); smokes:
``tools/smoke_serve.sh``, ``tools/smoke_serve_fleet.sh``,
``tools/smoke_lifecycle.sh``.

Exports resolve LAZILY (PEP 562): a spawned replica process imports
this package on its way to :mod:`~smartcal_tpu.serve.fleet`'s worker
entry point, and an eager ``from .server import CalibServer`` here
would make every stub-server replica (tests) pay the full jax import —
the real server factory imports jax inside the worker when it actually
builds a backend.
"""

import importlib

_EXPORTS = {
    "ExportCache": ".export", "ServeProgram": ".export",
    "abstract_like": ".export", "enable_compile_cache": ".export",
    "prime_backend_kernels": ".export", "sig_digest": ".export",
    "AutoscalePolicy": ".fleet", "FleetRouter": ".fleet",
    "calib_worker_spec": ".fleet", "make_calib_server": ".fleet",
    "Job": ".router", "JobResult": ".router", "MicroBatcher": ".router",
    "ShedError": ".router",
    "CalibServer": ".server",
    "PolicyPublisher": ".lifecycle", "ServingLearner": ".lifecycle",
    "TransitionStage": ".lifecycle", "build_obs_pool": ".lifecycle",
    "job_obs_vec": ".lifecycle",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    submodule = _EXPORTS.get(name)
    if submodule is None:
        raise AttributeError(f"module {__name__!r} has no attribute "
                             f"{name!r}")
    value = getattr(importlib.import_module(submodule, __name__), name)
    globals()[name] = value              # cache: resolve once
    return value


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
