"""Synthetic open-loop load generator for the calibration service.

OPEN loop: arrivals are a Poisson process at the offered rate,
independent of service progress — the generator never waits for a
response before submitting the next job, so queueing/shedding behavior
under overload is actually exercised (a closed loop self-throttles and
can never drive the server past saturation).

Episodes are pre-built (host-side sky draws are not the thing under
test) and cycled with a mixed direction-count/maxiter/rho profile, so
every batch the router packs is heterogeneous — the one-compile-serves-
every-mix property is load-tested, not just unit-tested.
"""

from __future__ import annotations

import time
from typing import List, Optional, Tuple

import numpy as np

from smartcal_tpu.obs import tracectx

from .router import Job, ShedError

# Serving backend scale presets (the "tier" kwargs a RadioBackend takes);
# shared by tools/serve_calib.py and tools/serve_fleet.py so the two
# drivers can never drift apart on what "tiny" means.
SERVE_TIERS = {
    # n_stations, n_freqs, n_times, tdelta, admm, lbfgs, init, npix
    "tiny": dict(n_stations=6, n_freqs=2, n_times=4, tdelta=2,
                 admm_iters=2, lbfgs_iters=3, init_iters=5, npix=32),
    "small": dict(n_stations=10, n_freqs=2, n_times=8, tdelta=4,
                  admm_iters=5, lbfgs_iters=5, init_iters=10, npix=64),
    "medium": dict(n_stations=14, n_freqs=3, n_times=20, tdelta=10,
                   admm_iters=10, lbfgs_iters=8, init_iters=30, npix=128),
}


def build_job_pool(backend, M: int, n: int, seed: int = 0,
                   key0=None, heterogeneous: bool = True,
                   diffuse_frac: float = 0.25, mixed=None
                   ) -> List[Tuple[int, object]]:
    """``n`` pre-built (k, episode) pairs padded to M directions (the
    server's contract).

    ``heterogeneous`` (the default — since ISSUE 20 for EVERY driver,
    not just the fleet's) draws a mixed pool: K uniform over [2, M] and
    a ``diffuse_frac`` fraction of diffuse-sky episodes per draw,
    instead of the old deterministic K cycle over point-source skies —
    ROADMAP #3 flags every serving number measured against the
    homogeneous pool as optimistic.  ``heterogeneous=False`` keeps the
    PR 15 uniform pool bit-for-bit for comparability.  ``mixed`` is the
    pre-ISSUE-20 name for the same knob; when given it wins (caller
    compatibility)."""
    import jax

    if mixed is not None:
        heterogeneous = bool(mixed)
    key = jax.random.PRNGKey(seed) if key0 is None else key0
    rng = np.random.default_rng(seed)
    pool = []
    for i in range(n):
        key, k = jax.random.split(key)
        if heterogeneous:
            kdirs = int(rng.integers(2, M + 1))
            diffuse = bool(rng.random() < diffuse_frac)
        else:
            kdirs = 2 + i % max(1, M - 1)
            diffuse = False
        ep, _ = backend.new_calib_episode(k, kdirs, M, diffuse=diffuse)
        pool.append((kdirs, ep))
    return pool


class OpenLoopLoadGen:
    """Submit Poisson arrivals at ``rate`` jobs/s for ``duration_s``,
    then wait for the tail and summarize.  Shed jobs count against the
    offered rate (they are the overload signal, not an error).

    Every submitted job lands in EXACTLY one bucket of the summary —
    ``completed`` (of which ``deadline_missed`` is the served-late
    subset), ``shed`` (sync at submit OR async: a fleet router losing a
    job's replica post-admission sheds it through the future with the
    same structured :class:`ShedError`), or ``failed`` (any other
    exception / drain timeout) — and the per-reason ``shed_reasons``
    sum to ``shed`` (tools/smoke_serve_fleet.sh asserts both).

    ``pick="random"`` (default) draws pool entries uniformly; ``"cycle"``
    keeps the PR 15 deterministic walk for comparability."""

    def __init__(self, server, pool, rate: float, duration_s: float,
                 seed: int = 0, deadline_s: Optional[float] = None,
                 maxiter_choices=(None,), pick: str = "random"):
        if pick not in ("random", "cycle"):
            raise ValueError(f"pick must be 'random' or 'cycle', "
                             f"got {pick!r}")
        self.server = server
        self.pool = pool
        self.rate = float(rate)
        self.duration_s = float(duration_s)
        self.deadline_s = deadline_s
        self.maxiter_choices = tuple(maxiter_choices)
        self.pick = pick
        self._rng = np.random.default_rng(seed)

    def run(self, drain_timeout_s: float = 120.0) -> dict:
        rng = self._rng
        t_end = time.monotonic() + self.duration_s
        futures, submitted = [], 0
        shed_reasons: dict = {}
        i = 0
        next_t = time.monotonic()
        while True:
            next_t += rng.exponential(1.0 / self.rate)
            if next_t > t_end:
                break
            delay = next_t - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            if self.pick == "random":
                idx = int(rng.integers(len(self.pool)))
                mi = self.maxiter_choices[
                    int(rng.integers(len(self.maxiter_choices)))]
            else:
                idx = i % len(self.pool)
                mi = self.maxiter_choices[i % len(self.maxiter_choices)]
            entry = self.pool[idx]
            # lifecycle pools carry a third element: the pre-computed
            # flattened observation (serve.lifecycle.build_obs_pool) the
            # policy forward / replay tee consume
            kdirs, ep = entry[0], entry[1]
            obs_vec = entry[2] if len(entry) > 2 else None
            rho = None
            if rng.random() < 0.5:       # half pinned-rho, half default/policy
                rho = np.exp(rng.uniform(np.log(0.1), np.log(10.0),
                                         kdirs)).astype(np.float32)
            job = Job(episode=ep, k=kdirs, rho=rho, maxiter=mi,
                      deadline_s=self.deadline_s, obs_vec=obs_vec,
                      trace=tracectx.new_root_carrier())
            submitted += 1
            i += 1
            try:
                futures.append(self.server.submit(job))
            except ShedError as e:
                shed_reasons[e.reason] = shed_reasons.get(e.reason, 0) + 1
        t0_wall = time.monotonic()
        results = []
        failed = 0
        for fut in futures:
            remaining = drain_timeout_s - (time.monotonic() - t0_wall)
            try:
                results.append(fut.result(timeout=max(0.1, remaining)))
            except ShedError as e:       # async shed (post-admission loss)
                shed_reasons[e.reason] = shed_reasons.get(e.reason, 0) + 1
            except Exception:            # failed / drain-timed-out job
                failed += 1
        return self.summarize(submitted, sum(shed_reasons.values()),
                              results, shed_reasons=shed_reasons,
                              failed=failed)

    def summarize(self, submitted: int, shed: int, results,
                  shed_reasons: Optional[dict] = None,
                  failed: int = 0) -> dict:
        # deadline misses are the served-LATE subset of completed jobs:
        # disjoint from sheds by construction (a shed job never serves)
        deadline_missed = int(sum(1 for r in results
                                  if getattr(r, "deadline_miss", False)))
        out = {"offered_rate": self.rate, "duration_s": self.duration_s,
               "submitted": submitted, "shed": shed,
               "shed_reasons": dict(shed_reasons or {}),
               "failed": int(failed),
               "completed": len(results),
               "deadline_missed": deadline_missed,
               "accounted": shed + int(failed) + len(results),
               "shed_rate": round(shed / max(1, submitted), 4)}
        if results:
            totals = np.asarray([r.total_s for r in results])
            waits = np.asarray([r.queue_wait_s for r in results])
            span = self.duration_s + float(totals.max())
            out.update({
                "achieved_jobs_s": round(len(results) / span, 3),
                "latency_p50_s": round(float(np.percentile(totals, 50)), 4),
                "latency_p99_s": round(float(np.percentile(totals, 99)), 4),
                "queue_wait_p50_s": round(float(np.percentile(waits, 50)),
                                          4),
                "queue_wait_p99_s": round(float(np.percentile(waits, 99)),
                                          4),
                "degraded": int(sum(1 for r in results if r.degraded)),
            })
        return out
