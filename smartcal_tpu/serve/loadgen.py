"""Synthetic open-loop load generator for the calibration service.

OPEN loop: arrivals are a Poisson process at the offered rate,
independent of service progress — the generator never waits for a
response before submitting the next job, so queueing/shedding behavior
under overload is actually exercised (a closed loop self-throttles and
can never drive the server past saturation).

Episodes are pre-built (host-side sky draws are not the thing under
test) and cycled with a mixed direction-count/maxiter/rho profile, so
every batch the router packs is heterogeneous — the one-compile-serves-
every-mix property is load-tested, not just unit-tested.
"""

from __future__ import annotations

import time
from typing import List, Optional, Tuple

import numpy as np

from .router import Job, ShedError


def build_job_pool(backend, M: int, n: int, seed: int = 0,
                   key0=None) -> List[Tuple[int, object]]:
    """``n`` pre-built (k, episode) pairs with K cycling over [2, M]
    (episodes padded to M directions — the server's contract)."""
    import jax

    key = jax.random.PRNGKey(seed) if key0 is None else key0
    pool = []
    for i in range(n):
        key, k = jax.random.split(key)
        kdirs = 2 + i % max(1, M - 1)
        ep, _ = backend.new_calib_episode(k, kdirs, M)
        pool.append((kdirs, ep))
    return pool


class OpenLoopLoadGen:
    """Submit Poisson arrivals at ``rate`` jobs/s for ``duration_s``,
    then wait for the tail and summarize.  Shed jobs count against the
    offered rate (they are the overload signal, not an error)."""

    def __init__(self, server, pool, rate: float, duration_s: float,
                 seed: int = 0, deadline_s: Optional[float] = None,
                 maxiter_choices=(None,)):
        self.server = server
        self.pool = pool
        self.rate = float(rate)
        self.duration_s = float(duration_s)
        self.deadline_s = deadline_s
        self.maxiter_choices = tuple(maxiter_choices)
        self._rng = np.random.default_rng(seed)

    def run(self, drain_timeout_s: float = 120.0) -> dict:
        rng = self._rng
        t_end = time.monotonic() + self.duration_s
        futures, shed, submitted = [], 0, 0
        i = 0
        next_t = time.monotonic()
        while True:
            next_t += rng.exponential(1.0 / self.rate)
            if next_t > t_end:
                break
            delay = next_t - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            kdirs, ep = self.pool[i % len(self.pool)]
            mi = self.maxiter_choices[i % len(self.maxiter_choices)]
            rho = None
            if rng.random() < 0.5:       # half pinned-rho, half default/policy
                rho = np.exp(rng.uniform(np.log(0.1), np.log(10.0),
                                         kdirs)).astype(np.float32)
            job = Job(episode=ep, k=kdirs, rho=rho, maxiter=mi,
                      deadline_s=self.deadline_s)
            submitted += 1
            i += 1
            try:
                futures.append(self.server.submit(job))
            except ShedError:
                shed += 1
        t0_wall = time.monotonic()
        results = []
        for fut in futures:
            remaining = drain_timeout_s - (time.monotonic() - t0_wall)
            try:
                results.append(fut.result(timeout=max(0.1, remaining)))
            except Exception:            # failed/timed-out job: counted only
                pass
        return self.summarize(submitted, shed, results)

    def summarize(self, submitted: int, shed: int, results) -> dict:
        out = {"offered_rate": self.rate, "duration_s": self.duration_s,
               "submitted": submitted, "shed": shed,
               "completed": len(results),
               "shed_rate": round(shed / max(1, submitted), 4)}
        if results:
            totals = np.asarray([r.total_s for r in results])
            waits = np.asarray([r.queue_wait_s for r in results])
            span = self.duration_s + float(totals.max())
            out.update({
                "achieved_jobs_s": round(len(results) / span, 3),
                "latency_p50_s": round(float(np.percentile(totals, 50)), 4),
                "latency_p99_s": round(float(np.percentile(totals, 99)), 4),
                "queue_wait_p50_s": round(float(np.percentile(waits, 50)),
                                          4),
                "queue_wait_p99_s": round(float(np.percentile(waits, 99)),
                                          4),
                "degraded": int(sum(1 for r in results if r.degraded)),
            })
        return out
