"""Online lifecycle: learn from served traffic, publish zero-downtime
policy hot-swaps (ISSUE 20 — ROADMAP open item #1).

Closes the train/serve loop around :class:`~smartcal_tpu.serve.server
.CalibServer`:

* **tee** — the server's ``transition_sink`` hook feeds every completed
  non-warm, obs-bearing request into a :class:`TransitionStage` (a
  bounded host staging ring: the batch worker pays one locked append,
  nothing else);
* **learn** — :class:`ServingLearner` drains the stage into the
  mesh-sharded VERSIONED replay (``rl/replay_sharded`` over
  ``replay.versioned_spec``) and runs the fused SAC step beside the
  server, IMPACT staleness-clipped IS weighting (arXiv:1912.00167) +
  ERE recency bias (arXiv:1906.04009) armed — served traffic is
  off-policy and ages across swaps, which is exactly the regime those
  knobs exist for;
* **publish** — :class:`PolicyPublisher` AOT-publishes each new
  snapshot keyed on ``(version, serve_signature)`` through the
  :class:`~smartcal_tpu.serve.export.ExportCache` and atomically swaps
  it into the server between micro-batch flushes
  (``CalibServer.swap_policy``; fleet-wide via
  ``FleetRouter.publish_policy`` weight frames).

The zero-compile hinge: the exported policy program takes
``actor_params`` as a TRACED OPERAND, so its StableHLO is identical for
every weight version — publication re-serializes the program under the
new versioned key (``ExportCache.publish``: provenance + a restartable
per-version artifact) and warms the installed executable with the new
params; it never re-traces, re-lowers, or re-compiles.  A policy update
therefore never drops a request, never pays a foreground compile, and
never blocks the batch worker (the export/warm run on the publisher's
thread; the swap itself is a locked pointer flip).

Driver: ``tools/serve_learn.py``; smoke: ``tools/smoke_lifecycle.sh``.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional, Tuple

import numpy as np

from smartcal_tpu import obs
from smartcal_tpu.envs import calib as calib_env


def _event(name: str, **fields) -> None:
    rl = obs.active()
    if rl is not None:
        rl.log(name, **fields)


# ---------------------------------------------------------------------------
# observation construction (the serving side of the CalibEnv contract)
# ---------------------------------------------------------------------------

def job_obs_vec(backend, episode, k: int, M: int,
                npix: Optional[int] = None,
                probe_iters: Optional[int] = None) -> np.ndarray:
    """Flattened policy observation for a serving job, in the CalibEnv
    convention (:mod:`smartcal_tpu.envs.calib`): influence image of a
    unit-rho probe calibration x ``INF_SCALE``, then an (M+1)x7
    sky/meta table x ``META_SCALE`` with the unit-rho columns (5/6) and
    the live-direction fraction in the spare last row.

    Built OFFLINE at pool-construction time (one probe calibrate +
    influence per entry) — the serving hot path never computes
    observations, it carries them."""
    npix = int(npix or backend.npix)
    rho = np.ones(M, np.float32)
    alpha = np.zeros(M, np.float32)
    mask = np.zeros(M, np.float32)
    mask[:k] = 1.0
    iters = int(probe_iters or backend.admm_iters)
    r = backend.calibrate(episode, rho, mask=mask, admm_iters=iters)
    img = np.asarray(backend.influence_image(episode, r, rho, alpha,
                                             npix=npix), np.float32)
    sky = np.zeros((M + 1, 7), np.float32)
    sky[:k, 5] = calib_env._to_unit(rho[:k])
    sky[:k, 6] = calib_env._to_unit(alpha[:k])
    sky[M, 0] = k / max(1, M)
    return np.concatenate([
        (img * calib_env.INF_SCALE).ravel(),
        (sky * calib_env.META_SCALE).ravel()]).astype(np.float32)


def build_obs_pool(backend, M: int, n: int, seed: int = 0,
                   heterogeneous: bool = True,
                   diffuse_frac: float = 0.25,
                   npix: Optional[int] = None
                   ) -> List[Tuple[int, object, np.ndarray]]:
    """A :func:`~smartcal_tpu.serve.loadgen.build_job_pool` pool with
    the flattened observation attached per entry — ``(k, episode,
    obs_vec)`` triples the lifecycle load generator submits, so every
    job can ride the policy forward AND the replay tee."""
    from .loadgen import build_job_pool

    pool = build_job_pool(backend, M, n, seed=seed,
                          heterogeneous=heterogeneous,
                          diffuse_frac=diffuse_frac)
    return [(k, ep, job_obs_vec(backend, ep, k, M, npix=npix))
            for k, ep in pool]


# ---------------------------------------------------------------------------
# the tee: batch worker -> learner staging
# ---------------------------------------------------------------------------

class TransitionStage:
    """Bounded thread-safe staging ring between the batch worker (the
    server's ``transition_sink``) and the learner's ingest loop.

    The worker-side cost is one locked list-extend per batch; overflow
    drops the OLDEST staged transitions (the learner is behind — recent
    traffic is worth more than stale, same bias ERE encodes) and counts
    them, never blocks."""

    def __init__(self, cap: int = 4096):
        self.cap = int(cap)
        self._lock = threading.Lock()
        self._items: list = []
        self._dropped = 0
        self._staged = 0

    def __call__(self, transitions: list) -> None:
        """The ``CalibServer(transition_sink=...)`` hook."""
        with self._lock:
            self._items.extend(transitions)
            self._staged += len(transitions)
            over = len(self._items) - self.cap
            if over > 0:
                del self._items[:over]
                self._dropped += over
        if transitions:
            obs.counter_add("lifecycle_staged", len(transitions))

    def drain(self) -> list:
        with self._lock:
            items, self._items = self._items, []
        return items

    def stats(self) -> dict:
        with self._lock:
            return {"staged": self._staged, "dropped": self._dropped,
                    "pending": len(self._items)}


# ---------------------------------------------------------------------------
# publication: versioned re-export + atomic swap
# ---------------------------------------------------------------------------

class PolicyPublisher:
    """Publish a new policy snapshot to a warmed server (and optionally
    a replica fleet): ExportCache entry keyed on (version,
    serve_signature) -> warm forward with the new params -> atomic
    ``swap_policy`` between micro-batch flushes.

    Runs on the CALLER's thread (the learner loop / a dedicated
    publisher thread) — never the batch worker's: the worker only ever
    sees the locked pointer flip inside ``swap_policy``."""

    def __init__(self, server, fleet=None, keep_versions: int = 8):
        self.server = server
        self.fleet = fleet
        self.keep_versions = int(keep_versions)
        self._lock = threading.Lock()
        self._stats = {"publishes": 0, "last_publish_s": 0.0,
                       "last_version": 0}

    def publish(self, actor_params, version: int) -> dict:
        """Synchronous publication; returns the timing record."""
        srv = self.server
        if srv._base_sig is None:
            raise RuntimeError("publish before server warmup() — no "
                               "serve signature to key the export on")
        t0 = time.monotonic()
        with obs.span("serve_publish", version=int(version)):
            sig = srv._policy_sig(srv._base_sig, version)
            t_exp = time.monotonic()
            prog = srv.cache.publish(sig, srv._program("policy"))
            export_s = time.monotonic() - t_exp
            swap = srv.swap_policy(actor_params, version, program=prog)
            srv.cache.prune("policy", self.keep_versions)
            reached = 0
            if self.fleet is not None:
                reached = self.fleet.publish_policy(actor_params, version)
        publish_s = time.monotonic() - t0
        with self._lock:
            self._stats["publishes"] += 1
            self._stats["last_publish_s"] = publish_s
            self._stats["last_version"] = int(version)
        obs.counter_add("policy_publishes")
        _event("policy_publish", version=int(version),
               export_s=round(export_s, 6),
               swap_s=round(swap["swap_s"], 6),
               publish_s=round(publish_s, 6), fleet_reached=reached)
        return {"version": int(version), "export_s": export_s,
                "swap_s": swap["swap_s"], "publish_s": publish_s,
                "fleet_reached": reached}

    def stats(self) -> dict:
        with self._lock:
            return dict(self._stats)


# ---------------------------------------------------------------------------
# the learner beside the server
# ---------------------------------------------------------------------------

class ServingLearner:
    """SAC learner over the mesh-sharded versioned replay, fed by the
    server tee and publishing through a :class:`PolicyPublisher`.

    ``version`` is the learner's LAST PUBLISHED version: transitions
    teed from the current serving snapshot carry it and get IMPACT
    weight exactly 1.0; transitions from older snapshots are stale and
    get the clipped importance ratio.  ``cfg.is_clip``/``cfg.ere_eta``
    should be armed for the lifecycle regime (the driver's defaults)."""

    def __init__(self, cfg, seed: int = 0, n_shards: int = 4,
                 publisher: Optional[PolicyPublisher] = None,
                 publish_every: int = 8, ingest_chunk: int = 16):
        import jax

        from smartcal_tpu.rl import replay as rp
        from smartcal_tpu.rl import replay_sharded as rps
        from smartcal_tpu.rl import sac

        self.cfg = cfg
        self.publisher = publisher
        self.publish_every = int(publish_every)
        self.ingest_chunk = int(ingest_chunk)
        self.key = jax.random.PRNGKey(seed)
        self.key, k0 = jax.random.split(self.key)
        self.state = sac.sac_init(k0, cfg)
        self._spec = rp.versioned_spec(
            rp.transition_spec(cfg.obs_dim, cfg.n_actions))
        self.buffer = rps.place_on_mesh(
            rps.replay_init(cfg.mem_size, self._spec, n_shards))
        self._rps = rps
        self._add = jax.jit(lambda buf, tr: rps.replay_add_batch(buf, tr))
        self._learn = jax.jit(
            lambda st, buf, key, ver: sac.learn(cfg, st, buf, key,
                                                learner_version=ver))
        self._pending: list = []
        self.version = 0
        self.learns = 0
        self.ingested = 0
        self.last_metrics: dict = {}

    @property
    def actor_params(self):
        return self.state.actor_params

    def warm(self) -> None:
        """Compile the ingest and learn programs BEFORE the serving
        window opens, so the steady state stays at zero compile events:
        one fixed-chunk store against a discarded buffer copy, then TWO
        real (empty-ring no-op) steps, then a warm re-publish of the
        current version.

        Two steps, not one: the first learn's inputs are the uncommitted
        init state + mesh-placed ring, but its OUTPUTS come back
        mesh-sharded (GSPMD propagates the ring's NamedSharding to every
        output), so the second call sees a different argument mapping
        and compiles a second executable — the sharding fixed point.
        Both executables must exist before the window or the second one
        compiles mid-serving.  ``lax.cond`` compiles both the learn and
        no-learn branches either way, and the no-learn branch returns
        state/ring bitwise unchanged, so warming through the REAL step
        path is a value-level no-op.  The warm publish (when a publisher
        is wired) re-publishes the current version so the exported
        policy's ``call_exported`` dispatch is also compiled against the
        learner's mesh-sharded params — the first real hot-swap would
        otherwise pay that compile in-window."""
        import jax

        fake = {k: np.zeros((self.ingest_chunk,) + tuple(shape), dtype)
                for k, (shape, dtype) in self._spec.items()}
        jax.block_until_ready(self._add(self.buffer, fake))  # discarded
        for _ in range(2):                   # sharding fixed point
            self.step()
        jax.block_until_ready((self.state, self.buffer))
        self.learns = 0                      # warm steps don't count
        if self.publisher is not None:
            self.publisher.publish(self.actor_params, self.version)

    def ingest(self, transitions: list) -> int:
        """Stage transition dicts and store them in FIXED-SIZE chunks
        (round-robin across the replay shards).  The fixed chunk keeps
        the jitted store at one compiled shape — a variable-size drain
        would re-trace per new batch size, breaking the zero-compile
        serving window.  Leftovers below a chunk stay pending for the
        next call; returns the number actually stored."""
        self._pending.extend(transitions)
        stored = 0
        while len(self._pending) >= self.ingest_chunk:
            batch = self._pending[:self.ingest_chunk]
            del self._pending[:self.ingest_chunk]
            flat = {k: np.stack([np.asarray(t[k]) for t in batch])
                    for k in batch[0]}
            self.buffer = self._add(self.buffer, flat)
            stored += len(batch)
        self.ingested += stored
        return stored

    def step(self, pull_metrics: bool = False) -> Optional[dict]:
        """One fused learn step at the current learner version (a no-op
        inside the jitted cond until the buffer holds a batch)."""
        import jax

        self.key, k = jax.random.split(self.key)
        self.state, self.buffer, metrics = self._learn(
            self.state, self.buffer, k,
            np.int32(self.version))
        self.learns += 1
        if pull_metrics:
            host = {k_: float(v) for k_, v in
                    jax.device_get(metrics).items()
                    if np.ndim(v) == 0}
            self.last_metrics = host
            return host
        return None

    def maybe_publish(self) -> Optional[dict]:
        """Publish version N+1 every ``publish_every`` learns (once the
        buffer has actually learned something)."""
        if (self.publisher is None or self.learns == 0
                or self.learns % self.publish_every != 0):
            return None
        if int(self.buffer.cntr) < self.cfg.batch_size:
            return None                  # nothing learned yet: hold fire
        self.version += 1
        return self.publisher.publish(self.actor_params, self.version)

    def staleness(self) -> dict:
        """Host staleness profile of the ring vs the published version
        (the lifecycle gauge source)."""
        return self._rps.version_staleness(self.buffer, self.version)
