"""Request router: bounded admission + deadline-aware micro-batching.

Incoming calibration jobs are heterogeneous — different direction
counts K, per-direction rho, ADMM iteration budgets — but since PR 9
every one of those is a TRACED operand of the batched solve, so any mix
packs into the same compiled program.  The router's job is purely
temporal: admit or shed (bounded queue — the overload half of the
circuit breaker), then gather admitted jobs into lane-sized batches
under a flush policy:

* FULL LANES — a batch of ``lanes`` jobs dispatches immediately;
* MAX WAIT — the first job of a batch never waits longer than
  ``max_wait_s`` for company;
* DEADLINE PULL — a job with an SLO deadline pulls the flush earlier,
  leaving (estimated) service time before its deadline.  The estimate
  is an EWMA of observed batch service times, fed back by the server.

Shed decisions are STRUCTURED: a ``serve_shed`` event (+ counter) with
the reason, never a silent drop — load generators and the SLO report
count them against the offered rate.
"""

from __future__ import annotations

import dataclasses
import itertools
import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, List, Optional

import numpy as np

from smartcal_tpu import obs

_ids = itertools.count()


class ShedError(RuntimeError):
    """A job the server refused to admit (queue full / circuit open)."""

    def __init__(self, reason: str, depth: Optional[int] = None):
        super().__init__(f"job shed: {reason}"
                         + (f" (queue depth {depth})"
                            if depth is not None else ""))
        self.reason = reason
        self.depth = depth


@dataclasses.dataclass
class Job:
    """One calibration request.

    ``episode`` is a backend ``Episode`` padded to the server's M
    directions; ``k`` is the live direction count (the mask length).
    ``rho``/``rho_spatial`` are (k,) or None — None asks the policy (or
    the server default) to pick.  ``maxiter`` overrides the ADMM
    iteration budget (traced, so any mix shares the compile).
    ``deadline_s`` is the SLO budget from submission.  ``obs_vec`` is an
    optional flattened observation for the policy forward."""

    episode: Any
    k: int
    rho: Optional[np.ndarray] = None
    rho_spatial: Optional[np.ndarray] = None
    maxiter: Optional[int] = None
    deadline_s: Optional[float] = None
    obs_vec: Optional[np.ndarray] = None
    warm: bool = False              # warmup probe: excluded from SLO stats
    job_id: int = dataclasses.field(default_factory=lambda: next(_ids))
    t_submit: float = dataclasses.field(default_factory=time.monotonic)
    future: Future = dataclasses.field(default_factory=Future)
    # times the fleet router re-dispatched this job after losing its
    # replica mid-flight (serve.fleet; bounded by max_requeues)
    requeues: int = 0
    # W3C-style trace carrier ({"trace": ..., "span": ...}) minted at
    # fleet admission (obs/tracectx.py): crosses the process boundary
    # in the job payload so replica-side events join the request's tree
    trace: Optional[dict] = None
    # policy version the server held when this job was ADMITTED
    # (stamped in CalibServer.submit).  A hot-swap can land between
    # admission and execution, so the serve_request event reports this
    # alongside the version that actually acted — never silently just
    # the new one.  None until a versioned server stamps it.
    version_admitted: Optional[int] = None


@dataclasses.dataclass
class JobResult:
    """What a resolved job future carries back to the client."""

    job_id: int
    lane: int
    batch_id: int
    sigma_res: float
    sigma_data_img: float
    sigma_res_img: float
    img_std: float
    degraded: bool
    queue_wait_s: float
    service_s: float
    total_s: float
    # served past its SLO deadline (completed anyway — deadline misses
    # and sheds are DISJOINT populations in the load-gen accounting)
    deadline_miss: bool = False


class MicroBatcher:
    """Bounded admission queue + the flush policy above.  Thread-safe:
    any number of submitter threads, one batch-worker consumer."""

    def __init__(self, lanes: int, max_wait_s: float = 0.05,
                 max_queue: int = 64, service_est_s: float = 0.5):
        self.lanes = int(lanes)
        self.max_wait_s = float(max_wait_s)
        self._jobs: "queue.Queue[Job]" = queue.Queue(
            maxsize=max(1, int(max_queue)))
        self._lock = threading.Lock()
        self._accepted = 0
        self._shed = 0
        self._service_est_s = float(service_est_s)

    # -- submitter side ----------------------------------------------------
    def submit(self, job: Job) -> Future:
        """Admit ``job`` (returns its future) or raise :class:`ShedError`
        with a structured reject event when the bounded queue is full."""
        try:
            self._jobs.put_nowait(job)
        except queue.Full:
            depth = self._jobs.qsize()
            with self._lock:
                self._shed += 1
            obs.counter_add("serve_shed")
            obs.note_shed()             # flight recorder: burst detection
            rl = obs.active()
            if rl is not None:
                rl.log("serve_shed", job_id=job.job_id, reason="queue_full",
                       depth=depth)
            raise ShedError("queue_full", depth=depth) from None
        with self._lock:
            self._accepted += 1
        obs.counter_add("serve_admitted")
        obs.gauge_set("serve_queue_depth", self._jobs.qsize())
        return job.future

    # -- worker side -------------------------------------------------------
    def next_batch(self, timeout: float = 0.2) -> List[Job]:
        """Block up to ``timeout`` for a first job, then gather until the
        flush policy fires.  Returns [] on an idle tick."""
        try:
            first = self._jobs.get(timeout=timeout)
        except queue.Empty:
            return []
        batch = [first]
        t0 = time.monotonic()
        while len(batch) < self.lanes:
            wait = self._flush_at(batch, t0) - time.monotonic()
            if wait <= 0:
                break
            try:
                batch.append(self._jobs.get(timeout=wait))
            except queue.Empty:
                break
        obs.gauge_set("serve_batch_lanes", len(batch))
        obs.gauge_set("serve_queue_depth", self._jobs.qsize())
        return batch

    def _flush_at(self, batch: List[Job], t0: float) -> float:
        """Monotonic instant this batch must dispatch: first-job max-wait,
        pulled earlier by any member's deadline minus the service
        estimate (never hold a job past the slack its SLO leaves)."""
        flush = t0 + self.max_wait_s
        est = self.service_estimate_s()
        for j in batch:
            if j.deadline_s is not None:
                flush = min(flush, j.t_submit + j.deadline_s - est)
        return flush

    def note_service_time(self, seconds: float) -> None:
        """Feed one observed batch service time into the EWMA the
        deadline pull reads (called by the server per batch)."""
        with self._lock:
            self._service_est_s += 0.3 * (float(seconds)
                                          - self._service_est_s)

    def service_estimate_s(self) -> float:
        with self._lock:
            return self._service_est_s

    def depth(self) -> int:
        return self._jobs.qsize()

    def stats(self) -> dict:
        with self._lock:
            return {"accepted": self._accepted, "shed": self._shed,
                    "service_est_s": round(self._service_est_s, 4)}

    def drain(self) -> List[Job]:
        """Remove and return every queued job (shutdown: fail them
        explicitly rather than stranding their futures)."""
        out = []
        while True:
            try:
                out.append(self._jobs.get_nowait())
            except queue.Empty:
                return out
