"""Replicated CalibServer fleet behind a deadline-aware front door.

One :class:`~smartcal_tpu.serve.server.CalibServer` is one batch worker
— ~7.5 jobs/s on the CPU tier (results/serve_r14.json), a demo.  This
module scales the service HORIZONTALLY: N replicas, each a spawned OS
process running its own ``CalibServer``, supervised with the PR 12
process-actor machinery transferred from actors to replicas — the
framed CRC-checked transport of :mod:`smartcal_tpu.runtime.ipc`,
heartbeat supervision, and backoff-restart accounting via
:class:`~smartcal_tpu.runtime.supervisor.RestartTracker` — behind a
:class:`FleetRouter` front door doing deadline-aware least-loaded
dispatch on each replica's streamed queue-depth / batch-fill gauges.

Scale-out stays cheap because every replica shares ONE on-disk AOT
``ExportCache`` + persistent-XLA cache tree: replica N's cold start is
every replica's warm start (seconds, not half a minute), which is what
makes load-driven autoscale viable — :class:`AutoscalePolicy` spawns a
replica on sustained queue pressure and reaps one on sustained idle.

Failure domains are per-replica, never fleet-wide:

* a replica crash costs only its in-flight jobs: the router reclaims
  that replica's pending table and re-dispatches each job (at most
  ``max_requeues`` times) to a survivor, shedding with a structured
  ``replica_lost`` reason only when no survivor can take it;
* a replica past ``max_restarts`` is marked failed — ITS circuit opens;
  the fleet sheds ``fleet_down`` only when no live replica remains, and
  ``fleet_saturated`` when every live replica's dispatch outbox is full.

Message vocabulary (framed via :mod:`~smartcal_tpu.runtime.ipc`;
tuples, kind first):

* router -> replica: ``("job", payload_dict)``, ``("weights",
  {"version", "params"})`` (policy hot-swap publication, latest-wins
  per replica — see :meth:`FleetRouter.publish_policy`), ``("stop",)``
* replica -> router: ``("ready", warmup_summary)``,
  ``("beat", gauges)``, ``("result", job_id, result_dict)``,
  ``("job_shed", job_id, reason)``, ``("job_failed", job_id, repr)``,
  ``("error", repr)``

The module imports no jax and no backend at import time: stub-server
replicas (tests) pay only the numpy/obs import, and the real server
factory (:func:`make_calib_server`) defers everything heavy until it
runs inside the worker process.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import os
import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from smartcal_tpu import obs
from smartcal_tpu.obs import tracectx
from smartcal_tpu.runtime import faults as rt_faults
from smartcal_tpu.runtime import ipc
from smartcal_tpu.runtime.backoff import BackoffPolicy
from smartcal_tpu.runtime.supervisor import RestartTracker, _to_host

from .router import Job, JobResult, ShedError

# Job fields that cross the process boundary (future/warm stay local:
# the future is the parent-side handle, and warmup probes never route).
# ``trace`` is the W3C carrier minted at fleet admission — it crosses
# so replica-side events join the request's span tree.
_JOB_FIELDS = ("k", "rho", "rho_spatial", "maxiter", "deadline_s",
               "obs_vec", "job_id", "t_submit", "requeues", "trace")


def _event(name: str, **fields) -> None:
    rl = obs.active()
    if rl is not None:
        rl.log(name, **fields)


# ---------------------------------------------------------------------------
# worker side (runs inside each spawned replica process)
# ---------------------------------------------------------------------------

def make_calib_server(tier: dict, M: int, lanes: int, cache_dir: str,
                      policy_seed: Optional[int] = None,
                      max_wait_s: float = 0.05, max_queue: int = 64,
                      deadline_default_s: Optional[float] = None,
                      **server_kw):
    """Picklable server factory for real replicas: builds a
    ``RadioBackend`` + ``CalibServer`` against the SHARED ``cache_dir``
    (AOT programs under ``programs/``, persistent XLA under ``xla/`` —
    armed here, before the process's first compile, because jax latches
    the cache decision at first use).  ``tier`` is the backend kwargs
    dict (see ``SERVE_TIERS`` in :mod:`~smartcal_tpu.serve.loadgen`).
    """
    del deadline_default_s               # reserved for router-side SLOs
    from .export import enable_compile_cache

    enable_compile_cache(f"{cache_dir}/xla")
    from smartcal_tpu.envs import radio

    backend = radio.RadioBackend(**tier)
    policy = None
    if policy_seed is not None:
        from smartcal_tpu.rl import sac

        obs_dim = backend.npix * backend.npix + (M + 1) * 7
        agent = sac.SACAgent(
            sac.SACConfig(obs_dim=obs_dim, n_actions=2 * M),
            seed=policy_seed, name_prefix="fleet")
        policy = (agent.cfg, agent.state.actor_params)
    from .server import CalibServer

    return CalibServer(backend, M=M, lanes=lanes, cache_dir=cache_dir,
                       policy=policy, max_wait_s=max_wait_s,
                       max_queue=max_queue, **server_kw)


class SleepServer:
    """Stdlib-only replica server whose service is a timed sleep:
    ``lanes`` worker threads each hold one job for ``service_s``.

    This is the ROUTER-CAPACITY harness, not a solver: sleeps overlap
    perfectly across processes even on a one-core host, so a fleet of
    these measures the front door itself — dispatch + IPC + pending
    bookkeeping per job — as a jobs/s ceiling that real replicas can
    approach but never beat.  ``tools/serve_fleet.py --stub`` sweeps it
    next to the real-CalibServer fleet for exactly that comparison."""

    def __init__(self, lanes: int = 2, service_s: float = 0.05,
                 max_queue: int = 128):
        import queue as _queue

        self.lanes = int(lanes)
        self.service_s = float(service_s)
        self._q: "queue.Queue" = _queue.Queue(
            maxsize=max(1, int(max_queue)))
        self._stop = threading.Event()
        self._served = 0
        self._slock = threading.Lock()
        self._workers: List[threading.Thread] = []

        outer = self

        class _Batcher:
            def depth(self):
                return outer._q.qsize()

            def service_estimate_s(self):
                return outer.service_s

        self.batcher = _Batcher()

    def warmup(self, seed: int = 0) -> dict:
        return {"wall_s": 0.0, "sources": {"solve": "sleep"},
                "export_cache_hit": 0, "export_cache_miss": 0}

    def start(self) -> None:
        for i in range(self.lanes):
            t = threading.Thread(target=self._loop, daemon=True,
                                 name=f"sleep-lane{i}")
            t.start()
            self._workers.append(t)

    def submit(self, job: Job):
        try:
            self._q.put_nowait(job)
        except queue.Full:
            raise ShedError("queue_full",
                            depth=self._q.qsize()) from None
        return job.future

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                job = self._q.get(timeout=0.05)
            except queue.Empty:
                continue
            with self._slock:
                self._served += 1
                n = self._served
            # minimal serve instrumentation mirroring CalibServer: the
            # stub fleet must exercise the SAME trace-stitching path
            # (serve_request + a batch-tagged stage span) so loadgen
            # demonstrations don't need a real solver; the fault hook
            # makes one replica's injected slowdown visible here too
            t0 = time.monotonic()
            with obs.span("serve_solve", batch=n):
                rt_faults.maybe_delay("serve_batch", n)
                time.sleep(self.service_s)
            service = time.monotonic() - t0
            total = time.monotonic() - job.t_submit
            _event("serve_request", job_id=job.job_id, lane=0,
                   batch=n, k=job.k,
                   queue_wait_s=round(max(0.0, total - service), 6),
                   service_s=round(service, 6),
                   total_s=round(total, 6),
                   **tracectx.child_fields(job.trace))
            job.future.set_result(JobResult(
                job_id=job.job_id, lane=0, batch_id=n,
                sigma_res=float(job.k), sigma_data_img=0.0,
                sigma_res_img=0.0, img_std=0.0, degraded=False,
                queue_wait_s=round(max(0.0, total - service), 6),
                service_s=round(service, 6), total_s=round(total, 6),
                deadline_miss=(job.deadline_s is not None
                               and total > job.deadline_s)))

    def stop(self) -> None:
        self._stop.set()
        for t in self._workers:
            t.join(timeout=1.0)

    def stats(self) -> dict:
        with self._slock:
            served = self._served
        return {"batches": served, "served": served, "degraded": 0,
                "failed": 0, "deadline_miss": 0,
                "service_est_s": self.service_s, "circuit_open": False}


def make_sleep_server(**kw) -> SleepServer:
    """Picklable factory for the router-capacity stub fleet."""
    return SleepServer(**kw)


def sleep_worker_spec(lanes: int = 2, service_s: float = 0.05,
                      beat_s: float = 0.05) -> dict:
    return {"factory": "smartcal_tpu.serve.fleet:make_sleep_server",
            "kwargs": {"lanes": int(lanes), "service_s": float(service_s)},
            "lanes": int(lanes), "beat_s": float(beat_s)}


def calib_worker_spec(tier: dict, M: int, lanes: int, cache_dir: str,
                      **factory_kw) -> dict:
    """The picklable ``worker_spec`` for a real-CalibServer fleet."""
    return {
        "factory": "smartcal_tpu.serve.fleet:make_calib_server",
        "kwargs": dict(tier=dict(tier), M=int(M), lanes=int(lanes),
                       cache_dir=cache_dir, **factory_kw),
        "lanes": int(lanes),
    }


def _server_gauges(server) -> dict:
    """The load signals a replica streams in every beat frame.  The
    compile counter rides along so the driver can assert ZERO
    steady-state compiles FLEET-wide, not just in the parent."""
    st = server.stats()
    batches = st.get("batches", 0)
    c = obs.counters_snapshot()
    return {
        "queue_depth": int(server.batcher.depth()),
        "service_est_s": float(st.get("service_est_s",
                               server.batcher.service_estimate_s())),
        "batch_fill": round(st.get("served", 0)
                            / max(1, batches * server.lanes), 4),
        "circuit_open": bool(st.get("circuit_open", False)),
        "served": int(st.get("served", 0)),
        "failed": int(st.get("failed", 0)),
        "degraded": int(st.get("degraded", 0)),
        "deadline_miss": int(st.get("deadline_miss", 0)),
        "compile_events": float(c.get("jax_compile_events", 0.0)),
        # which policy version this replica is serving (-1: no policy /
        # stub server) — the lifecycle driver's convergence signal that
        # a fleet-wide publication actually landed everywhere
        "policy_version": int(getattr(server, "policy_version", -1)),
    }


def _submit_remote(server, payload: dict, send,
                   replica_id: int = 0) -> None:
    """Rebuild the parent's Job (same job_id, same t_submit — monotonic
    clocks are system-wide on Linux, so queue-wait/deadline accounting
    spans the process boundary) and route its eventual outcome back as
    a result / job_shed / job_failed frame."""
    jid = payload["job_id"]
    job = Job(episode=payload["episode"],
              **{f: payload[f] for f in _JOB_FIELDS
                 if f in payload})
    # the admission hop gets its own span: serve_admit's wall t minus
    # fleet_dispatch's wall t (offset-corrected by the collector) is
    # the request's IPC + outbox time; the request's later events
    # chain under the admit span, not the remote root
    tf = tracectx.child_fields(job.trace)
    if tf:
        _event("serve_admit", job_id=jid, replica=replica_id,
               requeues=job.requeues, **tf)
        job.trace = {"trace": str(tf["trace"]), "span": str(tf["span"])}
    try:
        fut = server.submit(job)
    except ShedError as e:
        send(("job_shed", jid, e.reason), trace=job.trace)
        return
    except Exception as e:
        send(("job_failed", jid, repr(e)), trace=job.trace)
        return

    def _done(f, jid=jid):
        try:
            r = f.result()
        except ShedError as e:
            send(("job_shed", jid, e.reason), trace=job.trace)
            return
        except BaseException as e:      # noqa: BLE001 — relayed, not raised
            send(("job_failed", jid, repr(e)), trace=job.trace)
            return
        send(("result", jid, dataclasses.asdict(r)), trace=job.trace)

    fut.add_done_callback(_done)


class _WeightsPublisher(threading.Thread):
    """Replica-side policy-swap worker: weight frames land LATEST-WINS
    in a single slot and the swap (warm forward + locked pointer flip
    via ``CalibServer.swap_policy``) runs on this thread — never on the
    replica's frame-dispatch loop, so a beat or a job frame is never
    delayed because a snapshot arrived.  A burst of publications
    collapses to the newest version; each replica swaps independently
    (the fleet is never barriered on a publication)."""

    def __init__(self, server, replica_id: int):
        super().__init__(name=f"replica{replica_id}-weights", daemon=True)
        self.server = server
        self.replica_id = int(replica_id)
        self._lock = threading.Lock()
        self._slot = None                # latest-wins (version, params)
        self._wake = threading.Event()
        # NOT "_stop": threading.Thread.join(timeout=...) calls its own
        # private _stop() and an Event there makes any timed join raise
        self._stop_ev = threading.Event()
        self.swaps = 0

    def offer(self, version: int, params) -> None:
        with self._lock:
            self._slot = (int(version), params)
        self._wake.set()

    def request_stop(self) -> None:
        self._stop_ev.set()
        self._wake.set()

    def run(self) -> None:
        while not self._stop_ev.is_set():
            self._wake.wait(timeout=0.2)
            self._wake.clear()
            with self._lock:
                item, self._slot = self._slot, None
            if item is None:
                continue
            version, params = item
            try:
                self.server.swap_policy(params, version)
                self.swaps += 1
            except Exception as e:       # a bad frame must not kill the
                obs.counter_add("fleet_weights_swap_errors")  # replica
                _event("fleet_weights_swap_error",
                       replica=self.replica_id, version=version,
                       error=repr(e))


def replica_worker_main(conn, replica_id: int, spec: dict) -> None:
    """Entry point of a spawned replica process: pin the platform
    (same sitecustomize caveat as ``ipc.worker_main``), attach the
    simulated host, build the server from its picklable factory spec,
    warm up against the shared cache, then loop — drain job/stop
    frames, stream gauge beats."""
    platform = spec.get("platform", "cpu")
    if platform:
        os.environ["JAX_PLATFORMS"] = platform
        try:
            import jax

            jax.config.update("jax_platforms", platform)
        except Exception:
            pass
    if int(spec.get("n_hosts", 1)) > 1:
        # only a multi-host topology needs the simulated attach (and
        # the jax import it drags in — single-host stub replicas stay
        # jax-free)
        from smartcal_tpu.parallel import multihost

        multihost.attach_simulated(spec.get("host_id", 0),
                                   spec.get("n_hosts", 1))
    rl = None
    if spec.get("metrics"):
        rl = obs.RunLog(spec["metrics"], run_id=f"replica{replica_id}")
        obs.activate(rl)
        # fleet workers fly with the recorder armed by default: a
        # crash/circuit-open/shed-burst dumps the last events next to
        # the replica's own JSONL stream
        if spec.get("flight_recorder", True):
            obs.arm_flight_recorder(
                os.path.dirname(spec["metrics"]) or ".")
    obs.install_compile_listener()
    if spec.get("faults"):
        # per-replica deterministic fault plan (the injected-slowdown
        # demonstration targets exactly one replica of the fleet)
        rt_faults.install(rt_faults.FaultPlan(**dict(spec["faults"])))

    send_lock = threading.Lock()

    def send(msg, trace=None) -> bool:
        env = dict(trace) if trace else {}
        env["t"] = round(time.time(), 6)  # clock-offset handshake
        try:
            with send_lock:              # done-callbacks run on the
                ipc.send_msg(conn, msg, trace=env)  # batch worker;
            return True                  # beats on main
        except (OSError, BrokenPipeError, ValueError, EOFError):
            return False

    server = None
    try:
        factory = ipc.resolve_factory(spec["factory"])
        server = factory(**(spec.get("kwargs") or {}))
        summary = server.warmup(seed=int(spec.get("seed", 0)))
        server.start()
        send(("ready", summary))
    except BaseException as e:          # noqa: BLE001 — death IS the signal
        _event("replica_fatal", replica=replica_id, error=repr(e))
        obs.flush_flight_recorder("crash", {"error": repr(e)})
        send(("error", repr(e)))
        return
    beat_s = float(spec.get("beat_s", 0.1))
    last_beat = 0.0
    weights_pub: Optional[_WeightsPublisher] = None
    try:
        while True:
            if conn.poll(beat_s):
                try:
                    msg, _mtrace = ipc.recv_msg_traced(conn)
                except ipc.CorruptPayloadError as e:
                    # router->replica corruption: skip the one frame,
                    # but name its trace if the prelude survived
                    _event("ipc_corrupt_payload", side="replica",
                           replica=replica_id, error=repr(e),
                           **tracectx.fields_of(e.trace))
                    continue
                if msg[0] == "stop":
                    break
                if msg[0] == "job":
                    _submit_remote(server, msg[1], send, replica_id)
                elif msg[0] == "weights":
                    # policy hot-swap publication: hand the snapshot to
                    # the latest-wins swap worker (servers without a
                    # policy — stubs — ignore the frame, counted)
                    if weights_pub is None \
                            and hasattr(server, "swap_policy"):
                        weights_pub = _WeightsPublisher(server,
                                                        replica_id)
                        weights_pub.start()
                    if weights_pub is not None:
                        weights_pub.offer(msg[1]["version"],
                                          msg[1]["params"])
                    else:
                        obs.counter_add("fleet_weights_ignored")
            now = time.monotonic()
            if now - last_beat >= beat_s:
                last_beat = now
                send(("beat", _server_gauges(server)))
    except (EOFError, OSError, BrokenPipeError):
        pass                             # router gone: nothing to report
    finally:
        if weights_pub is not None:
            weights_pub.request_stop()
        try:
            server.stop()
        except Exception:
            pass
        if rl is not None:
            try:
                obs.flush_counters()
                while obs.active() is not None:
                    obs.deactivate()
                rl.close()           # flush the buffered tail — a short
            except Exception:        # run otherwise fits entirely in the
                pass                 # RunLog buffer and leaves no stream


# ---------------------------------------------------------------------------
# parent side
# ---------------------------------------------------------------------------

class _Replica(threading.Thread):
    """Parent-side replica slot: the spawned worker process, this pump
    thread (sole reader of the duplex pipe), and a FIFO sender thread
    (sole writer — jobs are NOT latest-wins like weights snapshots, so
    the outbox is a bounded queue, not the `_ProcessActor` single
    slot).  Duck-types the supervision surface the router polls
    (``last_beat`` / ``error`` / ``healthy``) plus the dispatch surface
    it ranks on (``gauges`` / ``dispatch`` / ``take_pending``)."""

    def __init__(self, router: "FleetRouter", replica_id: int, spec: dict):
        super().__init__(name=f"{router.name}-r{replica_id}-pump",
                         daemon=True)
        self.router = router
        self.replica_id = int(replica_id)
        self.spec = dict(spec)
        self.lanes = int(spec.get("lanes", 1))
        self._lock = threading.Lock()
        self._pending: Dict[int, Job] = {}   # job_id -> parent-side Job
        self._gauges = {
            "queue_depth": 0, "batch_fill": 0.0, "circuit_open": False,
            "service_est_s": float(spec.get("service_est_s", 0.5)),
        }
        # last-received-frame summaries: the PARENT-side black box for
        # this replica.  A SIGKILLed worker can never flush its own
        # ring, so the crashed replica's final observable events are
        # what the parent saw — dumped by the router on death detection.
        self._frames: "collections.deque" = collections.deque(
            maxlen=int(spec.get("frame_ring", 64)))
        # clock-offset handshake state (pump thread only): minimum of
        # (parent recv wall - peer send wall) over received envelopes
        self._offset_min: Optional[float] = None
        self._offset_logged: Optional[float] = None
        self._offset_last_log = 0.0
        self.t_spawn = time.monotonic()
        self.last_beat = time.monotonic()
        self.ready = threading.Event()
        self.ready_summary: Optional[dict] = None
        self.stop_event = threading.Event()
        self.error: Optional[BaseException] = None
        self._outbox: "queue.Queue[bytes]" = queue.Queue(
            maxsize=max(1, int(spec.get("dispatch_cap", 64))))
        self._sender: Optional[threading.Thread] = None
        self.proc = None
        self.conn = None

    # -- lifecycle ---------------------------------------------------------
    def _launch(self) -> None:
        import multiprocessing as mp

        ctx = mp.get_context("spawn")
        self.conn, child = ctx.Pipe(duplex=True)
        self.proc = ctx.Process(
            target=replica_worker_main,
            args=(child, self.replica_id, self.spec),
            name=f"{self.router.name}-r{self.replica_id}", daemon=True)
        self.proc.start()
        child.close()                    # parent keeps one end only

    def start(self) -> None:
        self._launch()
        self._sender = threading.Thread(
            target=self._send_loop,
            name=f"{self.router.name}-r{self.replica_id}-send", daemon=True)
        self._sender.start()
        super().start()

    def healthy(self) -> bool:
        """Pump alive and no terminal error — the slot can still speak."""
        return self.is_alive() and self.error is None

    def request_stop(self) -> None:
        try:
            self._outbox.put(ipc.frame_payload(("stop",)), timeout=0.2)
        except queue.Full:
            pass                         # sender drains; EOF stops worker
        self.stop_event.set()

    def hard_kill(self) -> None:
        try:
            if self.proc is not None and self.proc.is_alive():
                self.proc.kill()
        except Exception:
            pass

    def finalize(self, timeout: float = 2.0) -> None:
        if self.proc is None:
            return
        try:
            self.proc.join(timeout=timeout)
            if self.proc.is_alive():
                self.proc.terminate()
                self.proc.join(timeout=1.0)
        except Exception:
            pass

    def shutdown(self, timeout: float = 5.0) -> None:
        self.request_stop()
        if self.ident is not None:
            self.join(timeout=timeout)
        self.finalize(timeout=max(1.0, timeout / 2))

    # -- dispatch surface --------------------------------------------------
    def gauges(self) -> dict:
        with self._lock:
            g = dict(self._gauges)
            g["pending"] = len(self._pending)
        return g

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    def dispatch(self, job: Job) -> bool:
        """Stage ``job`` toward the worker; False when this replica's
        bounded dispatch outbox is full (the router tries the next
        candidate — per-replica back-pressure must never block the
        front door)."""
        blob = ipc.frame_payload(("job", _job_payload(job)),
                                 trace=job.trace)
        with self._lock:
            self._pending[job.job_id] = job
        try:
            self._outbox.put_nowait(blob)
        except queue.Full:
            with self._lock:
                self._pending.pop(job.job_id, None)
            return False
        return True

    def publish(self, blob: bytes) -> bool:
        """Stage a pre-framed weights frame toward the worker; False
        when the outbox is full — the frame is DROPPED, never retried:
        the next publication supersedes it, and a weight frame must
        never occupy outbox capacity a job dispatch needs."""
        try:
            self._outbox.put_nowait(blob)
        except queue.Full:
            return False
        return True

    def take_pending(self) -> List[Job]:
        """Remove and return every in-flight job (crash reclaim)."""
        with self._lock:
            jobs = list(self._pending.values())
            self._pending.clear()
        return jobs

    def _pop_pending(self, job_id: int) -> Optional[Job]:
        with self._lock:
            return self._pending.pop(job_id, None)

    # -- threads -----------------------------------------------------------
    def _send_loop(self) -> None:
        while True:
            try:
                blob = self._outbox.get(timeout=0.2)
            except queue.Empty:
                if self.stop_event.is_set():
                    return
                continue
            try:
                ipc.send_blob(self.conn, blob)
            except (OSError, BrokenPipeError, ValueError):
                return

    def _note_frame(self, kind: str, detail: dict) -> None:
        rec = {"t": round(time.time(), 3), "kind": kind,
               "replica": self.replica_id}
        rec.update(detail)
        with self._lock:
            self._frames.append(rec)

    def _note_envelope(self, trace: Optional[dict]) -> None:
        """Feed one received envelope into the clock-offset estimate:
        min over frames of (recv wall - send wall) bounds the peer's
        clock ahead-ness by the one-way delay.  Logged periodically as
        a ``clock_offset`` event (the collector's skew correction)."""
        if not trace or "t" not in trace:
            return
        try:
            delta = time.time() - float(trace["t"])
        except (TypeError, ValueError):
            return
        if self._offset_min is None or delta < self._offset_min:
            self._offset_min = delta
        now = time.monotonic()
        if (self._offset_logged != self._offset_min
                and now - self._offset_last_log >= 1.0):
            self._offset_last_log = now
            self._offset_logged = self._offset_min
            # offset_s: ADD to the peer's wall timestamps to land on
            # the parent's clock (<= one-way delay of the best frame)
            self.router._log("clock_offset",
                             peer=f"replica{self.replica_id}",
                             replica=self.replica_id,
                             offset_s=round(-self._offset_min, 6))

    def blackbox(self, reason: str, directory: str) -> Optional[str]:
        """Dump this slot's received-frame ring (the parent-side black
        box) to ``blackbox_replica<rid>.jsonl`` in ``directory``."""
        with self._lock:
            frames = list(self._frames)
        if not frames:
            return None
        try:
            os.makedirs(directory, exist_ok=True)
            path = os.path.join(
                directory, f"blackbox_replica{self.replica_id}.jsonl")
            header = {"t": round(time.time(), 3),
                      "event": "blackbox_flush", "reason": reason,
                      "side": "parent", "replica": self.replica_id,
                      "n_events": len(frames)}
            with open(path, "a") as fh:
                fh.write(json.dumps(header) + "\n")
                for rec in frames:
                    fh.write(json.dumps(obs.sanitize(rec)) + "\n")
            return path
        except OSError:
            return None

    def run(self) -> None:
        r = self.router
        while not self.stop_event.is_set():
            try:
                if not self.conn.poll(0.2):
                    if self.proc is not None and not self.proc.is_alive() \
                            and not self.conn.poll(0):
                        if self.error is None:
                            self.error = RuntimeError(
                                f"replica process exited (code "
                                f"{self.proc.exitcode})")
                        return
                    continue
                msg, mtrace = ipc.recv_msg_traced(self.conn)
            except ipc.CorruptPayloadError as e:
                # a replica died mid-send (or shipped garbage): drop the
                # one broken frame, log it — WITH the trace the frame's
                # surviving prelude names, so the merged timeline shows
                # which request's frame was lost instead of a bare drop
                r._log("ipc_corrupt_payload", replica=self.replica_id,
                       error=repr(e), **tracectx.fields_of(e.trace))
                self._note_frame("corrupt", {"error": repr(e),
                                             **tracectx.fields_of(e.trace)})
                obs.counter_add("ipc_corrupt_payloads")
                continue
            except (EOFError, OSError):
                if not self.stop_event.is_set() and self.error is None:
                    code = (self.proc.exitcode if self.proc is not None
                            else None)
                    self.error = RuntimeError(
                        f"replica channel closed (exit code {code})")
                return
            self.last_beat = time.monotonic()
            self._note_envelope(mtrace)
            kind = msg[0]
            if kind == "ready":
                self.ready_summary = msg[1]
                self.ready.set()
                self._note_frame("ready", {})
            elif kind == "beat":
                with self._lock:
                    self._gauges.update(msg[1])
                self._note_frame("beat", {k: msg[1].get(k) for k in
                                          ("queue_depth", "served",
                                           "circuit_open")})
            elif kind == "result":
                job = self._pop_pending(msg[1])
                if job is not None and not job.future.done():
                    job.future.set_result(JobResult(**msg[2]))
                self._note_frame("result", {
                    "job_id": msg[1],
                    "total_s": msg[2].get("total_s"),
                    **tracectx.fields_of(mtrace)})
                r._note_result(self.replica_id, job, msg[2])
            elif kind == "job_shed":
                job = self._pop_pending(msg[1])
                self._note_frame("job_shed", {"job_id": msg[1],
                                              "reason": msg[2]})
                if job is not None:
                    r._reclaim(job, self.replica_id, msg[2])
            elif kind == "job_failed":
                job = self._pop_pending(msg[1])
                if job is not None and not job.future.done():
                    job.future.set_exception(RuntimeError(msg[2]))
                self._note_frame("job_failed", {"job_id": msg[1],
                                                "error": msg[2]})
                r._note_failed(self.replica_id, msg[1], msg[2])
            elif kind == "error":
                self.error = RuntimeError(msg[1])
                self._note_frame("error", {"error": msg[1]})
                return


def _job_payload(job: Job) -> dict:
    """The picklable half of a Job (device arrays pulled to host)."""
    d = {f: getattr(job, f) for f in _JOB_FIELDS}
    d["episode"] = _to_host(job.episode)
    return d


@dataclasses.dataclass(frozen=True)
class AutoscalePolicy:
    """Load-driven scale knobs: spawn a replica when the fleet-mean
    backlog per live replica stays at/above ``spawn_depth`` jobs for
    ``spawn_sustain_s``; reap the newest idle replica after
    ``reap_idle_s`` of a drained fleet.  ``cooldown_s`` separates
    consecutive scale events so one burst cannot thrash the fleet."""

    min_replicas: int = 1
    max_replicas: int = 8
    spawn_depth: float = 2.0
    spawn_sustain_s: float = 2.0
    reap_idle_s: float = 10.0
    cooldown_s: float = 5.0


class FleetRouter:
    """The front door (see module doc).  Lifecycle::

        router = FleetRouter(calib_worker_spec(...), replicas=4)
        router.start()                  # replica 0 builds the shared
        fut = router.submit(Job(...))   # cache; 1..N warm-start off it
        fut.result(timeout=...)
        router.stop()

    Dispatch ranks live replicas by load score ``(pending + queue_depth)
    / lanes`` with batch-fill as the tiebreak; a job with a deadline
    first narrows to replicas whose ETA fits its remaining slack,
    falling back to plain least-loaded when none does (degrade to a
    late answer, never shed a servable job).  ``replica_factory`` and
    ``clock`` are injectable for tests (scripted gauges, fake time).
    """

    def __init__(self, worker_spec: dict, replicas: int = 1, *,
                 hosts: int = 1, name: str = "calib-fleet",
                 heartbeat_timeout: float = 10.0, max_restarts: int = 3,
                 backoff: Optional[BackoffPolicy] = None, seed: int = 0,
                 max_requeues: int = 1,
                 autoscale: Optional[AutoscalePolicy] = None,
                 poll_s: float = 0.05, metrics_dir: Optional[str] = None,
                 replica_factory: Optional[Callable] = None,
                 slo: Optional["obs.SloBurnDetector"] = None,
                 clock: Callable[[], float] = time.monotonic):
        import random

        self.worker_spec = dict(worker_spec)
        self.name = name
        self.hosts = max(1, int(hosts))
        self.n_initial = int(replicas)
        self.heartbeat_timeout = float(heartbeat_timeout)
        self.max_requeues = int(max_requeues)
        self.autoscale = autoscale
        self.metrics_dir = metrics_dir
        self.slo = slo
        self._clock = clock
        self._poll_s = float(poll_s)
        self._factory = replica_factory or _Replica
        self._tracker = RestartTracker(
            max_restarts,
            backoff or BackoffPolicy(base_s=0.25, factor=2.0, max_s=10.0,
                                     jitter=0.25),
            rng=random.Random(seed))
        self._lock = threading.Lock()
        self._replicas: Dict[int, Any] = {}  # rid -> _Replica (current)
        self._next_rid = 0
        self._stats = {"submitted": 0, "dispatched": 0, "completed": 0,
                       "failed": 0, "requeued": 0, "shed": 0,
                       "shed_reasons": {}, "replica_restarts": 0,
                       "scale_ups": 0, "scale_downs": 0}
        self._rr = 0                     # dispatch tiebreak rotation
        self._reclaim_q: "queue.Queue" = queue.Queue()
        self._retired: List[Any] = []    # reaped replicas awaiting join
        self._stop_ev = threading.Event()
        self._sup: Optional[threading.Thread] = None
        self._over_since: Optional[float] = None
        self._idle_since: Optional[float] = None
        self._depth_ewma: Optional[float] = None
        self._last_scale = -1e18

    # -- topology ----------------------------------------------------------
    def replica_host(self, rid: int) -> int:
        """Simulated host of replica ``rid`` — round-robin, so scale-up
        replicas spread across hosts instead of piling onto the last."""
        return rid % self.hosts

    def _replica_spec(self, rid: int) -> dict:
        """The per-process worker spec for slot ``rid``: base spec +
        host pinning + this generation's metrics path + any
        ``per_replica`` overrides ({rid: {...}} in the base spec — the
        injected-slowdown demonstration targets one replica's fault
        plan without touching the rest of the fleet)."""
        spec = dict(self.worker_spec, host_id=self.replica_host(rid),
                    n_hosts=self.hosts)
        over = spec.pop("per_replica", None) or {}
        ov = over.get(rid, over.get(str(rid)))
        if ov:
            spec.update(dict(ov))
        if self.metrics_dir:
            spec["metrics"] = os.path.join(
                self.metrics_dir,
                f"replica{rid}-g{self._tracker.attempts(rid)}.jsonl")
        return spec

    def _spawn_replica(self):
        with self._lock:
            rid = self._next_rid
            self._next_rid += 1
        r = self._factory(self, rid, self._replica_spec(rid))
        r.start()
        with self._lock:
            self._replicas[rid] = r
        obs.gauge_set("fleet_replicas_alive", len(self._live()))
        return r

    def _respawn(self, rid: int):
        """Fresh process in an existing slot (same rid: restart
        accounting and the per-slot circuit stay attached)."""
        r = self._factory(self, rid, self._replica_spec(rid))
        r.start()
        with self._lock:
            self._replicas[rid] = r
        return r

    def _live(self) -> list:
        with self._lock:
            reps = list(self._replicas.values())
        return [r for r in reps if r.healthy()]

    # -- lifecycle ---------------------------------------------------------
    def start(self, warm_timeout_s: float = 300.0,
              stagger: bool = True) -> dict:
        """Spawn the initial replicas and wait until every one is warm.
        ``stagger`` (default) brings replica 0 up ALONE first so a cold
        shared cache is built exactly once; the rest then warm-start
        off it concurrently.  Returns {rid: warmup_summary}."""
        if self._sup is not None:
            raise RuntimeError("router already started")
        first = self._spawn_replica()
        if stagger:
            self._wait_ready([first], warm_timeout_s)
        rest = [self._spawn_replica() for _ in range(self.n_initial - 1)]
        self._wait_ready(rest + ([] if stagger else [first]),
                         warm_timeout_s)
        sup = threading.Thread(target=self._supervise,
                               name=f"{self.name}-router", daemon=True)
        self._sup = sup
        sup.start()
        return self.warmups()

    def _wait_ready(self, replicas: list, timeout_s: float) -> None:
        deadline = time.monotonic() + timeout_s
        for r in replicas:
            while not r.ready.wait(timeout=0.1):
                if not r.healthy():
                    raise RuntimeError(
                        f"replica {r.replica_id} died during warmup: "
                        f"{r.error!r}")
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"replica {r.replica_id} not ready after "
                        f"{timeout_s}s")

    def warmups(self) -> dict:
        with self._lock:
            reps = dict(self._replicas)
        return {rid: r.ready_summary for rid, r in reps.items()
                if r.ready_summary is not None}

    def stop(self, timeout: float = 10.0) -> None:
        """Stop every replica, then fail whatever is still pending with
        a structured ``shutdown`` shed."""
        self._stop_ev.set()
        if self._sup is not None:
            self._sup.join(timeout=timeout)
        with self._lock:
            reps = list(self._replicas.values())
            retired = list(self._retired)
        for r in reps:
            r.request_stop()
        for r in reps + retired:
            r.shutdown(timeout=timeout)
        for r in reps:
            for job in r.take_pending():
                self._shed_async(job, "shutdown")
        while True:
            try:
                job, _reason = self._reclaim_q.get_nowait()
            except queue.Empty:
                break
            self._shed_async(job, "shutdown")

    # -- request path ------------------------------------------------------
    def submit(self, job: Job):
        """Admit ``job`` (returns its future) or shed synchronously:
        ``shutdown`` / ``fleet_down`` (no live replica) /
        ``fleet_saturated`` (every live replica's outbox full)."""
        if self._stop_ev.is_set():
            self._shed_sync(job, "shutdown")
        with self._lock:
            self._stats["submitted"] += 1
        return self._dispatch(job)

    def _candidates(self) -> list:
        """Live, warm replicas whose per-slot circuit is closed."""
        out = []
        for r in self._live():
            if not r.ready.is_set():
                continue
            if self._tracker.tracked(r.replica_id):
                continue
            if r.gauges().get("circuit_open"):
                continue
            out.append(r)
        return out

    def _rank(self, cands: list, job: Job) -> list:
        """Deadline-aware least-loaded order.  ETA per replica is
        (backlog batches + 1) * service estimate; a deadline narrows to
        replicas that fit the job's remaining slack, falling back to
        everyone when none does."""
        now = self._clock()
        scored = []
        for r in cands:
            g = r.gauges()
            backlog = (g["pending"] + g["queue_depth"]) / max(1, r.lanes)
            eta = (backlog + 1.0) * max(1e-3, g["service_est_s"])
            scored.append((r, backlog, g.get("batch_fill", 0.0), eta))
        if job.deadline_s is not None:
            slack = job.deadline_s - (now - job.t_submit)
            fits = [s for s in scored if s[3] <= slack]
            if fits:
                scored = fits
        rr = self._rr
        self._rr = rr + 1
        scored.sort(key=lambda s: (s[1], s[2],
                                   (s[0].replica_id - rr) % 997))
        return [s[0] for s in scored]

    def _dispatch(self, job: Job, requeue: bool = False):
        if job.trace is None and obs.active() is not None:
            # mint the request's trace root at fleet admission — every
            # later event (serve_admit / serve_request / fleet_result,
            # on either side of the pipe) joins this tree.  A requeue
            # keeps the ORIGINAL carrier: same trace_id, annotated hop.
            job.trace = tracectx.new_root_carrier()
        cands = self._candidates()
        if not cands:
            if requeue:
                return self._shed_async(job, "fleet_down")
            self._shed_sync(job, "fleet_down")
        for r in self._rank(cands, job):
            if r.dispatch(job):
                with self._lock:
                    self._stats["dispatched"] += 1
                    if requeue:
                        self._stats["requeued"] += 1
                obs.counter_add("fleet_dispatch")
                _event("fleet_dispatch", job_id=job.job_id,
                       replica=r.replica_id, requeue=bool(requeue),
                       **tracectx.fields_of(job.trace))
                return job.future
        if requeue:
            return self._shed_async(job, "fleet_saturated")
        self._shed_sync(job, "fleet_saturated")

    def _requeue(self, job: Job, reason: str) -> None:
        """A replica lost/refused ``job`` after admission: re-dispatch
        to a survivor (bounded), else shed with the structured reason
        on the future the client already holds."""
        if job.future.done():
            return
        job.requeues += 1
        if job.requeues > self.max_requeues:
            self._shed_async(job, reason)
            return
        self._dispatch(job, requeue=True)

    def _shed_record(self, job: Job, reason: str) -> None:
        with self._lock:
            self._stats["shed"] += 1
            reasons = self._stats["shed_reasons"]
            reasons[reason] = reasons.get(reason, 0) + 1
        obs.counter_add("serve_shed")
        obs.note_shed()                 # flight recorder burst detection
        if self.slo is not None:
            self.slo.observe(shed=True, now=self._clock())
        _event("serve_shed", job_id=job.job_id, reason=reason,
               scope="fleet", **tracectx.fields_of(job.trace))

    def _shed_sync(self, job: Job, reason: str) -> None:
        self._shed_record(job, reason)
        raise ShedError(reason)

    def _shed_async(self, job: Job, reason: str) -> None:
        """Shed a job whose future the client already holds (post-
        admission loss): the reason travels as the future's exception."""
        self._shed_record(job, reason)
        if not job.future.done():
            job.future.set_exception(ShedError(reason))

    # -- policy publication ------------------------------------------------
    def publish_policy(self, actor_params, version: int) -> int:
        """Fan one versioned weight frame out to every live warm
        replica (the fleet half of a policy hot-swap publication).

        The pytree is pulled to host and framed ONCE; each replica's
        swap then proceeds independently on its own ``_WeightsPublisher``
        thread — no fleet-wide barrier, and a replica mid-restart just
        misses this version and catches the next.  A full dispatch
        outbox drops the FRAME (counted, superseded by the next
        publication), never a job.  Returns the number of replicas
        reached."""
        blob = ipc.frame_payload(("weights",
                                  {"version": int(version),
                                   "params": _to_host(actor_params)}))
        reached = dropped = 0
        for r in self._live():
            if not r.ready.is_set():
                continue
            if r.publish(blob):
                reached += 1
            else:
                dropped += 1
        obs.counter_add("fleet_policy_publishes")
        if dropped:
            obs.counter_add("fleet_weights_dropped", dropped)
        _event("fleet_publish_policy", version=int(version),
               reached=reached, dropped=dropped)
        return reached

    # -- pump-thread callbacks ---------------------------------------------
    def _note_result(self, rid: int, job: Optional[Job], d: dict) -> None:
        with self._lock:
            self._stats["completed"] += 1
        if self.slo is not None:
            try:
                lat = float(d.get("total_s") or 0.0)
            except (TypeError, ValueError):
                lat = 0.0
            self.slo.observe(latency_s=lat, replica=rid,
                             now=self._clock())
        _event("fleet_result", replica=rid,
               job_id=d.get("job_id"), total_s=d.get("total_s"),
               degraded=d.get("degraded"),
               deadline_miss=d.get("deadline_miss"),
               requeues=getattr(job, "requeues", 0),
               **tracectx.fields_of(getattr(job, "trace", None)))

    def _note_failed(self, rid: int, job_id: int, err: str) -> None:
        with self._lock:
            self._stats["failed"] += 1
        _event("fleet_job_failed", replica=rid, job_id=job_id, error=err)

    def _reclaim(self, job: Job, rid: int, reason: str) -> None:
        """A remote shed (replica queue_full / circuit_open / shutdown)
        arrived on the pump thread: queue it for the supervision loop
        to re-dispatch (dispatching from the pump would deadlock a
        full-outbox retry against the very thread draining results)."""
        _event("fleet_reclaim", replica=rid, job_id=job.job_id,
               reason=reason)
        self._reclaim_q.put((job, reason))

    # -- supervision -------------------------------------------------------
    def _supervise(self) -> None:
        while not self._stop_ev.wait(self._poll_s):
            try:
                self.poll()
            except Exception as e:      # the front door must outlive a
                obs.counter_add("fleet_router_errors")   # bad pass
                _event("fleet_router_error", error=repr(e))

    def poll(self) -> list:
        """One supervision pass (public: tests drive it with an
        injected clock): detect dead/hung replicas, reclaim + requeue
        their in-flight jobs, perform due backoff respawns, drain the
        remote-shed reclaim queue, evaluate autoscale.  Returns the
        events emitted this pass."""
        now = self._clock()
        events = []
        with self._lock:
            replicas = dict(self._replicas)
        for rid, r in replicas.items():
            if self._tracker.tracked(rid):
                continue
            dead = not r.healthy()
            hung = (not dead and r.ready.is_set()
                    and now - r.last_beat > self.heartbeat_timeout)
            if not dead and not hung:
                continue
            if hung:
                r.hard_kill()
            r.stop_event.set()
            r.finalize(timeout=1.0)
            lost = r.take_pending()
            reason = (f"error:{r.error!r}" if r.error is not None
                      else ("exited" if dead else "hung"))
            if self.metrics_dir and hasattr(r, "blackbox"):
                # a SIGKILLed worker never flushes its own flight
                # recorder; the parent-side frame ring is the crashed
                # replica's black box
                r.blackbox(reason, self.metrics_dir)
            n = self._tracker.attempts(rid)
            delay = self._tracker.note_down(rid, now=now)
            with self._lock:
                self._replicas.pop(rid, None)
            if delay is None:
                ev = {"event": "fleet_replica_failed", "replica": rid,
                      "reason": reason, "restarts": n,
                      "lost_jobs": len(lost)}
            else:
                ev = {"event": "fleet_replica_down", "replica": rid,
                      "reason": reason, "restart_in_s": round(delay, 3),
                      "attempt": n + 1, "lost_jobs": len(lost)}
            events.append(ev)
            self._log(**ev)
            for job in lost:
                self._requeue(job, "replica_lost")
        if not self._stop_ev.is_set():
            for rid, _tok in self._tracker.due(now):
                self._respawn(rid)
                with self._lock:
                    self._stats["replica_restarts"] += 1
                ev = {"event": "fleet_replica_restart", "replica": rid,
                      "attempt": self._tracker.attempts(rid)}
                events.append(ev)
                self._log(**ev)
                obs.counter_add("fleet_replica_restarts")
        while True:
            try:
                job, reason = self._reclaim_q.get_nowait()
            except queue.Empty:
                break
            self._requeue(job, reason)
        events.extend(self._autoscale_pass(now))
        if self.slo is not None:
            ev = self.slo.evaluate(now=now)
            if ev is not None:
                ev = dict(ev, event="slo_burn")
                events.append(ev)
                self._log(**ev)
                obs.counter_add("fleet_slo_transitions")
            snap_fast = self.slo.snapshot(now=now)["fast"]
            obs.gauge_set("fleet_slo_burn", float(snap_fast["burn"]))
        self._gauge_tick()
        return events

    def _autoscale_pass(self, now: float) -> list:
        pol = self.autoscale
        if pol is None or self._stop_ev.is_set():
            return []
        live = self._live()
        if not live:
            return []
        gauges = [r.gauges() for r in live]
        depth = sum(g["pending"] + g["queue_depth"] for g in gauges)
        per = depth / len(live)
        # the SPAWN signal is an EWMA with hysteresis: micro-batches
        # drain the instantaneous depth to 0 between flushes, so the
        # raw gauge oscillates through the threshold many times a
        # second and a sustain clock keyed on it never runs out
        ew = self._depth_ewma
        ew = per if ew is None else ew + 0.3 * (per - ew)
        self._depth_ewma = ew
        events = []
        if ew >= pol.spawn_depth:
            if self._over_since is None:
                self._over_since = now
            if (now - self._over_since >= pol.spawn_sustain_s
                    and len(live) < pol.max_replicas
                    and now - self._last_scale >= pol.cooldown_s):
                r = self._spawn_replica()
                self._over_since = None
                self._last_scale = now
                with self._lock:
                    self._stats["scale_ups"] += 1
                ev = {"event": "fleet_scale_up", "replica": r.replica_id,
                      "depth_per_replica": round(ew, 2),
                      "replicas": len(live) + 1}
                events.append(ev)
                self._log(**ev)
                obs.counter_add("fleet_scale_ups")
        elif ew < 0.5 * pol.spawn_depth:
            self._over_since = None
        # the REAP signal stays instantaneous: a fleet is only safe to
        # shrink once it has been LITERALLY empty for reap_idle_s
        if depth == 0:
            if self._idle_since is None:
                self._idle_since = now
            if (now - self._idle_since >= pol.reap_idle_s
                    and len(live) > pol.min_replicas
                    and now - self._last_scale >= pol.cooldown_s):
                victim = max(live, key=lambda r: r.t_spawn)
                if victim.pending_count() == 0:
                    with self._lock:
                        self._replicas.pop(victim.replica_id, None)
                        self._retired.append(victim)
                        self._stats["scale_downs"] += 1
                    victim.request_stop()
                    self._idle_since = None
                    self._last_scale = now
                    ev = {"event": "fleet_scale_down",
                          "replica": victim.replica_id,
                          "replicas": len(live) - 1}
                    events.append(ev)
                    self._log(**ev)
                    obs.counter_add("fleet_scale_downs")
        else:
            self._idle_since = None
        return events

    def _gauge_tick(self) -> None:
        live = self._live()
        obs.gauge_set("fleet_replicas_alive", len(live))
        depth = 0
        for r in live:
            g = r.gauges()
            depth += g["pending"] + g["queue_depth"]
            obs.gauge_set("fleet_replica_depth",
                          g["pending"] + g["queue_depth"],
                          replica=r.replica_id)
        obs.gauge_set("fleet_queue_depth", depth)

    # -- chaos / introspection ---------------------------------------------
    def kill_replica(self, rid: int) -> bool:
        """SIGKILL replica ``rid``'s worker process (chaos hook for the
        kill-and-recover measurement); supervision handles the rest."""
        with self._lock:
            r = self._replicas.get(rid)
        if r is None:
            return False
        r.hard_kill()
        return True

    def replicas_alive(self) -> int:
        return len(self._live())

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._stats)
            out["shed_reasons"] = dict(self._stats["shed_reasons"])
            reps = dict(self._replicas)
        out["replicas_alive"] = sum(1 for r in reps.values()
                                    if r.healthy())
        out["failed_replicas"] = sorted(self._tracker.failed)
        out["per_replica"] = {
            rid: dict(r.gauges(), healthy=r.healthy(),
                      restarts=self._tracker.attempts(rid))
            for rid, r in reps.items()}
        return out

    # -- telemetry ---------------------------------------------------------
    def _log(self, event: str = "fleet_event", **fields) -> None:
        _event(fields.pop("event", event), **fields)
