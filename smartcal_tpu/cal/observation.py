"""Synthetic interferometer observations: array geometry -> uvw tracks.

In-framework replacement for the reference's external observation machinery:
``makems`` + casacore MS tables + LOFAR ANTENNA fixtures
(``calibration/generate_data.py:930-1000`` creates an MS with makems and
patches its FIELD table; ``find_valid_target`` at ``generate_data.py:50-105``
uses casacore ``measures`` to draw a target above the horizon).  Here the
whole chain is pure math on arrays: a LOFAR-like station layout, earth
rotation synthesis for uvw, and spherical-astronomy elevation checks
(see cal/coords.py) — no MS on disk, no C++ dependency in the hot path.

Conventions (match cal/kernels.py): B = N(N-1)/2 baselines enumerating
p < q row-major; visibility samples are time-major ck = t*B + b.
"""

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from smartcal_tpu.cal import coords

# LOFAR core reference position (superterp), public ITRF values (m) —
# reference generate_data.py:34-37 (X0, Y0, Z0).
LOFAR_X0 = 3826896.235
LOFAR_Y0 = 460979.455
LOFAR_Z0 = 5064658.203
LOFAR_LAT = 0.923717  # rad (~52.92 deg), derived from the ITRF position
OMEGA_EARTH = 7.2921159e-5  # rad/s (sidereal)

# frequency bands (MHz), reference generate_data.py:40-44
LBA_LOW, LBA_HIGH = 30.0, 70.0
HBA_LOW, HBA_HIGH = 110.0, 180.0

# approx A-team J2000 coordinates (rad): CasA, CygA, HerA, TauA, VirA —
# reference generate_data.py:59 (a_team_dirs)
ATEAM_DIRS = np.asarray([
    (6.123273, 1.026748),   # CasA
    (5.233838, 0.710912),   # CygA
    (4.412048, 0.087195),   # HerA
    (1.459697, 0.383912),   # TauA
    (3.276019, 0.216299),   # VirA
])
ATEAM_NAMES = ("CasA", "CygA", "HerA", "TauA", "VirA")
# approx 150 MHz integrated fluxes (Jy), public low-frequency catalog scale
ATEAM_FLUX = np.asarray([10690.0, 8247.0, 377.0, 1420.0, 1060.0])


def host_rng(key, salt=0):
    """Host-side numpy Generator derived from a JAX PRNG key + a per-consumer
    salt.  Every host RNG consumer must use a distinct salt, otherwise
    different draws (sky model, station layout, target, noise) would consume
    byte-identical bit streams and correlate across subsystems."""
    k = np.asarray(key, np.uint32).ravel()
    return np.random.default_rng(np.concatenate([k, [np.uint32(salt)]]))


class Observation(NamedTuple):
    """Geometry + spectral setup of one synthetic observation.

    uvw    : (T, B, 3) float32, meters (baseline p - q convention)
    freqs  : (Nf,) Hz
    ra0, dec0 : phase center (rad)
    lst0   : local sidereal time at the first sample (rad)
    times  : (T,) seconds from start (integration mid-points)
    n_stations : static int
    """

    uvw: jnp.ndarray
    freqs: jnp.ndarray
    ra0: float
    dec0: float
    lst0: float
    times: jnp.ndarray
    n_stations: int

    @property
    def n_baselines(self) -> int:
        return self.n_stations * (self.n_stations - 1) // 2

    @property
    def n_times(self) -> int:
        return self.uvw.shape[0]


def station_layout(key, n_stations: int, core_radius: float = 1500.0,
                   max_radius: float = 40e3, core_fraction: float = 0.6):
    """LOFAR-like station positions in local ENU (E, N, U) meters.

    ~``core_fraction`` of stations sit in a dense gaussian core, the rest
    spiral out with log-uniform radii up to ``max_radius`` (the qualitative
    LBA/HBA layout the reference gets from its ANTENNA table fixtures,
    ``generate_data.py:920-928``).
    """
    rng = host_rng(key, salt=10)
    n_core = max(2, int(core_fraction * n_stations))
    n_rem = n_stations - n_core
    core = rng.normal(scale=core_radius / 2.0, size=(n_core, 2))
    r = np.exp(rng.uniform(np.log(core_radius), np.log(max_radius),
                           size=n_rem))
    th = rng.uniform(0.0, 2 * np.pi, size=n_rem)
    rem = np.stack([r * np.cos(th), r * np.sin(th)], axis=-1)
    enu2 = np.concatenate([core, rem], axis=0)
    up = rng.normal(scale=5.0, size=(n_stations, 1))  # small height scatter
    return jnp.asarray(np.concatenate([enu2, up], axis=-1), jnp.float32)


def enu_to_equatorial(enu, lat: float = LOFAR_LAT):
    """ENU -> equatorial (X toward meridian/equator, Y east, Z north pole)."""
    e, n, u = enu[..., 0], enu[..., 1], enu[..., 2]
    x = -jnp.sin(lat) * n + jnp.cos(lat) * u
    y = e
    z = jnp.cos(lat) * n + jnp.sin(lat) * u
    return jnp.stack([x, y, z], axis=-1)


def uvw_tracks(xyz_eq, times, ra0, dec0, lst0):
    """Earth-rotation-synthesis station uvw: (T, N, 3) meters.

    Standard synthesis relations for hour angle H = LST - ra0:
      u =  sin(H) X + cos(H) Y
      v = -sin(d) cos(H) X + sin(d) sin(H) Y + cos(d) Z
      w =  cos(d) cos(H) X - cos(d) sin(H) Y + sin(d) Z
    """
    lst = lst0 + OMEGA_EARTH * times
    H = lst - ra0
    sh, ch = jnp.sin(H)[:, None], jnp.cos(H)[:, None]
    sd, cd = jnp.sin(dec0), jnp.cos(dec0)
    X, Y, Z = xyz_eq[None, :, 0], xyz_eq[None, :, 1], xyz_eq[None, :, 2]
    u = sh * X + ch * Y
    v = -sd * ch * X + sd * sh * Y + cd * Z
    w = cd * ch * X - cd * sh * Y + sd * Z
    return jnp.stack([u, v, w], axis=-1)


def baseline_uvw(station_uvw, n_stations: int):
    """(T, N, 3) station uvw -> (T, B, 3) baseline uvw, p < q row-major
    (uvw_p - uvw_q, the convention of the reference's readuvw text files)."""
    p, q = np.triu_indices(n_stations, 1)
    return station_uvw[:, p, :] - station_uvw[:, q, :]


def find_valid_target(key, low_el_deg: float = 3.0,
                      strategy: int = 0):
    """Draw (ra0, dec0, t0) with target elevation above ``low_el_deg``.

    Reference: generate_data.py:50-105 (casacore measures loop).  Strategies:
    0/2 uniform sky, 1 near a random A-team source.  t0 is seconds within a
    sidereal day, doubling as the LST seed.  Host-side (numpy + rejection).
    """
    rng = host_rng(key, salt=11)
    low_el = np.deg2rad(low_el_deg)
    while True:
        if strategy == 1:
            i = rng.integers(len(ATEAM_DIRS))
            dmax = np.deg2rad(0.5 + 30 * rng.random())
            ra0 = float(ATEAM_DIRS[i, 0] + rng.random() * dmax)
            dec0 = float(ATEAM_DIRS[i, 1] + rng.random() * dmax)
        else:
            ra0 = float(rng.random() * 2 * np.pi)
            dec0 = float(rng.random() * np.pi / 2)
        if dec0 > np.pi / 2:
            continue
        t0 = float(rng.random() * 24 * 3600.0)
        lst0 = OMEGA_EARTH * t0 % (2 * np.pi)
        _, el = coords.azel_from_radec(ra0, dec0, lst0, LOFAR_LAT)
        if float(el) > low_el:
            return ra0, dec0, t0


def make_observation(key, n_stations: int = 14, n_freqs: int = 3,
                     n_times: int = 20, t_int: float = 1.0,
                     flow_mhz: float = None, fhigh_mhz: float = None,
                     hba: bool = True, ra0: float = None, dec0: float = None,
                     t0: float = None, layout_kwargs=None) -> Observation:
    """Full synthetic observation (replaces makems + changefreq + FIELD patch).

    Frequencies are drawn inside the LBA/HBA band exactly like the reference
    (generate_data.py:993-1000): flow uniform in the lower half-band, fhigh in
    the upper, Nf channels linspaced between.
    """
    rng = host_rng(key, salt=12)
    if ra0 is None or dec0 is None:
        # find_valid_target validates a full (ra, dec, t) triple; any caller
        # substitution (one coordinate, or t0) voids that guarantee, so
        # re-establish the above-horizon property for the FINAL combination
        drawn = find_valid_target(key)
        caller_fixed = ra0 is not None or dec0 is not None or t0 is not None
        ra0 = drawn[0] if ra0 is None else ra0
        dec0 = drawn[1] if dec0 is None else dec0
        t0 = drawn[2] if t0 is None else t0
        if caller_fixed:
            low_el = np.deg2rad(3.0)
            el_max = np.pi / 2 - abs(LOFAR_LAT - dec0)
            if el_max <= low_el:
                raise ValueError(
                    f"dec0={dec0:.4f} rad never rises above 3 deg at the "
                    "LOFAR latitude; supply both ra0 and dec0 (or neither)")
            for _ in range(1000):
                lst0 = OMEGA_EARTH * t0 % (2 * np.pi)
                _, el = coords.azel_from_radec(ra0, dec0, lst0, LOFAR_LAT)
                if float(el) > low_el:
                    break
                t0 = float(rng.random() * 24 * 3600.0)
            else:
                raise ValueError(
                    "could not find an epoch with the target above the "
                    f"horizon for ra0={ra0:.4f} dec0={dec0:.4f}")
    elif t0 is None:
        # pointing fixed by the caller: draw only the epoch (elevation is
        # the caller's responsibility in this case)
        t0 = float(rng.random() * 24 * 3600.0)
    lo, hi = (HBA_LOW, HBA_HIGH) if hba else (LBA_LOW, LBA_HIGH)
    if flow_mhz is None:
        flow_mhz = lo + rng.random() * (hi - lo) / 2
    if fhigh_mhz is None:
        fhigh_mhz = lo + (hi - lo) / 2 + rng.random() * (hi - lo) / 2
    freqs = jnp.asarray(np.linspace(flow_mhz, fhigh_mhz, n_freqs) * 1e6,
                        jnp.float32)
    enu = station_layout(key, n_stations, **(layout_kwargs or {}))
    xyz = enu_to_equatorial(enu)
    times = jnp.arange(n_times, dtype=jnp.float32) * t_int + 0.5 * t_int
    lst0 = float(OMEGA_EARTH * t0 % (2 * np.pi))
    st_uvw = uvw_tracks(xyz, times, ra0, dec0, lst0)
    uvw = baseline_uvw(st_uvw, n_stations)
    return Observation(uvw=uvw, freqs=freqs, ra0=float(ra0),
                       dec0=float(dec0), lst0=lst0, times=times,
                       n_stations=n_stations)
