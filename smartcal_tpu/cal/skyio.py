"""Host-side text-format parsers/writers for the calibration data edge.

Parity targets: ``calibration/calibration_tools.py`` readsolutions (:88),
read_global_solutions (:122), read_spatial_solutions (:162), read_rho (:470),
read_skycluster (:488), readuvw/writeuvw (:505-522), readcluster (:1228),
and the sky/cluster parsing embedded in skytocoherencies (:244-282).

These are pure-numpy, vectorized (no per-line python math on the hot fields),
and only ever run at the host data edge — device code consumes the arrays.
"""

import numpy as np

from smartcal_tpu.cal import coords
from smartcal_tpu.cal.coherency import SkyArrays


def _data_lines(path):
    with open(path) as fh:
        return [ln for ln in fh
                if not ln.startswith("#") and len(ln.strip()) > 0]


def parse_sky_model(path):
    """SAGECal LSM sky model -> dict name -> field array (18 floats):
    [ra_h, ra_m, ra_s, dec_d, dec_m, dec_s, sI, sQ, sU, sV,
     sp1, sp2, sp3, RM, eX, eY, eP, f0].
    Gaussian sources are flagged by a leading 'G' in the name
    (reference calibration_tools.py:419-422)."""
    out = {}
    for ln in _data_lines(path):
        parts = ln.split()
        out[parts[0]] = np.asarray([float(x) for x in parts[1:19]],
                                   dtype=np.float64)
    return out


def parse_cluster_file(path):
    """Cluster file -> list of (cluster_line_order, [source names]).
    Format per line: cluster_id hybrid name1 name2 ...
    (reference calibration_tools.py:253-288)."""
    return [(i, ln.split()[2:]) for i, ln in enumerate(_data_lines(path))]


def build_sky_arrays(sky_path, cluster_path, ra0, dec0):
    """Parse sky + cluster files into a device-ready SkyArrays.

    The flux column stores log(sI); spectral coefficients pass through.
    Cluster ids follow cluster-file line order, as in the reference.
    """
    S = parse_sky_model(sky_path)
    clusters = parse_cluster_file(cluster_path)
    rows, cl_ids, names = [], [], []
    for cid, snames in clusters:
        for nm in snames:
            rows.append(S[nm])
            cl_ids.append(cid)
            names.append(nm)
    info = np.stack(rows)                                  # (S, 18)
    ra = coords.hms_to_rad(info[:, 0], info[:, 1], info[:, 2])
    # dec stays a per-row loop: dms_to_rad's negative-zero sign logic is
    # scalar-only
    dec = np.asarray([coords.dms_to_rad(*row[3:6]) for row in info])
    l, m, n = (np.asarray(v)
               for v in coords.radectolm(ra, dec, ra0, dec0))

    flux_coef = np.stack([np.log(info[:, 6]), info[:, 10],
                          info[:, 11], info[:, 12]], axis=-1)
    gauss = info[:, [14, 15, 16]]
    is_gauss = np.asarray([nm.startswith("G") for nm in names])
    return SkyArrays(
        lmn=np.stack([l, m, n], axis=-1), flux_coef=flux_coef,
        f0=info[:, 17], gauss=gauss, is_gauss=is_gauss,
        cluster=np.asarray(cl_ids), n_clusters=len(clusters))


def write_sky_model(path, rows):
    """SAGECal LSM writer: ``rows`` of (name, ra_rad, dec_rad, sI, sp1,
    eX, eY, eP, f0) -> the 18-column text format parse_sky_model reads.
    Gaussian sources are any with nonzero extent (name should lead 'G')."""
    with open(path, "w") as fh:
        fh.write("## LSM file\n")
        fh.write("### Name | RA (h m s) | DEC (d m s) | I Q U V | SI0 SI1 "
                 "SI2 | RM | eX eY eP | f0\n")
        for (name, ra, dec, sI, sp1, eX, eY, eP, f0) in rows:
            hh, mm, ss = coords.rad_to_ra(ra)
            dd, dm, ds = coords.rad_to_dec(dec)
            fh.write(f"{name} {hh} {mm} {ss:.6f} {dd} {dm} {ds:.6f} "
                     f"{sI} 0 0 0 {sp1} 0 0 0 {eX} {eY} {eP} {f0}\n")


def write_cluster_file(path, clusters, hybrid=1):
    """Cluster-file writer: ``clusters`` = [(cluster_id, [names])]."""
    with open(path, "w") as fh:
        fh.write("### Cluster file\n")
        for cid, names in clusters:
            fh.write(f"{cid} {hybrid} " + " ".join(names) + "\n")


def _sex_to_rad(txt, is_ra):
    """DP3 position field -> radians.

    Accepts Ra 'hh:mm:ss.s', Dec '+dd.mm.ss.s' (dot-separated sexagesimal
    needs >= 2 dots), colon-separated dec, and plain decimal degrees
    ('52.3444' — one dot — is degrees, NOT 52 deg 3444 min)."""
    t = txt.strip().replace("+", "")
    neg = t.startswith("-")
    body = t.lstrip("-")
    if ":" in body:
        parts = body.split(":")
    elif body.count(".") >= 2:             # dd.mm.ss[.frac] sexagesimal
        p = body.split(".")
        parts = [p[0], p[1], ".".join(p[2:]) if len(p) > 2 else "0"]
    else:      # plain decimal degrees (legal for both Ra and Dec)
        val = np.deg2rad(float(body))
        return -val if neg else val
    a, b, c = (float(x) for x in (parts + ["0", "0"])[:3])
    if is_ra:
        val = float(coords.hms_to_rad(a, b, c))
        return -val if neg else val
    val = np.deg2rad(a + b / 60.0 + c / 3600.0)
    return -val if neg else val


def _split_csv_brackets(ln):
    """Split a makesourcedb row on commas OUTSIDE [...] brackets (a
    multi-term SpectralIndex like '[-0.7, 0.02]' is one field)."""
    out, depth, cur = [], 0, []
    for ch in ln:
        if ch == "[":
            depth += 1
        elif ch == "]":
            depth = max(0, depth - 1)
        if ch == "," and depth == 0:
            out.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
    out.append("".join(cur).strip())
    return out


def parse_makesourcedb(path):
    """DP3 makesourcedb sky model -> (sources, patches).

    The format the LINC target download produces (and lsmtool consumes in
    the reference's ``convertmodel.py``): a ``format = Name, Type, Patch,
    Ra, Dec, I, ...`` header, patch-definition rows with empty Name/Type,
    and per-source rows.  Returns sources as dicts with keys name/type/
    patch/ra/dec/I/spectral_index/major/minor/orientation/ref_freq and
    the ordered patch-name list.
    """
    def _fields_from(spec):
        """Field names + their header defaults (e.g.
        ReferenceFrequency='134e6' declares the value used when a row
        leaves that column empty)."""
        names, defaults = [], {}
        for f in _split_csv_brackets(spec.strip(" ()")):
            if "=" in f:
                nm, dv = f.split("=", 1)
                nm = nm.strip().strip("()")
                defaults[nm] = dv.strip().strip("'\"")
            else:
                nm = f.strip().strip("()")
            names.append(nm)
        return names, defaults

    fields, defaults = None, {}
    sources, patches = [], []
    with open(path) as fh:
        for ln in fh:
            ln = ln.strip()
            if not ln or ln.startswith("#"):
                # two header styles exist: '# (<fields>) = format' (the
                # trailing marker; fields may themselves contain '=', e.g.
                # ReferenceFrequency='134e6') and 'format = <fields>'
                body = ln.lstrip("# ").rstrip()
                if body.lower().endswith("= format"):
                    fields, defaults = _fields_from(
                        body[:body.lower().rfind("= format")])
                continue
            if fields is None and ln.lower().startswith("format"):
                fields, defaults = _fields_from(ln.split("=", 1)[1])
                continue
            if fields is None:
                raise ValueError(
                    f"{path}: data row before any recognized 'format' "
                    "header — cannot assign columns")
            vals = _split_csv_brackets(ln)
            row = dict(zip(fields, vals))
            name = row.get("Name", "")
            if not name:                       # patch definition row
                if row.get("Patch"):
                    patches.append(row["Patch"])
                continue
            si_txt = row.get("SpectralIndex", "").strip("[] ")
            # multi-term indices split on ',' or ';'; first term used
            si = (float(si_txt.replace(";", ",").split(",")[0])
                  if si_txt else 0.0)
            f0 = float(row.get("ReferenceFrequency")
                       or defaults.get("ReferenceFrequency") or 0.0) \
                or 100e6
            asec = np.pi / (180.0 * 3600.0)
            sources.append({
                "name": name,
                "type": row.get("Type", "POINT").upper(),
                "patch": row.get("Patch", ""),
                "ra": _sex_to_rad(row["Ra"], True),
                "dec": _sex_to_rad(row["Dec"], False),
                "I": float(row.get("I", 0.0) or 0.0),
                "spectral_index": si,
                "major": float(row.get("MajorAxis") or 0.0) * asec,
                "minor": float(row.get("MinorAxis") or 0.0) * asec,
                "orientation": np.pi / 2 - (np.pi - np.deg2rad(
                    float(row.get("Orientation") or 0.0))),
                "ref_freq": f0,
            })
            if sources[-1]["patch"] and sources[-1]["patch"] not in patches:
                patches.append(sources[-1]["patch"])
    return sources, patches


def convert_dp3_skymodel(skymodel, out_sky, out_cluster, out_rho,
                         start_cluster=1, num_patches=0):
    """DP3 makesourcedb model -> SAGECal sky/cluster/rho text files.

    Reference: ``calibration/convertmodel.py:16-76`` (lsmtool-based) —
    one cluster per patch, Gaussian sources renamed 'G<patch><i>' and
    points 'P<patch><i>', rho 1.0 per cluster, patch order preserved.
    Returns the number of clusters written.
    """
    sources, patches = parse_makesourcedb(skymodel)
    if num_patches > 0:
        patches = patches[:num_patches]
    rows, clusters, rhos = [], [], []
    cid = start_cluster
    for patch in patches:
        names = []
        for ci, s in enumerate(p for p in sources if p["patch"] == patch):
            prefix = "G" if s["type"] == "GAUSSIAN" else "P"
            # separator prevents cross-patch collisions
            # ('X' idx 11 vs 'X1' idx 1 both -> 'PX11')
            name = f"{prefix}{patch}.{ci}"
            names.append(name)
            rows.append((name, s["ra"], s["dec"], s["I"],
                         s["spectral_index"], s["major"], s["minor"],
                         s["orientation"], s["ref_freq"]))
        if names:
            clusters.append((cid, names))
            rhos.append(cid)
            cid += 1
    write_sky_model(out_sky, rows)
    write_cluster_file(out_cluster, clusters)
    # rho 1.0 per cluster like the reference (:49), ids matching the
    # cluster file (the start_cluster interchange contract)
    write_rho(out_rho, np.ones(len(rhos), np.float32),
              np.zeros(len(rhos), np.float32), ids=rhos)
    return len(clusters)


def write_bbs_skymodel(path, rows, f0):
    """Inverse direction: SAGECal-style rows -> a DP3 makesourcedb file
    (the ``sky_bbs.txt`` the simulator emits for external DP3 runs,
    simulate.py:139-141).  ``rows`` as for :func:`write_sky_model`."""
    with open(path, "w") as fh:
        fh.write("# (Name, Type, Patch, Ra, Dec, I, Q, U, V, "
                 f"ReferenceFrequency='{f0}', SpectralIndex='[]', "
                 "MajorAxis, MinorAxis, Orientation) = format\n")
        fh.write(", , center, 00:00:00.0, +00.00.00.0\n")
        for (name, ra, dec, sI, sp1, eX, eY, eP, rf0) in rows:
            hh, mm, ss = coords.rad_to_ra(ra)
            # sign handled here: rad_to_dec carries it on the first
            # NONZERO field, which would print '+00.-30.00' for
            # declinations in (-1, 0) deg
            sgn = "-" if dec < 0 else "+"
            dd, dm, ds = coords.rad_to_dec(abs(float(dec)))
            stype = "GAUSSIAN" if (eX or eY) else "POINT"
            # inverse of the parse-side convention
            # (orientation = deg2rad(o) - pi/2), so write/parse round-trip
            ori_deg = np.rad2deg(eP + np.pi / 2)
            fh.write(f"{name}, {stype}, center, "
                     f"{int(hh):02d}:{int(mm):02d}:{ss:06.3f}, "
                     f"{sgn}{int(dd):02d}.{int(dm):02d}.{ds:06.3f}, "
                     f"{sI}, 0, 0, 0, {rf0}, [{sp1}], "
                     f"{eX * 180 * 3600 / np.pi}, "
                     f"{eY * 180 * 3600 / np.pi}, "
                     f"{ori_deg}\n")


def read_rho(path, n_clusters):
    """admm rho file: 'id hybrid rho_spectral rho_spatial' per cluster.
    Returns (rho_spectral, rho_spatial), each (K,) float32.
    Reference: calibration_tools.py:470-484."""
    vals = np.asarray([[float(x) for x in ln.split()[:4]]
                       for ln in _data_lines(path)], dtype=np.float32)
    assert vals.shape[0] == n_clusters
    return vals[:, 2].copy(), vals[:, 3].copy()


def write_rho(path, rho_spectral, rho_spatial, hybrid=1, ids=None):
    """Inverse of read_rho, format per reference calibenv.py:105-114.
    ``ids`` overrides the default 1..K numbering (files are matched by id
    externally, e.g. after convert_dp3_skymodel's start_cluster)."""
    with open(path, "w") as fh:
        fh.write("# id hybrid rho_spectral rho_spatial\n")
        for i, (rs, rp) in enumerate(zip(rho_spectral, rho_spatial)):
            cid = ids[i] if ids is not None else i + 1
            fh.write(f"{cid} {hybrid} {float(rs)} {float(rp)}\n")


def read_skycluster(path, n_rows):
    """skylmn table: 'cluster_id l m sI sP' -> (M, 5) float32.
    Reference: calibration_tools.py:488-502."""
    vals = np.asarray([[float(x) for x in ln.split()[:5]]
                       for ln in _data_lines(path)[:n_rows]], dtype=np.float32)
    return vals


def read_uvw_visibilities(path):
    """Text visibilities: u v w xx.re xx.im xy.re xy.im yx.re yx.im
    yy.re yy.im -> (XX, XY, YX, YY) complex vectors.
    Reference: readuvw, calibration_tools.py:505-512."""
    a = np.loadtxt(path, delimiter=" ")
    return (a[:, 3] + 1j * a[:, 4], a[:, 5] + 1j * a[:, 6],
            a[:, 7] + 1j * a[:, 8], a[:, 9] + 1j * a[:, 10])


def write_uvw_visibilities(path, XX, XY, YX, YY):
    """Inverse of read_uvw_visibilities (reference writeuvw, :515-522);
    writes only the 8 visibility columns, one sample per line."""
    cols = np.stack([XX.real, XX.imag, XY.real, XY.imag,
                     YX.real, YX.imag, YY.real, YY.imag], axis=-1)
    with open(path, "w") as fh:
        for row in cols:
            fh.write(" ".join(str(x) for x in row) + "\n")


def read_solutions(path):
    """Per-direction Jones solutions text file -> (freq, J).

    Header: 2 comment lines, then 'freq/MHz BW time N ? K'.  Body: Nt lines
    of 1+K floats; each block of 8N rows is one timeslot, station n's 8
    values are (J00.re, J00.im, J01.re, J01.im, J10.re, J10.im, J11.re,
    J11.im).  Returns J (K, 2*N*Nto, 2) complex64.
    Reference: readsolutions, calibration_tools.py:88-119."""
    with open(path) as fh:
        next(fh)
        next(fh)
        meta = next(fh).split()
        freq = float(meta[0]) * 1e6
        n_stat = int(meta[3])
        K = int(meta[5])
        body = np.loadtxt(fh, dtype=np.float32, ndmin=2)
    a = body[:, 1:1 + K]
    nto = a.shape[0] // (8 * n_stat)
    a = a[:nto * 8 * n_stat].reshape(nto, n_stat, 4, 2, K)
    c = a[:, :, :, 0, :] + 1j * a[:, :, :, 1, :]          # (Nto, N, 4, K)
    J = np.transpose(c, (3, 0, 1, 2)).reshape(K, 2 * n_stat * nto, 2)
    return freq, J.astype(np.complex64)


def write_solutions(path, freq, J, n_stat, bw_mhz=0.18, t_min=10.0):
    """Inverse of read_solutions: J (K, 2*N*Nto, 2) -> text file."""
    K = J.shape[0]
    nto = J.shape[1] // (2 * n_stat)
    c = J.reshape(K, nto, n_stat, 2, 2)                    # [k,t,n,i,j]
    c = np.transpose(c, (1, 2, 3, 4, 0)).reshape(nto, n_stat, 4, K)
    vals = np.empty((nto, n_stat, 8, K), dtype=np.float32)
    vals[:, :, 0::2] = c.real
    vals[:, :, 1::2] = c.imag
    flat = vals.reshape(nto * n_stat * 8, K)
    with open(path, "w") as fh:
        fh.write("# solutions file (smartcal_tpu)\n")
        fh.write("# freq(MHz) bandwidth(MHz) time_interval(min) stations"
                 " clusters effective_clusters\n")
        fh.write(f"{freq / 1e6} {bw_mhz} {t_min} {n_stat} {K} {K}\n")
        for i, row in enumerate(flat):
            fh.write(str(i % (8 * n_stat)) + " "
                     + " ".join(f"{x:.6e}" for x in row) + "\n")


def read_global_solutions(path):
    """Global Z polynomial solutions -> (N, freq, P, K, Z) with Z shaped
    (Nto, K, 2*P*N, 2) complex64.
    Reference: read_global_solutions, calibration_tools.py:122-160."""
    with open(path) as fh:
        next(fh)
        next(fh)
        meta = next(fh).split()
        freq = float(meta[0]) * 1e6
        P = int(meta[1])
        n_stat = int(meta[2])
        K = int(meta[4])
        body = np.loadtxt(fh, dtype=np.float32, ndmin=2)
    a = body[:, 1:1 + K]
    blk = 8 * P * n_stat
    nto = a.shape[0] // blk
    a = a[:nto * blk].reshape(nto, blk, K)
    c = a[:, 0::2, :] + 1j * a[:, 1::2, :]                # (Nto, 4PN, K)
    half = 2 * P * n_stat
    Z = np.empty((nto, K, half, 2), dtype=np.complex64)
    Z[..., 0] = np.transpose(c[:, :half, :], (0, 2, 1))
    Z[..., 1] = np.transpose(c[:, half:, :], (0, 2, 1))
    return n_stat, freq, P, K, Z


def read_spatial_solutions(path):
    """Spatial (spherical-harmonic) Z solutions -> (N, F, thetak, phik, Z)
    with Z shaped (Nto, 2*F*N, 2*G) complex64.
    Reference: read_spatial_solutions, calibration_tools.py:162-211."""
    with open(path) as fh:
        next(fh)
        next(fh)
        next(fh)
        meta = next(fh).split()
        F = int(meta[1])
        G = int(meta[2])
        n_stat = int(meta[3])
        thetak = [float(x) for x in next(fh).split()]
        phik = [float(x) for x in next(fh).split()]
        body = np.loadtxt(fh, dtype=np.float32, ndmin=2)
    a = body[:, 1:1 + G]
    blk = 8 * F * n_stat
    nto = a.shape[0] // blk
    a = a[:nto * blk].reshape(nto, blk, G)
    c = a[:, 0::2, :] + 1j * a[:, 1::2, :]                # (Nto, 4FN, G)
    half = 2 * F * n_stat
    Z = np.empty((nto, half, 2 * G), dtype=np.complex64)
    Z[:, :, 0::2] = c[:, :half, :]
    Z[:, :, 1::2] = c[:, half:, :]
    return n_stat, F, thetak, phik, Z


def read_cluster_lines(path):
    """Cluster file -> {order: raw line} for later regeneration of reduced
    cluster files.  Reference: readcluster, calibration_tools.py:1228-1249."""
    return {i: ln for i, ln in enumerate(_data_lines(path))}
