"""Host-side text-format parsers/writers for the calibration data edge.

Parity targets: ``calibration/calibration_tools.py`` readsolutions (:88),
read_global_solutions (:122), read_spatial_solutions (:162), read_rho (:470),
read_skycluster (:488), readuvw/writeuvw (:505-522), readcluster (:1228),
and the sky/cluster parsing embedded in skytocoherencies (:244-282).

These are pure-numpy, vectorized (no per-line python math on the hot fields),
and only ever run at the host data edge — device code consumes the arrays.
"""

import numpy as np

from smartcal_tpu.cal import coords
from smartcal_tpu.cal.coherency import SkyArrays


def _data_lines(path):
    with open(path) as fh:
        return [ln for ln in fh
                if not ln.startswith("#") and len(ln.strip()) > 0]


def parse_sky_model(path):
    """SAGECal LSM sky model -> dict name -> field array (18 floats):
    [ra_h, ra_m, ra_s, dec_d, dec_m, dec_s, sI, sQ, sU, sV,
     sp1, sp2, sp3, RM, eX, eY, eP, f0].
    Gaussian sources are flagged by a leading 'G' in the name
    (reference calibration_tools.py:419-422)."""
    out = {}
    for ln in _data_lines(path):
        parts = ln.split()
        out[parts[0]] = np.asarray([float(x) for x in parts[1:19]],
                                   dtype=np.float64)
    return out


def parse_cluster_file(path):
    """Cluster file -> list of (cluster_line_order, [source names]).
    Format per line: cluster_id hybrid name1 name2 ...
    (reference calibration_tools.py:253-288)."""
    return [(i, ln.split()[2:]) for i, ln in enumerate(_data_lines(path))]


def build_sky_arrays(sky_path, cluster_path, ra0, dec0):
    """Parse sky + cluster files into a device-ready SkyArrays.

    The flux column stores log(sI); spectral coefficients pass through.
    Cluster ids follow cluster-file line order, as in the reference.
    """
    S = parse_sky_model(sky_path)
    clusters = parse_cluster_file(cluster_path)
    rows, cl_ids, names = [], [], []
    for cid, snames in clusters:
        for nm in snames:
            rows.append(S[nm])
            cl_ids.append(cid)
            names.append(nm)
    info = np.stack(rows)                                  # (S, 18)
    ra = coords.hms_to_rad(info[:, 0], info[:, 1], info[:, 2])
    # dec stays a per-row loop: dms_to_rad's negative-zero sign logic is
    # scalar-only
    dec = np.asarray([coords.dms_to_rad(*row[3:6]) for row in info])
    l, m, n = (np.asarray(v)
               for v in coords.radectolm(ra, dec, ra0, dec0))

    flux_coef = np.stack([np.log(info[:, 6]), info[:, 10],
                          info[:, 11], info[:, 12]], axis=-1)
    gauss = info[:, [14, 15, 16]]
    is_gauss = np.asarray([nm.startswith("G") for nm in names])
    return SkyArrays(
        lmn=np.stack([l, m, n], axis=-1), flux_coef=flux_coef,
        f0=info[:, 17], gauss=gauss, is_gauss=is_gauss,
        cluster=np.asarray(cl_ids), n_clusters=len(clusters))


def read_rho(path, n_clusters):
    """admm rho file: 'id hybrid rho_spectral rho_spatial' per cluster.
    Returns (rho_spectral, rho_spatial), each (K,) float32.
    Reference: calibration_tools.py:470-484."""
    vals = np.asarray([[float(x) for x in ln.split()[:4]]
                       for ln in _data_lines(path)], dtype=np.float32)
    assert vals.shape[0] == n_clusters
    return vals[:, 2].copy(), vals[:, 3].copy()


def write_rho(path, rho_spectral, rho_spatial, hybrid=1):
    """Inverse of read_rho, format per reference calibenv.py:105-114."""
    with open(path, "w") as fh:
        fh.write("# id hybrid rho_spectral rho_spatial\n")
        for i, (rs, rp) in enumerate(zip(rho_spectral, rho_spatial)):
            fh.write(f"{i + 1} {hybrid} {float(rs)} {float(rp)}\n")


def read_skycluster(path, n_rows):
    """skylmn table: 'cluster_id l m sI sP' -> (M, 5) float32.
    Reference: calibration_tools.py:488-502."""
    vals = np.asarray([[float(x) for x in ln.split()[:5]]
                       for ln in _data_lines(path)[:n_rows]], dtype=np.float32)
    return vals


def read_uvw_visibilities(path):
    """Text visibilities: u v w xx.re xx.im xy.re xy.im yx.re yx.im
    yy.re yy.im -> (XX, XY, YX, YY) complex vectors.
    Reference: readuvw, calibration_tools.py:505-512."""
    a = np.loadtxt(path, delimiter=" ")
    return (a[:, 3] + 1j * a[:, 4], a[:, 5] + 1j * a[:, 6],
            a[:, 7] + 1j * a[:, 8], a[:, 9] + 1j * a[:, 10])


def write_uvw_visibilities(path, XX, XY, YX, YY):
    """Inverse of read_uvw_visibilities (reference writeuvw, :515-522);
    writes only the 8 visibility columns, one sample per line."""
    cols = np.stack([XX.real, XX.imag, XY.real, XY.imag,
                     YX.real, YX.imag, YY.real, YY.imag], axis=-1)
    with open(path, "w") as fh:
        for row in cols:
            fh.write(" ".join(str(x) for x in row) + "\n")


def read_solutions(path):
    """Per-direction Jones solutions text file -> (freq, J).

    Header: 2 comment lines, then 'freq/MHz BW time N ? K'.  Body: Nt lines
    of 1+K floats; each block of 8N rows is one timeslot, station n's 8
    values are (J00.re, J00.im, J01.re, J01.im, J10.re, J10.im, J11.re,
    J11.im).  Returns J (K, 2*N*Nto, 2) complex64.
    Reference: readsolutions, calibration_tools.py:88-119."""
    with open(path) as fh:
        next(fh)
        next(fh)
        meta = next(fh).split()
        freq = float(meta[0]) * 1e6
        n_stat = int(meta[3])
        K = int(meta[5])
        body = np.loadtxt(fh, dtype=np.float32, ndmin=2)
    a = body[:, 1:1 + K]
    nto = a.shape[0] // (8 * n_stat)
    a = a[:nto * 8 * n_stat].reshape(nto, n_stat, 4, 2, K)
    c = a[:, :, :, 0, :] + 1j * a[:, :, :, 1, :]          # (Nto, N, 4, K)
    J = np.transpose(c, (3, 0, 1, 2)).reshape(K, 2 * n_stat * nto, 2)
    return freq, J.astype(np.complex64)


def write_solutions(path, freq, J, n_stat, bw_mhz=0.18, t_min=10.0):
    """Inverse of read_solutions: J (K, 2*N*Nto, 2) -> text file."""
    K = J.shape[0]
    nto = J.shape[1] // (2 * n_stat)
    c = J.reshape(K, nto, n_stat, 2, 2)                    # [k,t,n,i,j]
    c = np.transpose(c, (1, 2, 3, 4, 0)).reshape(nto, n_stat, 4, K)
    vals = np.empty((nto, n_stat, 8, K), dtype=np.float32)
    vals[:, :, 0::2] = c.real
    vals[:, :, 1::2] = c.imag
    flat = vals.reshape(nto * n_stat * 8, K)
    with open(path, "w") as fh:
        fh.write("# solutions file (smartcal_tpu)\n")
        fh.write("# freq(MHz) bandwidth(MHz) time_interval(min) stations"
                 " clusters effective_clusters\n")
        fh.write(f"{freq / 1e6} {bw_mhz} {t_min} {n_stat} {K} {K}\n")
        for i, row in enumerate(flat):
            fh.write(str(i % (8 * n_stat)) + " "
                     + " ".join(f"{x:.6e}" for x in row) + "\n")


def read_global_solutions(path):
    """Global Z polynomial solutions -> (N, freq, P, K, Z) with Z shaped
    (Nto, K, 2*P*N, 2) complex64.
    Reference: read_global_solutions, calibration_tools.py:122-160."""
    with open(path) as fh:
        next(fh)
        next(fh)
        meta = next(fh).split()
        freq = float(meta[0]) * 1e6
        P = int(meta[1])
        n_stat = int(meta[2])
        K = int(meta[4])
        body = np.loadtxt(fh, dtype=np.float32, ndmin=2)
    a = body[:, 1:1 + K]
    blk = 8 * P * n_stat
    nto = a.shape[0] // blk
    a = a[:nto * blk].reshape(nto, blk, K)
    c = a[:, 0::2, :] + 1j * a[:, 1::2, :]                # (Nto, 4PN, K)
    half = 2 * P * n_stat
    Z = np.empty((nto, K, half, 2), dtype=np.complex64)
    Z[..., 0] = np.transpose(c[:, :half, :], (0, 2, 1))
    Z[..., 1] = np.transpose(c[:, half:, :], (0, 2, 1))
    return n_stat, freq, P, K, Z


def read_spatial_solutions(path):
    """Spatial (spherical-harmonic) Z solutions -> (N, F, thetak, phik, Z)
    with Z shaped (Nto, 2*F*N, 2*G) complex64.
    Reference: read_spatial_solutions, calibration_tools.py:162-211."""
    with open(path) as fh:
        next(fh)
        next(fh)
        next(fh)
        meta = next(fh).split()
        F = int(meta[1])
        G = int(meta[2])
        n_stat = int(meta[3])
        thetak = [float(x) for x in next(fh).split()]
        phik = [float(x) for x in next(fh).split()]
        body = np.loadtxt(fh, dtype=np.float32, ndmin=2)
    a = body[:, 1:1 + G]
    blk = 8 * F * n_stat
    nto = a.shape[0] // blk
    a = a[:nto * blk].reshape(nto, blk, G)
    c = a[:, 0::2, :] + 1j * a[:, 1::2, :]                # (Nto, 4FN, G)
    half = 2 * F * n_stat
    Z = np.empty((nto, half, 2 * G), dtype=np.complex64)
    Z[:, :, 0::2] = c[:, :half, :]
    Z[:, :, 1::2] = c[:, half:, :]
    return n_stat, F, thetak, phik, Z


def read_cluster_lines(path):
    """Cluster file -> {order: raw line} for later regeneration of reduced
    cluster files.  Reference: readcluster, calibration_tools.py:1228-1249."""
    return {i: ln for i, ln in enumerate(_data_lines(path))}
